#!/usr/bin/env python
"""Root-level training entry point (reference repo UX: ``python train.py``,
train.py:217-246).  All logic lives in :mod:`raft_tpu.cli.train`."""
from raft_tpu.cli.train import main

if __name__ == "__main__":
    main()
