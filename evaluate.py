#!/usr/bin/env python
"""Root-level evaluation entry point (reference ``python evaluate.py``,
evaluate.py:169-195).  All logic lives in :mod:`raft_tpu.cli.evaluate`."""
from raft_tpu.cli.evaluate import main

if __name__ == "__main__":
    main()
