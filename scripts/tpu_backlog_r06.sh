#!/bin/bash
# Round-6 TPU backlog, priority order: the PR-13 fused-kernel
# re-baseline.  The fused Pallas kernels (fused_lookup_encoder,
# fused_gru) were interpret-verified off-TPU only — this round measures
# them on hardware, lets autotune rank them into the registry, and
# re-runs the headline bench with the refreshed knob surface.  Every
# step is independently resumable.
set -x -o pipefail
cd "$(dirname "$0")/.."

# 0. Per-kernel microbench, fused vs unfused arms in isolation, at the
#    chairs train shape and the serving shape -> BENCH_KERNELS_r06*.json
#    (selected=false on a fresh chip: the registry has no fused winners
#    yet; step 3 re-runs it post-autotune so the slowdown gate is armed)
python scripts/bench_kernels.py --image 368x496 --batch 16 \
    2>&1 | tee /tmp/bench_kernels_r06.log | tail -1 \
    > BENCH_KERNELS_r06_pre.json
python scripts/bench_kernels.py --image 440x1024 --batch 8 \
    --corr-dtype int8 2>&1 | tee /tmp/bench_kernels_serve_r06.log \
    | tail -1 > BENCH_KERNELS_SERVE_r06_pre.json

# 1. Autotune sweeps with both fused kernels in the knob surface
#    (fused_lookup_encoder/fused_gru are real [False, True] axes on
#    TPU) — train at the chairs crop, eval + serve at the serving shape
python scripts/autotune.py --kind train --image 368x496 \
    --batch-per-chip 16 2>&1 | tee /tmp/autotune_train_r06.log | tail -3
python scripts/autotune.py --kind eval --image 440x1024 \
    --batch-per-chip 8 2>&1 | tee /tmp/autotune_eval_r06.log | tail -3
python scripts/autotune.py --kind serve --image 440x1024 \
    --batch-per-chip 8 2>&1 | tee /tmp/autotune_serve_r06.log | tail -3

# 2. Headline bench + eval refresh on the tuned registry (the r03-era
#    76.0 pairs/s/chip pin plus whatever the fused kernels buy)
python bench.py 2>&1 | tee /tmp/bench_r06.log | tail -2
BENCH_MODE=eval python bench.py 2>&1 | tee /tmp/bench_eval_r06.log | tail -2

# 3. Post-autotune kernel re-bench: now `selected` reflects the
#    registry's verdict, which arms the regression gate — a registry
#    that picked a fused kernel the microbench shows slower fails here
python scripts/bench_kernels.py --image 368x496 --batch 16 \
    2>&1 | tee /tmp/bench_kernels_r06_post.log | tail -1 \
    > BENCH_KERNELS_r06.json
python scripts/check_regression.py BENCH_KERNELS_r06.json \
    --max-kernel-slowdown lookup_encoder:5 --max-kernel-slowdown gru:5 \
    2>&1 | tail -3

# 4. Serve parity + throughput with the tuned knobs (fused_gru rides
#    the compiled encode/iter_step pieces in both batching modes)
python scripts/bench_serve.py --batching both --shapes 440x1024 \
    --requests 128 --concurrency 16 \
    2>&1 | tee /tmp/bench_serve_r06.log | tail -1 > BENCH_SERVE_r06.json
