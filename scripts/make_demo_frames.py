"""Regenerate the bundled ``demo-frames/`` sample (8 frames = 7 pairs).

The reference ships real sample imagery (``demo-frames/`` Sintel stills
and the fork's ``data_abel/`` street pair, reference demo.py:69,77-78);
this repo cannot copy those, so it bundles a PROCEDURAL street-like
scene instead: sky gradient, panning textured ground, parallax skyline,
independently moving circles, and a crossing "car" — enough structure
for RAFT to produce a readable colorwheel flow image in a bare clone.
The clip is long enough (>= 8 frames) to exercise the streaming
session API (docs/SERVING.md "Streaming sessions").

:func:`make_clip` is the importable, cv2-free variant with EXACTLY
known motion — a smooth texture rolled by a fixed shift per frame, so
every pixel's ground-truth flow is the shift itself.  The streaming
e2e test (tests/test_serve_stream.py) and ``scripts/bench_stream.py
--tiny`` measure EPE against it.

Deterministic (fixed seeds).  Usage:
    python scripts/make_demo_frames.py [outdir=demo-frames] [n_frames=8]
"""

from __future__ import annotations

import sys

import numpy as np

H, W = 384, 512


def _upsample_bilinear(base: np.ndarray, factor: int) -> np.ndarray:
    """Pure-numpy separable bilinear upsample of ``(h, w, c)`` — keeps
    :func:`make_clip` importable without cv2."""
    h, w, _ = base.shape
    ys = np.linspace(0, h - 1, h * factor)
    xs = np.linspace(0, w - 1, w * factor)
    y0 = np.floor(ys).astype(int)
    x0 = np.floor(xs).astype(int)
    y1 = np.minimum(y0 + 1, h - 1)
    x1 = np.minimum(x0 + 1, w - 1)
    fy = (ys - y0)[:, None, None]
    fx = (xs - x0)[None, :, None]
    a, b = base[y0][:, x0], base[y0][:, x1]
    c, d = base[y1][:, x0], base[y1][:, x1]
    return (1 - fy) * ((1 - fx) * a + fx * b) \
        + fy * ((1 - fx) * c + fx * d)


def make_clip(n_frames: int = 8, hw=(H, W), shift=(2, 1),
              seed: int = 3):
    """Synthetic streaming clip with exactly-known analytic motion.

    A smooth random texture (bilinear-upsampled low-frequency noise, so
    RAFT has trackable gradients) is rolled by ``shift`` = ``(dx, dy)``
    pixels per frame with wrap-around: every pixel of every consecutive
    pair has TRUE flow exactly ``shift``.

    Returns ``(frames, flow)``: ``frames`` float32 ``(n, H, W, 3)`` in
    [0, 255]; ``flow`` float32 ``(H, W, 2)``, last axis ``(x, y)`` —
    the ground truth of every consecutive pair.
    """
    h, w = hw
    dx, dy = shift
    rng = np.random.default_rng(seed)
    base = rng.uniform(0, 255, (h // 4 + 2, w // 4 + 2, 3))
    tex = _upsample_bilinear(base, 4)[:h, :w]
    frames = np.stack([
        np.roll(np.roll(tex, t * dy, axis=0), t * dx, axis=1)
        for t in range(n_frames)]).astype(np.float32)
    flow = np.zeros((h, w, 2), np.float32)
    flow[..., 0] = dx
    flow[..., 1] = dy
    return frames, flow


def main():
    import cv2

    out = sys.argv[1] if len(sys.argv) > 1 else "demo-frames"
    n_frames = int(sys.argv[2]) if len(sys.argv) > 2 else 8
    import os

    os.makedirs(out, exist_ok=True)
    rng = np.random.default_rng(42)
    rng2 = np.random.default_rng(7)

    bldgs = [(int(x), int(w), int(h), tuple(map(float, c)))
             for x, w, h, c in zip(rng.integers(0, W, 12),
                                   rng.integers(30, 80, 12),
                                   rng.integers(40, 140, 12),
                                   rng.uniform(70, 130, (12, 3)))]
    objs = [(float(x), float(y), int(r), tuple(map(float, c)), float(vx),
             float(vy))
            for x, y, r, c, vx, vy in zip(
                rng.uniform(50, W - 50, 5), rng.uniform(60, 170, 5),
                rng.integers(10, 26, 5), rng.uniform(120, 240, (5, 3)),
                rng.uniform(-12, 12, 5), rng.uniform(-4, 4, 5))]
    gsmall = rng2.uniform(60, 140, (24, 40, 3))
    ground = cv2.resize(gsmall, (W * 2, H), interpolation=cv2.INTER_CUBIC)

    def scene(t):
        img = np.zeros((H, W, 3), np.float32)
        sky = np.linspace([120, 170, 230], [200, 220, 245], H // 2)
        img[:H // 2] = sky[:, None, :]
        pan = 6 * t                      # camera pans right 6 px/frame
        img[H // 2:] = ground[H // 2:, pan:pan + W]
        for bx, bw, bh, c in bldgs:      # skyline: 2 px/frame parallax
            x0 = bx - 2 * t
            cv2.rectangle(img, (x0, H // 2 - bh), (x0 + bw, H // 2), c, -1)
        for cx, cy, r, c, vx, vy in objs:
            cv2.circle(img, (int(cx + vx * t), int(cy + vy * t)), r, c, -1)
        x = 60 + 18 * t                  # crossing "car"
        cv2.rectangle(img, (x, 250), (x + 90, 300), (30, 30, 160), -1)
        cv2.rectangle(img, (x + 15, 230), (x + 70, 252), (60, 60, 190), -1)
        cv2.circle(img, (x + 20, 300), 12, (25, 25, 25), -1)
        cv2.circle(img, (x + 70, 300), 12, (25, 25, 25), -1)
        # static film grain: photometrically consistent across frames
        img += np.random.default_rng(100).normal(0, 3, img.shape)
        return np.clip(img, 0, 255).astype(np.uint8)

    for t in range(n_frames):
        cv2.imwrite(f"{out}/frame_{t:04d}.png",
                    cv2.cvtColor(scene(t), cv2.COLOR_RGB2BGR))
    print(f"wrote {n_frames} frames to {out}/")


if __name__ == "__main__":
    main()
