"""Beyond-HBM TRAINING measurements (VERDICT r2, missing #3).

The on-demand Pallas path (``corr_impl='pallas'``) exists so RAFT can
TRAIN at shapes where the materialized all-pairs volume exceeds HBM
(reference ``--alternate_corr``, README.md:75-80 — whose backward the
reference never even wired, correlation.cpp:51-54).  Round 2 proved the
inference side (1440x2560 eval at 1.13 f/s where all-pairs OOMs); this
script measures full TRAINING steps — forward + backward + AdamW update
— at >=720p full-frame shapes, recording pairs/s and HBM headroom.

Shapes:
- 544x960   (~540p full frame; all-pairs volume at 1/8 res would be
  (68*120)^2 * 4 levels-ish ~ 23 GB fp32 -> beyond HBM at fp32, ~11.6 GB
  bf16 at batch 1)
- 736x1280  (720p, /8-aligned)
- 1440x2560 (the round-2 flagship eval shape, trained)

Usage: python scripts/bench_beyond_hbm.py [--out out.json]
"""

from __future__ import annotations

import argparse
import json
import os.path as osp
import sys
import time

sys.path.insert(0, osp.dirname(osp.dirname(osp.abspath(__file__))))


def measure(H, W, batch, corr_impl, remat_policy="save_corr", iters=12,
            steps=5, scan_unroll=1):
    # scan_unroll=1 here (vs the bench default 12): at beyond-HBM shapes
    # each refinement iteration is O(100 ms) of device work, so unroll
    # buys nothing — and the 12x graph crashed the remote compile helper
    # outright at 1440x2560 (HTTP 500, BENCH_BEYOND_HBM_r04 first run).
    import jax
    import numpy as np

    from raft_tpu.config import RAFTConfig, TrainConfig
    from raft_tpu.models.raft import RAFT
    from raft_tpu.parallel.mesh import make_mesh, shard_batch
    from raft_tpu.train.optim import make_optimizer
    from raft_tpu.train.step import init_state, make_train_step

    mesh = make_mesh(num_data=jax.device_count(), num_spatial=1)
    model_cfg = RAFTConfig.full(compute_dtype="bfloat16",
                                corr_impl=corr_impl,
                                remat=True, remat_policy=remat_policy,
                                scan_unroll=scan_unroll)
    cfg = TrainConfig(num_steps=1000, batch_size=batch,
                      image_size=(H, W), iters=iters)
    model = RAFT(model_cfg)
    tx = make_optimizer(cfg.lr, cfg.num_steps, cfg.wdecay, cfg.epsilon,
                        cfg.clip)
    state = init_state(model, tx, jax.random.PRNGKey(0), (48, 64))
    step_fn = make_train_step(model, tx, cfg, mesh)
    rng = np.random.default_rng(0)
    batch_d = shard_batch({
        "image1": rng.uniform(0, 255, (batch, H, W, 3)).astype(np.float32),
        "image2": rng.uniform(0, 255, (batch, H, W, 3)).astype(np.float32),
        "flow": (8 * rng.standard_normal((batch, H, W, 2))).astype(
            np.float32),
        "valid": np.ones((batch, H, W), np.float32),
    }, mesh)
    key = jax.random.PRNGKey(1)
    # Compile ONCE via AOT and reuse the executable for both the memory
    # accounting and the timing loop (compiling through the jit cache
    # AND hbm_usage separately risks paying the minutes-scale compile
    # twice at these shapes).  True peak-HBM accounting comes from XLA's
    # buffer assignment (round-3 VERDICT weak #2: device.memory_stats()
    # returns None on this backend and the old code silently recorded
    # 0.0 — hbm_usage() reports the executable's exact peak, or says
    # "unavailable").
    from raft_tpu.utils.profiling import hbm_usage
    compiled = step_fn.lower(state, batch_d, key).compile()
    step_fn = compiled
    hbm = hbm_usage(compiled)
    for _ in range(2):
        state, metrics = step_fn(state, batch_d, key)
    loss = float(metrics["loss"])   # sync
    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = step_fn(state, batch_d, key)
    float(metrics["loss"])
    dt = time.perf_counter() - t0
    stats = jax.local_devices()[0].memory_stats() or {}
    if "bytes_limit" in stats:
        limit = round(stats["bytes_limit"] / 2**30, 2)
        limit_src = "memory_stats.bytes_limit"
    else:
        # Measured allocation-probe artifact (scripts/hbm_limit.py) —
        # this backend's memory_stats() is None (VERDICT r4 weak #4).
        from raft_tpu.utils.profiling import load_hbm_limit
        limit, limit_src = load_hbm_limit(default_gb="unavailable")
    return {
        "shape": f"{H}x{W}", "batch": batch, "corr_impl": corr_impl,
        "remat_policy": remat_policy, "iters": iters,
        "pairs_per_sec_per_chip": round(
            steps * batch / dt / jax.device_count(), 3),
        "loss_finite": bool(np.isfinite(loss)),
        **hbm,
        "hbm_limit_gb": limit,
        "hbm_limit_source": limit_src,
    }


CASES = [
    # (H, W, batch, corr_impl) — training steps, full model, bf16.
    (544, 960, 2, "pallas"),
    (736, 1280, 1, "pallas"),
    (1088, 1920, 1, "pallas"),   # round-3 blocker: fused bwd VMEM OOM
    (1440, 2560, 1, "pallas"),   # round-2 flagship eval shape, trained
]


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="BENCH_BEYOND_HBM.json")
    ap.add_argument("--only", default=None,
                    help="run just one case, e.g. 1440x2560 "
                         "(for the bwd block_q sweep)")
    args = ap.parse_args(argv)
    results = []
    cases = [c for c in CASES
             if args.only is None or f"{c[0]}x{c[1]}" == args.only]
    if not cases:
        raise SystemExit(f"--only {args.only!r} matches no case "
                         f"(have: {[f'{h}x{w}' for h, w, _, _ in CASES]})")
    for H, W, b, impl in cases:
        try:
            r = measure(H, W, b, impl)
        except Exception as e:  # OOM / compile failure: record honestly
            r = {"shape": f"{H}x{W}", "batch": b, "corr_impl": impl,
                 "error": f"{type(e).__name__}: {str(e)[:300]}"}
        print(json.dumps(r), flush=True)
        results.append(r)
    with open(args.out, "w") as f:
        json.dump(results, f, indent=2)
    print(f"-> {args.out}", flush=True)


if __name__ == "__main__":
    main()
