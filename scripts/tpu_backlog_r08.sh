#!/bin/bash
# Round-8 TPU backlog, priority order: validate the flow-quality
# observability stack (raft_tpu/obs/quality.py) on hardware and arm
# the quality gates.  Off-TPU the proxies are calibrated on synthetic
# fixtures only (tests/test_quality.py); this round measures the
# sampled-scoring overhead on a real chip, runs the drill at
# production shapes, stamps the first real-dataset proxy<->EPE
# Spearman numbers, and turns on --max-quality-drift /
# --max-canary-proxy-delta for the BENCH series.  Every step is
# independently resumable.
set -x -o pipefail
cd "$(dirname "$0")/.."

# 0. The drill at production scale (NOT --tiny: (64, 96) bucket, an
#    8-iteration budget): proxy-canary refusal of scrambled weights,
#    then the chaos-injected hot swap caught by the PSI drift
#    detector.  The record's quality_drift_score /
#    canary_proxy_delta_pct are the first hardware data points for
#    the step-3 gates.
python scripts/quality_smoke.py 2>&1 \
    | tee /tmp/quality_smoke_r08.log | tail -1 > QUALITY_SMOKE_r08.json

# 1. Scoring overhead on the serve hot path: A/B the same slot-mode
#    load at rate 0 vs rate 1.  The photometric program runs off the
#    iter_step critical path, so p50 latency and pairs/sec should
#    move < 2%; a bigger delta means the per-retirement host transfer
#    of delta_max or the scoring program is contending with the
#    device loop — tune quality_sample_rate DOWN (0.05 is plenty for
#    drift detection at production request rates) before arming gates.
python scripts/bench_serve.py --batching slot --shapes 440x1024 \
    --requests 128 --concurrency 16 \
    2>&1 | tee /tmp/bench_serve_q0_r08.log | tail -1 > BENCH_SERVE_Q0_r08.json
python scripts/bench_serve.py --batching slot --shapes 440x1024 \
    --requests 128 --concurrency 16 --quality-sample-rate 1.0 \
    2>&1 | tee /tmp/bench_serve_q1_r08.log | tail -1 > BENCH_SERVE_Q1_r08.json

# 2. Real-dataset calibration: proxy<->EPE Spearman on FlyingChairs
#    with the real checkpoint (weights-blocked off-TPU; see
#    docs/REAL_WEIGHTS_RUNBOOK.md).  The synthetic-fixture bar is
#    0.6 for photometric AND residual; record what real data gives —
#    a proxy that calibrates on synthetic but not on Chairs is not a
#    trustworthy canary and its gate stays unarmed.
python -m raft_tpu evaluate --model checkpoints/raft --dataset chairs \
    --quality-proxies --quality-cycle 2>&1 \
    | tee /tmp/eval_quality_r08.log | tail -1 > EVAL_QUALITY_r08.json

# 3. Arm the quality gates against the fresh records.  Ceilings are
#    INTENTIONALLY loose on first arming (2x the drill's healthy-
#    traffic drift score; canary delta well under the scrambled
#    blowout but above legitimate-swap noise — see
#    FleetConfig.canary_proxy_budget): the point this round is that
#    the gates hold real data.  Both fail vacuously without
#    qualifying records, so a drill that silently skipped scoring
#    shows up here, not in a false pass.
python scripts/check_regression.py \
    --max-quality-drift 2.5 --max-canary-proxy-delta 1000 \
    2>&1 | tail -3

# 4. Drift soak under traced load: sampled scoring + drift detectors
#    live for a longer slot-mode run, then the telemetry fold — the
#    summary's quality block (per-proxy p50/p95, drift events) and
#    trace spans carrying quality_photometric attrs come from the
#    same stream, so slow AND bad requests correlate per trace tree.
RAFT_TRACE_SAMPLE_RATE=0.1 RAFT_TELEMETRY_DIR=/tmp/telem_r08 \
    python scripts/bench_serve.py --batching slot --shapes 440x1024 \
    --requests 256 --concurrency 8 --quality-sample-rate 0.25 \
    2>&1 | tail -1
python scripts/telemetry_summary.py /tmp/telem_r08 2>&1 | tail -1
python scripts/trace_report.py /tmp/telem_r08 2>&1 | tail -20
