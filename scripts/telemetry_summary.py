"""Fold a JSONL telemetry log into bench.py-format JSON.

Any instrumented run (``python -m raft_tpu train --telemetry_dir ...``
or ``RAFT_TELEMETRY_DIR=...``) leaves ``telemetry-p*.jsonl`` files; this
script turns the per-step ``train_step`` stream of one run into the ONE
JSON line bench.py prints — same ``metric``/``value``/``unit``/
``vs_baseline`` schema, same metric-name mapping (imported from
bench.py, so the series cannot drift) — letting BENCH_* trajectories be
produced from any real training run instead of only the synthetic
bench::

    python scripts/telemetry_summary.py runs/telemetry/
    python scripts/telemetry_summary.py runs/telemetry/telemetry-p0.jsonl

The last ``run_config`` record in the log (and its following
``train_step`` records) is summarized by default; ``--skip`` drops the
leading steps, whose wall time is trace+compile, from the steady-state
figure (the ``compile`` event is in the log if you want that number).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from bench import (  # noqa: E402
    BASELINE_PAIRS_PER_SEC_PER_CHIP,
    _stage_name,
    _train_metric_name,
)


def parse_args(argv=None):
    p = argparse.ArgumentParser(
        description="telemetry JSONL -> bench.py JSON")
    p.add_argument("path", help="telemetry-*.jsonl file, or a directory "
                                "of them (a multi-host run's per-process "
                                "files are merged by step)")
    p.add_argument("--skip", type=int, default=2,
                   help="leading steps to drop (compile + pipeline "
                        "fill); all steps are kept when fewer exist")
    return p.parse_args(argv)


def iter_records(path):
    files = ([path] if os.path.isfile(path)
             else sorted(glob.glob(os.path.join(path, "*.jsonl"))))
    if not files:
        raise SystemExit(f"no .jsonl telemetry under {path!r}")
    for fname in files:
        with open(fname) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    yield json.loads(line)
                except ValueError:
                    pass  # torn final line of a killed run


def last_run(records):
    """``(run_config, [train_step...], [train_health...], faults,
    [trace_span...], [cost_report...])`` of the LAST run in the log
    (files append across runs; run_config marks each start).  Logs from
    builds without training-health, tracing, or cost-model telemetry
    simply yield empty lists.

    ``faults`` counts the fault-tolerance events (docs/ROBUSTNESS.md)
    over the WHOLE log, not just the last run: resume fallback fires
    BEFORE the resumed run's run_config is written, and a quarantined
    sample is data rot regardless of which restart hit it — the
    check_regression gate wants the conservative total.

    ``quality`` collects the flow-quality stream
    (``quality_score``/``quality_drift`` events,
    ``raft_tpu/obs/quality.py``) over the whole log like ``faults`` —
    drift that fired before the last restart is still drift.

    ``retires`` collects ``serve_retire`` iteration counts over the
    whole log, split by the event's ``warm`` tag (streaming warm-start
    frames vs cold admissions, docs/SERVING.md "Streaming sessions") —
    the split is what makes the warm saving visible in a summary.

    ``incidents`` collects the incident-engine stream
    (``incident_open``/``incident_close``/``slo_burn`` events plus the
    final SLO gauge values, docs/OBSERVABILITY.md "Incidents & SLOs")
    over the whole log like ``faults`` — an incident that opened before
    the last restart still happened.

    ``fabric`` collects the multi-host fabric stream (``fleet_scale``
    autoscaler moves, ``net_retry`` wire failures,
    ``fleet_remote_rejoin`` partition heals; docs/SERVING.md
    "Multi-host fabric") over the whole log — the
    ``check_regression.py --max-scale-flaps / --max-net-retry-rate``
    gates read the totals."""
    run_cfg, steps, health, spans, costs = None, [], [], [], []
    faults = {"sample_quarantine": 0, "ckpt_fallback": 0,
              "serve_retry": 0, "chaos_inject": 0}
    quality = {"scores": [], "drifts": []}
    retires = {"warm": [], "cold": []}
    incidents = {"opened": [], "closed": 0, "burns": [],
                 "burn_gauge": {}, "budget_gauge": {}}
    fabric = {"scales": [], "net_retries": 0, "rejoins": 0}
    for rec in records:
        ev = rec.get("event")
        if ev == "run_config":
            run_cfg, steps, health, spans, costs = rec, [], [], [], []
        elif ev == "train_step":
            steps.append(rec)
        elif ev == "train_health":
            health.append(rec)
        elif ev == "trace_span":
            spans.append(rec)
        elif ev == "cost_report":
            costs.append(rec)
        elif ev == "quality_score":
            quality["scores"].append(rec)
        elif ev == "quality_drift":
            quality["drifts"].append(rec)
        elif ev == "serve_retire":
            it = rec.get("iters")
            if isinstance(it, (int, float)):
                retires["warm" if rec.get("warm")
                        else "cold"].append(int(it))
        elif ev == "incident_open":
            incidents["opened"].append(rec)
        elif ev == "incident_close":
            incidents["closed"] += 1
        elif ev == "slo_burn":
            incidents["burns"].append(rec)
        elif ev == "fleet_scale":
            fabric["scales"].append(rec)
        elif ev == "net_retry":
            fabric["net_retries"] += 1
        elif ev == "fleet_remote_rejoin":
            fabric["rejoins"] += 1
        elif ev == "metrics_summary":
            # The run's final raft_cost_mfu gauge values ride along as
            # a synthetic record so summarize() folds them next to the
            # compile-time cost_report stream.
            vals = rec.get("metrics", {}).get("raft_cost_mfu",
                                              {}).get("values")
            if vals:
                costs.append({"_mfu_gauge": vals})
            # Final SLO gauge values (same pattern): a healthy tracked
            # run summarizes with explicit 0.0 burn rates, so the
            # check_regression --max-slo-burn gate has a record to read
            # even when no slo_burn event ever fired.
            for gauge, key in (("raft_slo_burn_rate", "burn_gauge"),
                               ("raft_slo_budget_remaining",
                                "budget_gauge")):
                vals = rec.get("metrics", {}).get(gauge, {}).get(
                    "values")
                if vals:
                    incidents[key] = vals
        elif ev in faults:
            faults[ev] += 1
    return (run_cfg, steps, health, faults, spans, costs, quality,
            retires, incidents, fabric)


def _wait_s(rec):
    """Consumer-side input wait of one train_step record.

    PR 3 split ``data_wait_s`` into consumer-side ``queue_wait_s`` plus
    the producer-side ``h2d_s``/``prep_s`` spans; older logs carry only
    ``data_wait_s`` (which measured the same consumer-side block)."""
    return rec.get("queue_wait_s", rec.get("data_wait_s", 0.0))


def trace_summary(spans):
    """Fold ``trace_span`` records (raft_tpu/obs/trace.py) into
    per-name duration percentiles plus trace-level counts.  Returns
    ``{}`` for logs without tracing — old logs summarize unchanged."""
    if not spans:
        return {}
    by_name = {}
    roots = {}
    for s in spans:
        by_name.setdefault(s.get("name", "?"), []).append(
            float(s.get("dur_s", 0.0)))
        if s.get("parent_id") is None:
            tid = s.get("trace_id")
            roots[tid] = (roots.get(tid, False)
                          or s.get("status") == "error")
    span_ms = {}
    for name, durs in sorted(by_name.items()):
        durs.sort()
        span_ms[name] = {
            "p50_ms": round(durs[len(durs) // 2] * 1e3, 3),
            "p95_ms": round(durs[min(int(len(durs) * 0.95),
                                     len(durs) - 1)] * 1e3, 3),
            "n": len(durs),
        }
    out = {"span_ms": span_ms}
    if roots:
        out["traces_total"] = len(roots)
        out["traced_error_rate"] = round(
            sum(1 for err in roots.values() if err) / len(roots), 4)
    return out


def cost_summary(costs, value):
    """Fold the run's ``cost_report`` events (obs/cost.py, one per
    captured program) + final ``raft_cost_mfu`` gauge values into
    config-block fields.  ``value`` is the measured pairs/sec/chip —
    multiplying it back through the compiled step's ``flops_per_pair``
    is what turns the throughput into ``achieved_tflops``/``mfu``
    (None when the device peak is unknown, e.g. CPU).  ``{}`` for logs
    without cost telemetry — old logs summarize unchanged."""
    if not costs:
        return {}
    out = {}
    by_prog = {}
    for c in costs:
        if "_mfu_gauge" in c:
            out["mfu_gauge"] = c["_mfu_gauge"]
        elif c.get("program"):
            by_prog[c["program"]] = c  # last capture wins
    if by_prog:
        out["cost"] = {
            prog: {k: c.get(k) for k in
                   ("flops", "bytes", "flops_per_pair",
                    "arithmetic_intensity", "bound_by", "source")}
            for prog, c in sorted(by_prog.items())}
    tc = by_prog.get("train_step")
    if tc and tc.get("flops_per_pair"):
        fpp = tc["flops_per_pair"]
        achieved = value * fpp / 1e12
        peak = tc.get("peak_tflops")
        out["flops_per_pair"] = fpp
        out["achieved_tflops"] = round(achieved, 4)
        out["mfu"] = round(achieved / peak, 4) if peak else None
        out["bound_by"] = tc.get("bound_by")
    return out


def _pctl(vals, q):
    vals = sorted(vals)
    return vals[min(int(len(vals) * q), len(vals) - 1)]


def quality_summary(quality):
    """Fold the flow-quality stream (``quality_score`` /
    ``quality_drift`` events, raft_tpu/obs/quality.py) into
    config-block fields: per-proxy p50/p95 over the sampled scores,
    the drift-event count, and ``quality_drift_score`` — the PEAK PSI
    score any drift event reported, which is what
    ``scripts/check_regression.py --max-quality-drift`` gates on.
    Returns ``{}`` for logs without quality events — old logs
    summarize unchanged."""
    if not quality or not (quality.get("scores")
                           or quality.get("drifts")):
        return {}
    per_proxy = {}
    for rec in quality.get("scores", []):
        for key in ("photometric", "residual", "cycle"):
            v = rec.get(key)
            if isinstance(v, (int, float)) and v >= 0:
                per_proxy.setdefault(key, []).append(float(v))
    out = {"quality": {
        "scored_total": len(quality.get("scores", [])),
        **{k: {"p50": round(_pctl(v, 0.50), 6),
               "p95": round(_pctl(v, 0.95), 6), "n": len(v)}
           for k, v in sorted(per_proxy.items())},
    }}
    drifts = quality.get("drifts", [])
    if drifts:
        scores = [d.get("score") for d in drifts
                  if isinstance(d.get("score"), (int, float))]
        out["quality_drift_events"] = len(drifts)
        if scores:
            out["quality_drift_score"] = round(max(scores), 6)
    return out


def retire_summary(retires):
    """Fold ``serve_retire`` iteration counts, split by the ``warm``
    tag, into config-block fields — p50/p95/n per class plus
    ``warm_iters_saved_frac`` (1 - warm p50 / cold p50, the same figure
    ``scripts/bench_stream.py`` records and
    ``check_regression.py --min-warm-iters-saved-frac`` gates on).
    Returns ``{}`` for logs without retirements (training logs, old
    serve logs) — they summarize unchanged."""
    if not retires or not (retires.get("warm") or retires.get("cold")):
        return {}
    out = {"serve_iters_used": {
        k: {"p50": _pctl(v, 0.50), "p95": _pctl(v, 0.95), "n": len(v)}
        for k, v in sorted(retires.items()) if v}}
    warm, cold = retires.get("warm"), retires.get("cold")
    if warm and cold:
        w50, c50 = _pctl(warm, 0.50), _pctl(cold, 0.50)
        if c50 > 0:
            out["warm_iters_saved_frac"] = round(1.0 - w50 / c50, 4)
    return out


def _slo_label(label):
    """``"slo=avail"`` (registry snapshot label string) -> ``"avail"``."""
    for part in str(label).split(","):
        if part.startswith("slo="):
            return part[len("slo="):]
    return str(label)


def incident_summary(incidents):
    """Fold the incident-engine stream (``incident_open`` /
    ``incident_close`` / ``slo_burn`` events + final SLO gauge values,
    raft_tpu/obs/incident.py + obs/slo.py) into config-block fields:
    incident counts by peak severity, how many never closed, and the
    worst per-SLO burn rate / budget remaining — merged from burn
    events AND the final gauges, so a healthy tracked run reports an
    explicit 0.0 instead of omitting the field
    (``check_regression.py --max-incidents / --max-slo-burn`` gate on
    these).  Returns ``{}`` for logs without incident telemetry — old
    logs summarize unchanged."""
    if not incidents or not (incidents.get("opened")
                             or incidents.get("burns")
                             or incidents.get("burn_gauge")):
        return {}
    out = {}
    opened = incidents.get("opened", [])
    if opened:
        by_sev = {}
        for rec in opened:
            sev = rec.get("severity", "warning")
            by_sev[sev] = by_sev.get(sev, 0) + 1
        out["incidents"] = dict(sorted(by_sev.items()))
        out["incidents_total"] = len(opened)
        out["incidents_open"] = max(
            len(opened) - incidents.get("closed", 0), 0)
    rates = {_slo_label(k): float(v)
             for k, v in incidents.get("burn_gauge", {}).items()}
    budgets = {_slo_label(k): float(v)
               for k, v in incidents.get("budget_gauge", {}).items()}
    for rec in incidents.get("burns", []):
        name = rec.get("slo", "?")
        rate = rec.get("burn_rate")
        if isinstance(rate, (int, float)):
            rates[name] = max(rates.get(name, 0.0), float(rate))
        rem = rec.get("budget_remaining")
        if isinstance(rem, (int, float)):
            budgets[name] = min(budgets.get(name, 1.0), float(rem))
    if rates:
        out["slo_burn_rates"] = {k: round(v, 4)
                                 for k, v in sorted(rates.items())}
    if budgets:
        out["slo_budget_remaining"] = {
            k: round(v, 4) for k, v in sorted(budgets.items())}
    return out


def fabric_summary(fabric):
    """Fold the multi-host fabric stream (``fleet_scale`` /
    ``net_retry`` / ``fleet_remote_rejoin`` events, serve/remote.py +
    the fleet autoscaler) into config-block fields: autoscaler move
    counts by direction, the flap count (direction reversals —
    ``check_regression.py --max-scale-flaps`` gates it), request-path
    wire-failure totals, and partition rejoins.  Returns ``{}`` for
    logs without fabric events — old logs summarize unchanged."""
    if not fabric or not (fabric.get("scales")
                          or fabric.get("net_retries")
                          or fabric.get("rejoins")):
        return {}
    out = {}
    scales = fabric.get("scales", [])
    if scales:
        ups = sum(1 for s in scales if s.get("direction") == "up")
        flaps = 0
        last = None
        for s in scales:
            d = s.get("direction")
            if last is not None and d != last:
                flaps += 1
            last = d
        out["fleet_scale"] = {"ups": ups, "downs": len(scales) - ups,
                              "flaps": flaps}
        out["scale_flaps"] = flaps
    if fabric.get("net_retries"):
        out["net_retry_total"] = fabric["net_retries"]
    if fabric.get("rejoins"):
        out["remote_rejoins_total"] = fabric["rejoins"]
    return out


def summarize(run_cfg, steps, health=None, faults=None, spans=None,
              costs=None, quality=None, retires=None, incidents=None,
              fabric=None, skip=2):
    if run_cfg is None:
        raise SystemExit("no run_config event in log (telemetry written "
                         "by an older build?) — cannot recover batch "
                         "size / device count")
    if not steps:
        raise SystemExit("no train_step events in log")
    steps = sorted(steps, key=lambda r: r.get("step", 0))
    kept = steps[skip:] if len(steps) > skip else steps
    batch = run_cfg["batch_size"]
    n_dev = max(run_cfg.get("num_devices", 1), 1)
    h, w = run_cfg["image_size"]
    wall = sum(r["step_time_s"] for r in kept)
    wait = sum(_wait_s(r) for r in kept)
    h2d = sum(r.get("h2d_s", 0.0) for r in kept)
    value = len(kept) * batch / wall / n_dev if wall > 0 else 0.0
    vs = (value / BASELINE_PAIRS_PER_SEC_PER_CHIP
          if _stage_name(h, w) == "flyingchairs" else 0.0)
    times = sorted(r["step_time_s"] for r in kept)
    # Training-health fields from the run's last train_health record
    # (docs/OBSERVABILITY.md): non-finite step count gates
    # scripts/check_regression.py; the final update-ratio and per-
    # iteration EPE curve summarize where the run's numerics ended up.
    # Old logs without the event just omit the fields.
    # Fault-tolerance whole-log totals (docs/ROBUSTNESS.md): quarantined
    # samples and checkpoint-fallback steps are silent data/state rot a
    # bench number would otherwise hide; check_regression gates on them
    # (--max-quarantined / --max-ckpt-fallback).  chaos_injected
    # distinguishes a chaos drill from organic rot.
    health_cfg = {}
    if faults is not None:
        health_cfg["quarantined_total"] = faults.get(
            "sample_quarantine", 0)
        health_cfg["ckpt_fallback_total"] = faults.get("ckpt_fallback", 0)
        if faults.get("chaos_inject"):
            health_cfg["chaos_injected_total"] = faults["chaos_inject"]
    # Tuning-registry provenance (raft_tpu/tuning.py): the run_config
    # event carries whether the run's knobs came from the autotune
    # registry (tuned/tuning_key/tuning_registry_hash); fold it through
    # so summarized runs gate under check_regression --require-tuned
    # exactly like bench.py records.  Old logs predate the field — they
    # summarize as untuned.
    health_cfg["tuned"] = bool(run_cfg.get("tuned", False))
    for k in ("tuning_key", "tuning_registry_hash", "tuning_fallback"):
        if k in run_cfg:
            health_cfg[k] = run_cfg[k]
    # Distributed-tracing fold (docs/OBSERVABILITY.md "Distributed
    # tracing"): per-span-name duration percentiles + how many traces
    # completed and what fraction erred.  Absent without trace events.
    health_cfg.update(trace_summary(spans))
    # Cost-model fold (docs/OBSERVABILITY.md "Cost model & roofline").
    health_cfg.update(cost_summary(costs, value))
    # Flow-quality fold (docs/OBSERVABILITY.md "Flow quality").
    health_cfg.update(quality_summary(quality))
    # Streaming warm/cold retirement fold (docs/SERVING.md).
    health_cfg.update(retire_summary(retires))
    # Incident + SLO-burn fold (docs/OBSERVABILITY.md "Incidents &
    # SLOs").
    health_cfg.update(incident_summary(incidents))
    # Multi-host fabric fold (docs/SERVING.md "Multi-host fabric").
    health_cfg.update(fabric_summary(fabric))
    last_health = (health or [None])[-1]
    if last_health is not None:
        health_cfg["nonfinite_steps_total"] = last_health.get(
            "nonfinite_steps_total", 0)
        if "update_ratio" in last_health:
            health_cfg["final_update_ratio"] = last_health["update_ratio"]
        if "epe_iter" in last_health:
            health_cfg["final_epe_iter"] = last_health["epe_iter"]
    return {
        "metric": _train_metric_name(h, w),
        "value": round(value, 3),
        "unit": "image-pairs/sec/chip",
        "vs_baseline": round(vs, 3),
        "config": {
            "source": "telemetry",
            "batch_size": batch,
            "num_devices": n_dev,
            "image_size": [h, w],
            "steps_measured": len(kept),
            "steps_skipped": len(steps) - len(kept),
            # queue_wait_frac near 1 -> input-bound (consumer starving);
            # h2d_frac is producer-side and OVERLAPPED when device
            # prefetch is on — big h2d_frac + small queue_wait_frac
            # means the overlap is hiding the transfer, not a problem.
            "queue_wait_frac": round(wait / wall, 4) if wall > 0 else 0.0,
            "h2d_frac": round(h2d / wall, 4) if wall > 0 else 0.0,
            "step_time_p50_s": round(times[len(times) // 2], 6),
            **health_cfg,
        },
    }


def main(argv=None):
    args = parse_args(argv)
    (run_cfg, steps, health, faults, spans, costs, quality,
     retires, incidents, fabric) = last_run(iter_records(args.path))
    print(json.dumps(summarize(run_cfg, steps, health, faults, spans,
                               costs, skip=args.skip, quality=quality,
                               retires=retires, incidents=incidents,
                               fabric=fabric)))


if __name__ == "__main__":
    main()
