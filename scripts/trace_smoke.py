"""Distributed-tracing chaos drill: prove a hedged request yields ONE
trace tree (tier-1, CPU).

Brings up a 2-replica :class:`raft_tpu.serve.ReplicaFleet` behind the
hedging :class:`raft_tpu.serve.FlowRouter` with tracing at sample rate
1.0, makes the primary replica a straggler with the ``replica_slow``
chaos fault, and walks the promises docs/OBSERVABILITY.md's tracing
section makes:

1. **One tree per request**: the straggler fires the router's hedge —
   the request runs on BOTH replicas — yet the telemetry stream
   reconstructs to a single trace tree: one ``route`` root with two
   ``attempt`` subtrees (``hedge=false`` loser, ``hedge=true`` winner),
   each carrying its replica's ``queue``/``pad``/``device`` spans.
   The loser's spans land AFTER the root flushed (the straggler batch
   ends seconds later) — the late-span path must stitch them in.
2. **Critical path attribution**: scripts/trace_report.py's backward
   walk bottoms out in the WINNER's ``device`` span; the loser (which
   ends after the root) is excluded.
3. **Exports hold**: the tree round-trips through the Perfetto
   ``trace_event`` export and the bench-record fold
   (``critical_path_ms`` + full ``queue``/``pad``/``device`` span
   coverage, the shape scripts/check_regression.py gates on).

Prints one bench.py-format JSON line (``metric: trace_smoke``,
``value`` 1.0 = every promise held); exit 0, or an assertion failure.

::

    JAX_PLATFORMS=cpu python scripts/trace_smoke.py --tiny
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def parse_args(argv=None):
    p = argparse.ArgumentParser(description="distributed-tracing drill")
    p.add_argument("--tiny", action="store_true",
                   help="smallest shapes/counts (the tier-1 CPU drill)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--keep", default=None, metavar="DIR",
                   help="keep artifacts (telemetry, AOT dir, Perfetto "
                        "export) under DIR instead of a temp dir")
    return p.parse_args(argv)


def _load_report():
    spec = importlib.util.spec_from_file_location(
        "trace_report", os.path.join(REPO, "scripts", "trace_report.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _wait_for(pred, timeout_s, what):
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        if pred():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out after {timeout_s}s waiting for "
                         f"{what}")


def main(argv=None) -> int:
    args = parse_args(argv)
    workdir = args.keep or tempfile.mkdtemp(prefix="raft-trace-smoke-")
    telem_dir = os.path.join(workdir, "telemetry")
    os.makedirs(telem_dir, exist_ok=True)

    import jax
    import numpy as np

    from raft_tpu import chaos
    from raft_tpu.config import RAFTConfig
    from raft_tpu.models.raft import RAFT
    from raft_tpu.obs import EventSink, trace
    from raft_tpu.serve import (FleetConfig, FlowRouter, ReplicaFleet,
                                RouterConfig, ServeConfig)

    model_cfg = RAFTConfig.small_model()  # fp32: CPU-friendly
    if args.tiny:
        shape = (36, 52)      # -> bucket (40, 56): the tier-1 drill
        n_followups = 2
    else:
        shape = (68, 100)     # -> bucket (72, 104): heavier soak
        n_followups = 6
    bucket = tuple(-(-s // 8) * 8 for s in shape)
    model_img = jax.numpy.zeros((1,) + bucket + (3,))
    k = jax.random.PRNGKey(args.seed)
    variables = RAFT(model_cfg).init({"params": k, "dropout": k},
                                     model_img, model_img, iters=1)

    sink = EventSink(telem_dir)
    trace.configure(sample_rate=1.0, sink=sink)

    # The straggler sleep (3 s) dwarfs the hedge timer (0.25 s): the
    # hedge fires onto the sibling replica, which answers long before
    # the straggler — same proven geometry as test_fleet.py's drill.
    serve_cfg = ServeConfig(iters=2, max_batch=2, batch_sizes=(2,),
                            max_wait_ms=5, max_queue=64,
                            stall_timeout_s=30.0, chaos_slow_s=3.0)
    fleet = ReplicaFleet(
        variables, model_cfg, serve_cfg,
        FleetConfig(replicas=2, warmup_shapes=(shape,),
                    restart_backoff_s=0.05, restart_backoff_max_s=0.5,
                    health_poll_s=0.05,
                    aot_dir=os.path.join(workdir, "aot")))
    fleet.start()
    router = FlowRouter(fleet, RouterConfig(hedge_timeout_s=0.25))
    checks = {}
    rng = np.random.default_rng(args.seed)

    def frame():
        return rng.uniform(0, 255, shape + (3,)).astype(np.float32)

    report = _load_report()

    def span_count(name):
        sink.flush()
        try:
            return sum(1 for s in report.load_spans(telem_dir)
                       if s.get("name") == name)
        except SystemExit:  # no .jsonl file yet
            return 0

    try:
        # -- the hedged request ---------------------------------------
        chaos.install(chaos.FaultPlan.parse("replica_slow@batch=1",
                                            seed=args.seed))
        t0 = time.perf_counter()
        flow = router.infer(frame(), frame(), timeout=60)
        dt = time.perf_counter() - t0
        chaos.uninstall()
        assert flow.shape == shape + (2,)
        assert dt < 2.5, f"hedge did not cover the {dt:.1f}s straggler"
        rstats = router.router_stats()
        assert rstats["hedges_total"] == 1, rstats
        assert rstats["hedge_wins_total"] == 1, rstats

        # The loser attempt (and its queue/pad/device spans) only ends
        # when the straggler batch wakes up — wait for BOTH attempt
        # subtrees to reach the stream before reconstructing.
        _wait_for(lambda: span_count("attempt") >= 2, 30,
                  "both attempt spans (incl. the straggler's late one)")
        # a few untraced-path-free normal requests for stats depth
        for _ in range(n_followups):
            router.infer(frame(), frame(), timeout=60)
        _wait_for(lambda: span_count("route") >= 1 + n_followups, 30,
                  "the follow-up request roots")
        sink.flush()

        # -- 1. one tree, two attempts --------------------------------
        traces = report.build_traces(report.load_spans(telem_dir))
        hedged = [t for t in traces.values()
                  if report.root_of(t) is not None
                  and report.root_of(t).get("hedged")]
        assert len(hedged) == 1, \
            f"expected exactly one hedged trace, got {len(hedged)} " \
            f"of {len(traces)} total"
        tree = hedged[0]
        root = report.root_of(tree)
        assert root["name"] == "route", root
        attempts = [s for s in tree["spans"].values()
                    if s["name"] == "attempt"]
        assert len(attempts) == 2, attempts
        assert {a.get("hedge") for a in attempts} \
            == {True, False}, attempts
        assert {a.get("replica") for a in attempts} \
            == {"r0", "r1"}, attempts
        for a in attempts:  # each subtree carries its engine spans
            kids = {c["name"]
                    for c in tree["children"].get(a["span_id"], [])}
            assert {"queue", "pad", "device"} <= kids, (a, kids)
        winner = next(a for a in attempts if a.get("won"))
        loser = next(a for a in attempts if not a.get("won"))
        assert winner["hedge"] is True
        checks["one_tree"] = {
            "trace_id": root["trace_id"], "spans": len(tree["spans"]),
            "winner_replica": winner["replica"],
            "loser_dur_s": round(loser["dur_s"], 2)}

        # -- 2. critical path bottoms out in the winner's device ------
        path = report.critical_path(tree)
        names = [rec["name"] for rec, _ in path]
        assert names[0] == "route" and names[-1] == "device", names
        assert winner["span_id"] in [rec["span_id"] for rec, _ in path], \
            f"critical path skipped the hedge winner: {names}"
        assert loser["span_id"] not in [rec["span_id"] for rec, _ in
                                        path], \
            "the straggler (ends after the root) is on the critical path"
        report.print_waterfall(tree, out=sys.stderr)
        checks["critical_path"] = [
            f"{rec['name']}:{ms:.1f}ms" for rec, ms in path]

        # -- 3. exports: Perfetto + gateable bench record -------------
        events = report.perfetto_events(traces)
        out_json = os.path.join(workdir, "trace.perfetto.json")
        with open(out_json, "w") as f:
            json.dump(events, f)
        with open(out_json) as f:
            loaded = json.load(f)
        assert any(e.get("ph") == "X" for e in loaded["traceEvents"])
        rec = report.bench_record(traces)
        cov = set(rec["config"]["serve_span_names"])
        assert {"queue", "pad", "device"} <= cov, cov
        assert rec["config"]["critical_path_ms"].get("device", 0) > 0
        checks["exports"] = {
            "perfetto_events": len(loaded["traceEvents"]),
            "traces_total": rec["value"],
            "critical_path_ms": rec["config"]["critical_path_ms"]}
        ok = True
    finally:
        chaos.uninstall()
        fleet.stop(drain=False)  # never wait out a chaos straggler
        trace.reset_default_tracer()
        sink.close()

    print(json.dumps({
        "metric": "trace_smoke",
        "value": 1.0 if ok else 0.0,
        "unit": "pass",
        "vs_baseline": 0.0,
        "config": dict(checks, replicas=2,
                       workdir=workdir if args.keep else None),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
