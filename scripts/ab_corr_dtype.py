"""Corr-pyramid storage dtypes: seed-paired toy A/B (bf16/fp32/int8/...).

VERDICT r3 weak #5 / next #8: the shipped default stores the
materialized correlation pyramid in bf16 under bf16 compute
(``RAFTConfig.corr_dtype='auto'``), while the reference pins the volume
fp32 (core/corr.py:50).  The round-3 2-seed A/B was swamped by seed
variance; this runs >=8 seeds of the 300-step toy chairs stage per arm
and reports mean +/- sd of the final validation EPE, so the dtype
effect (if any) is measured against the noise floor instead of under
it.

``--dtypes`` takes any comma list of corr storage dtypes (e.g.
``float32,bfloat16,int8``); per-seed runs are PAIRED across arms (same
seeds, same data, same everything but ``corr_dtype``) and each
non-baseline arm's paired-gap stats are reported against the baseline
arm ('float32' when present, else the last listed).  The default pair
keeps the historical bf16-vs-fp32 comparison and its
``AB_CORR_DTYPE.json`` key names, so old artifacts resume cleanly.

Toy scale only — real-data full-stage EPE remains the definitive test
(weights/data-blocked, docs/REAL_WEIGHTS_RUNBOOK.md); for a pure
inference gate on a trained checkpoint use
``python -m raft_tpu evaluate --epe_delta float32,int8`` instead.

Usage: python scripts/ab_corr_dtype.py [--seeds 8] [--steps 300]
       [--dtypes bfloat16,float32] [--out AB_CORR_DTYPE.json]
"""

from __future__ import annotations

import argparse
import io
import json
import os.path as osp
import statistics
import sys
import tempfile
from contextlib import redirect_stdout

sys.path.insert(0, osp.dirname(osp.dirname(osp.abspath(__file__))))

from scripts.curriculum_toy import (CROP, _parse_validation,  # noqa: E402
                                    build_corpora)


def run_stage(data_root, workdir, corr_dtype, seed, steps, batch,
              impl="allpairs_pallas"):
    from raft_tpu.cli import train as train_cli

    name = f"ab-{corr_dtype}-{seed}"
    cli = [
        "--name", name, "--stage", "chairs",
        "--num_steps", str(steps),
        "--batch_per_chip", str(batch),
        "--image_size", str(CROP[0]), str(CROP[1]),
        "--iters", "8",
        "--val_freq", str(steps),
        "--seed", str(seed),
        # Pin an impl that actually CONSUMES corr_dtype ('auto' could
        # resolve differently per backend and silently equalize the
        # arms): allpairs_pallas on TPU; 'allpairs' honors corr_dtype
        # identically (fp32 re-accumulating lookup over stored levels)
        # and avoids the Pallas interpreter on CPU.
        "--corr_impl", impl,
        # unroll=1: identical math, and the per-run jit recompile (16
        # fresh step functions in one process) drops from ~10 min of
        # unrolled-graph XLA CPU compile to seconds.
        "--scan_unroll", "1",
        "--corr_dtype", corr_dtype,
        "--data_root", data_root,
        "--chairs_split", osp.join(workdir, "chairs_split.txt"),
        "--ckpt_dir", osp.join(workdir, "ckpts"),
        "--validation", "chairs",
    ]
    buf = io.StringIO()
    with redirect_stdout(buf):
        train_cli.main(cli)
    vals = _parse_validation(buf.getvalue())
    return vals.get("chairs_epe")



_SHORT = {"bfloat16": "bf16", "float32": "fp32", "float16": "fp16",
          "int8": "int8", "float8_e4m3fn": "fp8e4m3",
          "float8_e5m2": "fp8e5m2"}


def _short(dtype):
    return _SHORT.get(dtype, dtype)


def _baseline(dtypes):
    """The arm every other arm is compared against: fp32 (the
    reference's storage dtype) when present, else the last listed."""
    return "float32" if "float32" in dtypes else dtypes[-1]


def _finalize_stats(results):
    """Arm means/sds + per-arm paired-gap stats vs the baseline, from
    whatever per_seed prefix exists (called after every completed run so
    a cut-short session still leaves a complete, self-describing
    artifact).  With the historical default pair the emitted key names
    (``mean_diff_bf16_minus_fp32`` etc.) are unchanged."""
    import math

    dtypes = results["dtypes"]
    base = _baseline(dtypes)
    for dtype in dtypes:
        clean = [e for e in results["per_seed"][dtype] if e is not None]
        results["arms"][dtype] = {
            "n": len(clean),
            "mean": round(statistics.mean(clean), 4) if clean else None,
            "sd": round(statistics.stdev(clean), 4) if len(clean) > 1
            else None,
        }
    results["baseline"] = base
    results["paired"] = {}
    for dtype in dtypes:
        if dtype == base:
            continue
        # Paired per-seed differences are the primary readout (the seeds
        # are matched by construction); the Welch-ish arm gap below is
        # kept for context.
        pairs = [(x, y) for x, y in zip(results["per_seed"][dtype],
                                        results["per_seed"][base])
                 if x is not None and y is not None]
        tag = f"{_short(dtype)}_minus_{_short(base)}"
        if len(pairs) >= 2:
            diffs = [x - y for x, y in pairs]
            md = statistics.mean(diffs)
            sd = statistics.stdev(diffs)
            se = sd / math.sqrt(len(diffs))
            results["paired"][dtype] = {
                "n_pairs": len(diffs),
                f"mean_diff_{tag}": round(md, 4),
                "sd_diff": round(sd, 4),
                "stderr": round(se, 4),
                "t": round(md / se, 2) if se else None,
            }
        a, b = results["arms"][dtype], results["arms"][base]
        if a["sd"] is not None and b["sd"] is not None:
            se = math.sqrt((a["sd"] ** 2) / a["n"]
                           + (b["sd"] ** 2) / b["n"])
            results[f"mean_gap_{tag}"] = round(a["mean"] - b["mean"], 4)
            results[f"gap_stderr_{tag}"] = round(se, 4)
            results[f"gap_in_stderr_units_{tag}"] = round(
                (a["mean"] - b["mean"]) / se, 2) if se else None
    # Historical flat shape for the default bf16-vs-fp32 pair, so
    # downstream readers of old AB_CORR_DTYPE.json artifacts keep
    # working unchanged ("paired" flat; un-suffixed gap keys —
    # mean_gap_bf16_minus_fp32 already matches by construction).
    if set(dtypes) == {"bfloat16", "float32"}:
        if "bfloat16" in results["paired"]:
            results["paired"] = results["paired"]["bfloat16"]
        if "gap_stderr_bf16_minus_fp32" in results:
            results["gap_stderr"] = results.pop(
                "gap_stderr_bf16_minus_fp32")
            results["gap_in_stderr_units"] = results.pop(
                "gap_in_stderr_units_bf16_minus_fp32")


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--seeds", type=int, default=8)
    ap.add_argument("--steps", type=int, default=300)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--impl", default=None,
                    choices=["allpairs_pallas", "allpairs"],
                    help="default: allpairs_pallas on TPU, allpairs "
                         "elsewhere (the Pallas kernels would run in "
                         "the very slow interpreter off-TPU; both impls "
                         "honor corr_dtype identically)")
    ap.add_argument("--dtypes", default="bfloat16,float32",
                    help="comma list of corr storage dtypes to pair "
                         "(e.g. 'float32,bfloat16,int8'); the baseline "
                         "arm is float32 when present, else the last")
    ap.add_argument("--out", default="AB_CORR_DTYPE.json")
    args = ap.parse_args(argv)

    from raft_tpu.config import validate_corr_dtype

    args.dtypes = [validate_corr_dtype(d.strip(), flag="--dtypes")
                   for d in args.dtypes.split(",") if d.strip()]
    if len(args.dtypes) < 2 or len(set(args.dtypes)) != len(args.dtypes):
        raise SystemExit(f"--dtypes needs >= 2 distinct dtypes, got "
                         f"{args.dtypes}")

    import jax

    from raft_tpu.utils.profiling import enable_persistent_compile_cache

    # Only 2 distinct programs per arm exist across all seeds; without
    # the cache every run_stage recompiles them (~40 min/run on the
    # 1-core CPU fallback).
    enable_persistent_compile_cache()

    if args.impl is None:
        args.impl = ("allpairs_pallas"
                     if jax.default_backend() == "tpu" else "allpairs")
    workdir = tempfile.mkdtemp(prefix="raft_ab_dtype_")
    data_root = build_corpora(workdir)
    print(f"synthetic chairs in {data_root}", flush=True)

    results = {"steps": args.steps, "batch": args.batch,
               "impl": args.impl, "dtypes": list(args.dtypes),
               "arms": {},
               "per_seed": {d: [] for d in args.dtypes}}
    # Resume: runs are deterministic given (seed, dtype, params) —
    # verified across processes (the r04 fragment's seed-1000 pair
    # reproduced bit-for-bit in round 5) — so a prior partial artifact
    # with matching parameters seeds the per_seed lists and completed
    # runs are skipped.  Old two-arm artifacts carry no "dtypes" key;
    # they resume iff the requested arms are the historical pair.
    if osp.exists(args.out):
        try:
            with open(args.out) as f:
                prev = json.load(f)
        except Exception:
            prev = {}
        prev_dtypes = prev.get("dtypes",
                               ["bfloat16", "float32"] if prev else None)
        if (all(prev.get(k) == results[k]
                for k in ("steps", "batch", "impl"))
                and prev_dtypes == results["dtypes"]):
            for d in args.dtypes:
                results["per_seed"][d] = list(
                    prev.get("per_seed", {}).get(d, []))
            done = " / ".join(
                f"{len(results['per_seed'][d])} {_short(d)}"
                for d in args.dtypes)
            print(f"resuming: {done} runs already recorded", flush=True)
        elif prev:
            mism = {k: (prev.get(k), results[k])
                    for k in ("steps", "batch", "impl")
                    if prev.get(k) != results[k]}
            if prev_dtypes != results["dtypes"]:
                mism["dtypes"] = (prev_dtypes, results["dtypes"])
            print(f"existing {args.out} has different parameters "
                  f"{mism}; starting fresh and OVERWRITING it",
                  flush=True)
    # Seed-major, arms INNER: if the run is cut short, the finished
    # seeds still form a paired comparison (arm-major would leave one
    # arm empty).
    for i in range(args.seeds):
        for dtype in args.dtypes:
            lst = results["per_seed"][dtype]
            if len(lst) > i and lst[i] is not None:
                continue  # resumed from a prior partial artifact
            epe = run_stage(data_root, workdir, dtype, 1000 + i,
                            args.steps, args.batch, args.impl)
            print(f"{dtype} seed {1000 + i}: chairs EPE {epe}",
                  flush=True)
            if len(lst) > i:
                lst[i] = epe   # retry of a previously-failed (None) run
            else:
                lst.append(epe)
            _finalize_stats(results)   # arms/gap valid at every prefix
            with open(args.out, "w") as f:  # incremental: a crash later
                json.dump(results, f, indent=2)  # keeps finished seeds
    _finalize_stats(results)
    with open(args.out, "w") as f:
        json.dump(results, f, indent=2)
    print(json.dumps(results, indent=2), flush=True)
    print(f"-> {args.out}", flush=True)


if __name__ == "__main__":
    main()
