#!/bin/bash
# Round-4 TPU backlog: everything queued behind the mid-round tunnel
# death, in priority order.  Run when `python -c "import jax;
# jax.devices()"` responds again.  Each step is independently resumable.
set -x -o pipefail
cd "$(dirname "$0")/.."

# 1. Full 4-stage toy curriculum with the (verified) discriminative
#    validators -> CURRICULUM_TOY_r04.json
rm -rf /tmp/curr_r04
python scripts/curriculum_toy.py /tmp/curr_r04 \
    --out CURRICULUM_TOY_r04.json 2>&1 | tee /tmp/curr_r04.log | tail -20

# 2. 8-seed bf16-vs-fp32 corr-storage A/B -> AB_CORR_DTYPE.json
python scripts/ab_corr_dtype.py --out AB_CORR_DTYPE.json 2>&1 | tee /tmp/ab_r04.log | tail -25

# 3. Eval-forward refresh with the new regression pin
BENCH_MODE=eval python bench.py 2>&1 | tee /tmp/bench_eval_r04.log | tail -2

# 4. Final headline bench
python bench.py 2>&1 | tee /tmp/bench_r04.log | tail -2
