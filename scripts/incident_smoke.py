"""Incident-engine chaos drill: one cascade, ONE correlated incident
(tier-1, CPU).

Brings up a 2-replica fleet with SLO tracking and the incident engine
on, then walks the two promises docs/OBSERVABILITY.md's "Incidents &
SLOs" section makes:

1. **Quiet baseline is free**: under healthy load the incident manager
   opens NOTHING, and the SLO/flight-recorder layers add zero JIT
   compiles and zero device syncs (``CompileCounter``-pinned — the
   serve path stays byte-identical to the un-instrumented one).
2. **Cascade correlation**: a ``replica_kill`` plus a ``device_err``
   burst injected under open-loop load produce exactly ONE incident —
   the co-occurring signals (``serve_retry`` retries from the device
   burst, the ``replica_crash``/``fleet_restart`` arc from the kill)
   fold into it instead of opening one incident each.  Its forensic
   bundle under ``<telemetry_dir>/incidents/<id>/`` is self-contained:
   the event window, at least one captured trace tree, the metric
   snapshot, and the engine/fleet stats + resolved configs.

Prints one bench.py-format JSON line (``metric: incident_smoke``,
``value`` 1.0 = both promises held); exit 0, or an assertion failure.

::

    JAX_PLATFORMS=cpu python scripts/incident_smoke.py --tiny
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def parse_args(argv=None):
    p = argparse.ArgumentParser(description="incident-engine chaos drill")
    p.add_argument("--tiny", action="store_true",
                   help="smallest shapes/counts (the tier-1 CPU drill)")
    p.add_argument("--requests", type=int, default=None,
                   help="open-loop requests through the cascade")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--keep", default=None, metavar="DIR",
                   help="keep artifacts (telemetry + incident bundles) "
                        "under DIR instead of a temp dir")
    return p.parse_args(argv)


def _wait_for(pred, timeout_s, what):
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        if pred():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out after {timeout_s}s waiting for "
                         f"{what}")


def main(argv=None) -> int:
    args = parse_args(argv)
    n_requests = args.requests or (12 if args.tiny else 48)
    workdir = args.keep or tempfile.mkdtemp(prefix="raft-incident-smoke-")
    os.makedirs(workdir, exist_ok=True)
    telem_dir = os.path.join(workdir, "telemetry")
    env_prev = os.environ.get("RAFT_TELEMETRY_DIR")
    os.environ["RAFT_TELEMETRY_DIR"] = telem_dir

    import jax
    import numpy as np

    from raft_tpu import chaos
    from raft_tpu.config import RAFTConfig
    from raft_tpu.models.raft import RAFT
    from raft_tpu.obs import events, trace
    from raft_tpu.serve import (FleetConfig, FlowRouter, ReplicaFleet,
                                RouterConfig, ServeConfig)

    events.reset_default_sink()   # re-bind to the drill's telemetry dir
    sink = events.default_sink()
    # Full-rate tracing so the cascade's request trees land in the
    # stream (and therefore in the bundle's traces.jsonl).
    trace.configure(sample_rate=1.0, seed=args.seed, sink=sink)

    model_cfg = RAFTConfig.small_model()  # fp32: CPU-friendly
    shape = (36, 52)  # -> bucket (40, 56)
    model_img = jax.numpy.zeros((1, 40, 56, 3))
    k = jax.random.PRNGKey(args.seed)
    variables = RAFT(model_cfg).init({"params": k, "dropout": k},
                                     model_img, model_img, iters=1)

    quiet_s = 1.5 if args.tiny else 3.0
    serve_cfg = ServeConfig(
        iters=2, max_batch=2, batch_sizes=(2,), max_wait_ms=5,
        max_queue=64, stall_timeout_s=30.0,
        # enough headroom for the 3-error device_err burst
        device_retries=5, retry_backoff_s=0.01, retry_backoff_max_s=0.05,
        # SLOs at seconds scale (scaled_policy inside the tracker)
        slo_availability_target=0.99, slo_latency_target_ms=5000.0,
        slo_window_s=30.0,
        # the tentpole under test
        incidents=True, incident_window_s=10.0, incident_quiet_s=quiet_s,
        incident_cooldown_s=60.0)
    fleet = ReplicaFleet(
        variables, model_cfg, serve_cfg,
        FleetConfig(replicas=2, warmup_shapes=(shape,),
                    restart_backoff_s=0.05, restart_backoff_max_s=0.5,
                    health_poll_s=0.05,
                    aot_dir=os.path.join(workdir, "aot")),
        sink=sink)  # one stream: fleet + engines + tracer + chaos
    fleet.start()
    router = FlowRouter(fleet, RouterConfig())
    checks = {}
    rng = np.random.default_rng(args.seed)

    def frame():
        return rng.uniform(0, 255, shape + (3,)).astype(np.float32)

    def incidents_snap():
        return fleet.stats()["fleet"]["incidents"]

    try:
        # -- 1. quiet baseline: zero incidents, zero added compiles ---
        r0, r1 = fleet.replicas
        for _ in range(4):
            flow = router.infer(frame(), frame(), timeout=120)
            assert flow.shape == shape + (2,)
        counts0 = dict(r0.engine.compile_counter.counts())
        assert r1.engine.compile_counter.counts() == {}, \
            "replica 1 compiled despite AOT import (SLO/incident " \
            "layers must not add compiles)"
        for _ in range(4):
            router.infer(frame(), frame(), timeout=120)
        assert dict(r0.engine.compile_counter.counts()) == counts0, \
            "steady-state request compiled with incident engine on"
        snap = incidents_snap()
        assert snap["opened"] == 0 and snap["open"] is None, \
            f"quiet baseline opened an incident: {snap}"
        assert not os.path.isdir(os.path.join(telem_dir, "incidents")), \
            "bundle directory created with no incident"
        checks["quiet_baseline"] = {"requests": 8, "incidents": 0}

        # -- 2. the cascade: kill + device-error burst under load -----
        # p-based one-shot kill (the baseline already advanced the
        # batch counters, so a batch=N trigger would never match).
        chaos.install(chaos.FaultPlan.parse(
            "replica_kill@p=1,times=1;device_err@p=1,times=3",
            seed=args.seed))
        futures = []
        for _ in range(n_requests):
            futures.append(router.submit(frame(), frame()))
            time.sleep(0.01)  # open loop: arrivals keep coming
        results = [f.result(timeout=120) for f in futures]
        chaos.uninstall()
        assert all(r.shape == shape + (2,) for r in results), \
            "a request accepted before the cascade never produced flow"
        rstats = router.router_stats()
        assert rstats["dropped_total"] == 0, rstats
        _wait_for(lambda: sum(r.restarts for r in fleet.replicas) >= 1
                  and all(r.state == "ready" for r in fleet.replicas),
                  30, "supervised restart of the killed replica")

        # -- 3. exactly ONE correlated incident, then quiet close -----
        _wait_for(lambda: incidents_snap()["opened"] >= 1
                  and incidents_snap()["open"] is None,
                  30, "the incident to open and quiet-close")
        snap = incidents_snap()
        assert snap["opened"] == 1, \
            f"cascade must correlate into ONE incident, got {snap}"

        inc_root = os.path.join(telem_dir, "incidents")
        bundles = sorted(os.listdir(inc_root))
        assert len(bundles) == 1, f"expected 1 bundle, got {bundles}"
        bdir = os.path.join(inc_root, bundles[0])
        with open(os.path.join(bdir, "incident.json")) as f:
            inc = json.load(f)
        assert inc["status"] == "closed", inc
        signals = {s["event"] for s in inc["signals"]}
        assert "serve_retry" in signals, \
            f"device_err burst missing from signals: {sorted(signals)}"
        assert signals & {"replica_crash", "fleet_restart"}, \
            f"replica_kill arc missing from signals: {sorted(signals)}"
        with open(os.path.join(bdir, "events.jsonl")) as f:
            window = [json.loads(l) for l in f]
        assert window, "bundle event window is empty"
        with open(os.path.join(bdir, "traces.jsonl")) as f:
            spans = [json.loads(l) for l in f]
        assert spans, "bundle captured no trace tree"
        with open(os.path.join(bdir, "stats.json")) as f:
            stats = json.load(f)
        assert "fleet_stats" in stats and "fleet_config" in stats, \
            f"stats snapshot incomplete: {sorted(stats)}"
        checks["cascade"] = {
            "incident": inc["id"], "severity": inc["severity"],
            "signals": [s["event"] for s in inc["signals"]],
            "window_events": len(window), "trace_spans": len(spans)}

        # -- 4. post-cascade: still serving, still zero compiles ------
        restarted = next(r for r in fleet.replicas if r.restarts)
        flow = router.infer(frame(), frame(), timeout=120)
        assert flow.shape == shape + (2,)
        assert restarted.engine.compile_counter.counts() == {}, \
            "restarted replica compiled (AOT import must still hold)"
        slo = fleet.replicas[0].engine.stats()["slo"]
        assert "availability" in slo, slo
        checks["post_cascade"] = {
            "restarts": {r.name: r.restarts for r in fleet.replicas},
            "availability_budget": slo["availability"][
                "budget_remaining"]}
        # Gate producers: check_regression.py --max-incidents SEV:N
        # reads config.incidents (severity -> count) and --max-slo-burn
        # NAME:RATE reads config.slo_burn_rates (name -> burn rate).
        checks["incidents"] = {inc["severity"]: 1}
        checks["slo_burn_rates"] = {
            name: entry["burn_rate"] for name, entry in slo.items()}
        ok = True
    finally:
        chaos.uninstall()
        fleet.stop()
        trace.reset_default_tracer()
        events.reset_default_sink()
        if env_prev is None:
            os.environ.pop("RAFT_TELEMETRY_DIR", None)
        else:
            os.environ["RAFT_TELEMETRY_DIR"] = env_prev

    print(json.dumps({
        "metric": "incident_smoke",
        "value": 1.0 if ok else 0.0,
        "unit": "pass",
        "vs_baseline": 0.0,
        "config": dict(checks, requests=n_requests, replicas=2,
                       workdir=workdir if args.keep else None),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
