#!/bin/bash
# Round-9 TPU backlog, priority order: re-baseline the streaming
# session path (docs/SERVING.md "Streaming sessions") on hardware.
# Off-TPU the warm-start numbers come from random weights on a tiny
# synthetic clip; this round measures the real encoder-work saving
# (wenc vs enc in the cost ledger), the warm-vs-cold iters_used split
# at production shapes, and the accuracy cost of forward-warp carry
# with the real checkpoint — then arms the two streaming gates on the
# fresh records.  Every step is independently resumable.
set -x -o pipefail
cd "$(dirname "$0")/.."

# 0. The streaming bench at production scale: 24-frame clips, four
#    concurrent sessions (each pinning a slot lane), full 32-iteration
#    cold budget with warm frames capped at 8.  The record's
#    warm_iters_saved_frac / stream_epe_delta are the first hardware
#    data points for the step-3 gates; encoder_flops_saved_frac is
#    stamped from the compile-time cost ledger and should sit near the
#    ~34% the tiny CPU run predicts.
python scripts/bench_stream.py --hw 440x1024 --frames 24 --sessions 4 \
    --iters 32 --stream-warm-iters 8 --slots 8 \
    2>&1 | tee /tmp/bench_stream_r09.log | tail -1 > BENCH_STREAM_r09.json

# 1. Warm-budget sweep: the tiny run pins the saving by BUDGET
#    (stream_warm_iters < iters); on hardware the interesting number
#    is how few iterations a warm frame needs under the in-graph
#    early-exit predicate alone (no cap).  If the no-cap arm's warm
#    p50 already sits well under the cold p50, serve with no cap and
#    let convergence decide; otherwise keep the explicit budget.
python scripts/bench_stream.py --hw 440x1024 --frames 24 --sessions 4 \
    --iters 32 --early-exit-threshold 0.05 \
    2>&1 | tee /tmp/bench_stream_nocap_r09.log \
    | tail -1 > BENCH_STREAM_NOCAP_r09.json

# 2. Streamed accuracy with the real checkpoint (weights-blocked
#    off-TPU; see docs/REAL_WEIGHTS_RUNBOOK.md): the CPU stream_epe_
#    delta is random-weights noise — the number that decides whether
#    warm start ships is the delta with trained weights, where the
#    forward-warped init is actually near the optimum.  Compare the
#    streamed Sintel-clip EPE against the independent-pair arm in
#    BENCH_STREAM_r09.json before trusting the step-3 ceiling.
python -m raft_tpu evaluate --model checkpoints/raft --dataset sintel \
    2>&1 | tee /tmp/eval_stream_r09.log | tail -1 > EVAL_STREAM_r09.json

# 3. Arm the streaming gates against the fresh records.  Floors /
#    ceilings are INTENTIONALLY loose on first arming (half the
#    measured warm saving; 2x the measured EPE delta): the point this
#    round is that the gates hold real data.  Both fail vacuously
#    without a qualifying record, so a bench that silently skipped an
#    arm shows up here, not in a false pass.
python scripts/check_regression.py \
    --min-warm-iters-saved-frac 0.15 --max-stream-epe-delta 0.5 \
    2>&1 | tail -3

# 4. Session-lifecycle soak under traced load: long-running sessions
#    across a rolling update_weights, TTL evictions under lane
#    pressure, and a failover cold restart — then the telemetry fold.
#    The summary's serve_iters_used warm/cold split and the
#    warm-tagged device spans come from the same stream, so slow AND
#    cold-restarted frames correlate per trace tree.  Watch
#    raft_fleet_stream_restarts_total: restarts on every update mean
#    the generation check is too eager; zero across an update means
#    sessions are silently serving stale weights.
RAFT_TRACE_SAMPLE_RATE=0.1 RAFT_TELEMETRY_DIR=/tmp/telem_r09 \
    python scripts/bench_stream.py --hw 440x1024 --frames 48 \
    --sessions 8 --iters 32 --stream-warm-iters 8 --slots 8 \
    2>&1 | tail -1
python scripts/telemetry_summary.py /tmp/telem_r09 2>&1 | tail -1
python scripts/trace_report.py /tmp/telem_r09 2>&1 | tail -20
