"""Toy-scale FULL-curriculum end-to-end on real hardware.

The real corpora (400 GB) and model zoo are unreachable in this container,
so this is the strongest in-container quality evidence available
(VERDICT r2, next #3): build synthetic versions of all FIVE corpora in
the reference's exact directory layouts (SURVEY.md C8), then drive the
REAL ``raft_tpu.cli.train`` through the complete
chairs -> things -> sintel -> kitti curriculum — the
``scripts/train_standard.sh`` shape (reference train_standard.sh:3-6) at
toy scale — via the resumable curriculum driver
(``raft_tpu/curriculum.py``: stage ledger on disk, weights-only seeding
between stages, kill-anywhere resume by re-running the same command).
The validator EPE trajectory is written to a JSON ledger.

Scenes are rigid translations of smooth random textures (exactly
representable flow), so a correct training stack must drive EPE well
below 1 px at every stage.

Usage:  python scripts/curriculum_toy.py [workdir] [--steps N]
"""

from __future__ import annotations

import argparse
import json
import os
import os.path as osp
import sys
import tempfile

import numpy as np

sys.path.insert(0, osp.dirname(osp.dirname(osp.abspath(__file__))))

from raft_tpu.data import frame_utils  # noqa: E402

H, W = 128, 160          # native synthetic frame size
CROP = (64, 96)          # training crop (stages override nothing else)


def _texture(rng, h=H, w=W, margin=16):
    """Smooth random RGB texture with a border margin for shifting."""
    import cv2

    small = rng.uniform(0, 255, ((h + 2 * margin) // 8,
                                 (w + 2 * margin) // 8, 3))
    return cv2.resize(small, (w + 2 * margin, h + 2 * margin),
                      interpolation=cv2.INTER_CUBIC).clip(0, 255)


def _chain(rng, n_frames, max_shift=6):
    """(frames, flows): ``n_frames`` windows sliding over one texture
    canvas; ``flows[i]`` is the EXACT integer flow frame i -> i+1
    (frame_{i+1}(x) = frame_i(x - flow)), so EPE -> 0 is achievable."""
    m = 16 + max_shift * (n_frames - 1)
    canvas = _texture(rng, margin=m)
    oy, ox = m, m
    frames, offs = [], []
    for _ in range(n_frames):
        frames.append(canvas[oy:oy + H, ox:ox + W].astype(np.uint8))
        offs.append((oy, ox))
        u, v = rng.integers(-max_shift, max_shift + 1, 2)
        oy, ox = oy - v, ox - u
    flows = [
        np.broadcast_to(
            np.array([offs[i][1] - offs[i + 1][1],
                      offs[i][0] - offs[i + 1][0]], np.float32),
            (H, W, 2)).copy()
        for i in range(n_frames - 1)
    ]
    return frames, flows


def _pair(rng, max_shift=8):
    """(img1, img2, flow) — a 2-frame chain."""
    frames, flows = _chain(rng, 2, max_shift)
    return frames[0], frames[1], flows[0]


def _degrade(rng, img):
    """Sintel-'final'-style degradation: mild blur + per-frame local
    illumination field + occluder blobs + grain.  The real final pass
    adds motion blur / fog / effects over the clean render (reference
    README.md dataset notes); a toy analog that measurably RAISES final
    EPE over clean is what gives the clean/final validator pair
    discriminative power (VERDICT r3 weak #4 — identical fixtures made
    the two passes tautologically equal)."""
    import cv2

    # Lesson from two failed attempts (r04): global blur/gamma/grain DO
    # NOT raise EPE on rigid-translation scenes — blur is translation-
    # equivariant, monotone intensity maps are normalized away by the
    # instance-norm encoders, and constant flow lets the model regress
    # the global shift from any surviving matches.  What actually makes
    # the final pass harder is SPATIALLY LOCAL corruption, different per
    # frame: a smooth random illumination field (breaks brightness
    # constancy non-uniformly) and opaque occluder blobs (destroy local
    # matches outright, like final-pass fog/effects).
    out = cv2.GaussianBlur(img.astype(np.float32), (5, 5), 1.2)
    h, w = out.shape[:2]
    field = cv2.resize(rng.uniform(0.45, 1.55, (4, 5)).astype(np.float32),
                       (w, h), interpolation=cv2.INTER_CUBIC)
    out *= field[..., None]
    for _ in range(6):   # occluders, independent per frame
        cy, cx = rng.integers(0, h), rng.integers(0, w)
        r = int(rng.integers(6, 14))
        col = tuple(float(v) for v in rng.uniform(0, 255, 3))
        cv2.circle(out, (int(cx), int(cy)), r, col, -1)
    out += rng.normal(0.0, 8.0, out.shape).astype(np.float32)
    return np.clip(out, 0, 255).astype(np.uint8)


def _pair_piecewise(rng, max_shift=14, obj_shift=10):
    """A KITTI-style hard pair: background translation plus an
    independently-moving foreground rectangle (motion discontinuity,
    large displacements).  A 300-step toy model cannot fully fit the
    occlusion boundary, so the >3 px F1-all outlier metric stays
    strictly positive — making a KITTI F1 of exactly 0.0 a signal of a
    broken metric rather than a converged model."""
    img1, img2, flow = _pair(rng, max_shift)
    h0, w0 = rng.integers(H // 4, H // 2), rng.integers(W // 4, W // 2)
    y0 = int(rng.integers(0, H - h0))
    x0 = int(rng.integers(0, W - w0))
    du = int(rng.integers(obj_shift // 2, obj_shift + 1))
    dv = int(rng.integers(-obj_shift, -obj_shift // 2 + 1))
    # paste the shifted object region into img2 and overwrite its flow
    ys, xs = np.clip(y0 + dv, 0, H - h0), np.clip(x0 + du, 0, W - w0)
    obj = img1[y0:y0 + h0, x0:x0 + w0]
    img2 = img2.copy()
    img2[ys:ys + h0, xs:xs + w0] = obj
    flow = flow.copy()
    flow[y0:y0 + h0, x0:x0 + w0] = (xs - x0, ys - y0)
    return img1, img2, flow


def _save_img(path, arr):
    from PIL import Image

    Image.fromarray(arr).save(path)


def build_corpora(root: str, seed: int = 0):
    rng = np.random.default_rng(seed)
    ds = osp.join(root, "datasets")

    # FlyingChairs: ppm pairs + .flo + split file (reference layout).
    chairs = osp.join(ds, "FlyingChairs_release/data")
    os.makedirs(chairs, exist_ok=True)
    n_chairs, n_val = 24, 4
    for i in range(n_chairs):
        img1, img2, flow = _pair(rng)
        _save_img(osp.join(chairs, f"{i:05d}_img1.ppm"), img1)
        _save_img(osp.join(chairs, f"{i:05d}_img2.ppm"), img2)
        frame_utils.write_flo(osp.join(chairs, f"{i:05d}_flow.flo"), flow)
    with open(osp.join(root, "chairs_split.txt"), "w") as f:
        f.write("1\n" * (n_chairs - n_val) + "2\n" * n_val)

    # FlyingThings3D: frames_cleanpass/TRAIN/A/0000/left + .pfm flows
    # (into_future/into_past, 3-channel with a junk last channel).
    for scene in ("0000", "0001"):
        idirs = [osp.join(ds, f"FlyingThings3D/{p}/TRAIN/A", scene, "left")
                 for p in ("frames_cleanpass", "frames_finalpass")]
        ff = osp.join(ds, "FlyingThings3D/optical_flow/TRAIN/A", scene,
                      "into_future", "left")
        fp = osp.join(ds, "FlyingThings3D/optical_flow/TRAIN/A", scene,
                      "into_past", "left")
        for d in idirs + [ff, fp]:
            os.makedirs(d, exist_ok=True)
        frames, flows = _chain(rng, 5)
        for i, img in enumerate(frames):
            for idir in idirs:
                _save_img(osp.join(idir, f"{i:07d}.png"), img)
        pad = np.zeros((H, W, 1), np.float32)
        for i, flow in enumerate(flows):
            f3 = np.concatenate([flow, pad], axis=-1)
            frame_utils.write_pfm(osp.join(ff, f"{i:07d}.pfm"), f3)
            # into_past flow at index i+1 maps frame i+1 back to i
            frame_utils.write_pfm(osp.join(fp, f"{i + 1:07d}.pfm"), -f3)
        frame_utils.write_pfm(osp.join(fp, "0000000.pfm"),
                              np.zeros((H, W, 3), np.float32))

    # Sintel: training clean/final/flow, two scenes.
    for scene in ("alley_1", "market_2"):
        cdir = osp.join(ds, "Sintel/training/clean", scene)
        fdir = osp.join(ds, "Sintel/training/final", scene)
        wdir = osp.join(ds, "Sintel/training/flow", scene)
        for d in (cdir, fdir, wdir):
            os.makedirs(d, exist_ok=True)
        frames, flows = _chain(rng, 4)
        for i, img in enumerate(frames):
            _save_img(osp.join(cdir, f"frame_{i:04d}.png"), img)
            # final pass: degraded render -> final EPE must come out
            # strictly above clean EPE (discriminative validators).
            _save_img(osp.join(fdir, f"frame_{i:04d}.png"),
                      _degrade(rng, img))
        for i, flow in enumerate(flows):
            frame_utils.write_flo(osp.join(wdir, f"frame_{i:04d}.flo"),
                                  flow)

    # KITTI: sparse 16-bit PNG flow.
    kdir = osp.join(ds, "KITTI/training/image_2")
    kf = osp.join(ds, "KITTI/training/flow_occ")
    os.makedirs(kdir, exist_ok=True)
    os.makedirs(kf, exist_ok=True)
    for i in range(8):
        img1, img2, flow = _pair_piecewise(rng)
        _save_img(osp.join(kdir, f"{i:06d}_10.png"), img1)
        _save_img(osp.join(kdir, f"{i:06d}_11.png"), img2)
        frame_utils.write_flow_kitti(osp.join(kf, f"{i:06d}_10.png"), flow)

    # HD1K: sparse, sequence-scanned.
    hdir = osp.join(ds, "HD1k/hd1k_input/image_2")
    hf = osp.join(ds, "HD1k/hd1k_flow_gt/flow_occ")
    os.makedirs(hdir, exist_ok=True)
    os.makedirs(hf, exist_ok=True)
    frames, flows = _chain(rng, 3)
    for i, img in enumerate(frames):
        _save_img(osp.join(hdir, f"000000_{i:04d}.png"), img)
    for i, flow in enumerate(flows):
        frame_utils.write_flow_kitti(osp.join(hf, f"000000_{i:04d}.png"),
                                     flow)
    # HD1K scans flows; it needs one flow file per consumed pair only.
    frame_utils.write_flow_kitti(osp.join(hf, "000000_0002.png"), flows[-1])
    return ds


STAGES = [
    # (stage, validators, reference train_standard.sh:3-6 analog)
    ("chairs", ["chairs"]),
    ("things", ["sintel"]),
    ("sintel", ["sintel"]),
    ("kitti", ["kitti"]),
]


def _parse_validation(out: str) -> dict:
    """Structured numbers from the validator prints
    (raft_tpu/evaluate.py:182,208,239)."""
    import re

    # Match nan/inf too: a diverged run must PARSE (and then fail the
    # sanity checks) rather than leave vals empty and skip every check.
    num = r"([\d.]+|nan|inf)"
    vals = {}
    m = re.search(rf"Validation Chairs EPE: {num}", out)
    if m:
        vals["chairs_epe"] = float(m.group(1))
    for dstype in ("clean", "final"):
        m = re.search(rf"Validation \({dstype}\) EPE: {num}", out)
        if m:
            vals[f"sintel_{dstype}_epe"] = float(m.group(1))
    m = re.search(rf"Validation KITTI: {num}, {num}", out)
    if m:
        vals["kitti_epe"] = float(m.group(1))
        vals["kitti_f1"] = float(m.group(2))
    return vals


def _discriminative_checks(stage: str, vals: dict) -> dict:
    """Assertions that could actually FAIL (VERDICT r3 weak #4: with
    identical clean/final fixtures and trivially-fittable KITTI flow,
    the old toy validators could not catch a quality regression).

    - final EPE must exceed clean EPE (the final pass is degraded);
    - KITTI F1-all must be strictly positive (the piecewise fixtures
      contain unfittable >3 px occlusion-boundary outliers);
    - every stage's headline EPE must clear a sanity ceiling (the toy
      scenes are exactly representable, so a broken stack shows up as
      EPE in the tens).
    """
    checks = {}
    if "sintel_clean_epe" in vals and "sintel_final_epe" in vals:
        checks["final_epe_gt_clean"] = bool(
            vals["sintel_final_epe"] > vals["sintel_clean_epe"])
    if stage == "kitti" and "kitti_f1" in vals:
        checks["kitti_f1_positive"] = bool(vals["kitti_f1"] > 0.0)
    headline = {"chairs": "chairs_epe", "things": "sintel_clean_epe",
                "sintel": "sintel_clean_epe", "kitti": "kitti_epe"}[stage]
    # The headline metric must be PRESENT and sane; a validator that
    # printed nothing parseable is itself a failure (nan/inf parse as
    # floats and fail the < comparison).
    checks["epe_sane"] = bool(headline in vals
                              and vals[headline] < 10.0)
    return checks


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("workdir", nargs="?", default=None)
    ap.add_argument("--steps", type=int, default=300,
                    help="steps per stage")
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--out", default=None,
                    help="JSON ledger path (default workdir/curriculum.json)")
    args = ap.parse_args(argv)

    workdir = args.workdir or tempfile.mkdtemp(prefix="raft_curriculum_")
    os.makedirs(workdir, exist_ok=True)

    from raft_tpu.utils.profiling import enable_persistent_compile_cache

    # The four stages (and validators) each build fresh jit closures;
    # the shared persistent cache keeps later stages (and the A/B
    # harness, which drives the same programs) from recompiling them.
    # (No-op on the CPU backend, where cached executables are unsafe —
    # see enable_persistent_compile_cache.)
    enable_persistent_compile_cache()

    data_root = build_corpora(workdir)
    print(f"synthetic corpora in {data_root}", flush=True)

    # The schedule rides the resumable curriculum driver
    # (raft_tpu/curriculum.py): stage chaining, the on-disk stage
    # ledger, and validator-output capture are its job now — killing
    # this script anywhere and re-running the same command resumes
    # where it stopped instead of restarting stage 1.
    from raft_tpu.curriculum import (LEDGER_FILE, Manifest, StageLedger,
                                     StageSpec, run_curriculum)

    manifest = Manifest(
        base={"num_steps": args.steps, "batch_per_chip": args.batch,
              "image_size": list(CROP), "iters": 8,
              "val_freq": args.steps,  # validate at stage end
              "data_root": data_root,
              "chairs_split": osp.join(workdir, "chairs_split.txt"),
              "ckpt_dir": osp.join(workdir, "ckpts")},
        stages=[StageSpec(f"toy-{stage}", stage,
                          {"validation": list(validation)})
                for stage, validation in STAGES])
    run_curriculum(manifest, workdir)

    # Fold the driver's stage ledger (validator lines included) into
    # this script's historical evidence format, then apply the
    # discriminative checks over every stage at once.
    stage_ledger = StageLedger(osp.join(workdir, LEDGER_FILE))
    stage_ledger.load()
    ledger = {"steps_per_stage": args.steps, "stages": []}
    failed_stages = []
    for stage, _ in STAGES:
        entry = stage_ledger.stage(f"toy-{stage}")
        lines = entry.get("validation", [])
        epes = {"lines": lines} if lines else {}
        epes.update(_parse_validation("\n".join(lines)))
        checks = _discriminative_checks(stage, epes)
        ledger["stages"].append({"stage": stage, "validators": epes,
                                 "checks": checks})
        failed = [k for k, v in checks.items() if v is False]
        if failed:
            failed_stages.append({"stage": stage, "failed": failed})
    if failed_stages:  # write the evidence BEFORE failing the run
        ledger["failed_stage"] = failed_stages[0]
        _write_ledger(args, workdir, ledger)
        raise AssertionError(
            f"discriminative checks failed: {failed_stages}")

    _write_ledger(args, workdir, ledger)


def _write_ledger(args, workdir, ledger):
    out_path = args.out or osp.join(workdir, "curriculum.json")
    with open(out_path, "w") as f:
        json.dump(ledger, f, indent=2)
    print(json.dumps(ledger, indent=2), flush=True)
    print(f"ledger -> {out_path}", flush=True)


if __name__ == "__main__":
    main()
