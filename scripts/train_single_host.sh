#!/bin/bash
# Single-host full-model schedule (reference train_mixed.sh:3-6 — the 1-GPU
# mixed-precision variant; its --mixed_precision flag is bf16 here, the
# default on TPU).
set -e
mkdir -p checkpoints
python -u -m raft_tpu.cli.train --name raft-chairs --stage chairs --validation chairs --num_steps 120000 --batch_size 8 --lr 0.00025 --image_size 368 496 --wdecay 0.0001
python -u -m raft_tpu.cli.train --name raft-things --stage things --validation sintel --restore_ckpt checkpoints/raft-chairs --num_steps 120000 --batch_size 5 --lr 0.0001 --image_size 400 720 --wdecay 0.0001
python -u -m raft_tpu.cli.train --name raft-sintel --stage sintel --validation sintel --restore_ckpt checkpoints/raft-things --num_steps 120000 --batch_size 5 --lr 0.0001 --image_size 368 768 --wdecay 0.00001 --gamma 0.85
python -u -m raft_tpu.cli.train --name raft-kitti --stage kitti --validation kitti --restore_ckpt checkpoints/raft-sintel --num_steps 50000 --batch_size 5 --lr 0.0001 --image_size 288 960 --wdecay 0.00001 --gamma 0.85
