"""Flow-quality drill: prove the quality observability loop closes
(tier-1, CPU).

Brings up a 1-replica :class:`raft_tpu.serve.ReplicaFleet` with sampled
quality scoring on (``ServeConfig.quality_sample_rate=1``,
``raft_tpu/obs/quality.py``) over a procedural demo-frames-style
workload (smooth low-motion pairs, the bundled ``demo-frames/`` look —
see ``scripts/make_demo_frames.py``) and walks the two promises
docs/OBSERVABILITY.md's "Flow quality" section makes:

1. **The front door refuses degraded weights**: a finite-but-scrambled
   weight set (every param scaled x25 — passes the shape+finiteness
   canary that used to be the only gate) is pushed through
   ``update_weights`` and REFUSED at the golden-batch proxy gate
   (``FleetConfig.canary_proxy_budget``); the fleet keeps serving its
   current weights and emits ``fleet_canary_proxy`` /
   ``fleet_weight_update ok=false``.
2. **Drift catches what sneaks past the door**: the same scrambled
   weights are then hot-swapped directly into the live replica's
   engine — gated behind a deterministic ``weights_scramble`` chaos
   rule (:mod:`raft_tpu.chaos`), so the injection is telemetry-marked
   — and continued traffic makes the windowed PSI drift detector fire
   ``quality_drift``, which the fleet supervisor surfaces as
   ``fleet_quality_drift``.

Prints one bench.py-format JSON line (``metric: quality_smoke``,
``value`` 1.0 = both promises held) whose config block carries the
``quality_drift_score`` / ``canary_proxy_delta_pct`` figures that
``scripts/check_regression.py --max-quality-drift`` /
``--max-canary-proxy-delta`` gate on; exit 0, or an assertion failure.

::

    JAX_PLATFORMS=cpu python scripts/quality_smoke.py --tiny
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

#: Drift-detector sizing for the drill: under no drift the smoothed PSI
#: fluctuates around (bins-1)/window (see DriftDetector), so the tiny
#: window=8 needs a threshold well above the serve default 0.5 —
#: measured stable-noise peak ~0.9, fully-shifted ~1.7; 1.25 sits in
#: the gap.
DRIFT_WINDOW = 8
DRIFT_REFERENCE = 16
DRIFT_THRESHOLD = 1.25


def parse_args(argv=None):
    p = argparse.ArgumentParser(description="flow-quality drill")
    p.add_argument("--tiny", action="store_true",
                   help="smallest shapes/counts (the tier-1 CPU drill)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--keep", default=None, metavar="DIR",
                   help="keep artifacts (telemetry) under DIR instead "
                        "of a temp dir")
    p.add_argument("--aot-dir", default=None, metavar="DIR",
                   help="import pre-exported AOT executables (see "
                        "InferenceEngine.export_aot) instead of "
                        "compiling at fleet warmup; the fingerprint "
                        "gate still applies, so a mismatched export "
                        "falls back to compilation")
    return p.parse_args(argv)


def _wait_for(pred, timeout_s, what):
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        if pred():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out after {timeout_s}s waiting for "
                         f"{what}")


def _events(tdir, name):
    """All telemetry events called ``name`` in the JSONL dir."""
    out = []
    for path in sorted(glob.glob(os.path.join(tdir, "*.jsonl"))):
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if rec.get("event") == name:
                    out.append(rec)
    return out


def _demo_pairs(rng, shape, n):
    """Procedural demo-frames-style workload: a smooth textured scene
    panning 2 px per pair plus mild sensor noise — low-motion traffic
    with a STATIONARY quality distribution, so the drill's drift
    reference freezes on an honest baseline."""
    import numpy as np

    h, w = shape
    pad = 8
    base = rng.uniform(0.0, 255.0, (h + 2 * pad, w + 2 * pad, 3))
    kernel = np.ones(9) / 9.0
    for axis in (0, 1):
        base = np.apply_along_axis(
            lambda v: np.convolve(v, kernel, mode="same"), axis, base)
    base -= base.min()
    base *= 255.0 / max(base.max(), 1e-6)
    pairs = []
    for _ in range(n):
        im1 = base[pad:pad + h, pad:pad + w]
        im2 = base[pad:pad + h, pad - 2:pad - 2 + w]  # 2 px pan
        noise = rng.normal(0.0, 1.0, im1.shape)
        pairs.append(
            (np.clip(im1 + noise, 0, 255).astype(np.float32),
             np.clip(im2 + rng.normal(0.0, 1.0, im1.shape), 0,
                     255).astype(np.float32)))
    return pairs


def main(argv=None) -> int:
    args = parse_args(argv)
    workdir = args.keep or tempfile.mkdtemp(prefix="raft-quality-smoke-")
    os.makedirs(workdir, exist_ok=True)
    tdir = os.path.join(workdir, "telemetry")
    os.environ["RAFT_TELEMETRY_DIR"] = tdir

    import jax
    import numpy as np

    from raft_tpu import chaos
    from raft_tpu.config import RAFTConfig
    from raft_tpu.models.raft import RAFT
    from raft_tpu.obs import reset_default_sink
    from raft_tpu.serve import (FleetConfig, FlowRouter, ReplicaFleet,
                                RouterConfig, ServeConfig,
                                WeightUpdateError)

    reset_default_sink()  # bind the JSONL sink to this drill's dir

    model_cfg = RAFTConfig.small_model()  # fp32: CPU-friendly
    # --tiny: the tier-1 CPU sizing; the default exercises a bigger
    # bucket and a real iteration budget (on-device validation,
    # scripts/tpu_backlog_r08.sh).
    shape = (36, 52) if args.tiny else (64, 96)
    serve_iters = 2 if args.tiny else 8
    model_img = jax.numpy.zeros((1, 40, 56, 3))
    key = jax.random.PRNGKey(args.seed)
    variables = RAFT(model_cfg).init({"params": key, "dropout": key},
                                     model_img, model_img, iters=1)
    # Finite but useless: every parameter scaled far out of its trained
    # regime.  Passes the finiteness canary; the flow it produces is
    # garbage — exactly the failure mode the proxy gate exists for.
    scrambled = jax.device_get(jax.tree_util.tree_map(
        lambda x: np.asarray(x) * 25.0, jax.device_get(variables)))

    serve_cfg = ServeConfig(
        iters=serve_iters, max_batch=2, batch_sizes=(2,), max_wait_ms=5,
        max_queue=64, batching="slot", slots=2,  # scoring = slot path
        quality_sample_rate=1.0,
        quality_drift_reference=DRIFT_REFERENCE,
        quality_drift_window=DRIFT_WINDOW,
        quality_drift_threshold=DRIFT_THRESHOLD)
    fleet = ReplicaFleet(
        variables, model_cfg, serve_cfg,
        FleetConfig(replicas=1, warmup_shapes=(shape,),
                    restart_backoff_s=0.05, health_poll_s=0.05,
                    aot_dir=args.aot_dir or os.path.join(workdir,
                                                         "aot")))
    fleet.start()
    router = FlowRouter(fleet, RouterConfig())
    rng = np.random.default_rng(args.seed)
    n_good = DRIFT_REFERENCE + DRIFT_WINDOW  # freeze ref + fill window
    n_bad = 2 * DRIFT_WINDOW
    checks = {}
    try:
        # -- 1. healthy traffic: reference freezes, no drift ----------
        for im1, im2 in _demo_pairs(rng, shape, n_good):
            flow = router.infer(im1, im2, timeout=120)
            assert flow.shape == shape + (2,)
        eng = fleet.replicas[0].engine
        drift0 = eng.quality_drift()
        assert drift0 is not None, "quality scoring is off"
        _wait_for(lambda: all(
            d["observed"] >= n_good - 1
            for d in eng.quality_drift().values()),
            10, "quality scores to land")
        drift0 = eng.quality_drift()
        assert not any(d["drifted"] for d in drift0.values()), drift0
        assert sum(d["events"] for d in drift0.values()) == 0, drift0
        checks["baseline"] = {
            "requests": n_good,
            "scores": {k: round(d["score"], 3)
                       for k, d in drift0.items()}}

        # -- 2. scrambled weights REFUSED at the proxy gate -----------
        version0 = fleet.weights_version
        try:
            fleet.update_weights(scrambled)
            raise AssertionError(
                "finite-but-scrambled weights were NOT refused — the "
                "golden-batch proxy gate is broken")
        except WeightUpdateError as e:
            refusal = str(e)
        assert "proxy" in refusal, refusal
        assert fleet.weights_version == version0
        flow = router.infer(*_demo_pairs(rng, shape, 1)[0], timeout=120)
        assert flow.shape == shape + (2,)  # fleet kept serving
        proxy_events = _events(tdir, "fleet_canary_proxy")
        assert proxy_events and proxy_events[-1]["ok"] is False, \
            proxy_events
        delta_pct = float(proxy_events[-1]["delta_pct"])
        refusals = [e for e in _events(tdir, "fleet_weight_update")
                    if e.get("ok") is False]
        assert refusals, "no fleet_weight_update ok=false event"
        checks["proxy_refusal"] = {
            "refused": refusal[:140],
            "delta_pct": round(delta_pct, 1),
            "old": proxy_events[-1]["old"],
            "new": proxy_events[-1]["new"]}

        # -- 3. hot-swap past the gate: drift fires -------------------
        chaos.install(chaos.FaultPlan.parse("weights_scramble@call=0",
                                            seed=args.seed))
        assert chaos.should_inject("weights_scramble",
                                   point="serve.quality_drill"), \
            "chaos plan did not arm the scramble injection"
        eng._variables = jax.device_put(scrambled)
        for im1, im2 in _demo_pairs(rng, shape, n_bad):
            flow = router.infer(im1, im2, timeout=120)
            assert flow.shape == shape + (2,)
        _wait_for(lambda: any(d["events"] >= 1
                              for d in eng.quality_drift().values()),
                  10, "the PSI drift detector to fire")
        drift1 = eng.quality_drift()
        drift_score = max(d["score"] for d in drift1.values())
        assert drift_score > DRIFT_THRESHOLD, drift1
        drift_events = _events(tdir, "quality_drift")
        assert drift_events, "no quality_drift event reached telemetry"
        # The supervisor polls engine drift state and re-emits it
        # fleet-labeled for fleet-level alerting.
        _wait_for(lambda: _events(tdir, "fleet_quality_drift"),
                  10, "the fleet supervisor to surface the drift")
        assert _events(tdir, "chaos_inject"), \
            "the injected scramble left no chaos_inject marker"
        checks["drift"] = {
            "requests": n_bad,
            "drift_score": round(drift_score, 3),
            "events": sum(d["events"] for d in drift1.values()),
            "per_proxy": {k: round(d["score"], 3)
                          for k, d in drift1.items()}}
        ok = True
    finally:
        chaos.uninstall()
        fleet.stop()

    print(json.dumps({
        "metric": "quality_smoke",
        "value": 1.0 if ok else 0.0,
        "unit": "pass",
        "vs_baseline": 0.0,
        "config": {
            **checks,
            # The literal gate fields (scripts/check_regression.py
            # --max-quality-drift / --max-canary-proxy-delta).
            "quality_drift_score": round(drift_score, 6),
            "canary_proxy_delta_pct": round(delta_pct, 3),
            "drift_window": DRIFT_WINDOW,
            "drift_threshold": DRIFT_THRESHOLD,
            "workdir": workdir if args.keep else None},
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
