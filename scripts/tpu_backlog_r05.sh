#!/bin/bash
# Round-5 TPU backlog, priority order (the round started with the relay
# process dead — `jax.devices()` raises UNAVAILABLE).  Run the moment
# the chip answers; every step is independently resumable.
set -x -o pipefail
cd "$(dirname "$0")/.."

# 0. Measured usable-HBM limit (fast; unblocks the beyond-HBM "fits"
#    verdicts, VERDICT r4 weak #4) -> HBM_LIMIT.json
python scripts/hbm_limit.py 2>&1 | tee /tmp/hbm_limit.log | tail -5

# 1. Full 4-stage toy curriculum with discriminative validators
#    -> CURRICULUM_TOY_r05.json (VERDICT r4 weak #2)
rm -rf /tmp/curr_r05
python scripts/curriculum_toy.py /tmp/curr_r05 \
    --out CURRICULUM_TOY_r05.json 2>&1 | tee /tmp/curr_r05.log | tail -20

# 2. 8-seed bf16-vs-fp32 corr-storage A/B at full TPU scale
#    (the CPU fallback writes AB_CORR_DTYPE.json; the TPU run gets its
#    own artifact so a partial TPU pass can't clobber a complete CPU one)
python scripts/ab_corr_dtype.py --out AB_CORR_DTYPE_TPU.json \
    2>&1 | tee /tmp/ab_r05_tpu.log | tail -25

# 3. Headline bench (confirm the r04 builder-measured 76.0)
python bench.py 2>&1 | tee /tmp/bench_r05.log | tail -2

# 4. Eval-forward refresh against the 12.97 pin
BENCH_MODE=eval python bench.py 2>&1 | tee /tmp/bench_eval_r05.log | tail -2

# 5. Beyond-HBM refresh with the measured limit + bwd block_q sweep at
#    the slow shape (VERDICT r4 next #5: target >0.53 pairs/s at
#    1440x2560, or a measured negative)
python scripts/bench_beyond_hbm.py --out BENCH_BEYOND_HBM_r05.json \
    2>&1 | tee /tmp/bbh_r05.log | tail -6
for BQ in 1024 2048; do
  RAFT_ODM_BWD_BLOCK_Q=$BQ python scripts/bench_beyond_hbm.py \
      --only 1440x2560 --out /tmp/bbh_bq$BQ.json \
      2>&1 | tee /tmp/bbh_bq$BQ.log | tail -3
done

# 6. Spatial-shard artifact refresh (measured limit + spatial16 4K)
python scripts/shard_beyond_hbm.py --out SHARD_BEYOND_HBM_r05.json \
    2>&1 | tee /tmp/shard_r05.log | tail -12

# 7. Slot-mode serving re-baseline (PR 11 continuous batching): both
#    batching arms over the same mixed-difficulty workload, one record
#    with the slot/request p99 + throughput ratios -> BENCH_SERVE_SLOT_r05.json.
#    Gate the early-exit threshold on accuracy first, then bench with it.
python -m raft_tpu.cli.evaluate --model checkpoints/raft-things \
    --dataset sintel --early_exit_threshold 0.05,0.2 \
    2>&1 | tee /tmp/ee_sweep_r05.log | tail -8
#    (stamp the sweep's measured delta so check_regression's
#    --max-early-exit-epe-delta gate has the figure)
python scripts/bench_serve.py --batching both --shapes 440x1024 \
    --requests 128 --concurrency 16 --early-exit-threshold 0.05 \
    --early-exit-epe-delta "$(grep -o 'thr=0.05 .*delta [+-][0-9.]*' \
        /tmp/ee_sweep_r05.log | grep -o '[+-][0-9.]*$' | tr -d + \
        || echo 0)" \
    2>&1 | tee /tmp/bench_serve_slot_r05.log | tail -1 \
    > BENCH_SERVE_SLOT_r05.json

# 8. Serve-knob autotune at the serving shape (persists batching/slots/
#    early_exit_threshold winners under kind="serve" for this chip)
python scripts/autotune.py --kind serve --image 440x1024 \
    --batch-per-chip 8 2>&1 | tee /tmp/autotune_serve_r05.log | tail -3
