"""Input-pipeline microbench: decode+stack+H2D pairs/sec, prefetch A/B.

Measures the training INPUT path in isolation — per-sample "decode"
(synthetic, optionally slowed to model IO-bound storage), per-batch
stacking, optional noise prep, and the sharded ``device_put`` — driven
through :class:`raft_tpu.data.prefetch.DevicePipeline` by a consumer
whose synthetic "device step" sleeps ``--step-ms``.  One run measures
both arms: the overlapped pipeline at ``--depth`` and the serial path
(depth 0), so the JSON line answers "what does background device
prefetch buy at this shape?" without a second invocation.

Prints ONE bench.py-format JSON line (metric / value / unit /
vs_baseline) with the metric name from bench.py's shared
``_input_metric_name`` mapping — the same sharing rule that keeps
telemetry_summary.py's series from drifting.  ``value`` is the
overlapped arm's pairs/sec; the serial arm and the queue-wait split
land in ``config``.

``--tiny``: CPU smoke preset (tiny shapes, few batches, fake step) so
the pipeline stays testable without hardware; wired into the test tier
(tests/test_prefetch.py)::

    JAX_PLATFORMS=cpu python scripts/bench_input.py --tiny
    python scripts/bench_input.py --slow-ms 20   # IO-bound loader model
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def parse_args(argv=None):
    p = argparse.ArgumentParser(
        description="RAFT-TPU input-pipeline microbenchmark")
    p.add_argument("--image", default="368x496",
                   help="HxW batch shape (chairs crop default)")
    p.add_argument("--batch", type=int, default=16,
                   help="per-host batch size")
    p.add_argument("--batches", type=int, default=30,
                   help="batches measured per arm")
    p.add_argument("--depth", type=int, default=2,
                   help="device-prefetch depth of the overlapped arm")
    p.add_argument("--step-ms", type=float, default=None,
                   help="synthetic consumer step time; default = 0 "
                        "(drain at full speed: the pure pipeline "
                        "throughput bound).  Set it near your real "
                        "step time to read steady-state queue wait")
    p.add_argument("--slow-ms", type=float, default=0.0,
                   help="synthetic per-batch decode delay (slow-loader "
                        "mode: models IO-bound storage)")
    p.add_argument("--noise", action="store_true",
                   help="include the gaussian-noise host prep stage")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--tiny", action="store_true",
                   help="CPU smoke preset (tiny shape, few batches)")
    args = p.parse_args(argv)
    if args.tiny:
        args.image = "32x48"
        args.batch = 8   # divisible by the test env's 8 virtual devices
        args.batches = 8
        args.depth = 2
        args.step_ms = 2.0 if args.step_ms is None else args.step_ms
        args.noise = True
    if args.step_ms is None:
        args.step_ms = 0.0
    return args


def _sample_stream(n_batches, batch, hw, seed, slow_s):
    """Synthetic decoded samples -> stacked host batches.

    Per-sample arrays are generated up front (one template mutated per
    index — we are benchmarking stack+prep+H2D, not numpy's RNG) and
    stacked per batch like ``ShardedLoader.batches`` does; ``slow_s``
    sleeps per BATCH to model an IO-bound decode stage."""
    import numpy as np

    H, W = hw
    rng = np.random.default_rng(seed)
    base = {
        "image1": rng.uniform(0, 255, (H, W, 3)).astype(np.float32),
        "image2": rng.uniform(0, 255, (H, W, 3)).astype(np.float32),
        "flow": (8 * rng.standard_normal((H, W, 2))).astype(np.float32),
        "valid": np.ones((H, W), np.float32),
    }
    for i in range(n_batches):
        if slow_s > 0:
            time.sleep(slow_s)
        samples = []
        for j in range(batch):
            s = {k: v.copy() for k, v in base.items()}
            s["image1"][0, 0, 0] = float(i * batch + j)  # unique content
            samples.append(s)
        yield {k: np.stack([s[k] for s in samples]) for k in base}


def _run_arm(args, hw, depth, put_fn, prep_fn):
    """Drive one pipeline arm; returns (pairs_per_sec, stats dict)."""
    from raft_tpu.data.prefetch import DevicePipeline

    step_s = args.step_ms / 1e3
    pipe = DevicePipeline(
        _sample_stream(args.batches, args.batch, hw, args.seed,
                       args.slow_ms / 1e3),
        put_fn=put_fn, prep_fn=prep_fn, depth=depth)
    waits = []
    t0 = time.perf_counter()
    try:
        for _ in range(args.batches):
            t = time.perf_counter()
            batch = next(pipe)
            waits.append(time.perf_counter() - t)
            del batch
            if step_s > 0:
                time.sleep(step_s)  # the synthetic "device step"
        dt = time.perf_counter() - t0
    finally:
        pipe.close()
    # Steady state: drop the fill of the first `depth + 1` batches.
    steady = waits[depth + 1:] or waits
    return args.batches * args.batch / dt, {
        "pairs_per_sec": round(args.batches * args.batch / dt, 3),
        "queue_wait_mean_s": round(sum(steady) / len(steady), 6),
        "h2d_total_s": round(pipe.h2d_total_s, 6),
        "prep_total_s": round(pipe.prep_total_s, 6),
    }


def main(argv=None):
    args = parse_args(argv)

    import jax

    from bench import _input_metric_name
    from raft_tpu.parallel.mesh import make_batch_sharder, make_mesh
    from raft_tpu.train.loop import add_image_noise

    h, w = (int(x) for x in args.image.lower().split("x"))
    mesh = make_mesh()
    put_fn = make_batch_sharder(mesh)

    def make_prep():
        if not args.noise:
            return None
        import numpy as np

        rng = np.random.default_rng(args.seed + 1)
        return lambda b: add_image_noise(rng, b)

    _, warm = _run_arm(args, (h, w), 0, put_fn, make_prep())  # compile/alloc warmup
    value, overlapped = _run_arm(args, (h, w), args.depth, put_fn,
                                 make_prep())
    _, serial = _run_arm(args, (h, w), 0, put_fn, make_prep())
    del warm

    print(json.dumps({
        "metric": _input_metric_name(h, w),
        "value": round(value, 3),
        "unit": "image-pairs/sec",
        # No external input-pipeline baseline exists (the reference's
        # torch DataLoader was never measured in isolation); the serial
        # arm in config IS the comparison.
        "vs_baseline": 0.0,
        "config": {
            "image_size": [h, w], "batch": args.batch,
            "batches": args.batches, "depth": args.depth,
            "step_ms": args.step_ms, "slow_ms": args.slow_ms,
            "noise": args.noise, "devices": jax.device_count(),
            "overlapped": overlapped, "serial": serial,
            "overlap_speedup": round(
                overlapped["pairs_per_sec"]
                / max(serial["pairs_per_sec"], 1e-9), 3),
        },
    }))


if __name__ == "__main__":
    main()
