"""Autotune the performance knob surface and persist winners per hardware.

The r01->r03 bench trajectory (18.8 -> 74.8 image-pairs/sec/chip on the
CPU backend) came from HAND-tuning the knobs ``BENCH_r03.json`` records;
this script makes that automatic and durable: it sweeps a seeded,
time-boxed cross-product of the ``RAFTConfig`` knob surface with
bench.py-style timing (synthetic batches, warmup + steady-state steps,
``perf_counter``) and writes the winner into the per-hardware tuning
registry (``raft_tpu/tuning.py``), keyed by ``(kind, device_kind,
bucket_hw, batch)``.  From then on every train/eval/serve entry point
that leaves its knobs at the defaults gets the tuned configuration on
this hardware with no human in the loop.

::

    python scripts/autotune.py                        # train, chairs crop
    python scripts/autotune.py --image 400x720 --batch-per-chip 8
    python scripts/autotune.py --kind eval            # test-mode forward
    python scripts/autotune.py --kind serve           # engine dispatcher
    python scripts/autotune.py --tiny                 # CPU smoke (tier-1)

``--kind serve`` sweeps the ServeConfig dispatcher surface (``batching``
mode, ``slots``, ``early_exit_threshold`` — raft_tpu/serve/engine.py)
through a real InferenceEngine on a closed-loop synthetic workload and
persists the winner as a ``kind='serve'`` entry the engine consumes via
``tuning.resolve_serve_config`` (request mode ignores the slot-mode
knobs, so those points collapse to one measurement).

A finished sweep records a ``sweep_id`` (hash of the grid + timing
parameters + code version); re-running the same sweep against the same
registry is a CACHE HIT and exits immediately (``--force`` re-measures).
``--tiny`` is the CI smoke: a 2-point sweep on a toy shape, registry
write, a second in-process invocation that must hit the cache, and a
tiny train step that must CONSUME the entry via ``make_train_step``'s
default registry consult — the full zero-hand-knobs loop in one run.

Quantized corr storage ('int8') is excluded from the default eval grid —
it trades accuracy bounded by the calibration scale, so gate it with
``python -m raft_tpu evaluate ... --epe_delta float32,int8`` first and
opt in with ``--allow-quantized`` (docs/PERFORMANCE.md).
"""

from __future__ import annotations

import argparse
import hashlib
import itertools
import json
import os
import os.path as osp
import random
import sys
import time

sys.path.insert(0, osp.dirname(osp.dirname(osp.abspath(__file__))))

_AUTOTUNE_VERSION = 1


def parse_args(argv=None):
    p = argparse.ArgumentParser(
        description="sweep the RAFTConfig knob surface, persist the "
                    "winner in the per-hardware tuning registry")
    p.add_argument("--kind", default="train",
                   choices=["train", "eval", "serve"],
                   help="workload to tune: the jitted training step, "
                        "the test-mode eval forward, or the serving "
                        "engine's dispatcher knobs")
    p.add_argument("--image", default="368x496",
                   help="input HxW (the registry bucket key); default "
                        "is the chairs training crop")
    p.add_argument("--batch-per-chip", "--batch_per_chip", type=int,
                   default=16, help="per-device batch (registry key)")
    p.add_argument("--iters", type=int, default=None,
                   help="refinement iterations (default: 12 train / "
                        "32 eval)")
    p.add_argument("--steps", type=int, default=8,
                   help="timed steps per sweep point")
    p.add_argument("--warmup", type=int, default=2,
                   help="untimed warmup (compile) steps per point")
    p.add_argument("--time-box", type=float, default=900.0,
                   help="sweep wall-clock budget in seconds; points are "
                        "visited in seeded-shuffled order and the sweep "
                        "stops STARTING new points at the deadline (at "
                        "least one point always completes)")
    p.add_argument("--seed", type=int, default=0,
                   help="seed for the sweep-order shuffle and the "
                        "synthetic batch")
    p.add_argument("--out", default=None,
                   help="registry file (default: "
                        "$RAFT_TUNING_REGISTRY or "
                        "~/.cache/raft_tpu/tuning.json)")
    p.add_argument("--force", action="store_true",
                   help="re-measure even when the registry already "
                        "holds this exact sweep (same sweep_id)")
    p.add_argument("--allow-quantized", action="store_true",
                   help="include int8 corr storage in the eval grid "
                        "(run the EPE-delta gate first; "
                        "docs/PERFORMANCE.md)")
    p.add_argument("--tiny", action="store_true",
                   help="CPU smoke: 2-point sweep on a toy shape, "
                        "cache-hit re-invocation, and a tiny train "
                        "step consuming the written entry (tier-1)")
    p.add_argument("--seed-known", action="store_true",
                   help="no sweep: write the repo's MEASURED hand-tuned "
                        "winners (BENCH_r03.json, 74.8 pairs/s/chip on "
                        "the CI CPU backend) into the registry for this "
                        "device, provenance-labeled as seeded — the "
                        "known-good starting table a real sweep later "
                        "re-measures (a seeded entry has no sweep_id, "
                        "so it is never a cache hit)")
    return p.parse_args(argv)


# The r03 hand-tuned chairs-crop winners (BENCH_r03.json `config` block;
# 18.8 -> 74.8 image-pairs/sec/chip over r01).  `--seed-known` installs
# them as the registry's starting point on hardware nobody has swept
# yet; corr_impl 'allpairs_pallas' self-falls-back to 'allpairs' off-TPU
# (RAFTConfig.resolved_corr_impl), so one entry serves both backends.
_KNOWN_WINNERS = {
    ("train", (368, 496), 16): {
        "corr_impl": "allpairs_pallas",
        "corr_dtype": "auto",
        "corr_precision": "highest",
        "remat": False,
        "remat_upsample": False,
        "scan_unroll": 12,
        "fuse_upsample_in_scan": False,
        "upsample_loss_kernel": "xla",
    },
}


def seed_known(out=None):
    """Write the measured hand-tuned winners as seeded registry entries
    (provenance mode='seed-known', source=BENCH_r03.json)."""
    from raft_tpu import tuning

    keys = []
    for (kind, hw, batch), knobs in _KNOWN_WINNERS.items():
        keys.append(tuning.save_entry(
            kind, hw, batch, knobs,
            provenance={"tool": "scripts/autotune.py",
                        "mode": "seed-known",
                        "source": "BENCH_r03.json hand-tuned winners",
                        "best_value": 74.824,
                        "unit": "image-pairs/sec/chip"},
            path=out))
    return keys


def _grid(kind: str, tiny: bool, allow_quantized: bool):
    """The knob cross-product for one workload on this backend.

    Kept deliberately curated (not every RAFTConfig field): each axis
    here has MOVED a bench number in some round (BENCH_r0*.json), which
    is what makes the cross-product worth its compile time."""
    import jax

    on_tpu = jax.default_backend() == "tpu"
    # The fused Pallas kernels (PR 13) are real sweep axes only where
    # they can dispatch; off-TPU they resolve to the unfused paths, so a
    # single-value axis keeps them visible in the grid (and the
    # sweep_id) without duplicating measurements.
    fused_axis = [False, True] if on_tpu else [False]
    if kind == "serve":
        if tiny:
            return {"batching": ["request", "slot"], "slots": [2]}
        return {
            "batching": ["request", "slot"],
            "slots": [4, 8, 16],
            "early_exit_threshold": [0.0, 0.05, 0.2],
            "fused_gru": fused_axis,
        }
    if tiny:
        # Keep the tiny sweep at 2 points: one fused knob rides the
        # sweep -> save_entry -> resolve_config loop (it resolves to
        # the unfused path on the CPU smoke backend — this is plumbing
        # coverage, not a kernel measurement).  fused_lookup_encoder is
        # a single-value axis: the second fused knob doubled the smoke's
        # compile count without adding coverage the fused_gru axis
        # doesn't already give.
        return {"scan_unroll": [1],
                "fused_lookup_encoder": [False],
                "fused_gru": [False, True]}
    if kind == "eval":
        grid = {
            "corr_impl": (["allpairs", "allpairs_pallas", "pallas"]
                          if on_tpu else ["allpairs", "chunked"]),
            "corr_dtype": ["auto", "float32"],
        }
        if allow_quantized:
            grid["corr_dtype"].append("int8")
        return grid
    grid = {
        "corr_impl": (["allpairs_pallas", "allpairs"] if on_tpu
                      else ["allpairs"]),
        "corr_dtype": ["auto", "float32"],
        "scan_unroll": [1, 6, 12],
        "remat": [False, True],
        "remat_upsample": [False, True],
        "fuse_upsample_in_scan": [False, True],
        "fused_lookup_encoder": fused_axis,
        "fused_gru": fused_axis,
    }
    if on_tpu:
        grid["upsample_loss_kernel"] = ["xla", "pallas"]
    return grid


def _points(grid: dict, seed: int):
    keys = sorted(grid)
    pts = [dict(zip(keys, vals))
           for vals in itertools.product(*(grid[k] for k in keys))]
    if "batching" in grid:
        # Request mode ignores the slot-mode dispatcher knobs: collapse
        # those axes for every batching=request cross-product point
        # instead of re-timing identical configs.  Model-level knobs
        # (fused_gru etc.) affect BOTH batching modes, so they survive
        # the collapse.
        slot_only = ("slots", "early_exit_threshold")
        seen, uniq = set(), []
        for p in pts:
            if p.get("batching") == "request":
                p = {k: v for k, v in p.items() if k not in slot_only}
            key = json.dumps(p, sort_keys=True)
            if key not in seen:
                seen.add(key)
                uniq.append(p)
        pts = uniq
    random.Random(seed).shuffle(pts)
    return pts


def _sweep_id(kind, grid, hw, batch, iters, steps, warmup, seed) -> str:
    blob = json.dumps({"v": _AUTOTUNE_VERSION, "kind": kind,
                       "grid": {k: list(v) for k, v in sorted(grid.items())},
                       "hw": list(hw), "batch": batch, "iters": iters,
                       "steps": steps, "warmup": warmup, "seed": seed},
                      sort_keys=True)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def _synth_batch(hw, batch, seed):
    import numpy as np

    H, W = hw
    rng = np.random.default_rng(seed)
    return {
        "image1": rng.uniform(0, 255, (batch, H, W, 3)).astype(np.float32),
        "image2": rng.uniform(0, 255, (batch, H, W, 3)).astype(np.float32),
        "flow": (8.0 * rng.standard_normal((batch, H, W, 2))
                 ).astype(np.float32),
        "valid": np.ones((batch, H, W), np.float32),
    }


def _time_train_point(knobs, hw, batch_global, iters, steps, warmup,
                      seed, tiny):
    """pairs/sec/chip of one knob point — bench.py's measurement shape:
    jitted train step on a synthetic sharded batch, warmup to absorb
    compile, a blocking float() sync closing each timed region."""
    import jax

    from raft_tpu.config import RAFTConfig, TrainConfig
    from raft_tpu.models.raft import RAFT
    from raft_tpu.parallel.mesh import make_mesh, shard_batch
    from raft_tpu.train.optim import make_optimizer
    from raft_tpu.train.step import init_state, make_train_step

    mk = RAFTConfig.small_model if tiny else RAFTConfig.full
    model_cfg = mk(compute_dtype="bfloat16", **knobs)
    cfg = TrainConfig(num_steps=max(steps * 4, 100),
                      batch_size=batch_global, image_size=tuple(hw),
                      iters=iters)
    mesh = make_mesh(num_data=jax.device_count(), num_spatial=1)
    model = RAFT(model_cfg)
    tx = make_optimizer(cfg.lr, cfg.num_steps, cfg.wdecay, cfg.epsilon,
                        cfg.clip)
    state = init_state(model, tx, jax.random.PRNGKey(0), (48, 64))
    step_fn = make_train_step(model, tx, cfg, mesh)
    batch = shard_batch(_synth_batch(hw, batch_global, seed), mesh)
    key = jax.random.PRNGKey(1)
    metrics = None
    for _ in range(max(warmup, 1)):
        state, metrics = step_fn(state, batch, key)
    float(metrics["loss"])
    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = step_fn(state, batch, key)
    float(metrics["loss"])
    dt = time.perf_counter() - t0
    return steps * batch_global / dt / max(jax.device_count(), 1)


def _time_eval_point(knobs, hw, batch, iters, steps, warmup, seed, tiny):
    """frames/sec/chip of one knob point on the test-mode forward."""
    import jax
    import numpy as np

    from raft_tpu.config import RAFTConfig
    from raft_tpu.evaluate import make_eval_fn
    from raft_tpu.models.raft import RAFT

    mk = RAFTConfig.small_model if tiny else RAFTConfig.full
    model_cfg = mk(compute_dtype="bfloat16", **knobs)
    H, W = hw
    rng = np.random.default_rng(seed)
    img1 = (rng.uniform(0, 255, (batch, H, W, 3))).astype(np.float32)
    img2 = (rng.uniform(0, 255, (batch, H, W, 3))).astype(np.float32)
    model = RAFT(model_cfg)
    small = np.zeros((1, 64, 96, 3), np.float32)
    variables = jax.jit(
        lambda k: model.init({"params": k, "dropout": k}, small, small,
                             iters=2, train=False))(jax.random.PRNGKey(0))
    fwd = make_eval_fn(model_cfg, iters)
    up = None
    for _ in range(max(warmup, 1)):
        _, up = fwd(variables, img1, img2)
    float(up.sum())
    t0 = time.perf_counter()
    for _ in range(steps):
        _, up = fwd(variables, img1, img2)
    float(up.sum())
    dt = time.perf_counter() - t0
    return steps * batch / dt / max(jax.device_count(), 1)


def _time_serve_point(knobs, hw, batch, iters, steps, warmup, seed,
                      tiny):
    """pairs/sec/chip of one ServeConfig knob point through a real
    InferenceEngine on a closed-loop synthetic workload (``batch``
    concurrent requests per wave, ``steps`` timed waves)."""
    import jax
    import numpy as np

    from raft_tpu import tuning
    from raft_tpu.config import RAFTConfig
    from raft_tpu.models.raft import RAFT
    from raft_tpu.serve.engine import InferenceEngine, ServeConfig

    mk = RAFTConfig.small_model if tiny else RAFTConfig.full
    # Model-level knobs in a serve sweep (fused_gru etc.) configure the
    # RAFTConfig the engine compiles; dispatcher knobs go to ServeConfig.
    model_cfg = mk(**{k: knobs[k] for k in tuning.TUNABLE_KNOBS
                      if k in knobs})
    H, W = hw
    serve_kw = {k: knobs[k] for k in ("batching", "slots",
                                      "early_exit_threshold")
                if k in knobs}
    cfg = ServeConfig(iters=int(knobs.get("iters", iters)),
                      max_batch=batch, batch_sizes=(batch,),
                      max_wait_ms=2.0, **serve_kw)
    rng = np.random.default_rng(seed)
    pairs = [(rng.uniform(0, 255, (H, W, 3)).astype(np.float32),
              rng.uniform(0, 255, (H, W, 3)).astype(np.float32))
             for _ in range(batch)]
    model = RAFT(model_cfg)
    small = np.zeros((1, 64, 96, 3), np.float32)
    variables = jax.jit(
        lambda k: model.init({"params": k, "dropout": k}, small, small,
                             iters=2, train=False))(jax.random.PRNGKey(0))
    eng = InferenceEngine(variables, model_cfg, cfg)
    with eng:
        eng.warmup([hw])

        def wave():
            futs = [eng.submit(a, b) for a, b in pairs]
            for f in futs:
                f.result(timeout=600)

        for _ in range(max(warmup, 1)):
            wave()
        t0 = time.perf_counter()
        for _ in range(steps):
            wave()
        dt = time.perf_counter() - t0
    return steps * len(pairs) / dt / max(jax.device_count(), 1)


def run_sweep(kind, hw, batch_per_chip, iters, steps, warmup, time_box,
              seed, out, force=False, tiny=False, allow_quantized=False):
    """Sweep -> persist winner.  Returns the result record (one JSON
    line, bench.py schema) without printing it."""
    import jax

    from raft_tpu import tuning

    grid = _grid(kind, tiny, allow_quantized)
    sweep_id = _sweep_id(kind, grid, hw, batch_per_chip, iters, steps,
                        warmup, seed)
    out = out or tuning.default_registry_path()
    existing = tuning.lookup(kind, tuple(hw), batch_per_chip, path=out)
    if (existing is not None and existing[2]
            and existing[1].get("provenance", {}).get("sweep_id")
            == sweep_id and not force):
        return {
            "metric": f"autotune_{kind}_{hw[0]}x{hw[1]}_b{batch_per_chip}",
            "value": existing[1]["provenance"].get("best_value"),
            "unit": existing[1]["provenance"].get("unit", ""),
            "vs_baseline": 0.0,
            "config": {"cache_hit": True, "key": existing[0],
                       "knobs": existing[1]["knobs"],
                       "sweep_id": sweep_id, "registry": out},
        }

    n_dev = max(jax.device_count(), 1)
    batch_global = batch_per_chip * n_dev
    timer = {"train": _time_train_point, "eval": _time_eval_point,
             "serve": _time_serve_point}[kind]
    unit = {"train": "image-pairs/sec/chip",
            "eval": "frames/sec/chip",
            "serve": "pairs/sec/chip"}[kind]
    # The sweep must measure each point's RAW knobs — a registry consult
    # inside make_train_step would overwrite the very values under test
    # with the previous winner (a tuning feedback loop).
    prev_disable = os.environ.get(tuning.ENV_DISABLE)
    os.environ[tuning.ENV_DISABLE] = "0"
    points = _points(grid, seed)
    results = []
    deadline = time.monotonic() + time_box
    t_start = time.monotonic()
    try:
        for i, knobs in enumerate(points):
            if results and time.monotonic() > deadline:
                print(f"time box hit after {len(results)}/{len(points)} "
                      "points", flush=True)
                break
            value = timer(knobs, hw, batch_global if kind == "train"
                          else batch_per_chip, iters, steps, warmup,
                          seed, tiny)
            results.append((value, knobs))
            print(f"[{i + 1}/{len(points)}] {json.dumps(knobs)} -> "
                  f"{value:.3f} {unit}", flush=True)
    finally:
        if prev_disable is None:
            os.environ.pop(tuning.ENV_DISABLE, None)
        else:
            os.environ[tuning.ENV_DISABLE] = prev_disable
    best_value, best_knobs = max(results, key=lambda r: r[0])
    key = tuning.save_entry(
        kind, tuple(hw), batch_per_chip, best_knobs,
        provenance={"tool": "scripts/autotune.py", "seed": seed,
                    "sweep_id": sweep_id, "points_tried": len(results),
                    "points_total": len(points), "steps": steps,
                    "warmup": warmup, "iters": iters,
                    "best_value": round(best_value, 3), "unit": unit,
                    "time_box_s": time_box,
                    "elapsed_s": round(time.monotonic() - t_start, 1)},
        path=out)
    return {
        "metric": f"autotune_{kind}_{hw[0]}x{hw[1]}_b{batch_per_chip}",
        "value": round(best_value, 3),
        "unit": unit,
        "vs_baseline": 0.0,
        "config": {"cache_hit": False, "key": key, "knobs": best_knobs,
                   "points_tried": len(results),
                   "points_total": len(points), "sweep_id": sweep_id,
                   "registry": out},
    }


def _tiny_main(args) -> int:
    """The tier-1 smoke: sweep -> cache hit -> consumption, one run."""
    import tempfile

    import jax
    import numpy as np

    from raft_tpu import tuning

    hw, batch, iters, steps, warmup = (48, 64), 2, 2, 2, 1
    out = args.out or osp.join(tempfile.mkdtemp(prefix="raft_autotune_"),
                               "tuning.json")
    common = dict(kind="train", hw=hw, batch_per_chip=batch, iters=iters,
                  steps=steps, warmup=warmup, time_box=args.time_box,
                  seed=args.seed, out=out, tiny=True)
    first = run_sweep(**common)
    second = run_sweep(**common)       # must be served from the registry
    ok = (not first["config"]["cache_hit"]
          and second["config"]["cache_hit"]
          and second["config"]["knobs"] == first["config"]["knobs"])

    # Consumption: a tiny train step with every knob left at its default
    # must pick the winner up through make_train_step's registry consult.
    from raft_tpu.config import RAFTConfig, TrainConfig
    from raft_tpu.models.raft import RAFT
    from raft_tpu.train.optim import make_optimizer
    from raft_tpu.train.step import init_state, make_train_step

    prev = os.environ.get(tuning.ENV_REGISTRY)
    os.environ[tuning.ENV_REGISTRY] = out
    try:
        model_cfg = RAFTConfig.small_model()
        resolved, info = tuning.resolve_config(
            model_cfg, "train", hw, batch)
        consumed = info.tuned and all(
            getattr(resolved, k) == v
            for k, v in first["config"]["knobs"].items())
        cfg = TrainConfig(num_steps=10, batch_size=batch, image_size=hw,
                          iters=iters)
        model = RAFT(model_cfg)   # knobs at defaults — the step resolves
        tx = make_optimizer(cfg.lr, cfg.num_steps, cfg.wdecay,
                            cfg.epsilon, cfg.clip)
        state = init_state(model, tx, jax.random.PRNGKey(0), (48, 64))
        step_fn = make_train_step(model, tx, cfg, None)
        state, metrics = step_fn(state, _synth_batch(hw, batch, 0),
                                 jax.random.PRNGKey(1))
        step_ran = bool(np.isfinite(float(metrics["loss"])))
    finally:
        if prev is None:
            os.environ.pop(tuning.ENV_REGISTRY, None)
        else:
            os.environ[tuning.ENV_REGISTRY] = prev

    passed = ok and consumed and step_ran
    print(json.dumps({
        "metric": "autotune_tiny",
        "value": 1.0 if passed else 0.0,
        "unit": "pass",
        "vs_baseline": 0.0,
        "config": {
            "registry": out,
            "winner": first["config"]["knobs"],
            "first_cache_hit": first["config"]["cache_hit"],
            "second_cache_hit": second["config"]["cache_hit"],
            "consumed_by_train_step": bool(consumed),
            "tiny_step_loss_finite": bool(step_ran),
            "registry_hash": tuning.registry_file_hash(out),
        },
    }))
    return 0 if passed else 1


def main(argv=None) -> int:
    args = parse_args(argv)

    from raft_tpu.utils.profiling import enable_persistent_compile_cache

    # Every point is a fresh jit trace; across re-runs the persistent
    # cache turns repeat compiles into loads.
    enable_persistent_compile_cache()

    if args.seed_known:
        from raft_tpu import tuning

        keys = seed_known(args.out)
        print(json.dumps({
            "metric": "autotune_seed_known",
            "value": float(len(keys)),
            "unit": "entries",
            "vs_baseline": 0.0,
            "config": {"keys": keys,
                       "registry": args.out
                       or tuning.default_registry_path()},
        }))
        return 0

    if args.tiny:
        return _tiny_main(args)

    hw = tuple(int(x) for x in args.image.split("x"))
    iters = args.iters or (12 if args.kind == "train" else 32)
    rec = run_sweep(args.kind, hw, args.batch_per_chip, iters, args.steps,
                    args.warmup, args.time_box, args.seed, args.out,
                    force=args.force, allow_quantized=args.allow_quantized)
    print(json.dumps(rec))
    return 0


if __name__ == "__main__":
    sys.exit(main())
