"""Repo-wide raftlint sweep as a bench-format record.

Thin wrapper over ``python -m raft_tpu lint`` (raft_tpu/analysis/;
rule catalog in docs/ANALYSIS.md) that folds the run into the same
one-line JSON shape every other measurement tool here emits, so the
lint count rides the BENCH series and ``scripts/check_regression.py``
can gate it two ways:

- as a bench record (``metric: raftlint_findings``, value = active
  finding count — a flat-zero series any non-zero newest record
  visibly breaks), and
- as the full raftlint report (``--report PATH``), the input
  ``check_regression.py --lint-report`` validates structurally so a
  vanished lint run cannot pass vacuously.

``--fix`` automates the one mechanical repair: appending placeholder
catalog rows to docs/OBSERVABILITY.md for undocumented emissions
(TEL301/TEL303).  Stale rows, lock violations and jit impurities need
human judgment and are never auto-edited.

::

    python scripts/lint_repo.py --json            # bench record line
    python scripts/lint_repo.py --report lint.json
    python scripts/lint_repo.py --fix             # doc-sync, then lint
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from raft_tpu.analysis import (BASELINE_PATH, Workspace, files_scanned,
                               load_baseline, make_report, run_checks,
                               split_findings)
from raft_tpu.analysis import telemetry as _telemetry


def parse_args(argv=None):
    p = argparse.ArgumentParser(
        description="raftlint sweep -> bench-format record")
    p.add_argument("--root", default=REPO,
                   help="repo root to scan (default: this checkout)")
    p.add_argument("--only", default=None,
                   help="comma-separated checker families "
                        "(default: all)")
    p.add_argument("--json", action="store_true",
                   help="print the one-line bench record (default: "
                        "human-readable findings + summary)")
    p.add_argument("--report", default=None, metavar="PATH",
                   help="also write the full raftlint JSON report to "
                        "PATH (the check_regression.py --lint-report "
                        "input)")
    p.add_argument("--fix", action="store_true",
                   help="before linting, append placeholder doc rows "
                        "for undocumented telemetry emissions "
                        "(TEL301/TEL303) to docs/OBSERVABILITY.md — "
                        "the only mechanical fix; everything else "
                        "needs a human")
    return p.parse_args(argv)


def _apply_doc_fix(ws: Workspace) -> int:
    """Telemetry doc-sync: append placeholder rows for undocumented
    emissions.  Returns the number of rows added (0 = doc in sync)."""
    findings = _telemetry.check(ws)
    todo = [f for f in findings if f.rule in ("TEL301", "TEL303")]
    if not todo:
        return 0
    new_text, n_rows = _telemetry.fix_documentation(ws, todo)
    if n_rows:
        doc_abs = os.path.join(ws.root, _telemetry.DOC_PATH)
        with open(doc_abs, "w") as f:
            f.write(new_text)
        # The workspace caches parsed files; drop the stale doc entry
        # so the post-fix lint pass sees the appended rows.
        ws._cache.pop(_telemetry.DOC_PATH, None)
    return n_rows


def main(argv=None) -> int:
    args = parse_args(argv)
    ws = Workspace(args.root)
    families = None
    if args.only:
        families = [s.strip() for s in args.only.split(",") if s.strip()]

    fixed_rows = 0
    if args.fix:
        fixed_rows = _apply_doc_fix(ws)
        if fixed_rows and not args.json:
            print(f"--fix: appended {fixed_rows} placeholder row(s) to "
                  f"{_telemetry.DOC_PATH} (fill in the meaning/fields "
                  "columns)", file=sys.stderr)

    findings, rules_run = run_checks(ws, families)
    baseline = load_baseline(os.path.join(args.root, BASELINE_PATH))
    active, baselined, suppressed = split_findings(ws, findings,
                                                   baseline)
    report = make_report(active, baselined, suppressed,
                         files_scanned(ws), rules_run)

    if args.report:
        with open(args.report, "w") as f:
            json.dump(report, f, indent=2, sort_keys=True)
            f.write("\n")

    if args.json:
        print(json.dumps({
            "metric": "raftlint_findings",
            "value": float(len(active)),
            "unit": "findings",
            "vs_baseline": 0.0,
            "config": {
                "counts_by_rule": report["counts_by_rule"],
                "files_scanned": report["files_scanned"],
                "baselined": len(baselined),
                "suppressed": len(suppressed),
                "families": sorted(families) if families else "all",
                "fixed_doc_rows": fixed_rows,
            },
        }))
    else:
        for f in active:
            print(f"{f.rule} {f.path}:{f.line}: {f.message}")
        print(f"raftlint: {len(active)} finding(s), "
              f"{len(baselined)} baselined, {len(suppressed)} "
              f"suppressed, {report['files_scanned']} files")
    return 1 if active else 0


if __name__ == "__main__":
    sys.exit(main())
