"""Streaming-session benchmark: warm-start video flow vs independent
pairs (docs/SERVING.md "Streaming sessions").

Drives ``raft_tpu.serve.InferenceEngine`` in-process over a synthetic
clip with exactly-known motion (``scripts/make_demo_frames.make_clip``)
in two arms over the SAME frames:

- **stream**: one session per simulated camera; every frame after the
  first pair takes the warm path (carried fmap/ctx + forward-warped
  ``flow_init``), with the per-frame budget ``--stream-warm-iters``
  and the in-graph early-exit predicate compounding.
- **independent**: every consecutive pair submitted as a stateless
  request at the full budget — the arm serving today's API.

Prints ONE JSON line in the ``bench.py`` format.  The headline value
is the stream arm's frames/sec/chip; the record also carries the
cold-vs-warm ``iters_used`` histograms (separable because retirements
are ``warm``-tagged), the two figures the regression gates consume
(``config.warm_iters_saved_frac`` for ``--min-warm-iters-saved-frac``,
``config.stream_epe_delta`` for ``--max-stream-epe-delta``), and
``encoder_flops_saved_frac`` from the cost ledger (``wenc`` vs ``enc``
``flops_per_pair`` — the fmap-reuse saving, stamped at compile time).

EPE is measured against the clip's analytic ground truth on an
interior crop (the rolled texture wraps at the border).  With random
weights (``--tiny``) the absolute EPE is meaningless; the DELTA
between arms on identical frames is still exactly the cost of warm
start, which is what the gate bounds.

``--tiny``: CPU smoke preset::

    JAX_PLATFORMS=cpu python scripts/bench_stream.py --tiny
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def parse_args(argv=None):
    p = argparse.ArgumentParser(
        description="RAFT-TPU streaming-session benchmark")
    p.add_argument("--tiny", action="store_true",
                   help="CPU smoke preset (small model, fp32, tiny "
                        "clip)")
    p.add_argument("--hw", default="384x512",
                   help="HxW clip resolution")
    p.add_argument("--frames", type=int, default=24,
                   help="frames per clip (pairs = frames - 1)")
    p.add_argument("--sessions", type=int, default=4,
                   help="concurrent streaming sessions (simulated "
                        "cameras; each pins one slot lane)")
    p.add_argument("--small", action="store_true")
    p.add_argument("--precision", default="bf16",
                   choices=["bf16", "fp32"])
    p.add_argument("--iters", type=int, default=32,
                   help="cold / independent-pair refinement budget")
    p.add_argument("--stream-warm-iters", type=int, default=None,
                   help="warm-frame budget (default: same as --iters; "
                        "the warm saving then comes from early exit "
                        "alone)")
    p.add_argument("--early-exit-threshold", type=float, default=0.0)
    p.add_argument("--slots", type=int, default=8)
    p.add_argument("--shift", default="2x1",
                   help="DXxDY analytic motion, px/frame")
    p.add_argument("--request-timeout-s", type=float, default=120.0)
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args(argv)
    if args.tiny:
        args.small = True
        args.precision = "fp32"
        args.iters = 3
        args.hw = "36x52"
        args.frames = 8
        args.sessions = 2
        args.slots = min(args.slots, 4)
        if args.stream_warm_iters is None:
            args.stream_warm_iters = 2
    if args.frames < 2:
        raise SystemExit("--frames must be >= 2")
    return args


def _epe(flow, gt, margin: int = 8):
    """Mean endpoint error on the interior crop (the analytic clip
    wraps at the border, so the edge band's truth is undefined)."""
    import numpy as np

    d = (flow[margin:-margin, margin:-margin]
         - gt[margin:-margin, margin:-margin])
    return float(np.sqrt((d ** 2).sum(-1)).mean())


def _build_engine(args, variables, model_cfg, streaming: bool):
    from raft_tpu.serve import InferenceEngine, ServeConfig

    cfg = ServeConfig(
        iters=args.iters, batching="slot", slots=args.slots,
        early_exit_threshold=max(args.early_exit_threshold, 0.0),
        max_queue=max(256, args.sessions * args.frames),
        stream_warm_iters=args.stream_warm_iters if streaming else None,
        stream_ttl_s=max(60.0, 2 * args.request_timeout_s),
        max_sessions=max(64, args.sessions))
    eng = InferenceEngine(variables, model_cfg, cfg)
    eng.start()
    return eng


def _run_stream_arm(args, variables, model_cfg, clips):
    """One thread per session streams its clip; returns (elapsed,
    flows-by-session, engine stats)."""
    eng = _build_engine(args, variables, model_cfg, streaming=True)
    results = {}
    errs = []

    def worker(sid, frames):
        try:
            eng.stream_open(sid, frames[0])
            out = []
            for f in frames[1:]:
                r = eng.stream_ingest(sid, f,
                                      timeout=args.request_timeout_s)
                out.append((r["warm"], r["flow"]))
            eng.stream_close(sid)
            results[sid] = out
        except Exception as e:  # surfaced after join
            errs.append((sid, e))

    threads = [threading.Thread(target=worker,
                                args=(f"cam{i}", clips[i]))
               for i in range(args.sessions)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    dt = time.perf_counter() - t0
    try:
        stats = eng.stats()
    finally:
        eng.stop()
    if errs:
        raise RuntimeError(f"stream arm failed: {errs[0]}") from \
            errs[0][1]
    return dt, results, stats


def _run_indep_arm(args, variables, model_cfg, clips):
    """Every consecutive pair as a stateless request (full budget)."""
    eng = _build_engine(args, variables, model_cfg, streaming=False)
    try:
        futs = {}
        t0 = time.perf_counter()
        for i in range(args.sessions):
            frames = clips[i]
            for t in range(len(frames) - 1):
                futs[(i, t)] = eng.submit(frames[t], frames[t + 1],
                                          iters=args.iters)
        flows = {k: f.result(timeout=args.request_timeout_s)
                 for k, f in futs.items()}
        dt = time.perf_counter() - t0
        stats = eng.stats()
    finally:
        eng.stop()
    return dt, flows, stats


def main(argv=None):
    args = parse_args(argv)

    import jax
    import numpy as np

    from raft_tpu.config import RAFTConfig
    from raft_tpu.models.raft import RAFT
    from scripts.make_demo_frames import make_clip

    h, w = (int(t) for t in args.hw.lower().split("x"))
    dx, dy = (int(t) for t in args.shift.lower().split("x"))

    mk = RAFTConfig.small_model if args.small else RAFTConfig.full
    model_cfg = mk(compute_dtype="bfloat16"
                   if args.precision == "bf16" else "float32")
    model = RAFT(model_cfg)
    key = jax.random.PRNGKey(args.seed)
    img = jax.numpy.zeros((1, 64, 96, 3))
    variables = jax.jit(
        lambda k: model.init({"params": k, "dropout": k}, img, img,
                             iters=2, train=False))(key)

    clips, gt = [], None
    for i in range(args.sessions):
        frames, gt = make_clip(args.frames, (h, w), shift=(dx, dy),
                               seed=args.seed + 3 + i)
        clips.append(frames)

    s_dt, s_results, s_stats = _run_stream_arm(args, variables,
                                               model_cfg, clips)
    i_dt, i_flows, _ = _run_indep_arm(args, variables, model_cfg,
                                      clips)

    n_dev = max(jax.local_device_count(), 1)
    pairs = args.sessions * (args.frames - 1)
    margin = min(8, h // 4, w // 4)
    stream_epes, indep_epes, warm_flags = [], [], []
    for i in range(args.sessions):
        for t, (warm, flow) in enumerate(s_results[f"cam{i}"]):
            warm_flags.append(bool(warm))
            stream_epes.append(_epe(flow, gt, margin))
            indep_epes.append(_epe(i_flows[(i, t)], gt, margin))
    stream_epe = float(np.mean(stream_epes))
    indep_epe = float(np.mean(indep_epes))

    warm_hist = s_stats["iters_used_warm"]
    cold_hist = s_stats["iters_used_cold"]
    warm_p50, cold_p50 = warm_hist.get("p50"), cold_hist.get("p50")
    saved_frac = (1.0 - warm_p50 / cold_p50
                  if warm_p50 and cold_p50 else None)

    # Encoder-work saving from the compile-time cost ledger: the warm
    # program runs the encoders over ONE image instead of two.
    enc_fpp = wenc_fpp = None
    for key_, c in (s_stats.get("cost") or {}).items():
        if key_.endswith("/enc"):
            enc_fpp = c.get("flops_per_pair")
        elif key_.endswith("/wenc"):
            wenc_fpp = c.get("flops_per_pair")
    enc_saved = (1.0 - wenc_fpp / enc_fpp
                 if enc_fpp and wenc_fpp else None)

    tag = "tiny" if args.tiny else f"{h}x{w}"
    record = {
        "metric": f"serve_stream_{tag}_f{args.frames}"
                  f"_s{args.sessions}_iters{args.iters}",
        "value": round(pairs / s_dt / n_dev, 3),
        "unit": "frames/sec/chip",
        "vs_baseline": 0.0,
        "config": {
            "hw": args.hw, "frames": args.frames,
            "sessions": args.sessions, "iters": args.iters,
            "stream_warm_iters": args.stream_warm_iters,
            "early_exit_threshold": args.early_exit_threshold,
            "slots": args.slots, "shift": args.shift,
            "precision": args.precision, "small": args.small,
            "seed": args.seed,
            # The two gate inputs (scripts/check_regression.py):
            "warm_iters_saved_frac": (round(saved_frac, 4)
                                      if saved_frac is not None
                                      else None),
            "stream_epe_delta": round(stream_epe - indep_epe, 4),
        },
        "stream_epe": round(stream_epe, 4),
        "indep_epe": round(indep_epe, 4),
        "warm_share": round(sum(warm_flags) / max(len(warm_flags), 1),
                            4),
        "iters_used_warm": warm_hist,
        "iters_used_cold": cold_hist,
        "encoder_flops_saved_frac": (round(enc_saved, 4)
                                     if enc_saved is not None
                                     else None),
        "sessions": s_stats.get("sessions"),
        "compiles": s_stats.get("compiles"),
        "arms": {
            "stream": {"value": round(pairs / s_dt / n_dev, 3),
                       "epe": round(stream_epe, 4)},
            "independent": {"value": round(pairs / i_dt / n_dev, 3),
                            "epe": round(indep_epe, 4)},
        },
    }
    print(json.dumps(record))


if __name__ == "__main__":
    main()
