#!/bin/bash
# Round-7 TPU backlog, priority order: validate the cost model
# (raft_tpu/obs/cost.py) on hardware and arm the hardware-normalized
# gates.  Off-TPU the model is parity-pinned against interpret-mode
# XLA counts only; this round checks the analytic kernel formulas
# against XProf-measured FLOP rates, records the first real MFU
# baselines, and turns on --min-mfu / --max-flops-per-pair-growth for
# the BENCH series.  Every step is independently resumable.
set -x -o pipefail
cd "$(dirname "$0")/.."

# 0. The cost table itself on real hardware: device_kind must resolve
#    to a known peak (v5e/v4 -> nonzero MFU downstream; an "unknown"
#    kind here means PEAK_SPECS needs the new part's datasheet row
#    BEFORE any gate below is armed)
python -m raft_tpu cost --json | tee COST_r07.json | python -m json.tool | head -30

# 1. Analytic-formula validation: on TPU the fused kernels are opaque
#    custom_calls (cost_source=analytic in the fused arms), and
#    profile_step's hlo_stats carry XProf's *measured* flop rates for
#    the same ops.  Compare analytic_flops vs the custom-call rows in
#    hlo_stats.json — agreement within ~2x validates the formulas;
#    worse means a block-spec drift (fix obs/cost.py, not the gate).
python scripts/bench_kernels.py --image 368x496 --batch 16 \
    2>&1 | tee /tmp/bench_kernels_r07.log | tail -1 \
    > BENCH_KERNELS_r07.json
python scripts/profile_step.py /tmp/xprof_r07 2>&1 | tail -20

# 2. Headline benches with cost fields: train + eval + serve records
#    now carry flops_per_pair / achieved_tflops / mfu / bound_by —
#    these are the first hardware MFU numbers in the BENCH series
python bench.py 2>&1 | tee /tmp/bench_r07.log | tail -2 > BENCH_r07.json
BENCH_MODE=eval python bench.py 2>&1 | tee /tmp/bench_eval_r07.log \
    | tail -2 > BENCH_EVAL_r07.json
python scripts/bench_serve.py --batching both --shapes 440x1024 \
    --requests 128 --concurrency 16 \
    2>&1 | tee /tmp/bench_serve_r07.log | tail -1 > BENCH_SERVE_r07.json

# 3. Arm the hardware-normalized gates against the fresh records.
#    Floors are INTENTIONALLY soft on first arming (half of whatever
#    step 2 measured, rounded down): the point this round is that the
#    gates hold real data, not that the chip is already well fed —
#    ratchet the PCT once the MFU trend is understood.  Both gates
#    fail vacuously without qualifying records, so a wrong backend or
#    a cost-silent record shows up here, not in a false pass.
python scripts/check_regression.py \
    --min-mfu train_throughput:10 --min-mfu eval_forward:5 \
    --max-flops-per-pair-growth 5 2>&1 | tail -3

# 4. Traced serve run + the roofline fold: spans carry flops/mfu attrs
#    on hardware, so the cost-weighted critical path separates kernel
#    time from host/queueing time per request
RAFT_TRACE_SAMPLE_RATE=0.1 RAFT_TELEMETRY_DIR=/tmp/telem_r07 \
    python scripts/bench_serve.py --batching slot --shapes 440x1024 \
    --requests 64 --concurrency 8 2>&1 | tail -1
python scripts/trace_report.py /tmp/telem_r07 --roofline 2>&1 | tail -20
