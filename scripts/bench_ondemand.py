"""On-demand correlation benchmark in the beyond-HBM regime.

VERDICT round 1, item 4: the on-demand path exists to serve inputs whose
materialized all-pairs volume exceeds HBM (the reference serves these with
``alt_cuda_corr``, correlation_kernel.cu:19-119).  This benchmark runs a
test-mode forward at a shape where the all-pairs volume CANNOT fit
(1440x2560 -> N = (1440/8)*(2560/8) = 57600 queries; the fp32 level-0
volume alone is N^2*4 = 13.3 GB, ~17.7 GB with the pyramid, > the 16 GB
v5e HBM before counting activations) and compares the fused Pallas
on-demand kernel against the chunked XLA formulation.

Usage: python scripts/bench_ondemand.py [HxW] [iters] [impls]
``impls``: comma list (default "chunked,pallas" — the working number
prints first; the fused pallas kernels' Mosaic compile is known to blow
20-40 min budgets on the round-2 toolchain, see ROADMAP.md).  Prints
one JSON line per implementation.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main():
    import jax
    import numpy as np

    from raft_tpu.config import RAFTConfig
    from raft_tpu.evaluate import make_eval_fn
    from raft_tpu.models.raft import RAFT

    H, W = (int(x) for x in (sys.argv[1] if len(sys.argv) > 1
                             else "1440x2560").split("x"))
    iters = int(sys.argv[2]) if len(sys.argv) > 2 else 12
    rng = jax.random.PRNGKey(0)
    img = jax.random.uniform(rng, (1, H, W, 3), np.float32) * 255.0

    # chunked first: the fused pallas on-demand kernels are correct in
    # interpret mode but their Mosaic compile exceeded 20-40 min budgets
    # on the round-2 toolchain (ROADMAP.md) — running it second means the
    # working number always prints.
    impls = (sys.argv[3] if len(sys.argv) > 3 else "chunked,pallas") \
        .split(",")
    # Init once with the known-good impl (params are impl-independent);
    # ALWAYS jit init on the axon tunnel (unjitted init dispatches
    # op-by-op through remote compile — 20+ min at 720p), and init at a
    # tiny shape (conv params are size-independent).
    init_model = RAFT(RAFTConfig.full(compute_dtype="bfloat16",
                                      corr_impl="chunked"))
    small = jax.random.uniform(rng, (1, 64, 96, 3), np.float32)
    variables = jax.jit(
        lambda k: init_model.init({"params": k, "dropout": k},
                                  small, small, iters=1, train=False)
    )(rng)
    for impl in impls:
        cfg = RAFTConfig.full(compute_dtype="bfloat16", corr_impl=impl)
        fwd = make_eval_fn(cfg, iters)
        try:
            for _ in range(2):
                low, up = fwd(variables, img, img)
            float(up.sum())
            n = 5
            t0 = time.perf_counter()
            for _ in range(n):
                low, up = fwd(variables, img, img)
            float(up.sum())
            dt = (time.perf_counter() - t0) / n
            print(json.dumps({
                "metric": f"ondemand_eval_{H}x{W}_iters{iters}_{impl}",
                "value": round(1.0 / dt, 3),
                "unit": "frames/sec/chip",
                "vs_baseline": 0.0,
            }), flush=True)
        except Exception as e:
            print(json.dumps({
                "metric": f"ondemand_eval_{H}x{W}_iters{iters}_{impl}",
                "error": repr(e)[:200],
            }), flush=True)


if __name__ == "__main__":
    main()
