"""Reconstruct distributed trace trees from telemetry logs.

``raft_tpu/obs/trace.py`` emits one ``trace_span`` JSONL record per
span (trace_id / span_id / parent_id + monotonic timing); this script
turns a telemetry directory full of them back into trees and answers
the question flat logs cannot: *where did THIS request's milliseconds
go*::

    python scripts/trace_report.py runs/telemetry --slowest 3
    python scripts/trace_report.py runs/telemetry --trace 7f3a9c2d1b4e8f60
    python scripts/trace_report.py runs/telemetry --perfetto out.json
    python scripts/trace_report.py runs/telemetry --json

Per trace it prints a waterfall (children indented under parents,
offsets relative to the root start) and a **critical-path
attribution**: walking back from each span's end to the child whose
end reaches latest into it, the chain of spans that actually bounded
the end-to-end latency — a hedged request whose losing attempt was
slow but whose winner was fast correctly attributes to the winner.

``--perfetto`` exports Chrome/Perfetto ``trace_event`` JSON (load in
https://ui.perfetto.dev or chrome://tracing).  ``--json`` prints one
bench.py-format line whose config block carries ``critical_path_ms``
(per span name, p95 self-time on the critical path) and
``serve_span_names`` — the inputs for ``scripts/check_regression.py
--max-critical-path-ms`` and its span-coverage check.  ``--tiny``
round-trips a synthetic hedged trace through the real tracer + sink
and reports on it (the CI selftest).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

#: Two spans' monotonic clocks agree only within the same process;
#: cross-process skew plus float rounding means a child may end a hair
#: "after" its parent.  Ends within EPS still count as covered.
EPS_S = 1e-4


def parse_args(argv=None):
    p = argparse.ArgumentParser(
        description="trace_span JSONL -> trace trees, critical paths, "
                    "Perfetto export")
    p.add_argument("path", nargs="?", default=None,
                   help="telemetry-*.jsonl file or a directory of them")
    p.add_argument("--trace", default=None, metavar="ID",
                   help="report exactly this trace_id")
    p.add_argument("--slowest", type=int, default=5, metavar="N",
                   help="report the N slowest traces by root duration "
                        "(default 5)")
    p.add_argument("--perfetto", default=None, metavar="OUT.json",
                   help="export all loaded traces as Chrome/Perfetto "
                        "trace_event JSON")
    p.add_argument("--json", action="store_true",
                   help="print one bench.py-format JSON line "
                        "(critical_path_ms + serve_span_names in the "
                        "config block) instead of waterfalls")
    p.add_argument("--roofline", action="store_true",
                   help="per-span-name roofline table over the spans "
                        "that carry cost attrs (flops/bytes/mfu, "
                        "attached by the serve engine from "
                        "obs/cost.py), plus the cost-weighted "
                        "critical path")
    p.add_argument("--device-kind", default=None, metavar="KIND",
                   help="classify --roofline against this device's "
                        "peak specs (e.g. 'v5e') instead of the "
                        "current backend's — for reading a "
                        "TPU-captured trace on a laptop")
    p.add_argument("--tiny", action="store_true",
                   help="selftest: synthesize a hedged trace through "
                        "the real tracer, then report on it")
    return p.parse_args(argv)


# ---------------------------------------------------------------------------
# loading + tree building
# ---------------------------------------------------------------------------


def load_spans(path):
    """Every ``trace_span`` record under ``path`` (file or directory)."""
    files = ([path] if os.path.isfile(path)
             else sorted(glob.glob(os.path.join(path, "*.jsonl"))))
    if not files:
        raise SystemExit(f"no .jsonl telemetry under {path!r}")
    spans = []
    for fname in files:
        with open(fname) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue  # torn final line of a killed run
                if rec.get("event") == "trace_span":
                    spans.append(rec)
    return spans


def _end(rec):
    return float(rec.get("t_start_mono", 0.0)) + float(
        rec.get("dur_s", 0.0))


def build_traces(spans):
    """``{trace_id: {"spans": {id: rec}, "children": {id: [rec...]},
    "roots": [rec...]}}``.

    A span is an *effective root* when its parent_id is None OR names a
    span absent from the log — the serve handler's root continues a
    client-side span that never reaches this sink (wire propagation),
    and it must still anchor a tree."""
    traces = {}
    for rec in spans:
        t = traces.setdefault(rec["trace_id"],
                              {"spans": {}, "children": {}, "roots": []})
        t["spans"][rec["span_id"]] = rec
    for t in traces.values():
        for rec in t["spans"].values():
            pid = rec.get("parent_id")
            if pid is None or pid not in t["spans"]:
                t["roots"].append(rec)
            else:
                t["children"].setdefault(pid, []).append(rec)
        for kids in t["children"].values():
            kids.sort(key=lambda r: r.get("t_start_mono", 0.0))
        t["roots"].sort(key=lambda r: r.get("t_start_mono", 0.0))
    return traces


def root_of(trace):
    """The trace's primary root: the effective root with the longest
    duration (ties to the earliest start)."""
    if not trace["roots"]:
        return None
    return max(trace["roots"],
               key=lambda r: (float(r.get("dur_s", 0.0)),
                              -float(r.get("t_start_mono", 0.0))))


# ---------------------------------------------------------------------------
# critical path
# ---------------------------------------------------------------------------


def critical_path(trace, root=None):
    """``[(span, self_ms), ...]`` root-first: the chain of spans that
    bounded the root's latency.

    Walk: at each node pick the child whose END reaches latest without
    (meaningfully) exceeding the node's own end — the operation the
    node was still waiting on when it finished.  A hedge's losing
    attempt ends after the root settled, so it is (correctly) skipped.
    Each node's self-time is its duration minus its on-path child's —
    the milliseconds attributable to that node alone."""
    if root is None:
        root = root_of(trace)
    if root is None:
        return []
    path, node = [root], root
    while True:
        kids = trace["children"].get(node["span_id"], [])
        covered = [k for k in kids if _end(k) <= _end(node) + EPS_S]
        if not covered:
            break
        node = max(covered, key=_end)
        path.append(node)
    out = []
    for i, n in enumerate(path):
        child_s = (float(path[i + 1].get("dur_s", 0.0))
                   if i + 1 < len(path) else 0.0)
        self_s = max(float(n.get("dur_s", 0.0)) - child_s, 0.0)
        out.append((n, round(self_s * 1e3, 3)))
    return out


# ---------------------------------------------------------------------------
# human output
# ---------------------------------------------------------------------------

_SKIP_KEYS = {"event", "trace_id", "span_id", "parent_id", "name",
              "t_start", "t_start_mono", "dur_s", "status", "t_wall",
              "t_mono", "process", "step"}


def _attr_str(rec):
    attrs = [f"{k}={v}" for k, v in sorted(rec.items())
             if k not in _SKIP_KEYS]
    return (" [" + " ".join(attrs) + "]") if attrs else ""


def print_waterfall(trace, out=sys.stdout):
    """One tree, children indented, offsets in ms from the root start."""
    root = root_of(trace)
    if root is None:
        return
    t0 = float(root.get("t_start_mono", 0.0))
    on_path = {id(n) for n, _ in critical_path(trace, root)}

    def _one(rec, depth):
        off = (float(rec.get("t_start_mono", 0.0)) - t0) * 1e3
        dur = float(rec.get("dur_s", 0.0)) * 1e3
        status = rec.get("status", "ok")
        mark = "*" if id(rec) in on_path else " "
        flag = "" if status == "ok" else f"  !{status}"
        width = max(24 - 2 * depth, 1)
        print(f"{mark} {'  ' * depth}{rec['name']:<{width}}"
              f" {off:9.2f}ms +{dur:9.2f}ms{flag}{_attr_str(rec)}",
              file=out)
        for kid in trace["children"].get(rec["span_id"], []):
            _one(kid, depth + 1)

    print(f"trace {root['trace_id']}  "
          f"({len(trace['spans'])} spans; * = critical path)", file=out)
    for r in trace["roots"]:
        _one(r, 0)
    print("  critical path: "
          + " > ".join(f"{n['name']}:{ms:g}ms"
                       for n, ms in critical_path(trace, root)),
          file=out)


# ---------------------------------------------------------------------------
# Perfetto export
# ---------------------------------------------------------------------------


def perfetto_events(traces):
    """Chrome/Perfetto ``trace_event`` complete events (``ph: "X"``,
    microsecond timestamps).  One "process" per trace so concurrent
    requests don't interleave on a shared track; nesting depth maps to
    the thread id, which renders parents above their children."""
    events = []
    for i, (tid, t) in enumerate(sorted(traces.items())):
        root = root_of(t)
        if root is None:
            continue
        events.append({"ph": "M", "pid": i, "name": "process_name",
                       "args": {"name": f"trace {tid} "
                                        f"({root['name']})"}})

        def _walk(rec, depth, pid=i):
            events.append({
                "ph": "X", "pid": pid, "tid": depth,
                "name": rec["name"],
                "ts": round(float(rec.get("t_start", 0.0)) * 1e6, 1),
                "dur": round(float(rec.get("dur_s", 0.0)) * 1e6, 1),
                "args": {k: v for k, v in rec.items()
                         if k not in ("event",)},
            })
            for kid in t["children"].get(rec["span_id"], []):
                _walk(kid, depth + 1, pid)

        for r in t["roots"]:
            _walk(r, 0)
    return {"traceEvents": events, "displayTimeUnit": "ms"}


# ---------------------------------------------------------------------------
# bench-format summary (check_regression input)
# ---------------------------------------------------------------------------

#: Root names originated by the serve path — their trees must carry
#: the engine's queue/pad/device spans or serve instrumentation broke
#: (scripts/check_regression.py span-coverage check).
SERVE_ROOTS = ("serve_http", "route")


def _p95(vals):
    vals = sorted(vals)
    return vals[min(int(len(vals) * 0.95), len(vals) - 1)]


def bench_record(traces):
    """One bench.py-format record: ``critical_path_ms`` maps span name
    -> p95 self-time ms over every trace's critical path (what
    ``check_regression --max-critical-path-ms NAME:MS`` gates);
    ``serve_span_names`` lists every span name observed inside
    serve-rooted traces (the coverage check's input)."""
    self_ms, serve_names, errors = {}, set(), 0
    roots = 0
    for t in traces.values():
        root = root_of(t)
        if root is None:
            continue
        roots += 1
        if root.get("status") == "error":
            errors += 1
        for n, ms in critical_path(t, root):
            self_ms.setdefault(n["name"], []).append(ms)
        if root.get("name") in SERVE_ROOTS:
            serve_names.update(r["name"] for r in t["spans"].values())
    return {
        "metric": "trace_report",
        "value": roots,
        "unit": "traces",
        "vs_baseline": 0.0,
        "config": {
            "source": "trace_report",
            "traces_total": roots,
            "traced_error_rate": round(errors / roots, 4) if roots
            else 0.0,
            "critical_path_ms": {name: round(_p95(v), 3)
                                 for name, v in sorted(self_ms.items())},
            "serve_span_names": sorted(serve_names),
        },
    }


# ---------------------------------------------------------------------------
# roofline + cost-weighted critical path (--roofline)
# ---------------------------------------------------------------------------


def _resolve_spec(device_kind=None):
    """Peak specs for the roofline verdicts.  An explicit
    ``--device-kind`` wins (classify a TPU trace offline); otherwise
    the current backend's — degrading to unknown peaks (not an error)
    when no backend is importable, since this is a log-reading tool."""
    from raft_tpu.obs import cost as cost_mod

    try:
        return cost_mod.peak_spec(device_kind)
    except Exception:
        return cost_mod.PeakSpec(str(device_kind or "unknown"),
                                 None, None)


def _span_flops(rec):
    v = rec.get("flops")
    return float(v) if isinstance(v, (int, float)) and v > 0 else None


def roofline_report(traces, spec, out=sys.stdout):
    """Two tables off the cost attrs the engine attaches to its spans
    (``flops``/``bytes`` from the compile-time ledger, ``mfu`` from the
    observed call time — obs/cost.py):

    - per span name: total work, arithmetic intensity and the
      compute/memory verdict against ``spec``'s ridge point, plus the
      observed MFU spread (``-`` throughout on unknown peaks / CPU);
    - the **cost-weighted critical path**: of the FLOPs executed on
      the latency-bounding chains, which span name runs them, at what
      p95 self-time — a span owning most on-path FLOPs at low MFU is
      the optimization target; one owning milliseconds but no FLOPs
      is queueing/host overhead no kernel work will fix."""
    per = {}
    for t in traces.values():
        for rec in t["spans"].values():
            fl = _span_flops(rec)
            if fl is None:
                continue
            row = per.setdefault(rec["name"],
                                 {"n": 0, "flops": 0.0, "bytes": 0.0,
                                  "mfu": []})
            row["n"] += 1
            row["flops"] += fl
            row["bytes"] += float(rec.get("bytes", 0.0) or 0.0)
            if isinstance(rec.get("mfu"), (int, float)):
                row["mfu"].append(float(rec["mfu"]))
    ridge = spec.ridge
    print(f"roofline vs {spec.kind}: "
          f"peak {spec.tflops if spec.tflops else '-'} bf16 TFLOP/s, "
          f"{spec.hbm_gbps if spec.hbm_gbps else '-'} GB/s, "
          f"ridge {f'{ridge:.1f}' if ridge else '-'} flop/byte",
          file=out)
    if not per:
        print("  (no spans carry cost attrs — trace predates the cost "
              "model, or the engine ran with RAFT_TELEMETRY_COST=0)",
              file=out)
        return
    hdr = (f"  {'span':<16} {'n':>4} {'GFLOPs':>10} {'MB':>10} "
           f"{'flop/byte':>10} {'bound_by':>9} {'mfu_p50':>8} "
           f"{'mfu_max':>8}")
    print(hdr, file=out)
    print("  " + "-" * (len(hdr) - 2), file=out)
    for name, row in sorted(per.items(), key=lambda kv: -kv[1]["flops"]):
        ai = row["flops"] / row["bytes"] if row["bytes"] > 0 else None
        bound = ("unknown" if ai is None or ridge is None
                 else "compute" if ai >= ridge else "memory")
        mfu = sorted(row["mfu"])
        p50 = f"{mfu[len(mfu) // 2]:.4f}" if mfu else "-"
        mx = f"{mfu[-1]:.4f}" if mfu else "-"
        print(f"  {name:<16} {row['n']:>4} "
              f"{row['flops'] / 1e9:>10.3f} {row['bytes'] / 1e6:>10.3f} "
              f"{f'{ai:.3f}' if ai is not None else '-':>10} "
              f"{bound:>9} {p50:>8} {mx:>8}", file=out)

    on_path = {}
    total_flops = 0.0
    for t in traces.values():
        for n, ms in critical_path(t):
            row = on_path.setdefault(n["name"],
                                     {"flops": 0.0, "ms": []})
            row["ms"].append(ms)
            fl = _span_flops(n)
            if fl is not None:
                row["flops"] += fl
                total_flops += fl
    print("  cost-weighted critical path "
          "(share of on-path FLOPs, p95 self-time):", file=out)
    for name, row in sorted(on_path.items(),
                            key=lambda kv: (-kv[1]["flops"],
                                            -max(kv[1]["ms"]))):
        share = (f"{row['flops'] / total_flops * 100.0:5.1f}%"
                 if total_flops > 0 and row["flops"] > 0 else "    -")
        print(f"    {name:<16} {share}  {_p95(row['ms']):9.3f}ms "
              f"x{len(row['ms'])}", file=out)


# ---------------------------------------------------------------------------
# --tiny selftest
# ---------------------------------------------------------------------------


def _synthesize(directory):
    """Round-trip a hedged serve trace and a train-step trace through
    the REAL tracer + sink — the selftest exercises the same emit path
    production uses, not a hand-written log."""
    import time

    from raft_tpu.obs.events import EventSink
    from raft_tpu.obs.trace import Tracer, record_span

    sink = EventSink(directory)
    tracer = Tracer(sink=sink, sample_rate=1.0, seed=0)

    # Hedged request: attempt a is slow, the hedge (attempt b) wins.
    # Real sleeps (~0.1 s total), not synthetic stamps: span end order
    # must agree with the live clocks the Span objects read.
    root = tracer.start_trace("route", bucket="40x56")
    t0 = time.perf_counter()
    a = root.child("attempt", replica="r0", hedge=False)
    record_span(a, "queue", t0, t0 + 0.020)
    record_span(a, "device", t0 + 0.020, t0 + 0.100, retries=0,
                flops=2.0e9, bytes=1.0e9, mfu=0.18)
    time.sleep(0.040)
    b = root.child("attempt", replica="r1", hedge=True)
    record_span(b, "queue", t0 + 0.040, t0 + 0.042)
    record_span(b, "pad", t0 + 0.042, t0 + 0.043, real=1, ballast=1)
    record_span(b, "device", t0 + 0.043, t0 + 0.055, retries=0,
                flops=2.0e9, bytes=1.0e9, mfu=0.31)
    time.sleep(0.020)               # past b's device end
    b.end(status="ok", won=True)
    root.mark_keep()                # the hedge fired: tail-keep
    root.end(status="ok", hedged=True)
    time.sleep(0.045)               # past a's device end
    a.end(status="ok", won=False)   # loser lands late, after the flush

    st = tracer.start_trace("train_step", step=7)
    t1 = time.perf_counter() - 0.110
    record_span(st, "queue_wait", t1, t1 + 0.004)
    record_span(st, "h2d", t1 + 0.001, t1 + 0.003)
    record_span(st, "step_dispatch", t1 + 0.004, t1 + 0.104)
    st.end()
    sink.close()
    return root.trace_id


def _selftest():
    import tempfile

    with tempfile.TemporaryDirectory(prefix="raft-trace-tiny-") as tdir:
        hedged_id = _synthesize(tdir)
        traces = build_traces(load_spans(tdir))
        assert hedged_id in traces, "hedged trace did not round-trip"
        t = traces[hedged_id]
        root = root_of(t)
        assert root["name"] == "route" and len(t["roots"]) == 1, \
            "hedged request must reconstruct as ONE tree"
        attempts = t["children"].get(root["span_id"], [])
        assert len(attempts) == 2, \
            f"expected both attempts under the root, got {len(attempts)}"
        assert {a.get("hedge") for a in attempts} == {True, False}
        cp_names = [n["name"] for n, _ in critical_path(t, root)]
        assert "device" in cp_names, \
            f"critical path must bottom out in a device span: {cp_names}"
        # The winner (hedge=True) bounds latency, not the slow loser.
        assert any(n.get("hedge") is True for n, _ in
                   critical_path(t, root) if n["name"] == "attempt")
        for trace in traces.values():
            print_waterfall(trace)
        pf = perfetto_events(traces)
        json.loads(json.dumps(pf))  # exports as valid JSON
        assert any(e.get("ph") == "X" for e in pf["traceEvents"])
        # Roofline over the cost attrs the device spans carried, under
        # a KNOWN peak (v5e) so bound-by classifies and MFU folds.
        import io

        from raft_tpu.obs import cost as cost_mod

        buf = io.StringIO()
        roofline_report(traces, cost_mod.peak_spec("v5e"), out=buf)
        txt = buf.getvalue()
        print(txt, end="")
        assert "device" in txt and "memory" in txt, \
            f"2 flop/byte vs the v5e ridge must read memory-bound:\n{txt}"
        assert "0.31" in txt, f"max observed mfu must surface:\n{txt}"
        assert "cost-weighted critical path" in txt
        # The bench record stays the LAST stdout line (tests and the
        # backlog scripts tail it into check_regression).
        rec = bench_record(traces)
        assert rec["config"]["traces_total"] == 2
        assert {"queue", "pad", "device"} <= set(
            rec["config"]["serve_span_names"])
        print(json.dumps(rec))
    return 0


# ---------------------------------------------------------------------------


def main(argv=None):
    args = parse_args(argv)
    if args.tiny:
        return _selftest()
    if not args.path:
        raise SystemExit("pass a telemetry path (or --tiny)")
    traces = build_traces(load_spans(args.path))
    if not traces:
        raise SystemExit(f"no trace_span events under {args.path!r} "
                         "(run with tracing on: --trace-sample-rate / "
                         "$RAFT_TRACE_SAMPLE_RATE)")
    if args.perfetto:
        with open(args.perfetto, "w") as f:
            json.dump(perfetto_events(traces), f)
        print(f"perfetto export: {args.perfetto} "
              f"({len(traces)} traces) — load in https://ui.perfetto.dev",
              file=sys.stderr)
        if not (args.trace or args.json):
            return 0
    if args.json:
        print(json.dumps(bench_record(traces)))
        return 0
    if args.roofline:
        roofline_report(traces, _resolve_spec(args.device_kind))
        return 0
    if args.trace:
        matches = [t for tid, t in traces.items()
                   if tid.startswith(args.trace)]
        if not matches:
            raise SystemExit(f"trace {args.trace!r} not in log "
                             f"({len(traces)} traces present)")
        for t in matches:
            print_waterfall(t)
        return 0
    ranked = sorted(
        traces.values(),
        key=lambda t: float((root_of(t) or {}).get("dur_s", 0.0)),
        reverse=True)
    for t in ranked[:max(args.slowest, 1)]:
        print_waterfall(t)
        print()
    print(f"{len(traces)} traces total; showing the "
          f"{min(len(ranked), max(args.slowest, 1))} slowest "
          "(--trace <id> for one, --json for the gate record)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
