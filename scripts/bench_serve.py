"""Serving-engine load generator: closed-loop and Poisson open-loop.

Drives ``raft_tpu.serve.InferenceEngine`` in-process (no HTTP overhead in
the measurement) with mixed-resolution synthetic frame pairs and prints
ONE JSON line per run in the ``bench.py`` format (metric / value / unit /
vs_baseline), plus the client-observed latency percentiles and the
engine's compile ledger.

Two canonical load shapes:

- ``--mode closed``: ``--concurrency`` workers each keep exactly one
  request in flight (submit, wait, repeat) — the saturation-throughput
  number, what "pairs/sec/chip can this engine do" means.
- ``--mode open``: requests arrive on a Poisson process at ``--rate``
  req/s regardless of completions — the production-realistic number,
  where latency percentiles and 429 rejections are the story (an open
  loop keeps arriving while the server falls behind; a closed loop
  politely waits and hides the collapse).

The workload is **mixed difficulty** (the traffic shape continuous
batching exists for): ``--easy-frac`` of the requests are low-motion
pairs (image2 = image1 + noise) submitted with a small per-request
iteration budget, the rest are independent random pairs at the full
budget — all drawn from ``--seed``, so every batching arm replays the
identical request sequence.

``--batching slot`` serves the workload at GRU-iteration granularity
(``ServeConfig.batching="slot"`` with ``--slots`` lanes and
``--early-exit-threshold``; docs/SERVING.md "Continuous batching");
``--batching both`` runs request-mode and slot-mode over the same
workload back to back and emits ONE record whose headline is the slot
arm, with the request arm and the slot/request p99 + throughput ratios
nested under ``arms`` / ``slot_vs_request``.  The record carries
``iters_used`` percentiles and slot ``occupancy`` next to the latency
percentiles — the two sides of the early-exit trade.

``--replicas N`` (N > 1) drives a ``ReplicaFleet`` behind the
``FlowRouter`` instead of a bare engine (optionally with
``--hedge-timeout-s``); the record gains per-replica engine sections
and the router counters.  Every record carries ``errors`` /
``timeouts`` (per ``--request-timeout-s``) / ``error_rate`` (failures
over submitted, 429 sheds excluded) and ``retries_total`` so
``scripts/check_regression.py --max-serve-error-rate`` can gate the
series — a fleet that posts throughput while losing requests fails.

``--quality-sample-rate`` (slot mode) turns on sampled scoring with the
unsupervised flow-quality proxies (``raft_tpu/obs/quality.py``); the
record then carries per-proxy p50/p95 (``quality``) next to the latency
percentiles — throughput, latency, and output quality in one line.

``--tiny``: CPU-friendly smoke preset (small model, fp32, 3 iters, two
tiny resolutions, ``--batching both``) so the serving path — and the
slot-vs-request comparison — stays testable without hardware::

    JAX_PLATFORMS=cpu python scripts/bench_serve.py --tiny
    JAX_PLATFORMS=cpu python scripts/bench_serve.py --tiny --mode open
    JAX_PLATFORMS=cpu python scripts/bench_serve.py --tiny --replicas 2

There is no external serving baseline (the reference repo has no request
path at all); ``vs_baseline`` is 0.0 until a measured TPU number lands
in a ``BENCH_SERVE_r*.json`` and becomes the bar, like bench.py's eval
mode did in round 3.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def parse_args(argv=None):
    p = argparse.ArgumentParser(description="RAFT-TPU serving benchmark")
    p.add_argument("--mode", default="closed", choices=["closed", "open"])
    p.add_argument("--tiny", action="store_true",
                   help="CPU smoke preset (small model, 3 iters, tiny "
                        "shapes, few requests, --batching both)")
    p.add_argument("--shapes", default="440x1024",
                   help="comma-separated HxW request resolutions, cycled "
                        "round-robin (mixed-shape traffic)")
    p.add_argument("--requests", type=int, default=64)
    p.add_argument("--concurrency", type=int, default=8,
                   help="closed-loop: in-flight requests")
    p.add_argument("--rate", type=float, default=20.0,
                   help="open-loop: Poisson arrival rate, req/s")
    p.add_argument("--small", action="store_true")
    p.add_argument("--precision", default="bf16", choices=["bf16", "fp32"])
    p.add_argument("--iters", type=int, default=32)
    p.add_argument("--batching", default=None,
                   choices=["request", "slot", "both"],
                   help="request-level batching (the parity oracle), "
                        "GRU-iteration-level slot batching, or both arms "
                        "over the same workload in one record (default: "
                        "request; --tiny defaults to both)")
    p.add_argument("--slots", type=int, default=8,
                   help="slot mode: persistent device lanes per bucket")
    p.add_argument("--early-exit-threshold", type=float, default=0.0,
                   help="slot mode: retire a lane when its max flow "
                        "update drops below this (0 = off; gate the "
                        "value with evaluate.py --early_exit_threshold)")
    p.add_argument("--early-exit-epe-delta", type=float, default=None,
                   help="measured |EPE delta| of --early-exit-threshold "
                        "vs the full-iteration baseline (from evaluate.py"
                        " --early_exit_threshold), stamped into the "
                        "record for check_regression.py "
                        "--max-early-exit-epe-delta; with the threshold "
                        "at 0 the delta is exactly 0 and stamps itself")
    p.add_argument("--quality-sample-rate", type=float, default=0.0,
                   help="slot mode: score this fraction of retiring "
                        "requests with the unsupervised flow-quality "
                        "proxies (raft_tpu/obs/quality.py); the record "
                        "then carries per-proxy p50/p95 next to the "
                        "latency percentiles (0 = off, the zero-"
                        "overhead default)")
    p.add_argument("--easy-frac", type=float, default=0.5,
                   help="fraction of requests that are low-motion pairs "
                        "with a reduced per-request iteration budget "
                        "(the mixed-difficulty workload)")
    p.add_argument("--max-batch", type=int, default=8)
    p.add_argument("--max-wait-ms", type=float, default=5.0)
    p.add_argument("--max-queue", type=int, default=256)
    p.add_argument("--batch-sizes", default=None,
                   help="comma-separated compiled batch sizes")
    p.add_argument("--no-warmup", action="store_true",
                   help="include first-request compiles in the "
                        "measurement (cold-start experiment)")
    p.add_argument("--replicas", type=int, default=1,
                   help="drive a supervised replica fleet behind the "
                        "health-gated router instead of one engine "
                        "(docs/SERVING.md fleet section)")
    p.add_argument("--hedge-timeout-s", type=float, default=0.0,
                   help="fleet mode: router hedge timeout (0 = off)")
    p.add_argument("--request-timeout-s", type=float, default=120.0,
                   help="per-request wait bound; expiries count in the "
                        "'timeouts' figure instead of hanging the bench")
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args(argv)
    if args.tiny:
        args.small = True
        args.precision = "fp32"
        args.iters = 3
        args.shapes = "64x96,36x52"
        args.requests = 36
        # Saturating in-flight load (2 buckets x 4 lanes): request mode
        # must queue whole waves while slot mode admits per iteration —
        # the regime continuous batching is for.  At concurrency ==
        # slots the closed loop never queues and the comparison is
        # vacuous.
        args.concurrency = 12
        args.rate = 40.0
        args.max_batch = 4
        args.batch_sizes = args.batch_sizes or "4"
        args.max_wait_ms = 10.0
        args.max_queue = 64
        args.slots = min(args.slots, 4)
        args.batching = args.batching or "both"
    args.batching = args.batching or "request"
    if args.replicas > 1 and args.batching != "request":
        raise SystemExit("--replicas > 1 serves request-mode engines; "
                         "use --batching request (slot-mode fleets are "
                         "future work)")
    if not 0.0 <= args.easy_frac <= 1.0:
        raise SystemExit(f"--easy-frac must be in [0, 1], got "
                         f"{args.easy_frac}")
    return args


def _make_workload(shapes, n_requests, iters_full, easy_frac, rng):
    """``[(im1, im2, iters), ...]`` — the seeded mixed-difficulty
    request sequence, identical across batching arms.

    Easy requests (``easy_frac`` of traffic) are low-motion pairs
    (image2 = image1 + noise) with a per-request budget drawn from the
    bottom half of ``[1, iters_full]``; hard requests are independent
    pairs at the full budget.  Request-level batching ignores the
    per-request budget (every lockstep lane pays ``iters_full``); slot
    mode honors it — that asymmetry IS the benchmark.
    """
    import numpy as np

    workload = []
    for i in range(n_requests):
        h, w = shapes[i % len(shapes)]
        im1 = rng.uniform(0, 255, (h, w, 3)).astype(np.float32)
        if rng.random() < easy_frac:
            im2 = np.clip(im1 + rng.normal(0, 2, im1.shape), 0,
                          255).astype(np.float32)
            iters = int(rng.integers(1, max(iters_full // 2, 1) + 1))
        else:
            im2 = rng.uniform(0, 255, (h, w, 3)).astype(np.float32)
            iters = iters_full
        workload.append((im1, im2, iters))
    return workload


class _Outcomes:
    """Thread-safe request-outcome tally: a request either completes,
    is rejected at submit (429 shed — intentional, NOT an error), fails
    with an error, or times out client-side."""

    def __init__(self, timeout_s):
        self.timeout_s = timeout_s
        self.lock = threading.Lock()
        self.completed = 0
        self.rejected = 0
        self.errors = 0
        self.timeouts = 0

    def wait(self, fut) -> None:
        from concurrent.futures import TimeoutError as FutTimeout

        try:
            fut.result(timeout=self.timeout_s)
        except FutTimeout:
            with self.lock:
                self.timeouts += 1
        except Exception:
            with self.lock:
                self.errors += 1
        else:
            with self.lock:
                self.completed += 1


def _submit(service, item, with_iters: bool):
    im1, im2, iters = item
    if with_iters:
        return service.submit(im1, im2, iters=iters)
    return service.submit(im1, im2)


def _run_closed(service, workload, concurrency, out: "_Outcomes",
                with_iters: bool):
    """Each worker keeps one request in flight; returns elapsed seconds."""
    from raft_tpu.serve import QueueFullError

    next_i = [0]
    lock = threading.Lock()

    def worker():
        while True:
            with lock:
                i = next_i[0]
                if i >= len(workload):
                    return
                next_i[0] += 1
            try:
                fut = _submit(service, workload[i], with_iters)
            except QueueFullError:
                with out.lock:
                    out.rejected += 1
                continue
            out.wait(fut)

    threads = [threading.Thread(target=worker) for _ in range(concurrency)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return time.perf_counter() - t0


def _run_open(service, workload, rate, rng, out: "_Outcomes",
              with_iters: bool):
    """Poisson arrivals at ``rate`` req/s; returns elapsed seconds.

    Arrivals keep coming while earlier requests run — rejected submits
    (429 backpressure) are counted, not retried (a shed request's work
    is the balancer's problem, not this chip's)."""
    from raft_tpu.serve import QueueFullError

    futures = []
    t0 = time.perf_counter()
    for item in workload:
        time.sleep(rng.exponential(1.0 / rate))
        try:
            futures.append(_submit(service, item, with_iters))
        except QueueFullError:
            with out.lock:
                out.rejected += 1
    for f in futures:
        out.wait(f)
    return time.perf_counter() - t0


def _arm_cost_fields(stats: dict, iters: int, value: float) -> dict:
    """Hardware-normalized work figures for one arm, from the engine's
    compile-time cost ledger (``stats()["cost"]``, obs/cost.py).

    ``flops_per_pair`` is the full-budget pipeline (``enc`` +
    ``iters`` x ``iter``) per pair, averaged over the compiled buckets;
    ``achieved_tflops`` re-multiplies it by the measured pairs/sec/chip
    (slot-mode early exit makes this the NOMINAL figure — a lane that
    retires early did less work than stamped, so slot-mode MFU is an
    upper bound).  ``mfu`` stays None on unknown device peaks (CPU),
    which is what keeps those records out of ``--min-mfu``.
    """
    groups: dict = {}
    for key, c in (stats.get("cost") or {}).items():
        prefix, prog = key.rsplit("/", 1)
        groups.setdefault(prefix, {})[prog] = c
    fpps, peaks = [], []
    for progs in groups.values():
        enc, it = progs.get("enc"), progs.get("iter")
        if not enc or not it or not enc.get("flops_per_pair"):
            continue
        fpps.append(enc["flops_per_pair"]
                    + iters * it["flops_per_pair"])
        peaks.append(enc.get("peak_tflops"))
    if not fpps:
        return {}
    fpp = sum(fpps) / len(fpps)
    achieved = value * fpp / 1e12
    peak = peaks[0]
    return {"flops_per_pair": round(fpp, 1),
            "achieved_tflops": round(achieved, 4),
            "mfu": round(achieved / peak, 4) if peak else None}


def _run_arm(args, variables, model_cfg, workload, shapes,
             batching: str):
    """One batching arm over the shared workload: build the service,
    warm it, drive the load, return the arm's figures."""
    import jax
    import numpy as np

    from raft_tpu.serve import InferenceEngine, ServeConfig

    serve_cfg = ServeConfig(
        iters=args.iters, max_batch=args.max_batch,
        max_wait_ms=args.max_wait_ms, max_queue=args.max_queue,
        batch_sizes=tuple(int(b) for b in args.batch_sizes.split(","))
        if args.batch_sizes else None,
        batching=batching, slots=args.slots,
        early_exit_threshold=args.early_exit_threshold
        if batching == "slot" else 0.0,
        quality_sample_rate=min(max(args.quality_sample_rate, 0.0), 1.0)
        if batching == "slot" else 0.0)
    fleet = None
    if args.replicas > 1:
        from raft_tpu.serve import (FleetConfig, FlowRouter,
                                    ReplicaFleet, RouterConfig)

        fleet = ReplicaFleet(
            variables, model_cfg, serve_cfg,
            FleetConfig(replicas=args.replicas,
                        warmup_shapes=() if args.no_warmup
                        else tuple(shapes)))
        fleet.start()
        service = FlowRouter(fleet, RouterConfig(
            hedge_timeout_s=max(args.hedge_timeout_s, 0.0)))
    else:
        service = InferenceEngine(variables, model_cfg, serve_cfg)
        service.start()
    out = _Outcomes(args.request_timeout_s or None)
    rng = np.random.default_rng(args.seed + 1)  # arrival jitter only
    try:
        if not args.no_warmup and fleet is None:
            service.warmup(shapes)
        with_iters = fleet is None  # router submit is (im1, im2) only
        if args.mode == "closed":
            assert args.concurrency <= args.max_queue, \
                "closed loop would trip its own backpressure"
            dt = _run_closed(service, workload, args.concurrency, out,
                             with_iters)
        else:
            dt = _run_open(service, workload, args.rate, rng, out,
                           with_iters)
        stats = service.stats()
    finally:
        if fleet is not None:
            fleet.stop()
        else:
            service.stop()

    n_dev = max(jax.local_device_count(), 1)
    # error_rate covers FAILED requests (errors + client timeouts) over
    # everything submitted; 429 sheds are intentional backpressure and
    # stay a separate figure (check_regression gates on error_rate).
    error_rate = (out.errors + out.timeouts) / max(len(workload), 1)
    arm = {
        "batching": batching,
        "value": round(out.completed / dt / n_dev, 3),
        "latency_ms": None,
        "rejected": out.rejected,
        "errors": out.errors,
        "timeouts": out.timeouts,
        "error_rate": round(error_rate, 6),
        "iters_used": None,
        "occupancy": None,
    }
    if fleet is not None:
        arm["replicas"] = {
            name: {"retries": rep.get("retries", 0),
                   "completed": rep.get("completed", 0),
                   "restarts": rep.get("restarts", 0)}
            for name, rep in stats["replicas"].items()}
        arm["retries_total"] = sum(r["retries"]
                                   for r in arm["replicas"].values())
        arm["latency_ms"] = stats["router"]["latency_ms"]
        arm["compiles"] = {name: rep.get("compiles", {})
                          for name, rep in stats["replicas"].items()}
        arm["router"] = {
            k: stats["router"][k]
            for k in ("requests_total", "failovers_total", "hedges_total",
                      "hedge_wins_total", "rejected_total",
                      "dropped_total")}
    else:
        arm["retries_total"] = stats["retries"]
        arm["latency_ms"] = stats["latency_ms"]
        arm["occupancy"] = stats["occupancy"]
        arm["compiles"] = stats["compiles"]
        arm["iters_used"] = stats.get("iters_used")
        arm["cost"] = stats.get("cost")
        # Sampled flow-quality proxies (raft_tpu/obs/quality.py):
        # per-proxy p50/p95 ride next to the latency percentiles when
        # quality scoring is on; {"enabled": False} arms stay silent.
        q = stats.get("quality")
        if isinstance(q, dict) and q.get("enabled"):
            arm["quality"] = q
        arm.update(_arm_cost_fields(stats, args.iters, arm["value"]))
    return arm


def main(argv=None):
    args = parse_args(argv)

    import jax
    import numpy as np

    from raft_tpu.config import RAFTConfig
    from raft_tpu.models.raft import RAFT

    mk = RAFTConfig.small_model if args.small else RAFTConfig.full
    model_cfg = mk(compute_dtype="bfloat16" if args.precision == "bf16"
                   else "float32")
    model = RAFT(model_cfg)
    key = jax.random.PRNGKey(args.seed)
    img = jax.numpy.zeros((1, 64, 96, 3))
    variables = jax.jit(
        lambda k: model.init({"params": k, "dropout": k}, img, img,
                             iters=2, train=False))(key)

    shapes = []
    for tok in args.shapes.split(","):
        h, w = tok.strip().lower().split("x")
        shapes.append((int(h), int(w)))
    workload = _make_workload(shapes, args.requests, args.iters,
                              args.easy_frac,
                              np.random.default_rng(args.seed))

    arm_names = (["request", "slot"] if args.batching == "both"
                 else [args.batching])
    arms = {name: _run_arm(args, variables, model_cfg, workload, shapes,
                           name)
            for name in arm_names}
    head = arms[arm_names[-1]]  # slot arm headlines a "both" run

    tag = "tiny" if args.tiny else "+".join(f"{h}x{w}"
                                            for (h, w) in shapes)
    load = (f"c{args.concurrency}" if args.mode == "closed"
            else f"r{args.rate:g}")
    rep_tag = f"_x{args.replicas}" if args.replicas > 1 else ""
    record = {
        "metric": f"serve_{args.mode}loop_{tag}_{load}"
                  f"_iters{args.iters}_{head['batching']}{rep_tag}",
        "value": head["value"],
        "unit": "image-pairs/sec/chip",
        "vs_baseline": 0.0,
        "config": {"mode": args.mode, "requests": args.requests,
                   "concurrency": args.concurrency, "rate": args.rate,
                   "shapes": args.shapes, "iters": args.iters,
                   "batching": args.batching, "slots": args.slots,
                   "early_exit_threshold": args.early_exit_threshold,
                   "easy_frac": args.easy_frac,
                   "max_batch": args.max_batch,
                   "max_wait_ms": args.max_wait_ms,
                   "max_queue": args.max_queue,
                   "batch_sizes": args.batch_sizes,
                   "warmup": not args.no_warmup,
                   "replicas": args.replicas,
                   "precision": args.precision, "small": args.small,
                   "seed": args.seed},
    }
    # Early-exit accuracy stamp for the regression gate: a disabled
    # threshold costs exactly zero EPE; a nonzero threshold needs the
    # measured figure from the evaluate.py sweep (no stamp -> the gate
    # refuses to pass vacuously).
    ee_delta = args.early_exit_epe_delta
    if ee_delta is None and "slot" in arms \
            and args.early_exit_threshold == 0.0:
        ee_delta = 0.0
    if ee_delta is not None:
        record["config"]["early_exit_epe_delta"] = abs(ee_delta)
    record.update({k: head[k] for k in
                   ("latency_ms", "rejected", "errors", "timeouts",
                    "error_rate", "retries_total", "occupancy",
                    "compiles", "iters_used", "cost", "flops_per_pair",
                    "achieved_tflops", "mfu", "quality") if k in head})
    for k in ("replicas", "router"):
        if k in head:
            record[k] = head[k]
    if args.batching == "both":
        record["arms"] = arms
        req, slot = arms["request"], arms["slot"]
        req_p99 = (req["latency_ms"] or {}).get("p99_ms") or 0.0
        slot_p99 = (slot["latency_ms"] or {}).get("p99_ms") or 0.0
        record["slot_vs_request"] = {
            # > 1.0 on both ratios = slot mode wins both ways.
            "throughput_ratio": round(
                slot["value"] / req["value"], 3) if req["value"] else None,
            "p99_ratio": round(req_p99 / slot_p99, 3) if slot_p99
            else None,
            "p99_ms_request": req_p99,
            "p99_ms_slot": slot_p99,
        }
    print(json.dumps(record))


if __name__ == "__main__":
    main()
