"""Serving-engine load generator: closed-loop and Poisson open-loop.

Drives ``raft_tpu.serve.InferenceEngine`` in-process (no HTTP overhead in
the measurement) with mixed-resolution synthetic frame pairs and prints
ONE JSON line per run in the ``bench.py`` format (metric / value / unit /
vs_baseline), plus the client-observed latency percentiles and the
engine's compile ledger.

Two canonical load shapes:

- ``--mode closed``: ``--concurrency`` workers each keep exactly one
  request in flight (submit, wait, repeat) — the saturation-throughput
  number, what "pairs/sec/chip can this engine do" means.
- ``--mode open``: requests arrive on a Poisson process at ``--rate``
  req/s regardless of completions — the production-realistic number,
  where latency percentiles and 429 rejections are the story (an open
  loop keeps arriving while the server falls behind; a closed loop
  politely waits and hides the collapse).

``--replicas N`` (N > 1) drives a ``ReplicaFleet`` behind the
``FlowRouter`` instead of a bare engine (optionally with
``--hedge-timeout-s``); the record gains per-replica engine sections
and the router counters.  Every record carries ``errors`` /
``timeouts`` (per ``--request-timeout-s``) / ``error_rate`` (failures
over submitted, 429 sheds excluded) and ``retries_total`` so
``scripts/check_regression.py --max-serve-error-rate`` can gate the
series — a fleet that posts throughput while losing requests fails.

``--tiny``: CPU-friendly smoke preset (small model, fp32, 2 iters, two
tiny resolutions) so the serving path stays testable without hardware::

    JAX_PLATFORMS=cpu python scripts/bench_serve.py --tiny
    JAX_PLATFORMS=cpu python scripts/bench_serve.py --tiny --mode open
    JAX_PLATFORMS=cpu python scripts/bench_serve.py --tiny --replicas 2

There is no external serving baseline (the reference repo has no request
path at all); ``vs_baseline`` is 0.0 until a measured TPU number lands
in a ``BENCH_SERVE_r*.json`` and becomes the bar, like bench.py's eval
mode did in round 3.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def parse_args(argv=None):
    p = argparse.ArgumentParser(description="RAFT-TPU serving benchmark")
    p.add_argument("--mode", default="closed", choices=["closed", "open"])
    p.add_argument("--tiny", action="store_true",
                   help="CPU smoke preset (small model, 2 iters, tiny "
                        "shapes, few requests)")
    p.add_argument("--shapes", default="440x1024",
                   help="comma-separated HxW request resolutions, cycled "
                        "round-robin (mixed-shape traffic)")
    p.add_argument("--requests", type=int, default=64)
    p.add_argument("--concurrency", type=int, default=8,
                   help="closed-loop: in-flight requests")
    p.add_argument("--rate", type=float, default=20.0,
                   help="open-loop: Poisson arrival rate, req/s")
    p.add_argument("--small", action="store_true")
    p.add_argument("--precision", default="bf16", choices=["bf16", "fp32"])
    p.add_argument("--iters", type=int, default=32)
    p.add_argument("--max-batch", type=int, default=8)
    p.add_argument("--max-wait-ms", type=float, default=5.0)
    p.add_argument("--max-queue", type=int, default=256)
    p.add_argument("--batch-sizes", default=None,
                   help="comma-separated compiled batch sizes")
    p.add_argument("--no-warmup", action="store_true",
                   help="include first-request compiles in the "
                        "measurement (cold-start experiment)")
    p.add_argument("--replicas", type=int, default=1,
                   help="drive a supervised replica fleet behind the "
                        "health-gated router instead of one engine "
                        "(docs/SERVING.md fleet section)")
    p.add_argument("--hedge-timeout-s", type=float, default=0.0,
                   help="fleet mode: router hedge timeout (0 = off)")
    p.add_argument("--request-timeout-s", type=float, default=120.0,
                   help="per-request wait bound; expiries count in the "
                        "'timeouts' figure instead of hanging the bench")
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args(argv)
    if args.tiny:
        args.small = True
        args.precision = "fp32"
        args.iters = 2
        args.shapes = "64x96,36x52"
        args.requests = 24
        args.concurrency = 4
        args.rate = 40.0
        args.max_batch = 4
        args.batch_sizes = args.batch_sizes or "4"
        args.max_wait_ms = 10.0
        args.max_queue = 64
    return args


class _Outcomes:
    """Thread-safe request-outcome tally: a request either completes,
    is rejected at submit (429 shed — intentional, NOT an error), fails
    with an error, or times out client-side."""

    def __init__(self, timeout_s):
        self.timeout_s = timeout_s
        self.lock = threading.Lock()
        self.completed = 0
        self.rejected = 0
        self.errors = 0
        self.timeouts = 0

    def wait(self, fut) -> None:
        from concurrent.futures import TimeoutError as FutTimeout

        try:
            fut.result(timeout=self.timeout_s)
        except FutTimeout:
            with self.lock:
                self.timeouts += 1
        except Exception:
            with self.lock:
                self.errors += 1
        else:
            with self.lock:
                self.completed += 1


def _run_closed(engine, pairs, n_requests, concurrency, out: "_Outcomes"):
    """Each worker keeps one request in flight; returns elapsed seconds."""
    from raft_tpu.serve import QueueFullError

    next_i = [0]
    lock = threading.Lock()

    def worker():
        while True:
            with lock:
                i = next_i[0]
                if i >= n_requests:
                    return
                next_i[0] += 1
            im1, im2 = pairs[i % len(pairs)]
            try:
                fut = engine.submit(im1, im2)
            except QueueFullError:
                with out.lock:
                    out.rejected += 1
                continue
            out.wait(fut)

    threads = [threading.Thread(target=worker) for _ in range(concurrency)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return time.perf_counter() - t0


def _run_open(engine, pairs, n_requests, rate, rng, out: "_Outcomes"):
    """Poisson arrivals at ``rate`` req/s; returns elapsed seconds.

    Arrivals keep coming while earlier requests run — rejected submits
    (429 backpressure) are counted, not retried (a shed request's work
    is the balancer's problem, not this chip's)."""
    from raft_tpu.serve import QueueFullError

    futures = []
    t0 = time.perf_counter()
    for i in range(n_requests):
        time.sleep(rng.exponential(1.0 / rate))
        im1, im2 = pairs[i % len(pairs)]
        try:
            futures.append(engine.submit(im1, im2))
        except QueueFullError:
            with out.lock:
                out.rejected += 1
    for f in futures:
        out.wait(f)
    return time.perf_counter() - t0


def main(argv=None):
    args = parse_args(argv)

    import jax
    import numpy as np

    from raft_tpu.config import RAFTConfig
    from raft_tpu.models.raft import RAFT
    from raft_tpu.serve import InferenceEngine, ServeConfig

    mk = RAFTConfig.small_model if args.small else RAFTConfig.full
    model_cfg = mk(compute_dtype="bfloat16" if args.precision == "bf16"
                   else "float32")
    model = RAFT(model_cfg)
    key = jax.random.PRNGKey(args.seed)
    img = jax.numpy.zeros((1, 64, 96, 3))
    variables = jax.jit(
        lambda k: model.init({"params": k, "dropout": k}, img, img,
                             iters=2, train=False))(key)

    shapes = []
    for tok in args.shapes.split(","):
        h, w = tok.strip().lower().split("x")
        shapes.append((int(h), int(w)))
    rng = np.random.default_rng(args.seed)
    pairs = [(rng.uniform(0, 255, (h, w, 3)).astype(np.float32),
              rng.uniform(0, 255, (h, w, 3)).astype(np.float32))
             for (h, w) in shapes]

    serve_cfg = ServeConfig(
        iters=args.iters, max_batch=args.max_batch,
        max_wait_ms=args.max_wait_ms, max_queue=args.max_queue,
        batch_sizes=tuple(int(b) for b in args.batch_sizes.split(","))
        if args.batch_sizes else None)
    fleet = None
    if args.replicas > 1:
        from raft_tpu.serve import (FleetConfig, FlowRouter,
                                    ReplicaFleet, RouterConfig)

        fleet = ReplicaFleet(
            variables, model_cfg, serve_cfg,
            FleetConfig(replicas=args.replicas,
                        warmup_shapes=() if args.no_warmup
                        else tuple(shapes)))
        fleet.start()
        service = FlowRouter(fleet, RouterConfig(
            hedge_timeout_s=max(args.hedge_timeout_s, 0.0)))
    else:
        service = InferenceEngine(variables, model_cfg, serve_cfg)
        service.start()
    out = _Outcomes(args.request_timeout_s or None)
    try:
        if not args.no_warmup and fleet is None:
            service.warmup(shapes)
        if args.mode == "closed":
            assert args.concurrency <= args.max_queue, \
                "closed loop would trip its own backpressure"
            dt = _run_closed(service, pairs, args.requests,
                             args.concurrency, out)
        else:
            dt = _run_open(service, pairs, args.requests, args.rate,
                           rng, out)
        stats = service.stats()
    finally:
        if fleet is not None:
            fleet.stop()
        else:
            service.stop()

    n_dev = max(jax.local_device_count(), 1)
    pairs_per_sec_per_chip = out.completed / dt / n_dev
    # error_rate covers FAILED requests (errors + client timeouts) over
    # everything submitted; 429 sheds are intentional backpressure and
    # stay a separate figure (check_regression gates on error_rate).
    error_rate = (out.errors + out.timeouts) / max(args.requests, 1)
    if fleet is not None:
        per_replica = {
            name: {"retries": rep.get("retries", 0),
                   "completed": rep.get("completed", 0),
                   "restarts": rep.get("restarts", 0)}
            for name, rep in stats["replicas"].items()}
        retries_total = sum(r["retries"] for r in per_replica.values())
        latency = stats["router"]["latency_ms"]
        occupancy = None
        compiles = {name: rep.get("compiles", {})
                    for name, rep in stats["replicas"].items()}
    else:
        per_replica = None
        retries_total = stats["retries"]
        latency = stats["latency_ms"]
        occupancy = stats["occupancy"]
        compiles = stats["compiles"]
    tag = "tiny" if args.tiny else "+".join(f"{h}x{w}"
                                            for (h, w) in shapes)
    load = (f"c{args.concurrency}" if args.mode == "closed"
            else f"r{args.rate:g}")
    rep_tag = f"_x{args.replicas}" if args.replicas > 1 else ""
    record = {
        "metric": f"serve_{args.mode}loop_{tag}_{load}"
                  f"_iters{args.iters}{rep_tag}",
        "value": round(pairs_per_sec_per_chip, 3),
        "unit": "image-pairs/sec/chip",
        "vs_baseline": 0.0,
        "latency_ms": latency,
        "rejected": out.rejected,
        "errors": out.errors,
        "timeouts": out.timeouts,
        "error_rate": round(error_rate, 6),
        "retries_total": retries_total,
        "occupancy": occupancy,
        "compiles": compiles,
        "config": {"mode": args.mode, "requests": args.requests,
                   "concurrency": args.concurrency, "rate": args.rate,
                   "shapes": args.shapes, "iters": args.iters,
                   "max_batch": args.max_batch,
                   "max_wait_ms": args.max_wait_ms,
                   "max_queue": args.max_queue,
                   "batch_sizes": args.batch_sizes,
                   "warmup": not args.no_warmup,
                   "replicas": args.replicas,
                   "precision": args.precision, "small": args.small},
    }
    if per_replica is not None:
        record["replicas"] = per_replica
        record["router"] = {
            k: stats["router"][k]
            for k in ("requests_total", "failovers_total", "hedges_total",
                      "hedge_wins_total", "rejected_total",
                      "dropped_total")}
    print(json.dumps(record))


if __name__ == "__main__":
    main()
