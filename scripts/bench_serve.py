"""Serving-engine load generator: closed-loop and Poisson open-loop.

Drives ``raft_tpu.serve.InferenceEngine`` in-process (no HTTP overhead in
the measurement) with mixed-resolution synthetic frame pairs and prints
ONE JSON line per run in the ``bench.py`` format (metric / value / unit /
vs_baseline), plus the client-observed latency percentiles and the
engine's compile ledger.

Two canonical load shapes:

- ``--mode closed``: ``--concurrency`` workers each keep exactly one
  request in flight (submit, wait, repeat) — the saturation-throughput
  number, what "pairs/sec/chip can this engine do" means.
- ``--mode open``: requests arrive on a Poisson process at ``--rate``
  req/s regardless of completions — the production-realistic number,
  where latency percentiles and 429 rejections are the story (an open
  loop keeps arriving while the server falls behind; a closed loop
  politely waits and hides the collapse).

``--tiny``: CPU-friendly smoke preset (small model, fp32, 2 iters, two
tiny resolutions) so the serving path stays testable without hardware::

    JAX_PLATFORMS=cpu python scripts/bench_serve.py --tiny
    JAX_PLATFORMS=cpu python scripts/bench_serve.py --tiny --mode open

There is no external serving baseline (the reference repo has no request
path at all); ``vs_baseline`` is 0.0 until a measured TPU number lands
in a ``BENCH_SERVE_r*.json`` and becomes the bar, like bench.py's eval
mode did in round 3.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def parse_args(argv=None):
    p = argparse.ArgumentParser(description="RAFT-TPU serving benchmark")
    p.add_argument("--mode", default="closed", choices=["closed", "open"])
    p.add_argument("--tiny", action="store_true",
                   help="CPU smoke preset (small model, 2 iters, tiny "
                        "shapes, few requests)")
    p.add_argument("--shapes", default="440x1024",
                   help="comma-separated HxW request resolutions, cycled "
                        "round-robin (mixed-shape traffic)")
    p.add_argument("--requests", type=int, default=64)
    p.add_argument("--concurrency", type=int, default=8,
                   help="closed-loop: in-flight requests")
    p.add_argument("--rate", type=float, default=20.0,
                   help="open-loop: Poisson arrival rate, req/s")
    p.add_argument("--small", action="store_true")
    p.add_argument("--precision", default="bf16", choices=["bf16", "fp32"])
    p.add_argument("--iters", type=int, default=32)
    p.add_argument("--max-batch", type=int, default=8)
    p.add_argument("--max-wait-ms", type=float, default=5.0)
    p.add_argument("--max-queue", type=int, default=256)
    p.add_argument("--batch-sizes", default=None,
                   help="comma-separated compiled batch sizes")
    p.add_argument("--no-warmup", action="store_true",
                   help="include first-request compiles in the "
                        "measurement (cold-start experiment)")
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args(argv)
    if args.tiny:
        args.small = True
        args.precision = "fp32"
        args.iters = 2
        args.shapes = "64x96,36x52"
        args.requests = 24
        args.concurrency = 4
        args.rate = 40.0
        args.max_batch = 4
        args.batch_sizes = args.batch_sizes or "4"
        args.max_wait_ms = 10.0
        args.max_queue = 64
    return args


def _run_closed(engine, pairs, n_requests, concurrency):
    """Each worker keeps one request in flight; returns elapsed seconds."""
    next_i = [0]
    lock = threading.Lock()

    def worker():
        while True:
            with lock:
                i = next_i[0]
                if i >= n_requests:
                    return
                next_i[0] += 1
            im1, im2 = pairs[i % len(pairs)]
            engine.infer(im1, im2)

    threads = [threading.Thread(target=worker) for _ in range(concurrency)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return time.perf_counter() - t0, 0


def _run_open(engine, pairs, n_requests, rate, rng):
    """Poisson arrivals at ``rate`` req/s; returns (elapsed, rejected).

    Arrivals keep coming while earlier requests run — rejected submits
    (429 backpressure) are counted, not retried (a shed request's work
    is the balancer's problem, not this chip's)."""
    from raft_tpu.serve import QueueFullError

    futures, rejected = [], 0
    t0 = time.perf_counter()
    for i in range(n_requests):
        time.sleep(rng.exponential(1.0 / rate))
        im1, im2 = pairs[i % len(pairs)]
        try:
            futures.append(engine.submit(im1, im2))
        except QueueFullError:
            rejected += 1
    for f in futures:
        f.result()
    return time.perf_counter() - t0, rejected


def main(argv=None):
    args = parse_args(argv)

    import jax
    import numpy as np

    from raft_tpu.config import RAFTConfig
    from raft_tpu.models.raft import RAFT
    from raft_tpu.serve import InferenceEngine, ServeConfig

    mk = RAFTConfig.small_model if args.small else RAFTConfig.full
    model_cfg = mk(compute_dtype="bfloat16" if args.precision == "bf16"
                   else "float32")
    model = RAFT(model_cfg)
    key = jax.random.PRNGKey(args.seed)
    img = jax.numpy.zeros((1, 64, 96, 3))
    variables = jax.jit(
        lambda k: model.init({"params": k, "dropout": k}, img, img,
                             iters=2, train=False))(key)

    shapes = []
    for tok in args.shapes.split(","):
        h, w = tok.strip().lower().split("x")
        shapes.append((int(h), int(w)))
    rng = np.random.default_rng(args.seed)
    pairs = [(rng.uniform(0, 255, (h, w, 3)).astype(np.float32),
              rng.uniform(0, 255, (h, w, 3)).astype(np.float32))
             for (h, w) in shapes]

    serve_cfg = ServeConfig(
        iters=args.iters, max_batch=args.max_batch,
        max_wait_ms=args.max_wait_ms, max_queue=args.max_queue,
        batch_sizes=tuple(int(b) for b in args.batch_sizes.split(","))
        if args.batch_sizes else None)
    engine = InferenceEngine(variables, model_cfg, serve_cfg)
    engine.start()
    try:
        if not args.no_warmup:
            engine.warmup(shapes)
        if args.mode == "closed":
            assert args.concurrency <= args.max_queue, \
                "closed loop would trip its own backpressure"
            dt, rejected = _run_closed(engine, pairs, args.requests,
                                       args.concurrency)
        else:
            dt, rejected = _run_open(engine, pairs, args.requests,
                                     args.rate, rng)
        stats = engine.stats()
    finally:
        engine.stop()

    n_dev = max(jax.local_device_count(), 1)
    completed = args.requests - rejected
    pairs_per_sec_per_chip = completed / dt / n_dev
    tag = "tiny" if args.tiny else "+".join(f"{h}x{w}"
                                            for (h, w) in shapes)
    load = (f"c{args.concurrency}" if args.mode == "closed"
            else f"r{args.rate:g}")
    print(json.dumps({
        "metric": f"serve_{args.mode}loop_{tag}_{load}_iters{args.iters}",
        "value": round(pairs_per_sec_per_chip, 3),
        "unit": "image-pairs/sec/chip",
        "vs_baseline": 0.0,
        "latency_ms": stats["latency_ms"],
        "rejected": rejected,
        "occupancy": stats["occupancy"],
        "compiles": stats["compiles"],
        "config": {"mode": args.mode, "requests": args.requests,
                   "concurrency": args.concurrency, "rate": args.rate,
                   "shapes": args.shapes, "iters": args.iters,
                   "max_batch": args.max_batch,
                   "max_wait_ms": args.max_wait_ms,
                   "max_queue": args.max_queue,
                   "batch_sizes": args.batch_sizes,
                   "warmup": not args.no_warmup,
                   "precision": args.precision, "small": args.small},
    }))


if __name__ == "__main__":
    main()
