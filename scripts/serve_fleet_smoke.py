"""Fleet chaos drill: prove the self-healing serving fleet heals
(tier-1, CPU).

Brings up a 2-replica :class:`raft_tpu.serve.ReplicaFleet` behind the
health-gated :class:`raft_tpu.serve.FlowRouter` with a tiny model and
walks the three promises docs/SERVING.md's fleet section makes:

1. **AOT warm-start**: replica 0 compiles the warmup ladder and exports
   it; replica 1 imports and serves with ZERO JIT compiles
   (``CompileCounter``-asserted).
2. **Kill drill**: a deterministic ``replica_kill`` chaos fault takes a
   replica down mid-batch under open-loop load.  Every accepted request
   still resolves (failover, ``raft_fleet_dropped_total == 0``), the
   supervisor restarts the dead replica with backoff, and the restarted
   replica ALSO comes up with zero compiles (AOT import again).
3. **Rolling weight update**: new weights land in an orbax run-layout
   checkpoint; ``update_weights`` verifies the newest step (an actual
   restore), canaries the warming engine, then flips both replicas with
   zero downtime.  A TORN copy of the same checkpoint is refused at the
   verify gate — the fleet keeps serving the good version.  A
   NaN-poisoned weight set is refused at the canary gate.

Prints one bench.py-format JSON line (``metric: serve_fleet_smoke``,
``value`` 1.0 = every promise held); exit 0, or an assertion failure.

::

    JAX_PLATFORMS=cpu python scripts/serve_fleet_smoke.py --tiny
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def parse_args(argv=None):
    p = argparse.ArgumentParser(description="serving-fleet chaos drill")
    p.add_argument("--tiny", action="store_true",
                   help="smallest shapes/counts (the tier-1 CPU drill)")
    p.add_argument("--requests", type=int, default=None,
                   help="open-loop requests through the kill drill")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--keep", default=None, metavar="DIR",
                   help="keep artifacts (AOT dir, checkpoints, "
                        "telemetry) under DIR instead of a temp dir")
    return p.parse_args(argv)


def _wait_for(pred, timeout_s, what):
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        if pred():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out after {timeout_s}s waiting for "
                         f"{what}")


def main(argv=None) -> int:
    args = parse_args(argv)
    n_requests = args.requests or (16 if args.tiny else 64)
    workdir = args.keep or tempfile.mkdtemp(prefix="raft-fleet-smoke-")
    os.makedirs(workdir, exist_ok=True)
    os.environ.setdefault("RAFT_TELEMETRY_DIR",
                          os.path.join(workdir, "telemetry"))

    import jax
    import numpy as np

    from raft_tpu import chaos
    from raft_tpu.config import RAFTConfig
    from raft_tpu.models.raft import RAFT
    from raft_tpu.serve import (FleetConfig, FlowRouter, ReplicaFleet,
                                RouterConfig, ServeConfig,
                                WeightUpdateError)
    from raft_tpu.train.checkpoint import CheckpointManager

    model_cfg = RAFTConfig.small_model()  # fp32: CPU-friendly
    shape = (36, 52)  # -> bucket (40, 56)
    model_img = jax.numpy.zeros((1, 40, 56, 3))

    def init_vars(seed):
        k = jax.random.PRNGKey(seed)
        return RAFT(model_cfg).init({"params": k, "dropout": k},
                                    model_img, model_img, iters=1)

    variables = init_vars(args.seed)
    serve_cfg = ServeConfig(iters=2, max_batch=2, batch_sizes=(2,),
                            max_wait_ms=5, max_queue=64,
                            stall_timeout_s=30.0)
    fleet = ReplicaFleet(
        variables, model_cfg, serve_cfg,
        FleetConfig(replicas=2, warmup_shapes=(shape,),
                    restart_backoff_s=0.05, restart_backoff_max_s=0.5,
                    health_poll_s=0.05,
                    aot_dir=os.path.join(workdir, "aot")))
    t0 = time.perf_counter()
    fleet.start()
    router = FlowRouter(fleet, RouterConfig())
    checks = {}
    rng = np.random.default_rng(args.seed)

    def frame():
        return rng.uniform(0, 255, shape + (3,)).astype(np.float32)

    try:
        # -- 1. AOT warm-start ----------------------------------------
        r0, r1 = fleet.replicas
        assert r1.engine.aot_info["ok"] is True, r1.engine.aot_info
        assert r1.engine.compile_counter.counts() == {}, \
            "replica 1 compiled despite AOT import"
        flow = router.infer(frame(), frame(), timeout=120)
        assert flow.shape == shape + (2,)
        assert r1.engine.compile_counter.counts() == {}, \
            "first fleet request triggered a JIT compile on replica 1"
        checks["aot_warm_start"] = {
            "imported": r1.engine.aot_info["imported"],
            "startup_s": round(time.perf_counter() - t0, 2)}

        # -- 2. kill drill under open-loop load -----------------------
        chaos.install(chaos.FaultPlan.parse("replica_kill@batch=3",
                                            seed=args.seed))
        futures = []
        for _ in range(n_requests):
            futures.append(router.submit(frame(), frame()))
            time.sleep(0.01)  # open loop: arrivals keep coming
        results = [f.result(timeout=120) for f in futures]
        chaos.uninstall()
        assert all(r.shape == shape + (2,) for r in results), \
            "a request accepted before the kill never produced flow"
        rstats = router.router_stats()
        assert rstats["dropped_total"] == 0, rstats
        assert rstats["failovers_total"] >= 1, \
            f"kill fired but no failover recorded: {rstats}"
        _wait_for(lambda: sum(r.restarts for r in fleet.replicas) >= 1
                  and all(r.state == "ready" for r in fleet.replicas),
                  30, "supervised restart of the killed replica")
        restarted = next(r for r in fleet.replicas if r.restarts)
        assert restarted.engine.aot_info["ok"] is True
        assert restarted.engine.compile_counter.counts() == {}, \
            "restarted replica had to JIT-compile (AOT import failed)"
        flow = router.infer(frame(), frame(), timeout=120)
        assert flow.shape == shape + (2,)
        assert restarted.engine.compile_counter.counts() == {}, \
            "restarted replica compiled on its first request"
        checks["kill_drill"] = {
            "requests": n_requests,
            "failovers": rstats["failovers_total"],
            "dropped": rstats["dropped_total"],
            "restarts": {r.name: r.restarts for r in fleet.replicas}}

        # -- 3. rolling weight update (verify + canary gated) ---------
        new_vars = jax.device_get(init_vars(args.seed + 1))
        ckpt_dir = os.path.join(workdir, "ckpt-good")
        mgr = CheckpointManager(ckpt_dir, async_save=False)
        mgr.save(1, new_vars)
        mgr.wait()
        mgr.close()
        report = fleet.update_weights(ckpt_dir)
        assert report["ok"] and report["provenance"]["verified"], report
        assert sorted(report["flipped"]) == ["r0", "r1"], report
        assert fleet.weights_version == 2
        flow = router.infer(frame(), frame(), timeout=120)
        assert flow.shape == shape + (2,)

        # torn checkpoint: refused at the verify gate, version holds
        torn_dir = os.path.join(workdir, "ckpt-torn")
        mgr = CheckpointManager(torn_dir, async_save=False)
        mgr.save(1, new_vars)
        mgr.wait()
        mgr.close()
        chaos.tear_files(os.path.join(torn_dir, "1"))
        try:
            fleet.update_weights(torn_dir)
            raise AssertionError("torn checkpoint was NOT refused")
        except WeightUpdateError as e:
            torn_msg = str(e)
        assert fleet.weights_version == 2

        # NaN-poisoned weights: refused at the canary gate
        poisoned = jax.tree_util.tree_map(
            lambda x: np.full_like(x, np.nan), new_vars)
        try:
            fleet.update_weights(jax.device_get(poisoned))
            raise AssertionError("NaN weights were NOT refused")
        except WeightUpdateError as e:
            assert "canary" in str(e), e
        assert fleet.weights_version == 2
        flow = router.infer(frame(), frame(), timeout=120)
        assert flow.shape == shape + (2,)
        checks["rolling_update"] = {
            "version": fleet.weights_version,
            "flipped": report["flipped"],
            "torn_refused": torn_msg[:120],
            "update_s": report["seconds"]}

        # -- fleet-wide invariants ------------------------------------
        mt = fleet.metrics_text()
        assert 'replica="r0"' in mt and 'replica="r1"' in mt
        assert "raft_fleet_restarts_total" in mt
        health = fleet.health()
        assert health["ready"], health
        ok = True
    finally:
        chaos.uninstall()
        fleet.stop()

    print(json.dumps({
        "metric": "serve_fleet_smoke",
        "value": 1.0 if ok else 0.0,
        "unit": "pass",
        "vs_baseline": 0.0,
        "config": dict(checks, requests=n_requests, replicas=2,
                       workdir=workdir if args.keep else None),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
