"""Capture an op-level XProf profile of the bench training step.

Round-1 tuning worked from whole-step ablations only; this script closes
that gap: it runs the exact bench.py training configuration under a
``jax.profiler`` trace and converts the captured xplane with the local
``xprof`` package into per-HLO-op statistics (no TensorBoard UI needed —
this box is headless).

Usage:
    python scripts/profile_step.py [outdir]
Env: same knobs as bench.py (BENCH_BATCH, BENCH_IMAGE, BENCH_CORR_IMPL...).

Outputs in <outdir> (default ``$RAFT_TELEMETRY_DIR/xprof/bench-<ts>``
when telemetry is configured, else ``/tmp/raft_prof``) — the same
``xprof/`` layout the serve ``POST /debug/profile`` endpoint and the
train ``--profile-steps`` flag write, so every capture lands where
trace spans link to (``docs/OBSERVABILITY.md``).  An ``xprof_capture``
event is emitted into the telemetry stream, and any trace span
recorded during the capture carries ``xprof=<outdir>``:
    hlo_stats.json      per-op table (category, self time, FLOP rate)
    op_profile.json     xprof op_profile tree
    summary.txt         top self-time ops + per-category rollup

Before profiling, ask what the step is *bound by*: ``python -m
raft_tpu cost`` prints the compiled programs' FLOPs/bytes/roofline
verdict from compile-time metadata alone (``raft_tpu/obs/cost.py``;
``docs/PERFORMANCE.md`` has the triage table) — a memory-bound
verdict changes what to look for in the capture, and the measured
FLOP rates here are what validate the cost model's analytic kernel
formulas on hardware (``scripts/tpu_backlog_r07.sh``).
"""

from __future__ import annotations

import glob
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def capture(outdir: str) -> str:
    import jax
    import numpy as np

    from raft_tpu.config import RAFTConfig, TrainConfig
    from raft_tpu.models.raft import RAFT
    from raft_tpu.parallel.mesh import make_mesh, shard_batch
    from raft_tpu.train.optim import make_optimizer
    from raft_tpu.train.step import init_state, make_train_step

    n_dev = jax.device_count()
    mesh = make_mesh(num_data=n_dev, num_spatial=1)
    H, W = (int(x) for x in
            os.environ.get("BENCH_IMAGE", "368x496").split("x"))
    B = int(os.environ.get("BENCH_BATCH", 16)) * n_dev
    _d = RAFTConfig()
    model_cfg = RAFTConfig.full(
        compute_dtype=os.environ.get("BENCH_COMPUTE_DTYPE", "bfloat16"),
        corr_impl=os.environ.get("BENCH_CORR_IMPL", "allpairs_pallas"),
        corr_precision=os.environ.get("BENCH_CORR_PRECISION", "highest"),
        remat=os.environ.get("BENCH_REMAT", "1") == "1",
        remat_policy=os.environ.get("BENCH_REMAT_POLICY", _d.remat_policy),
        scan_unroll=int(os.environ.get("BENCH_SCAN_UNROLL", _d.scan_unroll)),
        remat_upsample=os.environ.get("BENCH_REMAT_UPSAMPLE", "1") == "1")
    cfg = TrainConfig(num_steps=1000, batch_size=B, image_size=(H, W),
                      iters=12)

    model = RAFT(model_cfg)
    tx = make_optimizer(cfg.lr, cfg.num_steps, cfg.wdecay, cfg.epsilon,
                        cfg.clip)
    state = init_state(model, tx, jax.random.PRNGKey(0), (48, 64))
    step_fn = make_train_step(model, tx, cfg, mesh)

    rng = np.random.default_rng(0)
    batch = shard_batch({
        "image1": rng.uniform(0, 255, (B, H, W, 3)).astype(np.float32),
        "image2": rng.uniform(0, 255, (B, H, W, 3)).astype(np.float32),
        "flow": (8.0 * rng.standard_normal((B, H, W, 2))).astype(np.float32),
        "valid": np.ones((B, H, W), np.float32),
    }, mesh)
    key = jax.random.PRNGKey(1)

    for _ in range(3):
        state, metrics = step_fn(state, batch, key)
    float(metrics["loss"])

    # Link the capture into the tracing layer: spans recorded during
    # the profiled window carry xprof=<outdir> (raft_tpu/obs/trace.py).
    from raft_tpu.obs import trace

    jax.profiler.start_trace(outdir)
    trace.set_active_profile(outdir)
    try:
        for _ in range(3):
            state, metrics = step_fn(state, batch, key)
        float(metrics["loss"])  # hard sync before stopping the trace
    finally:
        trace.set_active_profile(None)
        jax.profiler.stop_trace()

    paths = glob.glob(os.path.join(outdir, "plugins/profile/*/*.xplane.pb"))
    if not paths:
        raise RuntimeError(f"no xplane.pb under {outdir}")
    return max(paths, key=os.path.getmtime)


def convert(xplane: str, outdir: str) -> None:
    from xprof.convert import raw_to_tool_data as rtd

    for tool in ("hlo_stats", "op_profile"):
        try:
            data = rtd.xspace_to_tool_data([xplane], tool, {})
            if isinstance(data, tuple):
                data = data[0]
            out = os.path.join(outdir, f"{tool}.json")
            mode = "wb" if isinstance(data, bytes) else "w"
            with open(out, mode) as f:
                f.write(data)
            print(f"wrote {out}")
        except Exception as e:  # tool coverage varies by xprof version
            print(f"{tool} conversion failed: {e!r}")


def summarize(outdir: str) -> None:
    path = os.path.join(outdir, "hlo_stats.json")
    if not os.path.exists(path):
        return
    with open(path) as f:
        raw = f.read()
    data = json.loads(raw)
    # hlo_stats is a GViz table: {cols: [...], rows: [{c: [{v: ...}]}]}
    if isinstance(data, list):
        data = data[0]
    cols = [c.get("label") or c.get("id") for c in data["cols"]]
    rows = [[cell.get("v") if isinstance(cell, dict) else cell
             for cell in r["c"]] for r in data["rows"]]

    def col(name_frag):
        for i, c in enumerate(cols):
            if c and name_frag.lower() in str(c).lower():
                return i
        return None

    i_cat = col("category")
    i_name = col("HLO op name") or col("op name")
    i_self = col("Total self time (us)") or col("self time")
    i_prog = col("program")
    lines = [f"columns: {cols}", ""]

    by_cat = {}
    for r in rows:
        cat = r[i_cat] if i_cat is not None else "?"
        t = float(r[i_self] or 0) if i_self is not None else 0.0
        by_cat[cat] = by_cat.get(cat, 0.0) + t
    total = sum(by_cat.values())
    lines.append(f"== per-category self time (total {total/1e3:.1f} ms "
                 "across traced steps) ==")
    for cat, t in sorted(by_cat.items(), key=lambda kv: -kv[1]):
        lines.append(f"  {t/1e3:9.2f} ms  {100*t/max(total,1e-9):5.1f}%  {cat}")

    lines.append("")
    lines.append("== top 60 ops by self time ==")
    rows.sort(key=lambda r: -(float(r[i_self] or 0)
                              if i_self is not None else 0))
    for r in rows[:60]:
        t = float(r[i_self] or 0) / 1e3
        name = str(r[i_name])[:140] if i_name is not None else "?"
        cat = r[i_cat] if i_cat is not None else "?"
        prog = (str(r[i_prog])[:20] if i_prog is not None else "")
        lines.append(f"  {t:9.2f} ms  [{cat}] {prog} {name}")

    out = "\n".join(lines)
    with open(os.path.join(outdir, "summary.txt"), "w") as f:
        f.write(out + "\n")
    print(out)


def default_outdir() -> str:
    """Trace-linked layout when telemetry is configured, /tmp otherwise.

    ``$RAFT_TELEMETRY_DIR/xprof/bench-<ts>`` is the same directory the
    serve ``/debug/profile`` endpoint and the train ``--profile-steps``
    flag use, so one telemetry dir holds traces AND their profiles."""
    telem = os.environ.get("RAFT_TELEMETRY_DIR")
    if telem:
        return os.path.join(telem, "xprof",
                            time.strftime("bench-%Y%m%d-%H%M%S"))
    return "/tmp/raft_prof"


def _emit_capture_event(outdir: str) -> None:
    """Stamp the capture into the telemetry stream so trace_report /
    telemetry_summary readers can find the artifacts later."""
    try:
        from raft_tpu.obs.events import default_sink

        sink = default_sink()
        if sink is not None:
            sink.emit("xprof_capture", source="profile_step", dir=outdir)
    except Exception:
        pass  # telemetry must never fail a capture


if __name__ == "__main__":
    outdir = sys.argv[1] if len(sys.argv) > 1 else default_outdir()
    os.makedirs(outdir, exist_ok=True)
    t0 = time.time()
    xplane = capture(outdir)
    print(f"captured {xplane} in {time.time()-t0:.0f}s")
    _emit_capture_event(outdir)
    convert(xplane, outdir)
    summarize(outdir)
