"""Curriculum smoke: prove kill-anywhere resume converges (tier-1).

Drives the REAL curriculum driver (``raft_tpu.curriculum``) over a
micro on-disk FlyingChairs corpus with a two-stage manifest, twice:

- **Run A** — uninterrupted: both stages train to completion; its
  normalized stage ledger is the reference.  (Executed in full mode;
  ``--tiny`` substitutes the analytically-known result — every stage
  ``complete`` at ``final_step = steps`` — to keep the tier-1 CPU
  budget: each train invocation costs a fresh ~25 s XLA:CPU step-fn
  compile, and run A is two of them.)
- **Run B** — chaos-killed at BOTH kill classes docs/ROBUSTNESS.md
  promises resume across, then resumed by re-running the same command:

  1. ``preempt@step=3;torn_ckpt@step=3`` — a SIGTERM lands mid-stage 1
     (the cooperative flag fires at the step boundary where the last
     COMPLETED step is 3 — odd, so unsaved by the val_freq=2 cadence);
     the emergency checkpoint of step 3 is torn post-commit.  The
     driver exits 143 with stage 1 ``running``.
  2. ``stage_kill@step=1`` — the resume restores stage 1 past the torn
     step (exactly one ``ckpt_fallback``), finishes it, then dies at
     the stage BOUNDARY — after stage 1's ledger commit, before stage 2
     starts.  Exits 143 with stage 2 still ``pending``.
  3. no plan — stage 1 is skipped as complete, stage 2 trains to the
     end.

The final assertion is convergence: run B's normalized ledger (status +
per-stage final_step) equals run A's, with exactly the expected
telemetry (``chaos_inject`` = 3, ``ckpt_fallback`` = 1, every
``ckpt_commit`` ok).  ``verify-ckpt`` is then run over run B's stage-1
directory to check the torn step is reported CORRUPT alongside its
saved-topology stamp.

Prints one bench.py-format JSON line (``metric: curriculum_smoke``,
``value`` 1.0 = converged); exit 0/1.

::

    python scripts/curriculum_smoke.py --tiny     # the tier-1 CPU smoke
    python scripts/curriculum_smoke.py            # same flow, bigger shapes
"""

from __future__ import annotations

import argparse
import io
import json
import os
import os.path as osp
import shutil
import sys
import tempfile
from contextlib import redirect_stdout

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def parse_args(argv=None):
    p = argparse.ArgumentParser(
        description="chaos-killed curriculum resume smoke test")
    p.add_argument("--tiny", action="store_true",
                   help="smallest shapes/steps (the tier-1 CPU smoke)")
    p.add_argument("--seed", type=int, default=0,
                   help="chaos plan seed (the plans here are fully "
                        "deterministic; the seed only matters for "
                        "p= rules)")
    p.add_argument("--keep", default=None, metavar="DIR",
                   help="keep artifacts (corpus + workdirs + telemetry) "
                        "under DIR instead of a deleted temp dir")
    return p.parse_args(argv)


def build_chairs(root, n=10, n_val=2, hw=(64, 96), seed=0):
    """Micro FlyingChairs corpus in the reference layout: rigid integer
    translations of blocky random textures (exactly representable
    flow), ppm pairs + .flo + split file."""
    import numpy as np
    from PIL import Image

    from raft_tpu.data import frame_utils

    data = osp.join(root, "datasets", "FlyingChairs_release", "data")
    os.makedirs(data, exist_ok=True)
    H, W = hw
    rng = np.random.default_rng(seed)
    for i in range(n):
        coarse = rng.uniform(0, 255, (H // 8 + 3, W // 8 + 3, 3))
        big = np.kron(coarse, np.ones((8, 8, 1)))
        u = int(rng.integers(-4, 5))
        v = int(rng.integers(-4, 5))
        img1 = big[8:8 + H, 8:8 + W].astype(np.uint8)
        img2 = big[8 - v:8 - v + H, 8 - u:8 - u + W].astype(np.uint8)
        flow = np.zeros((H, W, 2), np.float32)
        flow[..., 0], flow[..., 1] = u, v
        Image.fromarray(img1).save(osp.join(data, f"{i:05d}_img1.ppm"))
        Image.fromarray(img2).save(osp.join(data, f"{i:05d}_img2.ppm"))
        frame_utils.write_flo(osp.join(data, f"{i:05d}_flow.flo"), flow)
    split = osp.join(root, "chairs_split.txt")
    with open(split, "w") as f:
        f.write("1\n" * (n - n_val) + "2\n" * n_val)
    return osp.join(root, "datasets"), split


def _read_events(tdir):
    import glob

    events = []
    for path in sorted(glob.glob(osp.join(tdir, "*.jsonl"))):
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    events.append(json.loads(line))
                except ValueError:
                    continue
    return events


def _counts(events):
    counts = {}
    for ev in events:
        name = ev.get("event")
        counts[name] = counts.get(name, 0) + 1
    return counts


def main(argv=None) -> int:
    args = parse_args(argv)

    root = args.keep or tempfile.mkdtemp(prefix="curriculum-smoke-")
    os.makedirs(root, exist_ok=True)

    env_backup = {k: os.environ.get(k)
                  for k in ("RAFT_TELEMETRY_DIR", "RAFT_TELEMETRY_HBM",
                            "RAFT_TELEMETRY_COST", "RAFT_CHAOS_SPEC")}
    # hbm + cost share one extra startup lower().compile() per train()
    # — this smoke re-enters the loop ~6 times, skip it.
    os.environ["RAFT_TELEMETRY_HBM"] = "0"
    os.environ["RAFT_TELEMETRY_COST"] = "0"
    os.environ.pop("RAFT_CHAOS_SPEC", None)  # plans installed directly

    from raft_tpu import chaos
    from raft_tpu.obs.events import reset_default_sink
    from raft_tpu.utils.profiling import enable_persistent_compile_cache

    # Six train invocations share one step program.  On TPU/GPU the
    # persistent cache dedupes their compiles; on the CPU test backend
    # the call is a guarded no-op (cached XLA:CPU executables abort on
    # deserialization — see enable_persistent_compile_cache).
    enable_persistent_compile_cache()

    # steps per stage (even: val_freq=2 saves land on even steps; the
    # preempt below fires at the boundary where the last completed step
    # is the odd `steps - 1` — UNSAVED, forcing the emergency
    # checkpoint that torn_ckpt then tears).
    steps = 4 if args.tiny else 6
    crop = (32, 48) if args.tiny else (48, 64)
    detail = {}
    try:
        import jax

        from raft_tpu.curriculum import (LEDGER_FILE, Manifest, StageLedger,
                                         StageSpec, run_curriculum)

        data_root, split = build_chairs(root, hw=(64, 96))
        # --tiny shrinks the model knobs to the floor (1 refinement
        # iteration, 1 corr level/radius): the smoke asserts ledger /
        # chaos / recovery semantics, which never look inside the
        # update operator — the extra compile time bought nothing.
        it = 1 if args.tiny else 2
        cl = 1 if args.tiny else 2
        manifest = Manifest(base={
            "small": True, "iters": it, "scan_unroll": 1,
            "corr_levels": cl, "corr_radius": cl, "precision": "fp32",
            "image_size": list(crop), "num_steps": steps, "val_freq": 2,
            "batch_per_chip": 1, "num_workers": 1, "device_prefetch": 2,
            "data_root": data_root, "chairs_split": split, "seed": 11,
        }, stages=[StageSpec("s1", "chairs", {}),
                   StageSpec("s2", "chairs", {})])

        def run_phase(workdir, tdir, plan, expect_exit):
            os.makedirs(tdir, exist_ok=True)
            os.environ["RAFT_TELEMETRY_DIR"] = tdir
            reset_default_sink()
            if plan is not None:
                chaos.install(chaos.FaultPlan.parse(plan, seed=args.seed))
            else:
                chaos.uninstall()
            code = None
            try:
                run_curriculum(manifest, workdir,
                               extra_argv=["--telemetry_dir", tdir])
            except SystemExit as e:
                code = e.code
            assert code == expect_exit, \
                f"plan {plan!r}: exited {code}, expected {expect_exit}"

        def ledger(workdir):
            led = StageLedger(osp.join(workdir, LEDGER_FILE))
            led.load()
            return led

        # ---- run A: uninterrupted reference -------------------------
        if args.tiny:
            # The uninterrupted ledger is deterministic; its known
            # value stands in for executing run A (full mode runs it).
            ref = {"status": "complete",
                   "stages": {s.name: {"status": "complete",
                                       "final_step": steps}
                              for s in manifest.stages}}
        else:
            wa, ta = osp.join(root, "run_a"), osp.join(root,
                                                       "telemetry_a")
            run_phase(wa, ta, plan=None, expect_exit=None)
            ref = ledger(wa).normalized()
            assert ref["status"] == "complete", ref
            assert all(s["final_step"] == steps
                       for s in ref["stages"].values()), ref
            ca = _counts(_read_events(ta))
            assert ca.get("chaos_inject", 0) == 0, ca
            assert ca.get("ckpt_fallback", 0) == 0, ca
        detail["reference"] = ref

        # ---- run B: killed mid-stage, killed at the boundary, resumed
        wb, tb = osp.join(root, "run_b"), osp.join(root, "telemetry_b")
        # phase 1: SIGTERM mid-stage 1 + torn emergency checkpoint (the
        # preempt seam's step context is the last COMPLETED step).
        run_phase(wb, tb, plan=f"preempt@step={steps - 1};"
                               f"torn_ckpt@step={steps - 1}",
                  expect_exit=143)
        assert ledger(wb).normalized()["stages"]["s1"]["status"] \
            == "running"
        # phase 2: resume past the torn step, die at the stage boundary.
        run_phase(wb, tb, plan="stage_kill@step=1", expect_exit=143)
        mid = ledger(wb).normalized()
        assert mid["stages"]["s1"] == {"status": "complete",
                                       "final_step": steps}, mid
        assert mid["stages"]["s2"]["status"] == "pending", mid
        # phase 3: resume to completion.
        run_phase(wb, tb, plan=None, expect_exit=None)

        led_b = ledger(wb)
        assert led_b.normalized() == ref, \
            f"resumed ledger diverged:\n{led_b.normalized()}\nvs\n{ref}"
        assert led_b.stage("s1")["runs"] == 2, led_b.stage("s1")
        assert led_b.stage("s2")["runs"] == 1, led_b.stage("s2")
        detail["converged"] = led_b.normalized()

        # ---- telemetry contract -------------------------------------
        ev_b = _read_events(tb)
        cb = _counts(ev_b)
        # preempt + torn_ckpt (phase 1) + stage_kill (phase 2).
        assert cb.get("chaos_inject", 0) == 3, cb
        # exactly one fallback: the phase-2 resume walking past torn
        # step `steps - 1` to the last val_freq save.
        assert cb.get("ckpt_fallback", 0) == 1, cb
        # background commits: steps 2 and `steps` per completed stage
        # pass (phase 1 commits only step 2; the torn emergency save is
        # synchronous), every one probed ok.
        commits = [ev for ev in ev_b if ev.get("event") == "ckpt_commit"]
        assert len(commits) == 4, [c.get("step") for c in commits]
        assert all(c.get("ok") for c in commits), commits
        assert all(c.get("commit_latency_s", -1) >= 0 for c in commits)
        detail["events"] = {k: cb.get(k, 0)
                            for k in ("chaos_inject", "ckpt_fallback",
                                      "ckpt_commit", "curriculum_stage")}

        # ---- verify-ckpt sees the torn step + the topology stamp ----
        from raft_tpu.cli import verify_ckpt

        buf = io.StringIO()
        with redirect_stdout(buf):
            rc = verify_ckpt.main(
                [osp.join(wb, "checkpoints", "s1"), "--json"])
        report = json.loads(buf.getvalue())
        assert rc == 1, (rc, report)  # torn step present, but resumable
        by_step = {r["step"]: r for r in report["steps"]}
        assert not by_step[steps - 1]["ok"], report
        assert report["latest_valid"] == steps, report
        assert by_step[steps]["topology"]["mesh"] \
            == {"data": jax.device_count(), "spatial": 1}, report
        detail["verify_ckpt"] = {"latest_valid": report["latest_valid"],
                                 "topology": by_step[steps]["topology"]}
        ok = True
    except AssertionError as e:
        print(f"curriculum_smoke FAILED: {e}", file=sys.stderr, flush=True)
        ok = False
    finally:
        chaos.uninstall()
        for k, v in env_backup.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        reset_default_sink()
        if args.keep is None:
            shutil.rmtree(root, ignore_errors=True)

    print(json.dumps({
        "metric": "curriculum_smoke",
        "value": 1.0 if ok else 0.0,
        "unit": "pass",
        "vs_baseline": 0.0,
        "config": dict(detail, tiny=bool(args.tiny),
                       steps_per_stage=steps, image_size=list(crop)),
    }))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
