"""Measure the usable device-memory limit -> HBM_LIMIT.json.

The beyond-HBM "fits" verdicts (scripts/shard_beyond_hbm.py,
scripts/bench_beyond_hbm.py) rested on the v5e 16 GB spec constant;
this records the limit the allocator will actually grant (VERDICT r4
weak #4).  Run on the TPU; the artifact is then consumed by both
scripts in place of the constant.

Usage: python scripts/hbm_limit.py [--out HBM_LIMIT.json]
"""

from __future__ import annotations

import argparse
import json
import os.path as osp
import sys

sys.path.insert(0, osp.dirname(osp.dirname(osp.abspath(__file__))))


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="HBM_LIMIT.json")
    args = ap.parse_args(argv)

    import jax

    from raft_tpu.utils.profiling import measure_hbm_limit

    res = measure_hbm_limit()
    res["device_kind"] = jax.local_devices()[0].device_kind
    with open(args.out, "w") as f:
        json.dump(res, f, indent=2)
    print(json.dumps(res, indent=2))


if __name__ == "__main__":
    main()
