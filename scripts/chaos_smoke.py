"""Chaos smoke: prove the self-healing paths actually heal (tier-1).

Runs, on CPU with a tiny model and synthetic in-memory data, the three
recovery paths docs/ROBUSTNESS.md promises — under a canned
deterministic :class:`raft_tpu.chaos.FaultPlan` — and asserts each run
COMPLETES with exactly the expected telemetry:

1. **Train under a corrupt sample** (``corrupt_image``): the loader
   quarantines the poisoned read (one ``sample_quarantine`` event, one
   deterministic replacement draw) and training reaches the target step
   with batch shapes unchanged.
2. **Resume past a torn checkpoint** (``torn_ckpt``): the newest saved
   step is torn post-commit; a second ``train()`` walks the fallback
   chain (one ``ckpt_fallback`` event), restores the newest VALID step,
   and trains on to the new target.
3. **Serve through a transient device error** (``device_err``): the
   first device batch fails with a retryable error; the engine
   re-dispatches once (one ``serve_retry`` event) and every co-batched
   request still succeeds.

Finally the telemetry log is folded through
``scripts/telemetry_summary.py`` to assert the run's
``quarantined_total`` / ``ckpt_fallback_total`` reach the
``check_regression.py`` gate fields.

Prints one bench.py-format JSON line (``metric: chaos_smoke``,
``value`` 1.0 = all scenarios healed); exit 0/1.

::

    python scripts/chaos_smoke.py --tiny     # the tier-1 CPU smoke
    python scripts/chaos_smoke.py            # same flow, bigger shapes
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def parse_args(argv=None):
    p = argparse.ArgumentParser(description="fault-injection smoke test")
    p.add_argument("--tiny", action="store_true",
                   help="smallest shapes/steps (the tier-1 CPU smoke)")
    p.add_argument("--seed", type=int, default=0,
                   help="chaos plan seed (the plan here is fully "
                        "deterministic; the seed only matters for "
                        "p= rules)")
    p.add_argument("--keep", default=None, metavar="DIR",
                   help="keep artifacts (telemetry + checkpoints) "
                        "under DIR instead of a deleted temp dir")
    return p.parse_args(argv)


def _make_dataset(n, hw):
    import numpy as np

    from raft_tpu.data.datasets import FlowDataset

    class SynthDataset(FlowDataset):
        def __init__(self):
            super().__init__()
            self.split = "synthetic"
            self.image_list = [(f"synth://{i}/a", f"synth://{i}/b")
                               for i in range(n)]

        def load(self, index, rng=None):
            # The chaos seam lives in FlowDataset.load; replicate the
            # injection check here since we synthesize instead of read.
            from raft_tpu import chaos
            from raft_tpu.data.datasets import SampleReadError

            ds, index = self._sample_parts(index)
            index = index % len(ds.image_list)
            if chaos.should_inject("corrupt_image",
                                   point="data.sample_read"):
                raise SampleReadError(ds.image_list[index][0], ds, index,
                                      "chaos-injected corrupt sample")
            H, W = hw
            r = np.random.default_rng(index)
            img1 = r.uniform(0, 255, (H, W, 3)).astype(np.float32)
            img2 = np.roll(img1, 1, axis=1)
            flow = np.zeros((H, W, 2), np.float32)
            flow[..., 0] = 1.0
            return {"image1": img1, "image2": img2, "flow": flow,
                    "valid": np.ones((H, W), np.float32)}

    return SynthDataset()


def _count_events(tdir):
    import glob

    counts = {}
    for path in sorted(glob.glob(os.path.join(tdir, "*.jsonl"))):
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    ev = json.loads(line).get("event")
                except ValueError:
                    continue
                counts[ev] = counts.get(ev, 0) + 1
    return counts


def main(argv=None) -> int:
    args = parse_args(argv)

    root = args.keep or tempfile.mkdtemp(prefix="chaos-smoke-")
    tdir = os.path.join(root, "telemetry")
    ckpt_root = os.path.join(root, "checkpoints")
    os.makedirs(tdir, exist_ok=True)

    env_backup = {k: os.environ.get(k)
                  for k in ("RAFT_TELEMETRY_DIR", "RAFT_TELEMETRY_HBM",
                            "RAFT_TELEMETRY_COST")}
    os.environ["RAFT_TELEMETRY_DIR"] = tdir
    # hbm + cost share one extra startup lower().compile() — skip it
    os.environ["RAFT_TELEMETRY_HBM"] = "0"
    os.environ["RAFT_TELEMETRY_COST"] = "0"

    from raft_tpu import chaos
    from raft_tpu.obs.events import reset_default_sink

    reset_default_sink()

    hw = (32, 48) if args.tiny else (48, 64)
    steps1, steps2 = (4, 6) if args.tiny else (6, 9)
    cfg_detail = {}
    try:
        import jax
        import numpy as np

        from raft_tpu.config import RAFTConfig, TrainConfig
        from raft_tpu.data.datasets import ShardedLoader
        from raft_tpu.models.raft import RAFT
        from raft_tpu.obs.events import EventSink
        from raft_tpu.serve import InferenceEngine, ServeConfig
        from raft_tpu.train.loop import train

        model_cfg = RAFTConfig.small_model(corr_levels=2, corr_radius=2,
                                           scan_unroll=1)

        # ---- scenario 1+2: train under corrupt sample + torn newest
        # checkpoint, then resume through the fallback chain ----------
        # num_workers=1 keeps the call-ordinal trigger deterministic
        # (docs/ROBUSTNESS.md determinism caveat).
        chaos.install(chaos.FaultPlan.parse(
            f"corrupt_image@call=3;torn_ckpt@step={steps1}",
            seed=args.seed))
        # The batch shards over the data mesh, so it must divide the
        # device count (8 virtual CPU devices under the test harness,
        # 1 standalone).
        bs = max(2, jax.device_count())
        n_samples = 4 * bs

        def make_cfg(num_steps, val_freq):
            return TrainConfig(
                name="chaos-smoke", num_steps=num_steps, batch_size=bs,
                image_size=hw, iters=2, val_freq=val_freq, log_freq=2,
                seed=11, ckpt_dir=ckpt_root, device_prefetch=2)

        # sample_retries=0: a one-shot injected corruption would
        # otherwise be healed by the same-file retry (the rule is
        # exhausted by the time the retry re-reads) — this smoke wants
        # to see the QUARANTINE path, not the retry path.
        loader = ShardedLoader(_make_dataset(n_samples, hw),
                               batch_size=bs, seed=7, num_workers=1,
                               sample_retries=0)
        state = train(model_cfg, make_cfg(steps1, val_freq=2),
                      loader=loader, telemetry_dir=tdir)
        assert int(state.step) == steps1, \
            f"train under chaos stopped at {int(state.step)} != {steps1}"
        assert loader.quarantined_total == 1, \
            f"expected exactly 1 quarantine, got " \
            f"{loader.quarantined_total}"

        # Resume: newest step is torn; fallback must restore an older
        # one and still reach the new target.  val_freq=3 keeps the
        # resumed run's saves off the torn step number.
        loader2 = ShardedLoader(_make_dataset(n_samples, hw),
                                batch_size=bs, seed=7, num_workers=1)
        state2 = train(model_cfg, make_cfg(steps2, val_freq=3),
                       loader=loader2, telemetry_dir=tdir)
        assert int(state2.step) == steps2, \
            f"resume stopped at {int(state2.step)} != {steps2}"
        cfg_detail["train_final_step"] = int(state2.step)

        # ---- scenario 3: serve retries one transient device error ----
        chaos.install(chaos.FaultPlan.parse("device_err@batch=1",
                                            seed=args.seed))
        rng = jax.random.PRNGKey(0)
        img = jax.numpy.zeros((1,) + hw + (3,))
        variables = RAFT(model_cfg).init({"params": rng, "dropout": rng},
                                         img, img, iters=1)
        sink = EventSink(tdir)
        eng = InferenceEngine(
            variables, model_cfg,
            ServeConfig(iters=2, max_batch=2, batch_sizes=(2,),
                        max_wait_ms=20, device_retries=1,
                        retry_backoff_s=0.01),
            sink=sink)
        eng.start()
        try:
            r = np.random.default_rng(3)
            ims = [r.uniform(0, 255, hw + (3,)).astype(np.float32)
                   for _ in range(4)]
            futs = [eng.submit(ims[0], ims[1]),
                    eng.submit(ims[2], ims[3])]
            flows = [f.result(timeout=600) for f in futs]
            for flow in flows:
                assert flow.shape == hw + (2,), flow.shape
            stats = eng.stats()
            assert stats["retries"] == 1, stats
            assert stats["completed"] == 2, stats
            cfg_detail["serve_retries"] = stats["retries"]
        finally:
            eng.stop()
            sink.close()

        # ---- telemetry contract ----
        counts = _count_events(tdir)
        expected = {"sample_quarantine": 1, "ckpt_fallback": 1,
                    "serve_retry": 1, "chaos_inject": 3}
        for ev, want in expected.items():
            got = counts.get(ev, 0)
            assert got == want, \
                f"event {ev}: expected {want}, got {got} ({counts})"
        cfg_detail["events"] = {k: counts.get(k, 0) for k in expected}

        # The gate fields reach the bench series: fold the log through
        # telemetry_summary and check the check_regression inputs.
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "telemetry_summary",
            os.path.join(REPO, "scripts", "telemetry_summary.py"))
        ts = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(ts)
        summary = ts.summarize(*ts.last_run(ts.iter_records(tdir)),
                               skip=0)
        assert summary["config"]["quarantined_total"] == 1, summary
        assert summary["config"]["ckpt_fallback_total"] == 1, summary
        cfg_detail["summary_gates"] = {
            "quarantined_total": summary["config"]["quarantined_total"],
            "ckpt_fallback_total":
                summary["config"]["ckpt_fallback_total"],
        }
        ok = True
    except AssertionError as e:
        print(f"chaos_smoke FAILED: {e}", file=sys.stderr, flush=True)
        ok = False
    finally:
        chaos.uninstall()
        for k, v in env_backup.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        reset_default_sink()
        if args.keep is None:
            shutil.rmtree(root, ignore_errors=True)

    print(json.dumps({
        "metric": "chaos_smoke",
        "value": 1.0 if ok else 0.0,
        "unit": "pass",
        "vs_baseline": 0.0,
        "config": dict(cfg_detail, tiny=bool(args.tiny),
                       image_size=list(hw)),
    }))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
