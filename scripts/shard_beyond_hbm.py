"""Spatially-sharded beyond-HBM TRAINING, demonstrated virtually.

VERDICT r3 ask #2: real multi-chip hardware is unavailable in this
container, so prove the §5 long-context story end-to-end on the virtual
CPU mesh:

1. At a REAL beyond-HBM shape (1088x1920, the round-3 single-chip
   blocker), AOT-compile the FULL training step and read XLA's buffer
   assignment (``memory_analysis``): the single-device peak exceeds the
   16 GB v5e HBM budget, while the ``--shard_spatial`` form's
   *per-device* peak fits — GSPMD splits the activations, the on-demand
   correlation query rows, and the conv halos across the ``spatial``
   mesh axis, which is exactly how a pod trains frames one chip cannot
   hold.  (Compile-only: one host CPU core cannot execute a 1088x1920
   step in reasonable time; the buffer assignment is the same object
   the TPU runtime allocates.)
2. EXECUTE one spatially-sharded training step at a scaled shape with
   the identical mesh/sharding config and assert a finite loss
   (sharded == unsharded numerics are pinned separately by
   tests/test_spatial_shard.py).

The on-demand path here is ``corr_impl='chunked'`` (the XLA blockwise
lookup, SURVEY C5) because the Pallas kernels would run in interpret
mode on a CPU mesh; the sharding partition — query rows over
``spatial`` — is identical for ``'pallas'``, whose single-chip beyond-
HBM training is certified on hardware in BENCH_BEYOND_HBM_r04.json.

Reference analog: ``--alternate_corr`` + DataParallel
(/root/reference/README.md:75-80, train.py:138) — which could shard
batch but never the frame; spatial sharding is the TPU-native extension
that actually covers beyond-HBM frames.

Usage: python scripts/shard_beyond_hbm.py [--out SHARD_BEYOND_HBM.json]
"""

from __future__ import annotations

import argparse
import json
import os
import os.path as osp
import sys
import time

sys.path.insert(0, osp.dirname(osp.dirname(osp.abspath(__file__))))

V5E_HBM_GB = 16.0   # spec fallback when no measured artifact exists


def _hbm_limit():
    """Measured limit from HBM_LIMIT.json (scripts/hbm_limit.py, run on
    the TPU) when available; the v5e spec constant otherwise (the spec
    overstates headroom by the runtime's own reservation, VERDICT r4
    weak #4).  Shared validation lives in profiling.load_hbm_limit."""
    from raft_tpu.utils.profiling import load_hbm_limit

    limit, src = load_hbm_limit(default_gb=V5E_HBM_GB)
    if src.startswith("no "):
        src = "v5e spec constant (" + src + ")"
    return limit, src


def _setup_cpu_mesh(n_devices: int) -> None:
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n_devices}"
        ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")


def _make_step(H, W, num_spatial, iters, corr_impl="chunked"):
    import jax

    from raft_tpu.config import RAFTConfig, TrainConfig
    from raft_tpu.models.raft import RAFT
    from raft_tpu.parallel.mesh import make_mesh, shard_batch
    from raft_tpu.train.optim import make_optimizer
    from raft_tpu.train.step import init_state, make_train_step

    mesh = make_mesh(num_data=1, num_spatial=num_spatial,
                     devices=jax.devices()[:num_spatial])
    # scan_unroll=1: at beyond-HBM shapes each iteration is O(100ms+) of
    # device work, so unroll buys nothing and the 12x graph is brutal to
    # compile (it crashed the TPU remote compile helper at 1440x2560).
    model_cfg = RAFTConfig.full(compute_dtype="bfloat16",
                                corr_impl=corr_impl, remat=True,
                                remat_policy="save_corr", scan_unroll=1)
    cfg = TrainConfig(num_steps=1000, batch_size=1, image_size=(H, W),
                     iters=iters)
    model = RAFT(model_cfg)
    tx = make_optimizer(cfg.lr, cfg.num_steps, cfg.wdecay, cfg.epsilon,
                        cfg.clip)
    state = init_state(model, tx, jax.random.PRNGKey(0), (48, 64))
    spatial = num_spatial > 1
    step_fn = make_train_step(model, tx, cfg, mesh, donate=False,
                              shard_spatial=spatial)

    def batch_for(rng):
        import numpy as np

        return shard_batch({
            "image1": rng.uniform(0, 255, (1, H, W, 3)).astype(np.float32),
            "image2": rng.uniform(0, 255, (1, H, W, 3)).astype(np.float32),
            "flow": (8 * rng.standard_normal((1, H, W, 2))).astype(
                np.float32),
            "valid": np.ones((1, H, W), np.float32),
        }, mesh, spatial=spatial)

    return step_fn, state, batch_for


def analyze(H, W, num_spatial, iters=12):
    """Per-device HBM peak of the compiled training step (no execution)."""
    import jax
    import numpy as np

    from raft_tpu.utils.profiling import hbm_usage

    step_fn, state, batch_for = _make_step(H, W, num_spatial, iters)
    batch = batch_for(np.random.default_rng(0))
    key = jax.random.PRNGKey(1)
    t0 = time.perf_counter()
    usage = hbm_usage(step_fn, state, batch, key)
    usage.update({
        "shape": f"{H}x{W}", "num_spatial": num_spatial, "iters": iters,
        "compile_s": round(time.perf_counter() - t0, 1),
    })
    if "temp_gb" in usage:
        # The CPU backend's peak_memory_in_bytes is not populated the way
        # the TPU one is (reports ~0.2 GB against a 12 GB temp), so the
        # per-device footprint here is args + outputs + temps — on the
        # real chip (BENCH_BEYOND_HBM_r04.json) peak tracks that sum to
        # within ~1%.
        usage["footprint_gb"] = round(
            usage["args_gb"] + usage["output_gb"] + usage["temp_gb"], 3)
        limit, _src = _hbm_limit()
        usage["fits_hbm_limit"] = bool(usage["footprint_gb"] < limit)
        usage["headroom_pct"] = round(
            100.0 * (1.0 - usage["footprint_gb"] / limit), 1)
    return usage


def run_scaled(H, W, num_spatial, iters=4):
    """Actually execute one sharded step at a scaled shape."""
    import numpy as np

    import jax

    step_fn, state, batch_for = _make_step(H, W, num_spatial, iters)
    batch = batch_for(np.random.default_rng(0))
    t0 = time.perf_counter()
    state, metrics = step_fn(state, batch, jax.random.PRNGKey(1))
    loss = float(metrics["loss"])
    return {
        "shape": f"{H}x{W}", "num_spatial": num_spatial, "iters": iters,
        "executed": True, "loss": round(loss, 4),
        "loss_finite": bool(np.isfinite(loss)),
        "wall_s": round(time.perf_counter() - t0, 1),
    }


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="SHARD_BEYOND_HBM.json")
    ap.add_argument("--spatial", type=int, default=4)
    args = ap.parse_args(argv)
    _setup_cpu_mesh(max(args.spatial, 16))  # spatial8/16 cases need 16

    limit, src = _hbm_limit()
    results = {"hbm_limit_gb": limit, "hbm_limit_source": src}
    for name, fn in [
        # 1088x1920 is single-chip-trainable when configured well (the
        # r04 TPU run: 12.7 GB peak with corr_impl='pallas', unroll 1);
        # sharding still cuts the footprint ~4x.  2176x3840 (4K-class)
        # is the shape NO single v5e chip can train — and spatial=4
        # brings it back under the 16 GB budget.
        ("single_device_1088x1920",
         lambda: analyze(1088, 1920, num_spatial=1)),
        (f"spatial{args.spatial}_1088x1920",
         lambda: analyze(1088, 1920, num_spatial=args.spatial)),
        ("single_device_1440x2560",
         lambda: analyze(1440, 2560, num_spatial=1)),
        (f"spatial{args.spatial}_1440x2560",
         lambda: analyze(1440, 2560, num_spatial=args.spatial)),
        ("single_device_2176x3840",
         lambda: analyze(2176, 3840, num_spatial=1)),
        (f"spatial{args.spatial}_2176x3840",
         lambda: analyze(2176, 3840, num_spatial=args.spatial)),
        ("spatial8_2176x3840",
         lambda: analyze(2176, 3840, num_spatial=8)),
        # spatial=16: the 4K datapoint with real headroom even against a
        # conservatively-measured limit (VERDICT r4 weak #4 asked for
        # >=10% headroom at spatial=8 OR this datapoint).
        ("spatial16_2176x3840",
         lambda: analyze(2176, 3840, num_spatial=16)),
        ("executed_spatial2_272x480",
         lambda: run_scaled(272, 480, num_spatial=2)),
    ]:
        try:
            results[name] = fn()
        except Exception as e:  # record honestly
            results[name] = {"error": f"{type(e).__name__}: {str(e)[:300]}"}
        print(name, "->", json.dumps(results[name]), flush=True)

    with open(args.out, "w") as f:
        json.dump(results, f, indent=2)
    print(f"-> {args.out}", flush=True)


if __name__ == "__main__":
    main()
