#!/bin/bash
# Full curriculum chairs -> things -> sintel -> kitti
# (reference train_standard.sh:3-6 hyperparameters; checkpoints are orbax
# directories and --restore_ckpt seeds weights only, so each stage starts
# its own LR schedule exactly like the reference's weights-only .pth loads).
set -e
mkdir -p checkpoints
python -u -m raft_tpu.cli.train --name raft-chairs --stage chairs --validation chairs --num_steps 100000 --batch_size 10 --lr 0.0004 --image_size 368 496 --wdecay 0.0001
python -u -m raft_tpu.cli.train --name raft-things --stage things --validation sintel --restore_ckpt checkpoints/raft-chairs --num_steps 100000 --batch_size 6 --lr 0.000125 --image_size 400 720 --wdecay 0.0001
python -u -m raft_tpu.cli.train --name raft-sintel --stage sintel --validation sintel --restore_ckpt checkpoints/raft-things --num_steps 100000 --batch_size 6 --lr 0.000125 --image_size 368 768 --wdecay 0.00001 --gamma 0.85
python -u -m raft_tpu.cli.train --name raft-kitti --stage kitti --validation kitti --restore_ckpt checkpoints/raft-sintel --num_steps 50000 --batch_size 6 --lr 0.0001 --image_size 288 960 --wdecay 0.00001 --gamma 0.85
