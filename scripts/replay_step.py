"""Re-run a forensic bundle's exact training step offline.

When the in-graph non-finite guard skips an update, the training loop
writes a bundle (``telemetry_dir/forensics/stepNNNNNNNN.npz``: the
post-noise host batch + step + RNG seed + per-step metrics + the model
and train config dicts — ``raft_tpu/obs/health.py``).  This script
replays that step — same batch, same ``fold_in(PRNGKey(seed), step)``
dropout key, same loss path (``raft_tpu.train.step.make_loss_fn``, the
function the train step differentiates) — against a checkpoint, and
reports where the numerics blew up::

    python scripts/replay_step.py --bundle runs/t/forensics/step00000042.npz \
        --ckpt checkpoints/raft-chairs
    # {"reproduced": true, "loss": Infinity, "nonfinite_grad_leaves":
    #  {"fnet/conv1/kernel": 123, ...}, ...}

``--ckpt`` should hold the run's checkpoint at (or before) the
offending step — with the guard on, params at the flagged step are
bit-identical to the last checkpoint plus the intervening *finite*
updates, so the latest pre-blow-up checkpoint usually reproduces; a
fresh init (``--random-init``) only answers "is the batch itself
poisoned" (inf/NaN pixels, absurd flow magnitudes), which the script
checks first either way.

Exit status: 0 normally; with ``--expect-nonfinite`` (the e2e test /
incident-runbook mode), non-zero when the replay comes out finite.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def parse_args(argv=None):
    p = argparse.ArgumentParser(
        description="replay a non-finite-step forensic bundle")
    p.add_argument("--bundle", required=True,
                   help="forensics .npz written by the train loop")
    p.add_argument("--ckpt", default=None,
                   help="orbax checkpoint dir of the run "
                        "(ckpt_dir/name); latest step is restored")
    p.add_argument("--random-init", action="store_true",
                   help="replay from a fresh init instead of a "
                        "checkpoint (batch-poisoning check only)")
    p.add_argument("--expect-nonfinite", action="store_true",
                   help="exit non-zero if the replay does NOT "
                        "reproduce a non-finite loss/grad")
    return p.parse_args(argv)


def _tuplify(cfg_dict, keys):
    out = dict(cfg_dict)
    for k in keys:
        if k in out and isinstance(out[k], list):
            out[k] = tuple(out[k])
    return out


def _leaf_name(path) -> str:
    import jax

    return "/".join(
        str(getattr(k, "key", getattr(k, "idx", k))) for k in path)


def replay(bundle_path: str, ckpt: str = None, random_init: bool = False):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from raft_tpu.config import RAFTConfig, TrainConfig
    from raft_tpu.models.raft import RAFT
    from raft_tpu.obs.health import load_forensic_bundle
    from raft_tpu.train.optim import make_optimizer
    from raft_tpu.train.step import init_state, make_loss_fn

    batch, meta = load_forensic_bundle(bundle_path)
    if batch is None:
        raise SystemExit(
            f"{bundle_path}: bundle has no batch arrays (the host batch "
            "was already evicted from the forensics ring when the flag "
            "was observed) — lower log_freq or raise forensic_keep on "
            "the next run to capture it")
    step = int(meta["step"])
    seed = int(meta.get("seed", 0))
    model_cfg = RAFTConfig(**meta["model_cfg"])
    cfg = TrainConfig(**_tuplify(meta["train_cfg"],
                                 ("image_size", "validation")))

    # Batch-level poisoning first: no model needed to spot an inf pixel.
    batch_nonfinite = {
        k: int(np.size(v) - np.isfinite(v).sum()) for k, v in batch.items()
    }

    model = RAFT(model_cfg)
    tx = make_optimizer(cfg.lr, cfg.num_steps, cfg.wdecay, cfg.epsilon,
                        cfg.clip)
    template = init_state(model, tx, jax.random.PRNGKey(cfg.seed),
                          (48, 64))
    restored_step = None
    if ckpt and not random_init:
        from raft_tpu.train.checkpoint import CheckpointManager

        state = CheckpointManager(ckpt).restore_latest(template)
        if state is None:
            raise SystemExit(f"no checkpoint under {ckpt!r}")
        restored_step = int(state.step)
    else:
        state = template

    # The train step's RNG: fold_in(PRNGKey(cfg.seed), step) — the loop
    # passes PRNGKey(seed) and folds the state's step in-graph.
    rng = jax.random.fold_in(jax.random.PRNGKey(seed), step)
    loss_fn = make_loss_fn(model, cfg)
    grad_fn = jax.jit(jax.value_and_grad(loss_fn, has_aux=True))
    jbatch = {k: jnp.asarray(v) for k, v in batch.items()}
    (loss, (metrics, _)), grads = grad_fn(state.params, state.batch_stats,
                                          jbatch, rng)

    loss = float(loss)
    bad_leaves = {}
    for path, leaf in jax.tree_util.tree_leaves_with_path(grads):
        n = int(leaf.size - jnp.isfinite(leaf).sum())
        if n:
            bad_leaves[_leaf_name(path)] = n
    reproduced = (not np.isfinite(loss)) or bool(bad_leaves)
    return {
        "bundle": bundle_path,
        "step": step,
        "restored_step": restored_step,
        "reproduced": reproduced,
        "loss": loss,
        "batch_nonfinite_elements": batch_nonfinite,
        "nonfinite_grad_leaves": bad_leaves,
        "metrics_at_capture": meta.get("metrics", {}),
    }


def main(argv=None):
    args = parse_args(argv)
    if not args.ckpt and not args.random_init:
        raise SystemExit("pass --ckpt <dir> (or --random-init for the "
                         "batch-poisoning check)")
    report = replay(args.bundle, ckpt=args.ckpt,
                    random_init=args.random_init)
    print(json.dumps(report))
    if args.expect_nonfinite and not report["reproduced"]:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
