"""Multi-host fabric drill: partition -> failover -> heal -> rejoin,
queue pressure -> scale-up, idle -> graceful scale-down (tier-1, CPU).

Brings up a hybrid fleet — one local replica plus one REMOTE replica
(a second engine in this process behind a real loopback HTTP server,
fronted by :class:`raft_tpu.serve.RemoteEngine`) — behind the
health-gated :class:`raft_tpu.serve.FlowRouter`, with signal-driven
elastic autoscaling on, and walks the four promises docs/SERVING.md's
"Multi-host fabric" section makes:

1. **Partition tolerance**: a deterministic ``net_partition`` chaos
   fault (``serve.remote`` seam) makes every wire operation to the
   remote time out.  Every request accepted during the partition still
   resolves (failover to the local replica,
   ``raft_fleet_dropped_total == 0``) and the whole cascade correlates
   into ONE incident (obs/incident.py).
2. **Heal -> rejoin**: when the fault plan's ``heal=`` ordinal passes,
   the supervisor observes the down->up health transition and REJOINS
   the remote — generation bump + breaker reset
   (``fleet_remote_rejoin``), after which bucket-affine traffic routes
   to it again.
3. **Elastic scale-up**: sustained queue pressure past
   ``autoscale_up_queue_frac`` for ``autoscale_up_consecutive`` ticks
   grows the fleet by exactly ONE local replica (hysteresis + cooldown:
   no flapping).
4. **Graceful scale-down**: when the fleet goes idle the autoscaler
   drains the newest local replica — its streaming session is migrated
   to a sibling first (``stream_restart reason=scale_down`` replay),
   in-flight work drains, and the stream continues with monotone frame
   numbering.  Zero dropped requests across the whole drill.

Prints one bench.py-format JSON line (``metric: fabric_smoke``,
``value`` 1.0 = every promise held) whose config carries the
``scale_flaps`` / ``net_retry_rate`` keys the
``check_regression.py --max-scale-flaps / --max-net-retry-rate`` gates
read; exit 0, or an assertion failure.

::

    JAX_PLATFORMS=cpu python scripts/fabric_smoke.py --tiny
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import threading
import time
import zlib

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def parse_args(argv=None):
    p = argparse.ArgumentParser(description="multi-host fabric drill")
    p.add_argument("--tiny", action="store_true",
                   help="smallest shapes/counts (the tier-1 CPU drill)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--keep", default=None, metavar="DIR",
                   help="keep artifacts (AOT dir, telemetry) under DIR "
                        "instead of a temp dir")
    return p.parse_args(argv)


def _wait_for(pred, timeout_s, what):
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        if pred():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out after {timeout_s}s waiting for "
                         f"{what}")


def main(argv=None) -> int:
    args = parse_args(argv)
    # --tiny is the tier-1 CPU profile: the smallest burst/backlog
    # that still drives every phase transition.  The default profile
    # doubles the load for a longer soak on real hosts.
    burst_n = 6 if args.tiny else 12
    press_n = 4 if args.tiny else 8
    backlog_cap = 24 if args.tiny else 48
    workdir = args.keep or tempfile.mkdtemp(prefix="raft-fabric-smoke-")
    os.makedirs(workdir, exist_ok=True)
    os.environ.setdefault("RAFT_TELEMETRY_DIR",
                          os.path.join(workdir, "telemetry"))

    import jax
    import numpy as np

    from raft_tpu import chaos
    from raft_tpu.cli.serve import make_server
    from raft_tpu.config import RAFTConfig
    from raft_tpu.models.raft import RAFT
    from raft_tpu.obs import EventSink
    from raft_tpu.serve import (FleetConfig, FlowRouter, InferenceEngine,
                                QueueFullError, RemoteConfig,
                                ReplicaFleet, RouterConfig, ServeConfig)

    model_cfg = RAFTConfig.small_model()  # fp32: CPU-friendly
    # Bucket (56, 40) is the drill's keystone: crc32 % 2 == 1 routes it
    # to the REMOTE replica (index 1) in the 2-replica fleet, and
    # crc32 % 3 == 2 pins the phase-4 stream to the SCALED-UP replica
    # (index 2) in the 3-replica fleet.
    shape = (52, 36)  # -> bucket (56, 40)
    bucket = (56, 40)
    assert zlib.crc32(repr(bucket).encode()) % 2 == 1
    assert zlib.crc32(repr(bucket).encode()) % 3 == 2
    model_img = jax.numpy.zeros((1,) + bucket + (3,))

    k = jax.random.PRNGKey(args.seed)
    variables = RAFT(model_cfg).init({"params": k, "dropout": k},
                                     model_img, model_img, iters=1)

    # ---- the "other host": a real engine behind a loopback server ----
    # Deliberately heterogeneous: max_queue=8 vs the locals' 32, so the
    # router's spill math must read THIS replica's capacity through the
    # queue_capacity() facade rather than the shared ServeConfig.
    remote_serve_cfg = ServeConfig(
        iters=2, batching="slot", slots=2, max_wait_ms=5, max_queue=8,
        stall_timeout_s=30.0)
    server_engine = InferenceEngine(variables, model_cfg,
                                    remote_serve_cfg)
    server_engine.start()
    server_engine.warmup([shape])
    server = make_server(server_engine, "127.0.0.1", 0)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()

    # ---- the fleet: 1 local + 1 remote, autoscaling 1..2 locals ------
    serve_cfg = ServeConfig(
        iters=2, batching="slot", slots=2, max_wait_ms=5, max_queue=32,
        stall_timeout_s=30.0,
        # ONE incident must span the whole drill: generous correlation
        # window, and a quiet-close threshold longer than the drill.
        incidents=True, incident_window_s=60.0, incident_quiet_s=120.0)
    rcfg = RemoteConfig(connect_timeout_s=0.5, request_timeout_s=30.0,
                        health_timeout_s=0.5, health_cache_s=0.25,
                        max_queue=8, workers=8)
    sink = EventSink.from_env()
    seen: list = []
    sink.add_observer(seen.append)

    def events(name):
        return [r for r in seen if r.get("event") == name]

    fleet = ReplicaFleet(
        variables, model_cfg, serve_cfg,
        FleetConfig(replicas=1, remote=(f"127.0.0.1:{port}",),
                    remote_cfg=rcfg, warmup_shapes=(shape,),
                    restart_backoff_s=0.05, restart_backoff_max_s=0.5,
                    health_poll_s=0.05,
                    autoscale_min=1, autoscale_max=2,
                    autoscale_interval_s=0.2,
                    autoscale_up_queue_frac=0.25,
                    autoscale_down_queue_frac=0.02,
                    autoscale_up_consecutive=2,
                    autoscale_down_consecutive=3,
                    autoscale_cooldown_s=2.0,
                    aot_dir=os.path.join(workdir, "aot")),
        sink=sink)
    fleet.start()
    router = FlowRouter(fleet, RouterConfig(breaker_threshold=2,
                                            breaker_cooldown_s=0.5),
                        sink=sink)
    checks = {}
    rng = np.random.default_rng(args.seed)

    def frame():
        return rng.uniform(0, 255, shape + (3,)).astype(np.float32)

    def autoscale():
        return fleet.stats()["fleet"]["autoscale"]

    try:
        r0, r1 = fleet.replicas
        assert getattr(r1, "is_remote", False)

        # -- 1a. pre-partition: the remote serves affine traffic ------
        for _ in range(3):
            flow = router.infer(frame(), frame(), timeout=120)
            assert flow.shape == shape + (2,)
        rstats = router.router_stats()
        assert rstats["requests_by_replica"].get("r1", 0) >= 1, \
            f"bucket {bucket} never routed to the remote: {rstats}"
        assert r1.queue_capacity() == 8, \
            "heterogeneous remote capacity not visible via the facade"
        gen0 = r1.generation

        # -- 1b. partition: failover, zero drops ----------------------
        heal_at = 16
        chaos.install(chaos.FaultPlan.parse(
            f"net_partition@step=0,heal={heal_at}", seed=args.seed))
        futures = [router.submit(frame(), frame())
                   for _ in range(burst_n)]
        results = [f.result(timeout=120) for f in futures]
        assert all(r.shape == shape + (2,) for r in results), \
            "a request accepted during the partition never resolved"
        rstats = router.router_stats()
        assert rstats["dropped_total"] == 0, rstats
        assert rstats["failovers_total"] >= 1, \
            f"partition fired but no failover recorded: {rstats}"
        assert len(events("net_retry")) >= 1, \
            "no net_retry event emitted during the partition"

        # -- 2. heal -> rejoin (generation-guarded breaker reset) -----
        _wait_for(lambda: r1.generation > gen0, 30,
                  "the healed remote to rejoin the fleet")
        rejoins = events("fleet_remote_rejoin")
        assert rejoins and rejoins[-1]["replica"] == "r1", rejoins
        chaos.uninstall()
        _wait_for(r1.eligible, 10, "the rejoined remote to pass the "
                                   "health gate")
        before = router.router_stats()["requests_by_replica"].get(
            "r1", 0)
        for _ in range(2):
            router.infer(frame(), frame(), timeout=120)
        after = router.router_stats()["requests_by_replica"].get(
            "r1", 0)
        assert after > before, \
            "affine traffic did not return to the healed remote"
        checks["partition"] = {
            "failovers": rstats["failovers_total"],
            "net_retries": len(events("net_retry")),
            "rejoin_generation": r1.generation}

        # -- 3. queue pressure -> exactly one scale-up ----------------
        futures = []
        deadline = time.time() + 20
        while autoscale()["ups"] < 1:
            assert time.time() < deadline, \
                f"no scale-up under sustained load: {autoscale()}"
            for _ in range(press_n):
                try:
                    futures.append(router.submit(frame(), frame()))
                except QueueFullError:
                    time.sleep(0.01)  # shed, not dropped: retry later
            if sum(not f.done() for f in futures) > backlog_cap:
                time.sleep(0.005)
        results = [f.result(timeout=120) for f in futures]
        assert all(r.shape == shape + (2,) for r in results)
        scales = events("fleet_scale")
        assert [e["direction"] for e in scales] == ["up"], scales
        assert len(fleet.replicas) == 3, \
            [r.name for r in fleet.replicas]
        r2 = fleet.replicas[-1]
        assert r2.name == "r2" and r2.state == "ready"
        assert router.router_stats()["dropped_total"] == 0
        checks["scale_up"] = {
            "requests": len(futures),
            "signals": scales[0]["signals"],
            "seconds": scales[0]["seconds"]}

        # -- 4. stream + idle -> graceful scale-down ------------------
        out = router.stream_ingest("cam0", frame(), timeout=120)
        assert out["frame"] == 0 and out["flow"] is None
        assert router._streams["cam0"].replica == "r2", \
            "stream did not open on the scale-up replica"
        out = router.stream_ingest("cam0", frame(), timeout=120)
        assert out["frame"] == 1 and out["flow"] is not None
        _wait_for(lambda: autoscale()["downs"] >= 1, 30,
                  "the idle fleet to scale down")
        scales = events("fleet_scale")
        assert [e["direction"] for e in scales] == ["up", "down"], \
            scales
        down = scales[-1]
        assert down["replica"] == "r2" and down["moved"] == 1, down
        rst = events("stream_restart")
        assert rst and rst[-1]["reason"] == "scale_down", rst
        assert rst[-1]["from_replica"] == "r2", rst
        assert len(fleet.replicas) == 2, \
            [r.name for r in fleet.replicas]
        # The migrated stream keeps going: next frame is the cold pair
        # on the new owner, monotone frame numbering intact.
        out = router.stream_ingest("cam0", frame(), timeout=120)
        assert out["frame"] == 2 and out["flow"] is not None
        summary = router.stream_close("cam0")
        assert summary["restarts"] >= 1, summary
        auto = autoscale()
        assert auto["ups"] == 1 and auto["downs"] == 1, auto
        assert auto["flaps"] <= 1, auto
        checks["scale_down"] = {
            "victim": down["replica"], "streams_moved": down["moved"],
            "stream_restarts": summary["restarts"]}

        # -- fleet-wide invariants ------------------------------------
        rstats = router.router_stats()
        assert rstats["dropped_total"] == 0, rstats
        incidents = fleet.stats()["fleet"]["incidents"]
        assert incidents["opened"] == 1, \
            f"the drill must correlate into ONE incident: {incidents}"
        signals = set((incidents.get("open") or {}).get("signals", ()))
        assert "net_retry" in signals and "stream_restart" in signals, \
            f"fabric signals did not correlate: {sorted(signals)}"
        mt = fleet.metrics_text()
        assert "raft_fleet_scale_events_total" in mt
        assert 'raft_remote_net_errors_total' in mt
        net_retries = len(events("net_retry"))
        requests = rstats["requests_total"]
        ok = True
    finally:
        chaos.uninstall()
        fleet.stop(drain=False)
        server.shutdown()
        server_engine.stop(drain=False)

    net_retry_rate = round(100.0 * net_retries / max(requests, 1), 2)
    print(json.dumps({
        "metric": "fabric_smoke",
        "value": 1.0 if ok else 0.0,
        "unit": "pass",
        "vs_baseline": 0.0,
        "config": {
            **checks,
            "requests": requests,
            "failovers": rstats["failovers_total"],
            "dropped": rstats["dropped_total"],
            "fleet_scale": {"ups": auto["ups"], "downs": auto["downs"],
                            "flaps": auto["flaps"]},
            "scale_flaps": auto["flaps"],
            "net_retry_total": net_retries,
            "net_retry_rate": net_retry_rate,
            "incidents_opened": incidents["opened"],
            "workdir": workdir if args.keep else None},
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
