"""Per-kernel microbench: fused Pallas kernels vs their unfused arms.

Benchmarks the two autotuner-ranked fused kernels in isolation, outside
the full model step:

- ``lookup_encoder`` — ``ops/pallas_corr.pallas_pyramid_lookup_encode``
  (quantized pyramid lookup + motion-encoder convc1 + relu in one
  kernel) vs the stock ``pallas_pyramid_lookup`` followed by the XLA
  1x1 conv, the exact pair ``RAFTConfig.fused_lookup_encoder`` toggles.
- ``gru`` — ``ops/pallas_gru.gru_gate_rh``/``gru_gate_blend`` gate
  chains around the XLA convs vs the all-XLA ConvGRU cell, the pair
  ``RAFTConfig.fused_gru`` toggles.

Both arms of each kernel land in ONE bench.py-format JSON line
(metric / value / unit / vs_baseline); per-kernel timings, speedups and
whether the tuning registry currently SELECTS the fused form on this
device go under ``config.kernels`` — the record
``scripts/check_regression.py --max-kernel-slowdown`` gates on.

``--tiny``: CPU interpret-mode smoke (tiny shapes, 1 rep) wired into
the test tier (tests/test_bench_kernels.py)::

    JAX_PLATFORMS=cpu python scripts/bench_kernels.py --tiny
    python scripts/bench_kernels.py --image 368x496 --batch 8
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

_MODEL_DIMS = {
    # levels, radius, convc1 out features, GRU hidden, GRU x-input dim
    "full": dict(levels=4, radius=4, features=256, hidden=128, xdim=256),
    "small": dict(levels=4, radius=3, features=96, hidden=96, xdim=146),
}


def parse_args(argv=None):
    p = argparse.ArgumentParser(
        description="RAFT-TPU fused-kernel microbenchmark")
    p.add_argument("--image", default="368x496",
                   help="full-res HxW (kernels run at 1/8 resolution)")
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--model", choices=sorted(_MODEL_DIMS), default="full")
    p.add_argument("--corr-dtype", default="float32",
                   choices=["float32", "bfloat16", "int8"],
                   help="pyramid storage dtype for lookup_encoder")
    p.add_argument("--kernels", default="lookup_encoder,gru",
                   help="comma list: lookup_encoder,gru")
    p.add_argument("--reps", type=int, default=20)
    p.add_argument("--warmup", type=int, default=3)
    p.add_argument("--interpret", action="store_true",
                   help="force Pallas interpreter (any backend)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--tiny", action="store_true",
                   help="CPU interpret smoke preset (tiny shape, 1 rep)")
    args = p.parse_args(argv)
    if args.tiny:
        args.image = "64x128"   # 1/8 res 8x16 -> exactly one 128-query block
        args.batch = 1
        args.model = "small"
        args.reps = 1
        args.warmup = 0
        args.interpret = True
    return args


def _time_ms(fn, reps, warmup):
    """Median wall ms of ``fn()`` (jitted; blocks on the result)."""
    import jax

    for _ in range(warmup):
        jax.block_until_ready(fn())
    times = []
    for _ in range(max(reps, 1)):
        t0 = time.perf_counter()
        jax.block_until_ready(fn())
        times.append((time.perf_counter() - t0) * 1e3)
    times.sort()
    return times[len(times) // 2]


def _bench_lookup_encoder(args, h8, w8, dims, interpret):
    import jax
    import jax.numpy as jnp

    from raft_tpu.ops.corr import build_corr_pyramid_flat
    from raft_tpu.ops.pallas_corr import (pallas_pyramid_lookup,
                                          pallas_pyramid_lookup_encode,
                                          pallas_pyramid_lookup_quantized)
    from raft_tpu.ops.sampler import coords_grid

    B, r, L, F = args.batch, dims["radius"], dims["levels"], dims["features"]
    kk = L * (2 * r + 1) ** 2
    keys = jax.random.split(jax.random.PRNGKey(args.seed), 4)
    f1 = jax.random.normal(keys[0], (B, h8, w8, 256), jnp.float32)
    f2 = jax.random.normal(keys[1], (B, h8, w8, 256), jnp.float32)
    pyr = build_corr_pyramid_flat(f1, f2, L, out_dtype=args.corr_dtype)
    coords = coords_grid(B, h8, w8) + jax.random.uniform(
        keys[2], (B, h8, w8, 2), minval=-2.0, maxval=2.0)
    w = jax.random.normal(keys[3], (kk, F), jnp.float32) * kk ** -0.5
    b = jnp.zeros((F,), jnp.float32)
    lookup = (pallas_pyramid_lookup_quantized
              if args.corr_dtype == "int8" else pallas_pyramid_lookup)

    @jax.jit
    def unfused(coords, w, b):
        taps = lookup(pyr, coords, r, interpret=interpret)
        return jax.nn.relu(
            jnp.einsum("bhwk,kf->bhwf", taps, w) + b)

    @jax.jit
    def fused(coords, w, b):
        return pallas_pyramid_lookup_encode(
            pyr, coords, w, b, r, 128, interpret)

    # AOT-compile each arm once: the SAME executable is timed and
    # cost-queried, so flops/bytes come free at compile time
    # (obs/cost.py).  Analytic twin: the kernel's hand-derived formula,
    # what real-TPU custom_call arms fall back to.
    from raft_tpu.obs.cost import analytic_lookup_encode_cost

    unfused_c = unfused.lower(coords, w, b).compile()
    fused_c = fused.lower(coords, w, b).compile()
    level_hw = [(max(h8 >> lv, 1), max(w8 >> lv, 1)) for lv in range(L)]
    analytic = analytic_lookup_encode_cost(
        B, level_hw, h8 * w8, r, F,
        pyramid_bytes=jnp.dtype(args.corr_dtype).itemsize)
    return {
        "unfused_ms": _time_ms(lambda: unfused_c(coords, w, b),
                               args.reps, args.warmup),
        "fused_ms": _time_ms(lambda: fused_c(coords, w, b),
                             args.reps, args.warmup),
    }, (unfused_c, fused_c, analytic)


def _bench_gru(args, h8, w8, dims, interpret):
    import jax
    import jax.numpy as jnp

    from raft_tpu.ops.pallas_gru import gru_gate_blend, gru_gate_rh

    B, hid, xdim = args.batch, dims["hidden"], dims["xdim"]
    cin = hid + xdim
    keys = jax.random.split(jax.random.PRNGKey(args.seed + 1), 6)
    hstate = jnp.tanh(jax.random.normal(keys[0], (B, h8, w8, hid)))
    x = jax.random.normal(keys[1], (B, h8, w8, xdim))
    wzr = jax.random.normal(keys[2], (3, 3, cin, 2 * hid)) * cin ** -0.5
    bzr = jax.random.normal(keys[3], (2 * hid,)) * 0.01
    wq = jax.random.normal(keys[4], (3, 3, cin, hid)) * cin ** -0.5
    bq = jax.random.normal(keys[5], (hid,)) * 0.01

    def _conv(v, w, b):
        return jax.lax.conv_general_dilated(
            v, w, (1, 1), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC")) + b

    @jax.jit
    def unfused(hstate, x):
        zr = jax.nn.sigmoid(_conv(
            jnp.concatenate([hstate, x], -1), wzr, bzr))
        z, rg = jnp.split(zr, 2, axis=-1)
        q = jnp.tanh(_conv(
            jnp.concatenate([rg * hstate, x], -1), wq, bq))
        return (1 - z) * hstate + z * q

    @jax.jit
    def fused(hstate, x):
        zr_raw = _conv(jnp.concatenate([hstate, x], -1), wzr, bzr)
        z_raw, r_raw = jnp.split(zr_raw, 2, axis=-1)
        q_raw = _conv(jnp.concatenate(
            [gru_gate_rh(r_raw, hstate, interpret), x], -1), wq, bq)
        return gru_gate_blend(z_raw, q_raw, hstate, interpret)

    from raft_tpu.obs.cost import analytic_gru_gate_cost

    unfused_c = unfused.lower(hstate, x).compile()
    fused_c = fused.lower(hstate, x).compile()
    # Analytic cost of the two Pallas gate kernels only (the 3x3 convs
    # around them are XLA-counted even on TPU; the custom_call bodies
    # are what XLA can't see there).
    gshape = (B, h8, w8, hid)
    rh = analytic_gru_gate_cost(gshape, "rh")
    blend = analytic_gru_gate_cost(gshape, "blend")
    analytic = (rh[0] + blend[0], rh[1] + blend[1])
    return {
        "unfused_ms": _time_ms(lambda: unfused_c(hstate, x),
                               args.reps, args.warmup),
        "fused_ms": _time_ms(lambda: fused_c(hstate, x),
                             args.reps, args.warmup),
    }, (unfused_c, fused_c, analytic)


_KNOB_BY_KERNEL = {"lookup_encoder": "fused_lookup_encoder",
                   "gru": "fused_gru"}


def _registry_selected(kernel, hw, batch):
    """(selected?, kind) — does any registry entry for this device pick
    the fused form of ``kernel`` at this bucket/batch?"""
    from raft_tpu import tuning

    knob = _KNOB_BY_KERNEL[kernel]
    for kind in ("train", "eval", "serve"):
        hit = tuning.lookup(kind, hw, batch)
        if hit and hit[1].get("knobs", {}).get(knob):
            return True, kind
    return False, None


def main(argv=None):
    args = parse_args(argv)

    from raft_tpu import tuning
    from raft_tpu.obs import cost as cost_mod

    h, w = (int(x) for x in args.image.lower().split("x"))
    h8, w8 = h // 8, w // 8
    dims = _MODEL_DIMS[args.model]
    interpret = True if args.interpret else None

    bench_fns = {"lookup_encoder": _bench_lookup_encoder,
                 "gru": _bench_gru}
    kernels = {}
    for name in args.kernels.split(","):
        name = name.strip()
        if not name:
            continue
        if name not in bench_fns:
            raise SystemExit(f"unknown kernel {name!r}; "
                             f"choose from {sorted(bench_fns)}")
        rec, (unfused_c, fused_c, analytic) = bench_fns[name](
            args, h8, w8, dims, interpret)
        rec["speedup"] = round(
            rec["unfused_ms"] / max(rec["fused_ms"], 1e-9), 3)
        rec["unfused_ms"] = round(rec["unfused_ms"], 4)
        rec["fused_ms"] = round(rec["fused_ms"], 4)
        rec["selected"], rec["selected_kind"] = _registry_selected(
            name, (h, w), args.batch)
        # Per-arm cost accounting (obs/cost.py): XLA's count where it
        # sees the body (interpret mode, unfused arm), the analytic
        # formula on real-TPU custom_call arms; MFU only on known
        # device peaks and non-interpret timings.
        interp = bool(args.interpret)
        for arm, exe in (("unfused", unfused_c), ("fused", fused_c)):
            pc = cost_mod.program_cost(
                exe, program=f"kernel_{name}_{arm}",
                pairs_per_call=args.batch, interpret=interp,
                analytic=analytic if arm == "fused" else None)
            rec[f"{arm}_flops"] = pc.flops
            rec[f"{arm}_bytes"] = pc.bytes
            rec[f"{arm}_cost_source"] = pc.source
            if arm == "fused":
                rec["flops_per_pair"] = pc.flops_per_pair
                secs = rec["fused_ms"] / 1e3
                at = pc.achieved_tflops(secs)
                rec["achieved_tflops"] = (round(at, 4)
                                          if at is not None else None)
                m = pc.mfu(secs)
                rec["mfu"] = round(m, 4) if m is not None else None
                rec["bound_by"] = pc.bound_by
                rec["analytic_flops"], rec["analytic_bytes"] = analytic
        kernels[name] = rec

    print(json.dumps({
        "metric": "kernel_fused_speedup_min",
        "value": min(k["speedup"] for k in kernels.values()),
        "unit": "x",
        # No external per-kernel baseline; the unfused arm in config IS
        # the comparison (speedup 1.0 == parity with unfused).
        "vs_baseline": 0.0,
        "config": {
            "device_kind": tuning.device_kind(),
            "interpret": bool(args.interpret),
            "image": [h, w], "batch": args.batch, "model": args.model,
            "corr_dtype": args.corr_dtype, "reps": args.reps,
            "tiny": bool(args.tiny),
            "kernels": kernels,
        },
    }))


if __name__ == "__main__":
    main()
