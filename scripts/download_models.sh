#!/bin/bash
# Fetch the published RAFT model zoo and convert every checkpoint to this
# framework's orbax format (the reference ships raw .pth files,
# /root/reference/download_models.sh:2-3 — same zip, plus the torch->flax
# conversion step the reference doesn't need).
#
# Requires network access.  See docs/REAL_WEIGHTS_RUNBOOK.md for the full
# first-network-access validation sequence (convert -> demo -> Sintel EPE).
set -e
cd "$(dirname "$0")/.."

if [ ! -f models.zip ]; then
    wget https://www.dropbox.com/s/4j4z58wuv8o0mfz/models.zip
fi
unzip -o models.zip   # -> models/raft-{things,sintel,kitti,chairs,small}.pth

mkdir -p checkpoints
for pth in models/*.pth; do
    name=$(basename "$pth" .pth)
    small=""
    [ "$name" = "raft-small" ] && small="--small"
    echo "converting $name ..."
    python -m raft_tpu.convert "$pth" "checkpoints/$name" $small
done
echo "done: $(ls checkpoints)"
