"""Regression gate: fold the newest bench/telemetry JSON against the
prior series and fail loudly.

Inputs are the one-line JSON records the repo's measurement tools
already produce — ``bench.py`` / ``scripts/bench_input.py`` /
``scripts/bench_serve.py`` output, driver ``BENCH_*.json`` wrappers
(the record under their ``parsed`` key), and
``scripts/telemetry_summary.py`` output (whose ``config`` block now
carries ``nonfinite_steps_total``).  Records are grouped by ``metric``
in the order given (the default glob sorts ``BENCH_r01..rNN``), and the
NEWEST record of each series is gated:

- **throughput regression**: newest ``value`` more than
  ``--max-drop-pct`` below the median of the last ``--window`` prior
  non-null values of the same metric -> exit 1;
- **numerics**: any ``config.nonfinite_steps_total > 0`` in a newest
  record -> exit 1 (a run that needed the non-finite guard is not a
  clean number);
- optional ``--min-vs-baseline``: newest ``vs_baseline`` below the
  floor -> exit 1 (BASELINE.json's 30 pairs/sec/chip north star is the
  1.0 point of that field);
- optional ``--max-early-exit-epe-delta``: adaptive early exit
  (``ServeConfig.early_exit_threshold``) trades refinement iterations
  for latency — this bounds what it may cost in accuracy.  The newest
  records must carry ``config.early_exit_epe_delta`` (max |EPE delta|
  vs the full-iteration baseline, from ``evaluate.py
  --early_exit_threshold`` / ``bench_serve.py``) or the raw
  ``config.early_exit_delta_vs_full`` arm dict; exceeding the budget
  -> exit 1, and NO record carrying the figure also -> exit 1 (an
  accuracy gate must not pass because the sweep silently didn't run);
- optional ``--max-quality-drift`` / ``--max-canary-proxy-delta``:
  flow-quality gates over the unsupervised proxies
  (``raft_tpu/obs/quality.py``) — the peak PSI drift score of the
  production quality distribution (``config.quality_drift_score``)
  and the golden-batch proxy regression of the last weight-update
  canary (``config.canary_proxy_delta_pct``).  Both fail vacuously
  when no record carries the figure, like ``--min-mfu``.

Records with ``value: null`` (backend unavailable — the CPU container
writing TPU series) are reported but never gate, so the check is safe
in CI without hardware.

::

    python scripts/check_regression.py                  # BENCH_*.json
    python scripts/check_regression.py runs/summary.json BENCH_r*.json
    python scripts/check_regression.py --tiny           # CPU self-test

``--tiny`` builds a synthetic series in a temp dir and asserts the gate
passes a flat series, catches an injected 30% drop, and catches an
injected non-finite count — the gate gating itself (wired into tier-1).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import statistics
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def parse_args(argv=None):
    p = argparse.ArgumentParser(
        description="bench/telemetry JSON regression gate")
    p.add_argument("paths", nargs="*",
                   help="JSON records, oldest first (default: "
                        "BENCH_*.json in the repo root, name-sorted)")
    p.add_argument("--max-drop-pct", type=float, default=10.0,
                   help="fail when the newest value drops more than "
                        "this %% below the prior-series median")
    p.add_argument("--window", type=int, default=3,
                   help="prior records per metric forming the "
                        "reference median")
    p.add_argument("--min-vs-baseline", type=float, default=None,
                   help="fail when the newest vs_baseline is below "
                        "this floor (unset = no check)")
    p.add_argument("--max-quarantined", type=int, default=0,
                   help="fail when a newest record's "
                        "config.quarantined_total exceeds this (silent "
                        "data rot gate; docs/ROBUSTNESS.md)")
    p.add_argument("--max-ckpt-fallback", type=int, default=0,
                   help="fail when a newest record's "
                        "config.ckpt_fallback_total exceeds this "
                        "(torn-checkpoint gate)")
    p.add_argument("--max-serve-error-rate", type=float, default=0.0,
                   help="fail when a newest serve record's error_rate "
                        "(failed + timed-out requests over submitted; "
                        "429 sheds excluded) exceeds this fraction — "
                        "a fleet drill that dropped requests must not "
                        "pass on throughput alone")
    p.add_argument("--max-early-exit-epe-delta", type=float,
                   default=None, metavar="EPE",
                   help="fail when a newest record's early-exit EPE "
                        "delta vs the full-iteration baseline "
                        "(config.early_exit_epe_delta, or max |delta| "
                        "over config.early_exit_delta_vs_full) exceeds "
                        "this; also fails when NO record carries the "
                        "figure (unset = no check)")
    p.add_argument("--max-quality-drift", type=float, default=None,
                   metavar="SCORE",
                   help="fail when a newest record's "
                        "config.quality_drift_score (peak PSI of the "
                        "flow-quality drift detector, from "
                        "scripts/telemetry_summary.py / "
                        "scripts/quality_smoke.py; "
                        "docs/OBSERVABILITY.md) exceeds this; also "
                        "fails when NO record carries the figure — a "
                        "drift gate must not pass because quality "
                        "scoring silently turned off (unset = no "
                        "check)")
    p.add_argument("--min-warm-iters-saved-frac", type=float,
                   default=None, metavar="FRAC",
                   help="fail when a newest record's "
                        "config.warm_iters_saved_frac (1 - warm-frame "
                        "iters_used p50 / cold p50, from "
                        "scripts/bench_stream.py; docs/SERVING.md "
                        "'Streaming sessions') is below this floor — "
                        "the warm start stopped saving refinement "
                        "work; also fails when NO record carries the "
                        "figure (unset = no check)")
    p.add_argument("--max-stream-epe-delta", type=float, default=None,
                   metavar="EPE",
                   help="fail when a newest record's "
                        "config.stream_epe_delta (streamed-arm EPE "
                        "minus independent-pair EPE on identical "
                        "frames, from scripts/bench_stream.py) exceeds "
                        "this; also fails when NO record carries the "
                        "figure (unset = no check)")
    p.add_argument("--max-canary-proxy-delta", type=float, default=None,
                   metavar="PCT",
                   help="fail when a newest record's "
                        "config.canary_proxy_delta_pct (relative "
                        "golden-batch proxy regression %% of the last "
                        "weight-update canary, from "
                        "scripts/quality_smoke.py) exceeds this; also "
                        "fails when NO record carries the figure "
                        "(unset = no check)")
    p.add_argument("--max-critical-path-ms", action="append",
                   default=[], metavar="NAME:MS",
                   help="fail when a newest record's "
                        "config.critical_path_ms[NAME] (p95 self-time "
                        "of span NAME on the trace critical path, from "
                        "scripts/trace_report.py --json) exceeds MS; "
                        "repeatable.  Also fails when NO record carries "
                        "the figure — a latency gate must not pass "
                        "because tracing silently turned off")
    p.add_argument("--max-kernel-slowdown", action="append",
                   default=[], metavar="NAME:PCT",
                   help="fail when a newest bench_kernels record shows "
                        "fused kernel NAME (config.kernels[NAME], from "
                        "scripts/bench_kernels.py) more than PCT%% "
                        "slower than its unfused arm WHILE the tuning "
                        "registry selects it on this device "
                        "(kernels[NAME].selected); repeatable.  Also "
                        "fails when NO non-interpret record carries the "
                        "figure — a kernel-perf gate must not pass "
                        "because the microbench silently didn't run "
                        "(interpret-mode smoke records don't count)")
    p.add_argument("--min-mfu", action="append", default=[],
                   metavar="NAME:PCT",
                   help="fail when the newest record of a metric series "
                        "containing NAME posts MFU below PCT%% "
                        "(config.mfu or top-level mfu, from the "
                        "obs/cost.py accounting in bench.py / "
                        "bench_serve.py / telemetry_summary.py); "
                        "repeatable.  Interpret-mode records and "
                        "records with no MFU (unknown device peak, "
                        "e.g. CPU) never qualify — and a named gate "
                        "with NO qualifying record fails (a "
                        "hardware-utilization gate must not pass "
                        "because the bench ran on the wrong backend)")
    p.add_argument("--max-flops-per-pair-growth", type=float,
                   default=None, metavar="PCT",
                   help="fail when a newest record's flops_per_pair "
                        "grew more than PCT%% over the prior-series "
                        "median (work creep: a 'faster' number that "
                        "quietly shrank shapes passes the throughput "
                        "gate; one that grew work per pair should not "
                        "slip through either).  Also fails when NO "
                        "record carries flops_per_pair (unset = no "
                        "check)")
    p.add_argument("--max-incidents", action="append", default=[],
                   metavar="SEV:N",
                   help="fail when a newest record's "
                        "config.incidents[SEV] (correlated incidents "
                        "opened at peak severity SEV — info/warning/"
                        "critical — from scripts/telemetry_summary.py "
                        "or scripts/incident_smoke.py; "
                        "docs/OBSERVABILITY.md 'Incidents & SLOs') "
                        "exceeds N; repeatable.  Also fails when NO "
                        "record carries config.incidents — the "
                        "incident engine silently off must not look "
                        "like zero incidents")
    p.add_argument("--max-slo-burn", action="append", default=[],
                   metavar="NAME:RATE",
                   help="fail when a newest record's "
                        "config.slo_burn_rates[NAME] (worst error-"
                        "budget burn rate of SLO NAME over the run, "
                        "1.0 = spending the budget exactly; from "
                        "scripts/telemetry_summary.py / "
                        "scripts/incident_smoke.py) exceeds RATE; "
                        "repeatable.  Also fails when NO record "
                        "carries the named rate — SLO tracking "
                        "silently off must not look like a healthy "
                        "burn rate")
    p.add_argument("--max-scale-flaps", type=int, default=None,
                   metavar="N",
                   help="fail when a newest record's "
                        "config.scale_flaps (autoscaler direction "
                        "reversals over the run, from "
                        "scripts/telemetry_summary.py / "
                        "scripts/fabric_smoke.py; docs/SERVING.md "
                        "'Multi-host fabric') exceeds N; also fails "
                        "when NO record carries the figure — the "
                        "autoscaler silently off must not look like a "
                        "flap-free run (unset = no check)")
    p.add_argument("--max-net-retry-rate", type=float, default=None,
                   metavar="PCT",
                   help="fail when a newest record's "
                        "config.net_retry_rate (request-path wire "
                        "failures as %% of routed requests, from "
                        "scripts/fabric_smoke.py) exceeds PCT; also "
                        "fails when NO record carries the figure "
                        "(unset = no check)")
    p.add_argument("--require-tuned", action="store_true",
                   help="fail when a newest record's config lacks "
                        "`tuned: true` — i.e. its knobs did NOT come "
                        "from the per-hardware tuning registry "
                        "(scripts/autotune.py); keeps a BENCH series "
                        "from silently drifting back to hand-set knobs")
    p.add_argument("--lint-report", default=None, metavar="PATH",
                   help="fail when the raftlint JSON report at PATH "
                        "(scripts/lint_repo.py --json, or `python -m "
                        "raft_tpu lint --json`) carries non-baselined "
                        "findings; ALSO fails when PATH is missing or "
                        "not a raftlint report — lint silently not "
                        "running must not look like lint passing "
                        "(docs/ANALYSIS.md)")
    p.add_argument("--tiny", action="store_true",
                   help="self-test on synthetic series (CPU smoke; "
                        "exercises the pass, drop and nonfinite paths)")
    return p.parse_args(argv)


def load_record(path):
    """One bench-format record from ``path`` (unwraps the driver's
    ``parsed`` envelope); None when the file holds neither."""
    try:
        with open(path) as f:
            d = json.load(f)
    except (OSError, ValueError):
        return None
    if isinstance(d, dict) and isinstance(d.get("parsed"), dict):
        d = d["parsed"]
    if isinstance(d, dict) and "metric" in d:
        return d
    return None


def build_series(paths):
    """metric -> [records oldest..newest] (input order preserved)."""
    series = {}
    for path in paths:
        rec = load_record(path)
        if rec is not None:
            series.setdefault(rec["metric"], []).append(
                dict(rec, _path=path))
    return series


#: Span names every serve-rooted trace must contain (the engine's
#: per-request instrumentation, raft_tpu/serve/engine.py) — a trace
#: tree without them means the instrumentation silently broke.
SERVE_REQUIRED_SPANS = ("queue", "pad", "device")


def parse_named_gates(items, flag, example):
    """``["device:50", ...] -> {"device": 50.0}``."""
    gates = {}
    for item in items or []:
        name, sep, val = str(item).rpartition(":")
        try:
            if not sep or not name:
                raise ValueError
            gates[name] = float(val)
        except ValueError:
            raise SystemExit(f"{flag} expects NAME:{example[0]} "
                             f"(e.g. {example[1]}), got {item!r}")
    return gates


def parse_cp_gates(items):
    return parse_named_gates(items, "--max-critical-path-ms",
                             ("MS", "device:50"))


def _rec_flops_per_pair(rec):
    """A record's flops_per_pair (bench.py/telemetry_summary put it in
    ``config``, bench_serve.py at the top level); None when absent."""
    cfg = rec.get("config") or {}
    v = cfg.get("flops_per_pair", rec.get("flops_per_pair"))
    return v if isinstance(v, (int, float)) and v > 0 else None


def check(series, max_drop_pct=10.0, window=3, min_vs_baseline=None,
          max_quarantined=0, max_ckpt_fallback=0, require_tuned=False,
          max_serve_error_rate=0.0, max_critical_path_ms=None,
          max_early_exit_epe_delta=None, max_kernel_slowdown=None,
          min_mfu=None, max_flops_per_pair_growth=None,
          max_quality_drift=None, max_canary_proxy_delta=None,
          min_warm_iters_saved_frac=None, max_stream_epe_delta=None,
          max_incidents=None, max_slo_burn=None, max_scale_flaps=None,
          max_net_retry_rate=None):
    """``(failures, report)`` over the newest record of each metric."""
    failures, report = [], []
    cp_gates = dict(max_critical_path_ms or {})
    cp_seen = set()
    inc_gates = dict(max_incidents or {})
    inc_seen = set()
    slo_gates = dict(max_slo_burn or {})
    slo_seen = set()
    ker_gates = dict(max_kernel_slowdown or {})
    ker_seen = set()
    mfu_gates = dict(min_mfu or {})
    mfu_seen = set()
    ee_seen = False
    fpp_seen = False
    qd_seen = False
    cpx_seen = False
    wis_seen = False
    sed_seen = False
    sf_seen = False
    nrr_seen = False
    for metric, recs in sorted(series.items()):
        newest = recs[-1]
        value = newest.get("value")
        cfg = newest.get("config") or {}
        entry = {"metric": metric, "value": value,
                 "path": newest.get("_path"), "n_records": len(recs)}
        if require_tuned and cfg.get("tuned") is not True:
            failures.append(
                f"{metric}: config.tuned is not true — knobs did not "
                "come from the tuning registry (run scripts/autotune.py "
                "or drop --require-tuned)")
        nf = cfg.get("nonfinite_steps_total")
        if isinstance(nf, (int, float)) and nf > 0:
            failures.append(
                f"{metric}: nonfinite_steps_total={int(nf)} — the run "
                "hit the non-finite guard; its numbers are not clean")
        # Fault-tolerance gates (docs/ROBUSTNESS.md): a run that had to
        # quarantine samples or walk past torn checkpoints produced a
        # number, but the number hides rot — fail unless the budget
        # says otherwise (chaos drills pass explicit budgets).
        q = cfg.get("quarantined_total")
        if isinstance(q, (int, float)) and q > max_quarantined:
            failures.append(
                f"{metric}: quarantined_total={int(q)} > "
                f"{max_quarantined} — samples were silently skipped "
                "(data rot or a chaos drill without a budget)")
        fb = cfg.get("ckpt_fallback_total")
        if isinstance(fb, (int, float)) and fb > max_ckpt_fallback:
            failures.append(
                f"{metric}: ckpt_fallback_total={int(fb)} > "
                f"{max_ckpt_fallback} — resume skipped torn "
                "checkpoint step(s)")
        # Serve-path gate: bench_serve records carry error_rate (failed
        # + timed-out requests over submitted; 429 sheds excluded).  A
        # fleet whose failover quietly loses requests still posts good
        # throughput — this is the gate that notices.
        er = newest.get("error_rate")
        if isinstance(er, (int, float)) and er > max_serve_error_rate:
            failures.append(
                f"{metric}: error_rate={er:g} > {max_serve_error_rate:g}"
                f" ({newest.get('errors', '?')} errors, "
                f"{newest.get('timeouts', '?')} timeouts)")
        # Trace-derived SLO gates (scripts/trace_report.py --json):
        # per-span critical-path budgets, plus a coverage check — a
        # serve trace tree missing the engine's queue/pad/device spans
        # means the instrumentation regressed, and a latency gate over
        # absent data would pass vacuously.
        cp = cfg.get("critical_path_ms")
        if isinstance(cp, dict):
            for name, budget in cp_gates.items():
                v = cp.get(name)
                if isinstance(v, (int, float)):
                    cp_seen.add(name)
                    if v > budget:
                        failures.append(
                            f"{metric}: critical-path {name} p95 "
                            f"{v:g}ms > budget {budget:g}ms")
        # Fused-kernel perf gate (scripts/bench_kernels.py records): the
        # tuning registry must not keep SELECTING a fused kernel that
        # the microbench shows slower than its unfused arm on this
        # device.  Interpret-mode smoke records are skipped — the
        # interpreter's timings say nothing about hardware.
        kers = cfg.get("kernels")
        if isinstance(kers, dict) and not cfg.get("interpret"):
            for name, budget in ker_gates.items():
                k = kers.get(name)
                if not isinstance(k, dict):
                    continue
                fu, un = k.get("fused_ms"), k.get("unfused_ms")
                if (isinstance(fu, (int, float))
                        and isinstance(un, (int, float)) and un > 0):
                    ker_seen.add(name)
                    if k.get("selected"):
                        slow = (fu / un - 1.0) * 100.0
                        if slow > budget:
                            failures.append(
                                f"{metric}: fused kernel {name!r} is "
                                f"{slow:.1f}% slower than unfused "
                                f"({fu:g}ms vs {un:g}ms, budget "
                                f"{budget:g}%) yet the tuning registry "
                                f"selects it "
                                f"({k.get('selected_kind')}) — re-run "
                                "scripts/autotune.py on this device")
        # Hardware-utilization floor (obs/cost.py MFU): interpret-mode
        # records and unknown-peak records (CPU — mfu is null there by
        # design, never a fabricated ratio) are EXCLUDED from
        # qualifying, so a TPU gate cannot be satisfied by a CPU smoke.
        mfu = cfg.get("mfu", newest.get("mfu"))
        if not cfg.get("interpret") and isinstance(mfu, (int, float)):
            for name, floor in mfu_gates.items():
                if name not in metric:
                    continue
                mfu_seen.add(name)
                if mfu * 100.0 < floor:
                    failures.append(
                        f"{metric}: mfu {mfu * 100.0:.2f}% < floor "
                        f"{floor:g}% — the chip is underutilized vs "
                        "the gated baseline (scheduling/fusion "
                        "regression, or the wrong device peak)")
        # Work-creep gate: flops_per_pair is hardware- and mesh-
        # invariant, so growth over the series means the program
        # genuinely does more work per pair — a throughput 'win' from
        # shape shrink shows up here as the mirror failure.
        if max_flops_per_pair_growth is not None:
            fpp = _rec_flops_per_pair(newest)
            if fpp is not None:
                fpp_seen = True
                prior_fpp = [v for v in map(_rec_flops_per_pair,
                                            recs[:-1]) if v is not None]
                if prior_fpp:
                    ref = statistics.median(
                        prior_fpp[-max(window, 1):])
                    growth = (fpp - ref) / ref * 100.0
                    if growth > max_flops_per_pair_growth:
                        failures.append(
                            f"{metric}: flops_per_pair {fpp:g} grew "
                            f"{growth:.1f}% over the prior-series "
                            f"median {ref:g} (budget "
                            f"{max_flops_per_pair_growth:g}%) — work "
                            "per pair crept up")
        # Early-exit accuracy gate: iterations saved by the convergence
        # cut (docs/SERVING.md) must stay within the EPE budget the
        # sweep measured (evaluate.py --early_exit_threshold).
        if max_early_exit_epe_delta is not None:
            ee = cfg.get("early_exit_epe_delta")
            dv = cfg.get("early_exit_delta_vs_full")
            if ee is None and isinstance(dv, dict):
                arms = [abs(v) for v in dv.values()
                        if isinstance(v, (int, float))]
                ee = max(arms) if arms else None
            if isinstance(ee, (int, float)):
                ee_seen = True
                if abs(ee) > max_early_exit_epe_delta:
                    failures.append(
                        f"{metric}: early-exit EPE delta {ee:g} exceeds "
                        f"budget {max_early_exit_epe_delta:g} — the "
                        "convergence threshold is trading too much "
                        "accuracy for latency")
        # Flow-quality gates (docs/OBSERVABILITY.md): the PSI drift
        # score is the unsupervised production-quality signal
        # (raft_tpu/obs/quality.py), the canary proxy delta is what the
        # last weight-update canary measured on the golden batch.  Like
        # --min-mfu, a gate with no qualifying record FAILS — quality
        # scoring silently off must not look like quality stable.
        if max_quality_drift is not None:
            qd = cfg.get("quality_drift_score")
            if isinstance(qd, (int, float)):
                qd_seen = True
                if qd > max_quality_drift:
                    failures.append(
                        f"{metric}: quality_drift_score {qd:g} > budget "
                        f"{max_quality_drift:g} — the flow-quality "
                        "proxy distribution shifted vs its reference "
                        "(input drift or a bad weight rollout)")
        if max_canary_proxy_delta is not None:
            cpx = cfg.get("canary_proxy_delta_pct")
            if isinstance(cpx, (int, float)):
                cpx_seen = True
                if cpx > max_canary_proxy_delta:
                    failures.append(
                        f"{metric}: canary_proxy_delta_pct {cpx:g}% > "
                        f"budget {max_canary_proxy_delta:g}% — the "
                        "weight-update canary scored worse on the "
                        "golden batch than the live fleet")
        # Streaming warm-start gates (scripts/bench_stream.py,
        # docs/SERVING.md "Streaming sessions"): warm-started frames
        # must keep converging in fewer iterations than cold ones, and
        # the accuracy cost of carrying state across frames stays
        # inside its budget.
        if min_warm_iters_saved_frac is not None:
            wis = cfg.get("warm_iters_saved_frac")
            if isinstance(wis, (int, float)):
                wis_seen = True
                if wis < min_warm_iters_saved_frac:
                    failures.append(
                        f"{metric}: warm_iters_saved_frac {wis:g} < "
                        f"floor {min_warm_iters_saved_frac:g} — "
                        "warm-started frames no longer converge "
                        "meaningfully faster than cold ones (broken "
                        "carry-over or a mis-set warm budget)")
        if max_stream_epe_delta is not None:
            sed = cfg.get("stream_epe_delta")
            if isinstance(sed, (int, float)):
                sed_seen = True
                if sed > max_stream_epe_delta:
                    failures.append(
                        f"{metric}: stream_epe_delta {sed:g} > budget "
                        f"{max_stream_epe_delta:g} — streaming warm "
                        "start costs more accuracy vs independent "
                        "pairs than the budget allows")
        # Incident-engine gates (docs/OBSERVABILITY.md "Incidents &
        # SLOs"): a run that paged its way through a cascade still
        # posts a throughput number — these are the gates that notice.
        # A record qualifies by carrying config.incidents at all (an
        # EMPTY dict is a healthy incident-enabled run; the key's
        # absence means the engine never ran).
        inc = cfg.get("incidents")
        if isinstance(inc, dict):
            for sev, budget in inc_gates.items():
                inc_seen.add(sev)
                n = inc.get(sev, 0)
                if isinstance(n, (int, float)) and n > budget:
                    failures.append(
                        f"{metric}: incidents[{sev!r}]={int(n)} > "
                        f"{budget:g} — the run opened more {sev} "
                        "incidents than the budget allows "
                        "(python -m raft_tpu incidents list)")
        sbr = cfg.get("slo_burn_rates")
        if isinstance(sbr, dict):
            for name, budget in slo_gates.items():
                v = sbr.get(name)
                if isinstance(v, (int, float)):
                    slo_seen.add(name)
                    if v > budget:
                        failures.append(
                            f"{metric}: slo_burn_rates[{name!r}]="
                            f"{v:g} > {budget:g} — the {name} SLO "
                            "burned its error budget faster than the "
                            "gate allows")
        # Multi-host fabric gates (docs/SERVING.md "Multi-host
        # fabric"): an autoscaler that reverses direction within one
        # run is flapping (its hysteresis/cooldown knobs regressed),
        # and a fabric drill whose wire-failure rate blows past the
        # budget is retrying its way through a problem the failover
        # machinery should have absorbed.
        if max_scale_flaps is not None:
            sf = cfg.get("scale_flaps")
            if isinstance(sf, (int, float)):
                sf_seen = True
                if sf > max_scale_flaps:
                    failures.append(
                        f"{metric}: scale_flaps={int(sf)} > "
                        f"{max_scale_flaps} — the autoscaler reversed "
                        "direction more than the budget allows "
                        "(hysteresis/cooldown too tight for the load)")
        if max_net_retry_rate is not None:
            nrr = cfg.get("net_retry_rate")
            if isinstance(nrr, (int, float)):
                nrr_seen = True
                if nrr > max_net_retry_rate:
                    failures.append(
                        f"{metric}: net_retry_rate={nrr:g}% > "
                        f"{max_net_retry_rate:g}% — the fabric burned "
                        "more wire retries per routed request than the "
                        "budget allows (partition outlasting the "
                        "breaker, or a flaky link)")
        sn = cfg.get("serve_span_names")
        if isinstance(sn, list) and sn:
            missing = sorted(set(SERVE_REQUIRED_SPANS) - set(sn))
            if missing:
                failures.append(
                    f"{metric}: serve traces are missing the "
                    f"{missing} span(s) — the request-path "
                    "instrumentation is incomplete (engine spans "
                    "lost?); refusing to gate on partial traces")
        if value is None:
            entry["skipped"] = "value null (backend unavailable)"
            report.append(entry)
            continue
        prior = [r.get("value") for r in recs[:-1]
                 if isinstance(r.get("value"), (int, float))]
        if prior:
            ref = statistics.median(prior[-max(window, 1):])
            entry["reference"] = ref
            if ref > 0:
                drop = (ref - value) / ref * 100.0
                entry["drop_pct"] = round(drop, 2)
                if drop > max_drop_pct:
                    failures.append(
                        f"{metric}: {value} is {drop:.1f}% below the "
                        f"prior-series median {ref} "
                        f"(threshold {max_drop_pct}%)")
        vs = newest.get("vs_baseline")
        if (min_vs_baseline is not None
                and isinstance(vs, (int, float)) and vs < min_vs_baseline):
            failures.append(f"{metric}: vs_baseline {vs} < floor "
                            f"{min_vs_baseline}")
        report.append(entry)
    for name in sorted(set(cp_gates) - cp_seen):
        failures.append(
            f"critical-path gate {name!r}: no record carries "
            f"config.critical_path_ms[{name!r}] — tracing is off or "
            "the span never appeared; the gate cannot pass vacuously")
    for name in sorted(set(ker_gates) - ker_seen):
        failures.append(
            f"kernel gate {name!r}: no non-interpret record carries "
            f"config.kernels[{name!r}] timings — the microbench "
            "(scripts/bench_kernels.py) did not run on hardware; the "
            "gate cannot pass vacuously")
    for name in sorted(set(mfu_gates) - mfu_seen):
        failures.append(
            f"mfu gate {name!r}: no qualifying record carries an MFU "
            "figure (interpret-mode and unknown-peak/CPU records are "
            "excluded) — the cost-instrumented bench did not run on "
            "known hardware; the gate cannot pass vacuously")
    if max_flops_per_pair_growth is not None and not fpp_seen:
        failures.append(
            "flops-per-pair gate: no record carries flops_per_pair "
            "(config or top-level) — the cost accounting did not run; "
            "the gate cannot pass vacuously")
    if max_early_exit_epe_delta is not None and not ee_seen:
        failures.append(
            "early-exit gate: no record carries "
            "config.early_exit_epe_delta (or early_exit_delta_vs_full) "
            "— the accuracy sweep did not run; the gate cannot pass "
            "vacuously")
    if max_quality_drift is not None and not qd_seen:
        failures.append(
            "quality-drift gate: no record carries "
            "config.quality_drift_score — quality scoring did not run "
            "(ServeConfig.quality_sample_rate 0, or the summary "
            "predates the quality proxies); the gate cannot pass "
            "vacuously")
    if min_warm_iters_saved_frac is not None and not wis_seen:
        failures.append(
            "warm-iters gate: no record carries "
            "config.warm_iters_saved_frac — the streaming bench "
            "(scripts/bench_stream.py) did not run, or its warm/cold "
            "histograms were empty; the gate cannot pass vacuously")
    if max_stream_epe_delta is not None and not sed_seen:
        failures.append(
            "stream-epe gate: no record carries "
            "config.stream_epe_delta — the streaming bench "
            "(scripts/bench_stream.py) did not run both arms; the "
            "gate cannot pass vacuously")
    for sev in sorted(set(inc_gates) - inc_seen):
        failures.append(
            f"incident gate {sev!r}: no record carries "
            "config.incidents — the incident engine "
            "(ServeConfig.incidents / RAFT_INCIDENTS=1) did not run; "
            "the gate cannot pass vacuously")
    for name in sorted(set(slo_gates) - slo_seen):
        failures.append(
            f"slo-burn gate {name!r}: no record carries "
            f"config.slo_burn_rates[{name!r}] — SLO tracking for that "
            "objective did not run (slo_* targets unset?); the gate "
            "cannot pass vacuously")
    if max_scale_flaps is not None and not sf_seen:
        failures.append(
            "scale-flap gate: no record carries config.scale_flaps — "
            "the autoscaler did not run (autoscale_max 0, or the "
            "summary predates the fabric fold); the gate cannot pass "
            "vacuously")
    if max_net_retry_rate is not None and not nrr_seen:
        failures.append(
            "net-retry gate: no record carries config.net_retry_rate "
            "— no fabric drill ran (scripts/fabric_smoke.py); the "
            "gate cannot pass vacuously")
    if max_canary_proxy_delta is not None and not cpx_seen:
        failures.append(
            "canary-proxy gate: no record carries "
            "config.canary_proxy_delta_pct — no proxy-gated weight "
            "update ran (canary_proxy_budget unset, or no update "
            "happened); the gate cannot pass vacuously")
    return failures, report


def lint_gate(path):
    """Failure list from a raftlint JSON report.  Three ways to fail:
    the report is missing/unreadable, it is not a raftlint report, or
    it carries non-baselined findings.  A clean report (``total: 0``)
    passes; an ABSENT report does not — the gate must distinguish
    "raftlint ran and found nothing" from "raftlint never ran"."""
    if REPO not in sys.path:
        sys.path.insert(0, REPO)
    from raft_tpu.analysis.core import load_report

    report, err = load_report(path)
    if report is None:
        return [f"lint gate: {err} — refusing to pass without a "
                "raftlint run (python -m raft_tpu lint --json PATH)"]
    findings = report.get("findings")
    total = report.get("total")
    n = total if isinstance(total, int) else len(findings)
    if n <= 0:
        return []
    by_rule = report.get("counts_by_rule") or {}
    head = "; ".join(
        "{}:{}:{} {}".format(f.get("rule"), f.get("path"),
                             f.get("line"), f.get("message", ""))[:160]
        for f in findings[:3] if isinstance(f, dict))
    return [f"lint gate: {n} non-baselined raftlint finding(s) "
            f"({json.dumps(by_rule)}) — fix them or baseline with a "
            f"justification (docs/ANALYSIS.md). First: {head}"]


def _selftest() -> int:
    """The gate gating itself: synthetic series through the real
    file-loading path."""

    def run(values, nonfinite_last=0, drop_pct=10.0, last_cfg=None,
            last_top=None, cfgs=None, **gate_kw):
        with tempfile.TemporaryDirectory() as td:
            paths = []
            for i, v in enumerate(values):
                rec = {"metric": "train_throughput_tiny", "value": v,
                       "unit": "image-pairs/sec/chip", "vs_baseline": 0.0,
                       "config": {}}
                if cfgs is not None:
                    rec["config"].update(cfgs[i])
                if i == len(values) - 1:
                    if nonfinite_last:
                        rec["config"]["nonfinite_steps_total"] = \
                            nonfinite_last
                    rec["config"].update(last_cfg or {})
                    rec.update(last_top or {})
                if i % 2:  # alternate raw and driver-wrapped envelopes
                    rec = {"n": i, "rc": 0, "parsed": rec}
                p = os.path.join(td, f"BENCH_r{i:02d}.json")
                with open(p, "w") as f:
                    json.dump(rec, f)
                paths.append(p)
            return check(build_series(paths), max_drop_pct=drop_pct,
                         **gate_kw)

    cases = [
        ("flat series passes", run([30.0, 31.0, 30.5]), False),
        ("30% drop fails", run([30.0, 31.0, 21.0]), True),
        ("nonfinite fails", run([30.0, 31.0, 30.5], nonfinite_last=2),
         True),
        ("null value never gates", run([30.0, 31.0, None]), False),
        ("single record passes", run([30.0]), False),
        ("quarantine fails", run([30.0, 31.0, 30.5],
                                 last_cfg={"quarantined_total": 3}),
         True),
        ("quarantine within budget passes",
         run([30.0, 31.0, 30.5], last_cfg={"quarantined_total": 3},
             max_quarantined=3), False),
        ("ckpt fallback fails", run([30.0, 31.0, 30.5],
                                    last_cfg={"ckpt_fallback_total": 1}),
         True),
        ("zero fault totals pass",
         run([30.0, 31.0, 30.5], last_cfg={"quarantined_total": 0,
                                           "ckpt_fallback_total": 0}),
         False),
        ("require-tuned fails untuned",
         run([30.0, 31.0, 30.5], require_tuned=True), True),
        ("require-tuned passes tuned",
         run([30.0, 31.0, 30.5], last_cfg={"tuned": True},
             require_tuned=True), False),
        ("untuned passes without the gate",
         run([30.0, 31.0, 30.5], last_cfg={"tuned": False}), False),
        ("serve error_rate fails",
         run([30.0, 31.0, 30.5],
             last_top={"error_rate": 0.125, "errors": 2, "timeouts": 1}),
         True),
        ("serve error_rate within budget passes",
         run([30.0, 31.0, 30.5], last_top={"error_rate": 0.125},
             max_serve_error_rate=0.2), False),
        ("zero error_rate passes",
         run([30.0, 31.0, 30.5],
             last_top={"error_rate": 0.0, "errors": 0, "timeouts": 0}),
         False),
        ("rejected-only record passes",
         run([30.0, 31.0, 30.5],
             last_top={"error_rate": 0.0, "rejected": 5}), False),
        ("critical path within budget passes",
         run([30.0, 31.0, 30.5],
             last_cfg={"critical_path_ms": {"device": 12.0}},
             max_critical_path_ms={"device": 50.0}), False),
        ("critical path over budget fails",
         run([30.0, 31.0, 30.5],
             last_cfg={"critical_path_ms": {"device": 80.0}},
             max_critical_path_ms={"device": 50.0}), True),
        ("critical-path gate without data fails",
         run([30.0, 31.0, 30.5],
             max_critical_path_ms={"device": 50.0}), True),
        ("serve span coverage complete passes",
         run([30.0, 31.0, 30.5],
             last_cfg={"serve_span_names": ["attempt", "device", "pad",
                                            "queue", "route"]}), False),
        ("serve span coverage missing fails",
         run([30.0, 31.0, 30.5],
             last_cfg={"serve_span_names": ["route", "attempt"]}), True),
        ("no serve traces skips coverage",
         run([30.0, 31.0, 30.5], last_cfg={"serve_span_names": []}),
         False),
        ("early-exit delta within budget passes",
         run([30.0, 31.0, 30.5],
             last_cfg={"early_exit_epe_delta": 0.03},
             max_early_exit_epe_delta=0.05), False),
        ("early-exit delta over budget fails",
         run([30.0, 31.0, 30.5],
             last_cfg={"early_exit_epe_delta": 0.09},
             max_early_exit_epe_delta=0.05), True),
        ("early-exit arm dict over budget fails",
         run([30.0, 31.0, 30.5],
             last_cfg={"early_exit_delta_vs_full": {"0.05": 0.01,
                                                    "0.2": -0.3}},
             max_early_exit_epe_delta=0.05), True),
        ("early-exit gate without data fails",
         run([30.0, 31.0, 30.5], max_early_exit_epe_delta=0.05), True),
        ("early-exit delta without the gate passes",
         run([30.0, 31.0, 30.5],
             last_cfg={"early_exit_epe_delta": 9.0}), False),
        ("selected fused kernel within budget passes",
         run([30.0, 31.0, 30.5],
             last_cfg={"kernels": {"gru": {
                 "fused_ms": 9.0, "unfused_ms": 10.0, "selected": True}}},
             max_kernel_slowdown={"gru": 5.0}), False),
        ("selected fused kernel slower fails",
         run([30.0, 31.0, 30.5],
             last_cfg={"kernels": {"gru": {
                 "fused_ms": 12.0, "unfused_ms": 10.0,
                 "selected": True, "selected_kind": "train"}}},
             max_kernel_slowdown={"gru": 5.0}), True),
        ("unselected slower fused kernel passes",
         run([30.0, 31.0, 30.5],
             last_cfg={"kernels": {"gru": {
                 "fused_ms": 12.0, "unfused_ms": 10.0,
                 "selected": False}}},
             max_kernel_slowdown={"gru": 5.0}), False),
        ("kernel gate without record fails",
         run([30.0, 31.0, 30.5], max_kernel_slowdown={"gru": 5.0}),
         True),
        ("interpret-only kernel record fails the gate",
         run([30.0, 31.0, 30.5],
             last_cfg={"interpret": True, "kernels": {"gru": {
                 "fused_ms": 9.0, "unfused_ms": 10.0, "selected": True}}},
             max_kernel_slowdown={"gru": 5.0}), True),
        ("slow kernel record without the gate passes",
         run([30.0, 31.0, 30.5],
             last_cfg={"kernels": {"gru": {
                 "fused_ms": 99.0, "unfused_ms": 10.0,
                 "selected": True}}}), False),
        ("mfu above floor passes",
         run([30.0, 31.0, 30.5], last_cfg={"mfu": 0.45},
             min_mfu={"train_throughput": 40.0}), False),
        ("mfu below floor fails",
         run([30.0, 31.0, 30.5], last_cfg={"mfu": 0.25},
             min_mfu={"train_throughput": 40.0}), True),
        ("mfu gate without record fails",
         run([30.0, 31.0, 30.5],
             min_mfu={"train_throughput": 40.0}), True),
        ("interpret record never satisfies the mfu gate",
         run([30.0, 31.0, 30.5],
             last_cfg={"interpret": True, "mfu": 0.45},
             min_mfu={"train_throughput": 40.0}), True),
        ("null mfu (CPU peak) never satisfies the mfu gate",
         run([30.0, 31.0, 30.5], last_cfg={"mfu": None},
             min_mfu={"train_throughput": 40.0}), True),
        ("low mfu without the gate passes",
         run([30.0, 31.0, 30.5], last_cfg={"mfu": 0.02}), False),
        ("flat flops_per_pair passes",
         run([30.0, 31.0, 30.5],
             cfgs=[{"flops_per_pair": 1e9}] * 3,
             max_flops_per_pair_growth=5.0), False),
        ("flops_per_pair growth over budget fails",
         run([30.0, 31.0, 30.5],
             cfgs=[{"flops_per_pair": 1e9}, {"flops_per_pair": 1e9},
                   {"flops_per_pair": 1.2e9}],
             max_flops_per_pair_growth=5.0), True),
        ("first costed record passes the growth gate",
         run([30.0, 31.0, 30.5],
             last_cfg={"flops_per_pair": 1e9},
             max_flops_per_pair_growth=5.0), False),
        ("flops_per_pair gate without data fails",
         run([30.0, 31.0, 30.5], max_flops_per_pair_growth=5.0), True),
        ("flops_per_pair growth without the gate passes",
         run([30.0, 31.0, 30.5],
             cfgs=[{"flops_per_pair": 1e9}, {"flops_per_pair": 1e9},
                   {"flops_per_pair": 9e9}]), False),
        ("quality drift within budget passes",
         run([30.0, 31.0, 30.5],
             last_cfg={"quality_drift_score": 0.2},
             max_quality_drift=0.5), False),
        ("quality drift over budget fails",
         run([30.0, 31.0, 30.5],
             last_cfg={"quality_drift_score": 1.7},
             max_quality_drift=0.5), True),
        ("quality-drift gate without data fails",
         run([30.0, 31.0, 30.5], max_quality_drift=0.5), True),
        ("high drift score without the gate passes",
         run([30.0, 31.0, 30.5],
             last_cfg={"quality_drift_score": 9.0}), False),
        ("canary proxy delta within budget passes",
         run([30.0, 31.0, 30.5],
             last_cfg={"canary_proxy_delta_pct": 12.0},
             max_canary_proxy_delta=50.0), False),
        ("canary proxy delta over budget fails",
         run([30.0, 31.0, 30.5],
             last_cfg={"canary_proxy_delta_pct": 180.0},
             max_canary_proxy_delta=50.0), True),
        ("canary-proxy gate without data fails",
         run([30.0, 31.0, 30.5], max_canary_proxy_delta=50.0), True),
        ("canary proxy delta without the gate passes",
         run([30.0, 31.0, 30.5],
             last_cfg={"canary_proxy_delta_pct": 999.0}), False),
        ("warm iters saving above floor passes",
         run([30.0, 31.0, 30.5],
             last_cfg={"warm_iters_saved_frac": 0.4},
             min_warm_iters_saved_frac=0.1), False),
        ("warm iters saving below floor fails",
         run([30.0, 31.0, 30.5],
             last_cfg={"warm_iters_saved_frac": 0.02},
             min_warm_iters_saved_frac=0.1), True),
        ("warm-iters gate without data fails",
         run([30.0, 31.0, 30.5], min_warm_iters_saved_frac=0.1), True),
        ("zero warm saving without the gate passes",
         run([30.0, 31.0, 30.5],
             last_cfg={"warm_iters_saved_frac": 0.0}), False),
        ("stream EPE delta within budget passes",
         run([30.0, 31.0, 30.5],
             last_cfg={"stream_epe_delta": 0.02},
             max_stream_epe_delta=0.1), False),
        ("stream EPE delta over budget fails",
         run([30.0, 31.0, 30.5],
             last_cfg={"stream_epe_delta": 0.7},
             max_stream_epe_delta=0.1), True),
        ("stream-epe gate without data fails",
         run([30.0, 31.0, 30.5], max_stream_epe_delta=0.1), True),
        ("high stream EPE delta without the gate passes",
         run([30.0, 31.0, 30.5],
             last_cfg={"stream_epe_delta": 9.0}), False),
        ("incidents within budget pass",
         run([30.0, 31.0, 30.5],
             last_cfg={"incidents": {"critical": 1}},
             max_incidents={"critical": 1}), False),
        ("incidents over budget fail",
         run([30.0, 31.0, 30.5],
             last_cfg={"incidents": {"critical": 2, "warning": 1}},
             max_incidents={"critical": 1}), True),
        ("empty incidents dict satisfies a zero budget",
         run([30.0, 31.0, 30.5], last_cfg={"incidents": {}},
             max_incidents={"critical": 0}), False),
        ("incident gate without data fails",
         run([30.0, 31.0, 30.5], max_incidents={"critical": 0}), True),
        ("incidents without the gate pass",
         run([30.0, 31.0, 30.5],
             last_cfg={"incidents": {"critical": 9}}), False),
        ("slo burn within budget passes",
         run([30.0, 31.0, 30.5],
             last_cfg={"slo_burn_rates": {"availability": 0.4}},
             max_slo_burn={"availability": 1.0}), False),
        ("slo burn over budget fails",
         run([30.0, 31.0, 30.5],
             last_cfg={"slo_burn_rates": {"availability": 14.4}},
             max_slo_burn={"availability": 1.0}), True),
        ("slo-burn gate without the named series fails",
         run([30.0, 31.0, 30.5],
             last_cfg={"slo_burn_rates": {"latency": 0.0}},
             max_slo_burn={"availability": 1.0}), True),
        ("slo-burn gate without data fails",
         run([30.0, 31.0, 30.5], max_slo_burn={"availability": 1.0}),
         True),
        ("hot slo burn without the gate passes",
         run([30.0, 31.0, 30.5],
             last_cfg={"slo_burn_rates": {"availability": 99.0}}),
         False),
        ("scale flaps within budget pass",
         run([30.0, 31.0, 30.5], last_cfg={"scale_flaps": 1},
             max_scale_flaps=1), False),
        ("scale flaps over budget fail",
         run([30.0, 31.0, 30.5], last_cfg={"scale_flaps": 3},
             max_scale_flaps=1), True),
        ("zero scale flaps satisfy a zero budget",
         run([30.0, 31.0, 30.5], last_cfg={"scale_flaps": 0},
             max_scale_flaps=0), False),
        ("scale-flap gate without data fails",
         run([30.0, 31.0, 30.5], max_scale_flaps=1), True),
        ("scale flaps without the gate pass",
         run([30.0, 31.0, 30.5], last_cfg={"scale_flaps": 9}), False),
        ("net retry rate within budget passes",
         run([30.0, 31.0, 30.5], last_cfg={"net_retry_rate": 4.0},
             max_net_retry_rate=25.0), False),
        ("net retry rate over budget fails",
         run([30.0, 31.0, 30.5], last_cfg={"net_retry_rate": 60.0},
             max_net_retry_rate=25.0), True),
        ("net-retry gate without data fails",
         run([30.0, 31.0, 30.5], max_net_retry_rate=25.0), True),
        ("hot net retry rate without the gate passes",
         run([30.0, 31.0, 30.5],
             last_cfg={"net_retry_rate": 99.0}), False),
    ]

    def run_lint(payload):
        """Lint-gate case through the real file path.  ``payload``:
        None = no file on disk; str = raw file contents; dict = a
        report skeleton (tool/findings/total filled in by the caller)."""
        with tempfile.TemporaryDirectory() as td:
            p = os.path.join(td, "lint.json")
            if payload is not None:
                with open(p, "w") as f:
                    f.write(payload if isinstance(payload, str)
                            else json.dumps(payload))
            return lint_gate(p), []

    finding = {"rule": "JIT101", "path": "raft_tpu/models/raft.py",
               "line": 7, "detail": "time.time",
               "message": "host call inside a jit-traced function"}
    cases += [
        ("lint clean report passes",
         run_lint({"tool": "raftlint", "findings": [], "total": 0}),
         False),
        ("lint new finding fails",
         run_lint({"tool": "raftlint", "findings": [finding],
                   "counts_by_rule": {"JIT101": 1}, "total": 1}), True),
        ("lint missing report fails", run_lint(None), True),
        ("lint garbage report fails", run_lint("not json {"), True),
        ("lint wrong-tool report fails",
         run_lint({"tool": "flake8", "findings": []}), True),
    ]
    bad = [name for name, (failures, _), want_fail in cases
           if bool(failures) != want_fail]
    print(json.dumps({
        "metric": "check_regression_selftest",
        "value": 0.0 if bad else 1.0,
        "unit": "pass",
        "vs_baseline": 0.0,
        "config": {"cases": len(cases), "failed": bad},
    }))
    return 1 if bad else 0


def main(argv=None):
    args = parse_args(argv)
    if args.tiny:
        return _selftest()
    paths = args.paths or sorted(
        glob.glob(os.path.join(REPO, "BENCH_*.json")))
    if not paths and not args.lint_report:
        raise SystemExit("no input records (no BENCH_*.json found and "
                         "no paths given)")
    failures, report = check(build_series(paths),
                             max_drop_pct=args.max_drop_pct,
                             window=args.window,
                             min_vs_baseline=args.min_vs_baseline,
                             max_quarantined=args.max_quarantined,
                             max_ckpt_fallback=args.max_ckpt_fallback,
                             require_tuned=args.require_tuned,
                             max_serve_error_rate=args.max_serve_error_rate,
                             max_critical_path_ms=parse_cp_gates(
                                 args.max_critical_path_ms),
                             max_early_exit_epe_delta=(
                                 args.max_early_exit_epe_delta),
                             max_kernel_slowdown=parse_named_gates(
                                 args.max_kernel_slowdown,
                                 "--max-kernel-slowdown",
                                 ("PCT", "gru:5")),
                             min_mfu=parse_named_gates(
                                 args.min_mfu, "--min-mfu",
                                 ("PCT", "train_throughput:40")),
                             max_flops_per_pair_growth=(
                                 args.max_flops_per_pair_growth),
                             max_quality_drift=args.max_quality_drift,
                             max_canary_proxy_delta=(
                                 args.max_canary_proxy_delta),
                             min_warm_iters_saved_frac=(
                                 args.min_warm_iters_saved_frac),
                             max_stream_epe_delta=(
                                 args.max_stream_epe_delta),
                             max_incidents=parse_named_gates(
                                 args.max_incidents, "--max-incidents",
                                 ("N", "critical:0")),
                             max_slo_burn=parse_named_gates(
                                 args.max_slo_burn, "--max-slo-burn",
                                 ("RATE", "availability:1")),
                             max_scale_flaps=args.max_scale_flaps,
                             max_net_retry_rate=args.max_net_retry_rate)
    if args.lint_report:
        failures.extend(lint_gate(args.lint_report))
    print(json.dumps({"ok": not failures, "failures": failures,
                      "checked": report}))
    if failures:
        for f in failures:
            print(f"REGRESSION: {f}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
