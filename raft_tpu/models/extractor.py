"""Feature / context encoders (NHWC, 1/8 resolution).

Re-designs the reference's ``core/extractor.py:118-267``:

- ``BasicEncoder``: 7x7/s2 stem -> three residual stages (64, 96/s2, 128/s2)
  -> 1x1 projection (extractor.py:135-148).
- ``SmallEncoder``: bottleneck blocks, 32 -> 64/s2 -> 96/s2
  (extractor.py:212-227).

Both take frames stacked on the batch axis for the shared-weight two-frame
encode (the reference's list-input trick, extractor.py:168-174, becomes an
explicit ``jnp.concatenate`` at the caller).  Dropout is channel-wise
(torch Dropout2d, extractor.py:186-187) -> flax Dropout broadcast over the
spatial axes.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp

from raft_tpu.models.layers import (BottleneckBlock,
                                    FoldedEntryResidualBlock,
                                    FoldedResidualBlock, Norm,
                                    ResidualBlock, FoldedNorm,
                                    FoldedStemConv, conv)


class BasicEncoder(nn.Module):
    output_dim: int = 128
    norm: str = "batch"
    dropout: float = 0.0
    dtype: Any = jnp.float32
    # Run the 64-channel layer1 stage in folded-width layout (column
    # pairs packed into channels -> lane-dense (8, 128) tiles; same math,
    # same param tree — see layers.fold_w).  Auto-disabled when the
    # /2-res width is odd or the norm mode can't fold.
    fold_layer1: bool = True

    @nn.compact
    def __call__(self, x, train: bool = False, freeze_bn: bool = False):
        dt = self.dtype
        x = x.astype(dt)
        stages = [(64, 1), (64, 1), (96, 2), (96, 1), (128, 2), (128, 1)]
        # Stem output width is ceil(W/2) for even W (pad 3, k=7, s=2);
        # folding needs it even, i.e. W % 4 == 0 (InputPadder-padded
        # inputs always are).
        folded = (self.fold_layer1 and x.shape[2] % 4 == 0
                  and self.norm in ("instance", "batch", "none"))
        start = 0
        if folded:
            # Stem emits the folded layout directly — no relayout pass.
            x = FoldedStemConv(3, 64, dt, name="conv1")(x)
            x = FoldedNorm(self.norm, 64, dt, name="norm1")(
                x, train, freeze_bn)
            x = nn.relu(x)
            for i in range(2):
                x = FoldedResidualBlock(64, self.norm, dt,
                                        name=f"layer1_{i}")(
                    x, train, freeze_bn)
            # layer2_0 (stride 2) consumes the folded layout directly —
            # its width step lands exactly on the folded column count,
            # so no unfold relayout is needed anywhere.
            x = FoldedEntryResidualBlock(96, self.norm, dt,
                                         name="layer2_0")(
                x, train, freeze_bn)
            start = 3
        else:
            x = conv(64, 7, 2, dt, name="conv1", in_features=3)(x)
            # stem GroupNorm uses 8 groups, not 64//8 (reference
            # extractor.py:124)
            x = Norm(self.norm, 64, num_groups=8, dtype=dt,
                     name="norm1")(x, train, freeze_bn)
            x = nn.relu(x)
        for i, (planes, stride) in enumerate(stages[start:], start=start):
            x = ResidualBlock(planes, self.norm, stride, dt,
                              name=f"layer{i // 2 + 1}_{i % 2}")(
                x, train, freeze_bn)

        x = conv(self.output_dim, 1, 1, dt, name="conv2", in_features=128)(x)

        if self.dropout > 0:
            x = nn.Dropout(self.dropout, broadcast_dims=(1, 2),
                           deterministic=not train)(x)
        return x


class SmallEncoder(nn.Module):
    output_dim: int = 128
    norm: str = "batch"
    dropout: float = 0.0
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False, freeze_bn: bool = False):
        dt = self.dtype
        x = x.astype(dt)
        x = conv(32, 7, 2, dt, name="conv1", in_features=3)(x)
        x = Norm(self.norm, 32, num_groups=8, dtype=dt, name="norm1")(
            x, train, freeze_bn)
        x = nn.relu(x)

        for i, (planes, stride) in enumerate(
                [(32, 1), (32, 1), (64, 2), (64, 1), (96, 2), (96, 1)]):
            x = BottleneckBlock(planes, self.norm, stride, dt,
                                name=f"layer{i // 2 + 1}_{i % 2}")(
                x, train, freeze_bn)

        x = conv(self.output_dim, 1, 1, dt, name="conv2", in_features=96)(x)

        if self.dropout > 0:
            x = nn.Dropout(self.dropout, broadcast_dims=(1, 2),
                           deterministic=not train)(x)
        return x
