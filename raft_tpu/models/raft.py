"""The RAFT model: encoders + correlation + scanned refinement (NHWC).

TPU-first re-design of the reference's ``core/raft.py``:

- The per-iteration Python loop (raft.py:122-139) becomes a single
  ``flax.linen.scan`` over a shared-weight refinement step — traced once,
  compiled once, with optional rematerialization of the step body
  (``config.remat``) for the backward pass.
- The per-step ``coords1.detach()`` (raft.py:123) becomes
  ``jax.lax.stop_gradient``.
- Frames are encoded with shared weights by stacking them on the batch axis
  (the reference's list-input trick, extractor.py:171-174).
- Mixed precision: encoders and the update block run in
  ``config.compute_dtype`` (bf16 on TPU — replaces the reference's
  torch.cuda.amp autocast + GradScaler, no loss scaling needed for bf16);
  correlation volumes and the coordinate state stay fp32
  (raft.py:102-103, corr.py:50).

API:
  ``model.apply(variables, image1, image2, iters=12)`` ->
      ``(iters, B, H, W, 2)`` stacked per-iteration upsampled flows (train
      mode list at raft.py:144).
  ``test_mode=True`` -> ``(flow_low, flow_up)`` (raft.py:141-142).

Images are NHWC float in [0, 255]; flow is ``(..., 2)`` with ``(x, y)``
channel order matching the reference.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from raft_tpu.config import RAFTConfig
from raft_tpu.models.extractor import BasicEncoder, SmallEncoder
from raft_tpu.models.update import BasicUpdateBlock, SmallUpdateBlock
from raft_tpu.ops.corr import (
    build_corr_pyramid,
    chunked_corr_lookup,
    corr_lookup,
    pool_fmap_pyramid,
)
from raft_tpu.ops.sampler import coords_grid, upflow8
from raft_tpu.ops.upsample import convex_upsample


class RefinementStep(nn.Module):
    """One GRU refinement iteration (the body of the reference's hot loop,
    raft.py:122-139)."""

    config: RAFTConfig

    @nn.compact
    def __call__(self, carry, inputs):
        cfg = self.config
        dt = cfg.dtype
        net, coords1 = carry[0], carry[1]
        inp, coords0, corr_state, loss_targets = inputs

        coords1 = jax.lax.stop_gradient(coords1)

        if cfg.corr_impl == "allpairs":
            corr = corr_lookup(corr_state, coords1, cfg.corr_radius,
                               cfg.corr_precision)
        elif cfg.corr_impl == "chunked":
            fmap1, f2_pyramid = corr_state
            corr = chunked_corr_lookup(fmap1, f2_pyramid, coords1,
                                       cfg.corr_radius,
                                       block_size=cfg.corr_block_size,
                                       precision=cfg.corr_precision)
        elif cfg.corr_impl == "pallas":
            from raft_tpu.ops.pallas_corr import pallas_corr_lookup

            fmap1, f2_pyramid = corr_state
            corr = pallas_corr_lookup(fmap1, tuple(f2_pyramid), coords1,
                                      cfg.corr_radius,
                                      min(cfg.corr_block_size, 128))
        else:
            raise ValueError(f"unknown corr_impl: {cfg.corr_impl!r}")

        flow = coords1 - coords0
        if cfg.small:
            block = SmallUpdateBlock(cfg.hidden_dim, dt, name="update_block")
        else:
            block = BasicUpdateBlock(cfg.hidden_dim, dt, name="update_block")
        net, mask, delta_flow = block(
            net, inp, corr.astype(dt), flow.astype(dt))

        coords1 = coords1 + delta_flow.astype(jnp.float32)
        new_flow = coords1 - coords0

        if mask is None:
            flow_up = upflow8(new_flow)
        else:
            flow_up = convex_upsample(new_flow, mask.astype(jnp.float32))

        if loss_targets is None:
            return (net, coords1), flow_up

        # Fused in-scan loss: reduce each iteration's upsampled flow to a
        # scalar immediately instead of stacking (iters, B, H, W, 2) to
        # HBM (the reference keeps a Python list of full-res flows,
        # train.py:47-60).  Numerics identical to
        # raft_tpu.train.loss.sequence_loss; the last flow rides the
        # carry so metrics are computed once, outside the scan.
        flow_gt, vmask = loss_targets
        abs_err = jnp.abs(flow_up - flow_gt)
        per_iter_loss = jnp.mean(vmask[..., None] * abs_err)
        return (net, coords1, flow_up), per_iter_loss


class RAFT(nn.Module):
    """Full / small RAFT (reference core/raft.py:24-144)."""

    config: RAFTConfig = RAFTConfig()

    @nn.compact
    def __call__(self, image1, image2, iters: int = 12,
                 flow_init: Optional[jax.Array] = None,
                 test_mode: bool = False, train: bool = False,
                 freeze_bn: bool = False,
                 loss_targets: Optional[tuple] = None):
        """``loss_targets``: optional ``(flow_gt (B,H,W,2), valid (B,H,W),
        max_flow)`` — fuses the sequence loss into the refinement scan and
        returns ``(per_iter_losses (iters,), metrics dict of (iters,))``
        instead of stacked flows (training fast path; the γ-weighting is
        applied by the caller)."""
        cfg = self.config
        dt = cfg.dtype
        hdim, cdim = cfg.hidden_dim, cfg.context_dim

        image1 = 2.0 * (image1.astype(jnp.float32) / 255.0) - 1.0
        image2 = 2.0 * (image2.astype(jnp.float32) / 255.0) - 1.0

        # Shared-weight two-frame encode: stack on batch.
        if cfg.small:
            fnet = SmallEncoder(128, "instance", cfg.dropout, dt, name="fnet")
            cnet = SmallEncoder(hdim + cdim, "none", cfg.dropout, dt,
                                name="cnet")
        else:
            fnet = BasicEncoder(256, "instance", cfg.dropout, dt, name="fnet")
            cnet = BasicEncoder(hdim + cdim, "batch", cfg.dropout, dt,
                                name="cnet")

        both = jnp.concatenate([image1, image2], axis=0)
        fmaps = fnet(both.astype(dt), train, freeze_bn)
        B = image1.shape[0]
        fmap1 = fmaps[:B].astype(jnp.float32)
        fmap2 = fmaps[B:].astype(jnp.float32)

        if cfg.corr_impl == "allpairs":
            corr_state = build_corr_pyramid(fmap1, fmap2, cfg.corr_levels,
                                            cfg.corr_precision)
        elif cfg.corr_impl in ("chunked", "pallas"):
            corr_state = (fmap1, pool_fmap_pyramid(fmap2, cfg.corr_levels))
        else:
            raise ValueError(f"unknown corr_impl: {cfg.corr_impl!r}")

        ctx = cnet(image1.astype(dt), train, freeze_bn)
        net = jnp.tanh(ctx[..., :hdim])
        inp = nn.relu(ctx[..., hdim:])

        _, H8, W8, _ = fmap1.shape
        coords0 = coords_grid(B, H8, W8)
        coords1 = coords_grid(B, H8, W8)
        if flow_init is not None:
            coords1 = coords1 + flow_init

        step = RefinementStep
        if cfg.remat:
            if cfg.remat_policy == "dots":
                step = nn.remat(
                    RefinementStep,
                    policy=jax.checkpoint_policies.dots_saveable)
            elif cfg.remat_policy == "full":
                step = nn.remat(RefinementStep)
            else:
                raise ValueError(
                    f"unknown remat_policy: {cfg.remat_policy!r} "
                    "(expected 'full' or 'dots')")
        scan = nn.scan(
            step,
            variable_broadcast="params",
            split_rngs={"params": False, "dropout": True},
            in_axes=nn.broadcast,
            out_axes=0,
            length=iters,
            unroll=cfg.scan_unroll,
        )(cfg, name="refine")

        if loss_targets is not None:
            from raft_tpu.train.loss import combined_valid

            flow_gt, valid, max_flow = loss_targets
            valid01 = combined_valid(flow_gt, valid, max_flow)
            lt = (flow_gt.astype(jnp.float32), valid01)
            carry0 = (net, coords1,
                      jnp.zeros(image1.shape[:-1] + (2,), jnp.float32))
            (_, _, last_flow), per_iter = scan(
                carry0, (inp, coords0, corr_state, lt))
            # (per-iteration loss scalars, last upsampled flow)
            return per_iter, last_flow

        (net, coords1), outs = scan(
            (net, coords1), (inp, coords0, corr_state, None))
        if test_mode:
            return coords1 - coords0, outs[-1]
        return outs
