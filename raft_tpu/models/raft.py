"""The RAFT model: encoders + correlation + scanned refinement (NHWC).

TPU-first re-design of the reference's ``core/raft.py``:

- The per-iteration Python loop (raft.py:122-139) becomes a single
  ``flax.linen.scan`` over a shared-weight refinement step — traced once,
  compiled once, with optional rematerialization of the step body
  (``config.remat``) for the backward pass.
- The per-step ``coords1.detach()`` (raft.py:123) becomes
  ``jax.lax.stop_gradient``.
- Frames are encoded with shared weights by stacking them on the batch axis
  (the reference's list-input trick, extractor.py:171-174).
- Mixed precision: encoders and the update block run in
  ``config.compute_dtype`` (bf16 on TPU — replaces the reference's
  torch.cuda.amp autocast + GradScaler, no loss scaling needed for bf16);
  correlation volumes and the coordinate state stay fp32
  (raft.py:102-103, corr.py:50).
- The convex-upsample stage (mask head + 8x upsample, raft.py:127-137) is
  **hoisted out of the refinement scan**: the mask depends only on the
  GRU state, so training runs it as a second lightweight scan over the
  stacked per-iteration ``(net, flow)`` pairs, two iterations per step
  (outside the remat'd heavy body), and inference applies it to the final
  iteration only — the reference pays the mask head + upsample every
  test-mode iteration (raft.py:122-139) for outputs it throws away
  (+30% measured on 32-iter Sintel-shape eval).

API:
  ``model.apply(variables, image1, image2, iters=12)`` ->
      ``(iters, B, H, W, 2)`` stacked per-iteration upsampled flows (train
      mode list at raft.py:144).
  ``test_mode=True`` -> ``(flow_low, flow_up)`` (raft.py:141-142).

Images are NHWC float in [0, 255]; flow is ``(..., 2)`` with ``(x, y)``
channel order matching the reference.
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name

from raft_tpu.config import RAFTConfig
from raft_tpu.models.extractor import BasicEncoder, SmallEncoder
from raft_tpu.models.update import (BasicUpdateBlock, FusedCorrLookup,
                                    MaskHead, SmallUpdateBlock)
from raft_tpu.ops.corr import (
    QuantizedLevel,
    build_corr_pyramid,
    build_corr_pyramid_flat,
    chunked_corr_lookup,
    corr_lookup,
    pool_fmap_pyramid,
)
from raft_tpu.ops.sampler import coords_grid, upflow8
from raft_tpu.ops.upsample import (convex_upsample, convex_upsample_flat,
                                   space_to_depth_flow)


def _remat_wrap(target, cfg):
    """Apply ``cfg.remat`` / ``cfg.remat_policy`` to a scan body (module
    class or function form) — one dispatch shared by both training scan
    shapes so a policy change can't silently diverge them."""
    if not cfg.remat:
        return target
    if cfg.remat_policy == "dots":
        return nn.remat(target,
                        policy=jax.checkpoint_policies.dots_saveable)
    if cfg.remat_policy == "save_corr":
        return nn.remat(
            target,
            policy=jax.checkpoint_policies.save_only_these_names(
                "corr", "motion"))
    if cfg.remat_policy == "save_corr_upsample":
        # For the single-scan fused path (``fuse_upsample_in_scan``):
        # additionally save the mask-head logits, so the backward does
        # not re-run the 128->256->576 mask convs per iteration — the
        # recompute that made fused+save_corr 13% SLOWER than two scans
        # at the things crop in round 3 (~40-47 MB bf16 per iteration of
        # saves at stage crops; the softmax/FMA upsample chain itself
        # still recomputes).
        return nn.remat(
            target,
            policy=jax.checkpoint_policies.save_only_these_names(
                "corr", "motion", "mask"))
    if cfg.remat_policy == "full":
        return nn.remat(target)
    raise ValueError(f"unknown remat_policy: {cfg.remat_policy!r} "
                     "(expected 'full', 'dots', 'save_corr' or "
                     "'save_corr_upsample')")


class RefinementStep(nn.Module):
    """One GRU refinement iteration (the body of the reference's hot loop,
    raft.py:122-131; the upsample half of that loop lives in
    :class:`UpsampleStep`)."""

    config: RAFTConfig

    @nn.compact
    def __call__(self, carry, inputs):
        cfg = self.config
        dt = cfg.dtype
        net, coords1 = carry
        inp, coords0, corr_state = inputs

        coords1 = jax.lax.stop_gradient(coords1)

        corr_impl = cfg.resolved_corr_impl
        if cfg.resolved_fused_lookup_encoder:
            # Defer the lookup INTO the motion encoder: the fused Pallas
            # kernel (ops/pallas_corr.pallas_pyramid_lookup_encode)
            # samples the pyramid and applies convc1 in one VMEM pass —
            # the (B, H/8, W/8, corr_planes) tap tensor never reaches
            # HBM.  coords1 is already detached above, and the fused
            # kernel's vjp preserves the unfused gradient semantics
            # (real dcorr for fp32/bf16 pyramids, the stop-gradient
            # zeros for quantized ones).  The 'corr' remat tag moves to
            # the fused conv output inside the encoder.
            corr = FusedCorrLookup(
                pyramid=corr_state, coords=coords1,
                channels=cfg.corr_planes, radius=cfg.corr_radius,
                block_q=cfg.lookup_block_q)
        elif corr_impl == "allpairs":
            corr = corr_lookup(corr_state, coords1, cfg.corr_radius,
                               cfg.resolved_corr_precision)
        elif corr_impl == "chunked":
            fmap1, f2_pyramid = corr_state
            corr = chunked_corr_lookup(fmap1, f2_pyramid, coords1,
                                       cfg.corr_radius,
                                       block_size=cfg.corr_block_size,
                                       precision=cfg.resolved_corr_precision)
        elif corr_impl == "allpairs_pallas":
            if isinstance(corr_state[0], QuantizedLevel):
                from raft_tpu.ops.pallas_corr import \
                    pallas_pyramid_lookup_quantized

                # Same kernel, int8/fp8 codes on the load path, dequant
                # fused onto the tap output (linear sampling); no
                # custom_vjp — the quantize boundary upstream is
                # stop_gradient'd, so the lookup is primal-only.
                corr = pallas_pyramid_lookup_quantized(
                    corr_state, coords1, cfg.corr_radius,
                    cfg.lookup_block_q, None, dt)
            else:
                from raft_tpu.ops.pallas_corr import pallas_pyramid_lookup

                # Taps are consumed in cfg.dtype (the astype below) — emit
                # them in that dtype from the kernel and skip the fp32
                # round-trip through HBM (np.dtype is hashable, so it works
                # as a custom_vjp static arg).
                corr = pallas_pyramid_lookup(corr_state, coords1,
                                             cfg.corr_radius,
                                             cfg.lookup_block_q, None, dt)
        elif corr_impl == "pallas":
            from raft_tpu.ops.pallas_corr import pallas_corr_lookup

            fmap1, f2_pyramid = corr_state
            corr = pallas_corr_lookup(fmap1, tuple(f2_pyramid), coords1,
                                      cfg.corr_radius,
                                      min(cfg.corr_block_size, 128))
        else:
            raise ValueError(f"unknown corr_impl: {cfg.corr_impl!r}")

        # Tag the sampled window features so remat_policy='save_corr' can
        # keep them (and only them) for the backward pass: the window
        # sampling is ~half the forward iteration, and its taps are small
        # (B, H/8, W/8, levels*(2r+1)^2).  (On the fused-lookup path the
        # taps never materialize; the encoder tags the fused conv output
        # instead.)
        if not isinstance(corr, FusedCorrLookup):
            corr = checkpoint_name(corr.astype(dt), "corr")

        flow = coords1 - coords0
        fused_gru = cfg.resolved_fused_gru
        if cfg.small:
            block = SmallUpdateBlock(cfg.hidden_dim, dt,
                                     fused_gru=fused_gru,
                                     name="update_block")
        else:
            block = BasicUpdateBlock(cfg.hidden_dim, dt,
                                     fused_gru=fused_gru,
                                     name="update_block")
        net, delta_flow = block(net, inp, corr, flow.astype(dt))

        coords1 = coords1 + delta_flow.astype(jnp.float32)
        new_flow = coords1 - coords0
        return (net, coords1), (net, new_flow)


class UpsampleStep(nn.Module):
    """Mask head + convex upsample (full model; the second half of the
    reference's loop body, raft.py:127-137).

    Scanned over the stacked ``(net, flow)`` pairs in iteration groups for
    training; called once on the final pair for inference.  The carry is
    unused (scan plumbing only).
    """

    config: RAFTConfig

    @nn.compact
    def __call__(self, carry, net, flow):
        cfg = self.config
        mask = MaskHead(cfg.hidden_dim, cfg.dtype, name="mask_head")(net)
        flow_up = convex_upsample(flow, mask.astype(jnp.float32))
        return carry, flow_up


class UpsampleLossStep(nn.Module):
    """Mask head + FLAT convex upsample + masked L1/EPE partial sums.

    The training-path replacement for :class:`UpsampleStep`: per-iteration
    upsampled flows are produced in space-to-depth ``(c, p, q)`` channel
    layout (:func:`convex_upsample_flat`) and compared against the
    space-to-depth ground truth *inside the scan*, so the only per-
    iteration outputs are five scalars — the full-resolution
    ``(B, 8H, 8W, 2)`` tensors (280 MB/step stacked, plus their pathological
    6-D layouts) never reach HBM.  Shares the ``mask_head`` parameter
    scope with :class:`UpsampleStep` (same tree, checkpoint-compatible).

    Inputs per scan step: ``net, flow`` with ``g`` iterations folded into
    batch; broadcast: ``gt128 (B, H, W, 128)``, ``vmask64 (B, H, W, 64)``.
    Emits ``(g, 5)``: ``[l1_sum, epe_sum, 1px_sum, 3px_sum, 5px_sum]``
    per folded iteration (sums over masked elements; the caller
    normalizes — reference loss semantics train.py:47-72).
    """

    config: RAFTConfig

    @nn.compact
    def __call__(self, carry, net, flow, gt128, vmask64):
        cfg = self.config
        udt = jnp.dtype(cfg.resolved_upsample_dtype)
        B = gt128.shape[0]
        g = net.shape[0] // B
        mask = MaskHead(cfg.hidden_dim, cfg.dtype, name="mask_head")(net)
        # Tagged so remat_policy='save_corr_upsample' can pin the logits
        # (no-op under the other policies / outside remat).
        mask = checkpoint_name(mask, "mask")
        if cfg.resolved_upsample_loss_kernel == "pallas":
            from raft_tpu.ops.pallas_upsample import \
                pallas_upsample_loss_sums

            sums = pallas_upsample_loss_sums(flow, mask, gt128, vmask64)
            return carry, jnp.sum(sums.reshape(g, B, 5), axis=1)
        if cfg.resolved_upsample_loss_kernel != "xla":
            raise ValueError(
                f"unknown upsample_loss_kernel: "
                f"{cfg.upsample_loss_kernel!r} (expected 'xla' or "
                "'pallas')")
        out = convex_upsample_flat(flow, mask,
                                   compute_dtype=udt)  # (gB, H, W, 128)
        # The ground-truth COMPARE always runs fp32: with both sides in
        # bf16, |out - gt| under ~0.2% of the flow magnitude rounds both
        # operands to the same value (bf16 ulp at a 400-px KITTI flow is
        # 2 px), dx becomes exactly 0 and those pixels stop producing L1
        # gradient — sub-pixel convergence would stall exactly where
        # RAFT's precision matters.  With gt full-precision, out's own
        # rounding only SHIFTS dx (sign noise ~1 ulp, no dead zone).
        # The expensive part (the 9-tap softmax/FMA chain) still runs in
        # ``udt``.
        out = out.astype(jnp.float32).reshape((g, B) + out.shape[1:])
        dx = out[..., :64] - gt128[None, ..., :64]
        dy = out[..., 64:] - gt128[None, ..., 64:]
        vm = vmask64[None]
        # Sums always accumulate fp32 (5.8M terms at training shapes —
        # bf16 accumulation would lose the loss signal entirely).
        def _fsum(x):
            return jnp.sum(x, axis=(1, 2, 3, 4), dtype=jnp.float32)
        l1 = _fsum(vm * (jnp.abs(dx) + jnp.abs(dy)))
        # Metrics need no gradient; without stop_gradient the sqrt's
        # derivative at exactly-zero dx²+dy² injects inf·0 = NaN into
        # the remat'd backward even though the metric cotangents are
        # zero.
        dx = jax.lax.stop_gradient(dx)
        dy = jax.lax.stop_gradient(dy)
        epe = jnp.sqrt(dx * dx + dy * dy)
        sums = jnp.stack([
            l1,
            _fsum(vm * epe),
            _fsum(vm * (epe < 1.0)),
            _fsum(vm * (epe < 3.0)),
            _fsum(vm * (epe < 5.0)),
        ], axis=-1)                                   # (g, 5)
        return carry, sums


def _make_encoders(cfg: RAFTConfig):
    """Construct the two shared-weight encoders with their canonical
    scope names (``fnet``/``cnet``).  Called from inside a compact
    method; used by both :class:`RAFT` and the slot-serving
    :class:`RAFTEncode` so the param tree cannot drift between them."""
    dt = cfg.dtype
    hdim, cdim = cfg.hidden_dim, cfg.context_dim
    if cfg.small:
        fnet = SmallEncoder(128, "instance", cfg.dropout, dt, name="fnet")
        cnet = SmallEncoder(hdim + cdim, "none", cfg.dropout, dt,
                            name="cnet")
    else:
        fnet = BasicEncoder(256, "instance", cfg.dropout, dt, name="fnet")
        cnet = BasicEncoder(hdim + cdim, "batch", cfg.dropout, dt,
                            name="cnet")
    return fnet, cnet


def _build_corr_state(cfg: RAFTConfig, fmap1, fmap2):
    """Correlation state from a pair of fp32 feature maps, dispatched on
    ``corr_impl``.  Shared by the cold encode (:func:`_encode_state`)
    and the streaming warm encode (:class:`RAFTEncodeWarm`, which feeds
    a *carried* ``fmap1`` from the previous frame), so the corr-state
    pytree structure cannot drift between the two admit programs."""
    corr_impl = cfg.resolved_corr_impl
    if corr_impl == "allpairs":
        # corr_dtype (storage) applies here too: the XLA lookup
        # re-accumulates fp32 in _sample_windows regardless.
        return build_corr_pyramid(
            fmap1, fmap2, cfg.corr_levels, cfg.resolved_corr_precision,
            out_dtype=jnp.dtype(cfg.resolved_corr_dtype))
    if corr_impl == "allpairs_pallas":
        return build_corr_pyramid_flat(
            fmap1, fmap2, cfg.corr_levels, cfg.resolved_corr_precision,
            pad_q=cfg.lookup_block_q,
            out_dtype=jnp.dtype(cfg.resolved_corr_dtype))
    if corr_impl in ("chunked", "pallas"):
        if cfg.corr_dtype_is_quantized:
            raise ValueError(
                f"corr_dtype={cfg.resolved_corr_dtype!r} requires a "
                "materialized pyramid (corr_impl 'allpairs' or "
                "'allpairs_pallas'); the on-demand "
                f"{corr_impl!r} path never stores the volume, so "
                "there is nothing to quantize")
        return (fmap1, pool_fmap_pyramid(fmap2, cfg.corr_levels))
    raise ValueError(f"unknown corr_impl: {cfg.corr_impl!r}")


def _encode_state(cfg: RAFTConfig, fnet, cnet, image1, image2, train,
                  freeze_bn, flow_init=None):
    """The pre-scan half of the forward pass: normalize → shared-weight
    two-frame encode → correlation state → context split → initial
    coordinate grids.  One body shared by :meth:`RAFT.__call__` and the
    iteration-granular serving split (:class:`RAFTEncode`), so the
    slot-mode parity pin (bit-identical to request mode) is structural
    rather than a copy that has to be kept in sync."""
    dt = cfg.dtype
    hdim = cfg.hidden_dim

    image1 = 2.0 * (image1.astype(jnp.float32) / 255.0) - 1.0
    image2 = 2.0 * (image2.astype(jnp.float32) / 255.0) - 1.0

    # Shared-weight two-frame encode: stack on batch.
    both = jnp.concatenate([image1, image2], axis=0)
    fmaps = fnet(both.astype(dt), train, freeze_bn)
    B = image1.shape[0]
    fmap1 = fmaps[:B].astype(jnp.float32)
    fmap2 = fmaps[B:].astype(jnp.float32)

    corr_state = _build_corr_state(cfg, fmap1, fmap2)

    ctx = cnet(image1.astype(dt), train, freeze_bn)
    net = jnp.tanh(ctx[..., :hdim])
    inp = nn.relu(ctx[..., hdim:])

    _, H8, W8, _ = fmap1.shape
    coords0 = coords_grid(B, H8, W8)
    coords1 = coords_grid(B, H8, W8)
    if flow_init is not None:
        coords1 = coords1 + flow_init
    return net, inp, coords0, coords1, corr_state


class RAFT(nn.Module):
    """Full / small RAFT (reference core/raft.py:24-144)."""

    config: RAFTConfig = RAFTConfig()

    @nn.compact
    def __call__(self, image1, image2, iters: int = 12,
                 flow_init: Optional[jax.Array] = None,
                 test_mode: bool = False, train: bool = False,
                 freeze_bn: bool = False,
                 loss_targets: Optional[tuple] = None):
        """``loss_targets``: optional ``(flow_gt (B,H,W,2), valid (B,H,W),
        max_flow)`` — computes the per-iteration L1 terms in-model (in
        space-to-depth layout; the full-res per-iteration flows never
        reach HBM) and returns ``(per_iter_losses (iters,), metrics
        dict)`` instead of stacked flows (the γ-weighting is applied by
        the caller)."""
        cfg = self.config

        fnet, cnet = _make_encoders(cfg)
        net, inp, coords0, coords1, corr_state = _encode_state(
            cfg, fnet, cnet, image1, image2, train, freeze_bn, flow_init)
        B = image1.shape[0]

        if (loss_targets is not None and not cfg.small and not test_mode
                and cfg.fuse_upsample_in_scan):
            return self._fused_inscan_losses(cfg, iters, net, inp, coords0,
                                             coords1, corr_state,
                                             loss_targets)

        step = _remat_wrap(RefinementStep, cfg)
        scan = nn.scan(
            step,
            variable_broadcast="params",
            split_rngs={"params": False, "dropout": True},
            in_axes=nn.broadcast,
            out_axes=0,
            length=iters,
            unroll=cfg.scan_unroll,
        )(cfg, name="refine")

        (net, coords1), (nets, flows) = scan(
            (net, coords1), (inp, coords0, corr_state))

        # --- Upsample stage (outside the heavy scan) ---
        if cfg.small:
            # No mask head: bilinear upflow8 (reference raft.py:134-135).
            return self._small_outputs(flows, coords1 - coords0,
                                       test_mode, loss_targets)

        if test_mode:
            # Only the final iteration's flow is returned in test mode
            # (raft.py:141-142) — upsample just that one.
            flow_low = coords1 - coords0
            up = UpsampleStep(cfg, name="upsampler")
            _, flow_up = up(None, net, flow_low)
            return flow_low, flow_up

        # Grouped upsample: fold groups of iterations into the batch axis
        # so the mask-head convs and the convex-combination einsum run at
        # g*B batch while the scan over groups keeps the full-res
        # transients bounded.  Measured on v5e (batch 12, 368x496, bf16):
        # g=1 13.6-13.8, g=2 14.4, g=3 13.9, g=4 14.1, g=6 12.8
        # pairs/s/chip; all-at-once (g=12) needs ~29 GB HBM and OOMs.
        # Rematerialized (cfg.remat_upsample): the backward keeps only the
        # stacked (iters, B, H/8, W/8, hdim) GRU states and recomputes two
        # convs + a softmax per group.
        I = iters
        # Largest divisor of I that is <= upsample_group (clamped to
        # [1, I] so misconfigured knobs degrade instead of raising a
        # bare StopIteration from inside the trace).
        g = next(g for g in range(max(1, min(cfg.upsample_group, I)), 0,
                                  -1)
                 if I % g == 0)
        nets_r = nets.reshape((I // g, g * B) + nets.shape[2:])
        flows_r = flows.reshape((I // g, g * B) + flows.shape[2:])

        if loss_targets is not None:
            # Sequence loss fused into the upsample scan: the full-res
            # per-iteration flows never reach HBM (see UpsampleLossStep).
            from raft_tpu.train.loss import combined_valid

            flow_gt, valid, max_flow = loss_targets
            vmask = combined_valid(flow_gt, valid, max_flow)
            gt128 = space_to_depth_flow(flow_gt.astype(jnp.float32))
            vmask64 = space_to_depth_flow(vmask[..., None])
            up_step = UpsampleLossStep
            if cfg.remat_upsample:
                up_step = nn.remat(UpsampleLossStep)
            up_scan = nn.scan(
                up_step,
                variable_broadcast="params",
                split_rngs={"params": False, "dropout": True},
                in_axes=(0, 0, nn.broadcast, nn.broadcast),
                out_axes=0,
                length=I // g,
                unroll=max(1, min(cfg.upsample_unroll, I // g)),
            )(cfg, name="upsampler")
            _, sums = up_scan(None, nets_r, flows_r, gt128, vmask64)
            return self._loss_outputs(sums.reshape(I, 5), gt128, vmask64, B)

        up_step = UpsampleStep
        if cfg.remat_upsample:
            up_step = nn.remat(UpsampleStep)
        up_scan = nn.scan(
            up_step,
            variable_broadcast="params",
            split_rngs={"params": False, "dropout": True},
            in_axes=0,
            out_axes=0,
            length=I // g,
            unroll=max(1, min(cfg.upsample_unroll, I // g)),
        )(cfg, name="upsampler")
        _, flow_ups = up_scan(None, nets_r, flows_r)
        flow_ups = flow_ups.reshape((I, B) + flow_ups.shape[2:])
        return flow_ups

    @staticmethod
    def _loss_outputs(sums, gt128, vmask64, B):
        """Normalize the per-iteration ``(iters, 5)`` partial sums into
        per-iteration mean losses + final-iteration metrics (reference
        sequence_loss semantics, train.py:47-72).  The per-iteration EPE
        sums the scan already produced become the refinement-convergence
        curve (``epe_iter``, docs/OBSERVABILITY.md) for free — no extra
        compute, it rides the metrics dict to the host at Logger
        cadence."""
        _, H8s, W8s, _ = gt128.shape
        n_all = B * H8s * W8s * 128              # loss mean incl. zeroed
        n_valid = jnp.maximum(jnp.sum(vmask64), 1.0)
        per_iter = sums[:, 0] / n_all
        metrics = {"epe": sums[-1, 1] / n_valid,
                   "1px": sums[-1, 2] / n_valid,
                   "3px": sums[-1, 3] / n_valid,
                   "5px": sums[-1, 4] / n_valid,
                   "epe_iter": sums[:, 1] / n_valid}
        return per_iter, metrics

    def _fused_inscan_losses(self, cfg, iters, net, inp, coords0, coords1,
                             corr_state, loss_targets):
        """Single-scan training path (``cfg.fuse_upsample_in_scan``): the
        refinement step AND the mask head + flat convex upsample + loss
        sums run in ONE scan body, so the per-iteration GRU states are
        consumed in place instead of being stacked to HBM and re-read by
        a second scan (~1.1 GB/step of stacking traffic at chairs batch
        16).  The function-form ``nn.scan`` binds the same ``refine`` /
        ``upsampler`` scopes as the two-scan path, so the param tree —
        and every checkpoint — is identical."""
        from raft_tpu.train.loss import combined_valid

        flow_gt, valid, max_flow = loss_targets
        B = flow_gt.shape[0]
        vmask = combined_valid(flow_gt, valid, max_flow)
        gt128 = space_to_depth_flow(flow_gt.astype(jnp.float32))
        vmask64 = space_to_depth_flow(vmask[..., None])

        def body(mdl, carry, _):
            carry, (net_i, flow_i) = RefinementStep(cfg, name="refine")(
                carry, (inp, coords0, corr_state))
            _, sums = UpsampleLossStep(cfg, name="upsampler")(
                None, net_i, flow_i, gt128, vmask64)
            return carry, sums[0]

        body = _remat_wrap(body, cfg)
        scan = nn.scan(
            body,
            variable_broadcast="params",
            split_rngs={"params": False, "dropout": True},
            length=iters,
            unroll=cfg.scan_unroll,
        )
        _, sums = scan(self, (net, coords1), None)
        return self._loss_outputs(sums, gt128, vmask64, B)

    def _small_outputs(self, flows, flow_low, test_mode, loss_targets):
        """Small-model upsampling: parameter-free ``upflow8`` applied to
        the stacked low-res flows (vectorized over iterations)."""
        if test_mode:
            return flow_low, upflow8(flows[-1])
        I, B, H8, W8, _ = flows.shape
        if loss_targets is None:
            up = upflow8(flows.reshape(I * B, H8, W8, 2))
            return up.reshape(I, B, H8 * 8, W8 * 8, 2)

        from raft_tpu.train.loss import combined_valid, flow_metrics

        flow_gt, valid, max_flow = loss_targets
        vmask = combined_valid(flow_gt, valid, max_flow)
        n_valid = jnp.maximum(jnp.sum(vmask), 1.0)

        def body(carry, flow):
            fu = upflow8(flow)
            loss = jnp.mean(vmask[..., None] * jnp.abs(fu - flow_gt))
            diff = jax.lax.stop_gradient(fu - flow_gt)  # metric: no grad
            epe = jnp.sum(vmask * jnp.sqrt(jnp.sum(diff ** 2, -1))
                          ) / n_valid
            return fu, (loss, epe)

        last_flow, (per_iter, epe_iter) = jax.lax.scan(
            body, jnp.zeros(flow_gt.shape, jnp.float32), flows)
        metrics = dict(flow_metrics(last_flow, flow_gt, vmask),
                       epe_iter=epe_iter)
        return per_iter, metrics


# ---------------------------------------------------------------------------
# Iteration-granular serving split (continuous batching)
# ---------------------------------------------------------------------------
#
# The slot-based serve path (serve/slots.py) runs the forward pass as
# two separately-jitted programs instead of one: ``encode`` (everything
# before the refinement scan) and one refinement iteration at a time
# (so requests can join/leave the device batch between iterations, and
# converged samples can exit early).  The modules below bind the
# SAME parameter scopes as :class:`RAFT` — ``fnet``/``cnet``/``refine``/
# ``upsampler`` — so a variables tree from ``RAFT.init`` (or any
# checkpoint) applies unchanged; extra subtrees a given program does not
# touch are simply never read.  The math is the scan body applied once.
#
# BOTH serve batching modes consume these same compiled programs —
# ``batching=request`` drives them in whole-batch lockstep, ``slot``
# continuously — which is what makes the slot-vs-request bitwise parity
# pin (tests/test_serve_slots.py) structural: XLA:CPU specializes
# reduction/fusion order to the surrounding program, so the same math
# compiled into two DIFFERENT programs can differ in the last ulp (the
# encoder's instance-norm and the corr einsum both do, measured ~1e-5
# relative — ``optimization_barrier`` does not pin it).  Sharing one
# executable chain sidesteps the whole class of drift.


class RAFTEncode(nn.Module):
    """Pre-scan half of the forward pass as a standalone program:
    ``(image1, image2) -> (net, inp, coords0, coords1, corr_state)``.

    Per-sample independent in inference mode (instance norm; batch norm
    runs on stored statistics), so lanes of a slot batch can be encoded
    together with ballast and scattered into slots without affecting
    each other."""

    config: RAFTConfig = RAFTConfig()

    @nn.compact
    def __call__(self, image1, image2,
                 flow_init: Optional[jax.Array] = None):
        fnet, cnet = _make_encoders(self.config)
        return _encode_state(self.config, fnet, cnet, image1, image2,
                             False, False, flow_init)


class RAFTFrameFeatures(nn.Module):
    """Single-frame feature stash for streaming sessions:
    ``image -> (fmap (fp32), ctx (model dtype))``.

    A streamed pair shares its first frame with the previous pair's
    second frame (consecutive-frame identity), so serving carries that
    frame's feature map AND its raw context-encoder output
    device-resident in the lane.  ``fmap`` feeds the next pair's corr
    build as ``fmap1``; ``ctx`` is split tanh/relu into ``net``/``inp``
    at warm-encode time (the split is cheap, the conv stack is not).
    Binds the same ``fnet``/``cnet`` scopes as :class:`RAFT`, inference
    mode, identical normalization to :func:`_encode_state`."""

    config: RAFTConfig = RAFTConfig()

    @nn.compact
    def __call__(self, image):
        cfg = self.config
        fnet, cnet = _make_encoders(cfg)
        image = 2.0 * (image.astype(jnp.float32) / 255.0) - 1.0
        fmap = fnet(image.astype(cfg.dtype), False, False)
        ctx = cnet(image.astype(cfg.dtype), False, False)
        return fmap.astype(jnp.float32), ctx


class RAFTEncodeWarm(nn.Module):
    """Warm-start encode for streamed frame N+1: only the NEW frame
    runs through the feature encoder — the carried previous-frame
    features stand in for frame 1 of the pair.

    ``(image2, fmap1, ctx1, flow_init) -> (net, inp, coords0, coords1,
    corr_state, fmap2, ctx2)`` where ``fmap1``/``ctx1`` are the carry
    stashed when the previous frame was encoded (its ``fmap2``/its
    :class:`RAFTFrameFeatures` ctx), ``flow_init`` is the previous
    pair's forward-warped flow (added to the ``coords1`` grid exactly
    like :func:`_encode_state`), and the returned ``fmap2``/``ctx2``
    are the NEXT carry.  Per warm frame the encoders run once each
    (fnet + cnet on the new frame) versus three conv-stack passes for a
    cold pair (fnet twice + cnet) — the fnet work per frame is halved,
    which the cost model exposes as ``wenc`` vs ``enc``
    flops-per-pair."""

    config: RAFTConfig = RAFTConfig()

    @nn.compact
    def __call__(self, image2, fmap1, ctx1, flow_init):
        cfg = self.config
        hdim = cfg.hidden_dim
        fnet, cnet = _make_encoders(cfg)
        image2 = 2.0 * (image2.astype(jnp.float32) / 255.0) - 1.0
        fmap2 = fnet(image2.astype(cfg.dtype), False, False)
        ctx2 = cnet(image2.astype(cfg.dtype), False, False)
        fmap2 = fmap2.astype(jnp.float32)

        corr_state = _build_corr_state(cfg, fmap1, fmap2)

        net = jnp.tanh(ctx1[..., :hdim])
        inp = nn.relu(ctx1[..., hdim:])

        B, H8, W8, _ = fmap1.shape
        coords0 = coords_grid(B, H8, W8)
        coords1 = coords_grid(B, H8, W8) + flow_init
        return net, inp, coords0, coords1, corr_state, fmap2, ctx2


class RAFTIterStep(nn.Module):
    """One GRU refinement iteration as a standalone program — exactly
    the scanned body (:class:`RefinementStep` under the ``refine``
    scope, which ``variable_broadcast='params'`` leaves un-stacked, so
    single application binds the identical tree).  The step is wrapped
    with the same ``cfg.remat`` policy as the training/inference scan:
    remat changes the compiled graph, and the slot-mode parity pin
    requires the identical program body, not just identical weights."""

    config: RAFTConfig = RAFTConfig()

    @nn.compact
    def __call__(self, net, coords1, inp, coords0, corr_state):
        step = _remat_wrap(RefinementStep, self.config)
        (net, coords1), _ = step(self.config, name="refine")(
            (net, coords1), (inp, coords0, corr_state))
        return net, coords1


class RAFTUpsample(nn.Module):
    """Final upsample as a standalone program: ``(net, flow_low) ->
    flow_up`` — :class:`UpsampleStep` under the ``upsampler`` scope for
    the full model, parameter-free ``upflow8`` for the small one (same
    dispatch as the test-mode tail of :meth:`RAFT.__call__`)."""

    config: RAFTConfig = RAFTConfig()

    @nn.compact
    def __call__(self, net, flow_low):
        cfg = self.config
        if cfg.small:
            return upflow8(flow_low)
        up = UpsampleStep(cfg, name="upsampler")
        _, flow_up = up(None, net, flow_low)
        return flow_up
