"""Recurrent update blocks (NHWC).

Re-designs the reference's ``core/update.py``: motion encoder + ConvGRU +
flow head (+ convex-upsample mask head in the full model).  All convs are
NHWC; the GRU is the natural ``lax.scan`` body (driven from the RAFT model).

Parity notes:
- The mask head output is scaled by 0.25 ("to balence gradients",
  update.py:123-125).
- ``BasicMotionEncoder`` emits 126 channels and appends the raw 2-channel
  flow -> 128 (update.py:91-97); the small variant emits 80 + 2 -> 82
  (update.py:70-77).
- ``SepConvGRU`` runs a horizontal (1x5) then vertical (5x1) GRU pass
  (update.py:33-60).
- Init: the reference applies kaiming only to the encoders; update-block
  convs keep torch's *default* Conv2d init — reproduced via
  ``torch_default_init``.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import flax.linen as nn
import jax.numpy as jnp

from jax.ad_checkpoint import checkpoint_name

from raft_tpu.models.layers import (_torch_default_uniform, conv,
                                    torch_bias_init)


def _tconv(features, kernel, cin, dtype, name):
    """Conv with torch's *default* init (the reference applies kaiming only
    to the encoders; update-block convs keep torch defaults)."""
    return conv(features, kernel, 1, dtype, name=name,
                torch_default_init=True, in_features=cin)


@dataclasses.dataclass
class FusedCorrLookup:
    """Deferred correlation lookup (``fused_lookup_encoder`` path).

    When ``RAFTConfig.resolved_fused_lookup_encoder`` is on, the
    refinement step hands the motion encoder THIS instead of the
    materialized ``(B, H/8, W/8, levels*(2r+1)^2)`` corr-feature tensor;
    the encoder then runs ``ops/pallas_corr.pallas_pyramid_lookup_encode``,
    which samples the pyramid AND applies convc1 (+bias+relu) in one
    Pallas kernel — the tap tensor never reaches HBM.  Plain Python
    container (not a pytree): it is built and consumed inside one scan
    body trace, never crossing a jit/scan boundary itself.
    """

    pyramid: Any        # list of per-level arrays or QuantizedLevel
    coords: Any         # (B, H/8, W/8, 2) fp32 lookup centers
    channels: int       # levels * (2r+1)^2 == convc1 fan-in
    radius: int
    block_q: int
    interpret: Any = None   # None = auto (TPU native / CPU interpreter)


class _Conv1x1Params(nn.Module):
    """Declare a 1x1 conv's parameters without applying it.

    Instantiated under the SAME scope name ("convc1") and with the same
    init/shape/dtype conventions as ``_tconv``'s ``nn.Conv``, so the
    param tree — and any torch-converted checkpoint — is interchangeable
    between the fused and unfused motion-encoder paths.
    """

    features: int
    cin: int

    @nn.compact
    def __call__(self):
        kernel = self.param("kernel", _torch_default_uniform,
                            (1, 1, self.cin, self.features))
        bias = self.param("bias", torch_bias_init(self.cin),
                          (self.features,))
        return kernel, bias


def _fused_corr_encode(fused: "FusedCorrLookup", kernel, bias, features,
                       dtype):
    """convc1(lookup(pyramid)) + relu via the fused Pallas kernel."""
    from raft_tpu.ops.pallas_corr import pallas_pyramid_lookup_encode

    cor = pallas_pyramid_lookup_encode(
        fused.pyramid, fused.coords,
        kernel.reshape(fused.channels, features), bias,
        fused.radius, fused.block_q, fused.interpret, jnp.dtype(dtype))
    # Same remat tag the unfused path puts on the sampled taps
    # (remat_policy='save_corr'): saving the fused conv output skips
    # both the re-lookup and the conv in the backward recompute.
    return checkpoint_name(cor, "corr")


class FlowHead(nn.Module):
    hidden_dim: int = 256
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        cin = x.shape[-1]
        x = nn.relu(_tconv(self.hidden_dim, 3, cin, self.dtype, "conv1")(x))
        return _tconv(2, 3, self.hidden_dim, self.dtype, "conv2")(x)


class ConvGRU(nn.Module):
    """The z and r gates read the same input, so their two convs are fused
    into one double-width conv + split (identical math; the torch->flax
    converter concatenates the reference's convz/convr kernels on the
    output axis).  Wider output channels keep the MXU busier than two
    narrow convs."""

    hidden_dim: int = 128
    dtype: Any = jnp.float32
    fused: bool = False

    @nn.compact
    def __call__(self, h, x):
        hx = jnp.concatenate([h, x], axis=-1)
        cin = hx.shape[-1]
        zr_raw = _tconv(2 * self.hidden_dim, 3, cin, self.dtype,
                        "convzr")(hx)
        if self.fused:
            from raft_tpu.ops.pallas_gru import (gru_gate_blend,
                                                 gru_gate_rh)

            z_raw, r_raw = jnp.split(zr_raw, 2, axis=-1)
            q_raw = _tconv(self.hidden_dim, 3, cin, self.dtype, "convq")(
                jnp.concatenate([gru_gate_rh(r_raw, h), x], axis=-1))
            return gru_gate_blend(z_raw, q_raw, h)
        zr = nn.sigmoid(zr_raw)
        z, r = jnp.split(zr, 2, axis=-1)
        q = jnp.tanh(_tconv(self.hidden_dim, 3, cin, self.dtype, "convq")(
            jnp.concatenate([r * h, x], axis=-1)))
        return (1 - z) * h + z * q


class SepConvGRU(nn.Module):
    """Horizontal (1x5) then vertical (5x1) GRU pass, with the z/r gate
    convs of each pass fused double-width (see ConvGRU)."""

    hidden_dim: int = 128
    dtype: Any = jnp.float32
    fused: bool = False

    def _pass(self, h, x, cin, ksize, zr_name, q_name):
        dt = self.dtype
        hx = jnp.concatenate([h, x], axis=-1)
        zr_raw = _tconv(2 * self.hidden_dim, ksize, cin, dt, zr_name)(hx)
        if self.fused:
            from raft_tpu.ops.pallas_gru import (gru_gate_blend,
                                                 gru_gate_rh)

            z_raw, r_raw = jnp.split(zr_raw, 2, axis=-1)
            q_raw = _tconv(self.hidden_dim, ksize, cin, dt, q_name)(
                jnp.concatenate([gru_gate_rh(r_raw, h), x], axis=-1))
            return gru_gate_blend(z_raw, q_raw, h)
        zr = nn.sigmoid(zr_raw)
        z, r = jnp.split(zr, 2, axis=-1)
        q = jnp.tanh(_tconv(self.hidden_dim, ksize, cin, dt, q_name)(
            jnp.concatenate([r * h, x], axis=-1)))
        return (1 - z) * h + z * q

    @nn.compact
    def __call__(self, h, x):
        cin = h.shape[-1] + x.shape[-1]
        # horizontal (1x5) then vertical (5x1) pass
        h = self._pass(h, x, cin, (1, 5), "convzr1", "convq1")
        return self._pass(h, x, cin, (5, 1), "convzr2", "convq2")


class SmallMotionEncoder(nn.Module):
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, flow, corr):
        dt = self.dtype
        if isinstance(corr, FusedCorrLookup):
            kernel, bias = _Conv1x1Params(96, corr.channels,
                                          name="convc1")()
            cor = _fused_corr_encode(corr, kernel, bias, 96, dt)
        else:
            cor = nn.relu(
                _tconv(96, 1, corr.shape[-1], dt, "convc1")(corr))
        flo = nn.relu(_tconv(64, 7, 2, dt, "convf1")(flow))
        flo = nn.relu(_tconv(32, 3, 64, dt, "convf2")(flo))
        out = nn.relu(_tconv(80, 3, 128, dt, "conv")(
            jnp.concatenate([cor, flo], axis=-1)))
        # Tagged for remat_policy='save_corr' (saved with the corr taps:
        # skipping the motion-encoder recompute in backward is nearly
        # free memory-wise, (B, H/8, W/8, 82|128) per iteration).
        return checkpoint_name(
            jnp.concatenate([out, flow], axis=-1), "motion")


class BasicMotionEncoder(nn.Module):
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, flow, corr):
        dt = self.dtype
        if isinstance(corr, FusedCorrLookup):
            kernel, bias = _Conv1x1Params(256, corr.channels,
                                          name="convc1")()
            cor = _fused_corr_encode(corr, kernel, bias, 256, dt)
        else:
            cor = nn.relu(
                _tconv(256, 1, corr.shape[-1], dt, "convc1")(corr))
        cor = nn.relu(_tconv(192, 3, 256, dt, "convc2")(cor))
        flo = nn.relu(_tconv(128, 7, 2, dt, "convf1")(flow))
        flo = nn.relu(_tconv(64, 3, 128, dt, "convf2")(flo))
        out = nn.relu(_tconv(126, 3, 64 + 192, dt, "conv")(
            jnp.concatenate([cor, flo], axis=-1)))
        # Tagged for remat_policy='save_corr' (saved with the corr taps:
        # skipping the motion-encoder recompute in backward is nearly
        # free memory-wise, (B, H/8, W/8, 82|128) per iteration).
        return checkpoint_name(
            jnp.concatenate([out, flow], axis=-1), "motion")


class SmallUpdateBlock(nn.Module):
    hidden_dim: int = 96
    dtype: Any = jnp.float32
    fused_gru: bool = False

    @nn.compact
    def __call__(self, net, inp, corr, flow):
        motion = SmallMotionEncoder(self.dtype, name="encoder")(flow, corr)
        x = jnp.concatenate([inp, motion], axis=-1)
        net = ConvGRU(self.hidden_dim, self.dtype, fused=self.fused_gru,
                      name="gru")(net, x)
        delta_flow = FlowHead(128, self.dtype, name="flow_head")(net)
        return net, delta_flow


class BasicUpdateBlock(nn.Module):
    """GRU update *without* the mask head: the convex-upsample mask
    (reference update.py:122-125) depends only on ``net``, so it is
    hoisted out of the refinement scan into :class:`MaskHead` (applied per
    iteration for training, final iteration only for inference — the
    reference recomputes it every iteration even in test mode,
    raft.py:127-137)."""

    hidden_dim: int = 128
    dtype: Any = jnp.float32
    fused_gru: bool = False

    @nn.compact
    def __call__(self, net, inp, corr, flow):
        motion = BasicMotionEncoder(self.dtype, name="encoder")(flow, corr)
        x = jnp.concatenate([inp, motion], axis=-1)
        net = SepConvGRU(self.hidden_dim, self.dtype,
                         fused=self.fused_gru, name="gru")(net, x)
        delta_flow = FlowHead(256, self.dtype, name="flow_head")(net)
        return net, delta_flow


class MaskHead(nn.Module):
    """Convex-upsample mask head (reference update.py:122-125,135), with
    the x0.25 scale ("to balence gradients")."""

    hidden_dim: int = 128
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, net):
        mask = nn.relu(_tconv(256, 3, self.hidden_dim, self.dtype,
                              "mask_conv1")(net))
        mask = _tconv(64 * 9, 1, 256, self.dtype, "mask_conv2")(mask)
        return 0.25 * mask
