from raft_tpu.models.extractor import BasicEncoder, SmallEncoder
from raft_tpu.models.update import (
    BasicUpdateBlock,
    MaskHead,
    ConvGRU,
    FlowHead,
    SepConvGRU,
    SmallUpdateBlock,
)
from raft_tpu.models.raft import RAFT

__all__ = [
    "BasicEncoder",
    "SmallEncoder",
    "BasicUpdateBlock",
    "MaskHead",
    "SmallUpdateBlock",
    "ConvGRU",
    "SepConvGRU",
    "FlowHead",
    "RAFT",
]
