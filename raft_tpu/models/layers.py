"""Shared building blocks: conv + norm factories, residual blocks (NHWC).

TPU-first re-design of the reference's ``core/extractor.py:6-115`` blocks:
NHWC layout (native for TPU convolutions), flax.linen functional modules,
explicit train/freeze flags instead of module-level ``.eval()`` mutation.

Norm semantics parity:
- 'group'    -> GroupNorm(planes // 8 groups)    (extractor.py:16-20)
- 'batch'    -> BatchNorm (running stats; ``freeze_bn`` pins them, the
                functional equivalent of RAFT.freeze_bn, raft.py:58-61)
- 'instance' -> per-sample, per-channel spatial norm, no affine
                (torch InstanceNorm2d defaults: affine=False)
- 'none'     -> identity
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

# torch kaiming_normal_(mode='fan_out', nonlinearity='relu') equivalent
# (reference extractor.py:150-153).
kaiming_out = nn.initializers.variance_scaling(2.0, "fan_out", "normal")


def _torch_default_uniform(key, shape, dtype=jnp.float32):
    """torch Conv2d default init: kaiming_uniform_(a=sqrt(5)) for weights and
    U(-1/sqrt(fan_in), 1/sqrt(fan_in)) for biases — both reduce to the same
    bound.  Weight shape is HWIO (fan_in = prod(shape[:-1])); bias shape is
    (features,) and the bound must then come from the matching conv's fan_in,
    so biases use :func:`torch_bias_init`."""
    import numpy as np

    fan_in = int(np.prod(shape[:-1])) if len(shape) > 1 else shape[0]
    bound = 1.0 / (fan_in ** 0.5)
    return jax.random.uniform(key, shape, dtype, -bound, bound)


def torch_bias_init(fan_in: int):
    bound = 1.0 / (fan_in ** 0.5)

    def init(key, shape, dtype=jnp.float32):
        return jax.random.uniform(key, shape, dtype, -bound, bound)

    return init


def conv(features: int, kernel, stride=1, dtype=jnp.float32,
         name: Optional[str] = None, torch_default_init: bool = False,
         in_features: Optional[int] = None) -> nn.Conv:
    """3x3/7x7/... conv with torch-style symmetric padding.

    XLA's ``SAME`` pads stride-2 convs asymmetrically (left-light), while
    torch pads symmetrically by ``k // 2`` — using SAME would shift every
    stride-2 feature map by one input pixel and break weight-conversion
    parity, so padding is always explicit here.

    ``torch_default_init=True`` reproduces torch's default Conv2d init
    (used by the reference everywhere *except* the encoders, which apply
    kaiming fan_out on weights only, extractor.py:150-153).  Bias init
    needs the conv's fan_in, which flax initializers can't see from the
    bias shape alone: pass ``in_features`` to enable torch-parity bias
    init (otherwise biases are zeros).
    """
    if isinstance(kernel, int):
        kernel = (kernel, kernel)
    if isinstance(stride, int):
        stride = (stride, stride)
    padding = [((k - 1) // 2, (k - 1) // 2) for k in kernel]
    kernel_init = _torch_default_uniform if torch_default_init else kaiming_out
    if in_features is not None:
        bias_init = torch_bias_init(in_features * kernel[0] * kernel[1])
    else:
        bias_init = nn.initializers.zeros_init()
    return nn.Conv(features, kernel, strides=stride, padding=padding,
                   kernel_init=kernel_init, bias_init=bias_init,
                   dtype=dtype, name=name)


class Norm(nn.Module):
    """Dispatch over the reference's four norm modes."""

    kind: str
    channels: int
    num_groups: Optional[int] = None  # default: channels // 8
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False, freeze_bn: bool = False):
        if self.kind == "group":
            groups = self.num_groups or self.channels // 8
            return nn.GroupNorm(num_groups=groups, epsilon=1e-5,
                                dtype=self.dtype)(x)
        if self.kind == "batch":
            return nn.BatchNorm(
                use_running_average=(not train) or freeze_bn,
                momentum=0.9, epsilon=1e-5, dtype=self.dtype)(x)
        if self.kind == "instance":
            return nn.GroupNorm(num_groups=None, group_size=1,
                                use_bias=False, use_scale=False,
                                epsilon=1e-5, dtype=self.dtype)(x)
        if self.kind == "none":
            return x
        raise ValueError(f"unknown norm kind: {self.kind}")


class ResidualBlock(nn.Module):
    """Two 3x3 convs + skip (reference extractor.py:6-57)."""

    planes: int
    norm: str
    stride: int = 1
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False, freeze_bn: bool = False):
        cin = x.shape[-1]
        y = conv(self.planes, 3, self.stride, self.dtype, name="conv1",
                 in_features=cin)(x)
        y = Norm(self.norm, self.planes, dtype=self.dtype, name="norm1")(
            y, train, freeze_bn)
        y = nn.relu(y)
        y = conv(self.planes, 3, 1, self.dtype, name="conv2",
                 in_features=self.planes)(y)
        y = Norm(self.norm, self.planes, dtype=self.dtype, name="norm2")(
            y, train, freeze_bn)
        y = nn.relu(y)

        if self.stride != 1:
            x = conv(self.planes, 1, self.stride, self.dtype,
                     name="downsample_conv", in_features=cin)(x)
            x = Norm(self.norm, self.planes, dtype=self.dtype,
                     name="norm3")(x, train, freeze_bn)
        return nn.relu(x + y)


class BottleneckBlock(nn.Module):
    """1x1 -> 3x3 -> 1x1 bottleneck (reference extractor.py:60-115).

    Note the reference's quirk: GroupNorm group count is ``planes // 8``
    even for the ``planes // 4``-channel inner convs (extractor.py:72-74);
    reproduced for weight parity.
    """

    planes: int
    norm: str
    stride: int = 1
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False, freeze_bn: bool = False):
        cin = x.shape[-1]
        p4 = self.planes // 4
        groups = self.planes // 8
        y = conv(p4, 1, 1, self.dtype, name="conv1", in_features=cin)(x)
        y = Norm(self.norm, p4, num_groups=groups, dtype=self.dtype,
                 name="norm1")(y, train, freeze_bn)
        y = nn.relu(y)
        y = conv(p4, 3, self.stride, self.dtype, name="conv2",
                 in_features=p4)(y)
        y = Norm(self.norm, p4, num_groups=groups, dtype=self.dtype,
                 name="norm2")(y, train, freeze_bn)
        y = nn.relu(y)
        y = conv(self.planes, 1, 1, self.dtype, name="conv3",
                 in_features=p4)(y)
        y = Norm(self.norm, self.planes, num_groups=groups, dtype=self.dtype,
                 name="norm3")(y, train, freeze_bn)
        y = nn.relu(y)

        if self.stride != 1:
            x = conv(self.planes, 1, self.stride, self.dtype,
                     name="downsample_conv", in_features=cin)(x)
            x = Norm(self.norm, self.planes, num_groups=groups,
                     dtype=self.dtype, name="norm4")(x, train, freeze_bn)
        return nn.relu(x + y)
