"""Shared building blocks: conv + norm factories, residual blocks (NHWC).

TPU-first re-design of the reference's ``core/extractor.py:6-115`` blocks:
NHWC layout (native for TPU convolutions), flax.linen functional modules,
explicit train/freeze flags instead of module-level ``.eval()`` mutation.

Norm semantics parity:
- 'group'    -> GroupNorm(planes // 8 groups)    (extractor.py:16-20)
- 'batch'    -> BatchNorm (running stats; ``freeze_bn`` pins them, the
                functional equivalent of RAFT.freeze_bn, raft.py:58-61)
- 'instance' -> per-sample, per-channel spatial norm, no affine
                (torch InstanceNorm2d defaults: affine=False)
- 'none'     -> identity
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

# torch kaiming_normal_(mode='fan_out', nonlinearity='relu') equivalent
# (reference extractor.py:150-153).
kaiming_out = nn.initializers.variance_scaling(2.0, "fan_out", "normal")


def _torch_default_uniform(key, shape, dtype=jnp.float32):
    """torch Conv2d default init: kaiming_uniform_(a=sqrt(5)) for weights and
    U(-1/sqrt(fan_in), 1/sqrt(fan_in)) for biases — both reduce to the same
    bound.  Weight shape is HWIO (fan_in = prod(shape[:-1])); bias shape is
    (features,) and the bound must then come from the matching conv's fan_in,
    so biases use :func:`torch_bias_init`."""
    import numpy as np

    fan_in = int(np.prod(shape[:-1])) if len(shape) > 1 else shape[0]
    bound = 1.0 / (fan_in ** 0.5)
    return jax.random.uniform(key, shape, dtype, -bound, bound)


def torch_bias_init(fan_in: int):
    bound = 1.0 / (fan_in ** 0.5)

    def init(key, shape, dtype=jnp.float32):
        return jax.random.uniform(key, shape, dtype, -bound, bound)

    return init


def conv(features: int, kernel, stride=1, dtype=jnp.float32,
         name: Optional[str] = None, torch_default_init: bool = False,
         in_features: Optional[int] = None) -> nn.Conv:
    """3x3/7x7/... conv with torch-style symmetric padding.

    XLA's ``SAME`` pads stride-2 convs asymmetrically (left-light), while
    torch pads symmetrically by ``k // 2`` — using SAME would shift every
    stride-2 feature map by one input pixel and break weight-conversion
    parity, so padding is always explicit here.

    ``torch_default_init=True`` reproduces torch's default Conv2d init
    (used by the reference everywhere *except* the encoders, which apply
    kaiming fan_out on weights only, extractor.py:150-153).  Bias init
    needs the conv's fan_in, which flax initializers can't see from the
    bias shape alone: pass ``in_features`` to enable torch-parity bias
    init (otherwise biases are zeros).
    """
    if isinstance(kernel, int):
        kernel = (kernel, kernel)
    if isinstance(stride, int):
        stride = (stride, stride)
    padding = [((k - 1) // 2, (k - 1) // 2) for k in kernel]
    kernel_init = _torch_default_uniform if torch_default_init else kaiming_out
    if in_features is not None:
        bias_init = torch_bias_init(in_features * kernel[0] * kernel[1])
    else:
        bias_init = nn.initializers.zeros_init()
    return nn.Conv(features, kernel, strides=stride, padding=padding,
                   kernel_init=kernel_init, bias_init=bias_init,
                   dtype=dtype, name=name)


class Norm(nn.Module):
    """Dispatch over the reference's four norm modes."""

    kind: str
    channels: int
    num_groups: Optional[int] = None  # default: channels // 8
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False, freeze_bn: bool = False):
        if self.kind == "group":
            groups = self.num_groups or self.channels // 8
            return nn.GroupNorm(num_groups=groups, epsilon=1e-5,
                                dtype=self.dtype)(x)
        if self.kind == "batch":
            return nn.BatchNorm(
                use_running_average=(not train) or freeze_bn,
                momentum=0.9, epsilon=1e-5, dtype=self.dtype)(x)
        if self.kind == "instance":
            return nn.GroupNorm(num_groups=None, group_size=1,
                                use_bias=False, use_scale=False,
                                epsilon=1e-5, dtype=self.dtype)(x)
        if self.kind == "none":
            return x
        raise ValueError(f"unknown norm kind: {self.kind}")


class ResidualBlock(nn.Module):
    """Two 3x3 convs + skip (reference extractor.py:6-57)."""

    planes: int
    norm: str
    stride: int = 1
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False, freeze_bn: bool = False):
        cin = x.shape[-1]
        y = conv(self.planes, 3, self.stride, self.dtype, name="conv1",
                 in_features=cin)(x)
        y = Norm(self.norm, self.planes, dtype=self.dtype, name="norm1")(
            y, train, freeze_bn)
        y = nn.relu(y)
        y = conv(self.planes, 3, 1, self.dtype, name="conv2",
                 in_features=self.planes)(y)
        y = Norm(self.norm, self.planes, dtype=self.dtype, name="norm2")(
            y, train, freeze_bn)
        y = nn.relu(y)

        if self.stride != 1:
            x = conv(self.planes, 1, self.stride, self.dtype,
                     name="downsample_conv", in_features=cin)(x)
            x = Norm(self.norm, self.planes, dtype=self.dtype,
                     name="norm3")(x, train, freeze_bn)
        return nn.relu(x + y)


def fold_w(x: jax.Array) -> jax.Array:
    """``(B, H, W, C) -> (B, H, W/2, 2C)``: adjacent column pairs packed
    into channels (block 0 = even columns, block 1 = odd).

    The 64-channel layer1 stage only fills half of a TPU (8, 128) lane
    tile, so every tensor moves 2x its bytes through HBM; folding makes
    the tiles dense (profiled: layer1 was ~45 ms/step, ~2/3 HBM-bound at
    half effective bandwidth)."""
    B, H, W, C = x.shape
    return x.reshape(B, H, W // 2, 2 * C)


def unfold_w(x: jax.Array) -> jax.Array:
    """Inverse of :func:`fold_w`."""
    B, H, Wf, C2 = x.shape
    return x.reshape(B, H, Wf * 2, C2 // 2)


def _fold_kernel_3x3(w: jax.Array) -> jax.Array:
    """``(3, 3, C, C)`` -> ``(3, 3, 2C, 2C)`` folded-width kernel.

    A stride-1 3x3 conv on the unfolded image equals a 3x3 conv on the
    folded image with this block-structured kernel: output parity j at
    folded column p is original column ``2p + j``, whose three width taps
    land in folded columns ``p-1..p+1`` at fixed (tap, parity) slots —
    half the folded kernel is structurally zero (2x the nominal FLOPs on
    half the pixels = same math), but every operand tile is lane-dense.
    Built per call from the UNCHANGED (3,3,C,C) parameter, so checkpoints
    and the torch converter never see the folded form; autodiff routes
    the weight gradient back through the slice adjoints."""
    C = w.shape[2]
    kf = jnp.zeros((3, 3, 2 * C, 2 * C), w.dtype)
    # output parity j=0 (orig col 2p): taps at orig cols 2p-1, 2p, 2p+1
    kf = kf.at[:, 0, C:, :C].set(w[:, 0])      # col 2p-1 = (p-1, blk1)
    kf = kf.at[:, 1, :C, :C].set(w[:, 1])      # col 2p   = (p,   blk0)
    kf = kf.at[:, 1, C:, :C].set(w[:, 2])      # col 2p+1 = (p,   blk1)
    # output parity j=1 (orig col 2p+1): taps at orig cols 2p, 2p+1, 2p+2
    kf = kf.at[:, 1, :C, C:].set(w[:, 0])      # col 2p   = (p,   blk0)
    kf = kf.at[:, 1, C:, C:].set(w[:, 1])      # col 2p+1 = (p,   blk1)
    kf = kf.at[:, 2, :C, C:].set(w[:, 2])      # col 2p+2 = (p+1, blk0)
    return kf


class _FoldedConv3x3(nn.Module):
    """3x3/stride-1 conv applied in folded-width layout.  Parameter names
    and shapes ("kernel" (3,3,C,C), "bias" (C,)) are identical to the
    ``conv()`` path, so the param tree is checkpoint-compatible.

    Parity with ``conv()`` is exact only at fp32+: the kernel fold runs
    in fp32 before the cast to self.dtype, while nn.Conv casts params
    first — a bf16-ULP-level difference under bf16 compute (bounded by
    tests/test_layers.py::test_encoder_folded_matches_unfolded_bf16)."""

    channels: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, xf):
        C = self.channels
        kernel = self.param("kernel", kaiming_out, (3, 3, C, C),
                            jnp.float32)
        bias = self.param("bias", torch_bias_init(C * 9), (C,),
                          jnp.float32)
        kf = _fold_kernel_3x3(kernel).astype(self.dtype)
        y = jax.lax.conv_general_dilated(
            xf.astype(self.dtype), kf, (1, 1), [(1, 1), (1, 1)],
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        return y + jnp.tile(bias, 2).astype(self.dtype)


def _pair_stats(x: jax.Array, axes, C: int):
    """Per-ORIGINAL-channel mean/var on the folded layout: lane c and
    lane c+C hold the same original channel (even/odd columns), so the
    per-lane moments combine exactly as the average of the two
    equal-count halves.  Stats run at fp32 minimum (matching flax's
    normalization internals) but never truncate wider inputs."""
    x = x.astype(jnp.promote_types(x.dtype, jnp.float32))
    m = jnp.mean(x, axis=axes)
    m2 = jnp.mean(x * x, axis=axes)
    mean_c = 0.5 * (m[..., :C] + m[..., C:])
    e2_c = 0.5 * (m2[..., :C] + m2[..., C:])
    return mean_c, e2_c - mean_c * mean_c


class _FoldedBatchNorm(nn.Module):
    """flax BatchNorm semantics (momentum 0.9, eps 1e-5, biased var,
    fp32 stats) on the folded layout; param/variable names match
    ``nn.BatchNorm`` for checkpoint compatibility.

    Parity with the unfolded path is exact only at fp32+: this module
    normalizes in fp32 and rounds once at the end, while nn.BatchNorm
    performs the (x-mean)*inv*scale+bias arithmetic in self.dtype — so
    under bf16 compute the two differ at bf16-ULP level (bounded by
    tests/test_layers.py::test_encoder_folded_matches_unfolded_bf16)."""

    channels: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, xf, use_running_average: bool):
        C = self.channels
        scale = self.param("scale", nn.initializers.ones_init(), (C,),
                           jnp.float32)
        bias = self.param("bias", nn.initializers.zeros_init(), (C,),
                          jnp.float32)
        ra_mean = self.variable("batch_stats", "mean",
                                lambda: jnp.zeros((C,), jnp.float32))
        ra_var = self.variable("batch_stats", "var",
                               lambda: jnp.ones((C,), jnp.float32))
        if use_running_average:
            mean_c, var_c = ra_mean.value, ra_var.value
        else:
            mean_c, var_c = _pair_stats(xf, (0, 1, 2), C)
            if not self.is_initializing() and \
                    self.is_mutable_collection("batch_stats"):
                ra_mean.value = 0.9 * ra_mean.value + 0.1 * mean_c
                ra_var.value = 0.9 * ra_var.value + 0.1 * var_c
        wdt = jnp.promote_types(xf.dtype, jnp.float32)
        inv = jax.lax.rsqrt(var_c + 1e-5) * scale
        y = (xf.astype(wdt) - jnp.tile(mean_c, 2)) \
            * jnp.tile(inv, 2) + jnp.tile(bias, 2)
        return y.astype(self.dtype)


class FoldedNorm(nn.Module):
    """Folded-layout dispatch over the norm modes a folded block
    supports (instance / batch / none; 'group' falls back to the
    unfolded path at the encoder level)."""

    kind: str
    channels: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, xf, train: bool = False, freeze_bn: bool = False):
        if self.kind == "batch":
            return _FoldedBatchNorm(self.channels, self.dtype,
                                    name="BatchNorm_0")(
                xf, (not train) or freeze_bn)
        if self.kind == "instance":
            C = self.channels
            mean_c, var_c = _pair_stats(xf, (1, 2), C)
            wdt = jnp.promote_types(xf.dtype, jnp.float32)
            inv = jax.lax.rsqrt(var_c + 1e-5)
            y = (xf.astype(wdt) - jnp.tile(mean_c, 2)[:, None,
                                                      None, :]) \
                * jnp.tile(inv, 2)[:, None, None, :]
            return y.astype(self.dtype)
        if self.kind == "none":
            return xf
        raise ValueError(f"unfoldable norm kind: {self.kind}")


class FoldedStemConv(nn.Module):
    """Original 7x7/stride-2 stem conv emitting the FOLDED layout
    directly: folded output column p holds original columns 2p (parity
    0, input center 4p, window 4p-3..4p+3) and 2p+1 (parity 1, center
    4p+2, window 4p-1..4p+5) — one (7, 9) kernel at stride (2, 4) whose
    width taps embed the original (7, 7) kernel at offsets 0 and 2.
    Param names/shapes match the unfolded stem ("kernel" (7,7,cin,P),
    "bias" (P,)), and the fold relayout after the stem disappears."""

    cin: int
    planes: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        C, P = self.cin, self.planes
        kernel = self.param("kernel", kaiming_out, (7, 7, C, P),
                            jnp.float32)
        bias = self.param("bias", torch_bias_init(C * 49), (P,),
                          jnp.float32)
        kf = jnp.zeros((7, 9, C, 2 * P), kernel.dtype)
        kf = kf.at[:, 0:7, :, :P].set(kernel)
        kf = kf.at[:, 2:9, :, P:].set(kernel)
        y = jax.lax.conv_general_dilated(
            x.astype(self.dtype), kf.astype(self.dtype), (2, 4),
            [(3, 3), (3, 2)], dimension_numbers=("NHWC", "HWIO", "NHWC"))
        return y + jnp.tile(bias, 2).astype(self.dtype)


class _FoldedEntryConv(nn.Module):
    """Original 3x3/stride-2 conv consuming the FOLDED layout: output
    column q is original column 2q, whose three width taps live in
    folded columns q-1 (parity 1) and q (parities 0 and 1) — a (3, 2)
    kernel at stride (2, 1) over ``(H, Wf)``.  Param names/shapes match
    ``conv()`` ("kernel" (3,3,C,P), "bias" (P,))."""

    cin: int
    planes: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, xf):
        C, P = self.cin, self.planes
        kernel = self.param("kernel", kaiming_out, (3, 3, C, P),
                            jnp.float32)
        bias = self.param("bias", torch_bias_init(C * 9), (P,),
                          jnp.float32)
        kf = jnp.zeros((3, 2, 2 * C, P), kernel.dtype)
        kf = kf.at[:, 0, C:, :].set(kernel[:, 0])   # col 2q-1 = (q-1, b1)
        kf = kf.at[:, 1, :C, :].set(kernel[:, 1])   # col 2q   = (q,   b0)
        kf = kf.at[:, 1, C:, :].set(kernel[:, 2])   # col 2q+1 = (q,   b1)
        y = jax.lax.conv_general_dilated(
            xf.astype(self.dtype), kf.astype(self.dtype), (2, 1),
            [(1, 1), (1, 0)], dimension_numbers=("NHWC", "HWIO", "NHWC"))
        return y + bias.astype(self.dtype)


class FoldedEntryResidualBlock(nn.Module):
    """:class:`ResidualBlock` with ``stride=2`` whose INPUT arrives in
    folded-width layout and whose output is standard: the stride-2 width
    step lands exactly on the folded column count, so consuming the fold
    directly removes the unfold relayout and halves the entry conv's
    input reads.  Identical math and parameter tree to the unfolded
    stride-2 block (the 1x1 downsample reads original even columns =
    folded parity-0 channels, even rows via slicing)."""

    planes: int
    norm: str
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, xf, train: bool = False, freeze_bn: bool = False):
        C = xf.shape[-1] // 2
        y = _FoldedEntryConv(C, self.planes, self.dtype, name="conv1")(xf)
        y = Norm(self.norm, self.planes, dtype=self.dtype, name="norm1")(
            y, train, freeze_bn)
        y = nn.relu(y)
        y = conv(self.planes, 3, 1, self.dtype, name="conv2",
                 in_features=self.planes)(y)
        y = Norm(self.norm, self.planes, dtype=self.dtype, name="norm2")(
            y, train, freeze_bn)
        y = nn.relu(y)

        x_even = xf[:, ::2, :, :C]            # orig even rows, even cols
        x = conv(self.planes, 1, 1, self.dtype, name="downsample_conv",
                 in_features=C)(x_even)
        x = Norm(self.norm, self.planes, dtype=self.dtype, name="norm3")(
            x, train, freeze_bn)
        return nn.relu(x + y)


class FoldedResidualBlock(nn.Module):
    """:class:`ResidualBlock` (stride 1) computed entirely in folded-width
    layout — identical math and parameter tree, lane-dense tiles."""

    planes: int
    norm: str
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, xf, train: bool = False, freeze_bn: bool = False):
        y = _FoldedConv3x3(self.planes, self.dtype, name="conv1")(xf)
        y = FoldedNorm(self.norm, self.planes, self.dtype,
                        name="norm1")(y, train, freeze_bn)
        y = nn.relu(y)
        y = _FoldedConv3x3(self.planes, self.dtype, name="conv2")(y)
        y = FoldedNorm(self.norm, self.planes, self.dtype,
                        name="norm2")(y, train, freeze_bn)
        y = nn.relu(y)
        return nn.relu(xf + y)


class BottleneckBlock(nn.Module):
    """1x1 -> 3x3 -> 1x1 bottleneck (reference extractor.py:60-115).

    Note the reference's quirk: GroupNorm group count is ``planes // 8``
    even for the ``planes // 4``-channel inner convs (extractor.py:72-74);
    reproduced for weight parity.
    """

    planes: int
    norm: str
    stride: int = 1
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False, freeze_bn: bool = False):
        cin = x.shape[-1]
        p4 = self.planes // 4
        groups = self.planes // 8
        y = conv(p4, 1, 1, self.dtype, name="conv1", in_features=cin)(x)
        y = Norm(self.norm, p4, num_groups=groups, dtype=self.dtype,
                 name="norm1")(y, train, freeze_bn)
        y = nn.relu(y)
        y = conv(p4, 3, self.stride, self.dtype, name="conv2",
                 in_features=p4)(y)
        y = Norm(self.norm, p4, num_groups=groups, dtype=self.dtype,
                 name="norm2")(y, train, freeze_bn)
        y = nn.relu(y)
        y = conv(self.planes, 1, 1, self.dtype, name="conv3",
                 in_features=p4)(y)
        y = Norm(self.norm, self.planes, num_groups=groups, dtype=self.dtype,
                 name="norm3")(y, train, freeze_bn)
        y = nn.relu(y)

        if self.stride != 1:
            x = conv(self.planes, 1, self.stride, self.dtype,
                     name="downsample_conv", in_features=cin)(x)
            x = Norm(self.norm, self.planes, num_groups=groups,
                     dtype=self.dtype, name="norm4")(x, train, freeze_bn)
        return nn.relu(x + y)
