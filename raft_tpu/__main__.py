"""``python -m raft_tpu <subcommand>`` — the ``raft-tpu`` multi-tool.

One stable entry point over the ``raft_tpu.cli`` modules (the repo-root
``train.py``/``evaluate.py``/``demo.py`` shims keep the reference repo's
UX; this is the installed-package spelling)::

    python -m raft_tpu train --name raft-chairs --stage chairs ...
    python -m raft_tpu curriculum --workdir runs/standard ...
    python -m raft_tpu evaluate --model checkpoints/raft-things ...
    python -m raft_tpu demo --model checkpoints/raft-things --path frames/
    python -m raft_tpu serve --model checkpoints/raft-things --port 8080
    python -m raft_tpu lk-compare --model ... --image1 a.png --image2 b.png
"""

from __future__ import annotations

import importlib
import sys

_SUBCOMMANDS = {
    "train": ("raft_tpu.cli.train", "offline training curriculum"),
    "curriculum": ("raft_tpu.cli.curriculum",
                   "full multi-stage schedule as ONE resumable job"),
    "evaluate": ("raft_tpu.cli.evaluate", "validation / leaderboard eval"),
    "demo": ("raft_tpu.cli.demo", "flow visualization over a frame dir"),
    "serve": ("raft_tpu.cli.serve", "online HTTP inference server"),
    "verify-ckpt": ("raft_tpu.cli.verify_ckpt",
                    "checkpoint integrity check / resume preview"),
    "lk-compare": ("raft_tpu.cli.lk_compare",
                   "RAFT vs Lucas-Kanade side-by-side"),
    "lint": ("raft_tpu.cli.lint",
             "raftlint static analysis (docs/ANALYSIS.md)"),
    "cost": ("raft_tpu.cli.cost",
             "per-program FLOPs/bytes/roofline cost table"),
    "incidents": ("raft_tpu.cli.incidents",
                  "list / show / timeline over incident bundles"),
}


def _usage() -> str:
    lines = ["usage: python -m raft_tpu <subcommand> [args...]", "",
             "subcommands:"]
    for name, (_, desc) in _SUBCOMMANDS.items():
        lines.append(f"  {name:<12} {desc}")
    lines.append("")
    lines.append("run a subcommand with --help for its flags")
    return "\n".join(lines)


def main(argv=None):
    argv = sys.argv[1:] if argv is None else list(argv)
    if not argv or argv[0] in ("-h", "--help"):
        print(_usage())
        return 0
    cmd, rest = argv[0], argv[1:]
    if cmd not in _SUBCOMMANDS:
        print(f"unknown subcommand {cmd!r}\n\n{_usage()}",
              file=sys.stderr)
        return 2
    module = importlib.import_module(_SUBCOMMANDS[cmd][0])
    return module.main(rest)


if __name__ == "__main__":
    sys.exit(main())
