"""Optical-flow datasets and the per-host sharded input pipeline.

Parity with the reference ``core/datasets.py`` (C8 in SURVEY.md): the five
dataset classes (MpiSintel, FlyingChairs, FlyingThings3D, KITTI, HD1K) with
identical directory conventions, the replicate-and-concat dataset mixing
(``100*clean + 100*final + ...``, datasets.py:218-221), grayscale tiling and
alpha dropping (datasets.py:67-73), and the dense validity rule
``|flow| < 1000`` (datasets.py:88).

TPU-first redesign (replaces torch DataLoader + nn.DataParallel scatter):

- Samples are NumPy NHWC; batches are plain dicts of stacked arrays handed
  straight to ``jax.device_put`` — no torch anywhere in the input path.
- ``ShardedLoader`` owns a *global* shuffle per epoch from a seeded
  generator, then each host takes a disjoint stride of the permutation
  (``indices[host_id::num_hosts]``): every host feeds its local devices and
  the SPMD train step sees a globally-shuffled batch — the pod-scale
  replacement for DataParallel's single-process scatter (train.py:138).
- Per-sample augmentation RNG is derived from
  ``SeedSequence([seed, epoch, index])`` — deterministic and independent of
  worker scheduling, unlike the reference's per-worker reseed
  (datasets.py:45-51).
- Decode/augment runs in a thread pool (cv2/PIL release the GIL); no
  process fork, which keeps the loader safe to use after JAX initializes.
"""

from __future__ import annotations

import dataclasses
import os
import os.path as osp
import threading
from concurrent.futures import ThreadPoolExecutor
from glob import glob
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from raft_tpu import chaos
from raft_tpu.data import frame_utils
from raft_tpu.data.augment import FlowAugmentor, SparseFlowAugmentor


class SampleReadError(ValueError):
    """A per-sample read/decode failure with its provenance attached.

    The bare reader errors (``ValueError: truncated .flo``) name the
    symptom but not which of 20k files is bad; every reader call in
    :meth:`FlowDataset.load` is wrapped so the exception (and the
    quarantine event built from it, docs/ROBUSTNESS.md) carries the
    dataset name, split, sample index, and file path.  Subclasses
    ``ValueError`` so existing handlers of decode errors keep working.
    """

    def __init__(self, path: str, dataset=None, index=None,
                 detail: str = ""):
        self.path = path
        self.dataset_name = getattr(dataset, "name", None) \
            or type(dataset).__name__
        self.split = getattr(dataset, "split", None)
        self.index = index
        super().__init__(
            f"{path}: {detail} [dataset={self.dataset_name} "
            f"split={self.split or '-'} sample={index}]")


def _read_sample(ds, index: int, path: str, reader):
    """Run one reader call with sample context attached to decode/IO
    failures (real corruption raises ValueError/OSError out of
    frame_utils; anything else is a bug and propagates untouched)."""
    try:
        return reader(path)
    except SampleReadError:
        raise
    except (ValueError, OSError) as e:
        raise SampleReadError(path, ds, index, str(e)) from e


def _to_rgb(img: np.ndarray) -> np.ndarray:
    """Grayscale -> 3-channel tile, drop alpha (reference datasets.py:67-73)."""
    img = np.asarray(img)
    if img.ndim == 2:
        return np.tile(img[..., None], (1, 1, 3)).astype(np.uint8)
    return img[..., :3].astype(np.uint8)


class FlowDataset:
    """Base dataset: lists of (img1, img2) paths and flow paths.

    ``dataset * n`` replicates the sample list and ``a + b`` concatenates —
    the reference's ``__rmul__`` mixing idiom (datasets.py:93-96) — except
    both return NEW datasets instead of mutating in place.
    """

    def __init__(self, aug_params: Optional[dict] = None,
                 sparse: bool = False):
        self.sparse = sparse
        self.is_test = False
        self.augmentor = None
        # Sample-error provenance (SampleReadError / quarantine events):
        # concrete datasets overwrite split after super().__init__.
        self.name = type(self).__name__
        self.split: Optional[str] = None
        if aug_params is not None:
            cls = SparseFlowAugmentor if sparse else FlowAugmentor
            self.augmentor = cls(**aug_params)
        self.flow_list: List[str] = []
        self.image_list: List[Tuple[str, str]] = []
        self.extra_info: List[tuple] = []

    # -- mixing ----------------------------------------------------------
    def _clone_shell(self) -> "FlowDataset":
        out = FlowDataset.__new__(FlowDataset)
        out.sparse = self.sparse
        out.is_test = self.is_test
        out.augmentor = self.augmentor
        out.name = self.name
        out.split = self.split
        out.flow_list = list(self.flow_list)
        out.image_list = list(self.image_list)
        out.extra_info = list(self.extra_info)
        return out

    def __mul__(self, v: int) -> "FlowDataset":
        out = self._clone_shell()
        out.flow_list = v * out.flow_list
        out.image_list = v * out.image_list
        out.extra_info = v * out.extra_info
        return out

    __rmul__ = __mul__

    def __add__(self, other: "FlowDataset") -> "ConcatFlowDataset":
        return ConcatFlowDataset([self, other])

    def __len__(self) -> int:
        return len(self.image_list)

    # -- loading ---------------------------------------------------------
    def _sample_parts(self, index: int):
        """Which member dataset + local index serves ``index`` (overridden
        by ConcatFlowDataset)."""
        return self, index

    def load(self, index: int,
             rng: Optional[np.random.Generator] = None) -> Dict[str, np.ndarray]:
        """Load (and optionally augment) one sample.

        Returns NHWC float32 ``image1``/``image2`` in [0,255], ``flow``
        (H,W,2) float32, ``valid`` (H,W) float32.  Test-mode datasets return
        images + ``extra_info`` only (reference datasets.py:36-43).
        """
        ds, index = self._sample_parts(index)
        index = index % len(ds.image_list)
        if chaos.should_inject("corrupt_image", point="data.sample_read"):
            raise SampleReadError(ds.image_list[index][0], ds, index,
                                  "chaos-injected corrupt sample")
        img1 = _read_sample(ds, index, ds.image_list[index][0],
                            lambda p: _to_rgb(frame_utils.read_gen(p)))
        img2 = _read_sample(ds, index, ds.image_list[index][1],
                            lambda p: _to_rgb(frame_utils.read_gen(p)))

        if ds.is_test:
            return {"image1": img1.astype(np.float32),
                    "image2": img2.astype(np.float32),
                    "extra_info": ds.extra_info[index]}

        valid = None
        if ds.sparse:
            flow, valid = _read_sample(ds, index, ds.flow_list[index],
                                       frame_utils.read_flow_kitti)
        else:
            flow = _read_sample(
                ds, index, ds.flow_list[index],
                lambda p: np.asarray(frame_utils.read_gen(p), np.float32))

        if ds.augmentor is not None:
            if rng is None:
                rng = np.random.default_rng()
            if ds.sparse:
                img1, img2, flow, valid = ds.augmentor(
                    rng, img1, img2, flow, valid)
            else:
                img1, img2, flow = ds.augmentor(rng, img1, img2, flow)

        if valid is None:
            valid = ((np.abs(flow[..., 0]) < 1000)
                     & (np.abs(flow[..., 1]) < 1000))
        return {"image1": img1.astype(np.float32),
                "image2": img2.astype(np.float32),
                "flow": flow.astype(np.float32),
                "valid": np.asarray(valid, np.float32)}


class ConcatFlowDataset(FlowDataset):
    """Concatenation of datasets with possibly different augmentors/sparsity
    (the reference concatenates via torch ConcatDataset, datasets.py:210)."""

    def __init__(self, parts: Sequence[FlowDataset]):
        flat: List[FlowDataset] = []
        for p in parts:
            flat.extend(p.parts if isinstance(p, ConcatFlowDataset) else [p])
        self.parts = flat
        self.is_test = False
        self.name = "Concat(" + "+".join(p.name for p in flat) + ")"
        self.split = None  # per-sample context comes from the member
        self._offsets = np.cumsum([len(p) for p in flat])

    def __len__(self) -> int:
        return int(self._offsets[-1]) if len(self.parts) else 0

    def __mul__(self, v: int) -> "ConcatFlowDataset":
        return ConcatFlowDataset(list(self.parts) * v)

    __rmul__ = __mul__

    def _sample_parts(self, index: int):
        index = index % len(self)
        part = int(np.searchsorted(self._offsets, index, side="right"))
        local = index - (0 if part == 0 else int(self._offsets[part - 1]))
        return self.parts[part], local


# ---------------------------------------------------------------------------
# Concrete datasets (directory conventions: reference datasets.py:102-197)
# ---------------------------------------------------------------------------

class MpiSintel(FlowDataset):
    """Consecutive-frame pairs per scene (reference datasets.py:102-118)."""

    def __init__(self, aug_params=None, split="training",
                 root="datasets/Sintel", dstype="clean"):
        super().__init__(aug_params)
        self.split = split
        flow_root = osp.join(root, split, "flow")
        image_root = osp.join(root, split, dstype)
        if split == "test":
            self.is_test = True
        for scene in sorted(os.listdir(image_root)):
            images = sorted(glob(osp.join(image_root, scene, "*.png")))
            for i in range(len(images) - 1):
                self.image_list.append((images[i], images[i + 1]))
                self.extra_info.append((scene, i))
            if split != "test":
                self.flow_list += sorted(
                    glob(osp.join(flow_root, scene, "*.flo")))


class FlyingChairs(FlowDataset):
    """Train/val split via ``chairs_split.txt`` (reference
    datasets.py:121-134)."""

    def __init__(self, aug_params=None, split="training",
                 root="datasets/FlyingChairs_release/data",
                 split_file="chairs_split.txt"):
        super().__init__(aug_params)
        self.split = split
        images = sorted(glob(osp.join(root, "*.ppm")))
        flows = sorted(glob(osp.join(root, "*.flo")))
        assert len(images) // 2 == len(flows), (len(images), len(flows))
        split_ids = np.loadtxt(split_file, dtype=np.int32)
        if split in ("training", "train"):
            want = 1
        elif split in ("validation", "val"):
            want = 2
        else:
            raise ValueError(f"unknown FlyingChairs split: {split!r}")
        for i in range(len(flows)):
            if split_ids[i] == want:
                self.flow_list.append(flows[i])
                self.image_list.append((images[2 * i], images[2 * i + 1]))


class FlyingThings3D(FlowDataset):
    """Left camera, both temporal directions; the into_past direction swaps
    the image order (reference datasets.py:137-158)."""

    def __init__(self, aug_params=None, root="datasets/FlyingThings3D",
                 dstype="frames_cleanpass"):
        super().__init__(aug_params)
        self.split = dstype
        for cam in ["left"]:
            for direction in ["into_future", "into_past"]:
                image_dirs = sorted(glob(osp.join(root, dstype, "TRAIN/*/*")))
                image_dirs = sorted(osp.join(f, cam) for f in image_dirs)
                flow_dirs = sorted(
                    glob(osp.join(root, "optical_flow/TRAIN/*/*")))
                flow_dirs = sorted(
                    osp.join(f, direction, cam) for f in flow_dirs)
                for idir, fdir in zip(image_dirs, flow_dirs):
                    images = sorted(glob(osp.join(idir, "*.png")))
                    flows = sorted(glob(osp.join(fdir, "*.pfm")))
                    for i in range(len(flows) - 1):
                        if direction == "into_future":
                            self.image_list.append((images[i], images[i + 1]))
                            self.flow_list.append(flows[i])
                        else:
                            self.image_list.append((images[i + 1], images[i]))
                            self.flow_list.append(flows[i + 1])


class KITTI(FlowDataset):
    """Sparse ``*_10/_11.png`` pairs (reference datasets.py:161-177)."""

    def __init__(self, aug_params=None, split="training",
                 root="datasets/KITTI"):
        super().__init__(aug_params, sparse=True)
        self.split = split
        if split == "testing":
            self.is_test = True
        root = osp.join(root, split)
        images1 = sorted(glob(osp.join(root, "image_2/*_10.png")))
        images2 = sorted(glob(osp.join(root, "image_2/*_11.png")))
        for img1, img2 in zip(images1, images2):
            self.extra_info.append((osp.basename(img1),))
            self.image_list.append((img1, img2))
        if split == "training":
            self.flow_list = sorted(glob(osp.join(root, "flow_occ/*_10.png")))


class HD1K(FlowDataset):
    """Sparse HD1K sequences, scanned by sequence index (reference
    datasets.py:180-196)."""

    def __init__(self, aug_params=None, root="datasets/HD1k"):
        super().__init__(aug_params, sparse=True)
        seq_ix = 0
        while True:
            flows = sorted(glob(osp.join(
                root, "hd1k_flow_gt", "flow_occ/%06d_*.png" % seq_ix)))
            images = sorted(glob(osp.join(
                root, "hd1k_input", "image_2/%06d_*.png" % seq_ix)))
            if not flows:
                break
            for i in range(len(flows) - 1):
                self.flow_list.append(flows[i])
                self.image_list.append((images[i], images[i + 1]))
            seq_ix += 1


# ---------------------------------------------------------------------------
# Stage mixtures (reference fetch_dataloader, datasets.py:199-234)
# ---------------------------------------------------------------------------

def fetch_dataset(stage: str, image_size: Tuple[int, int],
                  root: str = "datasets", train_ds: str = "C+T+K+S+H",
                  split_file: str = "chairs_split.txt") -> FlowDataset:
    """Build the per-stage training mixture with the reference's aug params
    and replication weights (datasets.py:202-228)."""
    crop = {"crop_size": tuple(image_size)}
    if stage == "chairs":
        aug = dict(crop, min_scale=-0.1, max_scale=1.0, do_flip=True)
        return FlyingChairs(aug, split="training",
                            root=osp.join(root, "FlyingChairs_release/data"),
                            split_file=split_file)
    if stage == "things":
        aug = dict(crop, min_scale=-0.4, max_scale=0.8, do_flip=True)
        things_root = osp.join(root, "FlyingThings3D")
        clean = FlyingThings3D(aug, root=things_root,
                               dstype="frames_cleanpass")
        final = FlyingThings3D(aug, root=things_root,
                               dstype="frames_finalpass")
        return clean + final
    if stage == "sintel":
        aug = dict(crop, min_scale=-0.2, max_scale=0.6, do_flip=True)
        things = FlyingThings3D(aug, root=osp.join(root, "FlyingThings3D"),
                                dstype="frames_cleanpass")
        clean = MpiSintel(aug, split="training",
                          root=osp.join(root, "Sintel"), dstype="clean")
        final = MpiSintel(aug, split="training",
                          root=osp.join(root, "Sintel"), dstype="final")
        if train_ds == "C+T+K+S+H":
            kitti = KITTI(dict(crop, min_scale=-0.3, max_scale=0.5,
                               do_flip=True), root=osp.join(root, "KITTI"))
            hd1k = HD1K(dict(crop, min_scale=-0.5, max_scale=0.2,
                             do_flip=True), root=osp.join(root, "HD1k"))
            return 100 * clean + 100 * final + 200 * kitti + 5 * hd1k + things
        if train_ds == "C+T+K/S":
            return 100 * clean + 100 * final + things
        raise ValueError(f"unknown train_ds mixture: {train_ds!r}")
    if stage == "kitti":
        aug = dict(crop, min_scale=-0.2, max_scale=0.4, do_flip=False)
        return KITTI(aug, split="training", root=osp.join(root, "KITTI"))
    raise ValueError(f"unknown stage: {stage!r}")


# ---------------------------------------------------------------------------
# Sharded loader
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class ShardedLoader:
    """Globally-shuffled, per-host-sharded, thread-prefetched batch iterator.

    Replaces the reference's ``DataLoader(shuffle=True, num_workers=4,
    drop_last=True)`` (datasets.py:230-231).  Batches are dicts of stacked
    NHWC float32 arrays with leading dim = per-host batch size.
    """

    dataset: FlowDataset
    batch_size: int            # per-host batch size
    seed: int = 1234
    num_hosts: int = 1
    host_id: int = 0
    num_workers: int = 4
    drop_last: bool = True
    # Decode-window depth in BATCHES: how many batches of decode futures
    # the thread pool keeps in flight ahead of the consumer.  0 = the
    # legacy default of max(2*batch_size, 2*num_workers) SAMPLES.
    # Raise it when per-sample decode latency is spiky (network
    # filesystems) so a slow sample doesn't drain the window; it bounds
    # decoded-sample host RAM at ~prefetch_batches*batch_size samples.
    prefetch_batches: int = 0
    # Self-healing sample reads (docs/ROBUSTNESS.md): a decode/IO error
    # (ValueError/OSError — a corrupt image, a truncated .flo) is
    # retried ``sample_retries`` times against the SAME file (transient
    # filesystem flakes), then the sample is QUARANTINED — skipped with
    # a `sample_quarantine` JSONL event + `raft_data_quarantined_total`
    # counter — and a deterministic replacement index (keyed on
    # seed/epoch/index, NOT on wall clock or scheduling) is drawn so
    # batch shape and the rest of the stream are unchanged.  Up to
    # ``sample_resamples`` replacements are tried before the loader
    # gives up: one bad file costs one event, a rotten dataset still
    # fails loudly.  Non-decode errors (a loader bug) propagate.
    sample_retries: int = 1
    sample_resamples: int = 8
    # Telemetry destinations for quarantine; None = the process-wide
    # defaults (train() points these at its own sink/registry).
    sink: Optional[object] = None
    registry: Optional[object] = None

    def __post_init__(self):
        assert 0 <= self.host_id < self.num_hosts
        assert len(self.dataset) > 0, "empty dataset"
        assert self.prefetch_batches >= 0, self.prefetch_batches
        assert self.sample_retries >= 0, self.sample_retries
        assert self.sample_resamples >= 0, self.sample_resamples
        self.quarantined_total = 0
        self._quarantine_lock = threading.Lock()

    def epoch_indices(self, epoch: int) -> np.ndarray:
        """The host's sample indices for ``epoch`` — a disjoint stride of a
        global permutation shared by all hosts (same seed everywhere)."""
        rng = np.random.default_rng(
            np.random.SeedSequence([self.seed, epoch]))
        perm = rng.permutation(len(self.dataset))
        return perm[self.host_id::self.num_hosts]

    #: Seed-stream salt separating replacement-index draws from the
    #: per-sample augmentation streams (arbitrary constant).
    _RESAMPLE_SALT = 0x51A7

    def _load_one(self, epoch: int, index: int) -> Dict[str, np.ndarray]:
        """Load one sample, self-healing decode failures.

        Healthy path: identical to the pre-chaos loader (same RNG
        derivation, one ``dataset.load``).  On ValueError/OSError the
        sample is retried then quarantined and a deterministic
        replacement drawn — see the ``sample_retries`` field comment.
        """
        if chaos.should_inject("worker_err", point="data.loader_worker"):
            from raft_tpu.chaos import InjectedWorkerCrash

            raise InjectedWorkerCrash(
                "chaos-injected loader-worker crash (not a decode "
                "error: must fail the run, not quarantine)")
        index = int(index)
        idx, last_err = index, None
        for resample in range(self.sample_resamples + 1):
            for _attempt in range(self.sample_retries + 1):
                # Fresh generator per attempt: a failed load may have
                # consumed part of the stream, and the replacement
                # sample must see exactly the draw it would get were it
                # drawn first-class (stream determinism).
                rng = np.random.default_rng(
                    np.random.SeedSequence([self.seed, epoch, idx]))
                try:
                    return self.dataset.load(idx, rng)
                except (ValueError, OSError) as e:
                    last_err = e
            self._quarantine(epoch, index, idx, resample, last_err)
            r = np.random.default_rng(np.random.SeedSequence(
                [self.seed, epoch, index, self._RESAMPLE_SALT, resample]))
            idx = int(r.integers(len(self.dataset)))
        raise RuntimeError(
            f"sample {index} and {self.sample_resamples} replacement "
            f"draw(s) all failed to load — giving up (last error: "
            f"{type(last_err).__name__}: {last_err})") from last_err

    def _quarantine(self, epoch: int, index: int, idx: int,
                    resample: int, err: Exception) -> None:
        from raft_tpu.obs.events import default_sink
        from raft_tpu.obs.registry import default_registry

        with self._quarantine_lock:
            self.quarantined_total += 1
        reg = self.registry if self.registry is not None \
            else default_registry()
        reg.counter(
            "raft_data_quarantined_total",
            "samples skipped after repeated read failures "
            "(replaced by a deterministic resample)").inc()
        sink = self.sink if self.sink is not None else default_sink()
        sink.emit("sample_quarantine",
                  dataset=getattr(err, "dataset_name", None)
                  or getattr(self.dataset, "name",
                             type(self.dataset).__name__),
                  split=getattr(err, "split", None),
                  path=getattr(err, "path", None),
                  epoch=int(epoch), index=int(idx),
                  original_index=int(index), resample=int(resample),
                  retries=int(self.sample_retries),
                  error=f"{type(err).__name__}: {err}")

    def steps_per_epoch(self) -> int:
        """Per-host batches per epoch (constant across epochs: the global
        permutation is resharded but its length never changes)."""
        n = len(self.epoch_indices(0))
        return n // self.batch_size if self.drop_last \
            else -(-n // self.batch_size)

    def batches_from_step(self, step: int) -> Iterator[Dict[str, np.ndarray]]:
        """Resume the stream as if ``step`` batches had already been drawn —
        auto-resume continues the shuffle instead of replaying epoch 0."""
        spe = self.steps_per_epoch()
        if spe == 0:
            raise ValueError(
                f"per-host dataset share smaller than "
                f"batch_size={self.batch_size} with drop_last — no "
                "batches would ever be produced")
        start_epoch, skip = divmod(step, spe)
        return self.batches(start_epoch, skip_batches=skip)

    def batches(self, start_epoch: int = 0,
                skip_batches: int = 0) -> Iterator[Dict[str, np.ndarray]]:
        """Infinite batch stream, epoch after epoch (the reference wraps its
        loader in an outer while-loop, train.py:161-208).  ``skip_batches``
        drops the first batches of the first epoch without decoding them
        (checkpoint resume mid-epoch)."""
        from collections import deque

        epoch = start_epoch
        # Bounded prefetch: a fixed window of decode futures in flight,
        # so the workers can't race ahead of the consumer and buffer an
        # entire epoch of decoded samples in host RAM.  The depth is the
        # ``prefetch_batches`` knob (in batches); 0 keeps the legacy
        # ~2-batch default.
        window = (self.prefetch_batches * self.batch_size
                  if self.prefetch_batches > 0
                  else max(2 * self.batch_size, 2 * self.num_workers))
        with ThreadPoolExecutor(max_workers=self.num_workers) as pool:
            while True:
                idx = self.epoch_indices(epoch)
                n = len(idx)
                usable = (n // self.batch_size) * self.batch_size \
                    if self.drop_last else n
                if usable == 0:
                    raise ValueError(
                        f"per-host dataset share ({n} samples) smaller than "
                        f"batch_size={self.batch_size} with drop_last — no "
                        "batches would ever be produced")
                if epoch == start_epoch and skip_batches:
                    skipped = min(skip_batches * self.batch_size, usable)
                    idx = idx[skipped:]
                    usable -= skipped
                pending = deque()
                it = iter(idx[:usable])
                for i in it:
                    pending.append(pool.submit(self._load_one, epoch, i))
                    if len(pending) >= window:
                        break
                buf: List[Dict[str, np.ndarray]] = []
                while pending:
                    buf.append(pending.popleft().result())
                    nxt = next(it, None)
                    if nxt is not None:
                        pending.append(
                            pool.submit(self._load_one, epoch, nxt))
                    if len(buf) == self.batch_size:
                        yield {k: np.stack([b[k] for b in buf])
                               for k in buf[0]}
                        buf = []
                if buf and not self.drop_last:
                    yield {k: np.stack([b[k] for b in buf]) for k in buf[0]}
                epoch += 1
