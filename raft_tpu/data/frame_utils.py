"""Optical-flow file formats: Middlebury .flo, Sintel .pfm, KITTI 16-bit PNG.

Parity with the reference ``core/utils/frame_utils.py`` (C10 in SURVEY.md),
re-implemented cv2-free: images load through PIL, KITTI 16-bit PNGs through
the NumPy codec in :mod:`raft_tpu.data.png16`.

Conventions: flow arrays are ``(H, W, 2)`` float32 with channel order
``(u, v) = (x, y)`` displacement; KITTI readers additionally return an
``(H, W)`` validity array.
"""

from __future__ import annotations

import os
import re
from typing import Tuple, Union

import numpy as np
from PIL import Image

from raft_tpu.data.png16 import read_png, write_png

#: Middlebury .flo magic number (frame_utils.py:10).
FLO_MAGIC = 202021.25


def read_flo(path: str) -> np.ndarray:
    """Read a Middlebury ``.flo`` file -> ``(H, W, 2)`` float32
    (reference ``readFlow``, frame_utils.py:12-31)."""
    with open(path, "rb") as f:
        magic = np.fromfile(f, "<f4", count=1)
        if magic.size == 0 or magic[0] != np.float32(FLO_MAGIC):
            raise ValueError(f"{path}: bad .flo magic {magic}")
        w = int(np.fromfile(f, "<i4", count=1)[0])
        h = int(np.fromfile(f, "<i4", count=1)[0])
        data = np.fromfile(f, "<f4", count=2 * w * h)
    if data.size != 2 * w * h:
        raise ValueError(f"{path}: truncated .flo ({data.size} floats)")
    return data.reshape(h, w, 2)


def write_flo(path: str, flow: np.ndarray) -> None:
    """Write ``(H, W, 2)`` flow as Middlebury ``.flo``
    (reference ``writeFlow``, frame_utils.py:70-99)."""
    flow = np.asarray(flow, np.float32)
    assert flow.ndim == 3 and flow.shape[2] == 2, flow.shape
    h, w, _ = flow.shape
    with open(path, "wb") as f:
        np.array([FLO_MAGIC], "<f4").tofile(f)
        np.array([w, h], "<i4").tofile(f)
        flow.astype("<f4").tofile(f)


def read_pfm(path: str) -> np.ndarray:
    """Read a ``.pfm`` (Sintel/Things disparity+flow container) with
    endianness handling (reference ``readPFM``, frame_utils.py:33-68).
    Returns ``(H, W)`` or ``(H, W, 3)`` float32, top row first."""
    with open(path, "rb") as f:
        header = f.readline().rstrip()
        if header == b"PF":
            color = True
        elif header == b"Pf":
            color = False
        else:
            raise ValueError(f"{path}: not a PFM file")
        m = re.match(rb"^(\d+)\s(\d+)\s*$", f.readline())
        if not m:
            raise ValueError(f"{path}: malformed PFM header")
        w, h = map(int, m.groups())
        scale = float(f.readline().rstrip())
        endian = "<" if scale < 0 else ">"
        data = np.fromfile(f, endian + "f4")
    shape = (h, w, 3) if color else (h, w)
    # PFM stores bottom row first.
    return np.flipud(data.reshape(shape)).astype(np.float32)


def write_pfm(path: str, data: np.ndarray) -> None:
    """Write a ``.pfm``: (H, W) -> ``Pf``, (H, W, 3) -> ``PF``; top row
    first in memory, stored bottom-up little-endian (scale -1.0) — the
    exact inverse of :func:`read_pfm`.  (The reference only reads PFM;
    the writer exists for synthetic-corpus fixtures.)"""
    data = np.asarray(data, np.float32)
    if data.ndim == 2:
        header = b"Pf"
    elif data.ndim == 3 and data.shape[2] == 3:
        header = b"PF"
    else:
        raise ValueError(f"PFM needs (H,W) or (H,W,3), got {data.shape}")
    with open(path, "wb") as f:
        f.write(header + b"\n")
        f.write(f"{data.shape[1]} {data.shape[0]}\n".encode())
        f.write(b"-1.0\n")
        np.flipud(data).astype("<f4").tofile(f)


def read_flow_kitti(path: str) -> Tuple[np.ndarray, np.ndarray]:
    """KITTI flow PNG: ``flow = (png_uint16 - 2^15) / 64``; the 3rd channel
    is the validity mask (reference ``readFlowKITTI``,
    frame_utils.py:102-107)."""
    png = read_png(path).astype(np.float32)
    if png.ndim != 3 or png.shape[2] < 3:
        raise ValueError(f"{path}: expected 3-channel KITTI flow PNG")
    flow = (png[:, :, :2] - 2 ** 15) / 64.0
    valid = png[:, :, 2]
    return flow, valid


def write_flow_kitti(path: str, flow: np.ndarray) -> None:
    """Inverse of :func:`read_flow_kitti` with all-valid mask (reference
    ``writeFlowKITTI``, frame_utils.py:116-120)."""
    flow = np.asarray(flow)
    uv = (64.0 * flow[:, :, :2] + 2 ** 15)
    valid = np.ones(flow.shape[:2] + (1,), np.float64)
    png = np.concatenate([uv, valid], axis=-1).astype(np.uint16)
    write_png(path, png)


def read_disp_kitti(path: str) -> Tuple[np.ndarray, np.ndarray]:
    """KITTI disparity PNG -> ``(flow, valid)`` with ``u = -disp``
    (reference ``readDispKITTI``, frame_utils.py:109-113)."""
    disp = read_png(path).astype(np.float32) / 256.0
    valid = disp > 0.0
    flow = np.stack([-disp, np.zeros_like(disp)], axis=-1)
    return flow, valid


def read_image(path: str) -> np.ndarray:
    """Load an image as ``(H, W, C)`` uint8 (RGB or grayscale)."""
    with Image.open(path) as im:
        return np.array(im)


def read_gen(path: str) -> np.ndarray:
    """Extension-dispatch reader (reference ``read_gen``,
    frame_utils.py:123-137).  Images -> uint8 arrays, ``.flo``/``.pfm`` ->
    float32 flow (PFM with the disparity channel dropped)."""
    ext = os.path.splitext(path)[-1].lower()
    if ext in (".png", ".jpeg", ".jpg", ".ppm", ".bmp"):
        return read_image(path)
    if ext in (".bin", ".raw", ".npy"):
        return np.load(path)
    if ext == ".flo":
        return read_flo(path).astype(np.float32)
    if ext == ".pfm":
        flow = read_pfm(path)
        return flow if flow.ndim == 2 else flow[:, :, :-1]
    raise ValueError(f"unsupported extension: {path}")
