"""Minimal pure-NumPy PNG codec for 16-bit images.

KITTI optical-flow ground truth is stored as 16-bit-per-channel RGB PNG
(reference ``core/utils/frame_utils.py:102-120`` reads it with
``cv2.IMREAD_ANYDEPTH``).  Neither PIL nor imageio in this environment can
round-trip 16-bit RGB PNGs, so we decode the format directly: parse chunks,
inflate the IDAT stream with :mod:`zlib`, and undo per-row filters with
NumPy — filters 0/1/2 fully vectorized, Average/Paeth walking pixel columns
with all byte lanes vectorized (real encoders emit Paeth-heavy files).

Supports non-interlaced, 8- or 16-bit, grayscale / RGB / RGBA.  Writes
16-bit big-endian PNGs with filter type 0.
"""

from __future__ import annotations

import struct
import zlib

import numpy as np

from raft_tpu.native import build as _native

_SIGNATURE = b"\x89PNG\r\n\x1a\n"
_CHANNELS = {0: 1, 2: 3, 4: 2, 6: 4}  # color type -> channel count


def read_png(path: str) -> np.ndarray:
    """Decode a PNG into ``(H, W)`` or ``(H, W, C)`` uint8/uint16."""
    with open(path, "rb") as f:
        raw = f.read()
    if raw[:8] != _SIGNATURE:
        raise ValueError(f"{path}: not a PNG file")

    pos = 8
    idat = []
    header = None
    while pos < len(raw):
        (length,) = struct.unpack(">I", raw[pos:pos + 4])
        ctype = raw[pos + 4:pos + 8]
        data = raw[pos + 8:pos + 8 + length]
        pos += length + 12  # length + type + data + crc
        if ctype == b"IHDR":
            header = struct.unpack(">IIBBBBB", data)
        elif ctype == b"IDAT":
            idat.append(data)
        elif ctype == b"IEND":
            break
    if header is None:
        raise ValueError(f"{path}: missing IHDR")
    width, height, depth, color, _comp, _filt, interlace = header
    if interlace:
        raise NotImplementedError(f"{path}: interlaced PNG unsupported")
    if color not in _CHANNELS or depth not in (8, 16):
        raise NotImplementedError(
            f"{path}: unsupported color type {color} / bit depth {depth}")

    nch = _CHANNELS[color]
    bpp = nch * depth // 8           # filter unit: bytes per pixel
    stride = width * bpp
    flat = np.frombuffer(zlib.decompress(b"".join(idat)), np.uint8)
    if flat.size != height * (stride + 1):
        raise ValueError(
            f"{path}: IDAT inflates to {flat.size} bytes, expected "
            f"{height * (stride + 1)}")

    data8 = _unfilter(flat, height, stride, bpp)

    if depth == 16:
        img = data8.reshape(height, width, nch, 2)
        img = (img[..., 0].astype(np.uint16) << 8) | img[..., 1]
    else:
        img = data8.reshape(height, width, nch)
    if nch == 1:
        img = img[..., 0]
    return img


def _unfilter(flat: np.ndarray, height: int, stride: int,
              bpp: int) -> np.ndarray:
    """Undo per-row PNG filters on the inflated scanline stream
    ``(height, 1 + stride)`` -> ``(height, stride)`` uint8."""
    lib = _native.load()
    if lib is not None:
        out = np.empty(height * stride, np.uint8)
        src = np.ascontiguousarray(flat)
        bad = lib.png_unfilter(
            src.ctypes.data, out.ctypes.data, height, stride, bpp)
        if bad:
            raise ValueError(f"bad filter type {bad}")
        return out.reshape(height, stride)

    rows = flat.reshape(height, stride + 1)
    ftypes = rows[:, 0]
    scan = rows[:, 1:].astype(np.int64)  # room for filter arithmetic

    # Unfilter. Rows depend on the row above, but within a row everything is
    # vectorizable per byte-lane: Sub is a running sum over pixel columns
    # (cumsum mod 256), Average/Paeth walk pixel columns with all bpp lanes
    # at once — width iterations instead of width*bpp.
    npix = stride // bpp
    out = np.zeros_like(scan)
    prev = np.zeros(stride, np.int64)
    for y in range(height):
        line = scan[y]
        ft = ftypes[y]
        if ft == 0:
            cur = line.copy()
        elif ft == 1:  # Sub: cumulative sum along pixel columns, mod 256
            cur = (np.cumsum(line.reshape(npix, bpp), axis=0) & 0xFF).ravel()
        elif ft == 2:  # Up
            cur = (line + prev) & 0xFF
        elif ft == 3:  # Average
            cur = line.reshape(npix, bpp).copy()
            pv = prev.reshape(npix, bpp)
            a = np.zeros(bpp, np.int64)
            for x in range(npix):
                a = (cur[x] + ((a + pv[x]) >> 1)) & 0xFF
                cur[x] = a
            cur = cur.ravel()
        elif ft == 4:  # Paeth
            cur = line.reshape(npix, bpp).copy()
            pv = prev.reshape(npix, bpp)
            a = np.zeros(bpp, np.int64)
            c = np.zeros(bpp, np.int64)
            for x in range(npix):
                b = pv[x]
                p = a + b - c
                pa, pb, pc = np.abs(p - a), np.abs(p - b), np.abs(p - c)
                pred = np.where((pa <= pb) & (pa <= pc), a,
                                np.where(pb <= pc, b, c))
                a = (cur[x] + pred) & 0xFF
                cur[x] = a
                c = b
            cur = cur.ravel()
        else:
            raise ValueError(f"bad filter type {ft}")
        out[y] = cur
        prev = cur

    return out.astype(np.uint8)


def write_png(path: str, img: np.ndarray) -> None:
    """Encode ``(H, W)`` or ``(H, W, C)`` uint8/uint16 as PNG (filter 0)."""
    img = np.asarray(img)
    if img.ndim == 2:
        img = img[..., None]
    h, w, nch = img.shape
    color = {1: 0, 2: 4, 3: 2, 4: 6}[nch]
    if img.dtype == np.uint16:
        depth = 16
        payload = img.astype(">u2").tobytes()
        stride = w * nch * 2
    elif img.dtype == np.uint8:
        depth = 8
        payload = img.tobytes()
        stride = w * nch
    else:
        raise TypeError(f"dtype {img.dtype} not supported (uint8/uint16)")

    rows = np.frombuffer(payload, np.uint8).reshape(h, stride)
    scan = np.concatenate([np.zeros((h, 1), np.uint8), rows], axis=1)

    def chunk(ctype: bytes, data: bytes) -> bytes:
        body = ctype + data
        return (struct.pack(">I", len(data)) + body
                + struct.pack(">I", zlib.crc32(body) & 0xFFFFFFFF))

    ihdr = struct.pack(">IIBBBBB", w, h, depth, color, 0, 0, 0)
    out = (_SIGNATURE + chunk(b"IHDR", ihdr)
           + chunk(b"IDAT", zlib.compress(scan.tobytes(), 6))
           + chunk(b"IEND", b""))
    with open(path, "wb") as f:
        f.write(out)
