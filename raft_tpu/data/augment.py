"""Host-side data augmentation (NumPy/cv2/PIL; no torch).

Behavioral parity with the reference ``core/utils/augmentor.py`` (C9 in
SURVEY.md): photometric color jitter (asymmetric w.p. 0.2 for dense data,
augmentor.py:40-48), occlusion "eraser" rectangles on frame 2
(augmentor.py:52-65), random scale with independent x/y stretch
(augmentor.py:67-89), h/v flips with flow sign fixes (augmentor.py:91-100),
random crop (augmentor.py:102-107), and the sparse variant's
nearest-neighbor flow-map rescale (augmentor.py:161-193).

TPU-first redesign choices:

- All randomness flows through an explicit ``np.random.Generator`` instead
  of global ``np.random`` state, so augmentation is deterministic per
  ``(seed, host, step, sample)`` — reproducible across pod restarts and
  shardable across hosts without the reference's per-worker reseed hack
  (datasets.py:45-51).
- The torchvision ``ColorJitter`` dependency is replaced by a NumPy/PIL
  implementation with the same parameterization (brightness/contrast/
  saturation factors ~ U[1-x, 1+x], hue shift ~ U[-h, h], applied in a
  random order, matching torchvision semantics for PIL inputs).
- Layout is NHWC throughout (TPU-native); images stay uint8 until batching.
"""

from __future__ import annotations

import ctypes
import dataclasses
from typing import Optional, Tuple

import numpy as np

import cv2

cv2.setNumThreads(0)  # decode/augment parallelism is ours, not cv2's
try:
    cv2.ocl.setUseOpenCL(False)
except AttributeError:  # minimal cv2 builds
    pass


# ---------------------------------------------------------------------------
# Native acceleration (raft_tpu/native/aug_ops.c)
#
# The C kernels fuse resize+flip+crop into one inverse-mapped pass over the
# output crop only, and run the photometric ops without float temporaries
# (~4x per-core vs the cv2/NumPy path, and they release the GIL so the
# loader's thread pool scales).  Every use degrades to the NumPy/cv2 code
# below when the library is unavailable; both paths consume the RNG in the
# same order, so seeds stay portable between them.
# ---------------------------------------------------------------------------

def _nlib():
    import os

    if os.environ.get("RAFT_TPU_NO_NATIVE_AUG"):
        return None
    from raft_tpu.native.build import load

    return load()


def _warp_native(lib, arr: np.ndarray, crop: Tuple[int, int], sx: float,
                 sy: float, rh: int, rw: int, hflip: bool, vflip: bool,
                 x0: int, y0: int,
                 chan_scale: Optional[np.ndarray] = None) -> np.ndarray:
    """Fused resize (cv2 center-aligned bilinear) + flip + crop."""
    arr = np.ascontiguousarray(arr)
    h, w = arr.shape[:2]
    c = 1 if arr.ndim == 2 else arr.shape[2]
    out_shape = (crop[0], crop[1]) if arr.ndim == 2 \
        else (crop[0], crop[1], c)
    out = np.empty(out_shape, arr.dtype)
    args = (arr.ctypes.data, h, w, c, out.ctypes.data, crop[0], crop[1],
            sx, sy, rh, rw, int(hflip), int(vflip), x0, y0)
    if arr.dtype == np.uint8:
        lib.aug_warp_u8(*args)
    else:
        if chan_scale is None:
            chan_scale = np.ones(c, np.float32)
        cs = np.ascontiguousarray(chan_scale, np.float32)
        lib.aug_warp_f32(*args, cs.ctypes.data)
    return out


# ---------------------------------------------------------------------------
# Photometric jitter (torchvision ColorJitter equivalent)
# ---------------------------------------------------------------------------

def _native_buf(img: np.ndarray, inplace: bool) -> np.ndarray:
    """Contiguous uint8 buffer for an in-place C kernel (reused verbatim
    when the caller already owns a mutable working copy)."""
    if inplace and img.dtype == np.uint8 and img.flags.c_contiguous:
        return img
    return np.array(img, dtype=np.uint8, order="C")


def _adjust_brightness(img: np.ndarray, factor: float,
                       inplace: bool = False) -> np.ndarray:
    # PIL ImageEnhance.Brightness: blend with black.
    lib = _nlib()
    if lib is not None:
        out = _native_buf(img, inplace)
        lib.aug_brightness(out.ctypes.data, out.size, factor)
        return out
    return np.clip(img.astype(np.float32) * factor, 0, 255).astype(np.uint8)


def _adjust_contrast(img: np.ndarray, factor: float,
                     inplace: bool = False) -> np.ndarray:
    # PIL ImageEnhance.Contrast: blend with the mean-gray image; PIL uses
    # int(round(mean of L channel)).
    lib = _nlib()
    if lib is not None:
        out = _native_buf(img, inplace)
        n_px = out.size // 3
        mean = round(lib.aug_gray_sum(out.ctypes.data, n_px) / n_px)
        lib.aug_contrast(out.ctypes.data, out.size, factor, float(mean))
        return out
    gray = cv2.cvtColor(img, cv2.COLOR_RGB2GRAY)
    mean = round(float(gray.mean()))
    out = img.astype(np.float32) * factor + mean * (1.0 - factor)
    return np.clip(out, 0, 255).astype(np.uint8)


def _adjust_saturation(img: np.ndarray, factor: float,
                       inplace: bool = False) -> np.ndarray:
    # PIL ImageEnhance.Color: blend with the grayscale image.
    lib = _nlib()
    if lib is not None:
        out = _native_buf(img, inplace)
        lib.aug_saturation(out.ctypes.data, out.size // 3, factor)
        return out
    gray = cv2.cvtColor(img, cv2.COLOR_RGB2GRAY)[..., None].astype(np.float32)
    out = img.astype(np.float32) * factor + gray * (1.0 - factor)
    return np.clip(out, 0, 255).astype(np.uint8)


def _adjust_hue(img: np.ndarray, shift: float,
                inplace: bool = False) -> np.ndarray:
    # shift in [-0.5, 0.5] turns of the hue circle (torchvision convention).
    lib = _nlib()
    if lib is not None:
        out = _native_buf(img, inplace)
        lib.aug_hue_shift(out.ctypes.data, out.size // 3,
                          int(round(shift * 180.0)))
        return out
    hsv = cv2.cvtColor(img, cv2.COLOR_RGB2HSV)
    h = hsv[..., 0].astype(np.int16)  # cv2 uint8 hue is [0, 180)
    h = (h + int(round(shift * 180.0))) % 180
    hsv[..., 0] = h.astype(np.uint8)
    return cv2.cvtColor(hsv, cv2.COLOR_HSV2RGB)


@dataclasses.dataclass(frozen=True)
class ColorJitter:
    """Photometric jitter with torchvision's parameter conventions
    (reference augmentor.py:32,138)."""

    brightness: float = 0.4
    contrast: float = 0.4
    saturation: float = 0.4
    hue: float = 0.5 / 3.14

    def __call__(self, rng: np.random.Generator, img: np.ndarray) -> np.ndarray:
        factors = {
            "brightness": rng.uniform(max(0.0, 1 - self.brightness),
                                      1 + self.brightness),
            "contrast": rng.uniform(max(0.0, 1 - self.contrast),
                                    1 + self.contrast),
            "saturation": rng.uniform(max(0.0, 1 - self.saturation),
                                      1 + self.saturation),
            "hue": rng.uniform(-self.hue, self.hue),
        }
        ops = ["brightness", "contrast", "saturation", "hue"]
        order = rng.permutation(ops)

        # One working copy mutated in place by the native kernels (three
        # extra 6 MB per-op copies per sample add up); the NumPy fallback
        # inside each _adjust_* ignores ``inplace`` and returns fresh
        # arrays as before.
        if _nlib() is not None:
            img = np.array(img, dtype=np.uint8, order="C")
        for name in order:
            f = factors[str(name)]
            if name == "brightness":
                img = _adjust_brightness(img, f, inplace=True)
            elif name == "contrast":
                img = _adjust_contrast(img, f, inplace=True)
            elif name == "saturation":
                img = _adjust_saturation(img, f, inplace=True)
            else:
                img = _adjust_hue(img, f, inplace=True)
        return img


# ---------------------------------------------------------------------------
# Dense augmentor
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class FlowAugmentor:
    """Dense-flow augmentation (reference FlowAugmentor, augmentor.py:15-120).

    ``crop_size`` is ``(H, W)``.  Scale is ``2**U(min_scale, max_scale)``
    with independent x/y stretch w.p. 0.8 (augmentor.py:74-82); the scale is
    clamped so the result fits ``crop + 8`` pixels (augmentor.py:70-72).
    """

    crop_size: Tuple[int, int]
    min_scale: float = -0.2
    max_scale: float = 0.5
    do_flip: bool = True
    spatial_aug_prob: float = 0.8
    stretch_prob: float = 0.8
    max_stretch: float = 0.2
    h_flip_prob: float = 0.5
    v_flip_prob: float = 0.1
    asymmetric_color_aug_prob: float = 0.2
    eraser_aug_prob: float = 0.5
    jitter: ColorJitter = dataclasses.field(default_factory=ColorJitter)

    def color_transform(self, rng, img1, img2):
        if rng.random() < self.asymmetric_color_aug_prob:
            return self.jitter(rng, img1), self.jitter(rng, img2)
        stack = np.concatenate([img1, img2], axis=0)
        stack = self.jitter(rng, stack)
        return np.split(stack, 2, axis=0)

    def eraser_transform(self, rng, img1, img2, bounds=(50, 100)):
        ht, wd = img1.shape[:2]
        if rng.random() < self.eraser_aug_prob:
            # ONE draw sequence for both backends — the native/NumPy seed
            # portability contract hangs on identical RNG consumption.
            rects = [(int(rng.integers(0, wd)), int(rng.integers(0, ht)),
                      int(rng.integers(bounds[0], bounds[1])),
                      int(rng.integers(bounds[0], bounds[1])))
                     for _ in range(rng.integers(1, 3))]
            lib = _nlib()
            if lib is not None:
                img2 = _native_buf(img2, inplace=False)
                sums = (ctypes.c_double * 3)()
                n_px = img2.size // 3
                lib.aug_channel_sums(img2.ctypes.data, n_px, sums)
                # numpy's float64 mean assigned into a uint8 array
                # truncates; replicate that cast exactly
                mc = [int(s / n_px) for s in sums]
                for x0, y0, dx, dy in rects:
                    lib.aug_fill_rect(img2.ctypes.data, ht, wd, y0, x0,
                                      dy, dx, mc[0], mc[1], mc[2])
                return img1, img2
            img2 = img2.copy()
            mean_color = img2.reshape(-1, 3).mean(axis=0)
            for x0, y0, dx, dy in rects:
                img2[y0:y0 + dy, x0:x0 + dx, :] = mean_color
        return img1, img2

    def _sample_scales(self, rng, ht, wd, pad):
        floor = max((self.crop_size[0] + pad) / float(ht),
                    (self.crop_size[1] + pad) / float(wd))
        scale = 2.0 ** rng.uniform(self.min_scale, self.max_scale)
        sx = sy = scale
        if rng.random() < self.stretch_prob:
            sx *= 2.0 ** rng.uniform(-self.max_stretch, self.max_stretch)
            sy *= 2.0 ** rng.uniform(-self.max_stretch, self.max_stretch)
        return max(sx, floor), max(sy, floor)

    def spatial_transform(self, rng, img1, img2, flow):
        ht, wd = img1.shape[:2]
        sx, sy = self._sample_scales(rng, ht, wd, pad=8)

        # All random decisions are drawn up front in the historical order,
        # so the native fused path and the cv2 fallback consume the RNG
        # identically (seeds stay portable between them).
        if not (rng.random() < self.spatial_aug_prob):
            sx = sy = 1.0
        hflip = vflip = False
        if self.do_flip:
            hflip = rng.random() < self.h_flip_prob
            vflip = rng.random() < self.v_flip_prob
        # cv2.resize dsize rounding is cvRound = round-half-to-even.
        rh = int(np.rint(ht * sy)) if sy != 1.0 else ht
        rw = int(np.rint(wd * sx)) if sx != 1.0 else wd
        # max(1, .): images exactly crop-sized (possible when the 20%
        # no-resize branch is drawn) crop at the origin instead of
        # crashing — the reference's np.random.randint(0, 0) raises here
        # (augmentor.py:103-104).
        y0 = int(rng.integers(0, max(1, rh - self.crop_size[0])))
        x0 = int(rng.integers(0, max(1, rw - self.crop_size[1])))

        lib = _nlib()
        if lib is not None:
            # One fused inverse-mapped pass per array, computed over the
            # output crop only; the flow unit rescale and flip sign fixes
            # fold into the f32 kernel's channel scale.
            cs = np.array([sx * (-1.0 if hflip else 1.0),
                           sy * (-1.0 if vflip else 1.0)], np.float32)
            warp = lambda a, s: _warp_native(
                lib, a, self.crop_size, sx, sy, rh, rw, hflip, vflip,
                x0, y0, s)
            return warp(img1, None), warp(img2, None), warp(flow, cs)

        if sx != 1.0 or sy != 1.0:
            img1 = cv2.resize(img1, None, fx=sx, fy=sy,
                              interpolation=cv2.INTER_LINEAR)
            img2 = cv2.resize(img2, None, fx=sx, fy=sy,
                              interpolation=cv2.INTER_LINEAR)
            flow = cv2.resize(flow, None, fx=sx, fy=sy,
                              interpolation=cv2.INTER_LINEAR)
            flow = flow * [sx, sy]
        if hflip:
            img1 = img1[:, ::-1]
            img2 = img2[:, ::-1]
            flow = flow[:, ::-1] * [-1.0, 1.0]
        if vflip:
            img1 = img1[::-1, :]
            img2 = img2[::-1, :]
            flow = flow[::-1, :] * [1.0, -1.0]
        sl = np.s_[y0:y0 + self.crop_size[0], x0:x0 + self.crop_size[1]]
        return img1[sl], img2[sl], flow[sl]

    def __call__(self, rng: np.random.Generator, img1, img2, flow):
        img1, img2 = self.color_transform(rng, img1, img2)
        img1, img2 = self.eraser_transform(rng, img1, img2)
        img1, img2, flow = self.spatial_transform(rng, img1, img2, flow)
        return (np.ascontiguousarray(img1), np.ascontiguousarray(img2),
                np.ascontiguousarray(flow.astype(np.float32)))


# ---------------------------------------------------------------------------
# Sparse augmentor (KITTI / HD1K)
# ---------------------------------------------------------------------------

def resize_sparse_flow_map(flow, valid, fx=1.0, fy=1.0):
    """Rescale a sparse flow field by scattering valid samples into the
    resized grid at rounded coordinates (reference augmentor.py:161-193 —
    bilinear resize would corrupt flow at valid/invalid boundaries)."""
    ht, wd = flow.shape[:2]
    ys, xs = np.nonzero(valid >= 1)
    flow0 = flow[ys, xs].astype(np.float32)

    ht1 = int(round(ht * fy))
    wd1 = int(round(wd * fx))
    xx = np.round(xs * fx).astype(np.int32)
    yy = np.round(ys * fy).astype(np.int32)
    flow1 = flow0 * [fx, fy]

    keep = (xx > 0) & (xx < wd1) & (yy > 0) & (yy < ht1)
    flow_img = np.zeros([ht1, wd1, 2], dtype=np.float32)
    valid_img = np.zeros([ht1, wd1], dtype=np.int32)
    flow_img[yy[keep], xx[keep]] = flow1[keep]
    valid_img[yy[keep], xx[keep]] = 1
    return flow_img, valid_img


@dataclasses.dataclass
class SparseFlowAugmentor(FlowAugmentor):
    """Sparse-flow augmentation for KITTI/HD1K (reference
    SparseFlowAugmentor, augmentor.py:122-246): symmetric-only color with
    softer jitter, no stretch, h-flip only, crop offsets biased past the
    image border by (20, 50) margins then clamped (augmentor.py:220-227)."""

    do_flip: bool = False
    jitter: ColorJitter = dataclasses.field(default_factory=lambda: ColorJitter(
        brightness=0.3, contrast=0.3, saturation=0.3, hue=0.3 / 3.14))
    margin_y: int = 20
    margin_x: int = 50

    def color_transform(self, rng, img1, img2):  # symmetric only
        stack = np.concatenate([img1, img2], axis=0)
        stack = self.jitter(rng, stack)
        return np.split(stack, 2, axis=0)

    def spatial_transform(self, rng, img1, img2, flow, valid):
        ht, wd = img1.shape[:2]
        floor = max((self.crop_size[0] + 1) / float(ht),
                    (self.crop_size[1] + 1) / float(wd))
        scale = 2.0 ** rng.uniform(self.min_scale, self.max_scale)
        sx = sy = max(scale, floor)

        if not (rng.random() < self.spatial_aug_prob):
            sx = sy = 1.0
        hflip = self.do_flip and rng.random() < 0.5
        # Sparse flow/valid keep the NumPy scatter rescale (nearest-
        # neighbor semantics, augmentor.py:161-193); only the two images
        # take the native fused path.
        if sx != 1.0:
            flow, valid = resize_sparse_flow_map(flow, valid, fx=sx, fy=sy)
        rh, rw = valid.shape[:2]
        if hflip:
            flow = flow[:, ::-1] * [-1.0, 1.0]
            valid = valid[:, ::-1]

        y0 = int(rng.integers(0, rh - self.crop_size[0] + self.margin_y))
        x0 = int(rng.integers(-self.margin_x,
                              rw - self.crop_size[1] + self.margin_x))
        y0 = int(np.clip(y0, 0, rh - self.crop_size[0]))
        x0 = int(np.clip(x0, 0, rw - self.crop_size[1]))
        sl = np.s_[y0:y0 + self.crop_size[0], x0:x0 + self.crop_size[1]]

        lib = _nlib()
        if lib is not None:
            warp = lambda a: _warp_native(
                lib, a, self.crop_size, sx, sy, rh, rw, hflip, False,
                x0, y0)
            return warp(img1), warp(img2), flow[sl], valid[sl]

        if sx != 1.0:
            img1 = cv2.resize(img1, None, fx=sx, fy=sy,
                              interpolation=cv2.INTER_LINEAR)
            img2 = cv2.resize(img2, None, fx=sx, fy=sy,
                              interpolation=cv2.INTER_LINEAR)
        if hflip:
            img1 = img1[:, ::-1]
            img2 = img2[:, ::-1]
        return img1[sl], img2[sl], flow[sl], valid[sl]

    def __call__(self, rng, img1, img2, flow, valid):  # type: ignore[override]
        img1, img2 = self.color_transform(rng, img1, img2)
        img1, img2 = self.eraser_transform(rng, img1, img2)
        img1, img2, flow, valid = self.spatial_transform(
            rng, img1, img2, flow, valid)
        return (np.ascontiguousarray(img1), np.ascontiguousarray(img2),
                np.ascontiguousarray(flow.astype(np.float32)),
                np.ascontiguousarray(valid))
