"""Host-side data subsystem: file formats, datasets, augmentation, loading."""

from raft_tpu.data.frame_utils import (  # noqa: F401
    read_disp_kitti,
    read_flo,
    read_flow_kitti,
    read_gen,
    read_image,
    read_pfm,
    write_flo,
    write_flow_kitti,
)
from raft_tpu.data.png16 import read_png, write_png  # noqa: F401
