"""Host-side data subsystem: file formats, datasets, augmentation, loading."""

from raft_tpu.data.frame_utils import (  # noqa: F401
    read_disp_kitti,
    read_flo,
    read_flow_kitti,
    read_gen,
    read_image,
    read_pfm,
    write_flo,
    write_flow_kitti,
)
from raft_tpu.data.png16 import read_png, write_png  # noqa: F401


def __getattr__(name):
    # Lazy: datasets/augment pull in cv2; keep bare `import raft_tpu.data`
    # light for codec-only users.
    _lazy = {
        "FlowDataset", "ConcatFlowDataset", "MpiSintel", "FlyingChairs",
        "FlyingThings3D", "KITTI", "HD1K", "ShardedLoader", "fetch_dataset",
    }
    if name in _lazy:
        from raft_tpu.data import datasets as _d
        return getattr(_d, name)
    if name in {"FlowAugmentor", "SparseFlowAugmentor", "ColorJitter"}:
        from raft_tpu.data import augment as _a
        return getattr(_a, name)
    if name == "DevicePipeline":
        from raft_tpu.data.prefetch import DevicePipeline
        return DevicePipeline
    raise AttributeError(name)
