"""Background device-prefetch: the overlapped training input pipeline.

The serial loop (PRs 0-2) ran fetch -> noise -> ``shard_batch``
(``jax.device_put``) -> step entirely on the consumer thread, so host
decode, host prep, and the H2D enqueue all sat in the step's critical
path — exactly the gap PR 2's ``data_wait_s`` input-bound detector made
visible.  :class:`DevicePipeline` moves stages (2) host prep (noise
injection / stacking) and (3) device placement onto ONE background
producer thread feeding a bounded buffer, so while the device runs step
N the producer is already prepping and transferring batches
N+1..N+depth.  ``device_put`` dispatch is async, so "transfer" costs the
producer only the enqueue; the copy itself overlaps device compute.
RAFT's 12-32 refinement iterations make each step long enough to hide
all of it (PAPER.md; docs/PERFORMANCE.md has the overlap model).

Ordering/determinism contract: exactly ONE producer pulls the host
iterator and applies ``prep_fn`` in stream order — the same order the
serial path uses — so a *stateful* prep (the noise RNG keyed on the
resume step, ``raft_tpu/train/loop.py``) sees an identical call
sequence whether the pipeline is buffered (``depth > 0``) or serial
(``depth == 0``), and resume via ``ShardedLoader.batches_from_step``
stays bit-equivalent.  ``depth == 0`` is not a degraded mode but the
exact old serial path (prep + put inline in ``__next__``), kept for
A/B against the overlapped one.

Boundedness: a semaphore of ``depth`` slots is acquired BEFORE the
producer touches the source iterator and released when the consumer
takes a batch, so at most ``depth`` batches are ever held by the
pipeline (pulled-but-undelivered) beyond the one the consumer is
stepping on — the device buffers of a deep queue would otherwise
accumulate in HBM.

Telemetry: the producer times both stages per batch and the consumer
reads them (``last_prep_s`` / ``last_h2d_s``) right after ``next()``,
so the train loop can split the old ``data_wait_s`` into consumer-side
queue wait (the true input-bound signal under overlap) and the
producer-side ``h2d_s`` span (docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Iterable, Iterator, Optional

from raft_tpu import chaos
from raft_tpu.chaos import InjectedProducerCrash

# Producer -> consumer message kinds.
_ITEM, _END, _ERROR = "item", "end", "error"


class PipelineInterrupted(Exception):
    """Raised out of ``next(pipeline)`` when the consumer's
    ``interrupt`` predicate turns true while the queue is empty — the
    cooperative-preemption path out of a blocked input wait
    (docs/ROBUSTNESS.md).  Not a stream error: the pipeline stays
    usable, the train loop translates it to its preemption exit."""


def _chaos_producer_point(ordinal: int) -> None:
    """`pipeline.producer` injection seam (docs/ROBUSTNESS.md): fires
    the ``producer_err`` fault before batch ``ordinal`` is pulled — on
    the producer thread when buffered, inline at depth 0 — exercising
    the error-propagation contract (the consumer's ``next()`` re-raises,
    ``close()`` joins).  One no-op module check when chaos is off."""
    if chaos.should_inject("producer_err", step=ordinal,
                           point="pipeline.producer"):
        raise InjectedProducerCrash(
            f"chaos-injected producer crash before batch {ordinal}")


class DevicePipeline:
    """Iterator of device-resident batches with bounded background
    prefetch.

    ``batches``: the host batch iterator (e.g. ``ShardedLoader.batches``
    or ``batches_from_step``).
    ``put_fn``: host batch -> device-resident batch (e.g.
    :func:`raft_tpu.parallel.make_batch_sharder`); None = identity
    (host-only pipelining, used by tests and the input microbench).
    ``prep_fn``: host-side prep applied before ``put_fn`` (noise
    injection); called in stream order by exactly one thread.
    ``depth``: buffered batches beyond the one handed to the consumer;
    0 = synchronous serial path (no thread).

    Iteration: ``next(pipeline)`` returns the next device-resident
    batch; ``StopIteration`` when the source ends.  A producer-side
    exception re-raises in the consumer's ``next()``.  ``close()``
    (also via context manager) stops the producer and drops buffered
    batches so their device memory frees promptly; it is called by the
    train loop's ``finally``.
    """

    def __init__(self, batches: Iterable, *,
                 put_fn: Optional[Callable] = None,
                 prep_fn: Optional[Callable] = None,
                 depth: int = 2, keep_host: bool = False,
                 interrupt: Optional[Callable[[], bool]] = None,
                 interrupt_poll_s: float = 0.1):
        if depth < 0:
            raise ValueError(f"device-prefetch depth must be >= 0, "
                             f"got {depth}")
        self._src: Iterator = iter(batches)
        self._put = put_fn if put_fn is not None else (lambda b: b)
        self._prep = prep_fn
        self.depth = int(depth)
        # keep_host: retain the post-prep HOST batch alongside each
        # device batch (``last_host_batch`` after next()) — the
        # forensics ring needs the exact host arrays the poisoned step
        # consumed (raft_tpu/obs/health.py).  Off by default: holding
        # the references keeps up to depth+ring batches of host RAM
        # alive that the serial path would have freed.
        self.keep_host = bool(keep_host)
        # interrupt: optional predicate polled while the consumer waits
        # on an empty buffer (the SIGTERM fix for the old caveat: a
        # preemption flag set while ``next()`` was blocked in
        # ``queue.get`` went unobserved until a batch arrived).  When it
        # turns true mid-wait, ``next()`` raises
        # :class:`PipelineInterrupted` instead of blocking on.  Only the
        # buffered path polls — at depth 0 the consumer is inside the
        # source iterator itself (host IO), which stays uninterruptible
        # exactly like the pre-pipeline serial loop.
        self._interrupt = interrupt
        self._interrupt_poll_s = max(float(interrupt_poll_s), 1e-3)
        # Per-batch producer spans, valid right after next() returns.
        self.last_prep_s = 0.0
        self.last_h2d_s = 0.0
        # (t_pull, t_prepped, t_put) perf_counter stamps for the batch
        # just delivered — lets the train loop's step trace place the
        # producer-side prep/h2d spans on the shared monotonic timeline
        # (obs/trace.record_span) instead of only knowing durations.
        self.last_stamps: Optional[tuple] = None
        self.last_host_batch = None
        # Cumulative, for the input microbench / pipeline stats.
        self.prep_total_s = 0.0
        self.h2d_total_s = 0.0
        self.batches_out = 0
        self._closed = False
        if self.depth > 0:
            # The queue itself is unbounded; _slots enforces the
            # in-flight bound (acquired BEFORE the source is pulled).
            self._q: queue.Queue = queue.Queue()
            self._slots = threading.Semaphore(self.depth)
            self._stop = threading.Event()
            self._thread = threading.Thread(
                target=self._produce, name="raft-device-prefetch",
                daemon=True)
            self._thread.start()

    # -- producer (depth > 0) -------------------------------------------
    def _produce(self) -> None:
        produced = 0  # pull ordinal, matches the serial path's count
        try:
            while True:
                # Slot first: never pull (or decode, or device_put) a
                # batch there is no buffer budget for.
                while not self._slots.acquire(timeout=0.05):
                    if self._stop.is_set():
                        return
                if self._stop.is_set():
                    return
                _chaos_producer_point(produced)
                produced += 1
                try:
                    batch = next(self._src)
                except StopIteration:
                    self._q.put((_END, None, None, 0.0, 0.0, None))
                    return
                t0 = time.perf_counter()
                if self._prep is not None:
                    batch = self._prep(batch)
                t1 = time.perf_counter()
                host = batch if self.keep_host else None
                batch = self._put(batch)
                t2 = time.perf_counter()
                self._q.put((_ITEM, batch, host, t1 - t0, t2 - t1,
                             (t0, t1, t2)))
        except BaseException as e:  # re-raised in the consumer
            self._q.put((_ERROR, e, None, 0.0, 0.0, None))

    # -- consumer --------------------------------------------------------
    def __iter__(self) -> "DevicePipeline":
        return self

    def _account(self, prep_s: float, h2d_s: float) -> None:
        self.last_prep_s = prep_s
        self.last_h2d_s = h2d_s
        self.prep_total_s += prep_s
        self.h2d_total_s += h2d_s
        self.batches_out += 1

    def __next__(self):
        if self._closed:
            raise StopIteration
        if self.depth == 0:
            # The exact old serial path: prep + put inline, on this
            # thread, one batch at a time.
            _chaos_producer_point(self.batches_out)
            batch = next(self._src)  # StopIteration propagates
            t0 = time.perf_counter()
            if self._prep is not None:
                batch = self._prep(batch)
            t1 = time.perf_counter()
            self.last_host_batch = batch if self.keep_host else None
            batch = self._put(batch)
            t2 = time.perf_counter()
            self.last_stamps = (t0, t1, t2)
            self._account(t1 - t0, t2 - t1)
            return batch
        if self._interrupt is None:
            kind, payload, host, prep_s, h2d_s, stamps = self._q.get()
        else:
            # Timed wait + flag re-check: a preemption request cannot
            # interrupt queue.get, so poll.  The poll costs nothing on
            # the hot path (the queue is non-empty whenever the
            # producer keeps up) and bounds the observation latency of
            # a SIGTERM during an input stall to interrupt_poll_s.
            while True:
                try:
                    (kind, payload, host, prep_s, h2d_s,
                     stamps) = self._q.get(timeout=self._interrupt_poll_s)
                    break
                except queue.Empty:
                    if self._interrupt():
                        raise PipelineInterrupted(
                            "preemption requested while waiting on the "
                            "input pipeline")
        if kind == _END:
            self._closed = True
            raise StopIteration
        if kind == _ERROR:
            self._closed = True
            raise payload
        self._slots.release()
        self.last_host_batch = host
        self.last_stamps = stamps
        self._account(prep_s, h2d_s)
        return payload

    def buffered(self) -> int:
        """Batches currently sitting in the buffer (0 on the serial
        path); bounded by ``depth``."""
        return 0 if self.depth == 0 else self._q.qsize()

    def close(self) -> None:
        """Stop the producer and drop buffered batches.  Idempotent.

        The producer may be blocked inside ``next(source)`` (host IO);
        like the serial loop, that cannot be interrupted — the stop flag
        is observed at the next slot/batch boundary, and the thread is
        daemonic so a wedged loader cannot hang interpreter exit."""
        self._closed = True
        if self.depth == 0:
            return
        self._stop.set()
        self._thread.join(timeout=5.0)
        try:
            while True:  # free buffered device arrays promptly
                self._q.get_nowait()
        except queue.Empty:
            pass

    def __enter__(self) -> "DevicePipeline":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False
