"""Convex-combination flow upsampling (reference ``core/raft.py:72-83``).

Each full-resolution pixel is a convex combination (softmax weights) of the
3x3 coarse-grid neighborhood of its parent cell.  The reference implements
this with ``F.unfold`` + reshapes in NCHW; here it is a single einsum over
extracted patches in NHWC, which XLA fuses cleanly.

Channel-order contract (for weight conversion parity): the mask produced by
the update block has ``64 * 9`` channels which factorize as
``(k, p, q) -> k * 64 + p * 8 + q`` where ``k`` indexes the 3x3 tap
(row-major: k = (dy+1)*3 + (dx+1), matching ``F.unfold``'s (ki, kj) order)
and ``(p, q)`` is the subpixel position (reference ``raft.py:75``:
``mask.view(N, 1, 9, 8, 8, H, W)``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _extract_3x3_patches(x: jax.Array) -> jax.Array:
    """``(B, H, W, C)`` -> ``(B, H, W, 9, C)``, taps in unfold order."""
    xp = jnp.pad(x, ((0, 0), (1, 1), (1, 1), (0, 0)))
    H, W = x.shape[1], x.shape[2]
    taps = []
    for di in range(3):       # row offset (ki)
        for dj in range(3):   # col offset (kj)
            taps.append(xp[:, di:di + H, dj:dj + W, :])
    return jnp.stack(taps, axis=3)


def convex_upsample(flow: jax.Array, mask: jax.Array,
                    factor: int = 8) -> jax.Array:
    """Upsample ``(B, H, W, 2)`` flow to ``(B, 8H, 8W, 2)``.

    Args:
      flow: coarse flow in coarse-pixel units (scaled by ``factor`` inside,
        reference ``raft.py:77``).
      mask: ``(B, H, W, 9 * factor * factor)`` unnormalized weights.
    """
    B, H, W, _ = flow.shape
    f = factor
    m = mask.reshape(B, H, W, 9, f, f)
    m = jax.nn.softmax(m, axis=3)

    patches = _extract_3x3_patches(factor * flow)  # (B, H, W, 9, 2)
    up = jnp.einsum("bhwkpq,bhwkc->bhpwqc", m, patches.astype(m.dtype))
    return up.reshape(B, f * H, f * W, 2)
