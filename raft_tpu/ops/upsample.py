"""Convex-combination flow upsampling (reference ``core/raft.py:72-83``).

Each full-resolution pixel is a convex combination (softmax weights) of the
3x3 coarse-grid neighborhood of its parent cell.  The reference implements
this with ``F.unfold`` + reshapes in NCHW; here it is a single einsum over
extracted patches in NHWC, which XLA fuses cleanly.

Channel-order contract (for weight conversion parity): the mask produced by
the update block has ``64 * 9`` channels which factorize as
``(k, p, q) -> k * 64 + p * 8 + q`` where ``k`` indexes the 3x3 tap
(row-major: k = (dy+1)*3 + (dx+1), matching ``F.unfold``'s (ki, kj) order)
and ``(p, q)`` is the subpixel position (reference ``raft.py:75``:
``mask.view(N, 1, 9, 8, 8, H, W)``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _extract_3x3_patches(x: jax.Array) -> jax.Array:
    """``(B, H, W, C)`` -> ``(B, H, W, 9, C)``, taps in unfold order."""
    xp = jnp.pad(x, ((0, 0), (1, 1), (1, 1), (0, 0)))
    H, W = x.shape[1], x.shape[2]
    taps = []
    for di in range(3):       # row offset (ki)
        for dj in range(3):   # col offset (kj)
            taps.append(xp[:, di:di + H, dj:dj + W, :])
    return jnp.stack(taps, axis=3)


def convex_upsample(flow: jax.Array, mask: jax.Array,
                    factor: int = 8) -> jax.Array:
    """Upsample ``(B, H, W, 2)`` flow to ``(B, 8H, 8W, 2)``.

    Args:
      flow: coarse flow in coarse-pixel units (scaled by ``factor`` inside,
        reference ``raft.py:77``).
      mask: ``(B, H, W, 9 * factor * factor)`` unnormalized weights.
    """
    B, H, W, _ = flow.shape
    f = factor
    m = mask.reshape(B, H, W, 9, f, f)
    m = jax.nn.softmax(m, axis=3)

    patches = _extract_3x3_patches(factor * flow)  # (B, H, W, 9, 2)
    up = jnp.einsum("bhwkpq,bhwkc->bhpwqc", m, patches.astype(m.dtype))
    return up.reshape(B, f * H, f * W, 2)


def convex_upsample_flat(flow: jax.Array, mask: jax.Array,
                         factor: int = 8,
                         compute_dtype=jnp.float32) -> jax.Array:
    """:func:`convex_upsample` in space-to-depth layout — the TPU-native
    training formulation.

    The 6-D ``(B, H, W, 9, 8, 8)`` shapes of the direct einsum put 2- and
    8-wide trailing dims in the lanes, which on TPU lowers to tiny-tile
    layouts plus relayout copies on every tensor touched (profiled at
    ~250 ms/step, HBM-bound at 5-7%% of peak BW).  Here every intermediate
    stays a channels-last 2-D tile: the softmax over the 9 taps uses
    contiguous 64-channel slices (channel order is ``k*64 + p*8 + q``,
    the converter contract), and the convex combination is 9 broadcast
    multiply-adds.

    Returns ``(B, H, W, 2 * factor**2)`` with channel order ``(c, p, q)``
    — ``out[..., c*ff + p*f + q] == convex_upsample(...)[..., f*h+p,
    f*w+q, c]`` (see :func:`space_to_depth_flow` for the matching ground
    -truth layout; :func:`depth_to_space_flow` restores pixel space).
    """
    B, H, W, _ = flow.shape
    ff = factor * factor
    # compute_dtype=bfloat16 halves the HBM traffic of the 9-tap
    # exp/FMA/divide chain (the softmax weights are in [0,1] and the
    # flow taps O(max_flow); rounding is ~0.4% relative on the upsampled
    # flow).  fp32 default preserves the reference's loss numerics (its
    # upsample_flow runs outside autocast, raft.py:72-83).
    m = mask.astype(compute_dtype)
    # Per-tap-group max (elementwise max over the 9 contiguous ff-channel
    # slices) keeps every group's softmax unconditionally stable — a
    # global per-pixel max would underflow denom to 0 (NaN) for any
    # subpixel group sitting far below the pixel's hottest group.
    taps = [m[..., k * ff:(k + 1) * ff] for k in range(9)]
    gmax = taps[0]
    for t in taps[1:]:
        gmax = jnp.maximum(gmax, t)
    gmax = jax.lax.stop_gradient(gmax)
    e = [jnp.exp(t - gmax) for t in taps]
    denom = sum(e)

    f8 = jnp.pad(factor * flow.astype(compute_dtype),
                 ((0, 0), (1, 1), (1, 1), (0, 0)))
    outx = 0.0
    outy = 0.0
    for k in range(9):
        di, dj = k // 3, k % 3   # unfold tap order (row-major)
        fk = f8[:, di:di + H, dj:dj + W, :]
        outx += e[k] * fk[..., 0:1]
        outy += e[k] * fk[..., 1:2]
    # One reciprocal + two muls instead of two 64-channel divides (TPU
    # divide is a multi-pass VPU op; profiled ~4 ms/step across the 12
    # iterations' forward+backward).
    inv = 1.0 / denom
    return jnp.concatenate([outx * inv, outy * inv], axis=-1)


def space_to_depth_flow(x: jax.Array, factor: int = 8) -> jax.Array:
    """``(B, f*H, f*W, C)`` -> ``(B, H, W, C * f * f)``, channel order
    ``(c, p, q)`` — the ground-truth-side layout matching
    :func:`convex_upsample_flat` (the sequence loss compares the two
    WITHOUT ever materializing full-resolution per-iteration flows)."""
    B, FH, FW, C = x.shape
    H, W = FH // factor, FW // factor
    x = x.reshape(B, H, factor, W, factor, C)
    x = x.transpose(0, 1, 3, 5, 2, 4)
    return x.reshape(B, H, W, C * factor * factor)


def depth_to_space_flow(x: jax.Array, channels: int = 2,
                        factor: int = 8) -> jax.Array:
    """Inverse of :func:`space_to_depth_flow`: ``(B, H, W, C*f*f)`` with
    ``(c, p, q)`` channel order -> ``(B, f*H, f*W, C)``."""
    B, H, W, _ = x.shape
    x = x.reshape(B, H, W, channels, factor, factor)
    x = x.transpose(0, 1, 4, 2, 5, 3)
    return x.reshape(B, H * factor, W * factor, channels)
