"""Correlation volumes, TPU-first.

Reference semantics (``core/corr.py``):

- ``CorrBlock`` (corr.py:12-60): materialize the all-pairs volume
  ``<f1(x), f2(y)> / sqrt(C)`` for every pair of 1/8-res pixels, average-pool
  it into a 4-level pyramid over the *target* dims, then per refinement step
  bilinearly sample a ``(2r+1)^2`` window around ``coords / 2^l`` at each
  level.
- ``AlternateCorrBlock`` + the CUDA kernel (corr.py:63-91,
  alt_cuda_corr/correlation_kernel.cu): never materialize the volume;
  compute windowed dot products on demand.  Because average pooling is
  linear, pooling the volume over target dims equals correlating against a
  pooled ``f2`` — the two reference paths are mathematically equivalent, and
  both are reproduced here by a single window-tap ordering contract.

TPU design:

- The all-pairs volume is one big einsum -> MXU.  Stored as
  ``(B, H1*W1, H2_l, W2_l)`` fp32 per level (reference casts corr to fp32,
  corr.py:50).
- Window lookup: bilinear sampling is linear in the correlation rows, so
  the ``(2r+1)^2`` taps factorize into two dense 1-D interpolation-weight
  mat-muls (``_sample_windows``) — no gathers (TPU gathers lower to serial
  loops).  Semantics match ``align_corners=True`` zeros-padding
  ``bilinear_sampler`` (corr.py:45) exactly.
- The memory-efficient path (``chunked_corr_lookup``) is blockwise: for a
  block of query pixels, compute its corr rows against pooled ``f2`` levels
  (small MXU matmuls), sample the windows, and discard the rows — the
  blockwise-attention pattern.  Fully differentiable (unlike the reference's
  CUDA path, whose backward is exposed but never wired: no autograd.Function
  exists, see correlation.cpp:51-54).

Window-tap ordering contract (weight-conversion parity): the reference
builds ``delta = stack(meshgrid(dy, dx))`` and adds it to ``(x, y)``
centroids (corr.py:36-41), so tap ``(i, j)`` of the window samples
displacement ``(dx = i - r, dy = j - r)`` — the *first* window axis walks x.
We reproduce that exactly; channel layout of the lookup output is
``level-major, then i (x-offset), then j (y-offset)``.
"""

from __future__ import annotations

from typing import List, NamedTuple, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np


# ---------------------------------------------------------------------------
# Quantized pyramid storage (int8 today, fp8 = a dtype swap)
#
# The correlation volume is pure *data* between its producer (one einsum)
# and its consumer (a linear window-sampling pass), so storage can drop
# below bf16 as long as the sampling re-accumulates fp32: store
# ``q = round(corr / scale)`` in int8 with a per-level symmetric scale
# calibrated from the level's correlation row maxima, and because the
# bilinear window sampling is LINEAR in the stored values, dequantization
# fuses into the lookup as one multiply on the sampled taps —
# ``taps_fp32 = sample(q) * scale`` — instead of ever materializing a
# dequantized volume.  fp8 variants reuse the identical path with a
# different ``(dtype, qmax)`` pair (no rounding to an integer grid; the
# cast itself rounds), which is why the spec table below is the ONLY
# place a new storage dtype has to be added.
#
# The quantize boundary is wrapped in stop_gradient: the stored values
# are integers (no tangent space), so gradients do not flow through the
# volume to the feature encoder — mirroring the reference's alternate
# CUDA path, whose backward kernel exists but is never wired
# (correlation.cpp:51-54).  Quantized storage is therefore an
# inference/serving optimization first; training with it keeps finite
# grads everywhere (the context encoder + update block still learn) but
# freezes fnet's correlation gradient.  See RAFTConfig.corr_dtype.
# ---------------------------------------------------------------------------

class QuantizedLevel(NamedTuple):
    """One quantized pyramid level: raw codes + the dequant scale.

    ``values``: same layout as the fp32 level it replaces (either
    ``(B, N, H, W)`` query-major or ``(B, H, W, Npad)`` query-minor),
    stored in the quantized dtype.
    ``scale``: ``(B, 1, 1, 1)`` fp32 per-batch-element dequant scale
    (symmetric: ``corr ≈ values * scale``), broadcastable against
    ``values`` in either layout.
    """

    values: jax.Array
    scale: jax.Array


CorrLevel = Union[jax.Array, QuantizedLevel]

# name -> (jnp dtype, qmax).  qmax is the largest magnitude the code
# space represents: 127 for int8; the fp8 formats use their finite max
# (448 for e4m3fn, 57344 for e5m2) so the scale maps the calibrated row
# maximum onto the top of the representable range.
_QUANT_SPECS = {
    "int8": (jnp.int8, 127.0),
    "float8_e4m3fn": (getattr(jnp, "float8_e4m3fn", None), 448.0),
    "float8_e5m2": (getattr(jnp, "float8_e5m2", None), 57344.0),
}


def corr_quant_spec(name: str):
    """``(dtype, qmax)`` for a quantized corr storage dtype name, or
    ``None`` when ``name`` is a plain (non-quantized) dtype.  Accepts
    strings, numpy/jnp dtype objects, and dtype classes."""
    try:
        name = str(np.dtype(name))   # normalize classes + instances
    except TypeError:
        name = str(name)             # 'auto' etc. — not a dtype at all
    spec = _QUANT_SPECS.get(name)
    if spec is None:
        return None
    dtype, qmax = spec
    if dtype is None:
        raise ValueError(
            f"corr_dtype={name!r} needs jax.numpy.{name} which this "
            "jax/ml_dtypes build does not provide")
    return dtype, qmax


def quantize_corr_level(corr: jax.Array, spec) -> QuantizedLevel:
    """Calibrate + quantize one fp32 pyramid level.

    Calibration is the per-level symmetric scale from the row maxima:
    ``scale = max_rows(max_x |corr_row|) / qmax`` per batch element (the
    max over rows of per-row maxima == the level max; computed that way
    so a future per-row scale refinement is a reduction-axis change).
    Wrapped in stop_gradient — see the module section comment.
    """
    dtype, qmax = spec
    c = jax.lax.stop_gradient(corr.astype(jnp.float32))
    if corr.size == 0:
        # Empty (over-pooled) trailing level: nothing to calibrate; the
        # lookups zero-fill its taps regardless of the scale.
        return QuantizedLevel(
            jnp.zeros(corr.shape, dtype),
            jnp.ones((corr.shape[0], 1, 1, 1), jnp.float32))
    # Row maxima (max |corr| over the trailing target axes), then the
    # level max over rows; keepdims so the scale broadcasts in both the
    # query-major and query-minor layouts.
    amax = jnp.max(jnp.abs(c), axis=(1, 2, 3), keepdims=True)
    scale = jnp.maximum(amax, 1e-12) / qmax
    q = c / scale
    if jnp.issubdtype(dtype, jnp.integer):
        q = jnp.round(q)
    q = jnp.clip(q, -qmax, qmax).astype(dtype)
    return QuantizedLevel(q, scale)


def dequantize_level(level: CorrLevel) -> jax.Array:
    """fp32 view of a pyramid level (tests/debugging; the hot lookups
    never call this — they fuse the scale into the sampled taps)."""
    if isinstance(level, QuantizedLevel):
        return level.values.astype(jnp.float32) * level.scale
    return level.astype(jnp.float32)


def _level_array(level: CorrLevel) -> jax.Array:
    return level.values if isinstance(level, QuantizedLevel) else level


def _store_level(corr: jax.Array, out_dtype, quant_spec) -> CorrLevel:
    """Round one fp32 level into its storage form (cast or quantize)."""
    if quant_spec is not None:
        return quantize_corr_level(corr, quant_spec)
    return corr.astype(out_dtype)


def resolve_precision(precision) -> jax.lax.Precision:
    """'default' | 'high' | 'highest' -> lax.Precision.

    Measured on v5e (bench.py): 'highest' (fp32) is FASTER than 'high'
    (bf16x3) for the correlation path and is the config default
    (RAFTConfig.corr_precision); 'default' (one bf16 pass) bought <2%
    end-to-end, so there is no reason to give up fp32 correlation
    (which the reference also keeps, corr.py:50).
    """
    if isinstance(precision, jax.lax.Precision):
        return precision
    return {"default": jax.lax.Precision.DEFAULT,
            "high": jax.lax.Precision.HIGH,
            "highest": jax.lax.Precision.HIGHEST}[precision]


def all_pairs_correlation(fmap1: jax.Array, fmap2: jax.Array,
                          precision="highest") -> jax.Array:
    """``(B, H, W, C) x (B, H, W, C) -> (B, H1*W1, H2, W2)`` fp32 volume."""
    B, H, W, C = fmap1.shape
    f1 = fmap1.reshape(B, H * W, C).astype(jnp.float32)
    f2 = fmap2.reshape(B, H * W, C).astype(jnp.float32)
    corr = jnp.einsum("bnc,bmc->bnm", f1, f2,
                      precision=resolve_precision(precision),
                      preferred_element_type=jnp.float32)
    # Reciprocal-MULTIPLY, not divide: TPU divide is a multi-pass VPU op
    # and XLA does not strength-reduce fp division by a constant; the
    # divide over the full (HW)^2 volume profiled at ~3.5 ms/step
    # (fwd+transpose) at the chairs bench shape.
    corr = corr * (1.0 / float(C) ** 0.5)
    return corr.reshape(B, H * W, H, W)


def _avg_pool_2x2(x: jax.Array) -> jax.Array:
    """2x2/stride-2 average pool over the last two spatial dims of
    ``(B, N, H, W)``; odd trailing row/col dropped (torch avg_pool2d)."""
    B, N, H, W = x.shape
    H2, W2 = H // 2, W // 2
    x = x[:, :, : H2 * 2, : W2 * 2]
    x = x.reshape(B, N, H2, 2, W2, 2)
    return x.sum(axis=(3, 5)) * 0.25   # sum*0.25: no divide pass


def build_corr_pyramid(fmap1: jax.Array, fmap2: jax.Array,
                       num_levels: int = 4,
                       precision="highest",
                       out_dtype=jnp.float32) -> List[CorrLevel]:
    """Materialized pyramid: level l is ``(B, H1*W1, H/2^l, W/2^l)``.

    ``out_dtype``: STORAGE dtype of the levels (``RAFTConfig.corr_dtype``
    semantics, same as :func:`build_corr_pyramid_flat` — pooling math
    stays fp32 and the lookup re-accumulates fp32; only stored values
    round).  Quantized names ('int8', fp8) yield
    :class:`QuantizedLevel` pairs with a per-level calibrated scale."""
    quant = corr_quant_spec(out_dtype)
    corr = all_pairs_correlation(fmap1, fmap2, precision)
    pyramid = [_store_level(corr, out_dtype, quant)]
    for _ in range(num_levels - 1):
        corr = _avg_pool_2x2(corr)
        pyramid.append(_store_level(corr, out_dtype, quant))
    return pyramid


def _avg_pool_2x2_qminor(x: jax.Array) -> jax.Array:
    """2x2/stride-2 average pool over the LEADING spatial dims of
    ``(B, H, W, N)``; odd trailing row/col dropped (torch avg_pool2d)."""
    B, H, W, N = x.shape
    H2, W2 = H // 2, W // 2
    x = x[:, : H2 * 2, : W2 * 2, :]
    x = x.reshape(B, H2, 2, W2, 2, N)
    return x.sum(axis=(2, 4)) * 0.25   # sum*0.25: no divide pass


def build_corr_pyramid_flat(fmap1: jax.Array, fmap2: jax.Array,
                            num_levels: int = 4, precision="highest",
                            pad_q: int = 128,
                            out_dtype=jnp.float32) -> List[CorrLevel]:
    """Materialized pyramid in QUERY-MINOR layout: level l is
    ``(B, H/2^l, W/2^l, Npad)`` with the flattened query dim zero-padded
    to a multiple of ``pad_q``.

    Same math as :func:`build_corr_pyramid` (padding ``fmap1`` with zero
    rows just appends all-zero correlation columns); the layout feeds
    :func:`raft_tpu.ops.pallas_corr.pallas_pyramid_lookup`.  Query-minor
    matters on TPU: with the target width in the minor dim, a chairs-crop
    level 2 is (.., 11, 15) and every (8, 128) tile is >8x padding —
    profiled round 2 at 66 GiB/s effective on the dcorr writes.  With
    queries minor the lane dim is Npad (a multiple of 128) and every
    level tiles densely."""
    B, H, W, C = fmap1.shape
    N = H * W
    n_pad = (-N) % pad_q
    f1 = fmap1.reshape(B, N, C).astype(jnp.float32)
    if n_pad:
        f1 = jnp.pad(f1, ((0, 0), (0, n_pad), (0, 0)))
    f2 = fmap2.astype(jnp.float32)
    corr = jnp.einsum("byxc,bqc->byxq", f2, f1,
                      precision=resolve_precision(precision),
                      preferred_element_type=jnp.float32)
    corr = corr * (1.0 / float(C) ** 0.5)   # mul, not divide (see above)
    # Pyramid math (pooling) stays fp32; only the STORED levels round to
    # ``out_dtype`` (XLA fuses the casts into the einsum/pool epilogues).
    # Quantized storage rides the same seam: the calibration max and the
    # round-to-code also fuse into the pool epilogue.
    quant = corr_quant_spec(out_dtype)
    pyramid = [_store_level(corr, out_dtype, quant)]
    for _ in range(num_levels - 1):
        corr = _avg_pool_2x2_qminor(corr)
        pyramid.append(_store_level(corr, out_dtype, quant))
    return pyramid


def _interp_weights_1d(c: jax.Array, n: int, radius: int) -> jax.Array:
    """Dense bilinear interpolation weights along one axis.

    ``w[..., t, p] = max(0, 1 - |c + t - r - p|)`` for positions
    ``p in [0, n)`` — each window tap has <=2 nonzero weights (the two
    neighboring pixels) and out-of-bounds taps get all-zero rows, which is
    exactly ``grid_sample(align_corners=True, padding='zeros')``
    (reference utils.py:57-65).
    """
    k = 2 * radius + 1
    taps = jnp.arange(k, dtype=jnp.float32) - radius
    pos = jnp.arange(n, dtype=jnp.float32)
    return jnp.maximum(
        0.0, 1.0 - jnp.abs(c[..., None, None] + taps[:, None] - pos))


def _sample_windows(corr: jax.Array, coords: jax.Array,
                    radius: int, precision="highest") -> jax.Array:
    """Bilinear window sampling as two batched mat-muls (MXU-friendly).

    Bilinear interpolation is linear in the image, so the ``(2r+1)^2``
    window taps factorize into dense 1-D weight matrices contracted
    against the correlation rows — no gathers (TPU gathers lower to serial
    loops; this formulation is the reason the lookup is fast on TPU, and
    the same math the Pallas kernel uses).

    Args:
      corr: ``(B, N, H, W)`` one pyramid level (N query pixels).
      coords: ``(B, N, 2)`` query centroids in this level's pixel units.

    Returns:
      ``(B, N, (2r+1)^2)`` sampled taps, x-major tap order.
    """
    B, N, H, W = corr.shape
    K = 2 * radius + 1
    c = coords.astype(jnp.float32)
    wx = _interp_weights_1d(c[..., 0], W, radius)     # (B, N, K, W)
    wy = _interp_weights_1d(c[..., 1], H, radius)     # (B, N, K, H)
    # a(b,n,j,x) = sum_y wy(b,n,j,y) corr(b,n,y,x)
    prec = resolve_precision(precision)
    a = jnp.einsum("bnjy,bnyx->bnjx", wy, corr.astype(jnp.float32),
                   precision=prec, preferred_element_type=jnp.float32)
    # tap(b,n,i,j) = sum_x wx(b,n,i,x) a(b,n,j,x)
    taps = jnp.einsum("bnix,bnjx->bnij", wx, a,
                      precision=prec, preferred_element_type=jnp.float32)
    return taps.reshape(B, N, K * K)


def corr_lookup(pyramid: Sequence[CorrLevel], coords: jax.Array,
                radius: int, precision="highest") -> jax.Array:
    """Sample the materialized pyramid (reference ``CorrBlock.__call__``).

    Args:
      pyramid: from :func:`build_corr_pyramid`.  Levels may be plain
        arrays (fp32/bf16 storage) or :class:`QuantizedLevel` pairs —
        sampling is linear in the stored values, so dequantization is
        ONE multiply on the sampled taps (never a dequantized volume).
      coords: ``(B, H1, W1, 2)`` target coordinates in level-0 pixel units,
        last axis ``(x, y)``.

    Returns:
      ``(B, H1, W1, levels * (2r+1)^2)`` fp32 features.
    """
    B, H1, W1, _ = coords.shape
    c = coords.reshape(B, H1 * W1, 2).astype(jnp.float32)
    outs = []
    for lvl, corr in enumerate(pyramid):
        taps = _sample_windows(_level_array(corr), c / (2.0 ** lvl),
                               radius, precision)
        if isinstance(corr, QuantizedLevel):
            # Fused dequant: taps are a linear map of the codes.
            taps = taps * corr.scale.reshape(B, 1, 1)
        outs.append(taps)
    out = jnp.concatenate(outs, axis=-1)
    return out.reshape(B, H1, W1, -1)


def pool_fmap_pyramid(fmap2: jax.Array, num_levels: int) -> List[jax.Array]:
    """Pooled target features ``(B, H_l, W_l, C)`` per level.  By linearity,
    correlating against pooled f2 == pooling the corr volume (reference
    AlternateCorrBlock pools fmaps, corr.py:68-72)."""
    levels = [fmap2]
    cur = fmap2
    for _ in range(num_levels - 1):
        t = cur.transpose(0, 3, 1, 2)          # (B, C, H, W)
        t = _avg_pool_2x2(t)
        cur = t.transpose(0, 2, 3, 1)
        levels.append(cur)
    return levels


def chunked_corr_lookup(fmap1: jax.Array, fmap2_pyramid: Sequence[jax.Array],
                        coords: jax.Array, radius: int,
                        block_size: int = 256,
                        precision="highest") -> jax.Array:
    """On-demand blockwise correlation lookup (memory-efficient path).

    Never materializes the ``O((HW)^2)`` volume: for each block of query
    pixels, computes its correlation rows against each pooled ``f2`` level
    (an MXU matmul), samples the ``(2r+1)^2`` window, and moves on.  The TPU
    analogue of the reference's ``alt_cuda_corr`` kernel (C6), but
    differentiable end-to-end via autodiff through the blockwise scan.

    Args:
      fmap1: ``(B, H1, W1, C)`` query features (always full resolution,
        reference corr.py:82).
      fmap2_pyramid: from :func:`pool_fmap_pyramid`.
      coords: ``(B, H1, W1, 2)`` in level-0 pixel units.
      block_size: query pixels per block.

    Returns:
      ``(B, H1, W1, levels * (2r+1)^2)`` fp32 features.
    """
    B, H1, W1, C = fmap1.shape
    N = H1 * W1
    K = 2 * radius + 1
    L = len(fmap2_pyramid)
    scale = 1.0 / jnp.sqrt(jnp.float32(C))

    f1 = fmap1.reshape(B, N, C).astype(jnp.float32)
    c = coords.reshape(B, N, 2).astype(jnp.float32)

    # Pad N up to a multiple of block_size so the scan has static shape.
    nblocks = -(-N // block_size)
    pad = nblocks * block_size - N
    f1 = jnp.pad(f1, ((0, 0), (0, pad), (0, 0)))
    c = jnp.pad(c, ((0, 0), (0, pad), (0, 0)))
    f1 = f1.reshape(B, nblocks, block_size, C)
    c = c.reshape(B, nblocks, block_size, 2)

    f2_flat = [lvl.astype(jnp.float32) for lvl in fmap2_pyramid]

    def block_fn(carry, blk):
        f1_b, c_b = blk  # (B, bs, C), (B, bs, 2)
        outs = []
        for lvl, f2 in enumerate(f2_flat):
            Bf, Hl, Wl, _ = f2.shape
            rows = jnp.einsum("bnc,bhwc->bnhw", f1_b, f2,
                              precision=resolve_precision(precision),
                              preferred_element_type=jnp.float32) * scale
            outs.append(_sample_windows(
                rows.reshape(B, block_size, Hl, Wl),
                c_b / (2.0 ** lvl), radius, precision))
        return carry, jnp.concatenate(outs, axis=-1)

    _, out = jax.lax.scan(
        block_fn, None,
        (f1.transpose(1, 0, 2, 3), c.transpose(1, 0, 2, 3)))
    # out: (nblocks, B, bs, L*K*K) -> (B, N, L*K*K)
    out = out.transpose(1, 0, 2, 3).reshape(B, nblocks * block_size, -1)
    out = out[:, :N]
    return out.reshape(B, H1, W1, L * K * K)
