"""Pure-JAX tensor utilities (NHWC layout).

TPU-native equivalents of the reference's ``core/utils/utils.py``:

- ``coords_grid`` (utils.py:74-77) — here NHWC ``(B, H, W, 2)`` with the last
  axis ordered ``(x, y)`` like the reference's channel order.
- ``bilinear_sampler`` (utils.py:57-71) — the reference wraps
  ``F.grid_sample(align_corners=True)`` with zeros padding; with
  ``align_corners=True`` the normalize/denormalize round-trips exactly to
  pixel coordinates, so this implements direct pixel-space bilinear
  interpolation with out-of-bounds corner contributions zeroed.
- ``upflow8`` (utils.py:80-82) — ``align_corners=True`` bilinear resize
  expressed as two dense interpolation matmuls (MXU-friendly on TPU, and
  exact; ``jax.image.resize`` uses half-pixel sampling which differs at
  edges).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def coords_grid(batch: int, ht: int, wd: int, dtype=jnp.float32) -> jax.Array:
    """Pixel-coordinate grid ``(B, H, W, 2)``, last axis ``(x, y)``."""
    x = jnp.arange(wd, dtype=dtype)
    y = jnp.arange(ht, dtype=dtype)
    xx, yy = jnp.meshgrid(x, y)  # (H, W) each
    grid = jnp.stack([xx, yy], axis=-1)  # (H, W, 2) -> (x, y)
    return jnp.broadcast_to(grid[None], (batch, ht, wd, 2))


def bilinear_sampler(img: jax.Array, coords: jax.Array,
                     mask: bool = False):
    """Sample ``img`` at pixel coordinates with zeros padding.

    Args:
      img: ``(B, H, W, C)``.
      coords: ``(B, ..., 2)`` pixel coordinates, last axis ``(x, y)``.
      mask: if True, also return an in-bounds indicator (strict inequalities,
        matching reference utils.py:67-69: ``-1 < normalized < 1``).

    Returns:
      ``(B, ..., C)`` samples (and optionally the mask ``(B, ...)``).
    """
    B, H, W, C = img.shape
    x = coords[..., 0]
    y = coords[..., 1]

    x0 = jnp.floor(x)
    y0 = jnp.floor(y)
    wx = x - x0
    wy = y - y0

    def gather(ix, iy):
        # zeros padding: out-of-range corners contribute 0
        valid = (ix >= 0) & (ix <= W - 1) & (iy >= 0) & (iy <= H - 1)
        ixc = jnp.clip(ix, 0, W - 1).astype(jnp.int32)
        iyc = jnp.clip(iy, 0, H - 1).astype(jnp.int32)
        flat = img.reshape(B, H * W, C)
        idx = (iyc * W + ixc).reshape(B, -1)
        out = jnp.take_along_axis(flat, idx[..., None], axis=1)
        out = out.reshape(*ix.shape, C)
        return out * valid[..., None].astype(img.dtype)

    v00 = gather(x0, y0)
    v01 = gather(x0 + 1, y0)
    v10 = gather(x0, y0 + 1)
    v11 = gather(x0 + 1, y0 + 1)

    wx = wx[..., None].astype(img.dtype)
    wy = wy[..., None].astype(img.dtype)
    out = ((1 - wy) * ((1 - wx) * v00 + wx * v01)
           + wy * ((1 - wx) * v10 + wx * v11))

    if mask:
        # Reference masks in normalized space with strict bounds
        # (utils.py:67-69); equivalent pixel-space condition:
        inb = (x > 0) & (x < W - 1) & (y > 0) & (y < H - 1)
        return out, inb.astype(img.dtype)
    return out


def forward_warp_flow(flow: jax.Array, eps: float = 1e-3) -> jax.Array:
    """Forward-warp (bilinear-splat) a flow field onto the next frame.

    RAFT's warm start initializes the next pair's ``coords1`` from the
    previous pair's flow *advected by itself*: the pixel at ``x`` with
    flow ``f(x)`` lands at ``x + f(x)`` in the next frame and carries
    its motion estimate along.  The reference demo does this on the
    host (``forward_interpolate``, scipy griddata); this is the
    on-device equivalent — each source pixel splats its flow vector
    into the four integer neighbours of its landing site with bilinear
    weights, splats are accumulated (scatter-add) and normalized by
    the accumulated weight.  Targets nobody lands on (dis-occlusions)
    get exactly the cold-start init of zero flow.

    Args:
      flow: ``(B, H, W, 2)`` flow field, last axis ``(x, y)``.
      eps: weight floor below which a target counts as unhit.

    Returns:
      ``(B, H, W, 2)`` forward-warped flow.
    """
    B, H, W, C = flow.shape
    base = coords_grid(B, H, W, dtype=flow.dtype)
    tgt = base + flow
    x = tgt[..., 0].reshape(B, -1)
    y = tgt[..., 1].reshape(B, -1)
    x0 = jnp.floor(x)
    y0 = jnp.floor(y)
    wx = x - x0
    wy = y - y0
    vals = flow.reshape(B, -1, C)
    idxs, payloads = [], []
    for dx, dy, w in ((0, 0, (1 - wx) * (1 - wy)),
                      (1, 0, wx * (1 - wy)),
                      (0, 1, (1 - wx) * wy),
                      (1, 1, wx * wy)):
        ix = x0 + dx
        iy = y0 + dy
        valid = ((ix >= 0) & (ix <= W - 1) & (iy >= 0)
                 & (iy <= H - 1)).astype(flow.dtype)
        w = w * valid
        idxs.append((jnp.clip(iy, 0, H - 1) * W
                     + jnp.clip(ix, 0, W - 1)).astype(jnp.int32))
        payloads.append(
            jnp.concatenate([vals * w[..., None], w[..., None]], axis=-1))
    idx = jnp.concatenate(idxs, axis=1)        # (B, 4*H*W)
    val = jnp.concatenate(payloads, axis=1)    # (B, 4*H*W, C+1)
    acc = jax.vmap(
        lambda i, v: jnp.zeros((H * W, C + 1), flow.dtype).at[i].add(v)
    )(idx, val)
    den = acc[..., C:]
    out = jnp.where(den > eps, acc[..., :C] / jnp.maximum(den, eps), 0.0)
    return out.reshape(B, H, W, C)


@functools.lru_cache(maxsize=64)
def _interp_matrix(src: int, dst: int) -> "np.ndarray":
    """Dense ``(dst, src)`` align_corners=True bilinear interpolation matrix.

    Built in numpy (not jnp) so the lru_cache holds host constants — caching
    jax arrays would leak tracers when called under jit/scan/remat.
    """
    import numpy as np

    if src == 1:
        return np.ones((dst, 1), dtype=np.float32)
    pos = np.arange(dst, dtype=np.float64) * (src - 1) / max(dst - 1, 1)
    lo = np.clip(np.floor(pos), 0, src - 2).astype(np.int64)
    frac = (pos - lo).astype(np.float32)
    rows = np.arange(dst)
    m = np.zeros((dst, src), dtype=np.float32)
    m[rows, lo] += 1.0 - frac
    m[rows, lo + 1] += frac
    return m


def resize_bilinear_align_corners(x: jax.Array, new_hw) -> jax.Array:
    """``align_corners=True`` bilinear resize of ``(B, H, W, C)``.

    Expressed as two dense matmuls (separable interpolation), which maps onto
    the MXU instead of a gather — exact parity with
    ``F.interpolate(..., mode='bilinear', align_corners=True)``.
    """
    B, H, W, C = x.shape
    nh, nw = new_hw
    mh = _interp_matrix(H, nh).astype(x.dtype)
    mw = _interp_matrix(W, nw).astype(x.dtype)
    hi = jax.lax.Precision.HIGHEST  # interpolation must be exact in fp32
    out = jnp.einsum("ih,bhwc->biwc", mh, x, precision=hi)
    out = jnp.einsum("jw,biwc->bijc", mw, out, precision=hi)
    return out


def upflow8(flow: jax.Array) -> jax.Array:
    """8x upsample a flow field ``(B, H, W, 2)`` and scale values by 8."""
    B, H, W, _ = flow.shape
    return 8.0 * resize_bilinear_align_corners(flow, (8 * H, 8 * W))
