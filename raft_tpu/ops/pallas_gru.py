"""Fused ConvGRU gate chains as Pallas TPU elementwise kernels.

The ConvGRU update (models/update.py ``ConvGRU``/``SepConvGRU``) is two
convolutions plus two bandwidth-bound elementwise chains:

    z, r = sigmoid(split(convzr([h, x])))      # chain 1 consumes r
    q    = tanh(convq([r * h, x]))
    h'   = (1 - z) * h + z * q                 # chain 2 consumes z, q

The convolutions stay XLA (convq's input depends on r, so conv+gate
cannot be one kernel without reimplementing conv), but each chain
becomes ONE Pallas VMEM pass instead of an XLA elementwise chain with
HBM round-trips between the sigmoid/tanh/blend stages:

- :func:`gru_gate_rh`     — ``sigmoid(r_raw) * h``
- :func:`gru_gate_blend`  — ``(1-sigmoid(z_raw))*h + sigmoid(z_raw)*tanh(q_raw)``

Both compute fp32 in VMEM regardless of the storage dtype and cast the
result back to ``h.dtype`` (the unfused path computes in the compute
dtype throughout, so under bf16 the two paths differ at rounding level;
under fp32 they match to float ulps).  Gradients run a recomputing
``custom_vjp`` (the pallas_upsample.py template): the backward kernel
re-derives sigmoid/tanh from the saved raw activations — nothing but
the primal inputs is kept live.

Layout: operands are flattened, zero-padded to a whole number of
``(256, 128)`` fp32-tile-aligned blocks and processed on a 1-D grid —
elementwise math has no spatial structure worth preserving, and the
flat layout keeps every block full-lane regardless of the (B, H, W, C)
shape.  Gate selection is ``RAFTConfig.fused_gru`` (autotuner-ranked,
default off — see docs/PERFORMANCE.md "Fused kernels").
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from raft_tpu.ops.pallas_util import auto_interpret, tpu_pallas_call

_LANES = 128
_BLOCK_ROWS = 256


# ---------------------------------------------------------------------------
# layout: NHWC (or any shape) <-> padded (rows, 128)
# ---------------------------------------------------------------------------

def _to_rows(arrays):
    """Flatten same-shape operands to blocked ``(rows, 128)`` layout."""
    shape = arrays[0].shape
    n = 1
    for d in shape:
        n *= d
    rows = -(-n // _LANES)
    rows = -(-rows // _BLOCK_ROWS) * _BLOCK_ROWS
    pad = rows * _LANES - n
    out = [jnp.pad(a.reshape(-1), (0, pad)).reshape(rows, _LANES)
           for a in arrays]
    return out, shape, n


def _rows_call(kernel, inputs, out_dtypes, interpret):
    rows = inputs[0].shape[0]
    spec = pl.BlockSpec((_BLOCK_ROWS, _LANES), lambda i: (i, 0))
    shapes = [jax.ShapeDtypeStruct((rows, _LANES), d) for d in out_dtypes]
    single = len(out_dtypes) == 1
    return tpu_pallas_call(
        kernel,
        grid=(rows // _BLOCK_ROWS,),
        in_specs=[spec] * len(inputs),
        out_specs=spec if single else [spec] * len(out_dtypes),
        out_shape=shapes[0] if single else shapes,
        interpret=interpret)(*inputs)


# ---------------------------------------------------------------------------
# kernel bodies (fp32 compute in VMEM, cast on the way out)
# ---------------------------------------------------------------------------

def _rh_fwd_kernel(r_ref, h_ref, o_ref):
    r = r_ref[...].astype(jnp.float32)
    h = h_ref[...].astype(jnp.float32)
    o_ref[...] = (jax.nn.sigmoid(r) * h).astype(o_ref.dtype)


def _rh_bwd_kernel(r_ref, h_ref, g_ref, dr_ref, dh_ref):
    r = r_ref[...].astype(jnp.float32)
    h = h_ref[...].astype(jnp.float32)
    g = g_ref[...].astype(jnp.float32)
    s = jax.nn.sigmoid(r)
    dr_ref[...] = (g * h * s * (1.0 - s)).astype(dr_ref.dtype)
    dh_ref[...] = (g * s).astype(dh_ref.dtype)


def _blend_fwd_kernel(z_ref, q_ref, h_ref, o_ref):
    sz = jax.nn.sigmoid(z_ref[...].astype(jnp.float32))
    tq = jnp.tanh(q_ref[...].astype(jnp.float32))
    h = h_ref[...].astype(jnp.float32)
    o_ref[...] = ((1.0 - sz) * h + sz * tq).astype(o_ref.dtype)


def _blend_bwd_kernel(z_ref, q_ref, h_ref, g_ref, dz_ref, dq_ref, dh_ref):
    sz = jax.nn.sigmoid(z_ref[...].astype(jnp.float32))
    tq = jnp.tanh(q_ref[...].astype(jnp.float32))
    h = h_ref[...].astype(jnp.float32)
    g = g_ref[...].astype(jnp.float32)
    dz_ref[...] = (g * (tq - h) * sz * (1.0 - sz)).astype(dz_ref.dtype)
    dq_ref[...] = (g * sz * (1.0 - tq * tq)).astype(dq_ref.dtype)
    dh_ref[...] = (g * (1.0 - sz)).astype(dh_ref.dtype)


# ---------------------------------------------------------------------------
# custom_vjp cores over the blocked layout
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _rh_core(r2, h2, interpret):
    return _rows_call(_rh_fwd_kernel, [r2, h2], [h2.dtype], interpret)


def _rh_core_fwd(r2, h2, interpret):
    return _rh_core(r2, h2, interpret), (r2, h2)


def _rh_core_bwd(interpret, res, g):
    r2, h2 = res
    dr, dh = _rows_call(_rh_bwd_kernel, [r2, h2, g],
                        [r2.dtype, h2.dtype], interpret)
    return dr, dh


_rh_core.defvjp(_rh_core_fwd, _rh_core_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _blend_core(z2, q2, h2, interpret):
    return _rows_call(_blend_fwd_kernel, [z2, q2, h2], [h2.dtype],
                      interpret)


def _blend_core_fwd(z2, q2, h2, interpret):
    return _blend_core(z2, q2, h2, interpret), (z2, q2, h2)


def _blend_core_bwd(interpret, res, g):
    z2, q2, h2 = res
    dz, dq, dh = _rows_call(_blend_bwd_kernel, [z2, q2, h2, g],
                            [z2.dtype, q2.dtype, h2.dtype], interpret)
    return dz, dq, dh


_blend_core.defvjp(_blend_core_fwd, _blend_core_bwd)


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------

def gru_gate_rh(r_raw, h, interpret=None):
    """Fused ``sigmoid(r_raw) * h`` (the reset-gated hidden state).

    ``r_raw`` is the r half of the convzr output BEFORE the sigmoid.
    Output dtype follows ``h``.  ``interpret=None`` auto-selects
    (native on TPU, interpreter on CPU).
    """
    if interpret is None:
        interpret = auto_interpret()
    (r2, h2), shape, n = _to_rows([r_raw, h])
    out = _rh_core(r2, h2, interpret)
    return out.reshape(-1)[:n].reshape(shape)


def gru_gate_blend(z_raw, q_raw, h, interpret=None):
    """Fused GRU hidden-state blend.

    Computes ``(1-sigmoid(z_raw))*h + sigmoid(z_raw)*tanh(q_raw)`` —
    the sigmoid/tanh/lerp tail of the ConvGRU update — in one VMEM
    pass.  ``z_raw``/``q_raw`` are the raw conv outputs (pre-sigmoid /
    pre-tanh).  Output dtype follows ``h``.
    """
    if interpret is None:
        interpret = auto_interpret()
    (z2, q2, h2), shape, n = _to_rows([z_raw, q_raw, h])
    out = _blend_core(z2, q2, h2, interpret)
    return out.reshape(-1)[:n].reshape(shape)
