from raft_tpu.ops.sampler import (
    bilinear_sampler,
    coords_grid,
    resize_bilinear_align_corners,
    upflow8,
)
from raft_tpu.ops.pad import (
    InputPadder,
    bucket_hw,
    ceil_to_multiple,
    max_bucket_hw,
)
from raft_tpu.ops.upsample import convex_upsample
from raft_tpu.ops.corr import (
    all_pairs_correlation,
    build_corr_pyramid,
    corr_lookup,
    chunked_corr_lookup,
)
__all__ = [
    "bilinear_sampler",
    "coords_grid",
    "resize_bilinear_align_corners",
    "upflow8",
    "InputPadder",
    "bucket_hw",
    "ceil_to_multiple",
    "max_bucket_hw",
    "convex_upsample",
    "all_pairs_correlation",
    "build_corr_pyramid",
    "corr_lookup",
    "chunked_corr_lookup",
]
