"""Fused on-demand correlation lookup as Pallas TPU kernels.

The TPU-native replacement for the reference's ``alt_cuda_corr`` extension
(``alt_cuda_corr/correlation_kernel.cu:19-256``, SURVEY.md C6) — and,
unlike the reference (whose backward kernel exists but is never wired into
autograd, correlation.cpp:51-54), fully differentiable via
``jax.custom_vjp``.

Math redesign for the MXU (no gathers, no scatters, no atomics):

For one pyramid level with pooled target features ``f2 (Hl*Wl, C)``, query
features ``f1 (N, C)`` and window centroids ``c = coords / 2^l``:

    rows(q, y, x) = <f1_q, f2[y, x]> / sqrt(C)          (MXU matmul)
    tap(q, i, j)  = bilinear(rows(q), c_q + (i - r, j - r))

Bilinear sampling with zeros padding is a *linear* map of the row image, so
it factorizes into two dense 1-D interpolation matrices:

    wx(q, i, x) = max(0, 1 - |c_q.x + i - r - x|)       (BQ, K, Wl)
    wy(q, j, y) = max(0, 1 - |c_q.y + j - r - y|)       (BQ, K, Hl)
    tap(q,i,j)  = sum_{y,x} wy(q,j,y) * rows(q,y,x) * wx(q,i,x)

i.e. two batched mat-muls per level — the gather-heavy CUDA design
(dynamic ``floor(coords)`` windows + bilinear *scatter* with shared-memory
staging, correlation_kernel.cu:55-114) becomes three MXU contractions, and
out-of-bounds taps fall out as zero weights (the sampler's zeros-padding
semantics, utils.py:57-65).  The backward pass is the transpose of the
same contractions; ``coords`` gets zero gradient by design, matching the
per-iteration ``coords1.detach()`` truncation (raft.py:123) and the CUDA
kernel's never-filled ``coords_grad`` (correlation_kernel.cu:307).

Layout contract: tap order is x-major (``i`` walks x), levels concatenated
level-major — identical to ``raft_tpu.ops.corr`` and the reference
(corr.py:36-41).

Blocking: queries are processed in ``block_q`` chunks (grid = (B, N/BQ));
one fused kernel instance holds EVERY level's ``f2`` and one query block's
rows in VMEM.  The correlation volume never exists in HBM.

Compile-time lesson (round 2, RESOLVED): the original kernels took
>10-40 minutes of Mosaic+remote compile at every shape.  The cause was
1-D vector layouts — deriving ``cx/cy`` as ``(BQ,)`` vectors gives
Mosaic "implicit dimension" layouts whose reductions it either rejects
("unsupported output implicit dimension") or compiles pathologically
slowly.  With every tap-center kept 2-D ``(1, BQ)`` (coords passed
query-minor ``(B, 2, Npad)``, exactly like the pyramid kernels), the
fused forward compiles in ~3 s and the backward in ~8 s.  Two related
Mosaic constraints learned on the way and kept in the code: mat-muls
must stay OUT of fori_loop bodies (one hoisted dot per level into a
VMEM scratch ref), and values cannot be dynamic_slice'd — tile passes
over materialized blocks need scratch REFS.

VMEM sizing: beyond-HBM shapes auto-drop the ``f2`` blocks to bf16
(fp32 accumulation) once fp32 ``f2`` + correlation scratch would
exceed ~48 MB (``_odm_f2_dtype``) — at the 1440x2560 target the fp32
form (~118 MB) cannot fit the budget.

Backward tiling (round 4 — removes the round-3 VMEM ceiling): the
FUSED backward holds every level's ``f2`` + ``df2`` + drows scratch in
VMEM per instance, which stops compiling at >=1088x1920 (level 0 alone
is 33-56 MB fp32, BENCH_BEYOND_HBM_r03.json).  ``_corr_bwd`` therefore
estimates the fused residency and moves oversized levels onto a BLOCKED
per-level pair (the TPU answer to the CUDA backward's tiled atomicAdd
accumulation, correlation_kernel.cu:123-256):

- ``_odm_bwd_df1_blocked_kernel``  grid (B, QB, TY): ``f2`` streams
  through VMEM in ``(tile_h, Wl)`` row tiles; the ``df1`` block (index
  constant across the innermost tile dim) accumulates in VMEM.
- ``_odm_bwd_df2_blocked_kernel``  grid (B, TY, QB): one ``df2``
  spatial tile (index constant across the innermost query dim)
  accumulates in VMEM while f1/coords/g stream.

Both kernels skip a (query block, row tile) pair entirely when no query
window can overlap the tile's rows (``_tile_overlaps`` — a min/max
bound on the block's ``cy``): ``drows`` for such a pair is exactly
zero, so the skip is lossless, and because query blocks are
raster-ordered their windows cluster in y — at bounded flow the dense
contraction sparsifies by roughly Hl / (window + flow extent).
"""

from __future__ import annotations

import functools
import os
from typing import Sequence

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from raft_tpu.ops.pallas_util import tpu_pallas_call


# Image rows per inner mat-mul tile; statically unrolled inside, fori_loop
# across tiles (full unroll over Hl explodes Mosaic compile time, per-row
# mat-muls are latency-bound).
_Y_TILE = 8


def _tap_weight(c: jax.Array, offset, pos) -> jax.Array:
    """Bilinear weight ``max(0, 1 - |c + offset - pos|)`` (zeros padding
    falls out as all-zero weights for out-of-range taps)."""
    return jnp.maximum(0.0, 1.0 - jnp.abs(c + offset - pos))


def _odm_fwd_level_body(f2_ref, f1, c_ref, out_ref, scratch_ref, lvl, off,
                        hl, wl, k, inv_scale):
    """One level of the fused on-demand forward: ONE (Hl*Wl, C) x
    (C, BQ) mat-mul materializes this level's correlation block into a
    VMEM scratch ref (<=1.5 MB at block_q=128), then the tap pass is
    the same pure-VPU tile loop as the pyramid kernel.  Mat-muls must
    stay OUT of the fori_loop bodies: the original row-streamed design
    (a dot per y-tile) made Mosaic compile time explode past 10-minute
    budgets even for a single standalone lookup.  (The scratch ref is
    needed because Mosaic cannot dynamic_slice VALUES, only refs.)"""
    bq = f1.shape[0]
    r = (k - 1) // 2
    lvl_div = 1.0 / (2.0 ** lvl)
    # Keep the tap centers 2-D (1, BQ): Mosaic represents 1-D vectors
    # with an implicit dim and rejects reductions mixing those layouts
    # ("unsupported output implicit dimension") — the pyramid kernel
    # compiles exactly this math with everything 2-D.
    cx = c_ref[0, 0:1, :] * lvl_div     # (1, BQ)
    cy = c_ref[0, 1:2, :] * lvl_div
    posx = jax.lax.broadcasted_iota(jnp.int32, (wl, bq), 0) \
        .astype(jnp.float32)            # (Wl, BQ)
    C = f1.shape[-1]

    f2m = f2_ref[0].reshape(hl * wl, C)
    scratch_ref[...] = jax.lax.dot_general(
        f2m, f1.astype(f2m.dtype), (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * inv_scale     # (Hl*Wl, BQ)

    t_y = min(_Y_TILE, hl)
    n_tiles = hl // t_y

    def _tile_taps(y0f, yis, blk, acc):
        for yi in yis:
            row = blk[yi * wl:(yi + 1) * wl, :]
            for j in range(k):
                acc[j] += _tap_weight(cy, j - r - yi, y0f) * row
        return acc

    def tile_body(t, acc):
        blk = scratch_ref[pl.ds(t * t_y * wl, t_y * wl), :]
        return _tile_taps((t * t_y).astype(jnp.float32), range(t_y), blk,
                          acc)

    acc = jax.lax.fori_loop(
        0, n_tiles, tile_body,
        [jnp.zeros((wl, bq), jnp.float32) for _ in range(k)])
    if hl % t_y:  # static remainder rows
        rem = hl - hl % t_y
        acc = _tile_taps(jnp.float32(rem), range(hl - rem),
                         scratch_ref[rem * wl:, :], acc)

    # Contract x with a ones-row mat-mul: the keepdims sublane-sum form
    # hits Mosaic "unsupported output implicit dimension" at some widths
    # (observed at wl=120 — a common 960-px-frame level-0 width) while
    # (1, Wl) @ (Wl, BQ) compiles at every width; with the 2-D tap-center
    # layouts above, compile time is seconds either way.
    ones_row = jnp.ones((1, wl), jnp.float32)
    for i in range(k):
        wx_i = _tap_weight(cx, float(i - r), posx)  # (Wl, BQ)
        for j in range(k):
            out_ref[0, off + i * k + j:off + i * k + j + 1, :] = \
                jax.lax.dot_general(
                    ones_row, wx_i * acc[j], (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)


def _odm_fwd_kernel(*refs, levels, k, kk_total, inv_scale):
    """Fused on-demand forward over every non-empty level (ONE
    pallas_call per lookup instead of one per level — the per-call
    overhead dominated the small levels).  refs =
    [f2_0..f2_{n-1}, f1, c, out, scratch_0..scratch_{n-1}];
    out: (1, L*k*k, BQ) query-minor."""
    nl = len(levels)
    f1_ref, c_ref, out_ref = refs[nl], refs[nl + 1], refs[nl + 2]
    scratch_refs = refs[nl + 3:]
    f1 = f1_ref[0]                      # (BQ, C)
    covered = 0
    for (lvl, off, hl, wl), f2_ref, scratch_ref in zip(levels, refs[:nl],
                                                       scratch_refs):
        _odm_fwd_level_body(f2_ref, f1, c_ref, out_ref, scratch_ref, lvl,
                            off, hl, wl, k, inv_scale)
        covered += k * k
    if covered < kk_total:  # empty (over-pooled) trailing levels
        out_ref[0, covered:, :] = jnp.zeros(
            (kk_total - covered, f1.shape[0]), jnp.float32)


def _odm_bwd_level_body(f2_ref, df2_ref, scratch_ref, f1, c_ref, g_ref,
                        lvl, off, hl, wl, k, inv_scale, is_first_block,
                        df1):
    """One level of the fused on-demand backward: per image row y,
    ``drows_y(x, q) = sum_ij g(i,j,q) wx_i(x,q) wy_j(y,q)`` feeds two
    mat-muls — ``df1 += drows @ f2`` and ``df2[y-tile] += drows^T-style
    contraction over queries`` (accumulated across query blocks; the TPU
    grid runs sequentially, so no atomics are needed — unlike the
    reference's atomicAdd scatter, correlation_kernel.cu:237)."""
    bq = f1.shape[0]
    r = (k - 1) // 2
    lvl_div = 1.0 / (2.0 ** lvl)
    cx = c_ref[0, 0:1, :] * lvl_div     # (1, BQ) — 2-D, see fwd body
    cy = c_ref[0, 1:2, :] * lvl_div
    posx = jax.lax.broadcasted_iota(jnp.int32, (wl, bq), 0) \
        .astype(jnp.float32)

    # b_j(x, q) = sum_i wx_i(x, q) g(i*k+j, q)
    b = [
        sum(_tap_weight(cx, float(ti - r), posx)
            * g_ref[0, off + ti * k + tj:off + ti * k + tj + 1, :]
            for ti in range(k))
        for tj in range(k)
    ]                                    # K_j x (Wl, BQ)

    @pl.when(is_first_block)
    def _():
        df2_ref[0] = jnp.zeros_like(df2_ref[0])

    C = f1.shape[-1]
    t_y = min(_Y_TILE, hl)
    n_tiles = hl // t_y

    # Assemble the full (Hl*Wl, BQ) drows image into a VMEM scratch ref
    # with a pure-VPU tile loop, then TWO mat-muls for the whole level —
    # mat-muls in fori bodies blow up Mosaic compile time (see forward
    # body), and Mosaic cannot dynamic_update_slice VALUES, only refs.
    def _tile_rows(y0f, yis):
        return jnp.concatenate([
            sum(_tap_weight(cy, tj - r - yi, y0f) * b[tj]
                for tj in range(k))
            for yi in yis
        ], axis=0) * inv_scale                           # (T*Wl, BQ)

    def tile_body(t, _):
        scratch_ref[pl.ds(t * t_y * wl, t_y * wl), :] = _tile_rows(
            (t * t_y).astype(jnp.float32), range(t_y))
        return 0

    jax.lax.fori_loop(0, n_tiles, tile_body, 0)
    if hl % t_y:  # static remainder rows
        rem = hl - hl % t_y
        scratch_ref[rem * wl:, :] = _tile_rows(jnp.float32(rem),
                                               range(hl - rem))

    drows = scratch_ref[...]
    f2_flat = f2_ref[0].reshape(hl * wl, C).astype(jnp.float32)
    df1 = df1 + jax.lax.dot_general(
        drows, f2_flat, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)              # (BQ, C)
    df2_ref[0] += jax.lax.dot_general(
        drows, f1, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).reshape(hl, wl, C)
    return df1


def _odm_bwd_kernel(*refs, levels, k, inv_scale):
    """Fused on-demand backward; refs = [f2_0.., f1, c, g, df1, df2_0..,
    scratch_0..].  ``df1`` accumulates across levels in registers and is
    written once; each level's ``df2`` accumulates across query blocks
    in HBM (sequential grid)."""
    nl = len(levels)
    f1_ref, c_ref, g_ref, df1_ref = refs[nl], refs[nl + 1], refs[nl + 2], \
        refs[nl + 3]
    df2_refs = refs[nl + 4:nl + 4 + nl]
    scratch_refs = refs[nl + 4 + nl:]
    f1 = f1_ref[0]
    is_first = pl.program_id(1) == 0
    df1 = jnp.zeros((f1.shape[0], f1.shape[1]), jnp.float32)
    for (lvl, off, hl, wl), f2_ref, df2_ref, scr in zip(
            levels, refs[:nl], df2_refs, scratch_refs):
        df1 = _odm_bwd_level_body(f2_ref, df2_ref, scr, f1, c_ref, g_ref,
                                  lvl, off, hl, wl, k, inv_scale,
                                  is_first, df1)
    df1_ref[0] = df1


# --- Blocked backward (beyond-HBM shapes) --------------------------------

# Per-instance VMEM budget above which the fused backward stops being
# offered a level (round 3 measured compile OOM at ~111 MB estimated
# residency against the 100 MB limit; 736x1280 at ~51 MB compiles).
_FUSED_BWD_BUDGET = 78 * 1024 * 1024
_BWD_TILE_H = 8          # f2 rows per streamed tile
_BWD_BLOCK_Q = 512       # query block of the blocked kernels (bigger than
                         # the fused 128: f2 re-streams once per query
                         # block in the df1 kernel, so fewer blocks =
                         # proportionally less DMA.  The block fetch is
                         # unconditional — _tile_overlaps skips COMPUTE,
                         # not DMA — so at 1440x2560 level 0 (bf16 f2 =
                         # 29.5 MB, 113 blocks at 512) the df1 kernel
                         # moves ~3.3 GB/call; 1024 halves that for
                         # ~24 MB more VMEM working set (drows + b_j
                         # doubling), still under the 100 MB limit.
                         # Override per-run with RAFT_ODM_BWD_BLOCK_Q to
                         # sweep on hardware (scripts/tpu_backlog_r05).


def _fused_bwd_est(nonempty, block_q, k):
    """Estimated per-instance VMEM bytes of the FUSED backward: every
    level's f2 + df2 + drows scratch resident, plus query blocks and the
    b_j working set at the widest level."""
    if not nonempty:
        return 0
    C = nonempty[0][1].shape[-1]
    rows = sum(f2.shape[1] * f2.shape[2] for _, f2 in nonempty)
    f2b = 2 if _odm_f2_dtype(nonempty, block_q) == jnp.bfloat16 else 4
    wl0 = max(f2.shape[2] for _, f2 in nonempty)
    return (rows * C * (f2b + 4)                 # f2 + fp32 df2
            + rows * block_q * 4                 # drows scratch
            + (k + 2) * wl0 * block_q * 4        # b_j + posx working set
            + block_q * 4 * (2 * C + 2 + len(nonempty) * k * k))


def _partition_bwd_levels(nonempty, block_q, k):
    """Partition levels for the backward: fused while the whole set fits
    the VMEM budget, biggest levels (level 0 first — pyramid sizes
    descend) onto the blocked per-level pair beyond it.  At <=736x1280
    everything stays fused; 1088x1920+ moves level 0 (and, if ever
    needed, more) out — the round-3 compile ceiling.

    Returns ``(blocked, fused)`` lists of ``(lvl, f2)`` pairs."""
    fused = list(nonempty)
    blocked = []
    while fused and _fused_bwd_est(fused, block_q, k) > _FUSED_BWD_BUDGET:
        blocked.append(fused.pop(0))
    return blocked, fused


def _tile_overlaps(c_ref, lvl, r, tile_h, t):
    """True iff ANY query in this block has a window row intersecting
    f2 rows [t*tile_h, (t+1)*tile_h).  Each query touches rows
    [cy - r - 1, cy + r + 1] (bilinear spreads one row past the tap
    radius); padded queries sit at -1e6 and never extend the max."""
    cy = c_ref[0, 1:2, :] * (1.0 / 2.0 ** lvl)
    y0 = (t * tile_h).astype(jnp.float32)
    return jnp.logical_and(jnp.max(cy) + (r + 1.0) >= y0,
                           jnp.min(cy) - (r + 1.0) <= y0 + (tile_h - 1.0))


def _bwd_window_rows(c_ref, g_ref, g_off, lvl, k, wl, tile_h, t,
                     inv_scale):
    """``drows`` for f2 rows [t*tile_h, (t+1)*tile_h) of one level:
    (tile_h*wl, BQ).  Same math as the fused backward's ``_tile_rows``
    but over a single streamed tile (tile_h is small and static, so no
    fori loop and no scratch ref — one concatenate feeds one mat-mul).
    ``g_off`` is this level's sublane offset into the full (L*k*k, BQ)
    cotangent block (Mosaic requires sublane block dims divisible by 8
    or whole, so the level can't be sliced by the block spec)."""
    bq = c_ref.shape[2]
    r = (k - 1) // 2
    lvl_div = 1.0 / (2.0 ** lvl)
    cx = c_ref[0, 0:1, :] * lvl_div     # (1, BQ) — 2-D, see fwd body
    cy = c_ref[0, 1:2, :] * lvl_div
    posx = jax.lax.broadcasted_iota(jnp.int32, (wl, bq), 0) \
        .astype(jnp.float32)
    b = [
        sum(_tap_weight(cx, float(ti - r), posx)
            * g_ref[0, g_off + ti * k + tj:g_off + ti * k + tj + 1, :]
            for ti in range(k))
        for tj in range(k)
    ]                                    # K_j x (Wl, BQ)
    y0f = (t * tile_h).astype(jnp.float32)
    return jnp.concatenate([
        sum(_tap_weight(cy, float(tj - r - yi), y0f) * b[tj]
            for tj in range(k))
        for yi in range(tile_h)
    ], axis=0) * inv_scale               # (tile_h*Wl, BQ)


def _odm_bwd_df1_blocked_kernel(f2_ref, c_ref, g_ref, df1_ref, *, lvl,
                                g_off, wl, k, inv_scale, tile_h):
    """df1 contribution of ONE blocked level; grid (B, QB, TY).  The df1
    block index is constant across the innermost tile dim, so it
    accumulates in VMEM while f2 streams tile by tile."""
    t = pl.program_id(2)

    @pl.when(t == 0)
    def _():
        df1_ref[0] = jnp.zeros_like(df1_ref[0])

    @pl.when(_tile_overlaps(c_ref, lvl, (k - 1) // 2, tile_h, t))
    def _():
        drows = _bwd_window_rows(c_ref, g_ref, g_off, lvl, k, wl,
                                 tile_h, t, inv_scale)
        f2t = f2_ref[0].reshape(tile_h * wl, -1).astype(jnp.float32)
        df1_ref[0] += jax.lax.dot_general(
            drows, f2t, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)          # (BQ, C)


def _odm_bwd_df2_blocked_kernel(f1_ref, c_ref, g_ref, df2_ref, *, lvl,
                                g_off, wl, k, inv_scale, tile_h):
    """df2 of ONE blocked level; grid (B, TY, QB).  The df2 spatial tile
    is constant across the innermost query dim, so it accumulates in
    VMEM (sequential grid — no atomics, unlike correlation_kernel.cu:237)
    while f1/coords/g stream."""
    t = pl.program_id(1)
    q = pl.program_id(2)

    @pl.when(q == 0)
    def _():
        df2_ref[0] = jnp.zeros_like(df2_ref[0])

    @pl.when(_tile_overlaps(c_ref, lvl, (k - 1) // 2, tile_h, t))
    def _():
        drows = _bwd_window_rows(c_ref, g_ref, g_off, lvl, k, wl,
                                 tile_h, t, inv_scale)
        f1 = f1_ref[0].astype(jnp.float32)               # (BQ, C)
        df2_ref[0] += jax.lax.dot_general(
            drows, f1, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).reshape(
                tile_h, wl, -1)


def _odm_bwd_blocked_level(lvl, f2, f1p, cpt, gp, k, inv_scale, block_q,
                           interpret, f2_dtype=jnp.float32):
    """Run the blocked kernel pair for one oversized level.

    Args:
      f2: this level's pooled target features ``(B, Hl, Wl, C)``.
      f1p / cpt / gp: query features ``(B, Npad, C)``, centroids
        ``(B, 2, Npad)`` and taps cotangent ``(B, L*k*k, Npad)``, all
        padded to a multiple of ``block_q``.
      f2_dtype: streaming dtype for f2 (the df1 kernel re-streams the
        whole level once per query block, so bf16 — what the fused path
        stores at beyond-HBM shapes anyway — halves the dominant DMA;
        the kernel accumulates fp32 regardless).

    Returns:
      ``(df1_level (B, Npad, C), df2_level (B, Hl, Wl, C))`` fp32.
    """
    B, Hl, Wl, C = f2.shape
    tile_h = min(_BWD_TILE_H, Hl)
    Hp = -(-Hl // tile_h) * tile_h
    TY = Hp // tile_h
    Npad = f1p.shape[1]
    QB = Npad // block_q
    f2p = f2.astype(f2_dtype)
    if Hp != Hl:
        # Zero rows contribute zero to df1 regardless of tap weights, and
        # the padded df2 rows are sliced away below — no in-kernel masks.
        f2p = jnp.pad(f2p, ((0, 0), (0, Hp - Hl), (0, 0), (0, 0)))
    lkk = gp.shape[1]
    kern1 = functools.partial(_odm_bwd_df1_blocked_kernel, lvl=lvl,
                              g_off=lvl * k * k, wl=Wl, k=k,
                              inv_scale=inv_scale, tile_h=tile_h)
    df1 = tpu_pallas_call(
        kern1,
        grid=(B, QB, TY),
        in_specs=[
            pl.BlockSpec((1, tile_h, Wl, C), lambda b, q, t: (b, t, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 2, block_q), lambda b, q, t: (b, 0, q),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, lkk, block_q), lambda b, q, t: (b, 0, q),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, block_q, C), lambda b, q, t: (b, q, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((B, Npad, C), jnp.float32),
        interpret=interpret,
    )(f2p, cpt, gp)

    kern2 = functools.partial(_odm_bwd_df2_blocked_kernel, lvl=lvl,
                              g_off=lvl * k * k, wl=Wl, k=k,
                              inv_scale=inv_scale, tile_h=tile_h)
    df2p = tpu_pallas_call(
        kern2,
        grid=(B, TY, QB),
        in_specs=[
            pl.BlockSpec((1, block_q, C), lambda b, t, q: (b, q, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 2, block_q), lambda b, t, q: (b, 0, q),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, lkk, block_q), lambda b, t, q: (b, 0, q),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, tile_h, Wl, C),
                               lambda b, t, q: (b, t, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((B, Hp, Wl, C), jnp.float32),
        interpret=interpret,
    )(f1p, cpt, gp)
    return df1, df2p[:, :Hl]


def _pad_coords_oor(coords, npad):
    """Pad the query dim to ``npad`` with far-out-of-range centers — every
    window weight becomes zero (the sampler's zeros-padding semantics), so
    padded queries contribute nothing in forward or backward."""
    pad = npad - coords.shape[1]
    if not pad:
        return coords
    return jnp.pad(coords, ((0, 0), (0, pad), (0, 0)),
                   constant_values=-1e6)


def _pad_queries(f1, coords, block_q):
    B, N, C = f1.shape
    nblocks = -(-N // block_q)
    pad = nblocks * block_q - N
    if pad:
        f1 = jnp.pad(f1, ((0, 0), (0, pad), (0, 0)))
        coords = _pad_coords_oor(coords, nblocks * block_q)
    return f1, coords, nblocks


def _auto_interpret() -> bool:
    from raft_tpu.ops.pallas_util import auto_interpret

    return auto_interpret()


# ---------------------------------------------------------------------------
# Fused lookup over the MATERIALIZED pyramid (the allpairs training path)
#
# The XLA window-sampling einsums (raft_tpu.ops.corr._sample_windows) are
# batched (K, Hl) x (Hl, Wl) mat-muls per query — M=9 streaming rows and a
# 46-of-128 contraction leave the MXU mostly idle.  Here the same math runs
# on the VPU with queries in the sublane dim: for each image row y, each of
# the K vertical taps accumulates ``wy_j(y) * row_y`` as one (BQ, Wl)
# fused-multiply-add, then the K horizontal taps contract x with a lane
# reduction — both interpolation stages fused in VMEM, the (BQ, K, Wl)
# intermediate never touches HBM.  ~10x faster than the einsum pair in
# isolation on v5e.
#
# The backward is the exact transpose, and is race-free by construction:
# each query owns its correlation row, so ``dcorr`` blocks never overlap
# (grid = (B, N/BQ) writes disjoint (BQ, Hl, Wl) slabs) — no atomics, no
# sequential-grid accumulation.
# ---------------------------------------------------------------------------

_ROW_TILE = 8


def _pyr_fwd_level_body(corr_ref, c_ref, out_ref, acc_ref, lvl, out_off,
                        hl, wl, k):
    """One level's forward sampling inside the fused kernel (QUERY-MINOR:
    queries live in lanes, x in sublanes): write ``(k*k, BQ)`` taps at
    sublane offset ``out_off`` of ``out_ref``.

    Tap accumulation lives in a ``(k*wl, BQ)`` VMEM scratch ref (not
    loop-carried registers) so each row tile can be SKIPPED outright
    when no query window reaches its rows — queries are raster-ordered,
    so one block's ``cy`` spans ~2 image rows plus flow, and at bounded
    flow most of the image contributes nothing to a block's taps (round
    4: same bound as the blocked backward's ``_tile_overlaps``).

    corr_ref: (1, hl, wl, BQ); c_ref: (1, 2, BQ); out: (1, L*k*k, BQ)."""
    bq = c_ref.shape[2]
    r = (k - 1) // 2
    lvl_div = 1.0 / (2.0 ** lvl)
    cx = c_ref[0, 0:1, :] * lvl_div      # (1, BQ)
    cy = c_ref[0, 1:2, :] * lvl_div
    posx = jax.lax.broadcasted_iota(jnp.int32, (wl, bq), 0) \
        .astype(jnp.float32)
    wx = [_tap_weight(cx, float(i - r), posx) for i in range(k)]  # (wl,BQ)

    T = min(_ROW_TILE, hl)
    nt = hl // T
    # Window bounds hoisted out of the tile loop: one min/max pair per
    # level instead of per tile.  Padded queries sit at -1e6: they relax
    # the lower bound but never extend the upper one.
    ymax = jnp.max(cy) + (r + 1.0)
    ymin = jnp.min(cy) - (r + 1.0)

    acc_ref[...] = jnp.zeros((k * wl, bq), jnp.float32)

    def tile_body(t, _):
        y0 = (t * T).astype(jnp.float32)

        @pl.when(jnp.logical_and(ymax >= y0, ymin <= y0 + (T - 1.0)))
        def _():
            blk = corr_ref[0, pl.ds(t * T, T), :, :]     # (T, wl, BQ)
            for yi in range(T):
                # fp32 accumulation regardless of the stored pyramid
                # dtype (corr_dtype='bfloat16' halves the HBM read
                # traffic; the convert rides the VMEM load).
                row = blk[yi, :, :].astype(jnp.float32)
                for j in range(k):
                    acc_ref[j * wl:(j + 1) * wl, :] += _tap_weight(
                        cy, float(j - r - yi), y0) * row
        return 0

    jax.lax.fori_loop(0, nt, tile_body, 0)
    if hl % T:
        rem = nt * T
        blk = corr_ref[0, rem:, :, :]
        for yi in range(hl - rem):
            row = blk[yi, :, :].astype(jnp.float32)
            for j in range(k):
                acc_ref[j * wl:(j + 1) * wl, :] += _tap_weight(
                    cy, float(j - r - yi), float(rem)) * row

    for i in range(k):
        for j in range(k):
            out_ref[0, out_off + i * k + j:out_off + i * k + j + 1, :] = \
                jnp.sum(wx[i] * acc_ref[j * wl:(j + 1) * wl, :], axis=0,
                        keepdims=True).astype(out_ref.dtype)


def _pyr_bwd_level_body(c_ref, g_ref, dcorr_ref, lvl, g_off, hl, wl, k):
    """One level's transpose inside the fused kernel (QUERY-MINOR):
    scatter the taps at sublane offset ``g_off`` of ``g_ref`` into this
    level's ``dcorr`` (1, hl, wl, BQ)."""
    bq = c_ref.shape[2]
    r = (k - 1) // 2
    lvl_div = 1.0 / (2.0 ** lvl)
    cx = c_ref[0, 0:1, :] * lvl_div
    cy = c_ref[0, 1:2, :] * lvl_div
    posx = jax.lax.broadcasted_iota(jnp.int32, (wl, bq), 0) \
        .astype(jnp.float32)

    # b_j(x, q) = sum_i wx_i(x, q) g(i*k+j, q)
    b = [sum(_tap_weight(cx, float(i - r), posx)
             * g_ref[0, g_off + i * k + j:g_off + i * k + j + 1, :]
             for i in range(k)) for j in range(k)]

    T = min(_ROW_TILE, hl)
    nt = hl // T
    # Same per-tile window bound as the forward: tiles no query window
    # reaches get a plain zero store instead of the 9-FMA-per-row
    # construction (the write itself cannot be skipped — dcorr is dense).
    ymax = jnp.max(cy) + (r + 1.0)
    ymin = jnp.min(cy) - (r + 1.0)

    def _rows(y0f, yis):
        return jnp.stack([
            sum(_tap_weight(cy, float(j - r - yi), y0f) * b[j]
                for j in range(k)) for yi in yis
        ], axis=0)                                   # (T, wl, BQ)

    def tile_body(t, _):
        y0 = (t * T).astype(jnp.float32)
        hit = jnp.logical_and(ymax >= y0, ymin <= y0 + (T - 1.0))

        @pl.when(hit)
        def _():
            dcorr_ref[0, pl.ds(t * T, T), :, :] = _rows(
                y0, range(T)).astype(dcorr_ref.dtype)

        @pl.when(jnp.logical_not(hit))
        def _():
            dcorr_ref[0, pl.ds(t * T, T), :, :] = jnp.zeros(
                (T, wl, bq), dcorr_ref.dtype)
        return 0

    jax.lax.fori_loop(0, nt, tile_body, 0)
    if hl % T:
        rem = nt * T
        dcorr_ref[0, rem:, :, :] = _rows(
            float(rem), range(hl - rem)).astype(dcorr_ref.dtype)


def _pyr_multi_fwd_kernel(*refs, levels, k, kk_total):
    """Fused forward over every non-empty level: round-2 profiling showed
    the per-call overhead of one pallas_call per level per direction
    (~200 calls/step at unroll 6) costing as much as the level-0 math —
    the small levels were pure overhead.  ``levels``: static list of
    ``(lvl, out_off, hl, wl)``; refs = [corr_0..corr_{n-1}, c, out,
    acc_0..acc_{n-1}]."""
    nl = len(levels)
    c_ref, out_ref = refs[nl], refs[nl + 1]
    acc_refs = refs[nl + 2:]
    bq = c_ref.shape[2]
    covered = 0
    for (lvl, off, hl, wl), corr_ref, acc_ref in zip(levels, refs[:nl],
                                                     acc_refs):
        _pyr_fwd_level_body(corr_ref, c_ref, out_ref, acc_ref, lvl, off,
                            hl, wl, k)
        covered += k * k
    if covered < kk_total:  # empty (over-pooled) trailing levels -> zeros
        out_ref[0, covered:, :] = jnp.zeros((kk_total - covered, bq),
                                            out_ref.dtype)


def _pyr_multi_bwd_kernel(*refs, levels, k):
    """Fused transpose over every non-empty level; refs =
    [c, g, dcorr_0..dcorr_{n-1}]."""
    c_ref, g_ref = refs[0], refs[1]
    for (lvl, off, hl, wl), dcorr_ref in zip(levels, refs[2:]):
        _pyr_bwd_level_body(c_ref, g_ref, dcorr_ref, lvl, off, hl, wl, k)


def _pyr_levels_fwd(pyramid, coords_p, radius, block_q, interpret,
                    out_dtype=jnp.float32):
    """All levels in ONE pallas_call -> (B, L*k*k, Npad) taps.

    Query-minor layout throughout: ``pyramid`` levels are
    ``(B, hl, wl, Npad)`` and ``coords_p`` is ``(B, 2, Npad)`` — queries
    in lanes, so every VMEM/HBM tile is dense (Npad is a multiple of
    128) and the per-tap contraction is a sublane reduction."""
    B = pyramid[0].shape[0]
    Npad = pyramid[0].shape[3]
    k = 2 * radius + 1
    L = len(pyramid)
    nonempty, levels = _odm_levels(pyramid, k)
    kern = functools.partial(_pyr_multi_fwd_kernel, levels=levels, k=k,
                             kk_total=L * k * k)
    in_specs = [
        pl.BlockSpec((1, c.shape[1], c.shape[2], block_q),
                     lambda b, i: (b, 0, 0, i),
                     memory_space=pltpu.VMEM)
        for _, c in nonempty
    ] + [pl.BlockSpec((1, 2, block_q), lambda b, i: (b, 0, i),
                      memory_space=pltpu.VMEM)]
    return tpu_pallas_call(
        kern,
        grid=(B, Npad // block_q),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, L * k * k, block_q),
                               lambda b, i: (b, 0, i),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((B, L * k * k, Npad), out_dtype),
        scratch_shapes=[
            pltpu.VMEM((k * c.shape[2], block_q), jnp.float32)
            for _, c in nonempty
        ],
        interpret=interpret,
    )(*[c for _, c in nonempty], coords_p)


def _pyr_levels_bwd(coords_p, g, shapes, radius, block_q, interpret):
    """Grouped transpose calls; ``g``: (B, L*k*k, Npad), levels
    query-minor (B, hl, wl, Npad).  Unlike the forward, level 0 stays
    its OWN pallas_call: one call producing all four dcorr outputs pins
    the whole group (537+134+33+8 MB fp32 at chairs batch 16) live per
    unrolled iteration and OOMs — keeping the big level separate lets
    XLA's scheduler interleave its accumulation and retire the temp
    early.  The SMALL levels (1..) are fused into one call: profiled at
    ~0.35 ms/call with near-zero math, they were pure per-call overhead
    (48 bwd calls/step at unroll 12), and their combined liveness is
    <15% of level 0's."""
    B, _, Npad = coords_p.shape
    k = 2 * radius + 1
    nonempty = [(lvl, s, dt) for lvl, (s, dt) in enumerate(shapes)
                if s[1] and s[2]]
    # [[level0], [level1..]] — singleton groups when only one level;
    # no groups at all when every level is empty (degenerate over-pooled
    # pyramid) so the all-zeros fallback below covers it instead of a
    # zero-output pallas_call.
    groups = [g for g in (nonempty[:1], nonempty[1:]) if g]
    by_level = {}
    for grp in groups:
        kern = functools.partial(
            _pyr_multi_bwd_kernel,
            levels=[(lvl, lvl * k * k, s[1], s[2]) for lvl, s, _ in grp],
            k=k)
        outs = tpu_pallas_call(
            kern,
            grid=(B, Npad // block_q),
            in_specs=[
                pl.BlockSpec((1, 2, block_q), lambda b, i: (b, 0, i),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((1, k * k * len(shapes), block_q),
                             lambda b, i: (b, 0, i),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=[
                pl.BlockSpec((1, s[1], s[2], block_q),
                             lambda b, i: (b, 0, 0, i),
                             memory_space=pltpu.VMEM)
                for _, s, _ in grp
            ],
            out_shape=[
                jax.ShapeDtypeStruct((B, s[1], s[2], Npad), dt)
                for _, s, dt in grp
            ],
            interpret=interpret,
        )(coords_p, g)
        for (lvl, _, _), out in zip(grp, outs):
            by_level[lvl] = out
    return [by_level.get(lvl, jnp.zeros(s, dt))
            for lvl, (s, dt) in enumerate(shapes)]


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5))
def pallas_pyramid_lookup(pyramid, coords, radius: int = 4,
                          block_q: int = 128, interpret=None,
                          out_dtype=jnp.float32):
    """Fused window sampling of a MATERIALIZED correlation pyramid.

    Drop-in replacement for :func:`raft_tpu.ops.corr.corr_lookup` (the
    reference ``CorrBlock.__call__``, corr.py:29-50) — same tap-order
    contract, same zeros-padding bilinear semantics.

    Args:
      pyramid: list of ``(B, Hl, Wl, Npad)`` QUERY-MINOR levels (fp32 or
        bf16 storage — see ``RAFTConfig.corr_dtype``; taps always
        accumulate fp32 in-kernel and cotangents match each level's
        stored dtype)
        (from :func:`raft_tpu.ops.corr.build_corr_pyramid_flat`) whose
        query dim is already padded to a multiple of ``block_q`` (zero
        fmap1 rows correlate to zero).
      coords: ``(B, H1, W1, 2)`` level-0 centroids (N = H1*W1 real
        queries), last axis ``(x, y)``.
      out_dtype: tap output dtype.  Pass bf16 when the consumer casts
        immediately anyway (the refinement step does) — halves the tap
        write/read traffic; accumulation stays fp32 in-kernel either
        way (hashable static arg: use ``jnp.bfloat16``, not a dtype
        instance).

    Returns:
      ``(B, H1, W1, L * (2r+1)^2)`` ``out_dtype`` lookup features.
    """
    out, _ = _pyr_fwd(pyramid, coords, radius, block_q, interpret,
                      out_dtype)
    return out


def _pyr_fwd(pyramid, coords, radius, block_q, interpret,
             out_dtype=jnp.float32):
    if interpret is None:
        interpret = _auto_interpret()
    B, H1, W1, _ = coords.shape
    N = H1 * W1
    Npad = pyramid[0].shape[3]
    if Npad % block_q:
        raise ValueError(
            f"pyramid query dim {Npad} is not a multiple of block_q "
            f"{block_q}; build the pyramid with "
            f"build_corr_pyramid_flat(..., pad_q={block_q}) — a mismatch "
            "would silently skip trailing query lanes in the Pallas grid")
    k = 2 * radius + 1
    c = _pad_coords_oor(coords.reshape(B, N, 2).astype(jnp.float32),
                        Npad).transpose(0, 2, 1)
    out = _pyr_levels_fwd(list(pyramid), c, radius, block_q, interpret,
                          out_dtype)
    out = out[:, :, :N].reshape(B, len(pyramid) * k * k, H1, W1)
    # The bwd needs each level's shape AND stored dtype (cotangents must
    # match the primal dtypes, which may differ per level); dtypes aren't
    # valid residual leaves, so carry a zero-size prototype per level.
    return (out.transpose(0, 2, 3, 1),
            (tuple(x.shape for x in pyramid),
             tuple(jnp.zeros((0,), x.dtype) for x in pyramid), coords))


def _pyr_bwd(radius, block_q, interpret, out_dtype, residuals, g):
    shapes, protos, coords = residuals
    if interpret is None:
        interpret = _auto_interpret()
    B, H1, W1, _ = coords.shape
    N = H1 * W1
    Npad = shapes[0][3]
    if Npad % block_q:
        raise ValueError(
            f"pyramid query dim {Npad} is not a multiple of block_q "
            f"{block_q}; build the pyramid with "
            f"build_corr_pyramid_flat(..., pad_q={block_q})")
    c = _pad_coords_oor(coords.reshape(B, N, 2).astype(jnp.float32),
                        Npad).transpose(0, 2, 1)
    g = g.reshape(B, N, -1).transpose(0, 2, 1).astype(jnp.float32)
    if Npad != N:
        g = jnp.pad(g, ((0, 0), (0, 0), (0, Npad - N)))
    # container must match the primal's (build_corr_pyramid_flat returns a
    # list)
    dpyr = _pyr_levels_bwd(c, g,
                           [(s, p.dtype) for s, p in zip(shapes, protos)],
                           radius, block_q, interpret)
    return dpyr, jnp.zeros_like(coords)


pallas_pyramid_lookup.defvjp(_pyr_fwd, _pyr_bwd)


def pallas_pyramid_lookup_quantized(pyramid, coords, radius: int = 4,
                                    block_q: int = 128, interpret=None,
                                    out_dtype=jnp.float32):
    """Fused window sampling of a QUANTIZED materialized pyramid.

    ``pyramid``: list of :class:`raft_tpu.ops.corr.QuantizedLevel` in
    query-minor layout (``values (B, Hl, Wl, Npad)`` int8/fp8 +
    ``scale (B, 1, 1, 1)`` fp32, from
    :func:`raft_tpu.ops.corr.build_corr_pyramid_flat` with a quantized
    ``out_dtype``).  The kernel is the SAME one the fp32/bf16 path runs
    — the per-tile ``astype(jnp.float32)`` that already rides the VMEM
    load converts the codes, taps accumulate fp32 in VMEM — and because
    sampling is linear in the stored values the dequant is one
    per-level multiply on the (small) tap output, fused by XLA into the
    kernel epilogue.  An fp8 pyramid is a dtype swap upstream, not a
    different lookup.

    No ``custom_vjp``: the quantize boundary is stop_gradient'd
    upstream (codes are integers — no tangent space) and ``coords`` is
    detached per iteration by the refinement step, so autodiff treats
    the whole lookup as primal-only — the reference's unwired
    alt_cuda_corr backward, made explicit.  HBM cost of the resident
    pyramid drops 4x vs fp32 (2x vs bf16), plus the halved lookup read
    traffic.

    Returns ``(B, H1, W1, L * (2r+1)^2)`` ``out_dtype`` features.
    """
    if interpret is None:
        interpret = _auto_interpret()
    values = [lv.values for lv in pyramid]
    scales = [lv.scale for lv in pyramid]
    B, H1, W1, _ = coords.shape
    N = H1 * W1
    Npad = values[0].shape[3]
    if Npad % block_q:
        raise ValueError(
            f"pyramid query dim {Npad} is not a multiple of block_q "
            f"{block_q}; build the pyramid with "
            f"build_corr_pyramid_flat(..., pad_q={block_q})")
    k = 2 * radius + 1
    L = len(values)
    c = _pad_coords_oor(
        jax.lax.stop_gradient(coords).reshape(B, N, 2).astype(jnp.float32),
        Npad).transpose(0, 2, 1)
    # Accumulate + emit fp32 from the kernel; the per-level dequant
    # multiply below needs full precision before the consumer cast.
    out = _pyr_levels_fwd(values, c, radius, block_q, interpret,
                          jnp.float32)                 # (B, L*k*k, Npad)
    scale = jnp.concatenate(
        [s.reshape(B, 1) for s in scales], axis=1)     # (B, L)
    out = out.reshape(B, L, k * k, Npad) * scale[:, :, None, None]
    out = out.reshape(B, L * k * k, Npad)[:, :, :N]
    out = out.reshape(B, L * k * k, H1, W1).transpose(0, 2, 3, 1)
    return out.astype(out_dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def pallas_corr_lookup(fmap1, fmap2_pyramid, coords, radius: int = 4,
                       block_q: int = 128, interpret=None):
    """Fused on-demand pyramid correlation lookup.

    Args:
      fmap1: ``(B, H1, W1, C)`` query features.
      fmap2_pyramid: sequence of pooled target features
        ``(B, Hl, Wl, C)`` (from :func:`raft_tpu.ops.corr.pool_fmap_pyramid`).
      coords: ``(B, H1, W1, 2)`` level-0 centroids, last axis ``(x, y)``.
      radius: window radius r.
      block_q: query pixels per kernel instance (MXU-aligned).
      interpret: force pallas interpreter (default: auto — on for non-TPU
        backends so tests run on CPU).

    Returns:
      ``(B, H1, W1, L * (2r+1)^2)`` fp32 lookup features.
    """
    out, _ = _corr_fwd(fmap1, fmap2_pyramid, coords, radius, block_q,
                       interpret)
    return out


def _odm_levels(fmap2_pyramid, k):
    nonempty = [(lvl, f2) for lvl, f2 in enumerate(fmap2_pyramid)
                if f2.shape[1] > 0 and f2.shape[2] > 0]
    levels = [(lvl, lvl * k * k, f2.shape[1], f2.shape[2])
              for lvl, f2 in nonempty]
    return nonempty, levels


def _odm_f2_dtype(nonempty, block_q):
    """fp32 f2 blocks whenever they fit the VMEM budget; bf16 beyond.

    At beyond-HBM shapes (the path's whole purpose — e.g. 1440x2560,
    where fp32 f2 + correlation scratch is ~118 MB against the 100 MB
    budget) bf16 f2 (fp32 accumulation via preferred_element_type)
    halves the resident footprint.  The threshold models the actual
    per-instance residency — f2 levels + per-level scratch + the query
    blocks — against the declared 100 MB limit with headroom for
    double-buffered block DMA, so fp32 is kept as long as it genuinely
    fits (1080p-class included)."""
    if not nonempty:
        return jnp.float32
    C = nonempty[0][1].shape[-1]
    rows = sum(f2.shape[1] * f2.shape[2] for _, f2 in nonempty)
    f2_bytes = rows * C * 4
    scratch_bytes = rows * block_q * 4
    blocks_bytes = 4 * block_q * (2 * C + 2 + 81 * len(nonempty)) * 2
    if f2_bytes + scratch_bytes + blocks_bytes > 88 * 1024 * 1024:
        return jnp.bfloat16
    return jnp.float32


def _corr_fwd(fmap1, fmap2_pyramid, coords, radius, block_q, interpret):
    if interpret is None:
        interpret = _auto_interpret()
    B, H1, W1, C = fmap1.shape
    N = H1 * W1
    k = 2 * radius + 1
    L = len(fmap2_pyramid)
    f1 = fmap1.reshape(B, N, C).astype(jnp.float32)
    c = coords.reshape(B, N, 2).astype(jnp.float32)
    f1p, cp, _ = _pad_queries(f1, c, block_q)
    Npad = f1p.shape[1]

    nonempty, levels = _odm_levels(fmap2_pyramid, k)
    f2dt = _odm_f2_dtype(nonempty, block_q)
    kern = functools.partial(_odm_fwd_kernel, levels=levels, k=k,
                             kk_total=L * k * k,
                             inv_scale=1.0 / float(C) ** 0.5)
    in_specs = [
        pl.BlockSpec((1, f2.shape[1], f2.shape[2], C),
                     lambda b, i: (b, 0, 0, 0), memory_space=pltpu.VMEM)
        for _, f2 in nonempty
    ] + [
        pl.BlockSpec((1, block_q, C), lambda b, i: (b, i, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, 2, block_q), lambda b, i: (b, 0, i),
                     memory_space=pltpu.VMEM),
    ]
    out = tpu_pallas_call(
        kern,
        grid=(B, Npad // block_q),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, L * k * k, block_q),
                               lambda b, i: (b, 0, i),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((B, L * k * k, Npad), jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((f2.shape[1] * f2.shape[2], block_q), jnp.float32)
            for _, f2 in nonempty
        ],
        interpret=interpret,
    )(*[f2.astype(f2dt) for _, f2 in nonempty], f1p,
      cp.transpose(0, 2, 1))
    out = out[:, :, :N].reshape(B, L * k * k, H1, W1).transpose(0, 2, 3, 1)
    return out, (fmap1, tuple(fmap2_pyramid), coords)


def _corr_bwd(radius, block_q, interpret, residuals, g):
    fmap1, fmap2_pyramid, coords = residuals
    if interpret is None:
        interpret = _auto_interpret()
    B, H1, W1, C = fmap1.shape
    N = H1 * W1
    k = 2 * radius + 1
    L = len(fmap2_pyramid)
    inv_scale = 1.0 / float(C) ** 0.5
    f1 = fmap1.reshape(B, N, C).astype(jnp.float32)
    c = coords.reshape(B, N, 2).astype(jnp.float32)
    g_base = g.reshape(B, N, -1).transpose(0, 2, 1).astype(jnp.float32)

    nonempty, _ = _odm_levels(fmap2_pyramid, k)
    blocked, fused = _partition_bwd_levels(nonempty, block_q, k)

    df1_acc = jnp.zeros((B, N, C), jnp.float32)
    df2_by_level = {}

    if fused:
        f1p, cp, _ = _pad_queries(f1, c, block_q)
        Npad = f1p.shape[1]
        gp = g_base
        if Npad != N:
            gp = jnp.pad(gp, ((0, 0), (0, 0), (0, Npad - N)))
        levels = [(lvl, lvl * k * k, f2.shape[1], f2.shape[2])
                  for lvl, f2 in fused]
        f2dt = _odm_f2_dtype(fused, block_q)
        kern = functools.partial(_odm_bwd_kernel, levels=levels, k=k,
                                 inv_scale=inv_scale)
        in_specs = [
            pl.BlockSpec((1, f2.shape[1], f2.shape[2], C),
                         lambda b, i: (b, 0, 0, 0),
                         memory_space=pltpu.VMEM)
            for _, f2 in fused
        ] + [
            pl.BlockSpec((1, block_q, C), lambda b, i: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 2, block_q), lambda b, i: (b, 0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, L * k * k, block_q), lambda b, i: (b, 0, i),
                         memory_space=pltpu.VMEM),
        ]
        out_specs = (pl.BlockSpec((1, block_q, C), lambda b, i: (b, i, 0),
                                  memory_space=pltpu.VMEM),) + tuple(
            pl.BlockSpec((1, f2.shape[1], f2.shape[2], C),
                         lambda b, i: (b, 0, 0, 0),
                         memory_space=pltpu.VMEM)
            for _, f2 in fused)
        out_shape = (jax.ShapeDtypeStruct((B, Npad, C),
                                          jnp.float32),) + tuple(
            jax.ShapeDtypeStruct((B, f2.shape[1], f2.shape[2], C),
                                 jnp.float32)
            for _, f2 in fused)
        outs = tpu_pallas_call(
            kern,
            grid=(B, Npad // block_q),
            in_specs=in_specs,
            out_specs=out_specs,
            out_shape=out_shape,
            scratch_shapes=[
                pltpu.VMEM((f2.shape[1] * f2.shape[2], block_q),
                           jnp.float32)
                for _, f2 in fused
            ],
            interpret=interpret,
        )(*[f2.astype(f2dt) for _, f2 in fused], f1p,
          cp.transpose(0, 2, 1), gp)
        df1_acc = df1_acc + outs[0][:, :N]
        for (lvl, _), out in zip(fused, outs[1:]):
            df2_by_level[lvl] = out

    if blocked:
        # Captured at TRACE time: this read happens inside the custom-vjp
        # backward while jit traces it, and the jit cache keys on shapes/
        # dtypes only — changing RAFT_ODM_BWD_BLOCK_Q later in the same
        # process silently returns the old program (an in-process sweep
        # would record identical timings for different nominal values).
        # Sweep with a fresh process per value (scripts/tpu_backlog_r05.sh
        # does), or plumb it through RAFTConfig like lookup_block_q.
        bq2 = int(os.environ.get("RAFT_ODM_BWD_BLOCK_Q", _BWD_BLOCK_Q))
        f1p2, cp2, _ = _pad_queries(f1, c, bq2)
        Npad2 = f1p2.shape[1]
        gp2 = g_base
        if Npad2 != N:
            gp2 = jnp.pad(gp2, ((0, 0), (0, 0), (0, Npad2 - N)))
        cpt2 = cp2.transpose(0, 2, 1)
        # Stream f2 in the dtype the FUSED path would store for the whole
        # pyramid (bf16 at beyond-HBM shapes, fp32 at small ones) — the
        # df1 kernel re-reads the level once per query block, so the
        # dtype is the dominant DMA knob.
        f2dt_blocked = _odm_f2_dtype(nonempty, block_q)
        for lvl, f2 in blocked:
            df1_l, df2_l = _odm_bwd_blocked_level(
                lvl, f2, f1p2, cpt2, gp2, k, inv_scale, bq2, interpret,
                f2_dtype=f2dt_blocked)
            df1_acc = df1_acc + df1_l[:, :N]
            df2_by_level[lvl] = df2_l

    df1 = df1_acc.reshape(fmap1.shape).astype(fmap1.dtype)
    df2s = []
    for lvl, f2 in enumerate(fmap2_pyramid):
        if lvl in df2_by_level:
            df2s.append(df2_by_level[lvl].astype(f2.dtype))
        else:
            df2s.append(jnp.zeros_like(f2))
    # coords gradient is structurally zero (reference detaches coords each
    # iteration, raft.py:123; CUDA kernel never fills coords_grad).
    return df1, tuple(df2s), jnp.zeros_like(coords)


pallas_corr_lookup.defvjp(_corr_fwd, _corr_bwd)


# ---------------------------------------------------------------------------
# Fused lookup -> motion-encoder convc1 (fused_lookup_encoder)
#
# The pyramid lookup's ONLY consumer is the motion encoder's first conv
# (models/update.py convc1) — a 1x1 conv over the (2r+1)^2*levels tap
# channels.  Unfused, the (B, H/8, W/8, 324) tap tensor round-trips HBM
# between the lookup kernel and the conv.  Here the tap block stays in
# the VMEM scratch the lookup already accumulates into and feeds one MXU
# contraction (taps^T @ W) + bias + relu in the same kernel instance:
# the tap tensor never materializes.
#
# Quantized pyramids ride for free: sampling is linear in the stored
# codes and the conv is linear in the taps, so the per-(batch, level)
# dequant scale FOLDS INTO THE CONV WEIGHTS (w_l <- scale_bl * w_l) —
# the kernel contracts raw-code taps against pre-scaled weights, fp32
# accumulation end to end, and dequant-on-tap semantics are preserved
# exactly.  Backward is a recomputing custom_vjp: relu-masked cotangent
# -> dW/db by re-running the (unfused) lookup, pyramid/coords
# cotangents via the unfused lookup's own vjp (real dcorr for fp32/bf16
# pyramids, structural zeros for quantized ones — the same stop-gradient
# boundary, so fnet still gets zero grad through a quantized volume).
# ---------------------------------------------------------------------------


def _pyr_encode_kernel(*refs, levels, k, kk_pad):
    """Fused taps -> 1x1 conv (+bias+relu) kernel body.

    refs = [corr_0..corr_{n-1}, c, w, bias, out, taps, acc_0..acc_{n-1}];
    ``w`` is (1, kk_pad, Fpad) fp32 with any dequant scale pre-folded,
    ``taps`` a (1, kk_pad, BQ) fp32 VMEM scratch standing in for the
    unfused kernel's HBM tap output.  The mat-mul runs once per grid
    instance, OUTSIDE the row-tile loops (in-loop mat-muls regress to
    scalar code — see the Mosaic lessons at the top of this file).
    """
    nl = len(levels)
    c_ref = refs[nl]
    w_ref = refs[nl + 1]
    b_ref = refs[nl + 2]
    out_ref = refs[nl + 3]
    taps_ref = refs[nl + 4]
    acc_refs = refs[nl + 5:]
    bq = c_ref.shape[2]
    covered = 0
    for (lvl, off, hl, wl), corr_ref, acc_ref in zip(levels, refs[:nl],
                                                     acc_refs):
        _pyr_fwd_level_body(corr_ref, c_ref, taps_ref, acc_ref, lvl, off,
                            hl, wl, k)
        covered += k * k
    if covered < kk_pad:  # empty trailing levels + sublane-pad rows
        taps_ref[0, covered:, :] = jnp.zeros((kk_pad - covered, bq),
                                             taps_ref.dtype)
    taps = taps_ref[0]                                 # (kk_pad, BQ)
    out = jax.lax.dot_general(
        taps, w_ref[0], (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)            # (BQ, Fpad)
    out = jnp.maximum(out + b_ref[...], 0.0)
    out_ref[0, :, :] = out.astype(out_ref.dtype)


def _pyr_levels_fwd_encode(values, coords_p, w_scaled, bias, radius,
                           block_q, interpret):
    """One pallas_call: lookup + convc1 -> (B, Npad, Fpad) fp32."""
    B = values[0].shape[0]
    Npad = values[0].shape[3]
    k = 2 * radius + 1
    nonempty, levels = _odm_levels(values, k)
    kk_pad, fpad = w_scaled.shape[1], w_scaled.shape[2]
    kern = functools.partial(_pyr_encode_kernel, levels=levels, k=k,
                             kk_pad=kk_pad)
    in_specs = [
        pl.BlockSpec((1, c.shape[1], c.shape[2], block_q),
                     lambda b, i: (b, 0, 0, i),
                     memory_space=pltpu.VMEM)
        for _, c in nonempty
    ] + [
        pl.BlockSpec((1, 2, block_q), lambda b, i: (b, 0, i),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, kk_pad, fpad), lambda b, i: (b, 0, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, fpad), lambda b, i: (0, 0),
                     memory_space=pltpu.VMEM),
    ]
    return tpu_pallas_call(
        kern,
        grid=(B, Npad // block_q),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, block_q, fpad),
                               lambda b, i: (b, i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((B, Npad, fpad), jnp.float32),
        scratch_shapes=[pltpu.VMEM((1, kk_pad, block_q), jnp.float32)] + [
            pltpu.VMEM((k * c.shape[2], block_q), jnp.float32)
            for _, c in nonempty
        ],
        interpret=interpret,
    )(*[c for _, c in nonempty], coords_p, w_scaled, bias)


def _is_quantized_pyramid(pyramid) -> bool:
    # Duck-typed (QuantizedLevel carries .values/.scale) so this module
    # needs no import from ops.corr.
    return hasattr(pyramid[0], "values")


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def pallas_pyramid_lookup_encode(pyramid, coords, weight, bias,
                                 radius: int = 4, block_q: int = 128,
                                 interpret=None, out_dtype=jnp.float32):
    """Fused pyramid lookup + motion-encoder convc1 (+bias+relu).

    Equivalent to::

        corr = pallas_pyramid_lookup[_quantized](pyramid, coords, ...)
        out  = relu(corr @ weight + bias)       # the 1x1 convc1

    but the ``(B, H1, W1, L*(2r+1)^2)`` tap tensor never reaches HBM:
    taps accumulate in the lookup's VMEM scratch and feed the conv
    contraction in the same kernel instance (fp32 accumulation both
    stages).  Accepts plain (fp32/bf16) OR quantized
    (:class:`~raft_tpu.ops.corr.QuantizedLevel`) pyramids — for the
    latter the per-(batch, level) dequant scale is folded into
    ``weight`` before the kernel, preserving dequant-on-tap semantics
    exactly.

    Args:
      pyramid: query-minor levels from ``build_corr_pyramid_flat``
        (arrays or QuantizedLevel; Npad a multiple of ``block_q``).
      coords: ``(B, H1, W1, 2)`` level-0 centroids (detached inside —
        coords cotangent is structurally zero, matching the unfused
        refinement-step contract).
      weight: ``(L*(2r+1)^2, F)`` convc1 kernel (the HWIO ``(1,1,KK,F)``
        conv param reshaped).
      bias: ``(F,)`` convc1 bias.

    Returns ``(B, H1, W1, F)`` ``out_dtype`` activations.

    Gradients: ``weight``/``bias`` always; pyramid cotangents are real
    for fp32/bf16 storage (delegated to the unfused lookup's vjp) and
    structural zeros for quantized storage (the stop-gradient boundary
    — fnet gets zero grad through a quantized volume, unchanged).
    """
    out, _ = _pyr_enc_fwd(pyramid, coords, weight, bias, radius, block_q,
                          interpret, out_dtype)
    return out


def _pyr_enc_fwd(pyramid, coords, weight, bias, radius, block_q,
                 interpret, out_dtype):
    if interpret is None:
        interpret = _auto_interpret()
    quantized = _is_quantized_pyramid(pyramid)
    values = [lv.values if quantized else lv for lv in pyramid]
    B, H1, W1, _ = coords.shape
    N = H1 * W1
    Npad = values[0].shape[3]
    if Npad % block_q:
        raise ValueError(
            f"pyramid query dim {Npad} is not a multiple of block_q "
            f"{block_q}; build the pyramid with "
            f"build_corr_pyramid_flat(..., pad_q={block_q})")
    k = 2 * radius + 1
    L = len(values)
    kk = L * k * k
    if weight.shape != (kk, weight.shape[1]) or weight.shape[0] != kk:
        raise ValueError(
            f"weight shape {weight.shape} does not match the tap count "
            f"levels*(2r+1)^2 = {kk}")
    F = weight.shape[1]
    kk_pad = -(-kk // 8) * 8          # sublane-tile align the contraction
    fpad = -(-F // 128) * 128         # lane-tile align the conv features
    c = _pad_coords_oor(
        jax.lax.stop_gradient(coords).reshape(B, N, 2).astype(jnp.float32),
        Npad).transpose(0, 2, 1)
    w32 = weight.astype(jnp.float32)
    if quantized:
        scale = jnp.concatenate(
            [lv.scale.reshape(B, 1) for lv in pyramid], axis=1)  # (B, L)
        wb = w32.reshape(L, k * k, F)[None] * scale[:, :, None, None]
        wb = wb.reshape(B, kk, F)
    else:
        wb = jnp.broadcast_to(w32[None], (B, kk, F))
    wb = jnp.pad(wb, ((0, 0), (0, kk_pad - kk), (0, fpad - F)))
    b2 = jnp.pad(bias.astype(jnp.float32).reshape(1, F),
                 ((0, 0), (0, fpad - F)))
    out = _pyr_levels_fwd_encode(values, c, wb, b2, radius, block_q,
                                 interpret)
    out = out[:, :N, :F].reshape(B, H1, W1, F).astype(out_dtype)
    return out, (pyramid, coords, weight, bias, out)


def _pyr_enc_bwd(radius, block_q, interpret, out_dtype, residuals, g):
    pyramid, coords, weight, bias, out = residuals
    if interpret is None:
        interpret = _auto_interpret()
    quantized = _is_quantized_pyramid(pyramid)
    # relu mask from the saved activations; fp32 math below.
    gm = (g * (out > 0)).astype(jnp.float32)           # (B, H1, W1, F)
    if quantized:
        # Recompute the DEQUANTIZED taps (dW is w.r.t. the scaled
        # contraction the forward ran); codes/scales get structural
        # zeros — int codes have no tangent space (float0), and the
        # scale sits behind the same stop-gradient as the codes.
        corr = pallas_pyramid_lookup_quantized(
            pyramid, coords, radius, block_q, interpret, jnp.float32)

        def _zero_ct(x):
            if jnp.issubdtype(x.dtype, jnp.inexact):
                return jnp.zeros_like(x)
            import numpy as np

            return np.zeros(x.shape, jax.dtypes.float0)

        dpyr = jax.tree_util.tree_map(_zero_ct, pyramid)
        dcoords = jnp.zeros_like(coords)
    else:
        # Delegate to the unfused lookup's own vjp: identical recompute
        # + transpose kernels, so the fused path inherits the exact
        # unfused gradient semantics (real per-level dcorr, zero
        # dcoords).
        def lookup(p, c):
            return pallas_pyramid_lookup(p, c, radius, block_q,
                                         interpret, jnp.float32)

        corr, pullback = jax.vjp(lookup, pyramid, coords)
        g_corr = jnp.einsum("bhwf,kf->bhwk", gm,
                            weight.astype(jnp.float32))
        dpyr, dcoords = pullback(g_corr)
    dw = jnp.einsum("bhwk,bhwf->kf", corr.astype(jnp.float32), gm)
    db = jnp.sum(gm, axis=(0, 1, 2))
    return dpyr, dcoords, dw.astype(weight.dtype), db.astype(bias.dtype)


pallas_pyramid_lookup_encode.defvjp(_pyr_enc_fwd, _pyr_enc_bwd)
