"""Input padding to multiples of 8 (reference ``core/utils/utils.py:7-24``).

The model downsamples by 8, so H and W must be divisible by 8.  'sintel'
mode centers the height padding; every other mode puts all height padding at
the bottom.  Width padding is always centered.  Padding is edge-replicate.

``target=(H, W)`` pads up to a fixed bucket shape instead of the next
multiple of 8 — the batched-evaluation path pads every KITTI resolution to
one common shape so the jitted forward compiles once (the placement policy
of the mode is preserved, and edge-replicate rows are identical however
many there are).

Bucket policy lives here too (:func:`ceil_to_multiple`,
:func:`bucket_hw`): both the offline validators
(``raft_tpu/evaluate.py``) and the serving engine
(``raft_tpu/serve/engine.py``) round request shapes to /8-aligned compile
buckets, and keeping the rounding in one place means eval and serve
cannot drift in which shapes they consider "the same program".
"""

from __future__ import annotations

from typing import Iterable, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np


def ceil_to_multiple(x: int, multiple: int = 8) -> int:
    """Smallest multiple of ``multiple`` that is >= ``x``."""
    return -(-int(x) // multiple) * multiple


def bucket_hw(ht: int, wd: int, multiple: int = 8,
              ladder: Optional[Sequence[Tuple[int, int]]] = None,
              ) -> Tuple[int, int]:
    """The /``multiple``-aligned compile bucket covering an ``(ht, wd)``
    image.

    Without a ``ladder`` this is the exact next-multiple round-up (one
    bucket per distinct aligned shape — what the validators use, where
    the shape population is known up front).  With a ``ladder`` of
    ``(H, W)`` bucket shapes, the smallest ladder entry that covers the
    image wins — a serving engine with unknown traffic uses a coarse
    ladder so nearby resolutions coalesce into one micro-batch instead
    of fragmenting into per-shape programs.  Images larger than every
    ladder entry fall back to the exact round-up (served correctly, at
    the cost of a dedicated compile)."""
    bh, bw = ceil_to_multiple(ht, multiple), ceil_to_multiple(wd, multiple)
    if ladder:
        fits = [(H, W) for (H, W) in ladder if H >= bh and W >= bw]
        if fits:
            return min(fits, key=lambda t: (t[0] * t[1], t))
    return bh, bw


def max_bucket_hw(shapes: Iterable[Tuple[int, int]],
                  multiple: int = 8) -> Tuple[int, int]:
    """One bucket covering every ``(ht, wd)`` in ``shapes`` (the
    validators' pad-everything-to-the-max policy, so a whole mixed-
    resolution split costs ONE compile)."""
    hs, ws = zip(*shapes)
    return ceil_to_multiple(max(hs), multiple), \
        ceil_to_multiple(max(ws), multiple)


class InputPadder:
    """Pads NHWC images so H, W are divisible by 8 (or match ``target``);
    unpads flow back."""

    def __init__(self, dims, mode: str = "sintel",
                 target: Optional[Tuple[int, int]] = None):
        self.ht, self.wd = dims[-3:-1] if len(dims) >= 3 else dims
        if target is None:
            pad_ht = ceil_to_multiple(self.ht) - self.ht
            pad_wd = ceil_to_multiple(self.wd) - self.wd
        else:
            pad_ht, pad_wd = target[0] - self.ht, target[1] - self.wd
            assert pad_ht >= 0 and pad_wd >= 0, (
                f"target {target} smaller than image "
                f"({self.ht}, {self.wd})")
        if mode == "sintel":
            self._pad = [pad_wd // 2, pad_wd - pad_wd // 2,
                         pad_ht // 2, pad_ht - pad_ht // 2]
        else:
            self._pad = [pad_wd // 2, pad_wd - pad_wd // 2, 0, pad_ht]

    def pad(self, *inputs):
        l, r, t, b = self._pad
        out = [jnp.pad(x, ((0, 0), (t, b), (l, r), (0, 0)), mode="edge")
               for x in inputs]
        return out if len(out) > 1 else out[0]

    def pad_np(self, *inputs):
        """Host-side variant: ``(H, W, C)`` numpy arrays, for assembling
        batched eval inputs without a device round-trip per image."""
        l, r, t, b = self._pad
        out = [np.pad(x, ((t, b), (l, r), (0, 0)), mode="edge")
               for x in inputs]
        return out if len(out) > 1 else out[0]

    def unpad(self, x):
        ht, wd = x.shape[-3:-1]
        l, r, t, b = self._pad
        return x[..., t:ht - b, l:wd - r, :]
