"""Input padding to multiples of 8 (reference ``core/utils/utils.py:7-24``).

The model downsamples by 8, so H and W must be divisible by 8.  'sintel'
mode centers the height padding; every other mode puts all height padding at
the bottom.  Width padding is always centered.  Padding is edge-replicate.

``target=(H, W)`` pads up to a fixed bucket shape instead of the next
multiple of 8 — the batched-evaluation path pads every KITTI resolution to
one common shape so the jitted forward compiles once (the placement policy
of the mode is preserved, and edge-replicate rows are identical however
many there are).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp
import numpy as np


class InputPadder:
    """Pads NHWC images so H, W are divisible by 8 (or match ``target``);
    unpads flow back."""

    def __init__(self, dims, mode: str = "sintel",
                 target: Optional[Tuple[int, int]] = None):
        self.ht, self.wd = dims[-3:-1] if len(dims) >= 3 else dims
        if target is None:
            pad_ht = (((self.ht // 8) + 1) * 8 - self.ht) % 8
            pad_wd = (((self.wd // 8) + 1) * 8 - self.wd) % 8
        else:
            pad_ht, pad_wd = target[0] - self.ht, target[1] - self.wd
            assert pad_ht >= 0 and pad_wd >= 0, (
                f"target {target} smaller than image "
                f"({self.ht}, {self.wd})")
        if mode == "sintel":
            self._pad = [pad_wd // 2, pad_wd - pad_wd // 2,
                         pad_ht // 2, pad_ht - pad_ht // 2]
        else:
            self._pad = [pad_wd // 2, pad_wd - pad_wd // 2, 0, pad_ht]

    def pad(self, *inputs):
        l, r, t, b = self._pad
        out = [jnp.pad(x, ((0, 0), (t, b), (l, r), (0, 0)), mode="edge")
               for x in inputs]
        return out if len(out) > 1 else out[0]

    def pad_np(self, *inputs):
        """Host-side variant: ``(H, W, C)`` numpy arrays, for assembling
        batched eval inputs without a device round-trip per image."""
        l, r, t, b = self._pad
        out = [np.pad(x, ((t, b), (l, r), (0, 0)), mode="edge")
               for x in inputs]
        return out if len(out) > 1 else out[0]

    def unpad(self, x):
        ht, wd = x.shape[-3:-1]
        l, r, t, b = self._pad
        return x[..., t:ht - b, l:wd - r, :]
