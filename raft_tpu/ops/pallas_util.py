"""Shared Pallas dispatch helpers.

One home for the two decisions every kernel in this repo used to make
for itself (copy-pasted between ``ops/pallas_corr.py`` and
``ops/pallas_upsample.py`` until PR 13):

- **interpret-mode selection** (:func:`auto_interpret`): Pallas kernels
  run natively only on TPU; on the CPU test backend they run in the
  interpreter, and on other accelerators they warn.
- **compiler-params construction** (:func:`compiler_params` /
  :func:`tpu_pallas_call`): the ``TPUCompilerParams`` →
  ``CompilerParams`` rename shim and the repo-wide 100 MB
  ``vmem_limit_bytes`` default live here, so a jax upgrade or a VMEM
  budget change is one edit, not four.
"""

from __future__ import annotations

import warnings

import jax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax renamed TPUCompilerParams -> CompilerParams across 0.4.x/0.5.x;
# accept either so the kernels (and their interpret-mode tests) run on
# both sides of the rename.
_CompilerParams = getattr(pltpu, "CompilerParams", None) \
    or pltpu.TPUCompilerParams

# Every kernel in the repo declares the same VMEM budget: large enough
# for the beyond-HBM correlation levels, small enough that Mosaic still
# double-buffers block DMA (see pallas_corr.py "VMEM sizing").
_DEFAULT_VMEM_LIMIT_MB = 100

_warned_interpret = False


def auto_interpret() -> bool:
    """Interpret mode for every non-TPU backend.

    Meant for the CPU test backend; on an accelerator backend that is
    not a TPU (e.g. GPU) the interpreter would be pathologically slow,
    so warn once — callers there should prefer the XLA paths
    (``corr_impl='allpairs'``/``'chunked'``, ``convex_upsample_flat`` +
    ``sequence_loss``).
    """
    backend = jax.default_backend()
    if backend == "tpu":
        return False
    if backend != "cpu":
        global _warned_interpret
        if not _warned_interpret:
            _warned_interpret = True
            warnings.warn(
                f"Pallas kernels on backend {backend!r} run in the (very "
                "slow) Pallas interpreter; prefer the XLA implementations "
                "on this backend", stacklevel=3)
    return True


def compiler_params(vmem_limit_mb: int = _DEFAULT_VMEM_LIMIT_MB):
    """The repo-standard TPU compiler params (rename-shimmed)."""
    return _CompilerParams(vmem_limit_bytes=vmem_limit_mb * 1024 * 1024)


def tpu_pallas_call(kernel, *, interpret=None,
                    vmem_limit_mb: int = _DEFAULT_VMEM_LIMIT_MB, **kw):
    """``pl.pallas_call`` with the repo-wide dispatch conventions.

    ``interpret=None`` resolves via :func:`auto_interpret` (native on
    TPU, interpreter on the CPU test backend); an explicit bool is
    passed through untouched (callers that already resolved it — e.g.
    through ``RAFTConfig.pallas_offtpu`` — stay in charge).  All other
    keyword arguments are forwarded to ``pl.pallas_call``.
    """
    if interpret is None:
        interpret = auto_interpret()
    return pl.pallas_call(
        kernel,
        compiler_params=compiler_params(vmem_limit_mb),
        interpret=interpret,
        **kw)
