"""Shared Pallas dispatch helpers."""

from __future__ import annotations

import warnings

import jax

_warned_interpret = False


def auto_interpret() -> bool:
    """Interpret mode for every non-TPU backend.

    Meant for the CPU test backend; on an accelerator backend that is
    not a TPU (e.g. GPU) the interpreter would be pathologically slow,
    so warn once — callers there should prefer the XLA paths
    (``corr_impl='allpairs'``/``'chunked'``, ``convex_upsample_flat`` +
    ``sequence_loss``).
    """
    backend = jax.default_backend()
    if backend == "tpu":
        return False
    if backend != "cpu":
        global _warned_interpret
        if not _warned_interpret:
            _warned_interpret = True
            warnings.warn(
                f"Pallas kernels on backend {backend!r} run in the (very "
                "slow) Pallas interpreter; prefer the XLA implementations "
                "on this backend", stacklevel=3)
    return True
