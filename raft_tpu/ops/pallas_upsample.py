"""Fused convex-upsample + sequence-loss Pallas TPU kernel.

The training-path upsample stage (reference ``core/raft.py:72-83`` +
``train.py:47-60``, fused here the way ``UpsampleLossStep`` fuses them in
XLA) is HBM-bound, not FLOP-bound: profiled on v5e at chairs batch 16,
the XLA chain spends ~10 ms/step writing its softmax intermediates to HBM
for the backward (five ``(6, 32, 46, 62, 64)`` bf16 saves per step with
``remat_upsample=0``) plus ~10 ms/step of scan-stacked softmax/FMA
kernels — against a ~2 ms traffic floor for the tensors it actually has
to touch (the 576-channel mask in, five scalars out).

This kernel computes the whole chain per batch element in VMEM:

    softmax over the 9 taps (64-subpixel groups, flat (c, p, q) layout
    of ``convex_upsample_flat``) -> convex combination of the 9 shifted
    flow windows -> fp32 compare vs space-to-depth ground truth ->
    masked L1 + EPE partial sums.

and the backward (``jax.custom_vjp``) RECOMPUTES the softmax in VMEM
from the saved inputs — no intermediate ever reaches HBM, removing both
the remat-off save traffic and the remat-on recompute kernels.

Numerics contract (same as the XLA path, tests/test_pallas_upsample.py):
- the ground-truth COMPARE runs fp32 (bf16-vs-bf16 compares dead-zone
  the L1 gradient, see ``convex_upsample_flat``); in-kernel arithmetic
  is fp32 throughout (inputs are read once, so bf16 compute would save
  no traffic — unlike the XLA chain where every intermediate round-trips
  HBM).
- loss sums accumulate fp32.
- EPE/1px/3px/5px sums are metrics: non-differentiable (the model wraps
  them in stop_gradient on the XLA path; here the backward simply
  ignores their cotangents).
- flow (the 1/8-res model output) and mask get gradients; ground truth
  and valid mask do not (they are data).

Inputs are pre-arranged by the wrapper:
- ``fb``  (gB, H+2, W+2, 128): flow * 8, ZERO-padded by 1 (matching
  ``convex_upsample_flat`` and the reference's F.unfold), each of x/y
  broadcast to 64 lanes (lane halves) — so every one of the 9 tap
  windows is a static 2-D slice with the subpixel lanes already in
  place (in-kernel lane broadcasts of a width-in-lanes tensor would be
  a relayout; Mosaic lesson from the correlation kernels: keep every
  operand's lanes where the math needs them).
- ``mask`` (gB, H, W, 576): raw mask-head logits, ``k*64 + p*8 + q``
  channel order.
- ``gt128`` (B, H, W, 128), ``vm64`` (B, H, W, 64): space-to-depth
  ground truth / valid mask, broadcast over the g folded iterations via
  the index map (grid step i reads block i % B).

Output: ``sums (gB, 8, 128)`` fp32 (TPU output blocks must tile to
(8, 128)); row 0 lanes 0..4 = [l1, epe, 1px, 3px, 5px] partial sums over
that batch element, everything else zero.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from raft_tpu.ops.pallas_util import auto_interpret, tpu_pallas_call


def _softmax_parts(m):
    """Per-tap-group softmax pieces from (H, W, 576) logits: list of 9
    fp32 (H, W, 64) exps and the (H, W, 64) denominator.  Group-wise max
    subtraction (matches ``convex_upsample_flat``: a global per-pixel
    max would underflow far-below-max groups to denom 0)."""
    taps = [m[:, :, k * 64:(k + 1) * 64].astype(jnp.float32)
            for k in range(9)]
    gmax = taps[0]
    for t in taps[1:]:
        gmax = jnp.maximum(gmax, t)
    es = [jnp.exp(t - gmax) for t in taps]
    denom = es[0]
    for e in es[1:]:
        denom = denom + e
    return es, denom


def _convex_out(fb_ref, es, inv, H, W):
    """fp32 (H, W, 64) outx, outy: softmax-weighted 9-tap combination of
    the pre-broadcast flow windows."""
    accx = jnp.zeros((H, W, 64), jnp.float32)
    accy = jnp.zeros((H, W, 64), jnp.float32)
    for k in range(9):
        di, dj = k // 3, k % 3
        fwin = fb_ref[0, di:di + H, dj:dj + W, :].astype(jnp.float32)
        accx = accx + es[k] * fwin[:, :, :64]
        accy = accy + es[k] * fwin[:, :, 64:]
    return accx * inv, accy * inv


def _total(x):
    """Full fp32 sum of (H, W, 64) -> (1, 1) via leading-dim reduce +
    two ones-dots (no 1-D intermediates: Mosaic implicit-dim lesson)."""
    W = x.shape[1]
    wc = jnp.sum(x, axis=0)                                  # (W, 64)
    row = jax.lax.dot_general(jnp.ones((1, W), jnp.float32), wc,
                              (((1,), (0,)), ((), ())),
                              preferred_element_type=jnp.float32)
    return jax.lax.dot_general(row, jnp.ones((64, 1), jnp.float32),
                               (((1,), (0,)), ((), ())),
                               preferred_element_type=jnp.float32)


def _upsample_loss_fwd_kernel(fb_ref, mask_ref, gt_ref, vm_ref, out_ref,
                              *, H, W):
    es, denom = _softmax_parts(mask_ref[0])
    inv = 1.0 / denom
    outx, outy = _convex_out(fb_ref, es, inv, H, W)
    gt = gt_ref[0].astype(jnp.float32)
    vm = vm_ref[0].astype(jnp.float32)
    dx = outx - gt[:, :, :64]
    dy = outy - gt[:, :, 64:]
    adx, ady = jnp.abs(dx), jnp.abs(dy)
    epe = jnp.sqrt(dx * dx + dy * dy)
    out_ref[...] = jnp.zeros_like(out_ref)
    out_ref[0, 0:1, 0:1] = _total(vm * (adx + ady))
    out_ref[0, 0:1, 1:2] = _total(vm * epe)
    out_ref[0, 0:1, 2:3] = _total(vm * (epe < 1.0).astype(jnp.float32))
    out_ref[0, 0:1, 3:4] = _total(vm * (epe < 3.0).astype(jnp.float32))
    out_ref[0, 0:1, 4:5] = _total(vm * (epe < 5.0).astype(jnp.float32))


def _upsample_loss_bwd_kernel(fb_ref, mask_ref, gt_ref, vm_ref, g_ref,
                              dmask_ref, dfb_ref, scratch_ref, *, H, W):
    """Recompute the softmax chain, then:
    dmask_k = w_k * (ghat_k - ghat_out),  ghat = gx*fx + gy*fy
    dfb accumulates w_k * (gx|gy) into the 9 shifted windows (overlap
    handled in a VMEM scratch, written once)."""
    es, denom = _softmax_parts(mask_ref[0])
    inv = 1.0 / denom
    outx, outy = _convex_out(fb_ref, es, inv, H, W)
    gt = gt_ref[0].astype(jnp.float32)
    vm = vm_ref[0].astype(jnp.float32)
    # Scalar load: a (1, 1) vector would broadcast in both sublanes AND
    # lanes when applied to (H, W, 64) operands, which Mosaic rejects
    # ("Broadcast in both sublanes and lanes"); a rank-0 scalar rides
    # the scalar registers instead.
    dl1 = g_ref[0, 0, 0]
    # d l1 / d out = vm * dabs(out - gt); metrics lanes are
    # non-differentiable by contract (ignored).  dabs uses jnp.abs's VJP
    # convention (+1 at exactly zero) rather than jnp.sign (0 at zero) so
    # the kernel's subgradient matches the XLA loss path bit-for-bit even
    # on exactly-zero residuals (reachable with integer synthetic flows).
    dabs_x = jnp.where(outx >= gt[:, :, :64], 1.0, -1.0)
    dabs_y = jnp.where(outy >= gt[:, :, 64:], 1.0, -1.0)
    gx = vm * dabs_x * dl1
    gy = vm * dabs_y * dl1
    gout = gx * outx + gy * outy
    scratch_ref[...] = jnp.zeros((H + 2, W + 2, 128), jnp.float32)
    for k in range(9):
        di, dj = k // 3, k % 3
        fwin = fb_ref[0, di:di + H, dj:dj + W, :].astype(jnp.float32)
        w_k = es[k] * inv
        ghat = gx * fwin[:, :, :64] + gy * fwin[:, :, 64:]
        dmask_ref[0, :, :, k * 64:(k + 1) * 64] = \
            (w_k * (ghat - gout)).astype(dmask_ref.dtype)
        scratch_ref[di:di + H, dj:dj + W, 0:64] = \
            scratch_ref[di:di + H, dj:dj + W, 0:64] + w_k * gx
        scratch_ref[di:di + H, dj:dj + W, 64:128] = \
            scratch_ref[di:di + H, dj:dj + W, 64:128] + w_k * gy
    dfb_ref[0] = scratch_ref[...].astype(dfb_ref.dtype)


def _specs(gB, B, H, W):
    lane = pl.BlockSpec((1, 8, 128), lambda i: (i, 0, 0),
                        memory_space=pltpu.VMEM)
    per_i = lambda shape: pl.BlockSpec(  # noqa: E731
        (1,) + shape, lambda i: (i, 0, 0, 0), memory_space=pltpu.VMEM)
    per_b = lambda shape: pl.BlockSpec(  # noqa: E731
        (1,) + shape, lambda i: (i % B, 0, 0, 0),
        memory_space=pltpu.VMEM)
    return {
        "fb": per_i((H + 2, W + 2, 128)),
        "mask": per_i((H, W, 576)),
        "gt": per_b((H, W, 128)),
        "vm": per_b((H, W, 64)),
        "sums": lane,
    }


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def _upsample_loss_core(fb, mask, gt128, vm64, interpret):
    out, _ = _core_fwd(fb, mask, gt128, vm64, interpret)
    return out


def _core_fwd(fb, mask, gt128, vm64, interpret):
    gB, Hp2, Wp2, _ = fb.shape
    H, W = Hp2 - 2, Wp2 - 2
    B = gt128.shape[0]
    s = _specs(gB, B, H, W)
    out = tpu_pallas_call(
        functools.partial(_upsample_loss_fwd_kernel, H=H, W=W),
        grid=(gB,),
        in_specs=[s["fb"], s["mask"], s["gt"], s["vm"]],
        out_specs=s["sums"],
        out_shape=jax.ShapeDtypeStruct((gB, 8, 128), jnp.float32),
        interpret=interpret,
    )(fb, mask, gt128, vm64)
    return out, (fb, mask, gt128, vm64)


def _core_bwd(interpret, residuals, g):
    fb, mask, gt128, vm64 = residuals
    gB, Hp2, Wp2, _ = fb.shape
    H, W = Hp2 - 2, Wp2 - 2
    B = gt128.shape[0]
    s = _specs(gB, B, H, W)
    dmask, dfb = tpu_pallas_call(
        functools.partial(_upsample_loss_bwd_kernel, H=H, W=W),
        grid=(gB,),
        in_specs=[s["fb"], s["mask"], s["gt"], s["vm"], s["sums"]],
        out_specs=[s["mask"], s["fb"]],
        out_shape=[
            jax.ShapeDtypeStruct(mask.shape, mask.dtype),
            jax.ShapeDtypeStruct(fb.shape, fb.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((H + 2, W + 2, 128), jnp.float32)],
        interpret=interpret,
    )(fb, mask, gt128, vm64, g.astype(jnp.float32))
    return dfb, dmask, jnp.zeros_like(gt128), jnp.zeros_like(vm64)


_upsample_loss_core.defvjp(_core_fwd, _core_bwd)


def _auto_interpret() -> bool:
    return auto_interpret()


def pallas_upsample_loss_sums(flow: jax.Array, mask: jax.Array,
                              gt128: jax.Array, vm64: jax.Array,
                              interpret=None) -> jax.Array:
    """Fused flat convex upsample + masked L1/EPE partial sums.

    Args:
      flow:  (gB, H, W, 2) 1/8-res flow (model output; differentiable).
      mask:  (gB, H, W, 576) mask-head logits (differentiable).
      gt128: (B, H, W, 128) space-to-depth fp32 ground truth; gB must be
        a multiple of B (iterations folded batch-major, i % B -> b).
      vm64:  (B, H, W, 64) space-to-depth valid mask.

    Returns:
      (gB, 5) fp32 [l1, epe, 1px, 3px, 5px] sums per batch element
      (sum over B outside for per-iteration values).  EPE/precision
      lanes are metrics: non-differentiable.
    """
    if interpret is None:
        interpret = _auto_interpret()
    gB, H, W, _ = flow.shape
    assert gB % gt128.shape[0] == 0, (gB, gt128.shape)
    f8 = jnp.pad(8.0 * flow.astype(jnp.float32),
                 ((0, 0), (1, 1), (1, 1), (0, 0)))
    fb = jnp.concatenate([
        jnp.broadcast_to(f8[..., 0:1], f8.shape[:3] + (64,)),
        jnp.broadcast_to(f8[..., 1:2], f8.shape[:3] + (64,)),
    ], axis=-1)
    sums = _upsample_loss_core(fb, mask, gt128.astype(jnp.float32),
                               vm64.astype(jnp.float32), interpret)
    return sums[:, 0, :5]
