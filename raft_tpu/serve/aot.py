"""AOT executable export/import: zero-compile replica warm-start.

The engine's compile cache is keyed ``(bucket_hw, lanes, program)``
with ``program`` in ``{"enc", "iter"}`` (the iteration-granular
serving split, ``serve/slots.py``), and each entry is an explicit
``jit.lower(...).compile()`` product (``jax.stages.Compiled``).  XLA
lets those be serialized (``jax.experimental.serialize_executable``),
and — crucially — the executable takes the *variables pytree as a
runtime argument*, so one exported artifact warm-starts a replica with
ANY weights of the same tree structure: a supervised restart after a
crash AND the warming engine of a rolling weight update both import
the same blobs and serve their first request with **zero JIT
compiles** (``CompileCounter``-asserted in ``tests/test_fleet.py``).

Artifact layout (one directory)::

    manifest.json                  # fingerprint + key index (below)
    trees.pkl                      # pickled in/out pytree TEMPLATES
    exe-<H>x<W>-b<B>-<prog>.bin    # one serialized executable per key

``trees.pkl`` holds each blob's input/output tree *structures*
rendered as plain int-leaf templates (``treedef.unflatten(range(n))``)
— plain dicts/tuples, no jax objects — because ``serialize()`` returns
treedefs that are not themselves portable.  Trees are stored PER BLOB
(format v2): the ``enc`` and ``iter`` programs take different pytrees,
and the corr-state structure inside the slot state can vary with the
corr impl/dtype.

Compatibility gate: an artifact is refused (``AOTImportError``) unless
its fingerprint — model config + variables tree structure/shapes/dtypes
+ iters — AND backend AND jax version match the importing engine.  A
stale artifact must fall back to lazy JIT compiles, never feed a
request through the wrong program.  The engine treats import failure as
a warm-start miss (``aot_import_error`` event), not a serve failure.

Blobs are pickles (that is the upstream wire format); treat artifact
directories with the same trust as checkpoint directories.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import pickle
import tempfile
from typing import Dict, Optional, Tuple

MANIFEST = "manifest.json"
TREES = "trees.pkl"
# v2: (bucket, lanes, program) keys + per-blob tree templates (the
# iteration-granular serving split).  v1 artifacts (whole-forward
# executables) are refused and the engine falls back to lazy compiles.
FORMAT_VERSION = 2


class AOTImportError(RuntimeError):
    """Artifact missing/corrupt/incompatible — the importer refuses it
    (the engine falls back to lazy JIT compiles)."""


def _blob_name(key: tuple) -> str:
    (h, w), bs, prog = key
    return f"exe-{h}x{w}-b{bs}-{prog}.bin"


def model_fingerprint(model_cfg, variables, iters: int) -> str:
    """Hash of everything that must match for an exported executable to
    be the RIGHT program: the model config, the variables pytree
    structure + per-leaf shape/dtype, and the iteration count baked
    into the traced call."""
    import jax

    leaves = jax.tree_util.tree_flatten_with_path(variables)[0]
    shapes = [(jax.tree_util.keystr(path), tuple(x.shape), str(x.dtype))
              for path, x in leaves]
    payload = json.dumps({
        "config": {k: repr(v) for k, v in sorted(
            dataclasses.asdict(model_cfg).items())},
        "shapes": shapes,
        # The full treedef, not just the leaves: an empty container
        # (e.g. a checkpoint layout adding ``batch_stats: {}``) changes
        # the executable's input pytree without changing any leaf, and
        # a structure-blind fingerprint would import an executable the
        # call site then cannot invoke.
        "treedef": str(jax.tree_util.tree_structure(variables)),
        "iters": int(iters),
    }, sort_keys=True)
    return hashlib.sha256(payload.encode()).hexdigest()[:16]


def _env_stamp() -> dict:
    import jax

    return {"jax": jax.__version__,
            "backend": jax.default_backend()}


def export_executables(executables: Dict[tuple, object], path: str, *,
                       fingerprint: str) -> dict:
    """Serialize ``{(bucket, lanes, program): Compiled}`` into
    directory ``path`` (atomic per file: tmp + rename, so a concurrent
    importer never sees a torn blob).  Returns the manifest written.
    Keys already exported with identical bytes are overwritten in
    place — export is idempotent and may be re-run as the compile
    cache grows."""
    from jax.experimental import serialize_executable as se

    if not executables:
        raise ValueError("nothing to export: empty executable cache "
                         "(warm the engine first)")
    os.makedirs(path, exist_ok=True)
    keys, trees = [], {}
    for key, exe in sorted(executables.items()):
        ser, in_tree, out_tree = se.serialize(exe)
        blob = _blob_name(key)
        trees[blob] = (
            in_tree.unflatten(list(range(in_tree.num_leaves))),
            out_tree.unflatten(list(range(out_tree.num_leaves))))
        _atomic_write(os.path.join(path, blob), ser)
        keys.append({"bucket": list(key[0]), "batch": int(key[1]),
                     "program": str(key[2]), "file": blob,
                     "sha256": hashlib.sha256(ser).hexdigest(),
                     "bytes": len(ser)})
    _atomic_write(os.path.join(path, TREES), pickle.dumps(trees))
    manifest = dict(_env_stamp(), format_version=FORMAT_VERSION,
                    fingerprint=fingerprint, keys=keys)
    _atomic_write(os.path.join(path, MANIFEST),
                  json.dumps(manifest, indent=1).encode())
    return manifest


def _atomic_write(path: str, data: bytes) -> None:
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path),
                               prefix=".aot-tmp-")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def read_manifest(path: str) -> dict:
    mpath = os.path.join(path, MANIFEST)
    try:
        with open(mpath) as f:
            manifest = json.load(f)
    except OSError as e:
        raise AOTImportError(f"no AOT manifest at {mpath}: {e}")
    except ValueError as e:
        raise AOTImportError(f"corrupt AOT manifest {mpath}: {e}")
    if manifest.get("format_version") != FORMAT_VERSION:
        raise AOTImportError(
            f"AOT artifact format {manifest.get('format_version')!r} "
            f"!= supported {FORMAT_VERSION}")
    return manifest


def import_executables(path: str, *, fingerprint: str,
                       keys: Optional[Tuple[tuple, ...]] = None
                       ) -> Dict[tuple, object]:
    """Load ``{(bucket, lanes, program): Compiled}`` from an artifact
    directory, gated on ``fingerprint`` + backend + jax version.
    ``keys`` restricts the import (default: everything in the
    manifest).  Raises :class:`AOTImportError` on any mismatch or
    corruption — partial results are never returned (an artifact
    either warm-starts the whole ladder or is refused)."""
    import jax
    from jax.experimental import serialize_executable as se

    manifest = read_manifest(path)
    env = _env_stamp()
    for field, want in (("fingerprint", fingerprint),
                        ("jax", env["jax"]),
                        ("backend", env["backend"])):
        got = manifest.get(field)
        if got != want:
            raise AOTImportError(
                f"AOT artifact {field} mismatch: artifact has {got!r}, "
                f"this engine needs {want!r} (stale export? re-run "
                "export on this build)")
    try:
        with open(os.path.join(path, TREES), "rb") as f:
            trees = pickle.load(f)
    except (OSError, pickle.UnpicklingError, ValueError, EOFError) as e:
        raise AOTImportError(f"corrupt AOT tree templates: {e}")
    if not isinstance(trees, dict):
        raise AOTImportError("AOT tree templates are not the per-blob "
                             "v2 layout (stale artifact?)")

    wanted = None if keys is None else {
        (tuple(b), int(bs), str(prog)) for (b, bs, prog) in keys}
    out: Dict[tuple, object] = {}
    for entry in manifest["keys"]:
        key = (tuple(entry["bucket"]), int(entry["batch"]),
               str(entry["program"]))
        if wanted is not None and key not in wanted:
            continue
        blob_path = os.path.join(path, entry["file"])
        try:
            with open(blob_path, "rb") as f:
                ser = f.read()
        except OSError as e:
            raise AOTImportError(f"missing AOT blob {blob_path}: {e}")
        if hashlib.sha256(ser).hexdigest() != entry["sha256"]:
            raise AOTImportError(
                f"AOT blob {entry['file']} checksum mismatch "
                "(torn write?)")
        templates = trees.get(entry["file"])
        if templates is None:
            raise AOTImportError(
                f"AOT blob {entry['file']} has no tree template")
        in_tree = jax.tree_util.tree_structure(templates[0])
        out_tree = jax.tree_util.tree_structure(templates[1])
        try:
            out[key] = se.deserialize_and_load(ser, in_tree, out_tree)
        except Exception as e:
            raise AOTImportError(
                f"AOT blob {entry['file']} failed to deserialize: "
                f"{type(e).__name__}: {e}")
    if not out:
        raise AOTImportError(
            f"AOT artifact at {path} holds none of the requested keys")
    return out
