"""Health-gated request router for a replica fleet (docs/SERVING.md).

The router is the fleet's front door: callers ``submit()`` here instead
of on an engine, and the router picks WHICH replica runs the request —
then stands behind it until the future resolves.  The contract that
matters is the last one: **an accepted request is never silently
dropped**.  Every accepted future terminates, either with a flow field
or with the error of its LAST attempt — a replica dying mid-batch costs
the request a failover hop, not an infinite wait.

Placement policy, in order:

1. **Health gate** — replicas that are not ready (crashed, stalled,
   stopping, still restarting) or whose circuit breaker is open get NO
   traffic.  The gate reads the engine's own ``health()`` signal, the
   same one ``GET /v1/healthz`` serves.
2. **Bucket affinity** — requests hash by their padded shape bucket
   (``crc32(bucket) % n``), so each bucket's compiled executables and
   micro-batching concentrate on one replica instead of smearing
   compile caches across the fleet.
3. **Least-loaded fallback** — when the affine replica is ineligible or
   saturated (pending ≥ 3/4 of ``max_queue``), the least-loaded
   eligible replica takes the request.

Failure handling:

- **Failover**: a request that fails with a *replica-indicting* error
  (:func:`is_failover_error` — replica-fatal chaos faults, an engine
  that stopped/crashed under the request, or a transient device error
  that out-lived the engine's own retry ladder) is re-dispatched on a
  sibling, **at most once per replica** (a tried-set, so a poison
  request that kills every replica fails after N attempts instead of
  cycling forever).  Deterministic request errors (bad shapes) are
  returned to the caller unchanged — re-running them elsewhere would
  only repeat the failure.
- **Hedging**: with ``hedge_timeout_s > 0``, a request still unresolved
  after the timeout gets ONE duplicate dispatch on a different replica;
  first result wins, the loser is ignored (bounded: one hedge per
  request, never to a replica already tried).  This covers stragglers —
  the ``replica_slow`` chaos fault — without the 2x load of
  always-mirror.
- **Circuit breaker**: ``breaker_threshold`` consecutive failover-class
  failures open a replica's breaker for ``breaker_cooldown_s`` (no
  traffic), so a flapping replica can't eat a failover hop from every
  request while the supervisor gets around to restarting it.
- **Backpressure**: when no eligible replica can accept (all queues
  full), ``submit()`` raises :class:`QueueFullError` carrying the
  fleet-wide queue depth — the HTTP edge turns it into a structured
  429 with Retry-After.

Streaming sessions (docs/SERVING.md "Streaming sessions"): a session's
device state (pinned lane, warm carry) lives in ONE replica's engine,
so the router layers **session affinity** on top of bucket affinity —
the replica picked at open serves every frame.  That state dies with
the engine; the router's job is to make that loss invisible at the
cost of one cold frame.  It keeps each stream's last frame host-side
and, when the pinned replica was restarted (rolling
``update_weights``, detected by the replica's ``generation`` bump) or
fails over mid-frame, re-opens the session on an eligible replica by
replaying that frame as the new frame 0 — the next pair runs cold and
the stream continues, monotone frame numbering intact
(``stream_restart`` event, ``raft_fleet_stream_restarts_total``).
"""

from __future__ import annotations

import dataclasses
import threading
import time
import zlib
from concurrent.futures import Future
from typing import List, Optional, Set

import numpy as np

from raft_tpu.chaos import is_transient_error
from raft_tpu.obs import EventSink, MetricRegistry
from raft_tpu.obs import trace
from raft_tpu.ops.pad import bucket_hw
from raft_tpu.serve.engine import QueueFullError
from raft_tpu.serve.stats import LatencyRecorder


def is_failover_error(exc: BaseException) -> bool:
    """True when a request failure indicts the REPLICA rather than the
    request — worth re-dispatching on a sibling.  Replica-fatal chaos
    faults carry ``replica_fatal``; an engine that stopped or crashed
    under an in-flight request raises the lifecycle RuntimeErrors; a
    transient device error that exhausted the engine's in-replica retry
    ladder may still succeed on a sibling's device.

    Network taxonomy (multi-host fabric, ``raft_tpu/serve/remote.py``):
    connection errors (refused / reset / ``RemoteDisconnected``),
    timeouts (``socket.timeout`` IS ``TimeoutError``), and HTTP-503
    carriers (``http_status`` attribute) all indict the remote HOST —
    a partitioned replica must look exactly like a crashed one."""
    if getattr(exc, "replica_fatal", False):
        return True
    if isinstance(exc, QueueFullError):
        return False
    if isinstance(exc, (ConnectionError, TimeoutError)):
        return True
    if getattr(exc, "http_status", None) == 503:
        return True
    if isinstance(exc, RuntimeError):
        msg = str(exc)
        if ("engine stopped" in msg or "engine crashed" in msg
                or "engine not started" in msg):
            return True
    return is_transient_error(exc)


@dataclasses.dataclass(frozen=True)
class RouterConfig:
    """Routing knobs (``FlowRouter``)."""

    #: Duplicate a still-unresolved request onto a second replica after
    #: this many seconds (0 disables hedging).  Bound it well above the
    #: p99 batch time or the hedge fires on healthy traffic.
    hedge_timeout_s: float = 0.0
    #: Consecutive failover-class failures before a replica's breaker
    #: opens (no traffic until the cooldown passes or the supervisor
    #: restarts it, which resets the breaker).
    breaker_threshold: int = 3
    breaker_cooldown_s: float = 2.0
    #: Affinity saturation point: route past the affine replica once its
    #: pending depth reaches this fraction of ``max_queue``.
    affinity_spill: float = 0.75

    def __post_init__(self):
        if self.hedge_timeout_s < 0:
            raise ValueError("hedge_timeout_s must be >= 0")
        if self.breaker_threshold < 1:
            raise ValueError("breaker_threshold must be >= 1")


class _RoutedRequest:
    """Book-keeping for one accepted request: the caller-facing future,
    the replicas tried, and first-wins settlement (primary vs hedge)."""

    __slots__ = ("image1", "image2", "bucket", "future", "tried",
                 "lock", "hedged", "timer", "t_submit", "last_exc",
                 "trace")

    def __init__(self, image1, image2, bucket):
        self.image1 = image1
        self.image2 = image2
        self.bucket = bucket
        self.future: Future = Future()
        self.tried: Set[str] = set()
        self.lock = threading.RLock()
        self.hedged = False
        self.timer: Optional[threading.Timer] = None
        self.t_submit = time.perf_counter()
        self.last_exc: Optional[BaseException] = None
        # The request's "route" span (None when untraced).  Settlement
        # closes it — a hedged request's two attempt spans both hang
        # off this one node, so the whole story is ONE tree.
        self.trace = None

    def settle_result(self, value) -> bool:
        with self.lock:
            if self.future.done():
                return False
            self._cancel_timer()
            self.future.set_result(value)
            if self.trace is not None:
                self.trace.end(status="ok", hedged=self.hedged,
                               replicas_tried=len(self.tried))
            return True

    def settle_exception(self, exc: BaseException) -> bool:
        with self.lock:
            if self.future.done():
                return False
            self._cancel_timer()
            self.future.set_exception(exc)
            if self.trace is not None:
                self.trace.end(status="error",
                               error=type(exc).__name__,
                               hedged=self.hedged,
                               replicas_tried=len(self.tried))
            return True

    def _cancel_timer(self) -> None:
        if self.timer is not None:
            self.timer.cancel()
            self.timer = None


class _RoutedStream:
    """Router-side record of one streaming session: which replica owns
    its device state, the generation/weights stamps that detect a
    restart, and the last frame (host-side) that seeds a cold re-open.
    ``lock`` serializes the session's frames through the router —
    streams are sequential by contract, so this costs nothing."""

    __slots__ = ("sid", "bucket", "iters", "ttl_s", "replica",
                 "generation", "weights_version", "last_image",
                 "frames", "restarts", "t_last", "lock")

    def __init__(self, sid, bucket, iters, ttl_s, replica,
                 generation, weights_version, image):
        self.sid = sid
        self.bucket = bucket
        self.iters = iters
        self.ttl_s = ttl_s
        self.replica = replica          # name, not the object
        self.generation = generation
        self.weights_version = weights_version
        self.last_image = image
        self.frames = 0                 # router-monotone frame index
        self.restarts = 0
        self.t_last = time.time()
        self.lock = threading.Lock()


class FlowRouter:
    """See module docstring.  ``fleet`` is duck-typed: it exposes
    ``replicas`` (list of :class:`raft_tpu.serve.fleet.Replica`),
    ``serve_cfg`` and ``registry``."""

    def __init__(self, fleet, cfg: RouterConfig = RouterConfig(), *,
                 sink: Optional[EventSink] = None):
        self.fleet = fleet
        self.cfg = cfg
        self.registry: MetricRegistry = fleet.registry
        self._sink = sink if sink is not None else EventSink.from_env()
        self._requests = self.registry.counter(
            "raft_fleet_requests_total",
            "requests dispatched, by target replica")
        self._failovers = self.registry.counter(
            "raft_fleet_failovers_total",
            "requests re-dispatched off a failed replica")
        self._hedges = self.registry.counter(
            "raft_fleet_hedges_total", "hedge duplicates dispatched")
        self._hedge_wins = self.registry.counter(
            "raft_fleet_hedge_wins_total",
            "requests whose hedge finished first")
        self._rejected = self.registry.counter(
            "raft_fleet_rejected_total",
            "submissions rejected (no eligible replica could accept)")
        # Tripwire, asserted == 0 by the chaos drill: incremented only
        # if a terminal path failed to settle an accepted future (a
        # router bug, not an operational condition).
        self._dropped = self.registry.counter(
            "raft_fleet_dropped_total",
            "accepted requests that were never settled (must stay 0)")
        self._latency = LatencyRecorder(
            registry=self.registry,
            metric="raft_fleet_request_latency_seconds")
        self._streams: dict = {}
        self._streams_lock = threading.Lock()
        self._stream_restarts = self.registry.counter(
            "raft_fleet_stream_restarts_total",
            "streaming sessions cold-restarted on a new engine "
            "(weight update or failover)")
        # Fleet-level SLOs (obs/slo.py): the router sees the outcome
        # the CLIENT sees — post-failover, post-hedge — so fleet
        # availability/latency live here, distinct from the per-engine
        # trackers (pre-failover, replica-labeled).  Same conditional-
        # construction contract: every knob 0 (default) builds nothing.
        self._slo = None
        scfg = fleet.serve_cfg
        if scfg.slo_availability_target > 0 or \
                scfg.slo_latency_target_ms > 0:
            from raft_tpu.obs import slo as slo_mod

            policy = slo_mod.scaled_policy(scfg.slo_window_s)
            specs = []
            if scfg.slo_availability_target > 0:
                specs.append(slo_mod.SLOSpec(
                    "fleet_availability", scfg.slo_availability_target,
                    "non-error request fraction the client sees "
                    "(post-failover)", windows=policy))
            if scfg.slo_latency_target_ms > 0:
                specs.append(slo_mod.SLOSpec(
                    "fleet_latency", 0.99,
                    f"client-observed requests under "
                    f"{scfg.slo_latency_target_ms}ms", windows=policy))
            self._slo = slo_mod.SLOTracker(
                specs, registry=self.registry, sink=self._sink)
        # Autoscaling fleets need the router back-reference (graceful
        # scale-down evacuates streams through it); duck-typed so stub
        # fleets in tests need not implement it.
        bind = getattr(fleet, "bind_router", None)
        if bind is not None:
            bind(self)

    # ------------------------------------------------------------------
    # client API (any thread)
    # ------------------------------------------------------------------

    def submit(self, image1, image2) -> Future:
        """Route one frame pair; returns a Future resolving to the
        ``(H, W, 2)`` flow.  Raises :class:`QueueFullError` when no
        eligible replica can accept, ``RuntimeError`` when the fleet
        has no live replica at all."""
        im1 = np.asarray(image1, dtype=np.float32)
        im2 = np.asarray(image2, dtype=np.float32)
        if im1.ndim != 3 or im1.shape[-1] != 3 or im1.shape != im2.shape:
            raise ValueError(
                f"expected two matching (H, W, 3) images, got "
                f"{im1.shape} and {im2.shape}")
        scfg = self.fleet.serve_cfg
        bucket = bucket_hw(im1.shape[0], im1.shape[1],
                           scfg.bucket_multiple, scfg.buckets)
        req = _RoutedRequest(im1, im2, bucket)
        # Root (or, under the HTTP handler's serve_http span, child) of
        # the request's trace tree; the no-op singleton normalizes to
        # None so untraced requests carry no span machinery at all.
        span = trace.default_tracer().begin(
            "route", bucket=f"{bucket[0]}x{bucket[1]}")
        req.trace = span if span else None
        try:
            self._dispatch(req, initial=True)
        except BaseException as e:
            if req.trace is not None:
                req.trace.end(status="error", error=type(e).__name__)
            raise
        return req.future

    def infer(self, image1, image2,
              timeout: Optional[float] = None) -> np.ndarray:
        return self.submit(image1, image2).result(timeout=timeout)

    # ------------------------------------------------------------------
    # streaming sessions (session affinity + cold-restart fallback)
    # ------------------------------------------------------------------

    def _replica_by_name(self, name: str):
        for r in self.fleet.replicas:
            if r.name == name:
                return r
        return None

    def stream_ingest(self, session_id: str, image, *,
                      iters: Optional[int] = None,
                      ttl_s: Optional[float] = None,
                      timeout: Optional[float] = None) -> dict:
        """Fleet-side ``POST /v1/stream/{id}`` semantics (same facade
        as ``InferenceEngine.stream_ingest``): first frame opens the
        session on the bucket-affine replica; later frames go to THAT
        replica.  A restarted or failed owner triggers one cold
        re-open (module docstring); ``flow`` comes back ``None`` on
        any frame that (re-)seeded rather than produced a pair."""
        im = np.asarray(image, dtype=np.float32)
        if im.ndim != 3 or im.shape[-1] != 3:
            raise ValueError(
                f"expected an (H, W, 3) image, got {im.shape}")
        sid = str(session_id)
        scfg = self.fleet.serve_cfg
        with self._streams_lock:
            self._sweep_streams_locked(time.time())
            rst = self._streams.get(sid)
            if rst is None:
                bucket = bucket_hw(im.shape[0], im.shape[1],
                                   scfg.bucket_multiple, scfg.buckets)
                replica = self._pick(bucket, set())
                if replica is None:
                    states = {r.name: r.state
                              for r in self.fleet.replicas}
                    raise RuntimeError(
                        f"no eligible replica to open stream "
                        f"{sid!r} on (fleet states: {states})")
                replica.engine.stream_open(sid, im, iters=iters,
                                           ttl_s=ttl_s)
                rst = _RoutedStream(
                    sid, bucket, iters,
                    float(ttl_s) if ttl_s is not None
                    else scfg.stream_ttl_s,
                    replica.name, replica.generation,
                    getattr(self.fleet, "weights_version", 0), im)
                self._streams[sid] = rst
                self._requests.inc(replica=replica.name)
                return {"session": sid, "frame": 0, "warm": False,
                        "flow": None}
        with rst.lock:
            replica = self._replica_by_name(rst.replica)
            wv = getattr(self.fleet, "weights_version", 0)
            if (replica is None or not replica.eligible()
                    or replica.generation != rst.generation):
                reason = ("weights_update"
                          if wv != rst.weights_version
                          else "replica_lost")
                replica = self._restart_stream(rst, reason, set())
            try:
                out = replica.engine.stream_ingest(sid, im,
                                                   timeout=timeout)
            except QueueFullError:
                raise  # backoff, session intact — client retries
            except Exception as e:
                if not is_failover_error(e):
                    raise
                if replica.generation == rst.generation:
                    replica.note_failure(self.cfg.breaker_threshold,
                                         self.cfg.breaker_cooldown_s)
                replica = self._restart_stream(rst, "failover",
                                               {replica.name})
                out = replica.engine.stream_ingest(sid, im,
                                                   timeout=timeout)
            self._requests.inc(replica=replica.name)
            rst.last_image = im
            rst.frames += 1
            rst.t_last = time.time()
            return {"session": sid, "frame": rst.frames,
                    "warm": bool(out["warm"]), "flow": out["flow"]}

    def stream_close(self, session_id: str) -> dict:
        """Close a stream everywhere; returns the owning engine's
        summary when it is still reachable, a router-side one when the
        device state is already gone (restart raced the close)."""
        sid = str(session_id)
        with self._streams_lock:
            rst = self._streams.pop(sid, None)
        if rst is None:
            raise ValueError(f"unknown session {session_id!r} "
                             "(expired, closed, or never opened)")
        with rst.lock:
            summary = None
            replica = self._replica_by_name(rst.replica)
            if (replica is not None
                    and replica.generation == rst.generation):
                try:
                    summary = replica.engine.stream_close(sid)
                except Exception:
                    summary = None
            if summary is None:
                summary = {"session": sid, "frames": rst.frames + 1,
                           "pairs": rst.frames, "warm_pairs": None}
            summary["restarts"] = rst.restarts
            return summary

    def _restart_stream(self, rst: _RoutedStream, reason: str,
                        exclude: Set[str]):
        """Re-open ``rst`` cold on an eligible replica, replaying its
        last frame as the new frame 0.  Called with ``rst.lock`` held.
        The old engine's copy (if any survives) is left to its TTL
        eviction — closing it could block on a dead replica."""
        target = self._pick(rst.bucket, exclude)
        if target is None:
            states = {r.name: r.state for r in self.fleet.replicas}
            raise RuntimeError(
                f"stream {rst.sid!r}: no eligible replica to restart "
                f"on (fleet states: {states})")
        target.engine.stream_open(rst.sid, rst.last_image,
                                  iters=rst.iters, ttl_s=rst.ttl_s)
        prev = rst.replica
        rst.replica = target.name
        rst.generation = target.generation
        rst.weights_version = getattr(self.fleet, "weights_version", 0)
        rst.restarts += 1
        self._stream_restarts.inc()
        self._sink.emit("stream_restart", sid=rst.sid,
                        bucket=f"{rst.bucket[0]}x{rst.bucket[1]}",
                        from_replica=prev, to_replica=target.name,
                        reason=reason)
        return target

    def _sweep_streams_locked(self, now: float) -> None:
        for sid, rst in list(self._streams.items()):
            if now - rst.t_last > rst.ttl_s and not rst.lock.locked():
                del self._streams[sid]

    def evacuate(self, replica_name: str,
                 reason: str = "scale_down") -> List[str]:
        """Migrate every streaming session owned by ``replica_name``
        onto a sibling via the cold-restart replay path (the fleet
        autoscaler calls this before draining a scale-down victim).
        Sessions with no eligible target are left in place — the next
        frame's owner-loss check restarts them lazily instead of
        failing the evacuation.  Returns the migrated session ids."""
        with self._streams_lock:
            owned = [rst for rst in self._streams.values()
                     if rst.replica == replica_name]
        moved = []
        for rst in owned:
            with rst.lock:
                if rst.replica != replica_name:
                    continue  # a concurrent frame already moved it
                try:
                    self._restart_stream(rst, reason, {replica_name})
                except RuntimeError:
                    continue
                moved.append(rst.sid)
        return moved

    # ------------------------------------------------------------------
    # placement
    # ------------------------------------------------------------------

    def _eligible(self, exclude: Set[str]) -> List[object]:
        return [r for r in self.fleet.replicas
                if r.name not in exclude and r.eligible()]

    def _queue_capacity(self, replica) -> int:
        """PER-REPLICA queue depth bound for the spill math.  A remote
        replica's ``max_queue`` is its own (read through the facade);
        only replicas without the method — or whose capacity is still
        unknown, e.g. an unreachable remote — fall back to the shared
        ``ServeConfig``."""
        qc = getattr(replica, "queue_capacity", None)
        if qc is not None:
            try:
                cap = qc()
            except Exception:
                cap = None
            if cap:
                return int(cap)
        return self.fleet.serve_cfg.max_queue

    def _pick(self, bucket: tuple, exclude: Set[str]):
        """Affinity first, least-loaded fallback, health-gated."""
        candidates = self._eligible(exclude)
        if not candidates:
            return None
        n = len(self.fleet.replicas)
        affine_idx = zlib.crc32(repr(bucket).encode()) % n
        for r in candidates:
            if r.index == affine_idx and r.pending() < (
                    self.cfg.affinity_spill * self._queue_capacity(r)):
                return r
        return min(candidates, key=lambda r: (r.pending(), r.index))

    # ------------------------------------------------------------------
    # dispatch / failover / hedging
    # ------------------------------------------------------------------

    def _dispatch(self, req: _RoutedRequest, *, initial: bool) -> None:
        """Hand ``req`` to an eligible replica, walking the eligibility
        list until one accepts.  Terminal failures raise synchronously
        on the initial dispatch (the caller hasn't been handed a future
        yet) and settle the future afterwards — the request is never
        left pending."""
        saw_full = None
        while True:
            with req.lock:
                if req.future.done():
                    return
                replica = self._pick(req.bucket, req.tried)
                if replica is not None:
                    req.tried.add(replica.name)
            if replica is None:
                self._terminal(req, saw_full, initial)
                return
            # One "attempt" span per dispatch; the engine's submitting
            # thread captures it (use_context) and the device worker
            # records queue/pad/device under it — so the span crosses
            # the dispatcher and device threads with the request.
            att = (req.trace.child("attempt", replica=replica.name,
                                   hedge=False)
                   if req.trace is not None else None)
            try:
                if att is not None:
                    with trace.use_context(att):
                        inner = replica.engine.submit(req.image1,
                                                      req.image2)
                else:
                    inner = replica.engine.submit(req.image1, req.image2)
            except QueueFullError as e:
                if att is not None:  # recorded, but not keep-forcing
                    att.end(status="full", queue_depth=e.queue_depth)
                saw_full = e  # full ≠ dead: no breaker strike
                continue
            except RuntimeError as e:
                # Lost the race with a crash/stop between the health
                # check and submit — treat exactly like a failed
                # attempt on that replica.
                if att is not None:
                    att.end(status="error", error=type(e).__name__)
                if not is_failover_error(e):
                    self._settle_or_raise(req, e, initial)
                    return
                req.last_exc = e
                self._strike(replica)
                continue
            self._requests.inc(replica=replica.name)
            if initial:
                self._maybe_arm_hedge(req)
            gen = replica.generation
            inner.add_done_callback(
                lambda f, r=replica, g=gen, a=att:
                    self._on_done(req, r, g, f, span=a))
            return

    def _strike(self, replica) -> None:
        """One failover-class breaker strike; emits
        ``fleet_breaker_open`` exactly on the closed->open transition."""
        if replica.note_failure(self.cfg.breaker_threshold,
                                self.cfg.breaker_cooldown_s):
            self._sink.emit("fleet_breaker_open", replica=replica.name,
                            cooldown_s=self.cfg.breaker_cooldown_s)

    def _slo_done(self, ok: bool,
                  latency_s: Optional[float] = None,
                  exc: Optional[BaseException] = None) -> None:
        """Feed the CLIENT-visible outcome of one settled request.
        Load-shed rejections (429-class QueueFullError) spend no
        availability budget — shedding under overload is the mechanism
        PROTECTING the SLO, not a violation of it."""
        if self._slo is None:
            return
        if exc is not None and isinstance(exc, QueueFullError):
            return
        self._slo.record("fleet_availability", ok)
        if ok and latency_s is not None:
            self._slo.record(
                "fleet_latency",
                latency_s * 1000.0
                <= self.fleet.serve_cfg.slo_latency_target_ms)

    def _terminal(self, req: _RoutedRequest, saw_full, initial: bool):
        """No replica left to try: fail the request loudly."""
        if saw_full is not None:
            depth = sum(r.pending() for r in self.fleet.replicas)
            exc: BaseException = QueueFullError(
                f"all eligible replicas at max_queue "
                f"({depth} requests in flight fleet-wide); retry after "
                f"{self.fleet.serve_cfg.retry_after_s:g}s",
                queue_depth=depth,
                retry_after_s=self.fleet.serve_cfg.retry_after_s)
            self._rejected.inc()
        elif req.last_exc is not None:
            exc = RuntimeError(
                f"request failed on {len(req.tried)} replica(s); "
                f"last error: {type(req.last_exc).__name__}: "
                f"{req.last_exc}")
            exc.__cause__ = req.last_exc
        else:
            states = {r.name: r.state for r in self.fleet.replicas}
            exc = RuntimeError(
                f"no eligible replica (fleet states: {states})")
            self._rejected.inc()
        self._settle_or_raise(req, exc, initial)

    def _settle_or_raise(self, req: _RoutedRequest, exc: BaseException,
                         initial: bool) -> None:
        if initial:
            req._cancel_timer()
            self._slo_done(False, exc=exc)
            raise exc
        if req.settle_exception(exc):
            self._slo_done(False, exc=exc)
        elif not req.future.done():
            # Unreachable by construction; the tripwire exists so a
            # future regression shows up as a nonzero counter in the
            # drill instead of a hung client.
            self._dropped.inc()

    def _maybe_arm_hedge(self, req: _RoutedRequest) -> None:
        if self.cfg.hedge_timeout_s <= 0 or len(self.fleet.replicas) < 2:
            return
        timer = threading.Timer(self.cfg.hedge_timeout_s,
                                self._hedge, args=(req,))
        timer.daemon = True
        req.timer = timer
        timer.start()

    def _hedge(self, req: _RoutedRequest) -> None:
        with req.lock:
            if req.future.done() or req.hedged:
                return
            req.hedged = True
            replica = self._pick(req.bucket, req.tried)
            if replica is None:
                return  # nowhere to hedge; primary still owns the request
            req.tried.add(replica.name)
        att = (req.trace.child("attempt", replica=replica.name,
                               hedge=True)
               if req.trace is not None else None)
        try:
            if att is not None:
                with trace.use_context(att):
                    inner = replica.engine.submit(req.image1, req.image2)
            else:
                inner = replica.engine.submit(req.image1, req.image2)
        except Exception as e:
            if att is not None:
                att.end(status="full"
                        if isinstance(e, QueueFullError) else "error",
                        error=type(e).__name__)
            return  # hedge is best-effort; the primary attempt stands
        if req.trace is not None:
            # Tail-keep: a fired hedge means a straggler — this trace
            # is exactly the kind worth keeping regardless of sampling.
            req.trace.mark_keep()
        self._hedges.inc()
        self._requests.inc(replica=replica.name)
        self._sink.emit("serve_hedge", replica=replica.name,
                        bucket=f"{req.bucket[0]}x{req.bucket[1]}")
        gen = replica.generation
        inner.add_done_callback(
            lambda f, r=replica, g=gen, a=att:
                self._on_done(req, r, g, f, hedge=True, span=a))

    def _on_done(self, req: _RoutedRequest, replica, generation: int,
                 inner: Future, *, hedge: bool = False,
                 span=None) -> None:
        exc = inner.exception()
        if exc is None:
            # End the attempt BEFORE settling so the root-span flush
            # carries it; a hedge loser finishing after settlement
            # still lands in the (kept) tree via the late-span path.
            if span is not None:
                span.end(status="ok", won=not req.future.done())
            replica.note_success()
            if req.settle_result(inner.result()):
                lat = time.perf_counter() - req.t_submit
                self._latency.record(lat)
                self._slo_done(True, lat)
                if hedge:
                    self._hedge_wins.inc()
            return
        if span is not None:  # error status tail-keeps the trace
            span.end(status="error", error=type(exc).__name__)
        if is_failover_error(exc):
            # Strike the replica only if this failure came from the
            # engine generation we dispatched to (a restarted engine
            # must not inherit its predecessor's strikes).
            if replica.generation == generation:
                self._strike(replica)
            req.last_exc = exc
            if not req.future.done():
                self._failovers.inc(replica=replica.name)
                self._sink.emit(
                    "serve_failover", replica=replica.name,
                    bucket=f"{req.bucket[0]}x{req.bucket[1]}",
                    tried=len(req.tried),
                    error=f"{type(exc).__name__}: {str(exc)[:200]}")
                self._dispatch(req, initial=False)
            return
        if req.settle_exception(exc):
            self._slo_done(False, exc=exc)

    # ------------------------------------------------------------------
    # introspection (the HTTP edge serves a router exactly like a bare
    # engine: same health/stats/metrics_text facade)
    # ------------------------------------------------------------------

    def health(self) -> dict:
        return self.fleet.health()

    def metrics_text(self) -> str:
        return self.fleet.metrics_text()

    def router_stats(self) -> dict:
        def total(counter):  # sum across label sets (per-replica lines)
            return sum(v for _, v in counter.items())

        return {
            "requests_total": total(self._requests),
            "requests_by_replica": {
                dict(k).get("replica", ""): v
                for k, v in self._requests.items()},
            "failovers_total": total(self._failovers),
            "hedges_total": self._hedges.value(),
            "hedge_wins_total": self._hedge_wins.value(),
            "rejected_total": self._rejected.value(),
            "dropped_total": self._dropped.value(),
            "latency_ms": self._latency.snapshot(),
            "streams_open": len(self._streams),
            "stream_restarts_total": int(
                self._stream_restarts.value()),
            "slo": (self._slo.snapshot() if self._slo is not None
                    else {"enabled": False}),
        }

    def stats(self) -> dict:
        return dict(self.fleet.stats(), router=self.router_stats())
