"""Async TPU inference serving engine: shape buckets + dynamic batching.

The offline entry points (``train.py`` / ``evaluate.py`` / ``demo.py``)
stream known datasets; a server sees *concurrent requests of unknown
resolution*.  Two classic levers make that fast on XLA backends, and both
live here:

1. **Shape-bucketed compile cache.**  Every request's ``(H, W)`` is
   rounded to a /8-aligned bucket (:func:`raft_tpu.ops.pad.bucket_hw` —
   the same policy the validators use, optionally snapped to a coarse
   configured ladder), and the engine keeps one AOT-compiled test-mode
   forward per ``(bucket_hw, batch_size)``.  Ahead-of-time
   ``jit.lower(...).compile()`` — not plain ``jax.jit`` call-site caching
   — so a compile is an *explicit, counted event*
   (:class:`raft_tpu.utils.profiling.CompileCounter`) and a shape that
   slipped past the bucketing would raise instead of silently
   recompiling per request.  Steady-state traffic never compiles.

2. **Dynamic micro-batching.**  Requests landing in the same bucket are
   coalesced by a per-bucket dispatcher into one device batch: the batch
   closes at ``max_batch`` items or ``max_wait_ms`` after its first
   item, whichever comes first (the latency/throughput trade-off knob —
   docs/SERVING.md).  Partial batches are padded (repeating the last
   item) up to the nearest compiled batch size, so batch shapes come
   from a small fixed set.

3. **Iteration-granular continuous batching** (``batching="slot"``).
   The unit of device work drops from one whole request to ONE GRU
   iteration over a persistent slot batch (``serve/slots.py``): the
   per-bucket dispatcher admits waiting requests into free slots
   (running the ``encode`` program for the new lanes), runs one
   ``iter_step`` over every active slot, and retires lanes whose
   iteration budget is spent or whose convergence predicate fired
   (max flow-update magnitude below ``early_exit_threshold``) — so a
   24-iteration straggler no longer pins lanes that finished, and easy
   inputs exit early (SEA-RAFT-style).  Whole-request mode stays the
   default (``batching="request"``) and is the parity oracle: BOTH
   modes drive the same two compiled ``encode``/``iter_step``
   executables (request mode in whole-batch lockstep), so with early
   exit disabled their outputs are bit-identical by construction —
   XLA specializes fusion/reduction order per program, so this is the
   only robust way to pin parity (see models/raft.py).

Architecture (three kinds of thread, one device):

- caller threads: ``submit()`` — bucket lookup, backpressure check,
  handoff to the engine's event loop.  Returns a
  ``concurrent.futures.Future``.
- the engine's asyncio loop thread: per-bucket dispatcher tasks coalesce
  micro-batches.  Pure bookkeeping, never touches the device.
- one device-worker thread: pads/stacks the batch, runs the compiled
  executable, unpads per-request results.  Single-threaded by
  construction so device work serializes instead of interleaving.

Backpressure: a bounded in-flight count (``max_queue``).  ``submit()``
beyond it raises :class:`QueueFullError` (the HTTP layer maps it to 429)
— the queue can never grow without bound, and latency under overload
stays bounded instead of collapsing.

4. **Streaming sessions** (``stream_open`` / ``stream_submit`` /
   ``stream_close``; ``POST /v1/stream/{id}`` at the HTTP layer).  A
   session pins one lane of its bucket's slot pool across frames: frame
   N+1 uploads only the NEW image — the previous frame's flow is
   forward-warped on-device into the lane's ``coords1`` init and the
   previous frame's feature map / context are reused as the next pair's
   frame-1 features (consecutive-frame identity), so a warm frame runs
   the feature encoder once instead of twice and starts its GRU
   iterations near the answer.  A session's FIRST pair goes through the
   unmodified ``encode_admit`` program (bit-identical to the stateless
   slot path); idle sessions are evicted back to the free pool after
   ``stream_ttl_s``.  See docs/SERVING.md "Streaming sessions".

Scope: single-host, single-device per engine (multi-chip serving is one
engine process per chip behind an external balancer).  Stateless
``submit()`` requests stay independent frame pairs; cross-request warm
start exists ONLY inside an explicit streaming session, whose device
state dies with the engine (rolling weight updates and replica failover
restart streams cold — the router re-seeds them, docs/SERVING.md).
"""

from __future__ import annotations

import asyncio
import dataclasses
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu import chaos
from raft_tpu.chaos import (InjectedDeviceError, InjectedReplicaKill,
                            ReplicaWedgedInterrupt, is_transient_error)
from raft_tpu.config import RAFTConfig
from raft_tpu.obs import EventSink, MetricRegistry
from raft_tpu.obs import cost as cost_mod
from raft_tpu.obs import trace
from raft_tpu.ops.pad import InputPadder, bucket_hw
from raft_tpu.serve.stats import Counters, LatencyRecorder
from raft_tpu.utils.profiling import CompileCounter


class QueueFullError(RuntimeError):
    """Backpressure rejection: ``max_queue`` requests already in flight.

    The 429-style signal — the caller should shed load or retry with
    backoff; the engine never queues without bound.  Carries the
    structured overload detail the HTTP layer returns (429 JSON body +
    ``Retry-After`` header) so load generators and the fleet router can
    back off *proportionally* instead of hammering: ``queue_depth`` is
    the in-flight count at rejection time, ``retry_after_s`` the
    suggested wait."""

    def __init__(self, msg: str, *, queue_depth: int = 0,
                 retry_after_s: float = 1.0):
        super().__init__(msg)
        self.queue_depth = int(queue_depth)
        self.retry_after_s = float(retry_after_s)


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Engine knobs (model hyperparameters stay in ``RAFTConfig``).

    ``max_wait_ms`` trades tail latency for batch fill: 0 ships every
    request alone (lowest latency, worst throughput); large values fill
    batches under light traffic but add up to that wait to p99.
    ``buckets`` is an optional explicit ``(H, W)`` ladder — with unknown
    traffic a coarse ladder coalesces nearby resolutions into one
    program instead of fragmenting per shape.  ``batch_sizes`` is the
    set of compiled batch shapes (default: powers of two up to
    ``max_batch``); micro-batches round up to the nearest one.
    ``stall_timeout_s``: readiness threshold — with requests pending
    and no device batch completed for this long, ``health()`` reports
    not-ready (``GET /v1/healthz`` -> 503) so a balancer drains a
    wedged replica; must exceed ``max_wait_ms`` + the worst cold
    compile (or warm up first); 0 disables the check.
    ``device_retries``: device-call re-dispatches for errors classified
    transient (:func:`raft_tpu.chaos.is_transient_error`) before the
    whole batch fails — one flaky dispatch no longer 500s every
    co-batched request; deterministic errors always fail fast
    (docs/ROBUSTNESS.md).  Retry backoff is EXPONENTIAL with jitter:
    attempt k sleeps ``min(retry_backoff_s * 2^(k-1),
    retry_backoff_max_s)`` scaled by a ±``retry_jitter`` fraction
    (seeded per engine, so chaos drills replay), and the whole retry
    ladder is capped by ``retry_deadline_s`` measured from the first
    failure — a batch never spends longer retrying than a client would
    plausibly wait.  The actual sleep lands in each ``serve_retry``
    event (``backoff_s``) so drills can assert the schedule.
    ``retry_after_s``: the backoff hint a 429 rejection carries.
    ``aot_dir``: warm-start artifact directory
    (``raft_tpu/serve/aot.py``) — compatible ``(bucket, batch)``
    executables are imported at construction so the first request
    compiles NOTHING; an incompatible/corrupt artifact is skipped
    (``aot_import_error`` event) and the engine compiles lazily.
    ``chaos_slow_s``/``chaos_hang_max_s`` size the injected
    ``replica_slow`` straggler sleep and the ``replica_hang`` wedge cap
    (drills only; no effect without an installed fault plan).
    ``batching``: ``"request"`` (whole-request micro-batches, the
    default) or ``"slot"`` (iteration-granular continuous batching over
    a persistent ``slots``-lane batch per bucket — docs/SERVING.md
    "Continuous batching").  ``early_exit_threshold``: per-sample
    convergence cut — a slot retires once its max flow-update magnitude
    (flow units at 1/8 resolution) drops below this; ``0`` disables
    (full budget always runs).  Slot mode honors a per-request
    ``iters`` budget (capped at ``cfg.iters``); request mode runs every
    lane to ``cfg.iters`` (lockstep).  All three are tuning-registry
    knobs (``scripts/autotune.py --kind serve``).
    ``quality_sample_rate``: fraction of retiring slot-mode requests
    scored with the label-free photometric quality proxy
    (``raft_tpu/obs/quality.py``; docs/OBSERVABILITY.md "Flow
    quality") — scored requests emit ``quality_score`` events and feed
    the ``raft_quality_*`` histograms plus the drift detector; the
    free convergence residual is recorded for EVERY retirement while
    sampling is on.  ``0`` (the default) disables quality scoring
    entirely: no monitor is built, no extra device fetch or program
    exists on the hot path (the zero-overhead contract,
    tests/test_quality.py).  ``quality_cycle`` additionally runs a
    sampled forward-backward cycle-consistency pass (one extra
    inference on the swapped frames per scored request).  The
    ``quality_drift_*`` knobs size the PSI drift detector (reference
    sample count, rolling window, firing threshold).
    Streaming-session knobs (slot mode only — docs/SERVING.md
    "Streaming sessions"): ``stream_ttl_s`` evicts a session whose
    client went quiet back to the free pool (its pinned lane is what
    the TTL protects); ``stream_warm_iters`` is the per-frame
    iteration budget for WARM-started frames (``None`` keeps the
    session budget — warm frames then rely on ``early_exit_threshold``
    to retire early; set it below ``iters`` to cap warm frames
    outright, the streaming policy RAFT's warm-start convergence
    buys); ``max_sessions`` bounds the open-session registry (opens
    beyond it are rejected 429-style).
    SLO / incident knobs (docs/OBSERVABILITY.md "Incidents & SLOs"):
    ``slo_availability_target`` (0 disables) is the non-error request
    fraction objective; ``slo_latency_target_ms`` (0 disables) tracks
    "99% of requests under this many ms"; ``slo_quality_bound``
    (0 disables; needs ``quality_sample_rate`` > 0) marks a sampled
    retirement bad when its photometric proxy exceeds the bound;
    ``slo_mfu_floor`` (0 disables) marks an iteration bad below the
    floor — constructed only when ``PEAK_SPECS`` knows the device
    peak.  ``slo_window_s`` rescales the Google-SRE burn-rate
    policy's 1h long window (obs/slo.py).  ``incidents`` builds an
    :class:`~raft_tpu.obs.incident.IncidentManager` on this engine's
    sink (leave False under a :class:`~raft_tpu.serve.fleet
    .ReplicaFleet`, which owns ONE manager for the shared stream);
    the ``incident_*`` knobs size its correlation window, quiet-close
    threshold, and post-close cooldown.  All of it is host-side deque
    arithmetic — zero device syncs, CompileCounter-pinned."""

    iters: int = 32
    max_batch: int = 8
    max_wait_ms: float = 5.0
    max_queue: int = 256
    bucket_multiple: int = 8
    buckets: Optional[Tuple[Tuple[int, int], ...]] = None
    batch_sizes: Optional[Tuple[int, ...]] = None
    pad_mode: str = "sintel"
    latency_window: int = 4096
    stall_timeout_s: float = 120.0
    device_retries: int = 1
    retry_backoff_s: float = 0.05
    retry_backoff_max_s: float = 2.0
    retry_jitter: float = 0.25
    retry_deadline_s: float = 10.0
    retry_after_s: float = 1.0
    aot_dir: Optional[str] = None
    chaos_slow_s: float = 0.5
    chaos_hang_max_s: float = 30.0
    batching: str = "request"
    slots: int = 8
    early_exit_threshold: float = 0.0
    quality_sample_rate: float = 0.0
    quality_cycle: bool = False
    quality_drift_reference: int = 256
    quality_drift_window: int = 64
    quality_drift_threshold: float = 0.5
    stream_ttl_s: float = 60.0
    stream_warm_iters: Optional[int] = None
    max_sessions: int = 64
    slo_availability_target: float = 0.0
    slo_latency_target_ms: float = 0.0
    slo_quality_bound: float = 0.0
    slo_mfu_floor: float = 0.0
    slo_window_s: float = 3600.0
    incidents: bool = False
    incident_window_s: float = 10.0
    incident_quiet_s: float = 30.0
    incident_cooldown_s: float = 60.0

    def __post_init__(self):
        if self.stream_ttl_s <= 0:
            raise ValueError("stream_ttl_s must be > 0")
        if self.stream_warm_iters is not None \
                and self.stream_warm_iters < 1:
            raise ValueError("stream_warm_iters must be >= 1 (None "
                             "keeps the session budget)")
        if self.max_sessions < 1:
            raise ValueError("max_sessions must be >= 1")
        if self.max_batch < 1 or self.max_queue < 1:
            raise ValueError("max_batch and max_queue must be >= 1")
        if self.batching not in ("request", "slot"):
            raise ValueError(f"batching must be 'request' or 'slot', "
                             f"got {self.batching!r}")
        if self.slots < 1:
            raise ValueError("slots must be >= 1")
        if self.early_exit_threshold < 0:
            raise ValueError("early_exit_threshold must be >= 0 "
                             "(0 disables early exit)")
        if not 0.0 <= self.quality_sample_rate <= 1.0:
            raise ValueError(
                f"quality_sample_rate must be in [0, 1] (0 disables "
                f"quality scoring), got {self.quality_sample_rate}")
        if (self.quality_drift_reference < 4
                or self.quality_drift_window < 2
                or self.quality_drift_threshold <= 0):
            raise ValueError(
                "need quality_drift_reference >= 4, "
                "quality_drift_window >= 2 and "
                "quality_drift_threshold > 0")
        if self.max_wait_ms < 0:
            raise ValueError("max_wait_ms must be >= 0")
        if self.stall_timeout_s < 0:
            raise ValueError("stall_timeout_s must be >= 0")
        if self.device_retries < 0 or self.retry_backoff_s < 0:
            raise ValueError(
                "device_retries and retry_backoff_s must be >= 0")
        if (self.retry_backoff_max_s < self.retry_backoff_s
                or self.retry_deadline_s <= 0
                or not 0 <= self.retry_jitter < 1):
            raise ValueError(
                "need retry_backoff_max_s >= retry_backoff_s, "
                "retry_deadline_s > 0 and 0 <= retry_jitter < 1")
        if not 0.0 <= self.slo_availability_target < 1.0:
            raise ValueError(
                "slo_availability_target must be in [0, 1) — 0 "
                f"disables, 1.0 leaves no error budget; got "
                f"{self.slo_availability_target}")
        if (self.slo_latency_target_ms < 0 or self.slo_quality_bound < 0
                or self.slo_window_s <= 0):
            raise ValueError(
                "need slo_latency_target_ms >= 0, slo_quality_bound "
                ">= 0 and slo_window_s > 0")
        if not 0.0 <= self.slo_mfu_floor < 1.0:
            raise ValueError("slo_mfu_floor must be in [0, 1) "
                             "(0 disables)")
        if (self.incident_window_s <= 0 or self.incident_quiet_s <= 0
                or self.incident_cooldown_s < 0):
            raise ValueError(
                "need incident_window_s > 0, incident_quiet_s > 0 and "
                "incident_cooldown_s >= 0")
        m = self.bucket_multiple
        for hw in self.buckets or ():
            if hw[0] % m or hw[1] % m:
                raise ValueError(
                    f"bucket {hw} not /{m}-aligned (the model "
                    f"downsamples by {m})")

    def resolved_batch_sizes(self) -> Tuple[int, ...]:
        if self.batch_sizes:
            sizes = tuple(sorted({int(b) for b in self.batch_sizes}))
            if sizes[0] < 1:
                raise ValueError(f"batch_sizes must be >= 1: {sizes}")
            return sizes
        sizes, b = set(), 1
        while b < self.max_batch:
            sizes.add(b)
            b *= 2
        sizes.add(self.max_batch)
        return tuple(sorted(sizes))


class _Request:
    __slots__ = ("image1", "image2", "bucket", "padder", "future",
                 "t_submit", "trace", "iters", "session", "warm")

    def __init__(self, image1, image2, bucket, padder, iters=None,
                 session=None, warm=False):
        self.image1 = image1
        self.image2 = image2
        self.bucket = bucket
        self.padder = padder
        # Per-request iteration budget (slot mode honors it, capped at
        # cfg.iters; request mode runs the full cfg.iters in lockstep).
        self.iters = iters
        # Streaming-session frame: ``session`` pins the request to the
        # session's lane, ``warm`` selects the warm-encode admit (carry
        # + forward-warped flow init) over the cold one.  Stateless
        # requests carry (None, False) and behave exactly as before.
        self.session = session
        self.warm = warm
        self.future: Future = Future()
        self.t_submit = time.perf_counter()
        # Trace context captured on the SUBMITTING thread (the router's
        # attempt span, or whatever the caller had open) and carried
        # across the dispatcher to the device worker, which records the
        # per-request queue/pad/device child spans under it.  None when
        # tracing is off or the request is untraced — the device worker
        # then skips span recording entirely.
        self.trace = trace.current()


class _StreamSession:
    """One streaming session's host-side record.  Device state (coords,
    carry) lives in the pinned lane; this object holds the bookkeeping
    the client API and the TTL sweep need.  Field ownership: created by
    ``stream_open`` (caller thread); ``lane`` is written only by the
    device worker at first-pair admission; ``inflight``/``t_last`` are
    written by ``stream_submit`` under the engine's sessions lock and
    cleared by the future's done callback; ``carry_ok`` flips on the
    device worker (stash/warm-encode success or failure)."""

    __slots__ = ("sid", "bucket", "padder", "shape", "iters", "ttl_s",
                 "lane", "frames", "pairs", "warm_pairs", "last_image",
                 "inflight", "t_last", "t_open", "carry_ok", "closed")

    def __init__(self, sid, bucket, padder, shape, iters, ttl_s,
                 first_image):
        self.sid = sid
        self.bucket = bucket
        self.padder = padder
        self.shape = shape
        self.iters = iters
        self.ttl_s = ttl_s
        self.lane: Optional[int] = None
        self.frames = 1          # the opening frame
        self.pairs = 0           # pairs retired successfully
        self.warm_pairs = 0
        self.last_image = first_image
        self.inflight: Optional[Future] = None
        self.t_last = time.time()
        self.t_open = self.t_last
        self.carry_ok = False    # device carry (fmap/ctx) is valid
        self.closed = False


class _Programs:
    """One ``(bucket, lanes)``'s compiled ``encode``/``iter_step`` pair
    plus cached call constants: the all-zeros device-resident state the
    lockstep (request-mode) pipeline restarts from, the all-lanes admit
    mask, the full-budget vector, and the disabled threshold.  The
    compiled programs are pure — ``state0`` is an input, never mutated —
    so one ``_Programs`` serves every batch of its shape."""

    __slots__ = ("enc", "it", "template", "state0", "mask_all",
                 "budget_full", "thr_off", "bucket", "lanes", "wenc",
                 "stash", "carry0")

    def __init__(self, enc, it, template, bucket, lanes, full_iters):
        self.enc = enc
        self.it = it
        self.template = template
        self.state0 = jax.device_put(template)
        self.mask_all = np.ones((lanes,), bool)
        self.budget_full = np.full((lanes,), full_iters, np.int32)
        self.thr_off = np.float32(0.0)
        self.bucket = bucket
        self.lanes = lanes
        # Streaming programs (warm encode / carry stash) + the zero
        # carry: compiled lazily by _get_stream_programs on the first
        # streamed pair — non-streaming engines never build them.
        self.wenc = None
        self.stash = None
        self.carry0 = None


class _SlotPool:
    """Per-bucket slot bookkeeping for continuous batching: the
    device-resident state pytree plus host mirrors of the lane
    assignments.  Touched only by the bucket's dispatcher coroutine and
    the device-worker call it awaits, so it needs no locking."""

    __slots__ = ("progs", "state", "reqs", "budgets", "active_np",
                 "t_admit", "carry", "pins")

    def __init__(self, slots: int):
        self.progs: Optional[_Programs] = None
        self.state = None
        self.reqs: List[Optional[_Request]] = [None] * slots
        self.budgets = np.zeros((slots,), np.int32)
        self.active_np = np.zeros((slots,), bool)
        self.t_admit = [0.0] * slots
        # Streaming: device-resident carry pytree (previous frame's
        # fmap/ctx per lane) and the lane -> session pin map.  A pinned
        # lane is excluded from regular admission even while idle — its
        # coords/carry are the session's warm-start state.
        self.carry = None
        self.pins: Dict[int, _StreamSession] = {}

    def live(self) -> List[_Request]:
        return [r for r in self.reqs if r is not None]

    def reset(self) -> None:
        """Zero the device state after a failed cycle.  Pinned sessions
        stay pinned but their warm-start state is gone — the caller
        marks them cold (``carry_ok = False``) so their next frame
        re-seeds through the cold path."""
        slots = len(self.reqs)
        self.reqs = [None] * slots
        self.budgets = np.zeros((slots,), np.int32)
        self.active_np = np.zeros((slots,), bool)
        if self.progs is not None:
            self.state = self.progs.state0
            self.carry = self.progs.carry0
        for s in self.pins.values():
            s.carry_ok = False


class InferenceEngine:
    """See module docstring.  Lifecycle::

        engine = InferenceEngine(variables, model_cfg, ServeConfig(...))
        engine.start()                      # or: with engine: ...
        engine.warmup([(436, 1024)])        # optional pre-compile
        flow = engine.infer(image1, image2)  # or .submit() -> Future
        print(engine.stats())
        engine.stop()
    """

    def __init__(self, variables, model_cfg: RAFTConfig,
                 cfg: ServeConfig = ServeConfig(), *,
                 registry: Optional[MetricRegistry] = None,
                 sink: Optional[EventSink] = None):
        # Deferred import: evaluate.py pulls the dataset stack, and the
        # dependency is one function (the shared inference overrides).
        from raft_tpu import tuning
        from raft_tpu.evaluate import make_inference_model
        from raft_tpu.serve import slots as slots_mod

        # Per-hardware tuning registry consult ('serve' entries first,
        # 'eval' as fallback): one model serves every bucket, so the
        # lookup is shape-agnostic (nearest/most-recent entry) — the
        # applied knobs and provenance surface in stats()["tuning"].
        # A 'serve' entry may also carry ServeConfig knobs (batching /
        # slots / early_exit_threshold / iters) — applied to whatever
        # the caller left at its dataclass default, so explicit flags
        # always win (raft_tpu/tuning.py precedence).
        cfg, self.serve_tuning_info = tuning.resolve_serve_config(cfg)
        self.cfg = cfg
        _, self.tuning_info = tuning.resolve_config(
            model_cfg, ("serve", "eval"))
        model = make_inference_model(model_cfg,
                                     tuning_kind=("serve", "eval"))
        # The serve hot path is the encode/iter_step program pair
        # (serve/slots.py) for BOTH batching modes — request mode drives
        # them in lockstep so slot mode is bit-identical to it by
        # construction (the parity pin, tests/test_serve_slots.py).
        self._model_cfg = model.config
        self._slots_mod = slots_mod
        self._encode_jit = jax.jit(slots_mod.make_encode_fn(
            self._model_cfg))
        self._iter_jit = jax.jit(slots_mod.make_iter_fn(self._model_cfg))
        # Streaming-session programs (warm encode + carry stash): jit
        # wrappers are cheap to build; nothing traces or compiles until
        # the first streamed pair (_get_stream_programs).
        self._warm_jit = jax.jit(slots_mod.make_warm_encode_fn(
            self._model_cfg))
        self._stash_jit = jax.jit(slots_mod.make_stash_fn(
            self._model_cfg))
        # Keep params resident on device: the executable is called with
        # this exact pytree every batch, so requests never re-upload it.
        self._variables = jax.device_put(variables)
        self._batch_sizes = cfg.resolved_batch_sizes()
        self._max_group = min(cfg.max_batch, self._batch_sizes[-1])

        # Compiled-program cache: keys are (bucket_hw, lanes, program)
        # with program in {"enc", "iter"}; _programs wraps each
        # (bucket, lanes) pair with its cached zero state / lockstep
        # constants.
        self._executables: Dict[tuple, object] = {}
        self._programs: Dict[tuple, _Programs] = {}
        self._compile_lock = threading.Lock()
        # Crash/stop state: ``crashed`` holds the reason string once the
        # device worker hit a fatal (replica-killing) fault — the fleet
        # supervisor polls it through health(); ``_stopped`` makes a
        # post-stop submit() fail with a CLEAR error instead of the
        # ambiguous not-started one.
        self.crashed: Optional[str] = None
        self._stopped = False
        # stop() must be idempotent under CONCURRENT callers: the fleet
        # supervisor restarting a crashed replica can race fleet.stop().
        self._stop_lock = threading.Lock()
        # Seeded per-engine jitter source for the retry backoff ladder
        # (chaos drills must replay the recorded backoff_s values).
        self._retry_rng = np.random.default_rng(0)
        # Retries the most recent _call_device performed (device-worker
        # thread only) — stamped onto traced requests' device spans and
        # the tail-keep trigger for retried batches.
        self._last_retries = 0
        # One registry per engine: every stats/exposition figure below
        # reads these same metric objects (see serve/stats.py), and
        # cli/serve.py renders them at GET /metrics.
        self.registry = registry or MetricRegistry()
        self._sink = sink if sink is not None else EventSink.from_env()
        self.compile_counter = CompileCounter(
            registry=self.registry, metric="raft_serve_compiles_total",
            labeler=lambda key: {"bucket": f"{key[0][0]}x{key[0][1]}",
                                 "batch": str(key[1]),
                                 "program": key[2]})

        self._latency = LatencyRecorder(cfg.latency_window,
                                        registry=self.registry)
        # Iterations each request actually consumed before retiring —
        # the early-exit win is this histogram's p50/p95 dropping below
        # cfg.iters (docs/OBSERVABILITY.md).
        self._iters_used = LatencyRecorder(
            cfg.latency_window, registry=self.registry,
            metric="raft_serve_iters_used",
            help="refinement iterations a request consumed before "
                 "retiring (early exit / per-request budget)",
            scale=1.0, suffix="")
        # Warm/cold split of the same observation: every retirement
        # lands in ONE of these two plus the combined histogram above,
        # so cold-vs-warm convergence is separable downstream
        # (telemetry_summary.py warm_iters_saved_frac).
        self._iters_used_warm = LatencyRecorder(
            cfg.latency_window, registry=self.registry,
            metric="raft_serve_iters_used_warm",
            help="iterations consumed by warm-started streamed frames",
            scale=1.0, suffix="")
        self._iters_used_cold = LatencyRecorder(
            cfg.latency_window, registry=self.registry,
            metric="raft_serve_iters_used_cold",
            help="iterations consumed by cold-started requests "
                 "(stateless pairs and session first pairs)",
            scale=1.0, suffix="")
        self._counters = Counters(registry=self.registry)
        # Streaming-session registry: sid -> _StreamSession.  Guarded
        # by _sessions_lock (caller threads open/submit/close; the
        # device worker pins lanes and the TTL sweep evicts).
        self._sessions: Dict[str, _StreamSession] = {}
        self._sessions_lock = threading.Lock()
        self._sessions_gauge = self.registry.gauge(
            "raft_serve_sessions_open",
            "streaming sessions currently open")
        self._stream_frames = self.registry.counter(
            "raft_serve_stream_frames_total",
            "streamed frames received (session mode)")
        self._stream_evicted = self.registry.counter(
            "raft_serve_stream_evictions_total",
            "streaming sessions evicted by the idle TTL")
        # Flow-quality scoring (obs/quality.py): built ONLY when the
        # sample rate is nonzero — at 0 the hot path carries no
        # monitor, no extra device fetch in _iter_slots, and no
        # quality program (the zero-overhead pin,
        # tests/test_quality.py).
        self._quality = None
        if cfg.quality_sample_rate > 0:
            from raft_tpu.obs import quality as quality_mod

            self._quality = quality_mod.QualityMonitor(
                registry=self.registry, sink=self._sink,
                sample_rate=cfg.quality_sample_rate,
                cycle=cfg.quality_cycle,
                drift_reference=cfg.quality_drift_reference,
                drift_window=cfg.quality_drift_window,
                drift_threshold=cfg.quality_drift_threshold,
                reservoir=cfg.latency_window)
        # Compile-time work accounting, keyed by the SAME (bucket,
        # lanes, prog) ledger keys as _executables: stamped once in
        # _get_programs, read back by spans/stats with zero device
        # work (obs/cost.py; docs/OBSERVABILITY.md "Cost model").
        self.cost_book = cost_mod.CostBook(registry=self.registry,
                                           sink=self._sink)
        # SLO tracking (obs/slo.py): specs are constructed ONLY for the
        # objectives the config enables — with every knob at 0 (the
        # default) there is no tracker, no per-request deque append,
        # and the hot path is byte-identical to before (the same
        # conditional-construction pattern as the quality monitor).
        self._slo = None
        # Last measured iteration MFU (None until a cost-stamped device
        # call completes on a known-peak device) — an autoscaler signal
        # (load_signals), not an SLO.
        self._last_mfu: Optional[float] = None
        slo_specs = []
        if (cfg.slo_availability_target > 0 or cfg.slo_latency_target_ms
                > 0 or cfg.slo_quality_bound > 0 or cfg.slo_mfu_floor
                > 0):
            from raft_tpu.obs import slo as slo_mod

            policy = slo_mod.scaled_policy(cfg.slo_window_s)
            if cfg.slo_availability_target > 0:
                slo_specs.append(slo_mod.SLOSpec(
                    "availability", cfg.slo_availability_target,
                    "non-error request fraction", windows=policy))
            if cfg.slo_latency_target_ms > 0:
                slo_specs.append(slo_mod.SLOSpec(
                    "latency", 0.99,
                    f"requests under {cfg.slo_latency_target_ms}ms",
                    windows=policy))
            if cfg.slo_quality_bound > 0 and cfg.quality_sample_rate > 0:
                slo_specs.append(slo_mod.SLOSpec(
                    "quality", 0.99,
                    f"sampled photometric proxy <= "
                    f"{cfg.slo_quality_bound}", windows=policy))
            if cfg.slo_mfu_floor > 0 and (
                    cost_mod.peak_spec().tflops or 0):
                # MFU floor only when PEAK_SPECS knows this device's
                # peak — on cpu/unknown kinds MFU is undefined and the
                # spec is silently skipped.
                slo_specs.append(slo_mod.SLOSpec(
                    "mfu", 0.9, f"iter MFU >= {cfg.slo_mfu_floor}",
                    windows=policy))
            if slo_specs:
                self._slo = slo_mod.SLOTracker(
                    slo_specs, registry=self.registry, sink=self._sink)
        # Incident correlation (obs/incident.py): one manager per
        # telemetry stream — a fleet builds its engines with
        # incidents=False and owns the manager itself (the engines
        # share its sink, and N observers would open N incidents for
        # one cascade).
        self._incidents = None
        if cfg.incidents:
            from raft_tpu.obs import incident as incident_mod

            self._incidents = incident_mod.IncidentManager(
                registry=self.registry,
                window_s=cfg.incident_window_s,
                quiet_close_s=cfg.incident_quiet_s,
                cooldown_s=cfg.incident_cooldown_s)
            self._incidents.attach(self._sink)
            self._incidents.recorder.add_provider("engine_stats",
                                                  self.stats)
            self._incidents.recorder.add_provider(
                "serve_config", lambda: dataclasses.asdict(self.cfg))
        self._pending_gauge = self.registry.gauge(
            "raft_serve_pending_requests", "requests in flight")
        self.registry.add_collect_hook(self._collect_pending)

        self._pending = 0
        self._pending_lock = threading.Lock()
        # Serve-side stall signal: perf_counter of the last COMPLETED
        # device batch (success or failure — either proves the device
        # worker is alive) and of start(); health() derives readiness.
        # _pending_since marks the 0 -> nonzero transition: a stall is
        # measured from when the waiting work ARRIVED, never from a
        # batch completed before an idle stretch (else a replica idle
        # longer than stall_timeout_s reads as stalled the instant a
        # request lands, and the fleet supervisor would restart it).
        self._last_batch_done: Optional[float] = None
        self._pending_since: Optional[float] = None
        self._t_started: Optional[float] = None
        self._stale_gauge = self.registry.gauge(
            "raft_serve_seconds_since_last_batch",
            "seconds since the last completed device batch (refreshed "
            "at scrape; absent before the first batch)")

        # Device-batch ordinal (1-based; device-worker thread only) —
        # the `device_err@batch=N` chaos trigger context.
        self._batch_seq = 0

        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread: Optional[threading.Thread] = None
        self._queues: Dict[tuple, asyncio.Queue] = {}
        self._dispatchers: Dict[tuple, asyncio.Task] = {}
        self._device_pool = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="raft-serve-device")
        self._accepting = False

        # AOT warm-start (raft_tpu/serve/aot.py): import serialized
        # executables so the first request compiles nothing.  The
        # fingerprint binds artifact to (model config, variables tree
        # shapes/dtypes, iters) — the executable takes variables as a
        # runtime argument, so the same artifact warm-starts restarted
        # replicas AND rolling-update engines carrying NEW weights.
        from raft_tpu.serve import aot as aot_mod

        self._aot_fingerprint = aot_mod.model_fingerprint(
            model_cfg, self._variables, cfg.iters)
        self.aot_info: dict = {"dir": cfg.aot_dir, "imported": 0,
                               "ok": None}
        if cfg.aot_dir:
            self._preload_aot(cfg.aot_dir)

    # ------------------------------------------------------------------
    # AOT warm-start artifacts
    # ------------------------------------------------------------------

    def _preload_aot(self, directory: str) -> None:
        from raft_tpu.serve import aot as aot_mod

        try:
            exes = aot_mod.import_executables(
                directory, fingerprint=self._aot_fingerprint)
        except aot_mod.AOTImportError as e:
            # A warm-start MISS, not a serve failure: log it and fall
            # back to lazy JIT compiles.
            self.aot_info.update(ok=False, error=str(e))
            self._sink.emit("aot_import_error", dir=directory,
                            error=str(e)[:300])
            return
        with self._compile_lock:
            self._executables.update(exes)
        self.aot_info.update(ok=True, imported=len(exes))
        self._sink.emit("aot_import", dir=directory, keys=len(exes))

    def export_aot(self, directory: str) -> dict:
        """Serialize every compiled ``(bucket, lanes, program)``
        executable into ``directory`` (atomic per file) so a fresh
        engine built with ``ServeConfig(aot_dir=directory)`` serves its
        first request with zero compiles.  Returns the manifest.
        Raises when the cache is empty (warm up first)."""
        from raft_tpu.serve import aot as aot_mod

        with self._compile_lock:
            exes = dict(self._executables)
        manifest = aot_mod.export_executables(
            exes, directory, fingerprint=self._aot_fingerprint)
        self._sink.emit("aot_export", dir=directory,
                        keys=len(manifest["keys"]))
        return manifest

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    def start(self) -> "InferenceEngine":
        if self._thread is not None:
            raise RuntimeError("engine already started")
        if self._stopped:
            raise RuntimeError(
                "engine stopped — engines are single-use; build a new "
                "InferenceEngine (the fleet supervisor does this on "
                "restart)")
        self._loop = asyncio.new_event_loop()
        started = threading.Event()

        def run():
            asyncio.set_event_loop(self._loop)
            self._loop.call_soon(started.set)
            self._loop.run_forever()

        self._thread = threading.Thread(target=run, name="raft-serve-loop",
                                        daemon=True)
        self._thread.start()
        started.wait()
        self._counters.mark_started()
        self._t_started = time.perf_counter()
        self._accepting = True
        return self

    def stop(self, drain: bool = True, timeout: float = 60.0) -> None:
        """Stop accepting, optionally drain in-flight work, shut down.

        Queued requests that cannot complete (``drain=False`` or drain
        timeout) fail with ``RuntimeError('engine stopped')``."""
        with self._stop_lock:
            self._stop_locked(drain, timeout)

    def _stop_locked(self, drain: bool, timeout: float) -> None:
        if self._thread is None:
            self._stopped = True
            return
        self._accepting = False
        self._stopped = True
        if drain:
            deadline = time.perf_counter() + timeout
            while time.perf_counter() < deadline:
                with self._pending_lock:
                    if self._pending == 0:
                        break
                time.sleep(0.005)

        async def _cancel_all():
            tasks = list(self._dispatchers.values())
            for t in tasks:
                t.cancel()
            await asyncio.gather(*tasks, return_exceptions=True)

        asyncio.run_coroutine_threadsafe(
            _cancel_all(), self._loop).result(timeout=10)
        self._device_pool.shutdown(wait=True)
        self._loop.call_soon_threadsafe(self._loop.stop)
        self._thread.join(timeout=10)
        self._loop.close()
        self._thread = None
        self._dispatchers.clear()
        self._queues.clear()
        if self._incidents is not None:
            # Finalize: an incident still open at shutdown closes with
            # its bundle written (close_reason="finalized").
            self._incidents.close()

    def __enter__(self) -> "InferenceEngine":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # ------------------------------------------------------------------
    # client API (any thread)
    # ------------------------------------------------------------------

    def submit(self, image1, image2,
               iters: Optional[int] = None) -> Future:
        """Enqueue one frame pair; returns a Future resolving to the
        ``(H, W, 2)`` float32 flow at the ORIGINAL resolution.

        ``iters`` is an optional per-request refinement budget, capped
        at ``cfg.iters``; honored in slot mode (request mode runs every
        lane to ``cfg.iters`` in lockstep — the parity oracle ignores
        per-request budgets by design).

        Raises :class:`QueueFullError` immediately (never blocks) when
        ``max_queue`` requests are already in flight."""
        if iters is not None and int(iters) < 1:
            raise ValueError(f"iters must be >= 1, got {iters}")
        if not self._accepting:
            # Fail FAST with the precise lifecycle state — a client
            # racing stop() must get an immediate, classifiable error
            # (the fleet router treats it as a failover signal), never
            # a hang on a dead loop.
            if self.crashed:
                raise RuntimeError(f"engine crashed: {self.crashed}")
            if self._stopped:
                raise RuntimeError(
                    "engine stopped — engines are single-use; build a "
                    "new InferenceEngine or route to a live replica")
            raise RuntimeError("engine not started (or stopping)")
        im1 = np.asarray(image1, dtype=np.float32)
        im2 = np.asarray(image2, dtype=np.float32)
        if im1.ndim != 3 or im1.shape[-1] != 3 or im1.shape != im2.shape:
            raise ValueError(
                f"expected two matching (H, W, 3) images, got "
                f"{im1.shape} and {im2.shape}")
        h, w = im1.shape[:2]
        bucket = bucket_hw(h, w, self.cfg.bucket_multiple, self.cfg.buckets)
        padder = InputPadder((h, w), mode=self.cfg.pad_mode, target=bucket)
        with self._pending_lock:
            if self._pending >= self.cfg.max_queue:
                self._counters.add_rejected()
                raise QueueFullError(
                    f"{self._pending} requests in flight >= max_queue="
                    f"{self.cfg.max_queue}; retry after "
                    f"{self.cfg.retry_after_s:g}s",
                    queue_depth=self._pending,
                    retry_after_s=self.cfg.retry_after_s)
            if self._pending == 0:
                self._pending_since = time.perf_counter()
            self._pending += 1
        req = _Request(im1, im2, bucket, padder,
                       None if iters is None else int(iters))
        try:
            self._loop.call_soon_threadsafe(self._enqueue, req)
        except RuntimeError:  # loop closed under our feet (stop race)
            with self._pending_lock:
                self._pending -= 1
            raise RuntimeError("engine stopped")
        return req.future

    def infer(self, image1, image2, timeout: Optional[float] = None,
              iters: Optional[int] = None) -> np.ndarray:
        """Blocking convenience wrapper around :meth:`submit`."""
        return self.submit(image1, image2,
                           iters=iters).result(timeout=timeout)

    # ------------------------------------------------------------------
    # client API — streaming sessions (any thread)
    # ------------------------------------------------------------------

    def _check_accepting(self) -> None:
        if self._accepting:
            return
        if self.crashed:
            raise RuntimeError(f"engine crashed: {self.crashed}")
        if self._stopped:
            raise RuntimeError(
                "engine stopped — engines are single-use; build a "
                "new InferenceEngine or route to a live replica")
        raise RuntimeError("engine not started (or stopping)")

    def stream_open(self, session_id: str, image, *,
                    iters: Optional[int] = None,
                    ttl_s: Optional[float] = None) -> dict:
        """Open a streaming session seeded with its first frame.

        No device work happens here — the frame is held host-side; the
        first :meth:`stream_submit` forms the session's first (cold)
        pair, which pins a lane in the bucket's slot pool.  ``iters``
        is the per-session refinement budget (capped at ``cfg.iters``);
        ``ttl_s`` overrides ``cfg.stream_ttl_s``.  Raises
        :class:`QueueFullError` when ``max_sessions`` sessions are
        already open (after sweeping expired ones)."""
        self._check_accepting()
        if self.cfg.batching != "slot":
            raise ValueError(
                "streaming sessions require batching='slot' (a session "
                "is a pinned lane in the slot pool)")
        if iters is not None and int(iters) < 1:
            raise ValueError(f"iters must be >= 1, got {iters}")
        if ttl_s is not None and float(ttl_s) <= 0:
            raise ValueError(f"ttl_s must be > 0, got {ttl_s}")
        im = np.asarray(image, dtype=np.float32)
        if im.ndim != 3 or im.shape[-1] != 3:
            raise ValueError(f"expected an (H, W, 3) image, got "
                             f"{im.shape}")
        h, w = im.shape[:2]
        bucket = bucket_hw(h, w, self.cfg.bucket_multiple,
                           self.cfg.buckets)
        padder = InputPadder((h, w), mode=self.cfg.pad_mode,
                             target=bucket)
        sess = _StreamSession(
            str(session_id), bucket, padder, (h, w),
            None if iters is None else int(iters),
            float(ttl_s) if ttl_s is not None else self.cfg.stream_ttl_s,
            im)
        with self._sessions_lock:
            evicted = self._sweep_unpinned_locked(time.time())
            if sess.sid in self._sessions:
                raise ValueError(f"session {sess.sid!r} already open")
            if len(self._sessions) >= self.cfg.max_sessions:
                self._counters.add_rejected()
                raise QueueFullError(
                    f"{len(self._sessions)} sessions open >= "
                    f"max_sessions={self.cfg.max_sessions}",
                    queue_depth=len(self._sessions),
                    retry_after_s=self.cfg.retry_after_s)
            self._sessions[sess.sid] = sess
            self._sessions_gauge.set(len(self._sessions))
        self._emit_evictions(evicted)
        self._sink.emit("stream_open", sid=sess.sid,
                        bucket=f"{bucket[0]}x{bucket[1]}",
                        iters=sess.iters, ttl_s=sess.ttl_s)
        return {"session": sess.sid, "frame": 0,
                "bucket": list(bucket)}

    def stream_submit(self, session_id: str, image) -> Future:
        """Stream the next frame into an open session; returns a Future
        resolving to the flow from the PREVIOUS frame to this one.

        Only this one new image crosses the wire/PCIe: the previous
        frame's features and flow are already device-resident in the
        session's lane (warm path) or held host-side for the first
        pair (cold path).  One frame may be in flight per session —
        streaming is ordered by construction."""
        self._check_accepting()
        im = np.asarray(image, dtype=np.float32)
        with self._sessions_lock:
            sess = self._sessions.get(str(session_id))
            if sess is None:
                raise ValueError(f"unknown session {session_id!r} "
                                 "(expired, closed, or never opened)")
            if im.shape != sess.last_image.shape:
                raise ValueError(
                    f"frame shape {im.shape} != session shape "
                    f"{sess.last_image.shape} (a session is fixed to "
                    "one resolution)")
            if sess.inflight is not None and not sess.inflight.done():
                raise ValueError(
                    f"session {sess.sid!r} already has a frame in "
                    "flight (stream frames sequentially)")
            warm = sess.carry_ok
            budget = sess.iters
            if warm and self.cfg.stream_warm_iters is not None:
                budget = self.cfg.stream_warm_iters
            with self._pending_lock:
                if self._pending >= self.cfg.max_queue:
                    self._counters.add_rejected()
                    raise QueueFullError(
                        f"{self._pending} requests in flight >= "
                        f"max_queue={self.cfg.max_queue}; retry after "
                        f"{self.cfg.retry_after_s:g}s",
                        queue_depth=self._pending,
                        retry_after_s=self.cfg.retry_after_s)
                if self._pending == 0:
                    self._pending_since = time.perf_counter()
                self._pending += 1
            req = _Request(sess.last_image, im, sess.bucket, sess.padder,
                           None if budget is None else int(budget),
                           session=sess, warm=warm)
            sess.last_image = im
            sess.frames += 1
            sess.t_last = time.time()
            sess.inflight = req.future
            # Stamped for the blocking facades (stream_ingest / the
            # HTTP route): which pair this Future resolves and whether
            # it took the warm path — decided here, race-free.
            req.future.stream_frame = sess.frames - 1
            req.future.stream_warm = warm
            self._stream_frames.inc()

        def _clear_inflight(_fut, sess=sess):
            with self._sessions_lock:
                if sess.inflight is _fut:
                    sess.inflight = None
                sess.t_last = time.time()

        req.future.add_done_callback(_clear_inflight)
        try:
            self._loop.call_soon_threadsafe(self._enqueue, req)
        except RuntimeError:  # loop closed under our feet (stop race)
            with self._pending_lock:
                self._pending -= 1
            raise RuntimeError("engine stopped")
        return req.future

    def stream_frame(self, session_id: str, image,
                     timeout: Optional[float] = None) -> np.ndarray:
        """Blocking convenience wrapper around :meth:`stream_submit`."""
        return self.stream_submit(session_id,
                                  image).result(timeout=timeout)

    def stream_ingest(self, session_id: str, image, *,
                      iters: Optional[int] = None,
                      ttl_s: Optional[float] = None,
                      timeout: Optional[float] = None) -> dict:
        """Open-on-first-use blocking facade (the ``POST
        /v1/stream/{id}`` semantics): an unknown session id opens the
        session with ``image`` as frame 0 (``flow=None``); a known one
        streams the frame and blocks for its flow.  Returns
        ``{"session", "frame", "warm", "flow"}``."""
        sid = str(session_id)
        with self._sessions_lock:
            known = sid in self._sessions
        if not known:
            ack = self.stream_open(sid, image, iters=iters,
                                   ttl_s=ttl_s)
            return {"session": sid, "frame": ack["frame"],
                    "warm": False, "flow": None}
        fut = self.stream_submit(sid, image)
        flow = fut.result(timeout=timeout)
        return {"session": sid, "frame": fut.stream_frame,
                "warm": fut.stream_warm, "flow": flow}

    def stream_close(self, session_id: str) -> dict:
        """Close a session and release its registry entry; the pinned
        lane returns to the free pool at the dispatcher's next sweep.
        Returns the session summary."""
        with self._sessions_lock:
            sess = self._sessions.get(str(session_id))
            if sess is None:
                raise ValueError(f"unknown session {session_id!r} "
                                 "(expired, closed, or never opened)")
            if sess.inflight is not None and not sess.inflight.done():
                raise ValueError(
                    f"session {sess.sid!r} has a frame in flight — "
                    "wait for it before closing")
            del self._sessions[sess.sid]
            sess.closed = True
            self._sessions_gauge.set(len(self._sessions))
        summary = {"session": sess.sid, "frames": sess.frames,
                   "pairs": sess.pairs, "warm_pairs": sess.warm_pairs}
        self._sink.emit("stream_close", sid=sess.sid,
                        frames=sess.frames, pairs=sess.pairs,
                        warm_pairs=sess.warm_pairs)
        return summary

    def _sweep_unpinned_locked(self, now: float) -> list:
        """Evict expired sessions that never pinned a lane (opened,
        then abandoned) — pinned ones are swept by their bucket's
        dispatcher, which owns the lane.  Caller holds
        ``_sessions_lock``; events are emitted by the caller OUTSIDE
        the lock (:meth:`_emit_evictions`)."""
        out = []
        for sid, s in list(self._sessions.items()):
            if s.lane is not None:
                continue
            if s.inflight is not None and not s.inflight.done():
                continue
            if now - s.t_last > s.ttl_s:
                del self._sessions[sid]
                s.closed = True
                out.append(s)
        if out:
            self._sessions_gauge.set(len(self._sessions))
        return out

    def _emit_evictions(self, evicted: list) -> None:
        for s in evicted:
            self._stream_evicted.inc()
            self._sink.emit(
                "stream_evict", sid=s.sid,
                bucket=f"{s.bucket[0]}x{s.bucket[1]}",
                lane=-1 if s.lane is None else int(s.lane),
                idle_s=round(time.time() - s.t_last, 3),
                ttl_s=s.ttl_s)

    def warmup(self, image_shapes: Sequence[Tuple[int, int]],
               batch_sizes: Optional[Sequence[int]] = None) -> List[tuple]:
        """Pre-compile the ``(bucket, lanes)`` program pairs for the
        given raw image ``(H, W)`` shapes (rounded through the same
        bucket policy as live traffic), so first requests don't pay the
        compile.  Slot mode compiles one pair per bucket at
        ``cfg.slots`` lanes (its only batch shape); request mode one
        pair per ``(bucket, batch_size)``.  Returns the list of
        ``(bucket, lanes)`` keys compiled or already present."""
        keys = []
        for (h, w) in image_shapes:
            bucket = bucket_hw(h, w, self.cfg.bucket_multiple,
                               self.cfg.buckets)
            if self.cfg.batching == "slot":
                self._get_programs(bucket, self.cfg.slots)
                keys.append((bucket, self.cfg.slots))
            else:
                for bs in (batch_sizes or self._batch_sizes):
                    self._get_executable(bucket, int(bs))
                    keys.append((bucket, int(bs)))
        return keys

    def compiled_keys(self) -> List[tuple]:
        """``(bucket, lanes, program)`` keys currently in the compile
        cache (compiled here or AOT-imported) — what :meth:`export_aot`
        would serialize."""
        with self._compile_lock:
            return sorted(self._executables)

    def _collect_pending(self, _reg) -> None:
        with self._pending_lock:
            pending = self._pending
            last = self._last_batch_done
        self._pending_gauge.set(pending)
        if last is not None:
            self._stale_gauge.set(time.perf_counter() - last)

    def health(self) -> dict:
        """Readiness snapshot (``GET /v1/healthz``).

        Liveness alone ("the HTTP thread answers") misses the real
        failure mode: a wedged device worker with requests piling up.
        Not-ready ⇔ accepting is off, OR requests are pending and no
        device batch has completed within ``stall_timeout_s`` of the
        NEWEST of {last completed batch, when the pending backlog
        started, start()} — the backlog term keeps a long-idle replica
        from reading as stalled the instant traffic resumes."""
        now = time.perf_counter()
        with self._pending_lock:
            pending = self._pending
            last = self._last_batch_done
            pending_since = self._pending_since
        since = None if last is None else now - last
        stalled = False
        if self.cfg.stall_timeout_s and pending > 0:
            refs = [t for t in (last, pending_since, self._t_started)
                    if t is not None]
            stalled = (bool(refs)
                       and now - max(refs) > self.cfg.stall_timeout_s)
        return {
            "ready": bool(self._accepting and not stalled
                          and not self.crashed),
            "accepting": bool(self._accepting),
            "stalled": stalled,
            "crashed": self.crashed,
            "pending": pending,
            "seconds_since_last_batch":
                None if since is None else round(since, 3),
            "stall_timeout_s": self.cfg.stall_timeout_s,
        }

    def metrics_text(self) -> str:
        """Prometheus text exposition of the engine registry (the same
        counters/histograms ``stats()`` reads — the surfaces cannot
        drift)."""
        return self.registry.render_prometheus()

    def queue_capacity(self) -> int:
        """This engine's admission-queue capacity — the router's spill
        math and the fleet autoscaler read it through the replica
        facade, so heterogeneous fleets (a remote replica with a
        different ``max_queue``) scale each replica by its OWN
        capacity, not a shared config's."""
        return self.cfg.max_queue

    def load_signals(self) -> dict:
        """Cheap load snapshot for the fleet autoscaler — reads only
        locks/atomics already maintained on the hot path (no device
        work, safe at the supervisor's poll cadence).

        Keys: ``pending`` / ``max_queue`` / ``queue_frac`` (admission
        pressure), ``occupancy`` (slot utilization), ``burn_rate``
        (worst SLO burn, 0.0 when SLOs are off), ``mfu`` (last measured
        iteration MFU, None before a known-peak measurement), and
        ``latency_p95_ms`` over the recent window."""
        with self._pending_lock:
            pending = self._pending
        cap = max(int(self.cfg.max_queue), 1)
        burn = 0.0
        if self._slo is not None:
            for snap in self._slo.snapshot().values():
                if isinstance(snap, dict):
                    burn = max(burn, float(snap.get("burn_rate") or 0.0))
        counters = self._counters.snapshot(
            max(jax.local_device_count(), 1))
        lat = self._latency.snapshot()
        return {
            "pending": pending,
            "max_queue": self.cfg.max_queue,
            "queue_frac": round(pending / cap, 4),
            "occupancy": float(counters.get("occupancy") or 0.0),
            "burn_rate": round(burn, 4),
            "mfu": self._last_mfu,
            "latency_p95_ms": float(lat.get("p95_ms") or 0.0),
        }

    def quality_drift(self) -> Optional[dict]:
        """Per-proxy drift-detector state (``None`` when quality
        scoring is disabled) — the fleet supervisor polls this to
        surface ``fleet_quality_drift`` events."""
        if self._quality is None:
            return None
        return self._quality.drift_snapshot()

    def stats(self) -> dict:
        """One JSON-able snapshot: counters, latency percentiles over the
        recent window, per-``(bucket, batch)`` compile counts."""
        out = self._counters.snapshot(max(jax.local_device_count(), 1))
        with self._pending_lock:
            out["pending"] = self._pending
        out["max_queue"] = self.cfg.max_queue
        out["latency_ms"] = self._latency.snapshot()
        out["batching"] = self.cfg.batching
        out["iters_used"] = self._iters_used.snapshot()
        out["iters_used_warm"] = self._iters_used_warm.snapshot()
        out["iters_used_cold"] = self._iters_used_cold.snapshot()
        # Streaming-session snapshot; sweeping lane-less expired
        # sessions here keeps the gauge honest even when no frames
        # arrive to trigger the open-path sweep.
        with self._sessions_lock:
            expired = self._sweep_unpinned_locked(time.time())
        self._emit_evictions(expired)
        with self._sessions_lock:
            out["sessions"] = {
                "open": len(self._sessions),
                "pinned": sum(1 for s in self._sessions.values()
                              if s.lane is not None),
                "frames_total": int(self._stream_frames.value()),
                "evicted_total": int(self._stream_evicted.value()),
            }
        out["compiles"] = {
            f"{hw[0]}x{hw[1]}/b{bs}/{prog}": n
            for (hw, bs, prog), n in sorted(
                self.compile_counter.counts().items())
        }
        out["num_buckets"] = len(
            {k[0] for k in self.compile_counter.counts()})
        # Tuning-registry provenance (raft_tpu/tuning.py): which knobs
        # this replica autotuned, so a fleet operator can tell a tuned
        # replica from one running hand-rolled defaults.
        out["tuning"] = dict(self.tuning_info.stamp(),
                             applied=dict(self.tuning_info.applied),
                             serve_applied=dict(
                                 self.serve_tuning_info.applied))
        # AOT warm-start provenance: how many executables this engine
        # imported instead of compiling (docs/SERVING.md fleet section).
        out["aot"] = dict(self.aot_info)
        # Flow-quality snapshot (obs/quality.py): per-proxy p50/p95 +
        # drift-detector state when sampling is on; a bare disabled
        # marker otherwise, so clients can branch without a key check.
        out["quality"] = (self._quality.snapshot()
                          if self._quality is not None
                          else {"enabled": False})
        # Compile-time work accounting per ledger key (obs/cost.py):
        # the `raft_tpu cost` table and bench_serve's per-pair stamps
        # read this — flops/bytes/roofline, captured once at compile.
        out["cost"] = {
            f"{hw[0]}x{hw[1]}/b{bs}/{prog}": c.as_record()
            for (hw, bs, prog), c in sorted(
                self.cost_book.table().items())
        }
        # SLO / incident snapshots (obs/slo.py, obs/incident.py): live
        # burn rates and the open-incident id ride /v1/stats; disabled
        # markers keep the key shape stable for clients.
        out["slo"] = (self._slo.snapshot() if self._slo is not None
                      else {"enabled": False})
        out["incidents"] = (self._incidents.snapshot()
                            if self._incidents is not None
                            else {"enabled": False})
        return out

    # ------------------------------------------------------------------
    # internals — event-loop thread
    # ------------------------------------------------------------------

    def _enqueue(self, req: _Request) -> None:
        q = self._queues.get(req.bucket)
        if q is None:
            q = self._queues[req.bucket] = asyncio.Queue()
            runner = (self._slot_dispatcher
                      if self.cfg.batching == "slot"
                      else self._dispatcher)
            self._dispatchers[req.bucket] = self._loop.create_task(
                runner(req.bucket, q))
        q.put_nowait(req)

    async def _dispatcher(self, bucket: tuple, q: asyncio.Queue) -> None:
        """Coalesce one bucket's requests into micro-batches forever.

        The device call is NOT awaited: the single worker thread
        serializes device work, and not awaiting lets the next batch
        fill while the previous one runs (pipelining the host-side
        pad/stack with device execution)."""
        batch: List[_Request] = []
        try:
            while True:
                batch = [await q.get()]
                deadline = self._loop.time() + self.cfg.max_wait_ms / 1e3
                while len(batch) < self._max_group:
                    wait = deadline - self._loop.time()
                    if wait <= 0:
                        break
                    try:
                        batch.append(
                            await asyncio.wait_for(q.get(), timeout=wait))
                    except asyncio.TimeoutError:
                        break
                fut = self._loop.run_in_executor(
                    self._device_pool, self._run_batch, bucket, batch)
                batch = []
                fut.add_done_callback(lambda f: f.exception())
        except asyncio.CancelledError:
            leftovers = batch
            while not q.empty():
                leftovers.append(q.get_nowait())
            for r in leftovers:
                if not r.future.done():
                    r.future.set_exception(RuntimeError("engine stopped"))
            if leftovers:
                with self._pending_lock:
                    self._pending -= len(leftovers)
            raise

    async def _slot_dispatcher(self, bucket: tuple,
                               q: asyncio.Queue) -> None:
        """Continuous-batching dispatcher (``batching="slot"``): one
        persistent ``cfg.slots``-lane batch per bucket.  Each cycle
        admits waiting requests into free slots and runs one
        ``iter_step`` over the actives; the device work runs on the
        single worker thread and IS awaited — unlike the request-mode
        dispatcher there is nothing to pipeline (the next cycle's
        admission depends on which lanes just retired), and awaiting
        makes the pool/waiting-list single-owner (no locking).  The
        loop blocks on the queue only when no lane is live and nothing
        waits — otherwise it spins cycles, which is the point: device
        work at iteration granularity."""
        pool = _SlotPool(self.cfg.slots)
        waiting: List[_Request] = []
        try:
            while True:
                if not waiting and not pool.live():
                    if pool.pins:
                        # Idle but holding pinned session lanes: wake
                        # at the earliest possible TTL expiry so the
                        # sweep in _slot_cycle can evict and return
                        # lanes to the free pool.
                        try:
                            waiting.append(await asyncio.wait_for(
                                q.get(),
                                timeout=self._pin_poll_s(pool)))
                        except asyncio.TimeoutError:
                            pass
                    else:
                        waiting.append(await q.get())
                while True:
                    try:
                        waiting.append(q.get_nowait())
                    except asyncio.QueueEmpty:
                        break
                await self._loop.run_in_executor(
                    self._device_pool, self._slot_cycle, bucket, pool,
                    waiting)
        except asyncio.CancelledError:
            leftovers = list(waiting) + pool.live()
            waiting.clear()
            pool.reset()
            while not q.empty():
                leftovers.append(q.get_nowait())
            for r in leftovers:
                if not r.future.done():
                    r.future.set_exception(RuntimeError("engine stopped"))
            if leftovers:
                with self._pending_lock:
                    self._pending -= len(leftovers)
            raise

    def _pin_poll_s(self, pool: _SlotPool) -> float:
        """Idle-poll interval while lanes are pinned: sleep until the
        earliest session TTL could expire, clamped to [0.05, 1.0] s."""
        now = time.time()
        nxt = min((s.t_last + s.ttl_s for s in pool.pins.values()),
                  default=now + 1.0)
        return float(min(max(nxt - now, 0.05), 1.0))

    # ------------------------------------------------------------------
    # internals — device-worker thread
    # ------------------------------------------------------------------

    def _get_programs(self, bucket: tuple, lanes: int) -> _Programs:
        """The compiled ``encode``/``iter_step`` pair for ``(bucket,
        lanes)`` — compiled (or AOT-imported) exactly once per key, as
        two explicit, counted events (``program`` label ``enc`` /
        ``iter``).  Both batching modes call through here, so slot and
        request mode can never run different device code."""
        pkey = (bucket, lanes)
        with self._compile_lock:
            progs = self._programs.get(pkey)
            if progs is not None:
                return progs
            H, W = bucket
            template = self._slots_mod.state_template(
                self._model_cfg, self._variables, lanes, bucket)
            state_spec = jax.tree_util.tree_map(
                lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
                template)
            im = jax.ShapeDtypeStruct((lanes, H, W, 3), jnp.float32)
            mask = jax.ShapeDtypeStruct((lanes,), jnp.bool_)
            budg = jax.ShapeDtypeStruct((lanes,), jnp.int32)
            thr = jax.ShapeDtypeStruct((), jnp.float32)
            enc = self._executables.get((bucket, lanes, "enc"))
            if enc is None:
                enc = self._encode_jit.lower(
                    self._variables, im, im, state_spec, mask,
                    budg).compile()
                self._executables[(bucket, lanes, "enc")] = enc
                self.compile_counter.record((bucket, lanes, "enc"))
            it = self._executables.get((bucket, lanes, "iter"))
            if it is None:
                it = self._iter_jit.lower(
                    self._variables, state_spec, thr).compile()
                self._executables[(bucket, lanes, "iter")] = it
                self.compile_counter.record((bucket, lanes, "iter"))
            # Stamp compile-time cost under the executables' own ledger
            # keys — pure host metadata off the Compiled objects (works
            # for AOT-imported executables too; never runs the program).
            for prog, exe in (("enc", enc), ("iter", it)):
                key = (bucket, lanes, prog)
                if self.cost_book.get(key) is None:
                    self.cost_book.stamp(key, cost_mod.program_cost(
                        exe, program=f"serve_{prog}_{H}x{W}_b{lanes}",
                        pairs_per_call=lanes))
            progs = _Programs(enc, it, template, bucket, lanes,
                              self.cfg.iters)
            self._programs[pkey] = progs
            return progs

    def _get_stream_programs(self, bucket: tuple,
                             lanes: int) -> _Programs:
        """The streaming extras for ``(bucket, lanes)`` — the zero
        carry plus compiled ``stash`` (frame-2 feature snapshot) and
        ``wenc`` (warm encode) programs — filled into the bucket's
        ``_Programs`` on the first streamed pair, so non-streaming
        traffic never compiles them.  Same compile-once,
        AOT-importable, cost-stamped discipline as :meth:`_get_programs`
        (``wenc``'s smaller ``flops_per_pair`` vs ``enc`` IS the
        per-frame encoder saving, visible in ``stats()["cost"]``)."""
        progs = self._get_programs(bucket, lanes)
        if progs.wenc is not None:
            return progs
        with self._compile_lock:
            if progs.wenc is not None:
                return progs
            H, W = bucket
            carry_tpl = self._slots_mod.carry_template(
                self._model_cfg, self._variables, lanes, bucket)
            carry_spec = jax.tree_util.tree_map(
                lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
                carry_tpl)
            state_spec = jax.tree_util.tree_map(
                lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
                progs.template)
            im = jax.ShapeDtypeStruct((lanes, H, W, 3), jnp.float32)
            mask = jax.ShapeDtypeStruct((lanes,), jnp.bool_)
            budg = jax.ShapeDtypeStruct((lanes,), jnp.int32)
            stash = self._executables.get((bucket, lanes, "stash"))
            if stash is None:
                stash = self._stash_jit.lower(
                    self._variables, im, carry_spec, mask).compile()
                self._executables[(bucket, lanes, "stash")] = stash
                self.compile_counter.record((bucket, lanes, "stash"))
            wenc = self._executables.get((bucket, lanes, "wenc"))
            if wenc is None:
                wenc = self._warm_jit.lower(
                    self._variables, im, carry_spec, state_spec,
                    mask, budg).compile()
                self._executables[(bucket, lanes, "wenc")] = wenc
                self.compile_counter.record((bucket, lanes, "wenc"))
            for prog, exe in (("stash", stash), ("wenc", wenc)):
                key = (bucket, lanes, prog)
                if self.cost_book.get(key) is None:
                    self.cost_book.stamp(key, cost_mod.program_cost(
                        exe, program=f"serve_{prog}_{H}x{W}_b{lanes}",
                        pairs_per_call=lanes))
            progs.carry0 = jax.device_put(carry_tpl)
            progs.stash = stash
            progs.wenc = wenc
            return progs

    def _pipeline_cost_attrs(self, bucket: tuple, lanes: int,
                             iters: int, seconds: float) -> dict:
        """Trace-span cost attrs for one request-mode pipeline call
        (``enc`` + ``iters`` x ``iter`` over the stamped ledger
        entries): ``flops``/``bytes`` always, ``mfu`` when the device
        peak is known.  ``{}`` before the programs are stamped."""
        enc = self.cost_book.get((bucket, lanes, "enc"))
        it = self.cost_book.get((bucket, lanes, "iter"))
        if enc is None or it is None:
            return {}
        total = cost_mod.ProgramCost(
            program=f"serve_pipeline_{bucket[0]}x{bucket[1]}_b{lanes}",
            flops=enc.flops + iters * it.flops,
            bytes=enc.bytes + iters * it.bytes,
            pairs_per_call=lanes, source=enc.source,
            device_kind=enc.device_kind)
        attrs = {"flops": total.flops, "bytes": total.bytes}
        m = total.mfu(seconds)
        if m is not None:
            attrs["mfu"] = round(m, 4)
            self._last_mfu = attrs["mfu"]
        return attrs

    def _get_executable(self, bucket: tuple, batch_size: int):
        """Request-mode device callable for one ``(bucket, batch)``:
        ``(variables, a1, a2) -> (None, flow_up)``.

        A thin lockstep pipeline over the SAME compiled program pair
        slot mode runs — admit all lanes into a fresh zero state, run
        exactly ``cfg.iters`` iter_steps with the threshold disabled;
        every lane retires on the final step, which upsamples in-graph.
        Pipelining through the pair (instead of one monolithic forward)
        is what makes slot-vs-request parity bit-exact: XLA specializes
        fusion/reduction order per program, so only sharing the
        executables pins the bits (models/raft.py)."""
        progs = self._get_programs(bucket, batch_size)
        iters = self.cfg.iters

        def pipeline(variables, a1, a2):
            state = progs.enc(variables, a1, a2, progs.state0,
                              progs.mask_all, progs.budget_full)
            flow_up = None
            for _ in range(iters):
                state, flow_up = progs.it(variables, state,
                                          progs.thr_off)
            return None, flow_up

        return pipeline

    def _call_device(self, exe, a1: np.ndarray, a2: np.ndarray,
                     bucket: tuple, seq: int) -> np.ndarray:
        """Run one compiled batch with transient-error retry (the
        request-mode thunk over :meth:`_retry_call`)."""

        def thunk():
            _, flow_up = exe(self._variables, a1, a2)
            # np.asarray blocks on the transfer — async dispatch
            # errors surface here, inside the retry scope.
            return np.asarray(flow_up)

        return self._retry_call(bucket, seq, thunk)

    def _retry_call(self, bucket: tuple, seq: int, thunk):
        """Run one device call (``thunk``) with transient-error retry.

        Errors classified transient (:func:`is_transient_error` — flaky
        dispatch/transport, or the injected ``device_err`` fault) are
        retried up to ``cfg.device_retries`` times with EXPONENTIAL
        backoff + jitter under a total ``retry_deadline_s`` cap
        (linear backoff hammered a recovering device runtime in lock
        step; the jitter de-correlates co-located replicas), each retry
        counted (``raft_serve_device_retries_total``) and logged as a
        ``serve_retry`` event carrying the ACTUAL ``backoff_s`` slept —
        chaos drills assert the schedule from the event stream.
        Anything deterministic (shape/dtype/compile errors) raises on
        the first attempt.  Host-side pad/stack work stays OUTSIDE the
        retry: it is deterministic, so re-running it could only repeat
        its failure.  ``thunk`` must be safe to re-run — the slot
        programs are pure (state in, state out), so a failed attempt
        leaves the device state it read untouched."""
        attempt = 0
        t_first_try = time.perf_counter()
        while True:
            try:
                if chaos.should_inject("device_err", step=seq,
                                       point="serve.device"):
                    raise InjectedDeviceError(
                        f"chaos-injected transient device error "
                        f"(batch {seq})")
                out = thunk()
                self._last_retries = attempt
                return out
            except Exception as e:
                if attempt >= self.cfg.device_retries \
                        or not is_transient_error(e):
                    raise
                attempt += 1
                base = min(self.cfg.retry_backoff_s * 2 ** (attempt - 1),
                           self.cfg.retry_backoff_max_s)
                backoff = base * (1.0 + self.cfg.retry_jitter
                                  * float(self._retry_rng.uniform(-1, 1)))
                elapsed = time.perf_counter() - t_first_try
                if elapsed + backoff > self.cfg.retry_deadline_s:
                    # Total-deadline cap: the ladder must not outlive
                    # what a waiting client would tolerate.
                    self._sink.emit(
                        "serve_retry_deadline",
                        bucket=f"{bucket[0]}x{bucket[1]}",
                        attempt=attempt, elapsed_s=round(elapsed, 4),
                        deadline_s=self.cfg.retry_deadline_s)
                    raise
                self._counters.add_retry()
                self._sink.emit("serve_retry",
                                bucket=f"{bucket[0]}x{bucket[1]}",
                                attempt=attempt,
                                backoff_s=round(backoff, 6),
                                elapsed_s=round(elapsed, 4),
                                error=f"{type(e).__name__}: {e}")
                time.sleep(backoff)

    def _crash(self, reason: str) -> None:
        """Mark this replica dead (fleet supervisor restarts it): stop
        accepting, stamp the reason, emit the forensic event.  In-flight
        and queued requests fail with replica-fatal errors the router
        classifies as failover signals."""
        self.crashed = reason
        self._accepting = False
        self._sink.emit("replica_crash", reason=reason[:300])

    def _chaos_replica_faults(self, seq: int) -> None:
        """The ``serve.replica`` injection seam (device-worker thread,
        step context = device-batch ordinal): the three replica-level
        faults the fleet drill kills/hangs/slows a member with.  No
        plan installed = three module-global ``None`` checks."""
        if chaos.should_inject("replica_slow", step=seq,
                               point="serve.replica"):
            # A straggler, not a failure: the batch completes late —
            # what the router's bounded hedge exists to cover.
            time.sleep(self.cfg.chaos_slow_s)
        if chaos.should_inject("replica_hang", step=seq,
                               point="serve.replica"):
            # Wedge the (single) device worker: health() turns stalled
            # once stall_timeout_s passes with requests pending, the
            # supervisor stops the engine, and the poll below notices
            # and aborts the batch with a replica-fatal error.
            t0 = time.perf_counter()
            while (self._accepting
                   and time.perf_counter() - t0
                   < self.cfg.chaos_hang_max_s):
                time.sleep(0.02)
            raise ReplicaWedgedInterrupt(
                f"chaos-injected device wedge interrupted after "
                f"{time.perf_counter() - t0:.2f}s (batch {seq})")
        if chaos.should_inject("replica_kill", step=seq,
                               point="serve.replica"):
            self._crash(f"chaos-injected replica kill (batch {seq})")
            raise InjectedReplicaKill(
                f"chaos-injected replica kill (batch {seq})")

    def _slo_request(self, ok: bool,
                     latency_s: Optional[float] = None,
                     n: int = 1) -> None:
        """Feed ``n`` request outcomes into the SLO tracker (callers
        gate on ``self._slo`` so the disabled path stays untouched).
        The latency SLO only observes SUCCESSFUL requests — an errored
        request already burned the availability budget, and a latency
        observation for it would double-count the failure."""
        self._slo.record("availability", ok, n=n)
        if ok and latency_s is not None:
            self._slo.record(
                "latency",
                latency_s * 1000.0 <= self.cfg.slo_latency_target_ms,
                n=n)

    def _run_batch(self, bucket: tuple, reqs: List[_Request]) -> None:
        n = len(reqs)
        bs = next((s for s in self._batch_sizes if s >= n), n)
        t_start = time.perf_counter()
        self._batch_seq += 1
        # Requests carrying a trace context get per-request queue/pad/
        # device child spans; a batch with no traced request pays only
        # this list comprehension.
        traced = [r for r in reqs if r.trace is not None]
        try:
            self._chaos_replica_faults(self._batch_seq)
            exe = self._get_executable(bucket, bs)
            t_pad0 = time.perf_counter()
            im1 = [r.padder.pad_np(r.image1) for r in reqs]
            im2 = [r.padder.pad_np(r.image2) for r in reqs]
            if bs > n:  # ballast lanes keep the compiled batch shape
                im1 += [im1[-1]] * (bs - n)
                im2 += [im2[-1]] * (bs - n)
            a1, a2 = np.stack(im1), np.stack(im2)
            t_pad1 = time.perf_counter()
            flow_up = self._call_device(exe, a1, a2, bucket,
                                        self._batch_seq)
            t_done = time.perf_counter()
            for j, r in enumerate(reqs):
                r.future.set_result(
                    np.asarray(r.padder.unpad(flow_up[j:j + 1])[0]))
                self._latency.record(t_done - r.t_submit)
                if self._slo is not None:
                    self._slo_request(True, t_done - r.t_submit)
            self._counters.add_batch(real=n, padded=bs - n, failed=False)
            self._sink.emit("serve_batch",
                            bucket=f"{bucket[0]}x{bucket[1]}", real=n,
                            ballast=bs - n,
                            seconds=round(t_done - t_start, 6))
            if traced:
                retries = self._last_retries
                bk = f"{bucket[0]}x{bucket[1]}"
                cost_attrs = self._pipeline_cost_attrs(
                    bucket, bs, self.cfg.iters, t_done - t_pad1)
                for r in traced:
                    trace.record_span(r.trace, "queue", r.t_submit,
                                      t_start, batch=self._batch_seq)
                    trace.record_span(r.trace, "pad", t_pad0, t_pad1,
                                      real=n, ballast=bs - n)
                    trace.record_span(r.trace, "device", t_pad1, t_done,
                                      bucket=bk, batch=self._batch_seq,
                                      retries=retries, **cost_attrs)
                    if retries:  # tail-keep: a retried batch is news
                        r.trace.mark_keep()
        except Exception as e:
            for r in reqs:
                if not r.future.done():
                    r.future.set_exception(e)
            # The batch's REAL lanes must stay in the lane accounting
            # (as failed_lanes) or occupancy/mean_batch_fill read too
            # healthy under errors — see Counters.add_batch.
            self._counters.add_batch(real=n, padded=bs - n, failed=True)
            if self._slo is not None:
                self._slo_request(False, n=n)
            self._sink.emit("serve_batch_error",
                            bucket=f"{bucket[0]}x{bucket[1]}", real=n,
                            error=f"{type(e).__name__}: {e}")
            if traced:
                t_err = time.perf_counter()
                for r in traced:
                    trace.record_span(r.trace, "queue", r.t_submit,
                                      t_start, batch=self._batch_seq)
                    trace.record_span(r.trace, "device", t_start, t_err,
                                      status="error",
                                      error=f"{type(e).__name__}",
                                      batch=self._batch_seq)
        finally:
            with self._pending_lock:
                self._pending -= len(reqs)
                self._last_batch_done = time.perf_counter()

    # ------------------------------------------------------------------
    # internals — device-worker thread, slot mode
    # ------------------------------------------------------------------

    def _slot_cycle(self, bucket: tuple, pool: _SlotPool,
                    waiting: List[_Request]) -> None:
        """One continuous-batching cycle: admit -> iterate.  Runs on
        the device-worker thread while the bucket's dispatcher awaits;
        ``waiting`` is the dispatcher's FIFO (drained here, oldest
        first, into the lowest free slots)."""
        self._batch_seq += 1
        seq = self._batch_seq
        try:
            self._chaos_replica_faults(seq)
            if pool.progs is None:
                pool.progs = self._get_programs(bucket, self.cfg.slots)
                pool.state = pool.progs.state0
            self._sweep_pins(bucket, pool)
            if waiting:
                self._admit_cycle(bucket, pool, waiting, seq)
            if pool.active_np.any():
                self._iter_slots(bucket, pool, seq)
        except Exception as e:
            # Replica-fatal fault (kill/wedge) or an unexpected bug:
            # every live lane's request dies with it; waiting requests
            # stay queued (a crashed engine's stop() fails them, a
            # surviving one serves them next cycle from a reset pool).
            live = pool.live()
            for r in live:
                if not r.future.done():
                    r.future.set_exception(e)
            if live:
                self._counters.add_failed_lanes(len(live))
                if self._slo is not None:
                    self._slo_request(False, n=len(live))
                with self._pending_lock:
                    self._pending -= len(live)
            pool.reset()
            self._sink.emit("serve_slot_error",
                            bucket=f"{bucket[0]}x{bucket[1]}",
                            lanes=len(live),
                            error=f"{type(e).__name__}: {e}")
        finally:
            with self._pending_lock:
                self._last_batch_done = time.perf_counter()

    def _sweep_pins(self, bucket: tuple, pool: _SlotPool) -> None:
        """Evict closed/expired pinned sessions and return their lanes
        to the free pool.  Runs on the device worker at the top of
        every cycle (the dispatcher's idle poll guarantees cycles keep
        happening while pins exist); no device work — the lane's carry
        simply stops being referenced and the next admit overwrites
        it."""
        now = time.time()
        evicted = []
        for lane, s in list(pool.pins.items()):
            if pool.reqs[lane] is not None or (
                    s.inflight is not None and not s.inflight.done()):
                continue
            if s.closed:
                del pool.pins[lane]
                s.lane = None
            elif now - s.t_last > s.ttl_s:
                del pool.pins[lane]
                s.lane = None
                s.closed = True
                evicted.append((lane, s))
        if not evicted:
            return
        with self._sessions_lock:
            for _, s in evicted:
                self._sessions.pop(s.sid, None)
            self._sessions_gauge.set(len(self._sessions))
        for lane, s in evicted:
            self._stream_evicted.inc()
            self._sink.emit("stream_evict", sid=s.sid,
                            bucket=f"{bucket[0]}x{bucket[1]}",
                            lane=lane,
                            idle_s=round(now - s.t_last, 3),
                            ttl_s=s.ttl_s)

    def _admit_cycle(self, bucket: tuple, pool: _SlotPool,
                     waiting: List[_Request], seq: int) -> None:
        """Partition the waiting FIFO into this cycle's admissions.
        Stateless requests fill free UNPINNED lanes (oldest first,
        lowest lane first).  A session frame goes to its pinned lane —
        pinning one on its first pair — via the warm program when the
        lane's carry is valid, else the cold path plus a carry stash.
        Requests that found no lane stay in ``waiting`` in order."""
        S = self.cfg.slots
        free = [i for i in range(S)
                if pool.reqs[i] is None and i not in pool.pins]
        cold: List[tuple] = []    # (lane, request): full-pair encode
        warm: List[tuple] = []    # (lane, request): warm encode
        stash: List[tuple] = []   # cold subset that seeds a carry
        leftover: List[_Request] = []
        for r in waiting:
            sess = r.session
            if sess is None:
                if free:
                    cold.append((free.pop(0), r))
                else:
                    leftover.append(r)
                continue
            lane = sess.lane
            if lane is None or pool.pins.get(lane) is not sess:
                if not free:
                    leftover.append(r)
                    continue
                lane = free.pop(0)
                sess.lane = lane
                pool.pins[lane] = sess
            if pool.reqs[lane] is not None:
                # One frame in flight per session makes this
                # unreachable in practice; requeue defensively.
                leftover.append(r)
                continue
            if r.warm and sess.carry_ok:
                warm.append((lane, r))
            else:
                # Cold (first pair, or carry invalidated by a reset /
                # stash failure): the unmodified encode program — bit
                # parity with the stateless path — plus a carry stash
                # so the NEXT frame can run warm.
                r.warm = False
                cold.append((lane, r))
                stash.append((lane, r))
        waiting[:] = leftover
        if cold:
            if self._admit_slots(bucket, pool, cold, seq) and stash:
                self._stash_carry(bucket, pool, stash, seq)
        if warm:
            self._admit_warm(bucket, pool, warm, seq)

    def _admit_slots(self, bucket: tuple, pool: _SlotPool,
                     admits: List[tuple], seq: int) -> bool:
        """Encode ``admits`` (``(slot_index, request)`` pairs) into
        their lanes.  The encode program scatters fresh state into the
        admitted lanes only — the other lanes' device state is carried
        through bit-for-bit, so a failed admit (retries exhausted)
        fails just the admitted requests and leaves every live lane
        serving."""
        S = self.cfg.slots
        H, W = bucket
        t0 = time.perf_counter()
        a1 = np.zeros((S, H, W, 3), np.float32)
        a2 = np.zeros((S, H, W, 3), np.float32)
        admit = np.zeros((S,), bool)
        budgets = pool.budgets.copy()
        for i, r in admits:
            a1[i] = r.padder.pad_np(r.image1)
            a2[i] = r.padder.pad_np(r.image2)
            admit[i] = True
            budgets[i] = min(int(r.iters or self.cfg.iters),
                             self.cfg.iters)
        t_pad = time.perf_counter()

        def thunk():
            state = pool.progs.enc(self._variables, a1, a2, pool.state,
                                   admit, budgets)
            # Blocks on a small leaf — async dispatch errors surface
            # here, inside the retry scope, before the pool commits.
            active = np.asarray(state["active"])
            return state, active

        try:
            state, active = self._retry_call(bucket, seq, thunk)
        except Exception as e:
            for _, r in admits:
                if not r.future.done():
                    r.future.set_exception(e)
            self._counters.add_failed_lanes(len(admits))
            if self._slo is not None:
                self._slo_request(False, n=len(admits))
            self._sink.emit("serve_admit_error",
                            bucket=f"{bucket[0]}x{bucket[1]}",
                            admits=len(admits), warm=False,
                            error=f"{type(e).__name__}: {e}")
            with self._pending_lock:
                self._pending -= len(admits)
            return False
        pool.state = state
        pool.active_np = active
        pool.budgets = budgets
        t_done = time.perf_counter()
        for i, r in admits:
            pool.reqs[i] = r
            pool.t_admit[i] = t_done
            if r.trace is not None:
                trace.record_span(r.trace, "queue", r.t_submit, t0,
                                  batch=seq, slot=i)
                trace.record_span(r.trace, "pad", t0, t_pad, slot=i)
        self._sink.emit("serve_admit",
                        bucket=f"{bucket[0]}x{bucket[1]}",
                        admits=len(admits), seq=seq, warm=False,
                        seconds=round(t_done - t0, 6))
        return True

    def _stash_carry(self, bucket: tuple, pool: _SlotPool,
                     admits: List[tuple], seq: int) -> None:
        """Snapshot frame 2's features into freshly cold-admitted
        session lanes' carry.  One extra encoder pass per session
        open — deliberately a SEPARATE program so the cold pair itself
        runs the unmodified ``enc`` executable (bit parity with the
        stateless path).  Failure never fails the pair: the session
        just stays cold and re-seeds on its next frame."""
        S = self.cfg.slots
        H, W = bucket
        t0 = time.perf_counter()
        progs = self._get_stream_programs(bucket, S)
        if pool.carry is None:
            pool.carry = progs.carry0
        a2 = np.zeros((S, H, W, 3), np.float32)
        admit = np.zeros((S,), bool)
        for i, r in admits:
            a2[i] = r.padder.pad_np(r.image2)
            admit[i] = True

        def thunk():
            carry = progs.stash(self._variables, a2, pool.carry, admit)
            # Blocks on a small slice — async dispatch errors surface
            # here, inside the retry scope, before the carry commits.
            np.asarray(carry["fmap"][0, :1, 0, 0])
            return carry

        try:
            carry = self._retry_call(bucket, seq, thunk)
        except Exception as e:
            for _, r in admits:
                if r.session is not None:
                    r.session.carry_ok = False
            self._sink.emit("stream_stash_error",
                            bucket=f"{H}x{W}", lanes=len(admits),
                            error=f"{type(e).__name__}: {e}")
            return
        pool.carry = carry
        for _, r in admits:
            if r.session is not None:
                r.session.carry_ok = True

    def _admit_warm(self, bucket: tuple, pool: _SlotPool,
                    admits: List[tuple], seq: int) -> None:
        """Warm-admit session frames into their pinned lanes: only the
        new image runs through the encoders (the carried fmap/ctx
        stand in for frame 1) and ``coords1`` starts from the lane's
        previous flow forward-warped by itself.  Scatter semantics
        match :meth:`_admit_slots` — a failed warm admit fails just
        the admitted frames (marking their sessions cold) and leaves
        every live lane serving."""
        S = self.cfg.slots
        H, W = bucket
        t0 = time.perf_counter()
        progs = self._get_stream_programs(bucket, S)
        if pool.carry is None:
            pool.carry = progs.carry0
        a2 = np.zeros((S, H, W, 3), np.float32)
        admit = np.zeros((S,), bool)
        budgets = pool.budgets.copy()
        for i, r in admits:
            a2[i] = r.padder.pad_np(r.image2)
            admit[i] = True
            budgets[i] = min(int(r.iters or self.cfg.iters),
                             self.cfg.iters)
        t_pad = time.perf_counter()

        def thunk():
            state, carry = progs.wenc(self._variables, a2, pool.carry,
                                      pool.state, admit, budgets)
            active = np.asarray(state["active"])
            return state, carry, active

        try:
            state, carry, active = self._retry_call(bucket, seq, thunk)
        except Exception as e:
            for _, r in admits:
                if not r.future.done():
                    r.future.set_exception(e)
                if r.session is not None:
                    r.session.carry_ok = False
            self._counters.add_failed_lanes(len(admits))
            if self._slo is not None:
                self._slo_request(False, n=len(admits))
            self._sink.emit("serve_admit_error",
                            bucket=f"{H}x{W}",
                            admits=len(admits), warm=True,
                            error=f"{type(e).__name__}: {e}")
            with self._pending_lock:
                self._pending -= len(admits)
            return
        pool.state = state
        pool.carry = carry
        pool.active_np = active
        pool.budgets = budgets
        t_done = time.perf_counter()
        for i, r in admits:
            pool.reqs[i] = r
            pool.t_admit[i] = t_done
            if r.trace is not None:
                trace.record_span(r.trace, "queue", r.t_submit, t0,
                                  batch=seq, slot=i)
                trace.record_span(r.trace, "pad", t0, t_pad, slot=i)
        self._sink.emit("serve_admit",
                        bucket=f"{H}x{W}",
                        admits=len(admits), seq=seq, warm=True,
                        seconds=round(t_done - t0, 6))

    def _iter_slots(self, bucket: tuple, pool: _SlotPool,
                    seq: int) -> None:
        """One ``iter_step`` over the active lanes; retire lanes whose
        budget is spent or whose convergence predicate fired.  On a
        non-transient failure every live request dies and the pool
        resets to the zero state — the programs are pure, so a FAILED
        attempt never corrupts state, and a retried one is
        bit-identical to an uninterrupted run
        (tests/test_serve_slots.py chaos case)."""
        prev_active = pool.active_np.copy()
        n_active = int(prev_active.sum())
        thr = np.float32(self.cfg.early_exit_threshold)
        t0 = time.perf_counter()

        def thunk():
            state, flow_up = pool.progs.it(self._variables, pool.state,
                                           thr)
            active = np.asarray(state["active"])
            iters_done = np.asarray(state["iters_done"])
            return state, flow_up, active, iters_done

        try:
            state, flow_up, active, iters_done = self._retry_call(
                bucket, seq, thunk)
        except Exception as e:
            live = pool.live()
            for r in live:
                if not r.future.done():
                    r.future.set_exception(e)
            self._counters.add_failed_lanes(len(live))
            if self._slo is not None and live:
                self._slo_request(False, n=len(live))
            self._sink.emit("serve_iter_error",
                            bucket=f"{bucket[0]}x{bucket[1]}",
                            lanes=len(live),
                            error=f"{type(e).__name__}: {e}")
            with self._pending_lock:
                self._pending -= len(live)
            pool.reset()
            return
        retries = self._last_retries
        t_done = time.perf_counter()
        pool.state = state
        pool.active_np = active
        self._counters.add_slot_step(n_active, self.cfg.slots)
        bk = f"{bucket[0]}x{bucket[1]}"
        # Iteration-level trace attribution: every traced request that
        # was active this cycle gets an iter_step child span under its
        # request root (trace_report.py critical paths then show which
        # iterations a request actually waited on).  The cost attrs
        # (flops/bytes, mfu on known peaks) come from the ledger entry
        # stamped at compile time — observe() also refreshes the
        # raft_cost_mfu/raft_cost_hbm_bw_util gauges, no device work.
        iter_attrs = self.cost_book.observe(
            (bucket, self.cfg.slots, "iter"), t_done - t0)
        if "mfu" in iter_attrs:
            self._last_mfu = iter_attrs["mfu"]
        if self._slo is not None and "mfu" in iter_attrs:
            # The MFU-floor SLO (only constructed on known peaks):
            # one observation per measured iteration.
            self._slo.record(
                "mfu", iter_attrs["mfu"] >= self.cfg.slo_mfu_floor)
        for i in np.nonzero(prev_active)[0]:
            r = pool.reqs[int(i)]
            if r is not None and r.trace is not None:
                trace.record_span(r.trace, "iter_step", t0, t_done,
                                  batch=seq, slot=int(i),
                                  active=n_active, **iter_attrs)
        newly = prev_active & ~active
        if not newly.any():
            return
        flow_np = np.asarray(flow_up)
        converged_np = np.asarray(state["converged"])
        # delta_max is only fetched when the quality monitor exists —
        # at quality_sample_rate=0 the retirement path transfers
        # exactly what it always did (the zero-overhead contract).
        dmax_np = (np.asarray(state["delta_max"])
                   if self._quality is not None else None)
        for i in np.nonzero(newly)[0]:
            i = int(i)
            r = pool.reqs[i]
            pool.reqs[i] = None
            if r is None:
                continue
            out = np.asarray(r.padder.unpad(flow_np[i:i + 1])[0])
            if not r.future.done():
                r.future.set_result(out)
            used = int(iters_done[i])
            self._latency.record(t_done - r.t_submit)
            self._iters_used.record(used)
            (self._iters_used_warm if r.warm
             else self._iters_used_cold).record(used)
            self._counters.add_completed()
            if self._slo is not None:
                self._slo_request(True, t_done - r.t_submit)
            if r.session is not None:
                r.session.pairs += 1
                if r.warm:
                    r.session.warm_pairs += 1
            self._sink.emit("serve_retire", bucket=bk, slot=i,
                            iters=used, warm=bool(r.warm),
                            converged=bool(converged_np[i]),
                            seconds=round(t_done - r.t_submit, 6))
            qattrs = None
            if self._quality is not None:
                qattrs = self._quality.note_retirement(
                    future=r.future, image1=r.image1, image2=r.image2,
                    flow=out, bucket=bk, residual=float(dmax_np[i]),
                    converged=bool(converged_np[i]), iters=used)
                if (self._slo is not None and qattrs is not None
                        and "quality_photometric" in qattrs):
                    # Quality SLO: one observation per SCORED
                    # retirement — bad when the photometric proxy
                    # breached its calibrated bound.
                    self._slo.record(
                        "quality",
                        qattrs["quality_photometric"]
                        <= self.cfg.slo_quality_bound)
                if qattrs is not None and self.cfg.quality_cycle:
                    # Sampled forward-backward pass: score THIS flow
                    # against a second inference on the swapped
                    # frames.  Best-effort — backpressure or an
                    # engine racing stop() just skips the cycle
                    # measurement, never fails the retirement.
                    try:
                        bfut = self.submit(r.image2, r.image1,
                                           iters=r.iters)
                    except Exception:
                        pass
                    else:
                        self._quality.begin_cycle(bfut, out, bk)
            if r.trace is not None:
                trace.record_span(r.trace, "device", pool.t_admit[i],
                                  t_done, bucket=bk, iters=used,
                                  warm=bool(r.warm),
                                  retries=retries, **(qattrs or {}))
                if retries:  # tail-keep: a retried request is news
                    r.trace.mark_keep()
            with self._pending_lock:
                self._pending -= 1
