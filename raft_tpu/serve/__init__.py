"""Online inference serving (see ``raft_tpu/serve/engine.py`` for the
architecture: shape-bucketed AOT compile cache + dynamic micro-batching
+ bounded-queue backpressure, and ``raft_tpu/serve/slots.py`` for
continuous batching at GRU-iteration granularity with adaptive early
exit — ``ServeConfig(batching="slot")``).

Entry points::

    python -m raft_tpu serve --small --port 8080   # HTTP server
    python scripts/bench_serve.py --tiny           # load generator
"""

from raft_tpu.serve.aot import (
    AOTImportError,
    export_executables,
    import_executables,
    model_fingerprint,
)
from raft_tpu.serve.engine import (
    InferenceEngine,
    QueueFullError,
    ServeConfig,
)
from raft_tpu.serve.fleet import (
    FleetConfig,
    Replica,
    ReplicaFleet,
    WeightUpdateError,
)
from raft_tpu.serve.remote import (
    RemoteConfig,
    RemoteEngine,
    RemoteNetworkError,
    RemoteProtocolError,
    RemoteReplica,
    classify_network_error,
)
from raft_tpu.serve.router import (
    FlowRouter,
    RouterConfig,
    is_failover_error,
)
from raft_tpu.serve.slots import EarlyExitRunner
from raft_tpu.serve.stats import LatencyRecorder

__all__ = [
    "AOTImportError",
    "EarlyExitRunner",
    "FleetConfig",
    "FlowRouter",
    "InferenceEngine",
    "LatencyRecorder",
    "QueueFullError",
    "RemoteConfig",
    "RemoteEngine",
    "RemoteNetworkError",
    "RemoteProtocolError",
    "RemoteReplica",
    "Replica",
    "ReplicaFleet",
    "RouterConfig",
    "classify_network_error",
    "ServeConfig",
    "WeightUpdateError",
    "export_executables",
    "import_executables",
    "is_failover_error",
    "model_fingerprint",
]
