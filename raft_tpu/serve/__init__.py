"""Online inference serving (see ``raft_tpu/serve/engine.py`` for the
architecture: shape-bucketed AOT compile cache + dynamic micro-batching
+ bounded-queue backpressure).

Entry points::

    python -m raft_tpu serve --small --port 8080   # HTTP server
    python scripts/bench_serve.py --tiny           # load generator
"""

from raft_tpu.serve.engine import (
    InferenceEngine,
    QueueFullError,
    ServeConfig,
)
from raft_tpu.serve.stats import LatencyRecorder

__all__ = [
    "InferenceEngine",
    "QueueFullError",
    "ServeConfig",
    "LatencyRecorder",
]
