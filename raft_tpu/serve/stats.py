"""Serving metrics: latency percentiles + throughput counters.

Both classes are thin views over ``raft_tpu.obs.MetricRegistry``
metrics: every figure the JSON ``/v1/stats`` snapshot reports is
derived from the same registry counters/histograms the Prometheus
``GET /metrics`` endpoint renders, so the two surfaces cannot drift
(they can only be read microseconds apart — per-metric locks, no
cross-metric atomic snapshot, which is fine for monitoring).

The engine records one latency sample per completed request (submit ->
result, i.e. including queueing and batching delay — the number a client
actually experiences) into a bounded reservoir, so a long-running
server's ``stats()`` reflects *recent* traffic and memory stays
O(window).  Percentiles are computed on snapshot, not on record: the
record path is on the request hot path, the snapshot path is a human
asking.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

import numpy as np

from raft_tpu.obs import MetricRegistry


class LatencyRecorder:
    """Bounded reservoir of per-request samples with percentile
    snapshots, backed by a registry histogram
    (``raft_serve_request_latency_seconds`` by default).

    The default shape records seconds and snapshots milliseconds
    (``p50_ms`` etc.); other per-request scalars reuse the same
    reservoir with ``scale``/``suffix`` overridden — the engine's
    ``raft_serve_iters_used`` histogram (iterations consumed before a
    slot retired, continuous-batching mode) uses ``scale=1.0,
    suffix=""`` and snapshots plain ``p50``/``p95``/``p99``/``mean``.

    Thread-safe: requests complete on the device-worker thread while
    ``snapshot`` is called from CLI/HTTP threads."""

    def __init__(self, window: int = 4096,
                 registry: Optional[MetricRegistry] = None,
                 metric: str = "raft_serve_request_latency_seconds",
                 help: str = "client-observed submit->result latency",
                 scale: float = 1e3, suffix: str = "_ms"):
        self._hist = (registry or MetricRegistry()).histogram(
            metric, help, reservoir=window)
        self._scale = float(scale)
        self._suffix = suffix

    def record(self, value: float) -> None:
        self._hist.observe(value)

    def snapshot(self) -> Dict[str, float]:
        """``{count, count_total, window_count, p50<sfx>, p95<sfx>,
        p99<sfx>, mean<sfx>}``.

        ``count_total`` is the LIFETIME number of recorded samples;
        the percentiles and mean are computed over the recent bounded
        window of ``window_count`` samples only (zeros when nothing
        completed).  ``count`` is a backwards-compat alias for
        ``count_total`` (older clients of the wire format read it);
        prefer the explicit names."""
        sfx = self._suffix
        count, _total, window = self._hist.collect()
        if not window:
            return {"count": count, "count_total": count,
                    "window_count": 0, f"p50{sfx}": 0.0,
                    f"p95{sfx}": 0.0, f"p99{sfx}": 0.0,
                    f"mean{sfx}": 0.0}
        vals = np.asarray(window, dtype=np.float64)
        p50, p95, p99 = np.percentile(vals, [50, 95, 99]) * self._scale
        return {"count": count,
                "count_total": count,
                "window_count": int(vals.size),
                f"p50{sfx}": round(float(p50), 3),
                f"p95{sfx}": round(float(p95), 3),
                f"p99{sfx}": round(float(p99), 3),
                f"mean{sfx}": round(float(vals.mean() * self._scale), 3)}


class Counters:
    """Lifetime request/batch counters over registry metrics.

    Lane accounting: a batch of ``real`` requests compiled at batch
    size ``real + padded`` contributes ``real`` real lanes and
    ``padded`` ballast lanes whether it succeeds or fails — a failed
    batch's real lanes land in ``failed_lanes`` instead of
    ``completed``, so ``occupancy`` (real / total lanes) and
    ``mean_batch_fill`` keep describing what the dispatcher packed,
    not just what happened to succeed (errors no longer make the
    batching look *healthier*).  ``occupancy`` is the knob-tuning
    signal for ``max_wait_ms`` vs ``max_batch``; throughput figures
    (``pairs_per_sec*``) count completed lanes only."""

    def __init__(self, registry: Optional[MetricRegistry] = None) -> None:
        r = registry or MetricRegistry()
        self._completed = r.counter("raft_serve_pairs_completed_total",
                                    "successfully served frame pairs")
        self._rejected = r.counter("raft_serve_requests_rejected_total",
                                   "backpressure rejections (HTTP 429)")
        self._errors = r.counter("raft_serve_batch_errors_total",
                                 "device batches that raised")
        self._retries = r.counter(
            "raft_serve_device_retries_total",
            "device-call re-dispatches after a transient error "
            "(docs/ROBUSTNESS.md)")
        self._batches = r.counter("raft_serve_batches_total",
                                  "device batches dispatched")
        self._ballast = r.counter("raft_serve_lanes_ballast_total",
                                  "batch lanes filled with repeated "
                                  "ballast to reach a compiled size")
        self._failed = r.counter("raft_serve_lanes_failed_total",
                                 "real lanes lost to failed batches")
        # Slot-mode (continuous batching) lane accounting: one
        # iter_step over S slots with A active contributes A active
        # and S total lanes; occupancy = active/total is the signal
        # for sizing `slots` (docs/PERFORMANCE.md).
        self._slot_steps = r.counter(
            "raft_serve_slot_steps_total",
            "iter_step device calls (continuous-batching mode)")
        self._slot_active = r.counter(
            "raft_serve_slot_lanes_active_total",
            "slot lanes active across iter_step calls")
        self._slot_lanes = r.counter(
            "raft_serve_slot_lanes_total",
            "slot lanes (active or idle) across iter_step calls")
        self._slot_occ = r.gauge(
            "raft_serve_slot_occupancy",
            "active/total slot lanes over the engine lifetime "
            "(continuous-batching mode)")
        self._uptime = r.gauge("raft_serve_uptime_seconds",
                               "seconds since the engine started")
        self._lock = threading.Lock()
        self._t0: Optional[float] = None
        r.add_collect_hook(lambda reg: self._uptime.set(self._uptime_s()))

    def _uptime_s(self) -> float:
        with self._lock:
            return (time.perf_counter() - self._t0) if self._t0 else 0.0

    def mark_started(self) -> None:
        with self._lock:
            self._t0 = time.perf_counter()

    def add_rejected(self, n: int = 1) -> None:
        self._rejected.inc(n)

    def add_retry(self, n: int = 1) -> None:
        self._retries.inc(n)

    def add_batch(self, real: int, padded: int, failed: bool) -> None:
        self._batches.inc()
        self._ballast.inc(padded)
        if failed:
            self._errors.inc()
            self._failed.inc(real)
        else:
            self._completed.inc(real)

    def add_completed(self, n: int = 1) -> None:
        """Slot-mode retirement: requests complete one at a time, not
        per batch (batch accounting happens in :meth:`add_slot_step`)."""
        self._completed.inc(n)

    def add_failed_lanes(self, n: int) -> None:
        """Slot-mode failure: ``n`` live lanes lost to a failed
        encode/iter_step call (counts one batch error)."""
        if n:
            self._errors.inc()
            self._failed.inc(n)

    def add_slot_step(self, active: int, slots: int) -> None:
        """One iter_step over ``slots`` lanes of which ``active`` held
        live requests."""
        self._slot_steps.inc()
        self._slot_active.inc(active)
        self._slot_lanes.inc(slots)
        total = self._slot_lanes.value()
        if total:
            self._slot_occ.set(
                round(self._slot_active.value() / total, 4))

    def snapshot(self, num_chips: int) -> Dict[str, float]:
        uptime = self._uptime_s()
        completed = self._completed.value()
        failed_lanes = self._failed.value()
        ballast = self._ballast.value()
        batches = self._batches.value()
        real_lanes = completed + failed_lanes
        total_lanes = real_lanes + ballast
        # Continuous-batching mode: occupancy/fill describe the slot
        # batch the dispatcher keeps resident, not padded micro-batches
        # (which slot mode never builds).  Request-mode engines have
        # zero slot lanes and keep the micro-batch math unchanged.
        slot_lanes = self._slot_lanes.value()
        if slot_lanes:
            real_lanes = self._slot_active.value()
            total_lanes = slot_lanes
            batches = self._slot_steps.value()
        return {
            "uptime_s": round(uptime, 3),
            "completed": completed,
            "rejected": self._rejected.value(),
            "errors": self._errors.value(),
            "retries": self._retries.value(),
            "batches": batches,
            "slot_steps": self._slot_steps.value(),
            "failed_lanes": failed_lanes,
            "mean_batch_fill": round(real_lanes / batches, 3)
            if batches else 0.0,
            "occupancy": round(real_lanes / total_lanes, 3)
            if total_lanes else 0.0,
            "pairs_per_sec": round(completed / uptime, 3)
            if uptime > 0 else 0.0,
            "pairs_per_sec_per_chip":
                round(completed / uptime / num_chips, 3)
                if uptime > 0 else 0.0,
        }
