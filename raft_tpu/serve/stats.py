"""Serving metrics: latency percentiles + throughput counters.

Both classes are thin views over ``raft_tpu.obs.MetricRegistry``
metrics: every figure the JSON ``/v1/stats`` snapshot reports is
derived from the same registry counters/histograms the Prometheus
``GET /metrics`` endpoint renders, so the two surfaces cannot drift
(they can only be read microseconds apart — per-metric locks, no
cross-metric atomic snapshot, which is fine for monitoring).

The engine records one latency sample per completed request (submit ->
result, i.e. including queueing and batching delay — the number a client
actually experiences) into a bounded reservoir, so a long-running
server's ``stats()`` reflects *recent* traffic and memory stays
O(window).  Percentiles are computed on snapshot, not on record: the
record path is on the request hot path, the snapshot path is a human
asking.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

import numpy as np

from raft_tpu.obs import MetricRegistry


class LatencyRecorder:
    """Bounded reservoir of per-request latencies with percentile
    snapshots, backed by a registry histogram
    (``raft_serve_request_latency_seconds``).

    Thread-safe: requests complete on the device-worker thread while
    ``snapshot`` is called from CLI/HTTP threads."""

    def __init__(self, window: int = 4096,
                 registry: Optional[MetricRegistry] = None,
                 metric: str = "raft_serve_request_latency_seconds"):
        self._hist = (registry or MetricRegistry()).histogram(
            metric, "client-observed submit->result latency",
            reservoir=window)

    def record(self, seconds: float) -> None:
        self._hist.observe(seconds)

    def snapshot(self) -> Dict[str, float]:
        """``{count, count_total, window_count, p50_ms, p95_ms, p99_ms,
        mean_ms}``.

        ``count_total`` is the LIFETIME number of recorded requests;
        the percentiles and ``mean_ms`` are computed over the recent
        bounded window of ``window_count`` samples only (zeros when
        nothing completed).  ``count`` is a backwards-compat alias for
        ``count_total`` (older clients of the wire format read it);
        prefer the explicit names."""
        count, _total, window = self._hist.collect()
        if not window:
            return {"count": count, "count_total": count,
                    "window_count": 0, "p50_ms": 0.0, "p95_ms": 0.0,
                    "p99_ms": 0.0, "mean_ms": 0.0}
        vals = np.asarray(window, dtype=np.float64)
        p50, p95, p99 = np.percentile(vals, [50, 95, 99]) * 1e3
        return {"count": count,
                "count_total": count,
                "window_count": int(vals.size),
                "p50_ms": round(float(p50), 3),
                "p95_ms": round(float(p95), 3),
                "p99_ms": round(float(p99), 3),
                "mean_ms": round(float(vals.mean() * 1e3), 3)}


class Counters:
    """Lifetime request/batch counters over registry metrics.

    Lane accounting: a batch of ``real`` requests compiled at batch
    size ``real + padded`` contributes ``real`` real lanes and
    ``padded`` ballast lanes whether it succeeds or fails — a failed
    batch's real lanes land in ``failed_lanes`` instead of
    ``completed``, so ``occupancy`` (real / total lanes) and
    ``mean_batch_fill`` keep describing what the dispatcher packed,
    not just what happened to succeed (errors no longer make the
    batching look *healthier*).  ``occupancy`` is the knob-tuning
    signal for ``max_wait_ms`` vs ``max_batch``; throughput figures
    (``pairs_per_sec*``) count completed lanes only."""

    def __init__(self, registry: Optional[MetricRegistry] = None) -> None:
        r = registry or MetricRegistry()
        self._completed = r.counter("raft_serve_pairs_completed_total",
                                    "successfully served frame pairs")
        self._rejected = r.counter("raft_serve_requests_rejected_total",
                                   "backpressure rejections (HTTP 429)")
        self._errors = r.counter("raft_serve_batch_errors_total",
                                 "device batches that raised")
        self._retries = r.counter(
            "raft_serve_device_retries_total",
            "device-call re-dispatches after a transient error "
            "(docs/ROBUSTNESS.md)")
        self._batches = r.counter("raft_serve_batches_total",
                                  "device batches dispatched")
        self._ballast = r.counter("raft_serve_lanes_ballast_total",
                                  "batch lanes filled with repeated "
                                  "ballast to reach a compiled size")
        self._failed = r.counter("raft_serve_lanes_failed_total",
                                 "real lanes lost to failed batches")
        self._uptime = r.gauge("raft_serve_uptime_seconds",
                               "seconds since the engine started")
        self._lock = threading.Lock()
        self._t0: Optional[float] = None
        r.add_collect_hook(lambda reg: self._uptime.set(self._uptime_s()))

    def _uptime_s(self) -> float:
        with self._lock:
            return (time.perf_counter() - self._t0) if self._t0 else 0.0

    def mark_started(self) -> None:
        with self._lock:
            self._t0 = time.perf_counter()

    def add_rejected(self, n: int = 1) -> None:
        self._rejected.inc(n)

    def add_retry(self, n: int = 1) -> None:
        self._retries.inc(n)

    def add_batch(self, real: int, padded: int, failed: bool) -> None:
        self._batches.inc()
        self._ballast.inc(padded)
        if failed:
            self._errors.inc()
            self._failed.inc(real)
        else:
            self._completed.inc(real)

    def snapshot(self, num_chips: int) -> Dict[str, float]:
        uptime = self._uptime_s()
        completed = self._completed.value()
        failed_lanes = self._failed.value()
        ballast = self._ballast.value()
        batches = self._batches.value()
        real_lanes = completed + failed_lanes
        total_lanes = real_lanes + ballast
        return {
            "uptime_s": round(uptime, 3),
            "completed": completed,
            "rejected": self._rejected.value(),
            "errors": self._errors.value(),
            "retries": self._retries.value(),
            "batches": batches,
            "failed_lanes": failed_lanes,
            "mean_batch_fill": round(real_lanes / batches, 3)
            if batches else 0.0,
            "occupancy": round(real_lanes / total_lanes, 3)
            if total_lanes else 0.0,
            "pairs_per_sec": round(completed / uptime, 3)
            if uptime > 0 else 0.0,
            "pairs_per_sec_per_chip":
                round(completed / uptime / num_chips, 3)
                if uptime > 0 else 0.0,
        }
