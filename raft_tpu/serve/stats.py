"""Serving metrics: latency percentiles + throughput counters.

The engine records one latency sample per completed request (submit ->
result, i.e. including queueing and batching delay — the number a client
actually experiences) into a bounded ring, so a long-running server's
``stats()`` reflects *recent* traffic and memory stays O(window).
Percentiles are computed on snapshot, not on record: the record path is
on the request hot path, the snapshot path is a human asking.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Dict, Optional

import numpy as np


class LatencyRecorder:
    """Bounded ring of per-request latencies with percentile snapshots.

    Thread-safe: requests complete on the device-worker thread while
    ``snapshot`` is called from CLI/HTTP threads."""

    def __init__(self, window: int = 4096):
        self._lock = threading.Lock()
        self._ring: collections.deque = collections.deque(maxlen=window)
        self._count = 0

    def record(self, seconds: float) -> None:
        with self._lock:
            self._ring.append(seconds)
            self._count += 1

    def snapshot(self) -> Dict[str, float]:
        """``{count, p50_ms, p95_ms, p99_ms, mean_ms}`` over the recent
        window (``count`` is lifetime; zeros when nothing completed)."""
        with self._lock:
            vals = np.asarray(self._ring, dtype=np.float64)
            count = self._count
        if vals.size == 0:
            return {"count": 0, "p50_ms": 0.0, "p95_ms": 0.0,
                    "p99_ms": 0.0, "mean_ms": 0.0}
        p50, p95, p99 = np.percentile(vals, [50, 95, 99]) * 1e3
        return {"count": count,
                "p50_ms": round(float(p50), 3),
                "p95_ms": round(float(p95), 3),
                "p99_ms": round(float(p99), 3),
                "mean_ms": round(float(vals.mean() * 1e3), 3)}


class Counters:
    """Lifetime request/batch counters (lock-shared with the engine).

    ``padded_lanes`` counts batch lanes filled with repeated ballast to
    reach a compiled batch size — ``occupancy`` (real / total lanes) is
    the knob-tuning signal for ``max_wait_ms`` vs ``max_batch``."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.completed = 0
        self.rejected = 0
        self.errors = 0
        self.batches = 0
        self.padded_lanes = 0
        self._t0: Optional[float] = None

    def mark_started(self) -> None:
        with self._lock:
            self._t0 = time.perf_counter()

    def add_rejected(self, n: int = 1) -> None:
        with self._lock:
            self.rejected += n

    def add_batch(self, real: int, padded: int, failed: bool) -> None:
        with self._lock:
            self.batches += 1
            self.padded_lanes += padded
            if failed:
                self.errors += 1
            else:
                self.completed += real

    def snapshot(self, num_chips: int) -> Dict[str, float]:
        with self._lock:
            uptime = (time.perf_counter() - self._t0) if self._t0 else 0.0
            total_lanes = self.completed + self.padded_lanes
            return {
                "uptime_s": round(uptime, 3),
                "completed": self.completed,
                "rejected": self.rejected,
                "errors": self.errors,
                "batches": self.batches,
                "mean_batch_fill": round(self.completed / self.batches, 3)
                if self.batches else 0.0,
                "occupancy": round(self.completed / total_lanes, 3)
                if total_lanes else 0.0,
                "pairs_per_sec": round(self.completed / uptime, 3)
                if uptime > 0 else 0.0,
                "pairs_per_sec_per_chip":
                    round(self.completed / uptime / num_chips, 3)
                    if uptime > 0 else 0.0,
            }
