"""Remote replica backend: the fleet facade over the npz wire protocol
(docs/SERVING.md, "Multi-host fabric").

One serving host's ``FlowRouter`` fronts replicas on OTHER hosts by
speaking the same HTTP protocol ``raft_tpu.cli.serve`` exposes —
``POST /v1/flow``, ``POST/DELETE /v1/stream/{id}``, ``GET /v1/healthz``,
``GET /v1/stats`` — through :class:`RemoteEngine`, which implements the
engine facade the router and fleet already duck-type (``submit`` /
``infer`` / ``stream_ingest`` / ``stream_close`` / ``health`` /
``stats`` / ``metrics_text``).  :class:`RemoteReplica` wraps it in the
:class:`~raft_tpu.serve.fleet.Replica` state machine so the router's
placement, breaker, failover, and hedging code paths need no remote
special case at all.

Design points:

- **Network-error taxonomy** — every wire failure is classified into
  one of five :class:`RemoteNetworkError` subclasses (refused / reset /
  timeout / mid-response disconnect / HTTP 503).  All carry
  ``replica_fatal = True``, so
  :func:`raft_tpu.serve.router.is_failover_error` re-dispatches the
  request on a sibling and the breaker accumulates strikes — a
  partitioned host is indistinguishable from a crashed replica to the
  router.  Only timeouts are additionally marked ``transient`` (a
  deadline flake is worth a same-path retry); refused/reset indict the
  host.  Malformed responses raise :class:`RemoteProtocolError`, which
  is deliberately NOT a failover signal — a server speaking garbage
  would speak the same garbage to the retry.
- **Trace propagation** — ``submit()`` captures the submitting
  thread's current span (the router's ``attempt`` span under
  ``use_context``) and sends it as the ``X-Raft-Trace`` header, so the
  remote host's ``serve_http`` span lands in the SAME trace tree as
  the client-side route/attempt spans (one tree in
  ``scripts/trace_report.py``).
- **Connection pooling + per-request deadlines** — a small pool of
  keep-alive ``http.client`` connections; a connection that saw any
  network error is discarded, never reused.  Every request runs under
  ``RemoteConfig.request_timeout_s`` (health probes under the much
  tighter ``health_timeout_s``).
- **Client-side pending** — ``pending()`` counts in-flight requests on
  THIS side of the wire (no network round trip), so router placement
  (`_pick`'s least-loaded fallback) stays cheap during a partition.
- **Chaos seam** (``serve.remote``, docs/ROBUSTNESS.md): ``net_refuse``
  (connect refused), ``net_slow`` (added latency), ``net_drop``
  (mid-response disconnect — the request reached the server, the
  response never arrived), and ``net_partition`` (every wire operation
  times out until the rule's ``heal=`` ordinal is reached).  All are
  deterministic under the :class:`~raft_tpu.chaos.FaultPlan` grammar.

Telemetry: each REQUEST-path network failure emits one ``net_retry``
event and bumps ``raft_remote_net_errors_total{kind=...}`` in the
engine's local registry (health probes are deliberately unrecorded —
a 20 Hz supervisor poll during a partition would drown the stream).
"""

from __future__ import annotations

import dataclasses
import http.client
import io
import json
import socket
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from typing import List, Optional, Tuple

import numpy as np

from raft_tpu import chaos
from raft_tpu.obs import EventSink, MetricRegistry
from raft_tpu.obs import trace
from raft_tpu.obs.exposition import render as render_metrics
from raft_tpu.serve.engine import QueueFullError

CHAOS_POINT = "serve.remote"


# ---------------------------------------------------------------------------
# network-error taxonomy
# ---------------------------------------------------------------------------


class RemoteNetworkError(RuntimeError):
    """Base of the wire-failure taxonomy.  ``replica_fatal`` makes every
    subclass a failover signal to the router (the remote HOST is
    suspect); ``transient`` stays False except for timeouts — retrying
    a refused/reset connection on the same path cannot help."""

    transient = False
    replica_fatal = True
    kind = "network"


class RemoteRefusedError(RemoteNetworkError):
    """TCP connect refused — nothing is listening (host down, process
    dead, or a ``net_refuse`` chaos fire)."""

    kind = "refused"


class RemoteResetError(RemoteNetworkError):
    """Connection reset / broken pipe mid-request."""

    kind = "reset"


class RemoteTimeoutError(RemoteNetworkError):
    """Per-request deadline exceeded (or a ``net_partition`` chaos
    fire — a partition and a very slow host are the same observable).
    Marked transient: a deadline flake is worth one same-path retry."""

    transient = True
    kind = "timeout"


class RemoteDisconnectedError(RemoteNetworkError):
    """The server closed the connection mid-response: the request may
    or may not have executed remotely.  Safe to fail over — flow
    inference is idempotent."""

    kind = "disconnect"


class RemoteUnavailableError(RemoteNetworkError):
    """HTTP 503 from the remote — its own health gate is draining it
    (stalled engine, shed load).  Carries ``http_status`` so
    classification survives message rewording."""

    kind = "unavailable"
    http_status = 503


class RemoteProtocolError(RuntimeError):
    """The remote answered, but not in the protocol (unexpected status,
    unparseable body).  NOT a failover signal: a server speaking the
    wrong protocol will speak it to every retry too."""


def classify_network_error(exc: BaseException,
                           address: str = "?") -> RemoteNetworkError:
    """Map a stdlib transport exception onto the taxonomy.  Order
    matters: ``http.client.RemoteDisconnected`` subclasses
    ``ConnectionResetError``, and ``socket.timeout`` IS
    ``TimeoutError`` on modern Pythons."""
    if isinstance(exc, RemoteNetworkError):
        return exc
    msg = f"{type(exc).__name__}: {exc}"
    if isinstance(exc, http.client.RemoteDisconnected):
        return RemoteDisconnectedError(
            f"remote {address} disconnected mid-response ({msg})")
    if isinstance(exc, ConnectionRefusedError):
        return RemoteRefusedError(
            f"remote {address} refused the connection ({msg})")
    if isinstance(exc, (ConnectionResetError, BrokenPipeError,
                        ConnectionAbortedError)):
        return RemoteResetError(
            f"connection to remote {address} reset ({msg})")
    if isinstance(exc, (TimeoutError, socket.timeout)):
        return RemoteTimeoutError(
            f"request to remote {address} timed out ({msg})")
    return RemoteNetworkError(
        f"network error talking to remote {address} ({msg})")


# ---------------------------------------------------------------------------
# config
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class RemoteConfig:
    """Wire knobs for one :class:`RemoteEngine`."""

    #: TCP connect deadline for a fresh pooled connection.
    connect_timeout_s: float = 2.0
    #: Per-request deadline (``/v1/flow``, ``/v1/stream``).
    request_timeout_s: float = 120.0
    #: Deadline for ``GET /v1/healthz`` probes — tight, so a partition
    #: flips the health gate fast.
    health_timeout_s: float = 1.0
    #: Health snapshots are cached this long: router eligibility checks
    #: per request must not each cost a network round trip.
    health_cache_s: float = 0.2
    #: Keep-alive connections retained for reuse.
    pool_size: int = 8
    #: Client threads running requests (bounds in-flight concurrency).
    workers: int = 8
    #: Client-side in-flight bound; ``None`` learns the remote's own
    #: ``max_queue`` from ``/v1/stats`` (router spill math reads it via
    #: ``queue_capacity()``).
    max_queue: Optional[int] = None
    #: Added latency per ``net_slow`` chaos fire.
    chaos_slow_s: float = 0.05

    def __post_init__(self):
        for f in ("connect_timeout_s", "request_timeout_s",
                  "health_timeout_s"):
            if getattr(self, f) <= 0:
                raise ValueError(f"{f} must be > 0")
        if self.pool_size < 1 or self.workers < 1:
            raise ValueError("pool_size and workers must be >= 1")
        if self.max_queue is not None and self.max_queue < 1:
            raise ValueError("max_queue must be >= 1 (or None)")


# ---------------------------------------------------------------------------
# the engine facade over the wire
# ---------------------------------------------------------------------------


class RemoteEngine:
    """``InferenceEngine``-shaped client of one remote serving host.

    The facade contract (what ``FlowRouter`` / ``ReplicaFleet`` call):
    ``submit`` returns a Future, raises :class:`QueueFullError` at the
    client-side bound and lifecycle ``RuntimeError`` after ``stop()``;
    ``health()``/``stats()``/``metrics_text()`` mirror the engine
    introspection surface; ``stream_open``/``stream_ingest``/
    ``stream_close`` speak the streaming-session protocol."""

    def __init__(self, address: str,
                 cfg: RemoteConfig = RemoteConfig(), *,
                 name: str = "remote",
                 registry: Optional[MetricRegistry] = None,
                 sink: Optional[EventSink] = None):
        host, sep, port = address.rpartition(":")
        if not sep or not host or not port.isdigit():
            raise ValueError(
                f"remote replica spec {address!r}: expected HOST:PORT")
        self.address = address
        self.name = name
        self.cfg = cfg
        self._host, self._port = host, int(port)
        self.registry = registry or MetricRegistry()
        self._sink = sink if sink is not None else EventSink.from_env()
        self._net_errors = self.registry.counter(
            "raft_remote_net_errors_total",
            "request-path network failures talking to this remote "
            "replica, by taxonomy kind")
        self._pool = ThreadPoolExecutor(
            max_workers=cfg.workers,
            thread_name_prefix=f"raft-remote-{name}")
        self._conns: List[http.client.HTTPConnection] = []
        self._conn_lock = threading.Lock()
        self._pending = 0
        self._pending_lock = threading.Lock()
        self._stopped = False
        self._health_cache: Optional[dict] = None
        self._health_t = 0.0
        self._health_lock = threading.Lock()
        self._learned_max_queue: Optional[int] = None
        self.crashed: Optional[str] = None  # facade parity; never set

    # -- lifecycle ------------------------------------------------------

    def start(self) -> "RemoteEngine":
        """Facade parity no-op: the remote host owns its own engine
        lifecycle (and its own warmup/AOT artifacts)."""
        return self

    def stop(self, drain: bool = True, timeout: float = 30.0) -> None:
        self._stopped = True
        self._pool.shutdown(wait=drain)
        with self._conn_lock:
            conns, self._conns = self._conns, []
        for c in conns:
            try:
                c.close()
            except Exception:
                pass

    def warmup(self, image_shapes, batch_sizes=None) -> list:
        return []  # the remote host warmed itself at ITS bring-up

    def compiled_keys(self) -> list:
        return []

    def quality_drift(self) -> Optional[dict]:
        return None

    # -- wire plumbing --------------------------------------------------

    def _get_conn(self) -> http.client.HTTPConnection:
        with self._conn_lock:
            if self._conns:
                return self._conns.pop()
        return http.client.HTTPConnection(
            self._host, self._port, timeout=self.cfg.connect_timeout_s)

    def _put_conn(self, conn: http.client.HTTPConnection) -> None:
        with self._conn_lock:
            if not self._stopped and len(self._conns) < self.cfg.pool_size:
                self._conns.append(conn)
                return
        try:
            conn.close()
        except Exception:
            pass

    @staticmethod
    def _discard(conn: http.client.HTTPConnection) -> None:
        # A connection that saw ANY network error is never reused: its
        # stream state is unknowable (half-read response, dead socket).
        try:
            conn.close()
        except Exception:
            pass

    def _chaos_pre(self) -> None:
        """Pre-dispatch chaos checks for the ``serve.remote`` seam.
        ``net_partition`` and ``net_refuse`` raise before any socket
        work — deterministic regardless of network timing."""
        if not chaos.enabled():
            return
        if chaos.should_inject("net_refuse", point=CHAOS_POINT):
            raise RemoteRefusedError(
                f"remote {self.address} refused the connection "
                "(chaos net_refuse)")
        if chaos.should_inject("net_partition", point=CHAOS_POINT):
            raise RemoteTimeoutError(
                f"request to remote {self.address} timed out "
                "(chaos net_partition)")
        if chaos.should_inject("net_slow", point=CHAOS_POINT):
            time.sleep(self.cfg.chaos_slow_s)

    def _request(self, method: str, path: str, body: bytes = b"",
                 headers: Tuple[Tuple[str, str], ...] = (),
                 timeout: Optional[float] = None,
                 record: bool = True,
                 raise_503: bool = True):
        """One wire round trip -> ``(status, body_bytes)``.  Network
        failures (and chaos fires at this seam) raise classified
        :class:`RemoteNetworkError`\\ s; with ``record`` the failure is
        counted + emitted as a ``net_retry`` event.  ``raise_503=False``
        returns 503 responses instead (health probes read the body)."""
        timeout = timeout if timeout is not None \
            else self.cfg.request_timeout_s
        try:
            self._chaos_pre()
            # net_drop fires mid-response: the request goes out and the
            # server executes it, but the response never arrives.
            drop = chaos.enabled() and chaos.should_inject(
                "net_drop", point=CHAOS_POINT)
            conn = self._get_conn()
            try:
                conn.timeout = timeout
                if conn.sock is not None:
                    conn.sock.settimeout(timeout)
                conn.request(method, path, body=body,
                             headers=dict(headers))
                if drop:
                    raise RemoteDisconnectedError(
                        f"remote {self.address} disconnected "
                        "mid-response (chaos net_drop)")
                resp = conn.getresponse()
                status, data = resp.status, resp.read()
            except (OSError, http.client.HTTPException,
                    RemoteNetworkError) as e:
                self._discard(conn)
                raise classify_network_error(e, self.address) from \
                    (None if isinstance(e, RemoteNetworkError) else e)
            self._put_conn(conn)
        except RemoteNetworkError as err:
            self._note_net_error(err, path, record)
            raise
        if status == 503 and raise_503:
            err = RemoteUnavailableError(
                f"remote {self.address} unavailable (HTTP 503): "
                f"{data[:200]!r}")
            self._note_net_error(err, path, record)
            raise err
        return status, data

    def _note_net_error(self, err: RemoteNetworkError, path: str,
                        record: bool) -> None:
        if not record:
            return
        self._net_errors.inc(kind=err.kind)
        self._sink.emit("net_retry", replica=self.name,
                        address=self.address, kind=err.kind, path=path,
                        error=str(err)[:200])

    # -- request path ---------------------------------------------------

    def pending_count(self) -> int:
        with self._pending_lock:
            return self._pending

    def queue_capacity(self) -> Optional[int]:
        """Queue depth bound the router's spill math should use for
        this replica: the configured client-side bound, else the
        remote's own ``max_queue`` learned (once) from ``/v1/stats``."""
        if self.cfg.max_queue is not None:
            return self.cfg.max_queue
        if self._learned_max_queue is None:
            try:
                status, data = self._request(
                    "GET", "/v1/stats",
                    timeout=self.cfg.health_timeout_s, record=False)
                if status == 200:
                    mq = json.loads(data).get("max_queue")
                    if isinstance(mq, (int, float)) and mq > 0:
                        self._learned_max_queue = int(mq)
            except (RemoteNetworkError, ValueError):
                return None
        return self._learned_max_queue

    def submit(self, image1, image2) -> Future:
        """Dispatch one frame pair to the remote; returns a Future
        resolving to the ``(H, W, 2)`` flow.  Shape validation and the
        lifecycle/queue-full contract mirror the in-process engine so
        the router cannot tell the difference."""
        if self._stopped:
            raise RuntimeError(
                "engine stopped — engines are single-use; build a new "
                f"RemoteEngine for {self.address}")
        im1 = np.asarray(image1, dtype=np.float32)
        im2 = np.asarray(image2, dtype=np.float32)
        if im1.ndim != 3 or im1.shape[-1] != 3 or im1.shape != im2.shape:
            raise ValueError(
                f"expected two matching (H, W, 3) images, got "
                f"{im1.shape} and {im2.shape}")
        with self._pending_lock:
            if (self.cfg.max_queue is not None
                    and self._pending >= self.cfg.max_queue):
                raise QueueFullError(
                    f"remote {self.address}: {self._pending} requests "
                    "already in flight client-side",
                    queue_depth=self._pending, retry_after_s=1.0)
            self._pending += 1
        # Capture the SUBMITTING thread's span (the router's attempt
        # span under use_context) — the worker thread serializes it
        # into X-Raft-Trace so the hop stays in one trace tree.
        hdr = trace.format_header(trace.current())
        fut = self._pool.submit(self._do_flow, im1, im2, hdr)

        def _dec(_f):
            with self._pending_lock:
                self._pending -= 1

        fut.add_done_callback(_dec)
        return fut

    def infer(self, image1, image2,
              timeout: Optional[float] = None) -> np.ndarray:
        return self.submit(image1, image2).result(timeout=timeout)

    def _do_flow(self, im1, im2, hdr: Optional[str]) -> np.ndarray:
        buf = io.BytesIO()
        np.savez(buf, image1=im1, image2=im2)
        headers = [("Content-Type", "application/octet-stream")]
        if hdr:
            headers.append((trace.HEADER, hdr))
        status, data = self._request("POST", "/v1/flow",
                                     body=buf.getvalue(),
                                     headers=tuple(headers))
        if status == 200:
            with np.load(io.BytesIO(data)) as z:
                return np.asarray(z["flow"])
        self._raise_structured(status, data)

    def _raise_structured(self, status: int, data: bytes):
        """Map the wire protocol's structured error responses back onto
        the exceptions the in-process engine raises, so callers (and
        the router's failover policy) see identical behavior."""
        try:
            obj = json.loads(data)
        except ValueError:
            obj = {}
        if status == 429:
            raise QueueFullError(
                obj.get("error", f"remote {self.address} queue full"),
                queue_depth=int(obj.get("queue_depth", 0)),
                retry_after_s=float(obj.get("retry_after_s", 1.0)))
        if status in (400, 404, 409):
            raise ValueError(
                obj.get("error", f"remote {self.address} rejected the "
                                 f"request (HTTP {status})"))
        raise RemoteProtocolError(
            f"remote {self.address} returned HTTP {status}: "
            f"{obj.get('error', data[:200])}")

    # -- streaming sessions --------------------------------------------

    def _stream_post(self, session_id: str, image, query: str = "",
                     timeout: Optional[float] = None) -> dict:
        im = np.asarray(image, dtype=np.float32)
        buf = io.BytesIO()
        np.savez(buf, image=im)
        headers = [("Content-Type", "application/octet-stream")]
        hdr = trace.format_header(trace.current())
        if hdr:
            headers.append((trace.HEADER, hdr))
        status, data = self._request(
            "POST", f"/v1/stream/{session_id}{query}",
            body=buf.getvalue(), headers=tuple(headers),
            timeout=timeout)
        if status != 200:
            self._raise_structured(status, data)
        with np.load(io.BytesIO(data)) as z:
            return {"session": str(session_id),
                    "frame": int(z["frame"]),
                    "warm": bool(z["warm"]) if "warm" in z else False,
                    "flow": (np.asarray(z["flow"])
                             if "flow" in z.files else None)}

    def stream_open(self, session_id: str, image, *,
                    iters: Optional[int] = None,
                    ttl_s: Optional[float] = None) -> dict:
        parts = []
        if iters is not None:
            parts.append(f"iters={int(iters)}")
        if ttl_s is not None:
            parts.append(f"ttl_s={float(ttl_s):g}")
        query = ("?" + "&".join(parts)) if parts else ""
        return self._stream_post(session_id, image, query)

    def stream_ingest(self, session_id: str, image, *,
                      iters: Optional[int] = None,
                      ttl_s: Optional[float] = None,
                      timeout: Optional[float] = None) -> dict:
        # iters/ttl_s only apply at open on the wire protocol; the
        # router always opens via stream_open first.
        return self._stream_post(session_id, image, timeout=timeout)

    def stream_close(self, session_id: str) -> dict:
        status, data = self._request("DELETE",
                                     f"/v1/stream/{session_id}")
        if status != 200:
            self._raise_structured(status, data)
        return json.loads(data)

    # -- introspection --------------------------------------------------

    def health(self) -> dict:
        """Engine-shaped readiness snapshot, cached ``health_cache_s``
        (router eligibility checks are per request).  A wire failure
        reads as not-ready with the taxonomy kind attached — the health
        gate sidelines a partitioned host exactly like a crashed one."""
        now = time.monotonic()
        with self._health_lock:
            if (self._health_cache is not None
                    and now - self._health_t < self.cfg.health_cache_s):
                return dict(self._health_cache,
                            pending=self.pending_count())
        try:
            status, data = self._request(
                "GET", "/v1/healthz",
                timeout=self.cfg.health_timeout_s, record=False,
                raise_503=False)
        except RemoteNetworkError as e:
            h = {"ready": False, "accepting": False, "stalled": False,
                 "crashed": None, "seconds_since_last_batch": None,
                 "stall_timeout_s": 0.0, "remote": self.address,
                 "net_error": e.kind}
        else:
            if status == 200:
                h = {"ready": True, "accepting": True, "stalled": False,
                     "crashed": None, "seconds_since_last_batch": None,
                     "stall_timeout_s": 0.0, "remote": self.address}
            else:  # 503: the remote's own health gate said drain
                try:
                    detail = json.loads(data)
                except ValueError:
                    detail = {}
                h = dict(detail, ready=False, remote=self.address)
                h.setdefault("accepting", False)
                h.setdefault("stalled", False)
                h.setdefault("crashed", None)
        with self._health_lock:
            self._health_cache = h
            self._health_t = time.monotonic()
        return dict(h, pending=self.pending_count())

    def load_signals(self) -> dict:
        """Autoscaler inputs, client-side only (never a network call —
        the autoscaler ticks on the supervisor thread)."""
        pending = self.pending_count()
        cap = self.cfg.max_queue or self._learned_max_queue or 0
        return {"pending": pending, "max_queue": cap,
                "queue_frac": round(pending / cap, 4) if cap else 0.0,
                "occupancy": 0.0, "burn_rate": 0.0, "mfu": None,
                "latency_p95_ms": 0.0}

    def stats(self) -> dict:
        """The remote's ``/v1/stats`` snapshot overlaid with the
        client-side view (in-flight count, net-error taxonomy counts);
        degrades to the client-side view alone during a partition."""
        client = {
            "remote": self.address,
            "pending_client": self.pending_count(),
            "net_errors": {dict(k).get("kind", ""): v
                           for k, v in self._net_errors.items()},
        }
        try:
            status, data = self._request(
                "GET", "/v1/stats",
                timeout=self.cfg.health_timeout_s, record=False)
            remote = json.loads(data) if status == 200 else {}
        except (RemoteNetworkError, ValueError):
            remote = {"unreachable": True}
        return dict(remote, **client)

    def metrics_text(self) -> str:
        """The CLIENT-side registry only (net-error counters): the
        remote host's own ``/metrics`` is scraped at the remote host —
        proxying it here would double-count every sample."""
        return render_metrics(self.registry)


# ---------------------------------------------------------------------------
# the fleet-facing replica wrapper
# ---------------------------------------------------------------------------

# Imported here (not at module top) in spirit only — fleet.py imports
# THIS module lazily at its point of use, so this top-level import is
# the acyclic direction: remote -> fleet -> engine.
from raft_tpu.serve.fleet import Replica  # noqa: E402


class RemoteReplica(Replica):
    """Fleet member backed by a :class:`RemoteEngine` — same state
    machine, breaker, and generation counter as a local
    :class:`~raft_tpu.serve.fleet.Replica`, so the router needs no
    remote special case.

    Differences from a local replica, all supervisor-side:

    - ``is_remote`` gates the fleet paths that cannot apply across the
      wire: no engine rebuild on crash (the remote host supervises its
      OWN engine), no rolling weight flip (the remote host rolls its
      own weights), no AOT export.
    - :meth:`poll` replaces the crash/stall restart logic: it watches
      the health transition.  While the remote is unreachable the
      breaker + health gate sideline it (exactly like a crashed local
      replica); when it answers again the replica REJOINS — generation
      bumps and the breaker resets under the lock, so strikes earned
      against the partitioned generation cannot sideline the healed
      one (``fleet_remote_rejoin`` event)."""

    is_remote = True

    def __init__(self, index: int, address: str,
                 cfg: RemoteConfig = RemoteConfig()):
        super().__init__(index)
        self.address = address
        self.remote_cfg = cfg
        self._down_seen = False

    def start(self, sink: Optional[EventSink] = None) -> RemoteEngine:
        """Build + adopt the wire client (no device work, no warmup —
        the remote host warmed itself at its own bring-up)."""
        eng = RemoteEngine(self.address, self.remote_cfg,
                           name=self.name, sink=sink)
        self.adopt(eng)
        self.set_state("ready")
        return eng

    def pending(self) -> int:
        """Client-side in-flight count — the local replica's version of
        this costs a lock; a network round trip per placement decision
        would not fly."""
        eng = self.engine
        return 0 if eng is None else eng.pending_count()

    def poll(self, sink: Optional[EventSink] = None) -> None:
        """Supervisor hook (called at the fleet's health-poll cadence):
        track down -> up transitions and rejoin on heal."""
        eng = self.engine
        if eng is None:
            return
        ready = bool(eng.health().get("ready"))
        if not ready:
            self._down_seen = True
            return
        if not self._down_seen:
            return
        self._down_seen = False
        with self._lock:
            # Generation-guarded breaker reset: the router tags strikes
            # with the generation it struck, and a bumped generation
            # means those strikes belonged to the partition, not to the
            # healed host.
            self.generation += 1
            self._consec_failures = 0
            self._broken_until = 0.0
            gen = self.generation
        if sink is not None:
            sink.emit("fleet_remote_rejoin", replica=self.name,
                      address=self.address, generation=gen)
