"""Slot-state programs for iteration-granular continuous batching.

The serve hot path (``serve/engine.py``) runs the forward pass as two
compiled programs instead of one whole-request forward:

- ``encode_admit(variables, image1, image2, state, admit, budgets)``:
  run the pre-scan half (:class:`raft_tpu.models.raft.RAFTEncode`) over
  the full slot batch and scatter the results into the lanes selected
  by ``admit`` (a ``(S,)`` bool mask), leaving every other lane's
  device-resident state untouched.  Inference encoders are per-lane
  independent (instance norm / stored batch statistics), so encoding a
  batch that carries ballast in the non-admitted lanes produces the
  same bits for the admitted lanes as any other batch content would.
- ``iter_step(variables, state, threshold)``: one GRU refinement
  iteration (:class:`RAFTIterStep`) over every active lane, masked with
  ``lax``-selects so retired/free lanes are no-ops.  The per-lane
  convergence predicate (max flow-update magnitude below ``threshold``,
  SEA-RAFT-style early exit) and the iteration-budget check both run
  in-graph; lanes retiring THIS call get their flow upsampled
  (:class:`RAFTUpsample`) inside the same program, guarded by a
  ``lax.cond`` so iterations with no retiree skip the upsample.

The slot state is a flat dict pytree (sorted keys, so treedefs are
reproducible across processes for AOT export):

======================  =========================  =======================
key                     shape/dtype                meaning
======================  =========================  =======================
``active``              ``(S,) bool``              lane holds a live request
``budget``              ``(S,) int32``             per-lane max iterations
``converged``           ``(S,) bool``              early-exit predicate fired
``coords0``             ``(S, H/8, W/8, 2) f32``   base coordinate grid
``coords1``             ``(S, H/8, W/8, 2) f32``   current flow coordinates
``corr``                corr-state pytree          per-lane corr pyramid
``delta_max``           ``(S,) f32``               last step's max |Δflow|
``inp``                 ``(S, H/8, W/8, C)``       context features
``iters_done``          ``(S,) int32``             iterations consumed
``net``                 ``(S, H/8, W/8, C)``       GRU hidden state
======================  =========================  =======================

Both serve batching modes drive these same two compiled programs
(``batching=request`` in whole-batch lockstep — admit everyone, run
exactly ``iters`` steps with the threshold disabled — ``slot``
continuously), which is what makes slot-vs-request bitwise parity
structural rather than numerical luck: XLA specializes reduction and
fusion order per program, so the same math compiled into two different
programs can differ in the last ulp (see the note in
``models/raft.py``).

``threshold`` is a runtime f32 scalar, not a compile-time constant, so
sweeping it (``evaluate.py --early_exit_threshold``, autotune) never
recompiles.  ``threshold <= 0`` disables early exit: ``delta_max`` is a
max of norms, hence ``>= 0``, and the predicate is a strict ``<``.

Streaming sessions (docs/SERVING.md "Streaming sessions") add two more
programs over the SAME slot state — the state pytree above is untouched,
so ``iter_step`` and the AOT artifacts stay byte-compatible:

- ``stash_carry(variables, image2, carry, admit)``: after a session's
  first (cold) pair is admitted through the unmodified ``encode_admit``
  (single-frame bit parity is structural), stash frame 2's feature map
  and raw context-encoder output into the lane's *carry* — a separate
  ``{"ctx", "fmap"}`` pytree the iter program never sees.
- ``encode_warm(variables, image2, carry, state, admit, budgets)``:
  admit streamed frame N+1 with only the NEW image — ``fmap1`` comes
  from the carry (consecutive-frame identity), the lane's previous flow
  (``coords1 - coords0``, still device-resident from its retirement) is
  forward-warped in-graph into the ``coords1`` init, and the new
  frame's features are returned as the next carry.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.config import RAFTConfig
from raft_tpu.models.raft import (RAFTEncode, RAFTEncodeWarm,
                                  RAFTFrameFeatures, RAFTIterStep,
                                  RAFTUpsample)
from raft_tpu.ops.sampler import forward_warp_flow


def _lane_select(mask, new, old):
    """Per-leaf ``jnp.where`` with ``mask`` broadcast over a ``(S, ...)``
    leaf's trailing dims (leaves of any rank, including zero-size
    pyramid tails)."""
    m = mask.reshape(mask.shape + (1,) * (new.ndim - 1))
    return jnp.where(m, new, old.astype(new.dtype))


def _pack_state(net, inp, coords0, coords1, corr_state, active, budget,
                converged, delta_max, iters_done):
    # Plain dict: insertion order is irrelevant to jax (dict pytrees
    # flatten in sorted key order), matching the docstring table.
    return {
        "active": active,
        "budget": budget,
        "converged": converged,
        "coords0": coords0,
        "coords1": coords1,
        "corr": corr_state,
        "delta_max": delta_max,
        "inp": inp,
        "iters_done": iters_done,
        "net": net,
    }


def state_template(model_cfg: RAFTConfig, variables, slots: int,
                   bucket_hw: Tuple[int, int]) -> dict:
    """Host-side all-zeros slot state for ``slots`` lanes at bucket
    ``(H, W)`` — the engine's reset/initial state and the shape spec the
    programs are lowered against.  Built from ``jax.eval_shape`` of the
    encode program, so the corr-state leaf structure (including
    quantized ``QuantizedLevel`` levels) can never drift from what
    ``encode_admit`` actually produces."""
    H, W = bucket_hw
    spec = jax.ShapeDtypeStruct((slots, H, W, 3), jnp.float32)
    net, inp, coords0, coords1, corr = jax.eval_shape(
        RAFTEncode(model_cfg).apply, variables, spec, spec)
    zeros = lambda s: np.zeros(s.shape, dtype=s.dtype)
    lanes = lambda dt: np.zeros((slots,), dtype=dt)
    return _pack_state(
        zeros(net), zeros(inp), zeros(coords0), zeros(coords1),
        jax.tree_util.tree_map(zeros, corr),
        lanes(np.bool_), lanes(np.int32), lanes(np.bool_),
        np.full((slots,), -1.0, np.float32), lanes(np.int32))


def make_encode_fn(model_cfg: RAFTConfig):
    """``encode_admit(variables, image1, image2, state, admit, budgets)
    -> state'`` (pure; the engine jits/lowers it)."""
    enc = RAFTEncode(model_cfg)

    def encode_admit(variables, image1, image2, state, admit, budgets):
        net, inp, coords0, coords1, corr = enc.apply(
            variables, image1, image2)
        sel = lambda new, old: _lane_select(admit, new, old)
        return _pack_state(
            sel(net, state["net"]),
            sel(inp, state["inp"]),
            sel(coords0, state["coords0"]),
            sel(coords1, state["coords1"]),
            jax.tree_util.tree_map(sel, corr, state["corr"]),
            state["active"] | admit,
            jnp.where(admit, budgets.astype(jnp.int32), state["budget"]),
            state["converged"] & ~admit,
            jnp.where(admit, jnp.float32(-1.0), state["delta_max"]),
            jnp.where(admit, jnp.int32(0), state["iters_done"]),
        )

    return encode_admit


def carry_template(model_cfg: RAFTConfig, variables, slots: int,
                   bucket_hw: Tuple[int, int]) -> dict:
    """Host-side all-zeros streaming carry for ``slots`` lanes: the
    previous frame's feature map (``fmap``, fp32) and raw context
    output (``ctx``, model dtype), shaped via ``jax.eval_shape`` of the
    stash program so dtype/shape can never drift from what
    ``stash_carry`` produces.  Kept OUTSIDE the slot state on purpose:
    ``iter_step`` never reads it, so non-streaming engines pay nothing
    and existing AOT artifacts stay valid."""
    H, W = bucket_hw
    spec = jax.ShapeDtypeStruct((slots, H, W, 3), jnp.float32)
    fmap, ctx = jax.eval_shape(
        RAFTFrameFeatures(model_cfg).apply, variables, spec)
    zeros = lambda s: np.zeros(s.shape, dtype=s.dtype)
    return {"ctx": zeros(ctx), "fmap": zeros(fmap)}


def make_stash_fn(model_cfg: RAFTConfig):
    """``stash_carry(variables, image2, carry, admit) -> carry'``
    (pure; the engine jits/lowers it).

    Runs after a cold session admit: computes frame 2's features and
    scatters them into the admitted lanes' carry.  One extra encoder
    pass per session (frame 1 only) buys fmap reuse for every
    subsequent warm frame."""
    feats = RAFTFrameFeatures(model_cfg)

    def stash_carry(variables, image2, carry, admit):
        fmap, ctx = feats.apply(variables, image2)
        sel = lambda new, old: _lane_select(admit, new, old)
        return {"ctx": sel(ctx, carry["ctx"]),
                "fmap": sel(fmap, carry["fmap"])}

    return stash_carry


def make_warm_encode_fn(model_cfg: RAFTConfig):
    """``encode_warm(variables, image2, carry, state, admit, budgets)
    -> (state', carry')`` (pure; the engine jits/lowers it).

    The streaming admit: for lanes in ``admit``, frame 1 of the pair is
    the PREVIOUS streamed frame — its feature map and context come from
    the carry, so only ``image2`` (the new frame) runs through the
    encoders.  The lane's previous flow (``coords1 - coords0``, intact
    since its retirement: ``iter_step``'s masked commit never touches
    inactive lanes) is forward-warped on-device into the ``coords1``
    init — RAFT's video warm start.  The scatter semantics mirror
    :func:`make_encode_fn` exactly; ``carry'`` holds the new frame's
    features for the next warm frame."""
    enc = RAFTEncodeWarm(model_cfg)

    def encode_warm(variables, image2, carry, state, admit, budgets):
        flow_init = forward_warp_flow(state["coords1"] - state["coords0"])
        net, inp, coords0, coords1, corr, fmap2, ctx2 = enc.apply(
            variables, image2, carry["fmap"], carry["ctx"], flow_init)
        sel = lambda new, old: _lane_select(admit, new, old)
        new_state = _pack_state(
            sel(net, state["net"]),
            sel(inp, state["inp"]),
            sel(coords0, state["coords0"]),
            sel(coords1, state["coords1"]),
            jax.tree_util.tree_map(sel, corr, state["corr"]),
            state["active"] | admit,
            jnp.where(admit, budgets.astype(jnp.int32), state["budget"]),
            state["converged"] & ~admit,
            jnp.where(admit, jnp.float32(-1.0), state["delta_max"]),
            jnp.where(admit, jnp.int32(0), state["iters_done"]),
        )
        new_carry = {"ctx": sel(ctx2, carry["ctx"]),
                     "fmap": sel(fmap2, carry["fmap"])}
        return new_state, new_carry

    return encode_warm


def make_iter_fn(model_cfg: RAFTConfig):
    """``iter_step(variables, state, threshold) -> (state', flow_up)``
    (pure; the engine jits/lowers it).

    ``flow_up`` is the full-resolution ``(S, H, W, 2)`` flow; only rows
    whose lane retired THIS call (``active`` flipping true -> false)
    are meaningful — the engine reads exactly those.  When no lane
    retires, the upsample branch is skipped entirely (``lax.cond``) and
    ``flow_up`` is zeros."""
    step = RAFTIterStep(model_cfg)
    upsample = RAFTUpsample(model_cfg)

    def iter_step(variables, state, threshold):
        active = state["active"]
        net, coords1 = step.apply(
            variables, state["net"], state["coords1"], state["inp"],
            state["coords0"], state["corr"])
        # Masked commit: inactive lanes keep their state bit-for-bit
        # (free lanes carry zeros; a retired lane's state is dead until
        # the next admit overwrites it, but must not drift meanwhile).
        net = _lane_select(active, net, state["net"])
        coords1 = _lane_select(active, coords1, state["coords1"])

        delta = coords1 - state["coords1"]
        dmax = jnp.max(jnp.sqrt(jnp.sum(delta * delta, axis=-1)),
                       axis=(1, 2))
        dmax = jnp.where(active, dmax, state["delta_max"])
        iters_done = state["iters_done"] + active.astype(jnp.int32)
        converged = active & (dmax < threshold)
        done = active & (converged | (iters_done >= state["budget"]))

        flow_low = coords1 - state["coords0"]
        S, H8, W8 = flow_low.shape[0], flow_low.shape[1], flow_low.shape[2]

        def _upsample(operands):
            n, f = operands
            return upsample.apply(variables, n, f)

        def _skip(operands):
            return jnp.zeros((S, H8 * 8, W8 * 8, 2), jnp.float32)

        flow_up = jax.lax.cond(jnp.any(done), _upsample, _skip,
                               (net, flow_low))
        new_state = _pack_state(
            net, state["inp"], state["coords0"], coords1,
            state["corr"], active & ~done, state["budget"],
            state["converged"] | converged, dmax, iters_done)
        return new_state, flow_up

    return iter_step


class EarlyExitRunner:
    """Offline (non-engine) driver of the slot programs over one fixed
    batch: encode, then iterate until every lane retires, returning the
    per-lane flow at ITS retirement iteration plus ``iters_used``.

    This is the measurement arm for the ``evaluate.py
    --early_exit_threshold`` quality gate and the early-exit tests:
    same compiled programs the serve path runs, no dispatcher in the
    way.  ``jax.jit`` call-site caching keys on shapes only, so
    sweeping thresholds re-uses one compile per batch shape."""

    def __init__(self, model_cfg: RAFTConfig):
        self.model_cfg = model_cfg
        self._encode = jax.jit(make_encode_fn(model_cfg))
        self._iter = jax.jit(make_iter_fn(model_cfg))

    def run(self, variables, image1, image2, iters: int,
            threshold: float = 0.0, return_residuals: bool = False):
        """``(flow_up (B, H, W, 2) f32, iters_used (B,) i32)`` for a
        ``/8``-aligned batch.  ``threshold <= 0`` reproduces the full
        ``iters``-step baseline.

        ``return_residuals=True`` appends the per-lane convergence
        residual ``delta_max`` (max flow-update magnitude, flow units
        at 1/8 resolution) captured at EACH lane's retirement
        iteration — the in-graph quality proxy ``obs/quality.py``
        calibrates against EPE.  Off by default so the baseline path
        transfers exactly what it always did."""
        B = int(np.asarray(image1).shape[0])
        admit = jnp.ones((B,), jnp.bool_)
        budgets = jnp.full((B,), int(iters), jnp.int32)
        state = state_template(self.model_cfg, variables, B,
                               tuple(np.asarray(image1).shape[1:3]))
        state = self._encode(variables, image1, image2, state, admit,
                             budgets)
        thr = jnp.float32(threshold)
        out = None
        prev_active = np.ones((B,), bool)
        iters_used = np.zeros((B,), np.int32)
        residuals = np.full((B,), -1.0, np.float32)
        for _ in range(int(iters)):
            state, flow_up = self._iter(variables, state, thr)
            active = np.asarray(state["active"])
            newly = prev_active & ~active
            if newly.any():
                flow_np = np.asarray(flow_up)
                if out is None:
                    out = np.zeros(flow_np.shape, np.float32)
                out[newly] = flow_np[newly]
                iters_used[newly] = np.asarray(state["iters_done"])[newly]
                if return_residuals:
                    residuals[newly] = np.asarray(
                        state["delta_max"])[newly]
            prev_active = active
            if not active.any():
                break
        assert out is not None and not prev_active.any(), \
            "lanes left active after their budget — iter_step retire " \
            "logic is broken"
        if return_residuals:
            return out, iters_used, residuals
        return out, iters_used
