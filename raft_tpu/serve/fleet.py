"""Self-healing replica fleet: N supervised engines behind one router
(docs/SERVING.md, "Fleet serving").

One :class:`~raft_tpu.serve.engine.InferenceEngine` is a single point
of failure: a crashed device worker takes every in-flight and future
request with it.  The fleet owns N engine replicas and keeps the
*service* alive across individual replica death:

- **Supervision** — a supervisor thread polls each replica's
  ``health()``; a crashed or stalled engine is stopped and REPLACED
  (engines are single-use) with a fresh one, after an exponential
  backoff with jitter (``restart_backoff_s`` doubling to
  ``restart_backoff_max_s``; the jitter keeps co-scheduled replicas
  from thundering back in lock step).  ``max_restart_failures``
  consecutive failed *rebuild attempts* mark the replica ``failed`` —
  a replica that cannot even construct an engine is a config problem,
  not a transient.
- **AOT warm-start** — replica 0 compiles the warmup ladder once and
  exports the executables (``raft_tpu/serve/aot.py``); every later
  engine build — fleet bring-up, supervised restart, rolling-update
  warming — imports them and serves its first request with **zero JIT
  compiles**.  The executable takes the variables pytree as a runtime
  argument, so the same artifact warm-starts an engine carrying NEW
  weights.
- **Rolling weight updates** — :meth:`ReplicaFleet.update_weights`
  loads checkpoint N+1 on a *warming* engine while the fleet keeps
  serving N, and flips replicas one at a time (old engine drains, new
  one takes over atomically under the replica lock) ONLY after the
  gates pass: the checkpoint verifies (an actual restore of the newest
  step — the only check that proves the bytes decode, same machinery
  as ``verify-ckpt``), a canary inference on the warming engine
  returns finite flow of the right shape, and a golden-batch QUALITY
  comparison (label-free photometric proxy, ``obs/quality.py``) shows
  the new weights not regressing beyond
  ``FleetConfig.canary_proxy_budget`` vs the live fleet on
  deterministic low-motion frames.  A torn checkpoint, a NaN-producing
  weight set, or finite-but-garbage weights (wild flow the old
  shape+finiteness canary waved through) is refused with
  :class:`WeightUpdateError`; the fleet keeps serving version N.
- **Quality-drift surfacing** — the supervisor also polls each
  replica's quality drift detectors (``engine.quality_drift()``,
  populated when ``ServeConfig.quality_sample_rate > 0``) and
  forwards new firings as ``fleet_quality_drift`` events +
  ``raft_fleet_quality_drift_total``, so one stream shows WHICH
  replica's serving quality walked away from its reference.

The fleet does placement-free supervision only; request routing
(affinity, failover, hedging) lives in
:class:`raft_tpu.serve.router.FlowRouter`, which reads this module's
:class:`Replica` objects.  ``metrics_text()`` aggregates every
replica's registry into one exposition, each sample labeled
``replica="rK"``, with fleet-level counters (restarts, weight updates)
on top — one scrape shows the whole fleet.
"""

from __future__ import annotations

import dataclasses
import os
import tempfile
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from raft_tpu.obs import EventSink, MetricRegistry
from raft_tpu.obs.exposition import render as render_metrics
from raft_tpu.serve.engine import InferenceEngine, ServeConfig


class WeightUpdateError(RuntimeError):
    """A rolling weight update was refused at a gate (checkpoint failed
    to verify, canary inference failed, golden-batch proxy score
    regressed past ``canary_proxy_budget``) or could not complete.
    The fleet keeps serving its current weights."""


def _golden_frames(h: int, w: int, seed: int = 0,
                   shift: Tuple[int, int] = (2, 1)):
    """Deterministic low-motion golden frame pair for the proxy canary:
    a smoothed random image and a small-translation crop of it.

    Smooth content + small true motion means any sane weight set —
    including a freshly initialized one predicting near-zero flow —
    scores a LOW photometric canary (the residual a couple of pixels
    of uncompensated shift causes on blurred content is tiny), while
    scrambled weights produce wild flow that lands out of bounds or on
    unrelated content and score high.  That separation, not absolute
    accuracy, is what the rolling-update gate needs."""
    rng = np.random.default_rng(seed)
    pad = 8
    base = rng.uniform(0.0, 255.0, (h + 2 * pad, w + 2 * pad, 3))
    kern = np.ones(9) / 9.0
    for ax in (0, 1):
        base = np.apply_along_axis(
            lambda m: np.convolve(m, kern, mode="same"), ax, base)
    lo, hi = float(base.min()), float(base.max())
    base = (base - lo) / max(hi - lo, 1e-6) * 255.0
    dy, dx = shift
    im1 = base[pad:pad + h, pad:pad + w].astype(np.float32)
    im2 = base[pad - dy:pad - dy + h,
               pad - dx:pad - dx + w].astype(np.float32)
    return im1, im2


def _golden_proxy_scores(engine: InferenceEngine, shapes) -> dict:
    """Label-free quality scores of ``engine``'s weights on the golden
    frames, one inference per shape; the scalar ``score`` (mean canary
    score: photometric error + out-of-bounds fraction,
    ``obs/quality.py``) is what the update gate compares."""
    from raft_tpu.obs import quality as quality_mod

    per = []
    for (h, w) in shapes:
        im1, im2 = _golden_frames(int(h), int(w))
        flow = engine.infer(im1, im2, timeout=300)
        s = quality_mod.score_pair(im1, im2, flow)
        per.append({"shape": [int(h), int(w)],
                    "photometric": round(s["photometric"], 5),
                    "valid_frac": round(s["valid_frac"], 4),
                    "canary": round(s["canary"], 5)})
    return {"score": round(float(np.mean([p["canary"] for p in per])),
                           5),
            "per_shape": per}


@dataclasses.dataclass(frozen=True)
class FleetConfig:
    """Fleet supervision knobs (:class:`ReplicaFleet`)."""

    replicas: int = 2
    #: Restart backoff ladder: ``restart_backoff_s * 2^k`` capped at
    #: ``restart_backoff_max_s``, ±``restart_jitter`` fraction.  The
    #: level resets after a replica stays healthy ``backoff_reset_s``.
    restart_backoff_s: float = 0.2
    restart_backoff_max_s: float = 10.0
    restart_jitter: float = 0.2
    backoff_reset_s: float = 30.0
    #: Consecutive failed engine REBUILDS (not crashes) before a
    #: replica is marked ``failed`` and left down.
    max_restart_failures: int = 5
    health_poll_s: float = 0.1
    #: AOT artifact directory (default: a fresh temp dir per fleet).
    aot_dir: Optional[str] = None
    #: Export replica 0's compiled executables after warmup so later
    #: engine builds import instead of compiling.
    auto_export_aot: bool = True
    #: Raw (H, W) image shapes replica 0 pre-compiles at bring-up (and
    #: the canary shapes for weight updates, unless overridden).
    warmup_shapes: Tuple[Tuple[int, int], ...] = ()
    canary_shapes: Tuple[Tuple[int, int], ...] = ()
    drain_timeout_s: float = 30.0
    #: Golden-batch proxy gate for rolling weight updates: the warming
    #: engine's label-free quality score (obs/quality.py canary score:
    #: photometric error + out-of-bounds fraction, on deterministic
    #: low-motion golden frames) may regress at most this RELATIVE
    #: fraction vs the live fleet's score before the update is refused
    #: (3.0 = the new weights may score up to 4x worse).  The budget is
    #: deliberately loose: legitimate weight swaps (even between
    #: unrelated random inits) score within ~1.6x of each other on the
    #: golden frames, while scrambled-but-finite weights produce wild
    #: flow and blow past 16x — exactly the failure mode the PR 8
    #: shape+finiteness canary waved through.  ``None`` disables the
    #: proxy gate (shape/finiteness checks still run).  Uses
    #: ``canary_shapes``/``warmup_shapes``; with neither configured the
    #: proxy gate is skipped (scoring an unwarmed shape would compile).
    canary_proxy_budget: Optional[float] = 3.0
    #: Remote replica specs (``HOST:PORT``) joined behind the same
    #: router (docs/SERVING.md "Multi-host fabric").  Remote replicas
    #: are supervised by health transitions only — the remote HOST owns
    #: its engine lifecycle, weights, and warmup.
    remote: Tuple[str, ...] = ()
    #: Wire knobs for the remote replicas — a
    #: :class:`raft_tpu.serve.remote.RemoteConfig` (left untyped to keep
    #: the remote module out of the fleet's import graph); ``None``
    #: uses its defaults.
    remote_cfg: Optional[object] = None
    #: Elastic autoscaling of LOCAL replicas (``autoscale_max = 0``
    #: disables it; otherwise ``autoscale_min <= replicas <=
    #: autoscale_max`` must hold).  Signals, hysteresis, and cooldown:
    #: pressure must persist ``autoscale_up_consecutive`` autoscaler
    #: ticks before a grow (fleet queue_frac over
    #: ``autoscale_up_queue_frac``, or SLO burn over
    #: ``autoscale_up_burn_rate`` when set) and
    #: ``autoscale_down_consecutive`` idle ticks before a shrink; every
    #: move opens an ``autoscale_cooldown_s`` window in which no further
    #: move is allowed — the fleet never flaps on one noisy sample.
    autoscale_min: int = 0
    autoscale_max: int = 0
    autoscale_interval_s: float = 2.0
    autoscale_up_queue_frac: float = 0.5
    autoscale_up_burn_rate: Optional[float] = None
    autoscale_down_queue_frac: float = 0.05
    autoscale_up_consecutive: int = 3
    autoscale_down_consecutive: int = 5
    autoscale_cooldown_s: float = 30.0

    def __post_init__(self):
        if self.replicas < 1:
            raise ValueError("replicas must be >= 1")
        if self.restart_backoff_max_s < self.restart_backoff_s:
            raise ValueError(
                "restart_backoff_max_s must be >= restart_backoff_s")
        if not 0 <= self.restart_jitter < 1:
            raise ValueError("restart_jitter must be in [0, 1)")
        if self.max_restart_failures < 1:
            raise ValueError("max_restart_failures must be >= 1")
        if (self.canary_proxy_budget is not None
                and self.canary_proxy_budget <= 0):
            raise ValueError(
                "canary_proxy_budget must be > 0 (None disables the "
                "proxy gate)")
        if self.autoscale_max:
            if self.autoscale_min < 1:
                raise ValueError(
                    "autoscale_min must be >= 1 when autoscaling is on")
            if not (self.autoscale_min <= self.replicas
                    <= self.autoscale_max):
                raise ValueError(
                    f"replicas ({self.replicas}) must start inside "
                    f"[autoscale_min, autoscale_max] = "
                    f"[{self.autoscale_min}, {self.autoscale_max}]")
            if self.autoscale_interval_s <= 0:
                raise ValueError("autoscale_interval_s must be > 0")
            if (self.autoscale_up_consecutive < 1
                    or self.autoscale_down_consecutive < 1):
                raise ValueError(
                    "autoscale_*_consecutive must be >= 1")
            if self.autoscale_cooldown_s < 0:
                raise ValueError("autoscale_cooldown_s must be >= 0")


class _LabeledSink:
    """EventSink wrapper stamping ``replica=<name>`` onto every event an
    engine emits, so one shared JSONL stream stays attributable."""

    def __init__(self, inner: EventSink, **labels):
        self._inner = inner
        self._labels = labels

    def emit(self, event, step=None, **fields):
        self._inner.emit(event, step=step, **{**self._labels, **fields})

    def relabel(self, **labels):
        """Re-stamp (the warming engine becomes a named replica when a
        rolling update adopts it)."""
        self._labels.update(labels)

    def __getattr__(self, name):
        return getattr(self._inner, name)


class Replica:
    """One supervised fleet member: a name (``rK``), the CURRENT engine
    (swapped under the lock on restart and weight flip), a state
    machine, and the router-facing breaker state.

    States: ``init`` → ``ready`` ⇄ ``restarting`` → ``failed`` /
    ``stopped``.  ``generation`` increments on every engine swap — the
    router uses it to avoid striking a fresh engine for its
    predecessor's failures."""

    def __init__(self, index: int):
        self.index = index
        self.name = f"r{index}"
        self._lock = threading.RLock()
        self._engine: Optional[InferenceEngine] = None
        self.state = "init"
        self.restarts = 0
        self.generation = 0
        # breaker (router-managed, supervisor-reset)
        self._consec_failures = 0
        self._broken_until = 0.0
        # supervisor book-keeping
        self.restart_failures = 0
        self.backoff_level = 0
        self.ready_since: Optional[float] = None

    @property
    def engine(self) -> Optional[InferenceEngine]:
        with self._lock:
            return self._engine

    def adopt(self, engine: InferenceEngine) -> Optional[InferenceEngine]:
        """Atomically make ``engine`` this replica's engine; returns the
        previous one (caller stops/drains it).  Resets the breaker —
        strikes belong to the old engine."""
        with self._lock:
            old, self._engine = self._engine, engine
            self.generation += 1
            self._consec_failures = 0
            self._broken_until = 0.0
            return old

    def set_state(self, state: str) -> None:
        with self._lock:
            self.state = state
            if state == "ready":
                self.ready_since = time.monotonic()

    def pending(self) -> int:
        eng = self.engine
        if eng is None:
            return 0
        return int(eng.health()["pending"])

    def queue_capacity(self) -> Optional[int]:
        """THIS replica's admission-queue bound, read through the
        engine facade — heterogeneous fleets (a remote with a different
        ``max_queue``) must not be spilled against a shared config's
        capacity.  ``None`` when unknowable (no engine, or a remote
        that has not learned its bound yet)."""
        eng = self.engine
        if eng is None:
            return None
        fn = getattr(eng, "queue_capacity", None)
        return fn() if fn is not None else None

    def breaker_open(self) -> bool:
        with self._lock:
            return time.monotonic() < self._broken_until

    def note_failure(self, threshold: int, cooldown_s: float) -> bool:
        """One failover-class strike.  Returns True when THIS strike
        transitioned the breaker from closed to open — the caller
        (router) emits the ``fleet_breaker_open`` event exactly once
        per open, not once per strike."""
        with self._lock:
            was_open = time.monotonic() < self._broken_until
            self._consec_failures += 1
            if self._consec_failures >= threshold:
                self._broken_until = time.monotonic() + cooldown_s
                return not was_open
            return False

    def note_success(self) -> None:
        with self._lock:
            self._consec_failures = 0

    def eligible(self) -> bool:
        """Router health gate: ready state, closed breaker, and the
        engine itself reporting ready."""
        with self._lock:
            if self.state != "ready" or self._engine is None:
                return False
            if time.monotonic() < self._broken_until:
                return False
            eng = self._engine
        return bool(eng.health()["ready"])


class ReplicaFleet:
    """See module docstring.  Lifecycle::

        fleet = ReplicaFleet(variables, model_cfg, serve_cfg,
                             FleetConfig(replicas=2,
                                         warmup_shapes=[(436, 1024)]))
        fleet.start()
        router = FlowRouter(fleet)
        flow = router.infer(image1, image2)
        fleet.update_weights("/ckpts/run1")   # rolling, gated
        fleet.stop()
    """

    def __init__(self, variables, model_cfg,
                 serve_cfg: ServeConfig = ServeConfig(),
                 fleet_cfg: FleetConfig = FleetConfig(), *,
                 registry: Optional[MetricRegistry] = None,
                 sink: Optional[EventSink] = None):
        self.model_cfg = model_cfg
        self.serve_cfg = serve_cfg
        self.fleet_cfg = fleet_cfg
        self.registry = registry or MetricRegistry()
        self._sink = sink if sink is not None else EventSink.from_env()
        self._variables = variables
        self._var_lock = threading.Lock()
        self.weights_version = 1
        self.replicas: List[Replica] = [
            Replica(i) for i in range(fleet_cfg.replicas)]
        if fleet_cfg.remote:
            # Lazy import: remote.py imports this module's Replica, so
            # the fleet must not import remote at module load.
            from raft_tpu.serve.remote import (RemoteConfig,
                                               RemoteReplica)

            rcfg = fleet_cfg.remote_cfg or RemoteConfig()
            for addr in fleet_cfg.remote:
                self.replicas.append(
                    RemoteReplica(len(self.replicas), addr, rcfg))
        #: Next fresh replica index — scale-ups keep numbering past any
        #: retired names (a drained r2 never comes back as a different
        #: engine under the same name).
        self._next_index = len(self.replicas)
        self._router = None
        self.aot_dir = fleet_cfg.aot_dir or tempfile.mkdtemp(
            prefix="raft-aot-")
        self._started = False
        self._stop_event = threading.Event()
        self._supervisor: Optional[threading.Thread] = None
        self._update_lock = threading.Lock()
        self._warming: Optional[InferenceEngine] = None
        self._backoff_rng = np.random.default_rng(0)

        self._restarts = self.registry.counter(
            "raft_fleet_restarts_total",
            "supervised replica restarts, by replica and reason")
        self._weight_updates = self.registry.counter(
            "raft_fleet_weight_updates_total",
            "rolling weight updates, by outcome")
        self._quality_drifts = self.registry.counter(
            "raft_fleet_quality_drift_total",
            "replica-local quality_drift firings surfaced by the "
            "supervisor, by replica and proxy")
        self._scale_events = self.registry.counter(
            "raft_fleet_scale_events_total",
            "autoscaler replica-count changes, by direction")
        # Autoscaler state (supervisor thread only): hysteresis streaks,
        # the cooldown window, and the flap count (direction reversals
        # — the check_regression.py --max-scale-flaps gate reads it off
        # the fleet_scale events).
        self._scale_last_dir: Optional[str] = None
        self._scale_flaps = 0
        self._scale_ups = 0
        self._scale_downs = 0
        self._scale_cooldown_until = 0.0
        self._up_streak = 0
        self._down_streak = 0
        self._autoscale_next = 0.0
        # Quality-drift dedup per (replica, engine generation, proxy)
        # and the cached golden-batch reference scores of the CURRENT
        # serving weights (update_weights' proxy gate; invalidated by
        # each successful flip — the new weights become the reference).
        self._drift_seen: Dict[tuple, int] = {}
        self._golden_ref: Optional[dict] = None
        self._pending_golden: Optional[dict] = None
        self._replica_gauge = self.registry.gauge(
            "raft_fleet_replicas", "replicas by current state")
        self._version_gauge = self.registry.gauge(
            "raft_fleet_weights_version", "serving weights version")
        self.registry.add_collect_hook(self._collect)
        # Incident correlation (obs/incident.py): the fleet owns ONE
        # manager observing the SHARED sink — every engine's
        # _LabeledSink writes through it, so a cross-replica cascade
        # (kill on r0, retries on r1) correlates into one incident.
        # _build_engine forces incidents=False on the engines for the
        # same reason: N engine-level observers on one stream would
        # open N incidents for one cascade.
        self._incidents = None
        if serve_cfg.incidents:
            from raft_tpu.obs import incident as incident_mod

            self._incidents = incident_mod.IncidentManager(
                registry=self.registry,
                window_s=serve_cfg.incident_window_s,
                quiet_close_s=serve_cfg.incident_quiet_s,
                cooldown_s=serve_cfg.incident_cooldown_s)
            self._incidents.attach(self._sink)
            self._incidents.recorder.add_provider("fleet_stats",
                                                  self.stats)
            self._incidents.recorder.add_provider(
                "serve_config",
                lambda: dataclasses.asdict(self.serve_cfg))
            self._incidents.recorder.add_provider(
                "fleet_config",
                lambda: dataclasses.asdict(self.fleet_cfg))

    def bind_router(self, router) -> None:
        """The router registers itself at construction
        (``FlowRouter.__init__``) so graceful scale-down can evacuate
        the victim's streaming sessions (``router.evacuate``) before
        the drain — sessions survive the shrink via ``stream_restart``
        replay instead of dying with their lane."""
        self._router = router

    def _collect(self, _reg) -> None:
        states: Dict[str, int] = {}
        for r in self.replicas:
            states[r.state] = states.get(r.state, 0) + 1
        for state, n in states.items():
            self._replica_gauge.set(n, state=state)
        self._version_gauge.set(self.weights_version)

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    def _build_engine(self, variables=None,
                      replica: str = "?") -> InferenceEngine:
        with self._var_lock:
            v = self._variables if variables is None else variables
        cfg = dataclasses.replace(self.serve_cfg, aot_dir=self.aot_dir,
                                  incidents=False)
        return InferenceEngine(v, self.model_cfg, cfg,
                               sink=_LabeledSink(self._sink,
                                                 replica=replica))

    def start(self) -> "ReplicaFleet":
        """Bring the fleet up: replica 0 warms (compiling whatever the
        AOT artifact doesn't already cover) and exports the compile
        cache; the rest import it and come up compile-free."""
        if self._started:
            raise RuntimeError("fleet already started")
        self._started = True
        for r in self.replicas:
            if getattr(r, "is_remote", False):
                # The remote host owns its engine lifecycle; this side
                # only builds the wire client (no device work).
                r.start(sink=self._sink)
                continue
            eng = self._build_engine(replica=r.name)
            eng.start()
            if self.fleet_cfg.warmup_shapes:
                # Cache-hit no-op on replicas whose AOT import covered
                # the ladder; compiles on replica 0 (or any AOT miss).
                eng.warmup(self.fleet_cfg.warmup_shapes)
            r.adopt(eng)
            r.set_state("ready")
            if (r.index == 0 and self.fleet_cfg.auto_export_aot
                    and eng.compiled_keys()):
                try:
                    eng.export_aot(self.aot_dir)
                except Exception as e:  # export is an optimization,
                    self._sink.emit(     # never a bring-up failure
                        "aot_export_error", error=str(e)[:300])
        self._supervisor = threading.Thread(
            target=self._supervise, name="raft-fleet-supervisor",
            daemon=True)
        self._supervisor.start()
        self._sink.emit("fleet_start", replicas=len(self.replicas),
                        aot_dir=self.aot_dir)
        return self

    def stop(self, drain: bool = True, timeout: float = 30.0) -> None:
        """Stop supervision, any in-progress warming engine, and every
        replica (optionally draining in-flight work).  Safe to call
        while a weight update's warmup is mid-flight: the warming
        engine is stopped and the update fails cleanly."""
        self._stop_event.set()
        if self._supervisor is not None:
            self._supervisor.join(timeout=30)
            self._supervisor = None
        warming = self._warming
        if warming is not None:
            try:
                warming.stop(drain=False, timeout=10)
            except Exception:
                pass
        for r in self.replicas:
            eng = r.engine
            r.set_state("stopped")
            if eng is not None:
                eng.stop(drain=drain, timeout=timeout)
        self._sink.emit("fleet_stop")
        if self._incidents is not None:
            self._incidents.close()

    def __enter__(self) -> "ReplicaFleet":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # ------------------------------------------------------------------
    # supervision
    # ------------------------------------------------------------------

    def _supervise(self) -> None:
        poll = self.fleet_cfg.health_poll_s
        while not self._stop_event.wait(poll):
            # Snapshot: _scale_up/_scale_down swap self.replicas
            # copy-on-write on this same thread, but a snapshot keeps
            # the iteration obviously safe either way.
            for r in list(self.replicas):
                if self._stop_event.is_set():
                    return
                if getattr(r, "is_remote", False):
                    # No rebuild across the wire: poll() watches the
                    # health transition and rejoins the replica
                    # (generation bump + breaker reset) when the
                    # partition heals.
                    r.poll(self._sink)
                    continue
                if r.state != "ready":
                    continue
                eng = r.engine
                if eng is None:
                    continue
                if eng.crashed:
                    self._restart(r, "crash")
                    continue
                if eng.health()["stalled"]:
                    self._restart(r, "stall")
                    continue
                if (r.backoff_level
                        and r.ready_since is not None
                        and time.monotonic() - r.ready_since
                        > self.fleet_cfg.backoff_reset_s):
                    r.backoff_level = 0
                self._note_quality_drift(r, eng)
            if (self.fleet_cfg.autoscale_max
                    and time.monotonic() >= self._autoscale_next):
                self._autoscale_next = (
                    time.monotonic()
                    + self.fleet_cfg.autoscale_interval_s)
                try:
                    self._autoscale_tick()
                except Exception as e:
                    # A failed scale move must not kill supervision.
                    self._sink.emit(
                        "fleet_scale_error",
                        error=f"{type(e).__name__}: {str(e)[:300]}")
            if self._incidents is not None:
                # Quiet-close poll: an incident over a stream that went
                # silent still closes (and writes its final bundle)
                # without waiting for another event.
                self._incidents.poll()

    def _note_quality_drift(self, r: Replica,
                            eng: InferenceEngine) -> None:
        """Forward each NEW replica-local quality_drift firing
        (engine drift detectors, obs/quality.py) as one fleet-level
        ``fleet_quality_drift`` event + counter bump.  Dedup is per
        (replica, engine generation, proxy): a restarted or flipped
        engine starts a fresh drift history."""
        try:
            drift = eng.quality_drift()
        except Exception:
            return
        if not drift:
            return
        for proxy, st in drift.items():
            events = int(st.get("events", 0))
            key = (r.name, r.generation, proxy)
            if events > self._drift_seen.get(key, 0):
                self._drift_seen[key] = events
                self._quality_drifts.inc(replica=r.name, proxy=proxy)
                self._sink.emit("fleet_quality_drift", replica=r.name,
                                proxy=proxy, score=st.get("score"),
                                events=events)

    def _backoff(self, level: int) -> float:
        cfg = self.fleet_cfg
        base = min(cfg.restart_backoff_s * 2 ** max(level - 1, 0),
                   cfg.restart_backoff_max_s)
        return base * (1.0 + cfg.restart_jitter
                       * float(self._backoff_rng.uniform(-1, 1)))

    def _restart(self, r: Replica, reason: str) -> None:
        """Stop the dead engine, back off, build + adopt a fresh one
        (AOT-imported: its first request compiles nothing).  Runs on
        the supervisor thread; restarts are sequential by design."""
        r.set_state("restarting")
        old = r.engine
        crash_reason = getattr(old, "crashed", None)
        self._sink.emit("fleet_restart_begin", replica=r.name,
                        reason=reason, crash=crash_reason)
        if old is not None:
            try:
                # drain=False: a crashed/wedged engine cannot finish its
                # queue; fail the stragglers fast so the router fails
                # them over while we rebuild.
                old.stop(drain=False, timeout=10)
            except Exception as e:
                self._sink.emit("fleet_restart_stop_error",
                                replica=r.name, error=str(e)[:300])
        while not self._stop_event.is_set():
            r.backoff_level += 1
            backoff = self._backoff(r.backoff_level)
            if self._stop_event.wait(backoff):
                return
            try:
                eng = self._build_engine(replica=r.name)
                eng.start()
            except Exception as e:
                r.restart_failures += 1
                self._sink.emit("fleet_restart_error", replica=r.name,
                                attempt=r.restart_failures,
                                error=f"{type(e).__name__}: "
                                      f"{str(e)[:300]}")
                if (r.restart_failures
                        >= self.fleet_cfg.max_restart_failures):
                    r.set_state("failed")
                    self._sink.emit("fleet_replica_failed",
                                    replica=r.name,
                                    attempts=r.restart_failures)
                    return
                continue
            r.adopt(eng)
            r.restarts += 1
            r.restart_failures = 0
            r.set_state("ready")
            self._restarts.inc(replica=r.name, reason=reason)
            self._sink.emit("fleet_restart", replica=r.name,
                            reason=reason, restarts=r.restarts,
                            backoff_s=round(backoff, 4),
                            aot=dict(eng.aot_info))
            return

    # ------------------------------------------------------------------
    # elastic autoscaling (supervisor thread)
    # ------------------------------------------------------------------

    def _local_replicas(self) -> List[Replica]:
        return [r for r in self.replicas
                if not getattr(r, "is_remote", False)]

    def load_signals(self) -> dict:
        """Fleet-wide load snapshot over ELIGIBLE replicas: queue
        pressure summed (pending and capacity pool across the fleet),
        burn rate / occupancy / latency taken at the worst replica
        (a saturated straggler is the scaling signal even when the
        mean looks fine).  Cheap by contract — every per-engine
        ``load_signals()`` reads locks/atomics only."""
        pending = cap = 0
        burn = occ = lat = 0.0
        mfu = None
        for r in list(self.replicas):
            if not r.eligible():
                continue
            eng = r.engine
            fn = getattr(eng, "load_signals", None)
            if fn is None:
                continue
            try:
                sig = fn()
            except Exception:
                continue
            pending += int(sig.get("pending") or 0)
            cap += int(sig.get("max_queue") or 0)
            burn = max(burn, float(sig.get("burn_rate") or 0.0))
            occ = max(occ, float(sig.get("occupancy") or 0.0))
            lat = max(lat, float(sig.get("latency_p95_ms") or 0.0))
            m = sig.get("mfu")
            if m is not None:
                mfu = m if mfu is None else max(mfu, m)
        return {"pending": pending, "max_queue": cap,
                "queue_frac": round(pending / cap, 4) if cap else 0.0,
                "burn_rate": round(burn, 4),
                "occupancy": round(occ, 4), "mfu": mfu,
                "latency_p95_ms": round(lat, 2)}

    def _autoscale_tick(self) -> None:
        """One scaling decision.  Hysteresis + cooldown keep it from
        flapping: pressure must persist ``autoscale_up_consecutive``
        ticks (idle: ``autoscale_down_consecutive``) before a move, and
        each move blocks further moves for ``autoscale_cooldown_s``."""
        cfg = self.fleet_cfg
        sig = self.load_signals()
        up = sig["queue_frac"] >= cfg.autoscale_up_queue_frac
        if cfg.autoscale_up_burn_rate is not None:
            up = up or sig["burn_rate"] >= cfg.autoscale_up_burn_rate
        down = (not up
                and (sig["pending"] == 0
                     or sig["queue_frac"]
                     <= cfg.autoscale_down_queue_frac))
        self._up_streak = self._up_streak + 1 if up else 0
        self._down_streak = self._down_streak + 1 if down else 0
        if time.monotonic() < self._scale_cooldown_until:
            return
        ready_locals = [r for r in self._local_replicas()
                        if r.state == "ready"]
        n = len(ready_locals)
        if (up and self._up_streak >= cfg.autoscale_up_consecutive
                and n < cfg.autoscale_max):
            self._scale_up(sig)
        elif (down
              and self._down_streak >= cfg.autoscale_down_consecutive
              and n > cfg.autoscale_min):
            # Victim = the NEWEST ready local replica: the longest-lived
            # ones hold the warmest affinity history.
            self._scale_down(ready_locals[-1], sig)

    def _scale_up(self, sig: dict) -> None:
        """Grow by one local replica.  The AOT artifact replica 0
        exported at bring-up makes this compile-free: the new engine
        imports the executables and serves its first request without a
        JIT compile."""
        t0 = time.perf_counter()
        r = Replica(self._next_index)
        self._next_index += 1
        eng = self._build_engine(replica=r.name)
        eng.start()
        if self.fleet_cfg.warmup_shapes:
            eng.warmup(self.fleet_cfg.warmup_shapes)
        r.adopt(eng)
        r.set_state("ready")
        # Copy-on-write: the router iterates fleet.replicas lock-free.
        self.replicas = self.replicas + [r]
        self._note_scale("up", r, sig, time.perf_counter() - t0)

    def _scale_down(self, victim: Replica, sig: dict) -> None:
        """Shrink by one local replica, gracefully: mark it draining
        (placement stops immediately), move its streaming sessions to
        siblings (``stream_restart reason=scale_down`` replay), then
        drain in-flight work to completion — zero dropped requests by
        construction."""
        t0 = time.perf_counter()
        victim.set_state("draining")
        moved: list = []
        if self._router is not None:
            try:
                moved = self._router.evacuate(victim.name,
                                              reason="scale_down")
            except Exception as e:
                self._sink.emit(
                    "fleet_scale_error", replica=victim.name,
                    error=f"evacuate: {type(e).__name__}: "
                          f"{str(e)[:300]}")
        eng = victim.engine
        if eng is not None:
            eng.stop(drain=True,
                     timeout=self.fleet_cfg.drain_timeout_s)
        victim.set_state("stopped")
        self.replicas = [r for r in self.replicas if r is not victim]
        self._note_scale("down", victim, sig,
                         time.perf_counter() - t0, moved=len(moved))

    def _note_scale(self, direction: str, r: Replica, sig: dict,
                    seconds: float, **extra) -> None:
        if (self._scale_last_dir is not None
                and self._scale_last_dir != direction):
            self._scale_flaps += 1
        self._scale_last_dir = direction
        if direction == "up":
            self._scale_ups += 1
        else:
            self._scale_downs += 1
        self._scale_cooldown_until = (
            time.monotonic() + self.fleet_cfg.autoscale_cooldown_s)
        self._up_streak = self._down_streak = 0
        self._scale_events.inc(direction=direction)
        self._sink.emit("fleet_scale", direction=direction,
                        replica=r.name, replicas=len(self.replicas),
                        flaps=self._scale_flaps,
                        seconds=round(seconds, 3), signals=sig,
                        **extra)

    # ------------------------------------------------------------------
    # rolling weight updates
    # ------------------------------------------------------------------

    def update_weights(self, source) -> dict:
        """Roll the fleet onto new weights with zero downtime.

        ``source`` is a checkpoint directory (bare-pytree or orbax
        run layout — run layouts are integrity-verified by actually
        restoring the newest step first) or an in-memory variables
        pytree.  Gates: verify-ckpt, then a canary inference on the
        warming engine (finite flow, correct shape), then the
        golden-batch quality-proxy comparison vs the live fleet
        (``canary_proxy_budget``).  Only after all pass does any
        serving replica flip; flips are one replica at a
        time, atomic per replica, old engine drained.  Raises
        :class:`WeightUpdateError` at any gate — the fleet keeps
        serving its current weights."""
        with self._update_lock:
            if not self._started or self._stop_event.is_set():
                raise WeightUpdateError("fleet is not running")
            t0 = time.perf_counter()
            warming = None
            try:
                new_vars, provenance = self._load_verified(source)
                warming = self._build_engine(new_vars,
                                             replica="warming")
                self._warming = warming
                warming.start()
                if self.fleet_cfg.warmup_shapes:
                    warming.warmup(self.fleet_cfg.warmup_shapes)
                canary = self._canary(warming)
            except Exception as e:
                self._abort_update_locked(warming, e)
                if isinstance(e, WeightUpdateError):
                    raise
                raise WeightUpdateError(
                    f"weight update aborted before any flip: "
                    f"{type(e).__name__}: {e}") from e
            # Commit point: gates passed.  New builds (supervised
            # restarts racing this update) must pick up the NEW
            # weights or the fleet would serve two versions forever.
            with self._var_lock:
                self._variables = new_vars
            flipped = []
            try:
                for r in list(self.replicas):
                    if self._stop_event.is_set():
                        break
                    if getattr(r, "is_remote", False):
                        continue  # the remote host rolls its own weights
                    if r.state != "ready":
                        continue  # supervisor rebuilds it on new vars
                    if warming is not None:
                        new_eng, warming = warming, None
                        self._warming = None
                        if isinstance(new_eng._sink, _LabeledSink):
                            new_eng._sink.relabel(replica=r.name)
                    else:
                        new_eng = self._build_engine(new_vars,
                                                     replica=r.name)
                        new_eng.start()
                    old = r.adopt(new_eng)
                    if old is not None:
                        old.stop(drain=True,
                                 timeout=self.fleet_cfg.drain_timeout_s)
                    flipped.append(r.name)
            except Exception as e:
                self._abort_update_locked(warming, e, flipped=flipped)
                raise WeightUpdateError(
                    f"weight update failed mid-roll (flipped: "
                    f"{flipped}; unflipped replicas rebuild onto the "
                    f"new weights on their next restart): "
                    f"{type(e).__name__}: {e}") from e
            if warming is not None:  # no ready replica consumed it
                self._warming = None
                warming.stop(drain=False, timeout=5)
            self.weights_version += 1
            if self._pending_golden is not None:
                # The flipped weights are the new golden reference for
                # the NEXT update's proxy comparison.
                self._golden_ref = self._pending_golden
                self._pending_golden = None
            self._weight_updates.inc(ok="true")
            report = {"ok": True, "version": self.weights_version,
                      "flipped": flipped, "provenance": provenance,
                      "canary": canary,
                      "seconds": round(time.perf_counter() - t0, 3)}
            self._sink.emit("fleet_weight_update", **report)
            return report

    def _abort_update_locked(self, warming, exc,
                             flipped: Optional[list] = None) -> None:
        # Caller holds self._update_lock (both call sites sit inside
        # update_weights' critical section; the *_locked suffix is the
        # lock-discipline convention — docs/ANALYSIS.md, LOCK201).
        self._warming = None
        self._pending_golden = None
        if warming is not None:
            try:
                warming.stop(drain=False, timeout=5)
            except Exception:
                pass
        self._weight_updates.inc(ok="false")
        self._sink.emit("fleet_weight_update", ok=False,
                        flipped=flipped or [],
                        error=f"{type(exc).__name__}: {str(exc)[:300]}")

    def _load_verified(self, source):
        """Load + integrity-gate new weights.  Never let a torn write
        through: for run-layout checkpoints the newest step is restored
        template-less (``CheckpointManager.verify``) BEFORE the model
        load; there is deliberately NO fallback to an older step — a
        silent version downgrade is worse than a refused update."""
        if isinstance(source, dict):
            return source, {"kind": "pytree"}
        path = os.path.abspath(str(source))
        if not os.path.isdir(path):
            raise WeightUpdateError(f"checkpoint dir not found: {path}")
        provenance: dict = {"kind": "dir", "path": path}
        if not os.path.exists(os.path.join(path, "_METADATA")):
            from raft_tpu.train.checkpoint import CheckpointManager

            mgr = CheckpointManager(path, async_save=False)
            try:
                steps = mgr.all_steps()
                if not steps:
                    raise WeightUpdateError(
                        f"no checkpoint steps under {path}")
                newest = max(steps)
                report = mgr.verify(newest)
                if not report["ok"]:
                    raise WeightUpdateError(
                        f"verify-ckpt gate failed for step {newest}: "
                        f"{report.get('error')}")
                provenance.update(step=newest, verified=True)
            finally:
                mgr.close()
        try:
            from raft_tpu.cli.evaluate import load_model_variables

            new_vars = load_model_variables(path)
        except WeightUpdateError:
            raise
        except Exception as e:
            raise WeightUpdateError(
                f"failed to load weights from {path}: "
                f"{type(e).__name__}: {e}") from e
        return self._conform(new_vars), provenance

    def _conform(self, new_vars):
        """Align EMPTY-container differences between the loaded tree and
        the serving tree (checkpoint layouts differ on whether an unused
        ``batch_stats`` collection exists) so an AOT-covered update
        keeps its zero-compile warm start — the executable's input
        pytree must match exactly.  Real structural changes (leaves
        added/removed) pass through untouched: the AOT fingerprint
        refuses the artifact and the warming engine falls back to lazy
        compiles, which is correct, just slower."""
        import jax

        with self._var_lock:
            cur = self._variables
        if not isinstance(new_vars, dict):
            return new_vars
        if "batch_stats" in cur and "batch_stats" not in new_vars:
            return dict(new_vars, batch_stats={})
        if ("batch_stats" not in cur and "batch_stats" in new_vars
                and not jax.tree_util.tree_leaves(
                    new_vars["batch_stats"])):
            return {k: v for k, v in new_vars.items()
                    if k != "batch_stats"}
        return new_vars

    def _canary(self, warming: InferenceEngine) -> dict:
        """Canary gates: the warming engine must (1) produce finite
        flow of the right shape on synthetic frames, and (2) not
        regress the golden-batch quality proxy beyond
        ``canary_proxy_budget`` relative to the live fleet
        (``obs/quality.py`` canary score on deterministic low-motion
        frames — the check that catches finite-but-garbage weights the
        contract checks wave through).  The proxy comparison is
        skipped (recorded, not failed) when no live replica can score
        the reference or no canary/warmup shapes are configured."""
        shapes = (self.fleet_cfg.canary_shapes
                  or self.fleet_cfg.warmup_shapes or ((64, 96),))
        rng = np.random.default_rng(0)
        report = []
        for (h, w) in shapes:
            im1 = rng.uniform(0, 255, (h, w, 3)).astype(np.float32)
            im2 = rng.uniform(0, 255, (h, w, 3)).astype(np.float32)
            try:
                flow = warming.infer(im1, im2, timeout=300)
            except Exception as e:
                raise WeightUpdateError(
                    f"canary inference failed at {h}x{w}: "
                    f"{type(e).__name__}: {e}") from e
            if flow.shape != (h, w, 2):
                raise WeightUpdateError(
                    f"canary flow shape {flow.shape} != {(h, w, 2)}")
            if not np.isfinite(flow).all():
                raise WeightUpdateError(
                    f"canary flow at {h}x{w} contains non-finite "
                    "values — refusing to roll these weights out")
            report.append({"shape": [h, w],
                           "flow_abs_mean":
                               round(float(np.abs(flow).mean()), 4)})
        return {"frames": report,
                "proxy": self._canary_proxy_gate_locked(warming)}

    def _canary_proxy_gate_locked(self, warming: InferenceEngine
                           ) -> Optional[dict]:
        budget = self.fleet_cfg.canary_proxy_budget
        shapes = (self.fleet_cfg.canary_shapes
                  or self.fleet_cfg.warmup_shapes)
        if budget is None or not shapes:
            return None
        ref = self._live_golden_scores_locked(shapes)
        try:
            new = _golden_proxy_scores(warming, shapes)
        except Exception as e:
            raise WeightUpdateError(
                f"canary proxy scoring failed: "
                f"{type(e).__name__}: {e}") from e
        self._pending_golden = new
        if ref is None:
            return {"skipped": "no live reference replica",
                    "new": new["score"]}
        delta = ((new["score"] - ref["score"])
                 / max(abs(ref["score"]), 1e-6))
        ok = delta <= budget
        proxy = {"old": ref["score"], "new": new["score"],
                 "delta_pct": round(100.0 * delta, 2),
                 "budget_pct": round(100.0 * budget, 2),
                 "ok": ok}
        self._sink.emit("fleet_canary_proxy", **proxy)
        if not ok:
            raise WeightUpdateError(
                f"canary proxy regression: golden-batch quality score "
                f"{new['score']:.4f} vs live {ref['score']:.4f} "
                f"({100.0 * delta:+.1f}% > canary_proxy_budget "
                f"{100.0 * budget:.0f}%) — refusing to roll these "
                f"weights out")
        return proxy

    def _live_golden_scores_locked(self, shapes) -> Optional[dict]:
        """Golden-batch scores of the CURRENT serving weights, lazily
        computed on the first eligible replica and cached until a
        successful flip replaces them (the flipped weights become the
        next update's reference)."""
        if self._golden_ref is not None:
            return self._golden_ref
        for r in self.replicas:
            if not r.eligible():
                continue
            eng = r.engine
            if eng is None:
                continue
            try:
                self._golden_ref = _golden_proxy_scores(eng, shapes)
                return self._golden_ref
            except Exception as e:
                self._sink.emit("fleet_canary_proxy_ref_error",
                                replica=r.name,
                                error=f"{type(e).__name__}: "
                                      f"{str(e)[:300]}")
                return None
        return None

    # ------------------------------------------------------------------
    # introspection
    # ------------------------------------------------------------------

    def health(self) -> dict:
        """Fleet readiness: ready ⇔ at least one replica is eligible
        for traffic.  Per-replica detail nested under ``replicas``."""
        reps = {}
        for r in self.replicas:
            eng = r.engine
            h = eng.health() if eng is not None else {"ready": False}
            extra = {}
            drift = (eng.quality_drift() if eng is not None else None)
            if drift is not None:
                extra["quality_drifted"] = any(
                    bool(s.get("drifted")) for s in drift.values())
            reps[r.name] = dict(h, state=r.state, restarts=r.restarts,
                                generation=r.generation,
                                breaker_open=r.breaker_open(), **extra)
        return {"ready": any(r.eligible() for r in self.replicas),
                "weights_version": self.weights_version,
                "replicas": reps}

    def stats(self) -> dict:
        reps = {}
        for r in self.replicas:
            eng = r.engine
            s = eng.stats() if eng is not None else {}
            reps[r.name] = dict(s, state=r.state, restarts=r.restarts,
                                generation=r.generation)
        return {
            "fleet": {
                "replicas": len(self.replicas),
                "weights_version": self.weights_version,
                "restarts_total": int(sum(
                    v for _, v in self._restarts.items())),
                "aot_dir": self.aot_dir,
                "incidents": (self._incidents.snapshot()
                              if self._incidents is not None
                              else {"enabled": False}),
                "autoscale": {
                    "enabled": bool(self.fleet_cfg.autoscale_max),
                    "min": self.fleet_cfg.autoscale_min,
                    "max": self.fleet_cfg.autoscale_max,
                    "ups": self._scale_ups,
                    "downs": self._scale_downs,
                    "flaps": self._scale_flaps,
                },
            },
            "replicas": reps,
        }

    def metrics_text(self) -> str:
        """One Prometheus exposition for the whole fleet: fleet-level
        series (restarts, weight updates, router counters — the router
        registers on this registry) plus every replica's engine
        registry with a ``replica`` label merged onto each sample."""
        parts = [render_metrics(self.registry)]
        for r in self.replicas:
            eng = r.engine
            if eng is not None:
                parts.append(render_metrics(
                    eng.registry, extra_labels={"replica": r.name}))
        return "".join(parts)
