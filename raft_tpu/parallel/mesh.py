"""Device mesh + sharding helpers (the XLA-collective replacement for the
reference's ``nn.DataParallel``, train.py:138 / SURVEY.md C16).

Design: a 2-D ``(data, spatial)`` mesh.  Data parallelism shards the batch
over ``data`` (gradient psum rides ICI, inserted by XLA from the sharding
annotations — no hand-written collectives).  The ``spatial`` axis is
reserved for sharding the correlation volume / feature maps over image
height for very large inputs (the long-context analog; SURVEY.md §5);
size 1 until explicitly requested.

Multi-host: each process constructs the same global mesh from
``jax.devices()`` and feeds only its addressable shard of the batch
(``raft_tpu.data.ShardedLoader`` handles the per-host slicing) —
DCN-vs-ICI placement is XLA's job, not ours.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
SPATIAL_AXIS = "spatial"


def make_mesh(num_data: Optional[int] = None, num_spatial: int = 1,
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build the ``(data, spatial)`` mesh.  Defaults to all devices on the
    data axis — RAFT at 5.3M params wants pure DP (SURVEY.md C16)."""
    devices = list(devices if devices is not None else jax.devices())
    if num_data is None:
        assert len(devices) % num_spatial == 0
        num_data = len(devices) // num_spatial
    n = num_data * num_spatial
    assert n <= len(devices), (num_data, num_spatial, len(devices))
    grid = np.asarray(devices[:n]).reshape(num_data, num_spatial)
    return Mesh(grid, (DATA_AXIS, SPATIAL_AXIS))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Leading (batch) dim sharded over the data axis."""
    return NamedSharding(mesh, P(DATA_AXIS))


def spatial_batch_sharding(mesh: Mesh) -> NamedSharding:
    """Batch over ``data`` AND image height over ``spatial`` — the
    long-context analog for RAFT (SURVEY.md §5): activations, the
    correlation pyramid's query rows, and the refinement state are split
    across chips by image rows, and GSPMD inserts the conv halo exchanges
    and cross-shard reductions (instance-norm statistics, all-pairs
    fmap2 gathers) automatically.  Use for inputs too large for one
    chip's HBM (720p+ all-pairs volumes)."""
    return NamedSharding(mesh, P(DATA_AXIS, SPATIAL_AXIS))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def mesh_shape(mesh: Mesh) -> Dict[str, int]:
    """``{'data': N, 'spatial': K}`` — the serializable topology stamp
    checkpoints record so a restore on a DIFFERENT mesh can report what
    the run was saved under (docs/ROBUSTNESS.md "Elastic resume")."""
    return {name: int(size)
            for name, size in zip(mesh.axis_names, mesh.devices.shape)}


def abstract_replicated(tree, mesh: Mesh):
    """Abstract (shape/dtype/sharding-only) view of ``tree`` with every
    leaf replicated over ``mesh`` — the reshard-on-restore template.

    Handing orbax an abstract template that CARRIES the target sharding
    makes the restore place bytes directly onto the new topology,
    whatever mesh (or device count) the checkpoint was saved under;
    restoring against concrete arrays instead would pin the layout to
    the template's (old) placement.  Params/opt_state are replicated in
    this repo (train/step.py), so ``P()`` everywhere is exact."""
    sh = replicated_sharding(mesh)
    return jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(np.shape(x), x.dtype, sharding=sh),
        tree)


def make_batch_sharder(mesh: Mesh, spatial: bool = False):
    """Build ``put(batch) -> sharded batch``: the host->device placement
    closure with the sharding and the single/multi-host branch resolved
    ONCE (the device-prefetch producer calls it once per batch from a
    background thread; ``raft_tpu/data/prefetch.py``).

    Single-host: a plain sharded ``device_put`` — dispatch is async, so
    the call returns as soon as the transfer is enqueued and the H2D copy
    itself overlaps whatever the device is running.  Multi-host: each
    process passes its *local* batch (its stride of the global shuffle
    from ``ShardedLoader``) and the global array is assembled from the
    process-local shards — the global batch is ``num_hosts * local_batch``.
    """
    sh = spatial_batch_sharding(mesh) if spatial else batch_sharding(mesh)
    if jax.process_count() == 1:
        def put(batch):
            return jax.tree_util.tree_map(
                lambda x: jax.device_put(x, sh), batch)
    else:
        def put(batch):
            return jax.tree_util.tree_map(
                lambda x: jax.make_array_from_process_local_data(sh, x),
                batch)
    return put


def shard_batch(batch: Dict[str, np.ndarray], mesh: Mesh,
                spatial: bool = False):
    """Place a host batch onto the mesh, batch-dim sharded over ``data``
    (and, with ``spatial=True``, image height over ``spatial``).

    One-shot form of :func:`make_batch_sharder` (see there for the
    single/multi-host semantics)."""
    return make_batch_sharder(mesh, spatial=spatial)(batch)
