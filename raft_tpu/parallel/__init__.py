"""SPMD parallelism: device mesh construction and sharding helpers."""

from raft_tpu.parallel.mesh import (  # noqa: F401
    abstract_replicated,
    make_mesh,
    batch_sharding,
    make_batch_sharder,
    mesh_shape,
    replicated_sharding,
    shard_batch,
    spatial_batch_sharding,
)
