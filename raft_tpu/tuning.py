"""Persisted per-hardware tuning registry for the performance knob surface.

The CPU bench went 18.8 -> 74.8 image-pairs/sec/chip (BENCH_r01 -> r03)
purely by hand-tuning the knobs ``BENCH_r03.json`` records (``corr_impl``,
``corr_dtype``, ``scan_unroll``, ``remat``, ``fuse_upsample_in_scan``,
``upsample_loss_kernel``, bucket/batch sizes).  Those winners are
HARDWARE facts, not code facts — a v5e picks differently from a v4 or a
CPU dev box — so this module turns them into a durable per-hardware
capability: ``scripts/autotune.py`` sweeps the cross-product on the
local machine and persists winners here, keyed by

    (kind, device_kind, bucket_hw, batch)

where ``kind`` is the workload ('train' | 'eval' | 'serve'),
``device_kind`` is ``jax.devices()[0].device_kind`` (e.g. 'TPU v5e',
'cpu'), ``bucket_hw`` the /8-aligned input shape and ``batch`` the
per-chip batch.  Every entry carries provenance (tool, time, host,
measured throughput, sweep id) so a BENCH_r0x series can always say
whether its knobs came from autotune or a human.

Consumers — ``make_train_step`` (raft_tpu/train/step.py),
``make_inference_model`` / ``make_eval_fn`` (raft_tpu/evaluate.py) and
``ServeEngine`` (raft_tpu/serve/engine.py) — consult the registry BY
DEFAULT through :func:`resolve_config`: a knob is overridden only while
it still sits at its ``RAFTConfig`` class default (i.e. the user left it
alone); anything the user pinned wins unconditionally.  Precedence,
highest first::

    explicit user knob  >  registry entry  >  RAFTConfig default

Lookup falls back to the NEAREST bucket of the same (kind, device_kind)
— bucket winners are smooth in shape, so the 368x496 entry is a far
better guess for 400x720 than the hand-rolled defaults — but never
across device kinds (a v5e winner is noise on a CPU).

Environment overrides:

- ``RAFT_TUNING=0``     — disable all registry consultation (A/B).
- ``RAFT_TUNING_REGISTRY=/path.json`` — registry file (default
  ``~/.cache/raft_tpu/tuning.json``).

The file is plain JSON, written atomically (tmp + rename), merge-on-save
so concurrent tools only ever lose a race, not the file.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import socket
import time
import warnings
from typing import Dict, Optional, Sequence, Tuple, Union

from raft_tpu.config import RAFTConfig

ENV_REGISTRY = "RAFT_TUNING_REGISTRY"
ENV_DISABLE = "RAFT_TUNING"

REGISTRY_VERSION = 1

# The knob surface the registry may set (every RAFTConfig performance
# knob that bench sweeps have moved at least once).  Anything else in an
# entry is ignored with a warning — a registry written by a newer build
# degrades, it doesn't crash.
TUNABLE_KNOBS = (
    "corr_impl", "corr_dtype", "corr_precision", "corr_block_size",
    "lookup_block_q", "remat", "remat_policy", "scan_unroll",
    "remat_upsample", "upsample_dtype", "upsample_group",
    "upsample_unroll", "upsample_loss_kernel", "fuse_upsample_in_scan",
    "fused_lookup_encoder", "fused_gru",
)

# ServeConfig-level knobs a kind='serve' entry may additionally carry
# (the continuous-batching dispatcher surface — batching mode, slot
# count, early-exit cut, iteration budget).  They resolve through
# :func:`resolve_serve_config`, never through :func:`resolve_config`
# (which only touches RAFTConfig).
SERVE_TUNABLE_KNOBS = ("batching", "slots", "early_exit_threshold",
                       "iters")

_CONFIG_DEFAULTS = {f.name: f.default
                    for f in dataclasses.fields(RAFTConfig)}

_warned_paths = set()


@dataclasses.dataclass(frozen=True)
class TuningInfo:
    """What :func:`resolve_config` did — the provenance stamp carried
    into bench/telemetry ``config`` blocks (``tuned`` / ``tuning_key`` /
    ``tuning_registry_hash``)."""

    tuned: bool
    key: Optional[str] = None
    exact: bool = True
    applied: Dict[str, object] = dataclasses.field(default_factory=dict)
    pinned: Dict[str, object] = dataclasses.field(default_factory=dict)
    registry_path: Optional[str] = None
    registry_hash: Optional[str] = None

    def stamp(self) -> Dict[str, object]:
        """The three provenance fields every emitted config block
        carries (bench.py, scripts/telemetry_summary.py)."""
        out = {"tuned": self.tuned}
        if self.tuned:
            out["tuning_key"] = self.key
            out["tuning_registry_hash"] = self.registry_hash
            if not self.exact:
                out["tuning_fallback"] = "nearest-bucket"
        return out


def enabled() -> bool:
    """Registry consultation on?  ``RAFT_TUNING=0`` turns it off."""
    return os.environ.get(ENV_DISABLE, "1") not in ("0", "off", "false")


def device_kind() -> str:
    """The local accelerator identity the winners are keyed by."""
    import jax

    return jax.devices()[0].device_kind


def default_registry_path() -> str:
    env = os.environ.get(ENV_REGISTRY)
    if env:
        return env
    return os.path.join(os.path.expanduser("~"), ".cache", "raft_tpu",
                        "tuning.json")


def registry_key(kind: str, device: str,
                 bucket_hw: Optional[Tuple[int, int]],
                 batch: Optional[int]) -> str:
    hw = "anyhw" if bucket_hw is None else f"{bucket_hw[0]}x{bucket_hw[1]}"
    b = "anyb" if batch is None else f"b{batch}"
    return "|".join((kind, device, hw, b))


def load_registry(path: Optional[str] = None) -> dict:
    """The parsed registry file ({'version', 'entries': {key: entry}});
    missing or corrupt files yield an empty registry (corrupt warns once
    per path — silently ignoring a half-written file would look exactly
    like 'the autotuner never ran')."""
    path = path or default_registry_path()
    if not os.path.exists(path):
        return {"version": REGISTRY_VERSION, "entries": {}}
    try:
        with open(path) as f:
            data = json.load(f)
        if not isinstance(data.get("entries"), dict):
            raise ValueError("no 'entries' mapping")
        return data
    except (OSError, ValueError) as e:
        if path not in _warned_paths:
            _warned_paths.add(path)
            warnings.warn(f"tuning registry {path!r} unreadable "
                          f"({type(e).__name__}: {e}); ignoring it")
        return {"version": REGISTRY_VERSION, "entries": {}}


def registry_file_hash(path: Optional[str] = None) -> Optional[str]:
    """Short content hash of the registry file (provenance stamp), or
    None when the file doesn't exist."""
    path = path or default_registry_path()
    try:
        with open(path, "rb") as f:
            return hashlib.sha256(f.read()).hexdigest()[:12]
    except OSError:
        return None


def save_entry(kind: str, bucket_hw: Tuple[int, int], batch: int,
               knobs: Dict[str, object],
               provenance: Optional[Dict[str, object]] = None,
               path: Optional[str] = None,
               device: Optional[str] = None) -> str:
    """Merge one winner into the registry file (atomic tmp+rename).

    Returns the entry key.  Unknown knob names are rejected here — the
    WRITE side is strict so the tolerant read side never has anything to
    tolerate from our own tools.  ``kind='serve'`` entries may carry the
    ServeConfig knob surface (:data:`SERVE_TUNABLE_KNOBS`) on top of the
    model knobs."""
    allowed = set(TUNABLE_KNOBS)
    if kind == "serve":
        allowed |= set(SERVE_TUNABLE_KNOBS)
    bad = sorted(set(knobs) - allowed)
    if bad:
        raise ValueError(f"unknown tunable knob(s) {bad}; allowed: "
                         f"{', '.join(sorted(allowed))}")
    path = path or default_registry_path()
    device = device or device_kind()
    key = registry_key(kind, device, bucket_hw, batch)
    reg = load_registry(path)
    reg["version"] = REGISTRY_VERSION
    reg["entries"][key] = {
        "kind": kind,
        "device_kind": device,
        "bucket_hw": list(bucket_hw),
        "batch": int(batch),
        "knobs": dict(knobs),
        "provenance": dict(provenance or {}, host=socket.gethostname(),
                           updated=time.time()),
    }
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(reg, f, indent=2, sort_keys=True)
    os.replace(tmp, path)
    return key


def _bucket_distance(a: Sequence[int], b: Tuple[int, int]) -> float:
    """Log-area distance plus a mild aspect penalty: the 368x496 entry
    should beat the 288x960 one for a 400x720 query even though their
    areas straddle it."""
    import math

    area = math.log(max(a[0] * a[1], 1) / max(b[0] * b[1], 1))
    aspect = math.log((a[1] / max(a[0], 1)) / (b[1] / max(b[0], 1)))
    return abs(area) + 0.5 * abs(aspect)


# Nearest-bucket fallback is only trusted within this distance (log-area
# + aspect units; ~4.5x area).  Knob winners are smooth ACROSS NEARBY
# crops — chairs (368x496) transfers to things (400x720, d≈0.60) — but
# not across regimes: the chairs winners (scan_unroll=12, no remat)
# actively hurt at beyond-HBM shapes (unroll-12 crashed the 1440x2560
# compile, round 4) and at toy shapes, whose d from chairs is >= 3.
# Beyond the cutoff the config defaults are the safer guess.
_MAX_FALLBACK_DISTANCE = 1.5


def lookup(kind: Union[str, Sequence[str]],
           bucket_hw: Optional[Tuple[int, int]] = None,
           batch: Optional[int] = None,
           device: Optional[str] = None,
           path: Optional[str] = None):
    """Best registry entry for this workload on this hardware.

    Returns ``(key, entry, exact)`` or ``None``.  ``kind`` may be a
    preference list (the serve engine tries 'serve' then 'eval').
    Exact ``(kind, device, bucket, batch)`` hits win; otherwise the
    NEAREST bucket/batch of the same (kind, device) — never another
    device kind.  ``bucket_hw=None`` / ``batch=None`` match the most
    recently updated entry of the kind (shape-agnostic consumers like
    ``make_eval_fn``, which compiles per streamed shape)."""
    kinds = (kind,) if isinstance(kind, str) else tuple(kind)
    device = device or device_kind()
    entries = load_registry(path)["entries"]
    for k in kinds:
        if bucket_hw is not None and batch is not None:
            exact_key = registry_key(k, device, bucket_hw, batch)
            if exact_key in entries:
                return exact_key, entries[exact_key], True
        cands = [(key, e) for key, e in entries.items()
                 if e.get("kind") == k and e.get("device_kind") == device]
        if not cands:
            continue
        if bucket_hw is None:
            best = max(cands, key=lambda kv: kv[1].get(
                "provenance", {}).get("updated", 0))
            return best[0], best[1], False

        def score(kv):
            e = kv[1]
            d = _bucket_distance(e.get("bucket_hw", (1, 1)), bucket_hw)
            if batch is not None and e.get("batch"):
                import math

                d += 0.1 * abs(math.log(e["batch"] / batch))
            return d

        best = min(cands, key=score)
        exact = (tuple(best[1].get("bucket_hw", ())) == tuple(bucket_hw)
                 and (batch is None or best[1].get("batch") == batch))
        if not exact and score(best) > _MAX_FALLBACK_DISTANCE:
            continue   # too far to trust the transfer; try the next kind
        return best[0], best[1], exact
    return None


def resolve_config(model_cfg: RAFTConfig,
                   kind: Union[str, Sequence[str]],
                   bucket_hw: Optional[Tuple[int, int]] = None,
                   batch: Optional[int] = None,
                   path: Optional[str] = None
                   ) -> Tuple[RAFTConfig, TuningInfo]:
    """Apply the registry to every knob the user left at its default.

    A knob whose current value differs from the ``RAFTConfig`` class
    default was pinned by the user (or an upstream resolve) and is left
    alone — so calling this twice is idempotent, and CLI flags always
    beat the registry.  Disabled (``RAFT_TUNING=0``) or no matching
    entry -> the config comes back untouched with ``tuned=False``."""
    if not enabled():
        return model_cfg, TuningInfo(tuned=False)
    hit = lookup(kind, bucket_hw, batch, path=path)
    if hit is None:
        return model_cfg, TuningInfo(tuned=False)
    key, entry, exact = hit
    applied, pinned, unknown = {}, {}, []
    for knob, value in entry.get("knobs", {}).items():
        if knob not in TUNABLE_KNOBS or knob not in _CONFIG_DEFAULTS:
            unknown.append(knob)
            continue
        current = getattr(model_cfg, knob)
        if current != _CONFIG_DEFAULTS[knob]:
            pinned[knob] = current     # user (or caller) pinned it
        elif current != value:
            applied[knob] = value
    if unknown:
        warnings.warn(f"tuning entry {key!r} carries unknown knob(s) "
                      f"{sorted(unknown)} (newer registry?); ignored")
    reg_path = path or default_registry_path()
    info = TuningInfo(tuned=True, key=key, exact=exact, applied=applied,
                      pinned=pinned, registry_path=reg_path,
                      registry_hash=registry_file_hash(reg_path))
    if applied:
        model_cfg = model_cfg.replace(**applied)
    return model_cfg, info


def resolve_serve_config(serve_cfg,
                         bucket_hw: Optional[Tuple[int, int]] = None,
                         batch: Optional[int] = None,
                         path: Optional[str] = None):
    """Apply a ``kind='serve'`` registry entry's ServeConfig knobs
    (:data:`SERVE_TUNABLE_KNOBS`) to every knob the user left at its
    dataclass default — same precedence as :func:`resolve_config`
    (explicit user knob > registry > default), same idempotence.

    Returns ``(serve_cfg, TuningInfo)``.  Model knobs in the same entry
    are ignored here (the engine resolves those onto RAFTConfig
    separately); a registry value that fails ServeConfig validation is
    dropped with a warning rather than crashing the engine."""
    if not enabled():
        return serve_cfg, TuningInfo(tuned=False)
    hit = lookup("serve", bucket_hw, batch, path=path)
    if hit is None:
        return serve_cfg, TuningInfo(tuned=False)
    key, entry, exact = hit
    defaults = {f.name: f.default
                for f in dataclasses.fields(type(serve_cfg))}
    applied, pinned = {}, {}
    for knob, value in entry.get("knobs", {}).items():
        if knob not in SERVE_TUNABLE_KNOBS or knob not in defaults:
            continue  # model knob or unknown: not ours
        current = getattr(serve_cfg, knob)
        if current != defaults[knob]:
            pinned[knob] = current     # user (or caller) pinned it
        elif current != value:
            applied[knob] = value
    reg_path = path or default_registry_path()
    info = TuningInfo(tuned=True, key=key, exact=exact, applied=applied,
                      pinned=pinned, registry_path=reg_path,
                      registry_hash=registry_file_hash(reg_path))
    if applied:
        try:
            serve_cfg = dataclasses.replace(serve_cfg, **applied)
        except (ValueError, TypeError) as e:
            warnings.warn(f"tuning entry {key!r} serve knobs {applied} "
                          f"rejected by ServeConfig ({e}); ignored")
            info = dataclasses.replace(info, applied={})
    return serve_cfg, info
