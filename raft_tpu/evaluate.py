"""Validation + leaderboard submission (reference ``evaluate.py``).

Parity surface (SURVEY.md C13):

- ``validate_chairs``  — EPE @ 24 iters (reference evaluate.py:75-93)
- ``validate_sintel``  — clean+final EPE/1px/3px/5px @ 32 iters with
  InputPadder (evaluate.py:96-128)
- ``validate_kitti``   — EPE + F1-all (``epe>3 ∧ epe/mag>0.05``) @ 24 iters
  (evaluate.py:131-166)
- ``create_sintel_submission`` — optional warm start: previous frame's
  1/8-res flow forward-interpolated into the next frame's ``flow_init``
  (evaluate.py:22-51)
- ``create_kitti_submission``  — 16-bit PNG flow writer (evaluate.py:54-72)

TPU shape of the loop: one jitted test-mode forward per padded image shape
(Sintel/KITTI resolutions are constant per split, so each validator
compiles once and streams images through it); metrics accumulate on host in
NumPy.  The reference's per-image ``np.mean(epe_list)`` ragged-array quirk
(evaluate.py:118-125) is resolved in favor of the printed per-pixel mean.
"""

from __future__ import annotations

import os
import os.path as osp
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.config import RAFTConfig
from raft_tpu.data import datasets, frame_utils
from raft_tpu.models.raft import RAFT
from raft_tpu.obs import default_sink, span
from raft_tpu.ops.pad import InputPadder, max_bucket_hw
from raft_tpu.utils.warp import forward_interpolate


def default_alternate_corr_impl() -> str:
    """The ``--alternate_corr`` implementation for this backend: the
    fused on-demand Pallas kernels on TPU (the ``alt_cuda_corr`` analog —
    1.13 f/s at 1440x2560 where all-pairs OOMs, 2x the chunked path),
    the XLA chunked formulation elsewhere (interpret-mode Pallas is
    impractically slow on CPU)."""
    return "pallas" if jax.default_backend() == "tpu" else "chunked"


def make_inference_model(model_cfg: RAFTConfig, *,
                         bucket_hw=None, batch=None,
                         tuning_kind=("eval",)) -> RAFT:
    """The RAFT module with the inference-only config overrides applied.

    Every inference entry point (the validators here, the serving engine
    in ``raft_tpu/serve``) funnels through this function so the overrides
    live once: the scan unroll is forced to 1 (the config default tunes
    the training backward pass; at 32 forward-only iterations unroll 6
    measured 10.8 vs 11.9 frames/s on v5e), and the training-optimized
    ``allpairs_pallas`` impl maps back to ``allpairs`` (10.4 vs 12.0
    frames/s at the Sintel eval shape, whose W/8=128 rows fill the MXU
    lane tile).  Explicit memory-saving choices (``chunked`` /
    ``pallas``) are respected.

    The per-hardware tuning registry (raft_tpu/tuning.py) is consulted
    first for ``tuning_kind`` (default 'eval'; the serve engine passes
    ('serve', 'eval')): knobs left at their RAFTConfig defaults take the
    autotuned winner for ``(bucket_hw, batch)`` — or the nearest /
    most-recent entry when the shape isn't known yet, as here where the
    jit compiles per streamed shape.  The inference overrides above are
    applied AFTER tuning, so they hold unconditionally."""
    from raft_tpu import tuning

    model_cfg, _ = tuning.resolve_config(model_cfg, tuning_kind,
                                         bucket_hw, batch)
    overrides = {"scan_unroll": 1}
    if model_cfg.corr_impl == "allpairs_pallas":
        overrides["corr_impl"] = "allpairs"
    return RAFT(model_cfg.replace(**overrides))


def make_eval_fn(model_cfg: RAFTConfig, iters: int):
    """Jitted ``(variables, image1, image2, flow_init) -> (flow_low,
    flow_up)`` test-mode forward.  ``flow_init`` may be None (traced as a
    static branch via two separate jit entries).  Inference-only config
    overrides (and the tuning-registry consult) are applied by
    :func:`make_inference_model`."""
    model = make_inference_model(model_cfg)

    @jax.jit
    def fwd(variables, image1, image2):
        return model.apply(variables, image1, image2, iters=iters,
                           test_mode=True, train=False)

    @jax.jit
    def fwd_init(variables, image1, image2, flow_init):
        return model.apply(variables, image1, image2, iters=iters,
                           flow_init=flow_init, test_mode=True, train=False)

    def capture_cost(variables, image1, image2):
        """Compile-time cost of the no-init forward at this shape
        (obs/cost.py) — one extra ``lower().compile()``, cheap under
        the persistent compile cache; host metadata only.  The cost
        CLI and bench.py's eval arm call this directly."""
        from raft_tpu.obs import cost as cost_mod

        compiled = fwd.lower(variables, image1, image2).compile()
        h, w = image1.shape[1], image1.shape[2]
        return cost_mod.program_cost(
            compiled, program=f"inference_{h}x{w}",
            pairs_per_call=image1.shape[0])

    # One cost_report per distinct compiled shape when telemetry is on
    # (the validators stream constant-shape batches, so this fires once
    # per split) — the hbm_usage precedent, RAFT_TELEMETRY_COST=0 skips.
    cost_seen: set = set()
    cost_on = os.environ.get("RAFT_TELEMETRY_COST", "1") == "1"

    def eval_fn(variables, image1, image2, flow_init=None):
        if cost_on and flow_init is None \
                and image1.shape not in cost_seen:
            cost_seen.add(image1.shape)
            sink = default_sink()
            if sink.enabled:
                try:
                    sink.emit("cost_report", **capture_cost(
                        variables, image1, image2).as_record())
                except Exception:
                    pass
        if flow_init is None:
            return fwd(variables, image1, image2)
        return fwd_init(variables, image1, image2, flow_init)

    eval_fn.capture_cost = capture_cost
    return eval_fn


def _prep(sample: Dict[str, np.ndarray], mode: str):
    """Host sample -> padded (1,H,W,3) device arrays + padder."""
    image1 = jnp.asarray(sample["image1"])[None]
    image2 = jnp.asarray(sample["image2"])[None]
    padder = InputPadder(image1.shape, mode=mode)
    image1, image2 = padder.pad(image1, image2)
    return image1, image2, padder


def _peek_hw(path: str):
    """Image (H, W) from the file header only (no pixel decode)."""
    from PIL import Image

    with Image.open(path) as im:
        w, h = im.size
    return h, w


_BUCKET_CACHE: Dict[tuple, tuple] = {}


def _bucket_hw(ds) -> tuple:
    """One /8-aligned bucket shape covering every image in the dataset.

    KITTI's native resolutions vary per sequence (375x1242, 370x1224, ...)
    so a per-shape jit would pay one XLA compile per distinct resolution
    (minutes at every val_freq).  Padding everything to the max shape
    compiles ONCE; edge-replicate padding repeats the border row, so
    content inside the original frame sees the same receptive fields (the
    residual effect is the instance-norm statistics over the slightly
    larger canvas, which the reference also pays in its own right-padding,
    core/utils/utils.py:7-24).  The bucket-vs-exact residual is bounded at
    rel=0.15 on random-init weights (tests/test_evaluate.py); pinning it
    tighter (expected well under 0.01 EPE on a trained model, whose
    features are far from the decision boundaries random init sits on)
    needs real weights — weights-blocked, see
    docs/REAL_WEIGHTS_RUNBOOK.md.

    Header peeks are cached per image-path set: validators construct a
    fresh dataset every call (val_freq cadence), and re-opening every
    header ~20x per stage was pure waste."""
    key = tuple(p1 for (p1, _) in ds.image_list)
    hit = _BUCKET_CACHE.get(key)
    if hit is None:
        if len(_BUCKET_CACHE) >= 64:   # a handful of dataset variants is
            _BUCKET_CACHE.clear()      # the use case; don't grow forever
        hit = _BUCKET_CACHE[key] = max_bucket_hw(_peek_hw(p) for p in key)
    return hit


def _batched_flows(variables, eval_fn, ds, mode: str, batch_size: int,
                   target=None):
    """Stream the dataset through the jitted forward in fixed-shape
    batches; yields ``(sample, flow (H, W, 2) np, unpadded)`` per image.

    Every image is padded to ``target`` (or its own /8 shape — then all
    images must share a resolution), so the whole pass costs ONE
    compilation; the final partial batch is filled by repeating the last
    image (discarded on yield)."""
    n = len(ds)
    for start in range(0, n, batch_size):
        idxs = list(range(start, min(start + batch_size, n)))
        samples = [ds.load(i) for i in idxs]
        with span("raft_eval_pad", dataset=mode):
            padders = [InputPadder(s["image1"].shape, mode=mode,
                                   target=target) for s in samples]
            im1 = [p.pad_np(s["image1"]) for p, s in zip(padders, samples)]
            im2 = [p.pad_np(s["image2"]) for p, s in zip(padders, samples)]
            pad_n = batch_size - len(idxs)
            if pad_n:  # keep the compiled batch shape on the final chunk
                im1 += [im1[-1]] * pad_n
                im2 += [im2[-1]] * pad_n
            batch1 = jnp.asarray(np.stack(im1))
            batch2 = jnp.asarray(np.stack(im2))
        # The forward span covers dispatch AND the host transfer below,
        # so it measures real device time per batch (one event per
        # batch in the JSONL log when telemetry is enabled).
        with span("raft_eval_forward", dataset=mode, emit=True):
            _, flow_up = eval_fn(variables, batch1, batch2)
            flow_up = np.asarray(flow_up)
        for j, (s, p) in enumerate(zip(samples, padders)):
            yield s, np.asarray(p.unpad(flow_up[j:j + 1])[0])


def validate_chairs(variables, model_cfg: RAFTConfig = RAFTConfig.full(),
                    iters: int = 24,
                    root: str = "datasets/FlyingChairs_release/data",
                    split_file: str = "chairs_split.txt",
                    eval_fn=None, batch_size: int = 4) -> Dict[str, float]:
    """FlyingChairs validation-split EPE (reference evaluate.py:75-93).

    Images are a constant 384x512, so the whole split streams through one
    compiled ``(batch_size, 384, 512)`` forward."""
    eval_fn = eval_fn or make_eval_fn(model_cfg, iters)
    ds = datasets.FlyingChairs(split="validation", root=root,
                               split_file=split_file)
    epe_list = []
    for sample, flow in _batched_flows(variables, eval_fn, ds, "chairs",
                                       batch_size):
        with span("raft_eval_epe", dataset="chairs"):
            epe = np.sqrt(np.sum((flow - sample["flow"]) ** 2, axis=-1))
            epe_list.append(epe.reshape(-1))
    epe = float(np.mean(np.concatenate(epe_list)))
    print(f"Validation Chairs EPE: {epe:.3f}", flush=True)
    default_sink().emit("eval", dataset="chairs", chairs=epe)
    return {"chairs": epe}


def validate_sintel(variables, model_cfg: RAFTConfig = RAFTConfig.full(),
                    iters: int = 32, root: str = "datasets/Sintel",
                    eval_fn=None, batch_size: int = 2) -> Dict[str, float]:
    """Sintel training-split clean+final EPE (reference evaluate.py:96-128).

    All frames are 436x1024 -> one 440x1024 bucket, one compile per
    dstype pass (same compiled shape for both)."""
    eval_fn = eval_fn or make_eval_fn(model_cfg, iters)
    results = {}
    for dstype in ("clean", "final"):
        ds = datasets.MpiSintel(split="training", dstype=dstype, root=root)
        epe_list = []
        for sample, flow in _batched_flows(variables, eval_fn, ds,
                                           "sintel", batch_size,
                                           target=_bucket_hw(ds)):
            with span("raft_eval_epe", dataset="sintel"):
                epe = np.sqrt(np.sum((flow - sample["flow"]) ** 2,
                                     axis=-1))
                epe_list.append(epe.reshape(-1))
        epe_all = np.concatenate(epe_list)
        epe = float(np.mean(epe_all))
        px1 = float(np.mean(epe_all < 1))
        px3 = float(np.mean(epe_all < 3))
        px5 = float(np.mean(epe_all < 5))
        print(f"Validation ({dstype}) EPE: {epe:.3f}, 1px: {px1:.3f}, "
              f"3px: {px3:.3f}, 5px: {px5:.3f}", flush=True)
        default_sink().emit("eval", dataset=f"sintel-{dstype}", epe=epe,
                            px1=px1, px3=px3, px5=px5)
        results[dstype] = epe
    return results


def validate_kitti(variables, model_cfg: RAFTConfig = RAFTConfig.full(),
                   iters: int = 24, root: str = "datasets/KITTI",
                   eval_fn=None, batch_size: int = 4,
                   bucket: bool = True) -> Dict[str, float]:
    """KITTI-15 training-split EPE + F1-all (reference evaluate.py:131-166).

    ``bucket=True`` (default) pads every native resolution to one common
    /8-aligned shape so the whole split costs ONE compile instead of one
    per resolution (the every-5000-step validation cadence made per-shape
    compiles the dominant wall-clock cost).  ``bucket=False`` restores the
    reference's exact per-shape padding (per-image batches)."""
    eval_fn = eval_fn or make_eval_fn(model_cfg, iters)
    ds = datasets.KITTI(split="training", root=root)
    target, bs = (_bucket_hw(ds), batch_size) if bucket else (None, 1)
    epe_list, out_list = [], []
    for sample, flow in _batched_flows(variables, eval_fn, ds, "kitti",
                                       bs, target=target):
        with span("raft_eval_epe", dataset="kitti"):
            epe = np.sqrt(np.sum((flow - sample["flow"]) ** 2, axis=-1))
            mag = np.sqrt(np.sum(sample["flow"] ** 2, axis=-1))
            val = sample["valid"] >= 0.5
            out = (epe > 3.0) & ((epe / np.maximum(mag, 1e-12)) > 0.05)
            epe_list.append(epe[val].mean())
            out_list.append(out[val])
    epe = float(np.mean(epe_list))
    f1 = 100.0 * float(np.mean(np.concatenate(out_list)))
    print(f"Validation KITTI: {epe:.3f}, {f1:.3f}", flush=True)
    default_sink().emit("eval", dataset="kitti", epe=epe, f1=f1)
    return {"kitti-epe": epe, "kitti-f1": f1}


def create_sintel_submission(variables,
                             model_cfg: RAFTConfig = RAFTConfig.full(),
                             iters: int = 32, warm_start: bool = False,
                             root: str = "datasets/Sintel",
                             output_path: str = "sintel_submission",
                             eval_fn=None, batch_size: int = 4) -> None:
    """Write test-split ``.flo`` predictions (reference evaluate.py:22-51).

    ``warm_start``: seed each frame with the previous frame's 1/8-res flow
    forward-warped along itself (evaluate.py:40-41) — the scattered-data
    interpolation runs on host.

    Batching: warm start chains frames *within* a sequence, but distinct
    sequences are independent — so each batch lane carries one SEQUENCE
    and time steps across lanes share one compiled forward (the
    reference streams batch-1 frames, evaluate.py:30).  A zero
    ``flow_init`` is identical to no warm start (coords1 += 0), which
    lets lane restarts and non-warm-start lanes share the jit entry.
    Finished lanes repeat their last frame; outputs for those are
    discarded."""
    eval_fn = eval_fn or make_eval_fn(model_cfg, iters)
    for dstype in ("clean", "final"):
        ds = datasets.MpiSintel(split="test", aug_params=None,
                                dstype=dstype, root=root)
        seq_frames: Dict[str, list] = {}
        for i, (scene, frame) in enumerate(ds.extra_info):
            seq_frames.setdefault(scene, []).append((frame, i))
        lanes_all = [[i for _, i in sorted(v)] for v in seq_frames.values()]
        if not lanes_all:
            continue  # empty split: a graceful no-op, like the old loop
        B = min(batch_size, len(lanes_all))
        for g0 in range(0, len(lanes_all), B):
            real = lanes_all[g0:g0 + B]
            # Padding lanes keep the compiled batch shape but are pure
            # ballast: length 0, so they never decode, never
            # forward-interpolate, never write — they just replicate the
            # last real lane's pixels below.
            lanes = real + [[]] * (B - len(real))
            flow_prev = None
            cache = [None] * B  # (sample, padder) of a finished lane
            for t in range(max(len(ln) for ln in real)):
                samples, padders = [], []
                for j, ln in enumerate(lanes):
                    if t < len(ln):
                        s = ds.load(ln[t])
                        p = InputPadder(s["image1"].shape, mode="sintel")
                        cache[j] = (s, p)
                    elif cache[j] is not None:
                        s, p = cache[j]  # finished lane: no re-decode
                    else:
                        s, p = samples[len(real) - 1], padders[
                            len(real) - 1]  # padding lane: mirror
                    samples.append(s)
                    padders.append(p)
                im1 = np.stack([p.pad_np(s["image1"])
                                for p, s in zip(padders, samples)])
                im2 = np.stack([p.pad_np(s["image2"])
                                for p, s in zip(padders, samples)])
                flow_low, flow_up = eval_fn(variables, jnp.asarray(im1),
                                            jnp.asarray(im2), flow_prev)
                flow_up = np.asarray(flow_up)
                if warm_start:
                    # Per-lane host forward-warp, only for lanes still
                    # active NEXT step (griddata is the slowest host op
                    # here; finished/padding lanes keep a zero init —
                    # their dummy outputs are never written).
                    low = np.asarray(flow_low)
                    flow_prev = jnp.asarray(np.stack([
                        forward_interpolate(low[j])
                        if t + 1 < len(lanes[j]) else
                        np.zeros_like(low[j])
                        for j in range(B)]))
                for j, (ln, s, p) in enumerate(zip(lanes, samples,
                                                   padders)):
                    if t >= len(ln):
                        continue  # finished/padding lane
                    scene, frame = s["extra_info"]
                    out_dir = osp.join(output_path, dstype, scene)
                    os.makedirs(out_dir, exist_ok=True)
                    frame_utils.write_flo(
                        osp.join(out_dir, f"frame{frame + 1:04d}.flo"),
                        np.asarray(p.unpad(flow_up[j:j + 1])[0]))


def create_kitti_submission(variables,
                            model_cfg: RAFTConfig = RAFTConfig.full(),
                            iters: int = 24, root: str = "datasets/KITTI",
                            output_path: str = "kitti_submission",
                            eval_fn=None, batch_size: int = 4,
                            bucket: bool = False) -> None:
    """Write test-split 16-bit PNG flow (reference evaluate.py:54-72).

    ``bucket=False`` (default) keeps the reference's exact minimal
    per-image padding (batch 1, one compile per native resolution) —
    this is the artifact actually uploaded to the leaderboard, and the
    bucket residual (instance-norm statistics over the padded canvas)
    is only bounded at rel=0.15 on random-init weights until real
    weights land (see :func:`_bucket_hw`).  ``bucket=True`` streams the
    split through the bucketed fixed-shape batch path (one compile
    total, like the validators) when throughput matters more than
    bit-exactness."""
    eval_fn = eval_fn or make_eval_fn(model_cfg, iters)
    ds = datasets.KITTI(split="testing", aug_params=None, root=root)
    os.makedirs(output_path, exist_ok=True)
    target, bs = (_bucket_hw(ds), batch_size) if bucket else (None, 1)
    for sample, flow in _batched_flows(variables, eval_fn, ds, "kitti",
                                       bs, target=target):
        (frame_id,) = sample["extra_info"]
        frame_utils.write_flow_kitti(osp.join(output_path, frame_id), flow)


VALIDATORS = {
    "chairs": validate_chairs,
    "sintel": validate_sintel,
    "kitti": validate_kitti,
}


def evaluate_epe_delta(variables, model_cfg: RAFTConfig, dtypes,
                       dataset: str = "chairs", iters: int = 24,
                       batch_size: int = 4, **validator_kwargs) -> Dict:
    """Same checkpoint, same data, N corr-storage dtypes: the accuracy
    gate for quantized correlation (the ``scripts/ab_corr_dtype.py``
    paired methodology promoted into the eval CLI).

    Runs the chosen validator once per dtype in ``dtypes`` — the arms
    differ ONLY in ``corr_dtype``, everything else (weights, data order,
    iteration count, padding) is bit-identical — and reports each arm's
    metrics plus the deltas against the FIRST dtype (the baseline arm;
    pass 'float32' first to gate against the reference storage).  The
    acceptance bar for int8 storage is ``|delta| < 0.05`` EPE on the
    toy/tiny fixtures (asserted in tests/test_corr.py) and a real-data
    run before any quality-critical deployment (docs/PERFORMANCE.md).

    Returns ``{"dataset", "dtypes", "per_dtype": {dtype: metrics},
    "delta_vs_<base>": {dtype: {metric: delta}}}``.
    """
    from raft_tpu.config import validate_corr_dtype

    dtypes = [validate_corr_dtype(d) for d in dtypes]
    if len(dtypes) < 2:
        raise ValueError(f"--epe_delta needs >= 2 dtypes, got {dtypes}")
    validator = VALIDATORS[dataset]
    per_dtype: Dict[str, Dict[str, float]] = {}
    for dt in dtypes:
        cfg = model_cfg.replace(corr_dtype=dt)
        print(f"--- corr_dtype={dt} ---", flush=True)
        per_dtype[dt] = validator(variables, cfg, iters=iters,
                                  batch_size=batch_size,
                                  **validator_kwargs)
    base = dtypes[0]
    deltas = {
        dt: {k: round(per_dtype[dt][k] - per_dtype[base][k], 6)
             for k in per_dtype[base]}
        for dt in dtypes[1:]
    }
    for dt, d in deltas.items():
        line = ", ".join(f"{k}: {v:+.4f}" for k, v in d.items())
        print(f"EPE delta {dt} - {base} [{dataset}]: {line}", flush=True)
    default_sink().emit("eval_epe_delta", dataset=dataset, base=base,
                        dtypes=list(dtypes),
                        deltas={dt: d for dt, d in deltas.items()})
    return {"dataset": dataset, "dtypes": list(dtypes),
            "per_dtype": per_dtype, f"delta_vs_{base}": deltas}


# Validation datasets the early-exit sweep can stream (monkeypatchable
# seam, like VALIDATORS): name -> zero-config constructor.
EARLY_EXIT_DATASETS = {
    "chairs": lambda **kw: datasets.FlyingChairs(split="validation",
                                                 **kw),
    "sintel": lambda **kw: datasets.MpiSintel(split="training",
                                              dstype="clean", **kw),
    "kitti": lambda **kw: datasets.KITTI(split="training", **kw),
}


def _early_exit_flows(variables, runner, ds, mode: str, batch_size: int,
                      iters: int, threshold: float, target=None):
    """Stream ``ds`` through :class:`raft_tpu.serve.slots
    .EarlyExitRunner` in fixed-shape batches; yields ``(sample,
    flow (H, W, 2) np unpadded, iters_used, residual)`` per image —
    the early-exit mirror of :func:`_batched_flows`.  ``residual`` is
    the lane's convergence ``delta_max`` at its retirement iteration
    (the in-graph quality proxy, ``obs/quality.py``)."""
    n = len(ds)
    for start in range(0, n, batch_size):
        idxs = list(range(start, min(start + batch_size, n)))
        samples = [ds.load(i) for i in idxs]
        padders = [InputPadder(s["image1"].shape, mode=mode,
                               target=target) for s in samples]
        im1 = [p.pad_np(s["image1"]) for p, s in zip(padders, samples)]
        im2 = [p.pad_np(s["image2"]) for p, s in zip(padders, samples)]
        pad_n = batch_size - len(idxs)
        if pad_n:  # keep the compiled batch shape on the final chunk
            im1 += [im1[-1]] * pad_n
            im2 += [im2[-1]] * pad_n
        with span("raft_eval_forward", dataset=mode, emit=True):
            flow_up, used, resid = runner.run(
                variables, np.stack(im1), np.stack(im2), iters,
                threshold, return_residuals=True)
        for j, (s, p) in enumerate(zip(samples, padders)):
            yield s, np.asarray(p.unpad(flow_up[j:j + 1])[0]), \
                int(used[j]), float(resid[j])


def evaluate_early_exit_delta(variables, model_cfg: RAFTConfig,
                              thresholds, dataset: str = "chairs",
                              iters: int = 24, batch_size: int = 4,
                              bucket: bool = True,
                              **dataset_kwargs) -> Dict:
    """Same checkpoint, same data, N early-exit thresholds vs the
    full-iteration baseline: the accuracy gate for adaptive early exit
    (``--early_exit_threshold`` in the eval CLI; the serve knob it
    clears is ``ServeConfig.early_exit_threshold``).

    Arm 0 is ALWAYS the full-budget baseline (threshold 0 disables the
    convergence cut, so every lane runs all ``iters`` refinements); each
    requested threshold then re-streams the same samples through the
    same :class:`~raft_tpu.serve.slots.EarlyExitRunner` — the identical
    compiled ``encode``/``iter_step`` programs the serving engine runs,
    so the measured EPE delta is exactly what slot-mode serving would
    ship.  Per arm: ground-truth EPE, its delta vs baseline, and the
    iters_used distribution (mean/p50/p95 — the throughput win).

    Returns ``{"dataset", "iters", "thresholds", "per_threshold":
    {thr: {"epe", "epe_delta", "iters_mean", "iters_p50", "iters_p95",
    "residual_mean", "residual_p50"}}, "delta_vs_full":
    {thr: epe_delta}}`` with threshold keys rendered as strings
    (JSON-stable).  The residual stats are the lanes' convergence
    ``delta_max`` at retirement — the in-graph quality proxy
    (``obs/quality.py``) — stamped next to the measured EPE delta so
    the predicate that triggered the exit and the accuracy it cost sit
    in the same record.

    The regression gate (``scripts/check_regression.py
    --max-early-exit-epe-delta``) reads the max ``delta_vs_full``
    magnitude from the bench record this feeds."""
    thrs = [float(t) for t in thresholds]
    if not thrs:
        raise ValueError("--early_exit_threshold needs >= 1 threshold")
    if any(t < 0 for t in thrs):
        raise ValueError(f"thresholds must be >= 0: {thrs}")
    try:
        make_ds = EARLY_EXIT_DATASETS[dataset]
    except KeyError:
        raise ValueError(f"unknown dataset {dataset!r}; choose from "
                         f"{sorted(EARLY_EXIT_DATASETS)}")
    from raft_tpu.serve.slots import EarlyExitRunner

    arms, seen = [], set()
    for t in [0.0] + thrs:          # baseline first, dedup after
        if t not in seen:
            seen.add(t)
            arms.append(t)
    runner = EarlyExitRunner(make_inference_model(model_cfg).config)
    ds = make_ds(**dataset_kwargs)
    target = _bucket_hw(ds) if bucket else None
    per: Dict[str, Dict[str, float]] = {}
    base_epe = None
    for t in arms:
        epes, used_all, resid_all = [], [], []
        print(f"--- early_exit_threshold={t:g} ---", flush=True)
        for sample, flow, used, resid in _early_exit_flows(
                variables, runner, ds, dataset, batch_size, iters, t,
                target=target):
            epe = np.sqrt(np.sum((flow - sample["flow"]) ** 2, axis=-1))
            epes.append(epe.reshape(-1))
            used_all.append(used)
            resid_all.append(resid)
        epe = float(np.mean(np.concatenate(epes)))
        used_np = np.asarray(used_all, np.float64)
        resid_np = np.asarray(resid_all, np.float64)
        if base_epe is None:
            base_epe = epe
        per[f"{t:g}"] = {
            "epe": round(epe, 6),
            "epe_delta": round(epe - base_epe, 6),
            "iters_mean": round(float(used_np.mean()), 3),
            "iters_p50": float(np.percentile(used_np, 50)),
            "iters_p95": float(np.percentile(used_np, 95)),
            "residual_mean": round(float(resid_np.mean()), 6),
            "residual_p50": round(float(np.percentile(resid_np, 50)), 6),
        }
        print(f"early-exit thr={t:g} [{dataset}]: EPE {epe:.4f} "
              f"(delta {epe - base_epe:+.4f}), iters p50 "
              f"{per[f'{t:g}']['iters_p50']:g} p95 "
              f"{per[f'{t:g}']['iters_p95']:g}", flush=True)
    deltas = {k: v["epe_delta"] for k, v in per.items() if k != "0"}
    default_sink().emit("eval_early_exit_delta", dataset=dataset,
                        iters=iters, thresholds=[f"{t:g}" for t in arms],
                        deltas=deltas)
    return {"dataset": dataset, "iters": iters,
            "thresholds": [f"{t:g}" for t in arms],
            "per_threshold": per, "delta_vs_full": deltas}


def evaluate_quality_proxies(variables, model_cfg: RAFTConfig,
                             dataset: str = "chairs", iters: int = 24,
                             batch_size: int = 4, bucket: bool = True,
                             cycle: bool = False,
                             **dataset_kwargs) -> Dict:
    """Calibrate the unsupervised quality proxies
    (:mod:`raft_tpu.obs.quality`) against ground truth: stream a
    labeled dataset through the serve-identical
    :class:`~raft_tpu.serve.slots.EarlyExitRunner`, score every sample
    with the label-free proxies the production path emits, and report
    the Spearman rank correlation of each proxy with the true per-image
    EPE.

    The point: serving scores live traffic with these proxies
    (``ServeConfig.quality_sample_rate``) and the fleet gates weight
    rollouts on them (``canary_proxy_budget``) — neither surface ever
    sees a label.  This function is the receipt that the proxies RANK
    bad flow as bad: a proxy with Spearman >= ~0.6 on labeled data is a
    trustworthy drift/canary signal; one near 0 is vibes.

    Proxies scored per image:

    - ``photometric``: occlusion-masked charbonnier warp error
      (:func:`raft_tpu.obs.quality.photometric_error`), the same
      statistic the serve sampler records.
    - ``residual``: convergence ``delta_max`` at lane retirement, the
      in-graph early-exit predicate value.
    - ``cycle`` (``cycle=True`` only — doubles the forward cost): a
      second pass on swapped frames, forward-backward consistency via
      :func:`raft_tpu.obs.quality.cycle_error`.

    Returns ``{"dataset", "iters", "n", "epe_mean", "spearman":
    {proxy: rho}, "proxy_means": {proxy: mean}}`` and emits an
    ``eval_quality_proxies`` event with the same payload."""
    try:
        make_ds = EARLY_EXIT_DATASETS[dataset]
    except KeyError:
        raise ValueError(f"unknown dataset {dataset!r}; choose from "
                         f"{sorted(EARLY_EXIT_DATASETS)}")
    from raft_tpu.obs import quality
    from raft_tpu.serve.slots import EarlyExitRunner

    runner = EarlyExitRunner(make_inference_model(model_cfg).config)
    ds = make_ds(**dataset_kwargs)
    target = _bucket_hw(ds) if bucket else None
    epes, photo, resids, flows_fw = [], [], [], []
    for sample, flow, _used, resid in _early_exit_flows(
            variables, runner, ds, dataset, batch_size, iters, 0.0,
            target=target):
        epe = np.sqrt(np.sum((flow - sample["flow"]) ** 2, axis=-1))
        epes.append(float(epe.mean()))
        scores = quality.score_pair(sample["image1"], sample["image2"],
                                    flow)
        photo.append(scores["photometric"])
        resids.append(resid)
        if cycle:
            flows_fw.append(flow)
    proxies = {"photometric": photo, "residual": resids}
    if cycle:
        cyc = []
        swapped = _SwappedPairs(ds)
        for k, (_s, flow_bw, _u, _r) in enumerate(_early_exit_flows(
                variables, runner, swapped, dataset, batch_size, iters,
                0.0, target=target)):
            err, _occ = quality.cycle_error(flows_fw[k][None],
                                            flow_bw[None])
            cyc.append(float(np.asarray(err)[0]))
        proxies["cycle"] = cyc
    spear = {k: round(quality.spearman(v, epes), 4)
             for k, v in proxies.items()}
    rec = {
        "dataset": dataset, "iters": iters, "n": len(epes),
        "epe_mean": round(float(np.mean(epes)), 6),
        "spearman": spear,
        "proxy_means": {k: round(float(np.mean(v)), 6)
                        for k, v in proxies.items()},
    }
    default_sink().emit("eval_quality_proxies", **rec)
    for k, rho in spear.items():
        print(f"quality proxy [{dataset}] {k}: spearman(EPE) "
              f"{rho:+.4f}", flush=True)
    return rec


class _SwappedPairs:
    """Frame-swapped view of an eval dataset: ``image1``/``image2``
    exchanged (flow passed through untouched) so a second
    :func:`_early_exit_flows` pass yields the BACKWARD flow for the
    cycle-consistency proxy."""

    def __init__(self, ds):
        self._ds = ds

    def __len__(self):
        return len(self._ds)

    def load(self, i):
        s = dict(self._ds.load(i))
        s["image1"], s["image2"] = s["image2"], s["image1"]
        return s
