"""raftlint — repo-specific static analysis for raft-tpu.

Four checker families over the defect classes this codebase has paid
for at runtime (see docs/ANALYSIS.md for the rule catalog):

- :mod:`raft_tpu.analysis.jit_purity` — ``JIT101..JIT104``: host
  impurity inside jit-traced code;
- :mod:`raft_tpu.analysis.locks` — ``LOCK201/LOCK202``: guarded-
  attribute discipline and lock-acquisition-order cycles;
- :mod:`raft_tpu.analysis.telemetry` — ``TEL301..TEL305``: emission
  sites vs the OBSERVABILITY.md catalog vs regression-gate keys;
- :mod:`raft_tpu.analysis.contracts` — ``CFG401..CFG403``: argparse
  flags vs config dataclasses vs tuning-registry knobs.

Entry points: ``python -m raft_tpu lint`` (CLI) and
``scripts/lint_repo.py`` (bench-style JSON record + ``--fix``).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from raft_tpu.analysis.core import (  # noqa: F401  (public API)
    Finding, SourceFile, Workspace, load_baseline, load_report,
    make_report, split_findings, write_baseline,
)

BASELINE_PATH = "lint_baseline.json"

#: family name -> (module, rule IDs) — the registry ``run_checks``
#: dispatches on and ``--only`` filters by.
CHECKER_FAMILIES = {
    "jit": ("raft_tpu.analysis.jit_purity",
            ("JIT101", "JIT102", "JIT103", "JIT104")),
    "locks": ("raft_tpu.analysis.locks", ("LOCK201", "LOCK202")),
    "telemetry": ("raft_tpu.analysis.telemetry",
                  ("TEL301", "TEL302", "TEL303", "TEL304", "TEL305")),
    "contracts": ("raft_tpu.analysis.contracts",
                  ("CFG401", "CFG402", "CFG403")),
}


def run_checks(ws: Workspace,
               families: Optional[Sequence[str]] = None,
               ) -> Tuple[List[Finding], List[str]]:
    """Run the selected checker families (default: all) against the
    workspace.  Returns ``(findings, rules_run)`` — unfiltered;
    callers route through :func:`split_findings` for suppression and
    baseline handling."""
    import importlib

    findings: List[Finding] = []
    rules: List[str] = []
    for family in (families or sorted(CHECKER_FAMILIES)):
        if family not in CHECKER_FAMILIES:
            raise ValueError(
                f"unknown checker family {family!r}; have "
                f"{sorted(CHECKER_FAMILIES)}")
        modname, family_rules = CHECKER_FAMILIES[family]
        mod = importlib.import_module(modname)
        findings.extend(mod.check(ws))
        rules.extend(family_rules)
    return findings, rules


def files_scanned(ws: Workspace) -> int:
    return sum(1 for sf in ws._cache.values() if sf is not None)
